"""Kafka driver against an in-process protocol fake.

FakeKafka is a single-node broker speaking the same wire APIs the driver
uses (Metadata/Produce/Fetch/FindCoordinator/group membership/offsets).
Its record-batch codec is written independently of the driver's (spec in
hand) so an encode/decode bug in kafka.py cannot cancel itself out.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time

import pytest

from kubeai_tpu.routing.kafka import (
    KafkaBroker,
    crc32c,
    decode_record_batches,
    encode_record_batch,
)

# ---- independent wire helpers (fake side) ------------------------------------


def _rd_i8(b, p):  return struct.unpack_from(">b", b, p)[0], p + 1
def _rd_i16(b, p): return struct.unpack_from(">h", b, p)[0], p + 2
def _rd_i32(b, p): return struct.unpack_from(">i", b, p)[0], p + 4
def _rd_i64(b, p): return struct.unpack_from(">q", b, p)[0], p + 8


def _rd_str(b, p):
    n, p = _rd_i16(b, p)
    if n < 0:
        return None, p
    return b[p:p + n].decode(), p + n


def _rd_bytes(b, p):
    n, p = _rd_i32(b, p)
    if n < 0:
        return None, p
    return b[p:p + n], p + n


def _rd_varint(b, p):
    shift = z = 0
    while True:
        v = b[p]
        p += 1
        z |= (v & 0x7F) << shift
        if not v & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), p


def _wr_varint(out: bytearray, v: int):
    z = (v << 1) ^ (v >> 63)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _fake_parse_batch(blob: bytes) -> list[bytes]:
    """Record values out of a produce record set (independent parser)."""
    values = []
    p = 0
    while p + 61 <= len(blob):
        _, p = _rd_i64(blob, p)  # base offset
        blen, p = _rd_i32(blob, p)
        end = p + blen
        _, p = _rd_i32(blob, p)  # leader epoch
        magic, p = _rd_i8(blob, p)
        assert magic == 2, magic
        batch_crc, p0 = struct.unpack_from(">I", blob, p)[0], p + 4
        assert batch_crc == crc32c(blob[p0:end]), "produce batch CRC mismatch"
        p = p0
        _, p = _rd_i16(blob, p)  # attributes
        _, p = _rd_i32(blob, p)  # last offset delta
        _, p = _rd_i64(blob, p)
        _, p = _rd_i64(blob, p)
        _, p = _rd_i64(blob, p)  # producer id
        _, p = _rd_i16(blob, p)
        _, p = _rd_i32(blob, p)  # base sequence
        count, p = _rd_i32(blob, p)
        for _ in range(count):
            rlen, p = _rd_varint(blob, p)
            rend = p + rlen
            _, p = _rd_i8(blob, p)  # attributes
            _, p = _rd_varint(blob, p)  # ts delta
            _, p = _rd_varint(blob, p)  # offset delta
            klen, p = _rd_varint(blob, p)
            if klen > 0:
                p += klen
            vlen, p = _rd_varint(blob, p)
            values.append(bytes(blob[p:p + vlen]))
            p = rend
        p = end
    return values


def _fake_encode_batch(base_offset: int, values: list[bytes]) -> bytes:
    """Fetch-response record set (independent encoder)."""
    recs = bytearray()
    for i, v in enumerate(values):
        body = bytearray()
        body += struct.pack(">b", 0)
        _wr_varint(body, 0)
        _wr_varint(body, i)
        _wr_varint(body, -1)
        _wr_varint(body, len(v))
        body += v
        _wr_varint(body, 0)
        _wr_varint(recs, len(body))
        recs += body
    after = bytearray()
    after += struct.pack(">h", 0)
    after += struct.pack(">i", len(values) - 1)
    after += struct.pack(">q", 0)
    after += struct.pack(">q", 0)
    after += struct.pack(">q", -1)
    after += struct.pack(">h", -1)
    after += struct.pack(">i", -1)
    after += struct.pack(">i", len(values))
    after += recs
    out = bytearray()
    out += struct.pack(">q", base_offset)
    out += struct.pack(">i", 9 + len(after))
    out += struct.pack(">i", -1)
    out += struct.pack(">b", 2)
    out += struct.pack(">I", crc32c(bytes(after)))
    out += after
    return bytes(out)


# ---- the fake broker ---------------------------------------------------------


class FakeKafka:
    def __init__(self, partitions: int = 1):
        self.partitions = partitions
        self.logs: dict[tuple[str, int], list[bytes]] = {}
        # Retention truncation: offsets below log_start are gone.
        self.log_start: dict[tuple[str, int], int] = {}
        self.offsets: dict[tuple[str, str, int], int] = {}  # (group, t, p)
        self.groups: dict[str, dict] = {}  # group -> {gen, members, assigns}
        self.lock = threading.Lock()
        self.fail_next_fetches = 0
        self.produces = 0
        self._next_member = 0
        self._stop = threading.Event()
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(64)
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def close(self):
        self._stop.set()
        try:
            self.srv.close()
        except OSError:
            pass

    def log(self, topic, part=0):
        return self.logs.setdefault((topic, part), [])

    def _accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn):
        try:
            while not self._stop.is_set():
                hdr = self._read_n(conn, 4)
                if hdr is None:
                    return
                (n,) = struct.unpack(">i", hdr)
                frame = self._read_n(conn, n)
                if frame is None:
                    return
                api, p = _rd_i16(frame, 0)
                ver, p = _rd_i16(frame, p)
                corr, p = _rd_i32(frame, p)
                _, p = _rd_str(frame, p)  # client id
                body = self._dispatch(api, ver, frame[p:])
                resp = struct.pack(">i", corr) + body
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _read_n(conn, n):
        chunks = b""
        while len(chunks) < n:
            try:
                c = conn.recv(n - len(chunks))
            except OSError:
                return None
            if not c:
                return None
            chunks += c
        return chunks

    # -- api handlers -----------------------------------------------------------

    def _dispatch(self, api, ver, body) -> bytes:
        return {
            3: self._metadata,
            0: self._produce,
            1: self._fetch,
            2: self._list_offsets,
            10: self._find_coordinator,
            11: self._join_group,
            14: self._sync_group,
            12: self._heartbeat,
            13: self._leave_group,
            8: self._offset_commit,
            9: self._offset_fetch,
        }[api](body)

    @staticmethod
    def _str(s: str | None) -> bytes:
        if s is None:
            return struct.pack(">h", -1)
        return struct.pack(">h", len(s)) + s.encode()

    @staticmethod
    def _bytes(b: bytes | None) -> bytes:
        if b is None:
            return struct.pack(">i", -1)
        return struct.pack(">i", len(b)) + b

    def _metadata(self, body) -> bytes:
        n, p = _rd_i32(body, 0)
        topics = []
        for _ in range(max(0, n)):
            t, p = _rd_str(body, p)
            topics.append(t)
        out = bytearray()
        out += struct.pack(">i", 1)  # one broker
        out += struct.pack(">i", 0) + self._str("127.0.0.1")
        out += struct.pack(">i", self.port) + self._str(None)  # rack
        out += struct.pack(">i", 0)  # controller id
        out += struct.pack(">i", len(topics))
        for t in topics:
            out += struct.pack(">h", 0) + self._str(t)
            out += struct.pack(">b", 0)  # internal
            out += struct.pack(">i", self.partitions)
            for pid in range(self.partitions):
                out += struct.pack(">h", 0) + struct.pack(">i", pid)
                out += struct.pack(">i", 0)  # leader = node 0
                out += struct.pack(">i", 1) + struct.pack(">i", 0)  # replicas
                out += struct.pack(">i", 1) + struct.pack(">i", 0)  # isr
        return bytes(out)

    def _produce(self, body) -> bytes:
        _, p = _rd_str(body, 0)  # transactional id
        _, p = _rd_i16(body, p)  # acks
        _, p = _rd_i32(body, p)  # timeout
        ntop, p = _rd_i32(body, p)
        out_topics = []
        with self.lock:
            for _ in range(ntop):
                topic, p = _rd_str(body, p)
                nparts, p = _rd_i32(body, p)
                parts = []
                for _ in range(nparts):
                    pid, p = _rd_i32(body, p)
                    blob, p = _rd_bytes(body, p)
                    log = self.log(topic, pid)
                    base = len(log)
                    log.extend(_fake_parse_batch(blob or b""))
                    self.produces += 1
                    parts.append((pid, base))
                out_topics.append((topic, parts))
        out = bytearray()
        out += struct.pack(">i", len(out_topics))
        for topic, parts in out_topics:
            out += self._str(topic)
            out += struct.pack(">i", len(parts))
            for pid, base in parts:
                out += struct.pack(">i", pid) + struct.pack(">h", 0)
                out += struct.pack(">q", base) + struct.pack(">q", -1)
        out += struct.pack(">i", 0)  # throttle
        return bytes(out)

    def _fetch(self, body) -> bytes:
        p = 0
        _, p = _rd_i32(body, p)  # replica
        max_wait, p = _rd_i32(body, p)
        _, p = _rd_i32(body, p)  # min bytes
        _, p = _rd_i32(body, p)  # max bytes
        _, p = _rd_i8(body, p)  # isolation
        ntop, p = _rd_i32(body, p)
        wants = []
        for _ in range(ntop):
            topic, p = _rd_str(body, p)
            nparts, p = _rd_i32(body, p)
            for _ in range(nparts):
                pid, p = _rd_i32(body, p)
                off, p = _rd_i64(body, p)
                _, p = _rd_i32(body, p)
                wants.append((topic, pid, off))
        fail = False
        with self.lock:
            if self.fail_next_fetches > 0:
                self.fail_next_fetches -= 1
                fail = True
        # Long-poll lite: wait briefly for data.
        if not fail:
            deadline = time.time() + max_wait / 1000.0
            while time.time() < deadline:
                with self.lock:
                    if any(len(self.log(t, pd)) > o for t, pd, o in wants):
                        break
                time.sleep(0.02)
        out = bytearray()
        out += struct.pack(">i", 0)  # throttle
        out += struct.pack(">i", len(wants))
        with self.lock:
            for topic, pid, off in wants:
                truncated = off < self.log_start.get((topic, pid), 0)
                out += self._str(topic)
                out += struct.pack(">i", 1)
                out += struct.pack(">i", pid)
                if fail:
                    out += struct.pack(">h", 16)  # NOT_COORDINATOR
                elif truncated:
                    out += struct.pack(">h", 1)  # OFFSET_OUT_OF_RANGE
                else:
                    out += struct.pack(">h", 0)
                log = self.log(topic, pid)
                out += struct.pack(">q", len(log))  # high watermark
                out += struct.pack(">q", len(log))  # last stable
                out += struct.pack(">i", 0)  # aborted txns
                blob = (
                    b"" if fail or truncated or off >= len(log)
                    else _fake_encode_batch(off, log[off:off + 100])
                )
                out += self._bytes(blob)
        return bytes(out)

    def _list_offsets(self, body) -> bytes:
        p = 0
        _, p = _rd_i32(body, p)  # replica id
        ntop, p = _rd_i32(body, p)
        wants = []
        for _ in range(ntop):
            topic, p = _rd_str(body, p)
            nparts, p = _rd_i32(body, p)
            for _ in range(nparts):
                pid, p = _rd_i32(body, p)
                ts, p = _rd_i64(body, p)
                wants.append((topic, pid, ts))
        out = bytearray()
        out += struct.pack(">i", len(wants))
        with self.lock:
            for topic, pid, ts in wants:
                off = (
                    self.log_start.get((topic, pid), 0)
                    if ts == -2 else len(self.log(topic, pid))
                )
                out += self._str(topic) + struct.pack(">i", 1)
                out += struct.pack(">i", pid) + struct.pack(">h", 0)
                out += struct.pack(">q", -1) + struct.pack(">q", off)
        return bytes(out)

    def _find_coordinator(self, body) -> bytes:
        return (
            struct.pack(">h", 0) + struct.pack(">i", 0)
            + self._str("127.0.0.1") + struct.pack(">i", self.port)
        )

    def _group(self, name):
        return self.groups.setdefault(
            name, {"gen": 0, "members": {}, "assigns": {}}
        )

    def _prune_locked(self, g):
        """Expire members whose session lapsed (real-broker behavior for
        crashed clients; polite ones LeaveGroup)."""
        now = time.time()
        stale = [
            mid for mid, (_, timeout_ms, last) in g["members"].items()
            if now - last > timeout_ms / 1000.0
        ]
        for mid in stale:
            del g["members"][mid]
        if stale:
            g["gen"] += 1
            g["assigns"] = {}

    def _join_group(self, body) -> bytes:
        p = 0
        group, p = _rd_str(body, p)
        session_ms, p = _rd_i32(body, p)
        member_id, p = _rd_str(body, p)
        _, p = _rd_str(body, p)  # protocol type
        nproto, p = _rd_i32(body, p)
        metas = {}
        for _ in range(nproto):
            name, p = _rd_str(body, p)
            meta, p = _rd_bytes(body, p)
            metas[name] = meta
        with self.lock:
            g = self._group(group)
            self._prune_locked(g)
            if not member_id:
                self._next_member += 1
                member_id = f"member-{self._next_member}"
            if member_id not in g["members"]:
                g["gen"] += 1
                g["assigns"] = {}
            g["members"][member_id] = (
                metas.get("range", b""), session_ms, time.time()
            )
            leader = sorted(g["members"])[0]
            out = bytearray()
            out += struct.pack(">h", 0)
            out += struct.pack(">i", g["gen"])
            out += self._str("range")
            out += self._str(leader)
            out += self._str(member_id)
            out += struct.pack(">i", len(g["members"]))
            for mid, (meta, _, _) in sorted(g["members"].items()):
                out += self._str(mid) + self._bytes(meta)
        return bytes(out)

    def _sync_group(self, body) -> bytes:
        p = 0
        group, p = _rd_str(body, p)
        gen, p = _rd_i32(body, p)
        member_id, p = _rd_str(body, p)
        nassign, p = _rd_i32(body, p)
        incoming = {}
        for _ in range(nassign):
            mid, p = _rd_str(body, p)
            blob, p = _rd_bytes(body, p)
            incoming[mid] = blob
        with self.lock:
            g = self._group(group)
            if gen != g["gen"]:
                return struct.pack(">h", 22) + self._bytes(b"")
            if incoming:
                g["assigns"] = incoming
            if member_id not in g["assigns"]:
                # Real brokers park non-leaders here until the leader's
                # SyncGroup arrives; this fake is non-blocking, so tell
                # the member to retry (its rejoin loop converges).
                return struct.pack(">h", 27) + self._bytes(b"")
            mine = g["assigns"][member_id]
        return struct.pack(">h", 0) + self._bytes(mine)

    def _heartbeat(self, body) -> bytes:
        p = 0
        group, p = _rd_str(body, p)
        gen, p = _rd_i32(body, p)
        member_id, p = _rd_str(body, p)
        with self.lock:
            g = self._group(group)
            self._prune_locked(g)
            if member_id not in g["members"]:
                return struct.pack(">h", 25)  # UNKNOWN_MEMBER_ID
            meta, timeout_ms, _ = g["members"][member_id]
            g["members"][member_id] = (meta, timeout_ms, time.time())
            if gen != g["gen"]:
                return struct.pack(">h", 27)  # REBALANCE_IN_PROGRESS
        return struct.pack(">h", 0)

    def _leave_group(self, body) -> bytes:
        p = 0
        group, p = _rd_str(body, p)
        member_id, p = _rd_str(body, p)
        with self.lock:
            g = self._group(group)
            if g["members"].pop(member_id, None) is not None:
                g["gen"] += 1
                g["assigns"] = {}
        return struct.pack(">h", 0)

    def _offset_commit(self, body) -> bytes:
        p = 0
        group, p = _rd_str(body, p)
        _, p = _rd_i32(body, p)  # generation
        _, p = _rd_str(body, p)  # member
        _, p = _rd_i64(body, p)  # retention
        ntop, p = _rd_i32(body, p)
        out_topics = []
        with self.lock:
            for _ in range(ntop):
                topic, p = _rd_str(body, p)
                nparts, p = _rd_i32(body, p)
                parts = []
                for _ in range(nparts):
                    pid, p = _rd_i32(body, p)
                    off, p = _rd_i64(body, p)
                    _, p = _rd_str(body, p)  # metadata
                    self.offsets[(group, topic, pid)] = off
                    parts.append(pid)
                out_topics.append((topic, parts))
        out = bytearray()
        out += struct.pack(">i", len(out_topics))
        for topic, parts in out_topics:
            out += self._str(topic) + struct.pack(">i", len(parts))
            for pid in parts:
                out += struct.pack(">i", pid) + struct.pack(">h", 0)
        return bytes(out)

    def _offset_fetch(self, body) -> bytes:
        p = 0
        group, p = _rd_str(body, p)
        ntop, p = _rd_i32(body, p)
        wants = []
        for _ in range(ntop):
            topic, p = _rd_str(body, p)
            nparts, p = _rd_i32(body, p)
            for _ in range(nparts):
                pid, p = _rd_i32(body, p)
                wants.append((topic, pid))
        out = bytearray()
        out += struct.pack(">i", len(wants))
        with self.lock:
            for topic, pid in wants:
                out += self._str(topic) + struct.pack(">i", 1)
                out += struct.pack(">i", pid)
                out += struct.pack(
                    ">q", self.offsets.get((group, topic, pid), -1)
                )
                out += self._str(None) + struct.pack(">h", 0)
        return bytes(out)


# ---- unit: codec -------------------------------------------------------------


def test_crc32c_known_vector():
    assert crc32c(b"123456789") == 0xE3069283


def test_record_batch_roundtrip_against_independent_codec():
    values = [b"alpha", b"", b"gamma" * 100]
    blob = encode_record_batch(values, 1234)
    assert _fake_parse_batch(blob) == values  # driver enc -> fake dec
    blob2 = _fake_encode_batch(7, values)
    assert decode_record_batches(blob2) == [
        (7, b"alpha"), (8, b""), (9, b"gamma" * 100)
    ]  # fake enc -> driver dec


# ---- driver vs fake ----------------------------------------------------------


@pytest.fixture
def kafka():
    fake = FakeKafka()
    broker = KafkaBroker(
        "127.0.0.1", fake.port, session_timeout_ms=2000,
        fetch_max_wait_ms=100,
    )
    yield fake, broker
    broker.close()
    fake.close()


def _url(fake, topic="requests"):
    return f"kafka://127.0.0.1:{fake.port}/{topic}"


def test_factory_scheme():
    from kubeai_tpu.routing.brokers import make_broker

    b = make_broker("kafka://somehost:9093/reqs")
    assert isinstance(b, KafkaBroker) and b.port == 9093
    assert KafkaBroker.topic_of("kafka://h:9092/reqs") == "reqs"


def test_publish_receive_ack_commits(kafka):
    fake, broker = kafka
    broker.publish(_url(fake), b"m1")
    broker.publish(_url(fake), b"m2")
    got = [broker.receive(_url(fake), timeout=10) for _ in range(2)]
    assert [m.body for m in got] == [b"m1", b"m2"]
    for m in got:
        m.ack()
    deadline = time.time() + 5
    while time.time() < deadline:
        if fake.offsets.get(("kubeai", "requests", 0)) == 2:
            break
        time.sleep(0.05)
    assert fake.offsets.get(("kubeai", "requests", 0)) == 2


def test_nack_redelivers(kafka):
    fake, broker = kafka
    broker.publish(_url(fake), b"retry-me")
    msg = broker.receive(_url(fake), timeout=10)
    assert msg is not None and msg.body == b"retry-me"
    msg.nack()
    again = broker.receive(_url(fake), timeout=10)
    assert again is not None and again.body == b"retry-me"
    again.ack()


def test_committed_offset_resumes_after_restart(kafka):
    fake, broker = kafka
    broker.publish(_url(fake), b"first")
    broker.publish(_url(fake), b"second")
    msg = broker.receive(_url(fake), timeout=10)
    assert msg.body == b"first"
    msg.ack()
    time.sleep(0.2)  # let the commit land
    broker.close()

    b2 = KafkaBroker(
        "127.0.0.1", fake.port, session_timeout_ms=2000,
        fetch_max_wait_ms=100,
    )
    try:
        # close() sent LeaveGroup, so the new member owns the partition
        # immediately and resumes from the committed offset without
        # replaying "first".
        msg2 = b2.receive(_url(fake), timeout=10)
        assert msg2 is not None and msg2.body == b"second"
    finally:
        b2.close()


def test_consumer_survives_fetch_errors(kafka):
    fake, broker = kafka
    fake.fail_next_fetches = 2
    broker.publish(_url(fake), b"after-outage")
    msg = broker.receive(_url(fake), timeout=20)
    assert msg is not None and msg.body == b"after-outage"
    assert fake.fail_next_fetches == 0


def test_two_topics_share_one_group(kafka):
    """One group, two stream topics (the manager's shape): the leader
    must assign each topic to its subscriber, not just its own."""
    fake, broker = kafka
    broker.publish(_url(fake, "reqA"), b"a1")
    broker.publish(_url(fake, "reqB"), b"b1")
    got = set()
    deadline = time.time() + 25
    while len(got) < 2 and time.time() < deadline:
        for t in ("reqA", "reqB"):
            m = broker.receive(_url(fake, t), timeout=1)
            if m is not None:
                m.ack()
                got.add(m.body)
    assert got == {b"a1", b"b1"}


def test_resume_after_retention_truncation(kafka):
    """Committed offset below the log-start offset: the consumer resolves
    the earliest offset via ListOffsets instead of live-locking at 0."""
    fake, broker = kafka
    with fake.lock:
        fake.log("requests", 0).extend([b"old-0", b"old-1", b"live-2"])
        fake.log_start[("requests", 0)] = 2
        fake.offsets[("kubeai", "requests", 0)] = 1  # truncated away
    msg = broker.receive(_url(fake), timeout=20)
    assert msg is not None and msg.body == b"live-2"
    msg.ack()


@pytest.mark.slow
def test_two_members_split_partitions():
    fake = FakeKafka(partitions=2)
    b1 = KafkaBroker(
        "127.0.0.1", fake.port, session_timeout_ms=1500,
        fetch_max_wait_ms=100,
    )
    b2 = KafkaBroker(
        "127.0.0.1", fake.port, session_timeout_ms=1500,
        fetch_max_wait_ms=100,
    )
    try:
        # Preload both partitions directly in the fake's logs.
        with fake.lock:
            fake.log("requests", 0).extend([b"p0-a", b"p0-b"])
            fake.log("requests", 1).extend([b"p1-a", b"p1-b"])
        got: list[bytes] = []
        lock = threading.Lock()

        def drain(b):
            while True:
                m = b.receive(_url(fake), timeout=8)
                if m is None:
                    return
                m.ack()
                with lock:
                    got.append(m.body)

        t1 = threading.Thread(target=drain, args=(b1,))
        t2 = threading.Thread(target=drain, args=(b2,))
        t1.start(); t2.start()
        t1.join(timeout=40); t2.join(timeout=40)
        assert sorted(got) == [b"p0-a", b"p0-b", b"p1-a", b"p1-b"]
    finally:
        b1.close()
        b2.close()
        fake.close()
