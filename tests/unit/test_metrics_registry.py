"""Metrics registry: exposition-format round-trip fidelity, histogram
`le` normalization, and metric-name hygiene for every instrument bundle.

The registry is the autoscaling TRANSPORT (the leader scrapes every
replica's /metrics and decodes it with parse_prometheus_text), so
expose() → parse must be lossless — including label values containing
quotes, backslashes, and commas, which _fmt_labels escapes and the
parser must faithfully unescape."""

import random

import pytest

from kubeai_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Metrics,
    Registry,
    _split_label_pairs,
    lint_registry,
    parse_prometheus_text,
)


# ---- round-trip ---------------------------------------------------------------

NASTY_VALUES = [
    "plain",
    'quote"inside',
    "back\\slash",
    "comma,inside",
    "trailing\\",
    'mix\\",bo\\th"',
    "=equals=",
    '"',
    "\\",
]


def test_counter_gauge_roundtrip_nasty_labels():
    reg = Registry()
    c = Counter("kubeai_rt_total", "c", reg)
    g = Gauge("kubeai_rt_gauge", "g", reg)
    for i, v in enumerate(NASTY_VALUES):
        c.inc(i + 1, model=v)
        g.set(i * 2.5, model=v, zone=v[::-1])
    parsed = parse_prometheus_text(reg.expose())
    for i, v in enumerate(NASTY_VALUES):
        assert parsed[("kubeai_rt_total", (("model", v),))] == i + 1
        key = tuple(sorted([("model", v), ("zone", v[::-1])]))
        assert parsed[("kubeai_rt_gauge", key)] == i * 2.5


def test_histogram_roundtrip_recovers_buckets_sum_count():
    reg = Registry()
    h = Histogram(
        "kubeai_rt_seconds", "h", reg, buckets=(0.1, 1.0, 10.0)
    )
    for val in (0.05, 0.5, 5.0, 50.0):
        h.observe(val, model='m"1')
    parsed = parse_prometheus_text(reg.expose())

    def bucket(le):
        key = tuple(sorted([("le", le), ("model", 'm"1')]))
        return parsed[("kubeai_rt_seconds_bucket", key)]

    assert bucket("0.1") == 1
    assert bucket("1") == 2
    assert bucket("10") == 3
    assert bucket("+Inf") == 4
    assert parsed[("kubeai_rt_seconds_count", (("model", 'm"1'),))] == 4
    assert parsed[("kubeai_rt_seconds_sum", (("model", 'm"1'),))] == (
        pytest.approx(55.55)
    )


def test_roundtrip_property_random_labels():
    """Property-style sweep: random label sets over an alphabet loaded
    with the exposition format's special characters must survive
    expose() → parse exactly."""
    rng = random.Random(7)
    alphabet = 'ab\\",=x '
    reg = Registry()
    c = Counter("kubeai_prop_total", "c", reg)
    expected = {}
    for i in range(60):
        val = "".join(
            rng.choice(alphabet) for _ in range(rng.randrange(1, 9))
        )
        c.inc(1, model=val, idx=str(i))
        key = tuple(sorted([("model", val), ("idx", str(i))]))
        expected[key] = expected.get(key, 0) + 1
    parsed = parse_prometheus_text(reg.expose())
    for key, count in expected.items():
        assert parsed[("kubeai_prop_total", key)] == count


def test_large_counter_values_do_not_truncate():
    # %g would render 123456789 as 1.23457e+08 — a real token counter
    # passes 1e6 within minutes.
    reg = Registry()
    c = Counter("kubeai_big_total", "c", reg)
    c.inc(123_456_789, model="m")
    parsed = parse_prometheus_text(reg.expose())
    assert parsed[("kubeai_big_total", (("model", "m"),))] == 123_456_789


def test_split_label_pairs_tracks_escape_state():
    # An escaped quote inside a value must not toggle the in-quotes flag.
    pairs = _split_label_pairs('a="x\\",y",b="z"')
    assert pairs == ['a="x\\",y"', 'b="z"']
    # Escaped backslash before the closing quote.
    pairs = _split_label_pairs('a="x\\\\",b="z"')
    assert pairs == ['a="x\\\\"', 'b="z"']


# ---- histogram semantics ------------------------------------------------------


def test_histogram_le_rendering_is_g_style():
    reg = Registry()
    h = Histogram("kubeai_le_seconds", "h", reg)  # default buckets
    h.observe(0.003)
    text = reg.expose()
    assert 'le="0.005"' in text
    assert 'le="1"' in text  # int bucket renders bare
    assert 'le="1.0"' not in text
    assert 'le="+Inf"' in text
    # Float-typed integral bounds normalize identically.
    reg2 = Registry()
    h2 = Histogram(
        "kubeai_le2_seconds", "h", reg2, buckets=(1.0, 2.0)
    )
    h2.observe(0.5)
    assert 'le="1"' in reg2.expose()


def test_histogram_get_returns_observation_count():
    h = Histogram("kubeai_get_seconds", "h", None)
    assert h.get() == 0
    h.observe(0.2)
    h.observe(0.4)
    h.observe(9.0, model="m")
    assert h.get() == 2
    assert h.get(model="m") == 1
    assert h.sum_for() == pytest.approx(0.6)
    assert h.sum_for(model="m") == pytest.approx(9.0)


def test_histogram_bucket_counts_cumulative_once():
    h = Histogram("kubeai_cum_seconds", "h", None, buckets=(1.0, 2.0))
    h.observe(0.5)
    lines = h.collect()
    by_le = {
        line.split(" ")[0]: int(line.split(" ")[1])
        for line in lines
        if "_bucket" in line
    }
    # One observation <= 1.0 must count exactly once in every le >= it.
    assert by_le['kubeai_cum_seconds_bucket{le="1"}'] == 1
    assert by_le['kubeai_cum_seconds_bucket{le="2"}'] == 1
    assert by_le['kubeai_cum_seconds_bucket{le="+Inf"}'] == 1


# ---- metric-name hygiene ------------------------------------------------------


def _bundle_registries():
    yield "operator", Metrics().registry
    from kubeai_tpu.engine.server import EngineMetrics

    yield "engine", EngineMetrics().registry


def test_every_instrument_bundle_passes_hygiene():
    """New instruments can't silently drift from the naming scheme:
    ^kubeai_[a-z0-9_]+$, unique per registry, counters end in _total,
    histograms in _seconds."""
    for name, reg in _bundle_registries():
        assert lint_registry(reg) == [], f"{name} bundle failed hygiene"


def test_lint_catches_violations():
    reg = Registry()
    Counter("kubeai_bad_counter", "no _total suffix", reg)
    Histogram("kubeai_bad_hist", "no _seconds suffix", reg)
    Gauge("not_kubeai_prefixed", "bad prefix", reg)
    Gauge("kubeai_dup", "", reg)
    Gauge("kubeai_dup", "", reg)
    errs = "\n".join(lint_registry(reg))
    assert "kubeai_bad_counter" in errs
    assert "kubeai_bad_hist" in errs
    assert "not_kubeai_prefixed" in errs
    assert "duplicate" in errs
