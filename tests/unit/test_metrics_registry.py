"""Metrics registry: exposition-format round-trip fidelity, histogram
`le` normalization, and metric-name hygiene for every instrument bundle.

The registry is the autoscaling TRANSPORT (the leader scrapes every
replica's /metrics and decodes it with parse_prometheus_text), so
expose() → parse must be lossless — including label values containing
quotes, backslashes, and commas, which _fmt_labels escapes and the
parser must faithfully unescape."""

import random

import pytest

from kubeai_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Metrics,
    Registry,
    _split_label_pairs,
    lint_registry,
    parse_prometheus_text,
)


# ---- round-trip ---------------------------------------------------------------

NASTY_VALUES = [
    "plain",
    'quote"inside',
    "back\\slash",
    "comma,inside",
    "trailing\\",
    'mix\\",bo\\th"',
    "=equals=",
    '"',
    "\\",
]


def test_counter_gauge_roundtrip_nasty_labels():
    reg = Registry()
    c = Counter("kubeai_rt_total", "c", reg)
    g = Gauge("kubeai_rt_gauge", "g", reg)
    for i, v in enumerate(NASTY_VALUES):
        c.inc(i + 1, model=v)
        g.set(i * 2.5, model=v, zone=v[::-1])
    parsed = parse_prometheus_text(reg.expose())
    for i, v in enumerate(NASTY_VALUES):
        assert parsed[("kubeai_rt_total", (("model", v),))] == i + 1
        key = tuple(sorted([("model", v), ("zone", v[::-1])]))
        assert parsed[("kubeai_rt_gauge", key)] == i * 2.5


def test_histogram_roundtrip_recovers_buckets_sum_count():
    reg = Registry()
    h = Histogram(
        "kubeai_rt_seconds", "h", reg, buckets=(0.1, 1.0, 10.0)
    )
    for val in (0.05, 0.5, 5.0, 50.0):
        h.observe(val, model='m"1')
    parsed = parse_prometheus_text(reg.expose())

    def bucket(le):
        key = tuple(sorted([("le", le), ("model", 'm"1')]))
        return parsed[("kubeai_rt_seconds_bucket", key)]

    assert bucket("0.1") == 1
    assert bucket("1") == 2
    assert bucket("10") == 3
    assert bucket("+Inf") == 4
    assert parsed[("kubeai_rt_seconds_count", (("model", 'm"1'),))] == 4
    assert parsed[("kubeai_rt_seconds_sum", (("model", 'm"1'),))] == (
        pytest.approx(55.55)
    )


def test_roundtrip_property_random_labels():
    """Property-style sweep: random label sets over an alphabet loaded
    with the exposition format's special characters must survive
    expose() → parse exactly."""
    rng = random.Random(7)
    alphabet = 'ab\\",=x '
    reg = Registry()
    c = Counter("kubeai_prop_total", "c", reg)
    expected = {}
    for i in range(60):
        val = "".join(
            rng.choice(alphabet) for _ in range(rng.randrange(1, 9))
        )
        c.inc(1, model=val, idx=str(i))
        key = tuple(sorted([("model", val), ("idx", str(i))]))
        expected[key] = expected.get(key, 0) + 1
    parsed = parse_prometheus_text(reg.expose())
    for key, count in expected.items():
        assert parsed[("kubeai_prop_total", key)] == count


def test_large_counter_values_do_not_truncate():
    # %g would render 123456789 as 1.23457e+08 — a real token counter
    # passes 1e6 within minutes.
    reg = Registry()
    c = Counter("kubeai_big_total", "c", reg)
    c.inc(123_456_789, model="m")
    parsed = parse_prometheus_text(reg.expose())
    assert parsed[("kubeai_big_total", (("model", "m"),))] == 123_456_789


def test_split_label_pairs_tracks_escape_state():
    # An escaped quote inside a value must not toggle the in-quotes flag.
    pairs = _split_label_pairs('a="x\\",y",b="z"')
    assert pairs == ['a="x\\",y"', 'b="z"']
    # Escaped backslash before the closing quote.
    pairs = _split_label_pairs('a="x\\\\",b="z"')
    assert pairs == ['a="x\\\\"', 'b="z"']


# ---- histogram semantics ------------------------------------------------------


def test_histogram_le_rendering_is_g_style():
    reg = Registry()
    h = Histogram("kubeai_le_seconds", "h", reg)  # default buckets
    h.observe(0.003)
    text = reg.expose()
    assert 'le="0.005"' in text
    assert 'le="1"' in text  # int bucket renders bare
    assert 'le="1.0"' not in text
    assert 'le="+Inf"' in text
    # Float-typed integral bounds normalize identically.
    reg2 = Registry()
    h2 = Histogram(
        "kubeai_le2_seconds", "h", reg2, buckets=(1.0, 2.0)
    )
    h2.observe(0.5)
    assert 'le="1"' in reg2.expose()


def test_histogram_get_returns_observation_count():
    h = Histogram("kubeai_get_seconds", "h", None)
    assert h.get() == 0
    h.observe(0.2)
    h.observe(0.4)
    h.observe(9.0, model="m")
    assert h.get() == 2
    assert h.get(model="m") == 1
    assert h.sum_for() == pytest.approx(0.6)
    assert h.sum_for(model="m") == pytest.approx(9.0)


def test_histogram_bucket_counts_cumulative_once():
    h = Histogram("kubeai_cum_seconds", "h", None, buckets=(1.0, 2.0))
    h.observe(0.5)
    lines = h.collect()
    by_le = {
        line.split(" ")[0]: int(line.split(" ")[1])
        for line in lines
        if "_bucket" in line
    }
    # One observation <= 1.0 must count exactly once in every le >= it.
    assert by_le['kubeai_cum_seconds_bucket{le="1"}'] == 1
    assert by_le['kubeai_cum_seconds_bucket{le="2"}'] == 1
    assert by_le['kubeai_cum_seconds_bucket{le="+Inf"}'] == 1


# ---- metric-name hygiene ------------------------------------------------------


def _bundle_registries():
    yield "operator", Metrics().registry
    from kubeai_tpu.engine.server import EngineMetrics

    yield "engine", EngineMetrics().registry


def test_every_instrument_bundle_passes_hygiene():
    """New instruments can't silently drift from the naming scheme:
    ^kubeai_[a-z0-9_]+$, unique per registry, counters end in _total,
    histograms in _seconds."""
    for name, reg in _bundle_registries():
        assert lint_registry(reg) == [], f"{name} bundle failed hygiene"


def test_lint_catches_violations():
    reg = Registry()
    Counter("kubeai_bad_counter", "no _total suffix", reg)
    Histogram("kubeai_bad_hist", "no _seconds suffix", reg)
    Gauge("not_kubeai_prefixed", "bad prefix", reg)
    Gauge("kubeai_dup", "", reg)
    Gauge("kubeai_dup", "", reg)
    errs = "\n".join(lint_registry(reg))
    assert "kubeai_bad_counter" in errs
    assert "kubeai_bad_hist" in errs
    assert "not_kubeai_prefixed" in errs
    assert "duplicate" in errs


# ---- exposition hardening (fleet-aggregator scrape input) ---------------------


def test_parse_tolerates_inf_nan_and_exponent_values():
    """Real Prometheus exposition legally carries +Inf/-Inf/NaN samples
    and exponent-format floats — the aggregator's scrape must decode
    them, not crash or skip the whole family."""
    import math

    text = (
        'up{job="a"} +Inf\n'
        'down{job="a"} -Inf\n'
        'weird NaN\n'
        "big 1.5e9\n"
        "tiny 2E-3\n"
    )
    parsed = parse_prometheus_text(text)
    assert parsed[("up", (("job", "a"),))] == float("inf")
    assert parsed[("down", (("job", "a"),))] == float("-inf")
    assert math.isnan(parsed[("weird", ())])
    assert parsed[("big", ())] == 1.5e9
    assert parsed[("tiny", ())] == 2e-3


def test_parse_tolerates_trailing_timestamps():
    """`name{labels} value timestamp` — the optional millisecond
    timestamp must be ignored, never mistaken for the value (the old
    rsplit-once decoder read the timestamp as the sample)."""
    text = (
        'reqs{model="m1"} 25 1722772800000\n'
        "plain 3 1722772800000\n"
        'inf_ts{x="y"} +Inf 1722772800000\n'
    )
    parsed = parse_prometheus_text(text)
    assert parsed[("reqs", (("model", "m1"),))] == 25
    assert parsed[("plain", ())] == 3
    assert parsed[("inf_ts", (("x", "y"),))] == float("inf")


def test_parse_tolerates_brace_inside_quoted_label_value():
    parsed = parse_prometheus_text('m{v="a}b{c"} 7\n')
    assert parsed[("m", (("v", "a}b{c"),))] == 7


def test_parse_skips_garbage_lines_without_raising():
    text = (
        "no_value\n"
        "m{unterminated 4\n"
        "m{} not_a_number\n"
        "ok 1\n"
    )
    parsed = parse_prometheus_text(text)
    assert parsed == {("ok", ()): 1.0}


def test_roundtrip_registry_expose_with_inf_observations():
    """expose() → parse survives a histogram whose +Inf bucket carries
    everything and a counter pushed through exponent-sized values."""
    reg = Registry()
    c = Counter("kubeai_huge_total", "", reg)
    c.inc(1.5e12)
    h = Histogram("kubeai_h_seconds", "", reg, buckets=(0.1, 1))
    h.observe(50.0)  # lands only in +Inf
    parsed = parse_prometheus_text(reg.expose())
    assert parsed[("kubeai_huge_total", ())] == 1.5e12
    assert parsed[("kubeai_h_seconds_bucket", (("le", "+Inf"),))] == 1
    assert parsed[("kubeai_h_seconds_bucket", (("le", "0.1"),))] == 0
    assert parsed[("kubeai_h_seconds_count", ())] == 1


# ---- label-churn hygiene (Registry.remove) ------------------------------------


def _series_count(reg: Registry) -> int:
    """Labelled sample lines currently exposed (HELP/TYPE excluded)."""
    return len(parse_prometheus_text(reg.expose()))


def test_histogram_remove_drops_bucket_sum_count_state():
    reg = Registry()
    h = Histogram("kubeai_churn_seconds", "", reg, buckets=(1,))
    baseline = _series_count(reg)
    h.observe(0.5, endpoint="10.0.0.1:8000")
    assert _series_count(reg) > baseline
    h.remove(endpoint="10.0.0.1:8000")
    assert _series_count(reg) == baseline
    assert h.get(endpoint="10.0.0.1:8000") == 0


def test_endpoint_churn_returns_registry_to_baseline():
    """Endpoints retired by reconcile_endpoints must not leave stale
    per-endpoint breaker series accumulating — after full churn the
    series count returns to its pre-churn baseline."""
    from kubeai_tpu.routing.health import (
        OUTCOME_5XX,
        OUTCOME_SUCCESS,
        BreakerPolicy,
    )
    from kubeai_tpu.routing.loadbalancer import Group

    metrics = Metrics()
    group = Group(
        metrics=metrics, model="m1",
        breaker=BreakerPolicy(consecutive_failures=2, min_samples=1),
    )
    baseline = _series_count(metrics.registry)
    for generation in range(3):
        addrs = {f"10.0.{generation}.{i}:8000": set() for i in range(4)}
        group.reconcile_endpoints(addrs)
        for addr in addrs:
            a, done = group.get_best_addr(
                "LeastLoad", "", "", timeout=1,
                exclude=set(addrs) - {addr},
            )
            # Trip some circuits so BOTH the state gauge and the
            # ejection counter get per-endpoint series.
            done(outcome=OUTCOME_5XX if generation % 2 else OUTCOME_SUCCESS)
    group.reconcile_endpoints({})  # everything retired
    assert _series_count(metrics.registry) == baseline


def test_pod_replacement_churn_leaves_no_stale_lb_series():
    """LB-level churn driven through sync_model (the PR 5 health pass
    replaces pods → new addresses every generation): the registry's
    series count must return to baseline once the pods are gone."""
    from kubeai_tpu.operator.k8s.store import KubeStore
    from kubeai_tpu.routing.health import OUTCOME_CONNECT_ERROR
    from kubeai_tpu.routing.loadbalancer import LoadBalancer

    store = KubeStore()
    metrics = Metrics()
    lb = LoadBalancer(store, metrics=metrics)
    baseline = _series_count(metrics.registry)
    for generation in range(3):
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"model-m1-g{generation}",
                "namespace": "default",
                "labels": {"model": "m1"},
                "annotations": {
                    "model-pod-ip": "127.0.0.1",
                    "model-pod-port": str(9000 + generation),
                },
            },
            "status": {
                "conditions": [{"type": "Ready", "status": "True"}],
                "podIP": "127.0.0.1",
            },
        }
        store.create(pod)
        lb.sync_model("m1")
        addr, done = lb.await_best_address("m1", timeout=1)
        done(outcome=OUTCOME_CONNECT_ERROR, error="gen churn")
        store.delete("Pod", "default", pod["metadata"]["name"])
        lb.sync_model("m1")
    assert _series_count(metrics.registry) == baseline


# ---- shared bucket-quantile estimator (SLO plane + aggregator) ----------------


def test_quantile_estimator_empty_buckets_returns_empty():
    from kubeai_tpu.metrics.registry import quantiles_from_buckets

    assert quantiles_from_buckets([], 0.0, 0.0) == {}
    # Buckets present but zero observations: still no estimate.
    assert quantiles_from_buckets([(0.5, 0.0), (float("inf"), 0.0)],
                                  0.0, 0.0) == {}


def test_quantile_estimator_single_inf_bucket():
    """A histogram that is one +Inf bucket carries no finite bound to
    report — the estimator says +Inf rather than inventing a number."""
    from kubeai_tpu.metrics.registry import quantiles_from_buckets

    out = quantiles_from_buckets([(float("inf"), 10.0)], 10.0, 25.0)
    assert out["count"] == 10.0
    assert out["mean_s"] == 2.5
    assert out["p95_s"] == float("inf")


def test_quantile_estimator_reports_containing_bucket_bound():
    from kubeai_tpu.metrics.registry import quantiles_from_buckets

    buckets = [(0.1, 50.0), (0.5, 90.0), (1.0, 100.0),
               (float("inf"), 100.0)]
    out = quantiles_from_buckets(buckets, 100.0, 30.0)
    assert out["p50_s"] == 0.1
    assert out["p95_s"] == 1.0
    # A quantile landing in +Inf reports the largest FINITE bound.
    buckets = [(0.1, 100.0), (float("inf"), 101.0)]
    assert quantiles_from_buckets(buckets, 101.0, 11.0)["p99_s"] == 0.1


def test_count_over_threshold_edge_cases():
    from kubeai_tpu.metrics.registry import count_over_threshold

    # Zero observations / no buckets: nothing can be over.
    assert count_over_threshold([], 0.0, 0.5) == 0.0
    assert count_over_threshold([(0.5, 0.0)], 0.0, 0.5) == 0.0
    buckets = [(0.25, 60.0), (0.5, 80.0), (1.0, 95.0),
               (float("inf"), 100.0)]
    # Threshold on a bound: observations in that bucket count as good.
    assert count_over_threshold(buckets, 100.0, 0.5) == 20.0
    # Threshold between bounds resolves to the NEXT bound (conservative
    # toward the service: in-bucket observations may be below it).
    assert count_over_threshold(buckets, 100.0, 0.3) == 20.0
    # Threshold past every finite bound: the buckets cannot see up
    # there, so badness is 0, not a guess.
    assert count_over_threshold(buckets, 100.0, 5.0) == 0.0


def test_estimator_is_shared_by_aggregator_and_slo_paths():
    """One estimator, two consumers: the aggregator's per-endpoint
    quantile view and the SLO evaluator's burn-rate read must flow
    through the same functions so they can never disagree about the
    same scrape."""
    from kubeai_tpu.fleet import aggregator as agg_mod
    from kubeai_tpu.fleet import slo as slo_mod
    from kubeai_tpu.metrics import registry as reg_mod

    assert agg_mod.quantiles_from_buckets is reg_mod.quantiles_from_buckets
    assert slo_mod.quantiles_from_buckets is reg_mod.quantiles_from_buckets
    assert slo_mod.count_over_threshold is reg_mod.count_over_threshold


# ---- trace-id exemplars -------------------------------------------------------


def test_histogram_exemplars_keep_last_trace_per_bucket():
    reg = Registry()
    h = Histogram("kubeai_ex_seconds", "h", reg, buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="req-a", model="m")
    h.observe(0.07, exemplar="req-b", model="m")   # same bucket: wins
    h.observe(0.5, exemplar="req-c", model="m")
    h.observe(30.0, exemplar="req-inf", model="m")  # overflow bucket
    assert h.exemplars(model="m") == {
        "0.1": "req-b", "1": "req-c", "+Inf": "req-inf",
    }
    # Exemplars are per label set; an unobserved set has none.
    assert h.exemplars(model="other") == {}


def test_histogram_exemplar_is_optional_and_unexposed():
    """Exemplars never leak into the exposition text (the scrape
    transport stays plain Prometheus); omitting one records nothing."""
    reg = Registry()
    h = Histogram("kubeai_ex2_seconds", "h", reg, buckets=(1.0,))
    h.observe(0.5)
    h.observe(0.6, exemplar="req-z")
    assert "req-z" not in reg.expose()
    assert h.exemplars() == {"1": "req-z"}
