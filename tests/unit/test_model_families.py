"""Gemma / Qwen2 / Mixtral parity against the HF reference implementations
and engine integration for each family."""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeai_tpu.engine import Engine, EngineConfig
from kubeai_tpu.engine.sampling import SamplingParams
from kubeai_tpu.engine.weights import load_hf_config, load_params
from kubeai_tpu.models.registry import get_model_family

GREEDY = SamplingParams(temperature=0.0, max_tokens=6)


def _roundtrip(family_name, hf_model, out_dir, prompt=(3, 14, 15, 92, 65)):
    import torch

    cfg = get_model_family(family_name).config_from_hf(
        load_hf_config(str(out_dir))
    )
    params = load_params(family_name, str(out_dir), cfg, dtype=jnp.float32)
    fam = get_model_family(family_name)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, 10)).astype(np.int32)
    ours, _, _ = fam.prefill(
        params, cfg, jnp.asarray(tokens), jnp.asarray([10], jnp.int32)
    )
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens.astype(np.int64))).logits[0, -1]
    np.testing.assert_allclose(
        np.asarray(ours)[0], theirs.numpy(), rtol=5e-3, atol=5e-3
    )

    # Greedy generation parity through the engine.
    eng = Engine(
        family_name, cfg, params,
        cfg=EngineConfig(num_slots=2, max_seq_len=64),
    )
    ours_gen = eng.generate([list(prompt)], GREEDY)[0]
    with torch.no_grad():
        out = hf_model.generate(
            torch.tensor([list(prompt)]), max_new_tokens=6,
            do_sample=False, pad_token_id=0,
        )
    assert ours_gen == out[0, len(prompt):].tolist()


@pytest.mark.slow
def test_qwen2_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_cfg = Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, max_position_embeddings=512,
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    model = Qwen2ForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    _roundtrip("qwen", model, tmp_path)


@pytest.mark.slow
def test_gemma_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import GemmaConfig as HFGemmaConfig
    from transformers import GemmaForCausalLM

    hf_cfg = HFGemmaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rope_theta=10000.0, max_position_embeddings=512,
    )
    torch.manual_seed(2)
    model = GemmaForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    _roundtrip("gemma", model, tmp_path)


@pytest.mark.slow
def test_mixtral_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig as HFMixtralConfig
    from transformers import MixtralForCausalLM

    hf_cfg = HFMixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        rope_theta=10000.0, max_position_embeddings=512,
    )
    torch.manual_seed(3)
    model = MixtralForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    _roundtrip("mixtral", model, tmp_path)


@pytest.mark.slow
def test_mixtral_expert_parallel_matches_single(devices8):
    """EP: experts sharded over the tp axis give identical outputs."""
    from kubeai_tpu.models import mixtral
    from kubeai_tpu.parallel.mesh import MeshConfig, build_mesh

    cfg = mixtral.MixtralConfig.tiny()
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(num_slots=2, max_seq_len=64)
    eng1 = Engine("mixtral", cfg, params, cfg=ecfg)
    mesh = build_mesh(MeshConfig(dp=1, sp=1, tp=4), devices=devices8[:4])
    eng4 = Engine("mixtral", cfg, params, mesh=mesh, cfg=ecfg)
    prompts = [[1, 2, 3, 4], [9, 8, 7]]
    assert eng1.generate(prompts, GREEDY) == eng4.generate(prompts, GREEDY)


@pytest.mark.slow
def test_gemma2_parity(tmp_path):
    """Gemma-2: sandwich norms + attention/final logit softcapping."""
    torch = pytest.importorskip("torch")
    from transformers import Gemma2Config as HFG2, Gemma2ForCausalLM

    hf_cfg = HFG2(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rope_theta=10000.0, max_position_embeddings=512,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        query_pre_attn_scalar=16, sliding_window=512,  # > seq len: behaves as full attention
    )
    torch.manual_seed(4)
    model = Gemma2ForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    _roundtrip("gemma", model, tmp_path)


@pytest.mark.slow
def test_gemma2_sliding_window_parity(tmp_path):
    """Gemma-2 sliding-window attention ENFORCED: HF parity with a window
    smaller than the sequence (alternating local/global layers), plus a
    divergence check against the unwindowed config."""
    torch = pytest.importorskip("torch")
    from transformers import Gemma2Config as HFG2, Gemma2ForCausalLM

    hf_cfg = HFG2(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rope_theta=10000.0, max_position_embeddings=512,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        query_pre_attn_scalar=16, sliding_window=8,  # << prompt length
        attn_implementation="eager",
    )
    torch.manual_seed(11)
    model = Gemma2ForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    prompt = tuple(int(x) for x in
                   np.random.default_rng(2).integers(1, 256, 24))
    _roundtrip("gemma", model, tmp_path, prompt=prompt)

    # Divergence: ignoring the window (Gemma-1 style full attention) must
    # change the logits once the prompt exceeds the window.
    import dataclasses

    from kubeai_tpu.models import gemma as gm

    cfg = get_model_family("gemma").config_from_hf(
        load_hf_config(str(tmp_path))
    )
    assert cfg.sliding_window == 8
    params = load_params("gemma", str(tmp_path), cfg, dtype=jnp.float32)
    tokens = jnp.asarray([list(prompt)], jnp.int32)
    lengths = jnp.asarray([len(prompt)], jnp.int32)
    with_win, _, _ = gm.prefill(params, cfg, tokens, lengths)
    no_win, _, _ = gm.prefill(
        params, dataclasses.replace(cfg, sliding_window=None), tokens, lengths
    )
    assert float(jnp.max(jnp.abs(with_win - no_win))) > 1e-3

    # Short sequences (<= window) are unaffected by windowing.
    short = tokens[:, :6]
    sl = jnp.asarray([6], jnp.int32)
    a, _, _ = gm.prefill(params, cfg, short, sl)
    b, _, _ = gm.prefill(
        params, dataclasses.replace(cfg, sliding_window=None), short, sl
    )
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_gemma_mixtral_paged_equivalence():
    """Slot-vs-paged decode equivalence for the non-llama families
    (gemma2 incl. alternating sliding-window layers; mixtral MoE)."""
    import dataclasses

    from kubeai_tpu.models import gemma as gm, mixtral as mx

    g2 = dataclasses.replace(
        gm.GemmaConfig.tiny(), sandwich_norms=True,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=8,
    )
    for fam, cfg, params in (
        ("gemma", g2, gm.init_params(g2, jax.random.PRNGKey(1))),
        (
            "mixtral",
            mx.MixtralConfig.tiny(),
            mx.init_params(mx.MixtralConfig.tiny(), jax.random.PRNGKey(2)),
        ),
    ):
        prompts = [
            np.random.default_rng(5).integers(1, 200, n).tolist()
            for n in (5, 19, 33)
        ]
        sp = SamplingParams(temperature=0.0, max_tokens=10)
        outs = {}
        for mode in ("slot", "paged"):
            eng = Engine(
                fam, cfg, params,
                cfg=EngineConfig(
                    num_slots=3, max_seq_len=64, cache_mode=mode,
                    page_size=16, decode_chunk=4,
                ),
            )
            assert eng.cache_mode == mode
            outs[mode] = eng.generate(prompts, sp)
        assert outs["slot"] == outs["paged"], fam


@pytest.mark.parametrize(
    "rope_scaling",
    [
        {"rope_type": "linear", "factor": 2.0},
        {"rope_type": "yarn", "factor": 4.0,
         "original_max_position_embeddings": 32},
        {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
         "high_freq_factor": 4.0, "original_max_position_embeddings": 32},
    ],
    ids=["linear", "yarn", "llama3"],
)
@pytest.mark.slow
def test_rope_scaling_variant_parity(tmp_path, rope_scaling):
    """Context-extension rope variants match HF exactly (logits + greedy),
    with prompts LONGER than original_max_position_embeddings (32) so
    the scaled bands actually engage (engine context caps at 64)."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFLlama, LlamaForCausalLM

    hf_cfg = HFLlama(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=10000.0,
        rope_scaling=dict(rope_scaling),
        attn_implementation="eager",
    )
    torch.manual_seed(7)
    model = LlamaForCausalLM(hf_cfg)
    model.eval()
    out_dir = tmp_path / rope_scaling["rope_type"]
    model.save_pretrained(out_dir, safe_serialization=True)
    prompt = tuple(int(x) for x in
                   np.random.default_rng(9).integers(1, 256, 56))
    _roundtrip("llama", model, out_dir, prompt=prompt)


def test_dynamic_ntk_frequencies_rescale():
    """Dynamic NTK: frequencies rescale at the serving context and reduce
    to the base frequencies when no extension is configured."""
    from kubeai_tpu.ops.rope import rope_frequencies

    base = rope_frequencies(32, 10000.0, None)
    dyn = rope_frequencies(
        32, 10000.0,
        {"rope_type": "dynamic", "factor": 4.0,
         "original_max_position_embeddings": 2048,
         "max_position_embeddings": 8192},
    )
    # Extended context lowers every non-constant frequency.
    assert (dyn[1:] < base[1:]).all()
    # Without original_max_position_embeddings, HF reads the model's
    # context length — the top-level fallback must engage, not no-op.
    fallback = rope_frequencies(
        32, 10000.0, {"rope_type": "dynamic", "factor": 4.0},
        max_position_embeddings=2048,
    )
    assert (fallback[1:] < base[1:]).all()
    import pytest as _pytest

    with _pytest.raises(ValueError):
        rope_frequencies(32, 10000.0, {"rope_type": "dynamic", "factor": 4.0})
    # "default" is HF's explicit no-scaling marker.
    np.testing.assert_allclose(
        rope_frequencies(32, 10000.0, {"rope_type": "default"}), base
    )


# ---- gemma chunked prefill (round 5: enables chunked admission + the
# prefix cache for the family) ------------------------------------------------


def _gemma_chunk_vs_whole(cfg, seed=3):
    from kubeai_tpu.models import gemma as G

    rng = np.random.default_rng(seed)
    params = G.init_params(cfg, jax.random.PRNGKey(seed))
    S, L = 50, 64
    tokens = rng.integers(1, cfg.vocab_size, S)
    want_logits, k_want, v_want = G.prefill(
        params, cfg, jnp.asarray(tokens[None]), jnp.asarray([S])
    )
    C = 16
    k_slot = jnp.zeros((cfg.num_layers, L, cfg.num_kv_heads, cfg.head_dim),
                       jnp.float32)
    v_slot = jnp.zeros_like(k_slot)
    logits = None
    n_chunks = -(-S // C)
    for i in range(n_chunks):
        start = i * C if i < n_chunks - 1 else S - C
        chunk = tokens[start:start + C]
        logits, k_slot, v_slot = G.prefill_chunk(
            params, cfg, jnp.asarray(chunk[None]), jnp.asarray(start),
            jnp.asarray(S), k_slot, v_slot,
            want_logits=(i == n_chunks - 1),
        )
    np.testing.assert_allclose(
        np.asarray(k_slot[:, :S]),
        np.asarray(k_want[:, 0], np.float32),
        atol=2e-2, rtol=2e-2,
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(want_logits), atol=2e-2, rtol=2e-2
    )
    assert int(jnp.argmax(logits)) == int(jnp.argmax(want_logits))


@pytest.mark.slow
def test_gemma_prefill_chunk_matches_whole_prompt():
    from kubeai_tpu.models import gemma as G

    cfg = dc.replace(G.GemmaConfig.tiny(), dtype=jnp.float32)
    _gemma_chunk_vs_whole(cfg)


@pytest.mark.slow
def test_gemma2_prefill_chunk_matches_whole_prompt():
    """Gemma-2 specifics through the chunk graph: sandwich norms, logit
    softcaps, query scale, and the per-layer sliding-window alternation
    with a window SMALLER than the prompt."""
    from kubeai_tpu.models import gemma as G

    cfg = dc.replace(
        G.GemmaConfig.tiny(), dtype=jnp.float32, sandwich_norms=True,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        query_pre_attn_scalar=16.0, sliding_window=8,
    )
    _gemma_chunk_vs_whole(cfg, seed=5)


@pytest.mark.slow
def test_gemma2_engine_chunked_and_prefix_cache():
    """The engine's chunked admission AND prefix cache serve gemma2
    exactly like whole-prompt admission."""
    from kubeai_tpu.engine import Engine, EngineConfig
    from kubeai_tpu.engine.sampling import SamplingParams
    from kubeai_tpu.models import gemma as G

    cfg = dc.replace(
        G.GemmaConfig.tiny(), sandwich_norms=True,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        query_pre_attn_scalar=16.0, sliding_window=8,
    )
    params = G.init_params(cfg, jax.random.PRNGKey(1))
    rng = np.random.default_rng(7)
    system = rng.integers(1, cfg.vocab_size, 48).tolist()
    prompts = [system + rng.integers(1, cfg.vocab_size, 12).tolist()
               for _ in range(2)]
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    base = dict(num_slots=2, max_seq_len=256, page_size=16)
    want = Engine("gemma", cfg, params, cfg=EngineConfig(**base)).generate(
        prompts, sp
    )
    chunked = Engine(
        "gemma", cfg, params, cfg=EngineConfig(prefill_chunk=32, **base)
    )
    assert chunked.generate(prompts, sp) == want
    apc = Engine(
        "gemma", cfg, params,
        cfg=EngineConfig(prefill_chunk=32, prefix_cache=True, **base),
    )
    assert apc.generate(prompts, sp) == want
    assert apc.prefix_stats["hit_tokens"] > 0


@pytest.mark.slow
def test_mixtral_engine_chunked_and_prefix_cache():
    """Mixtral (dense top-k MoE) through the engine's chunked admission
    and prefix cache — streams exact vs whole-prompt admission."""
    from kubeai_tpu.models import mixtral as MX

    cfg = MX.MixtralConfig.tiny()
    params = MX.init_params(cfg, jax.random.PRNGKey(1))
    rng = np.random.default_rng(8)
    system = rng.integers(1, cfg.vocab_size, 48).tolist()
    prompts = [system + rng.integers(1, cfg.vocab_size, 12).tolist()
               for _ in range(2)]
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    base = dict(num_slots=2, max_seq_len=256, page_size=16)
    want = Engine("mixtral", cfg, params, cfg=EngineConfig(**base)).generate(
        prompts, sp
    )
    chunked = Engine(
        "mixtral", cfg, params, cfg=EngineConfig(prefill_chunk=32, **base)
    )
    assert chunked.generate(prompts, sp) == want
    apc = Engine(
        "mixtral", cfg, params,
        cfg=EngineConfig(prefill_chunk=32, prefix_cache=True, **base),
    )
    assert apc.generate(prompts, sp) == want
    assert apc.prefix_stats["hit_tokens"] > 0
