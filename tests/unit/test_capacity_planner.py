"""Cluster-wide capacity planner: priority bin-packing onto the chip
budget, scheduling-class preemption, slice right-sizing, staleness
fallback — deterministic sim invariants plus focused unit tests, plus
the satellite hardening suites (ceil_div, pod_chip_count)."""

import json
import os
import sys

import pytest

from testutil import http_get

from kubeai_tpu.autoscaler.autoscaler import ceil_div
from kubeai_tpu.config import System
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import Model, ModelSpec, Scheduling
from kubeai_tpu.fleet import (
    CapacityPlanner,
    model_chips_per_replica,
    model_scheduling_class,
)
from kubeai_tpu.metrics.registry import Metrics
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.loadbalancer import LoadBalancer
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.routing.openai_server import OpenAIServer
from kubeai_tpu.routing.proxy import ModelProxy

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
)

pytestmark = pytest.mark.planner


# ---- deterministic sim (benchmarks/capacity_planner_sim.py) ------------------


def test_capacity_planner_sim_invariants():
    """Tier-1 contract: (a) no realtime SLO violation persists while
    feasible chips sit idle, (b) batch preempted before realtime is
    throttled, (c) allocated chips never exceed the inventory, (d)
    abundant budget = no-op equivalence with the uncoordinated
    autoscaler — plus right-sizing, joint disagg damping, preemption
    marking, and stale-snapshot fallback."""
    from benchmarks.capacity_planner_sim import ALL_CHECKS, run_sim

    result = run_sim()
    for check in ALL_CHECKS:
        check(result)


# ---- ceil_div (shared replicas-from-signal idiom) ----------------------------


def test_ceil_div_values():
    assert ceil_div(0, 1) == 0
    assert ceil_div(1, 1) == 1
    assert ceil_div(7, 2) == 4
    assert ceil_div(8, 2) == 4
    assert ceil_div(0.1, 1) == 1
    assert ceil_div(35, 10) == 4
    assert ceil_div(2.7, 0.8) == 4  # float target (utilization fraction)


def test_ceil_div_zero_divisor_raises():
    with pytest.raises(ValueError):
        ceil_div(5, 0)


def test_ceil_div_negative_divisor_raises():
    with pytest.raises(ValueError):
        ceil_div(5, -2)


# ---- pod_chip_count hardening (satellite) ------------------------------------


def _pod_with_resources(resources):
    return {
        "metadata": {"name": "p"},
        "spec": {"containers": [{"name": "c", "resources": resources}]},
    }


def test_pod_chip_count_valid_shapes():
    assert k8sutils.pod_chip_count(
        _pod_with_resources({"limits": {"google.com/tpu": "4"}})
    ) == 4
    assert k8sutils.pod_chip_count(
        _pod_with_resources({"requests": {"google.com/tpu": 8}})
    ) == 8
    # Limits win over requests (scheduler semantics).
    assert k8sutils.pod_chip_count(
        _pod_with_resources({
            "limits": {"google.com/tpu": "2"},
            "requests": {"google.com/tpu": "8"},
        })
    ) == 2
    # The `4.0` float spelling of an integral quantity is tolerated.
    assert k8sutils.pod_chip_count(
        _pod_with_resources({"limits": {"google.com/tpu": "4.0"}})
    ) == 4


@pytest.mark.parametrize(
    "resources",
    [
        {"limits": {"google.com/tpu": "four"}},  # non-numeric string
        {"limits": {"google.com/tpu": "500m"}},  # milli-quantity
        {"limits": {"google.com/tpu": "2.5"}},   # fractional chip
        {"limits": {"google.com/tpu": "-4"}},    # negative
        {"limits": {"google.com/tpu": None}},    # explicit null
        {"limits": "bogus"},                     # limits not a mapping
        "bogus",                                 # resources not a mapping
        {},                                      # absent requests/limits
        None,                                    # resources absent
    ],
)
def test_pod_chip_count_malformed_counts_zero(resources):
    """Every malformed shape returns 0 with a warning — never raises —
    so one bad manifest cannot blind the fleet chip inventory."""
    assert k8sutils.pod_chip_count(_pod_with_resources(resources)) == 0


def test_pod_chip_count_malformed_container_does_not_blind_others():
    pod = {
        "metadata": {"name": "p"},
        "spec": {"containers": [
            {"name": "bad", "resources": {"limits": {"google.com/tpu": "x"}}},
            {"name": "good", "resources": {"limits": {"google.com/tpu": "4"}}},
            "not-a-container",
        ]},
    }
    assert k8sutils.pod_chip_count(pod) == 4


def test_pod_chip_count_missing_spec():
    assert k8sutils.pod_chip_count({}) == 0
    assert k8sutils.pod_chip_count({"spec": {}}) == 0


def test_node_chip_capacity_and_shape():
    node = {
        "metadata": {"name": "n", "labels": {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "2x4",
        }},
        "status": {"allocatable": {"google.com/tpu": "8"}},
    }
    assert k8sutils.node_chip_capacity(node) == 8
    assert k8sutils.node_slice_shape(node) == "tpu-v5-lite-podslice/2x4"
    # Allocatable wins over capacity; malformed counts zero.
    node["status"] = {
        "allocatable": {"google.com/tpu": "oops"},
        "capacity": {"google.com/tpu": "8"},
    }
    assert k8sutils.node_chip_capacity(node) == 0
    assert k8sutils.node_chip_capacity({"metadata": {"name": "n"}}) == 0


# ---- planner unit behavior ---------------------------------------------------


def _mk_model(name, cls="standard", replicas=1, **kw):
    return Model(
        name=name,
        spec=ModelSpec(
            url="hf://org/x", engine="KubeAITPU",
            features=["TextGeneration"], replicas=replicas,
            min_replicas=0, max_replicas=10, target_requests=10,
            scale_down_delay_seconds=0,
            scheduling=Scheduling(default_priority=cls),
            **kw,
        ),
    )


def test_model_scheduling_class_defaults():
    assert model_scheduling_class(_mk_model("a", "realtime")) == "realtime"
    assert model_scheduling_class(_mk_model("a", "batch")) == "batch"
    m = _mk_model("a")
    m.spec.scheduling.default_priority = ""
    assert model_scheduling_class(m) == "standard"


def test_model_chips_per_replica_sources():
    m = _mk_model("a")
    # Observed pods win.
    assert model_chips_per_replica(
        m, None, {"total": 2, "chips": 8}
    ) == 4
    # Resource-profile fallback: name:count multiplies the profile chips.
    cfg = System()
    cfg.default_and_validate()
    from kubeai_tpu.config.system import ResourceProfile

    cfg.resource_profiles["tpu-v5e"] = ResourceProfile(
        requests={"google.com/tpu": "4"}
    )
    m.spec.resource_profile = "tpu-v5e:2"
    assert model_chips_per_replica(m, cfg, {}) == 8
    # Nothing sizable → 1 (a replica still costs something).
    m.spec.resource_profile = ""
    assert model_chips_per_replica(m, cfg, {}) == 1


class _StubFleet:
    def __init__(self, snap):
        self.snap = snap

    def snapshot(self):
        return self.snap


def _snapshot(ts, models=None, budget=None):
    return {
        "ts": ts,
        "models": models or {},
        "chips": {
            "total": 0, "by_shape": {}, "pods_by_shape": {},
            "budget": budget or {
                "total": 0, "by_shape": {}, "nodes_by_shape": {},
                "slice_chips": {},
            },
        },
    }


def _planner(store, snap, clock_now=1000.0, **kw):
    mc = ModelClient(store)
    return CapacityPlanner(
        fleet=_StubFleet(snap), model_client=mc, store=store,
        metrics=Metrics(), interval_s=1.0, staleness_s=3.0,
        clock=lambda: clock_now, **kw,
    )


def test_unknown_budget_plans_unconstrained():
    """A cluster with no Node chip capacity has an unknown budget: the
    plan allocates every desire, preempts nothing — pre-planner
    behavior, not a zero-capacity lockdown."""
    store = KubeStore()
    store.create(_mk_model("m", "batch", replicas=3).to_dict())
    snap = _snapshot(1000.0)
    p = _planner(store, snap)
    plan = p.tick()
    assert plan is not None and plan["budget_known"] is False
    rec = plan["models"]["m"]
    assert rec["allocated_replicas"] == rec["target_replicas"]
    assert rec["preempted_replicas"] == 0
    assert p.allocation_for("m") == {
        "replicas": rec["allocated_replicas"], "class": "batch",
        "plan_ts": plan["ts"], "prewarm_replicas": 0,
    }


def test_fixed_models_reserve_chips_off_the_top():
    """An autoscaling-disabled model is not under plan control but its
    chips reduce what arbitration can hand out."""
    store = KubeStore()
    fixed = _mk_model("fixed", "standard", replicas=2)
    fixed.spec.autoscaling_disabled = True
    store.create(fixed.to_dict())
    store.create(_mk_model("wants", "realtime", replicas=1).to_dict())
    budget = {
        "total": 12, "by_shape": {"s4": 12}, "nodes_by_shape": {"s4": 3},
        "slice_chips": {"s4": 4},
    }
    models = {
        "fixed": {"pods": {"total": 2, "chips": 8},
                  "replicas": {"unified": 2}, "endpoints": {},
                  "queue": {"depth": 0, "oldest_wait_s": 0,
                            "per_class": {}}},
        "wants": {"pods": {"total": 1, "chips": 4},
                  "replicas": {"unified": 1},
                  "endpoints": {
                      "a:1": {"stale": False, "active_requests": 25.0},
                  },
                  "queue": {"depth": 0, "oldest_wait_s": 0,
                            "per_class": {}}},
    }
    p = _planner(store, _snapshot(1000.0, models, budget))
    plan = p.tick()
    f = plan["models"]["fixed"]
    assert f["kind"] == "fixed" and f["chips_allocated"] == 8
    assert p.allocation_for("fixed") is None  # not under plan control
    w = plan["models"]["wants"]
    # 25 active / 10 target = 3 desired, but only 4 chips remain after
    # the fixed reservation.
    assert w["desired_replicas"] == 3
    assert w["allocated_replicas"] == 1
    assert w["throttled_replicas"] == 2
    assert plan["allocated_chips"]["total"] == 12


def test_allocation_for_goes_stale_with_the_clock():
    store = KubeStore()
    store.create(_mk_model("m", "standard", replicas=1).to_dict())
    now = {"t": 1000.0}
    mc = ModelClient(store)
    p = CapacityPlanner(
        fleet=_StubFleet(_snapshot(1000.0)), model_client=mc,
        store=store, metrics=Metrics(), interval_s=1.0, staleness_s=3.0,
        clock=lambda: now["t"],
    )
    assert p.tick() is not None
    assert p.allocation_for("m") is not None
    now["t"] = 1010.0  # plan aged past staleness
    assert p.allocation_for("m") is None
    # And a stale SNAPSHOT refuses to plan at all.
    assert p.tick() is None
    assert p.metrics.planner_stale_ticks.get() >= 1


def test_leader_gating_and_forced_tick():
    class Follower:
        is_leader = False

    store = KubeStore()
    store.create(_mk_model("m").to_dict())
    p = _planner(store, _snapshot(1000.0), leader=Follower())
    assert p.tick() is None  # followers do not plan...
    assert p.tick(force=True) is not None  # ...unless forced (reads)


def test_plan_endpoint_real_http():
    """Acceptance: GET /v1/fleet/plan serves the latest plan with the
    budget/allocation arithmetic; 404 when no planner is configured."""
    store = KubeStore()
    store.create(_mk_model("m", "realtime", replicas=1).to_dict())
    metrics = Metrics()
    mc = ModelClient(store)
    lb = LoadBalancer(store)
    budget = {
        "total": 8, "by_shape": {"s4": 8}, "nodes_by_shape": {"s4": 2},
        "slice_chips": {"s4": 4},
    }
    models = {
        "m": {"pods": {"total": 1, "chips": 4},
              "replicas": {"unified": 1},
              "endpoints": {"a:1": {"stale": False,
                                    "active_requests": 15.0}},
              "queue": {"depth": 0, "oldest_wait_s": 0, "per_class": {}}},
    }
    planner = CapacityPlanner(
        fleet=_StubFleet(_snapshot(1000.0, models, budget)),
        model_client=mc, store=store, metrics=metrics,
        interval_s=1.0, staleness_s=3.0, clock=lambda: 1000.0,
    )
    server = OpenAIServer(
        ModelProxy(lb, mc, metrics=metrics), mc, metrics=metrics,
        planner=planner,
    )
    server.start()
    try:
        status, body = http_get(
            f"127.0.0.1:{server.port}", "/v1/fleet/plan", timeout=30
        )
        assert status == 200
        payload = json.loads(body)
        assert payload["object"] == "fleet.plan"
        assert payload["plan_available"] is True
        assert payload["budget"]["total"] == 8
        assert payload["models"]["m"]["allocated_replicas"] == 2
        assert payload["models"]["m"]["telemetry_source"] == "aggregator"

        bare = OpenAIServer(
            ModelProxy(lb, mc, metrics=metrics), mc, metrics=metrics
        )
        bare.start()
        try:
            status, _ = http_get(
                f"127.0.0.1:{bare.port}", "/v1/fleet/plan", timeout=30
            )
            assert status == 404
        finally:
            bare.stop()
    finally:
        server.stop()


def test_preempt_annotation_round_trip():
    """Victim marking is idempotent and self-clearing: pods marked while
    preempted, unmarked once the model is no longer squeezed."""
    store = KubeStore()
    store.create(_mk_model("b", "batch", replicas=2).to_dict())
    for j in range(2):
        store.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": f"model-b-{j}", "namespace": "default",
                "labels": {md.POD_MODEL_LABEL: "b"},
                "creationTimestamp": float(j),
            },
            "spec": {"containers": [{
                "name": "s",
                "resources": {"limits": {"google.com/tpu": "4"}},
            }]},
            "status": {},
        })
    budget = {
        "total": 4, "by_shape": {"s4": 4}, "nodes_by_shape": {"s4": 1},
        "slice_chips": {"s4": 4},
    }
    models = {
        "b": {"pods": {"total": 2, "chips": 8},
              "replicas": {"unified": 2},
              "endpoints": {"a:1": {"stale": False,
                                    "active_requests": 20.0}},
              "queue": {"depth": 0, "oldest_wait_s": 0, "per_class": {}}},
    }
    p = _planner(store, _snapshot(1000.0, models, budget))
    plan = p.tick()
    rec = plan["models"]["b"]
    assert rec["desired_replicas"] == 2 and rec["allocated_replicas"] == 1
    assert rec["preempted_replicas"] == 1
    marked = [
        pod["metadata"]["name"]
        for pod in store.list("Pod", "default")
        if k8sutils.get_annotation(pod, md.PLANNER_PREEMPT_ANNOTATION)
    ]
    assert marked == ["model-b-1"], "youngest pod is the victim"
    # Demand collapses → allocation covers current → marks clear.
    models["b"]["endpoints"]["a:1"]["active_requests"] = 0.0
    models["b"]["pods"] = {"total": 1, "chips": 4}
    models["b"]["replicas"] = {"unified": 1}
    p.tick()
    marked = [
        pod["metadata"]["name"]
        for pod in store.list("Pod", "default")
        if k8sutils.get_annotation(pod, md.PLANNER_PREEMPT_ANNOTATION)
    ]
    assert marked == []
