"""Routing tier tests (reference suites: internal/loadbalancer/*_test.go,
internal/modelproxy/handler_test.go, internal/apiutils/*_test.go)."""

import json
import threading
import time

import pytest

from testutil import FakeEngine, http_post

from kubeai_tpu.crd.model import Model, ModelSpec, LoadBalancing
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing import apiutils
from kubeai_tpu.routing.chwbl import CHWBL
from kubeai_tpu.routing.loadbalancer import Group, LoadBalancer, LoadBalancerTimeout
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.routing.openai_server import OpenAIServer
from kubeai_tpu.routing.proxy import ModelProxy
from kubeai_tpu.routing.xxhash import xxhash64


# ---- xxhash -----------------------------------------------------------------


def test_xxhash64_vectors():
    assert xxhash64(b"") == 0xEF46DB3751D8E999
    assert xxhash64(b"a") == 0xD24EC4F1A98C6E5B
    assert xxhash64(b"abc") == 0x44BC2CF5AD770999
    # >=32 bytes path
    assert xxhash64(b"x" * 100) == xxhash64(b"x" * 100)
    assert xxhash64(b"x" * 100) != xxhash64(b"x" * 101)


# ---- apiutils ---------------------------------------------------------------


def test_parse_request_model_and_prefix():
    body = json.dumps(
        {
            "model": "llama",
            "messages": [
                {"role": "system", "content": "be nice"},
                {"role": "user", "content": "hello world, this is the prefix"},
            ],
            "some_vendor_field": {"x": 1},
        }
    ).encode()
    p = apiutils.parse_request(body, "/v1/chat/completions", {})
    assert p.model == "llama" and p.adapter == ""
    assert p.prefix.startswith("hello world")
    # Unknown fields preserved.
    assert json.loads(p.body)["some_vendor_field"] == {"x": 1}


def test_parse_request_adapter_rewrites_body():
    body = json.dumps({"model": "llama_finetune", "prompt": "hi"}).encode()
    p = apiutils.parse_request(body, "/v1/completions", {})
    assert (p.model, p.adapter) == ("llama", "finetune")
    assert json.loads(p.body)["model"] == "finetune"
    assert p.model_and_adapter == "llama_finetune"


def test_parse_request_errors():
    with pytest.raises(apiutils.APIError):
        apiutils.parse_request(b"not json", "/v1/completions", {})
    with pytest.raises(apiutils.APIError):
        apiutils.parse_request(b"{}", "/v1/completions", {})
    with pytest.raises(apiutils.APIError):
        apiutils.parse_label_selector("novalue")


def test_parse_multipart_strips_model_field():
    boundary = "XX"
    body = (
        b"--XX\r\n"
        b'Content-Disposition: form-data; name="model"\r\n\r\n'
        b"whisper_acc\r\n"
        b"--XX\r\n"
        b'Content-Disposition: form-data; name="file"; filename="a.wav"\r\n\r\n'
        b"AUDIO\r\n"
        b"--XX--\r\n"
    )
    p = apiutils.parse_request(
        body,
        "/v1/audio/transcriptions",
        {"content-type": f'multipart/form-data; boundary="{boundary}"'},
    )
    assert (p.model, p.adapter) == ("whisper", "acc")
    assert b'name="model"' not in p.body
    assert b"AUDIO" in p.body


# ---- CHWBL ------------------------------------------------------------------


def test_chwbl_consistency_and_stickiness():
    ring = CHWBL()
    for ep in ("a:1", "b:1", "c:1"):
        ring.add(ep)
    loads = {"a:1": 0, "b:1": 0, "c:1": 0}
    picks = {ring.get(f"prefix-{i}", loads) for i in range(50)}
    assert picks == {"a:1", "b:1", "c:1"}  # spreads across endpoints
    # Same key -> same endpoint while loads are balanced.
    assert len({ring.get("stable-key", loads) for _ in range(10)}) == 1


def test_chwbl_minimal_redistribution_on_removal():
    ring = CHWBL()
    for ep in ("a:1", "b:1", "c:1"):
        ring.add(ep)
    loads3 = {"a:1": 0, "b:1": 0, "c:1": 0}
    before = {f"k{i}": ring.get(f"k{i}", loads3) for i in range(100)}
    ring.remove("c:1")
    loads2 = {"a:1": 0, "b:1": 0}
    moved = 0
    for k, ep in before.items():
        now = ring.get(k, loads2)
        if ep != "c:1" and now != ep:
            moved += 1
    # Keys not on the removed endpoint overwhelmingly stay put.
    assert moved <= 5


def test_chwbl_bounded_load_displaces():
    ring = CHWBL(load_factor=1.0)
    for ep in ("a:1", "b:1"):
        ring.add(ep)
    loads = {"a:1": 0, "b:1": 0}
    home = ring.get("key", loads)
    other = "b:1" if home == "a:1" else "a:1"
    # Overload the home endpoint: bounded-load walks to the other.
    loads[home] = 100
    loads[other] = 0
    assert ring.get("key", loads) == other


def test_chwbl_adapter_walk_and_fallback():
    ring = CHWBL()
    for ep in ("a:1", "b:1", "c:1"):
        ring.add(ep)
    loads = {"a:1": 0, "b:1": 0, "c:1": 0}
    # Only b has the adapter: every key lands on b.
    for i in range(20):
        assert ring.get(f"k{i}", loads, adapter_endpoints={"b:1"}) == "b:1"
    # All adapter-serving endpoints over the bound: still returns an
    # adapter endpoint (the ring-order default), NEVER one without the
    # adapter — the engine would silently serve the base model
    # (reference: balance_chwbl.go defaultEndpoint).
    hot = {"a:1": 0, "b:1": 1000, "c:1": 0}
    for i in range(20):
        assert ring.get(f"k{i}", hot, adapter_endpoints={"b:1"}) == "b:1"
    # No adapter endpoints at all -> not found; caller handles fallback.
    assert ring.get("k", loads, adapter_endpoints=set()) is None


# ---- endpoint group ---------------------------------------------------------


def test_group_blocks_until_endpoint_arrives():
    g = Group()
    result = {}

    def waiter():
        addr, done = g.get_best_addr("LeastLoad", "", "", timeout=5)
        result["addr"] = addr
        done()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    assert "addr" not in result  # blocked (scale-from-zero hold)
    g.reconcile_endpoints({"10.0.0.1:8000": set()})
    t.join(timeout=5)
    assert result["addr"] == "10.0.0.1:8000"


def test_group_timeout():
    g = Group()
    with pytest.raises(LoadBalancerTimeout):
        g.get_best_addr("LeastLoad", "", "", timeout=0.05)


def test_group_least_load_and_accounting():
    g = Group()
    g.reconcile_endpoints({"a:1": set(), "b:1": set()})
    addr1, done1 = g.get_best_addr("LeastLoad", "", "", timeout=1)
    addr2, done2 = g.get_best_addr("LeastLoad", "", "", timeout=1)
    assert {addr1, addr2} == {"a:1", "b:1"}  # spreads by in-flight
    done1()
    done1()  # double-done is a no-op
    assert g.total_in_flight == 1
    done2()
    assert g.total_in_flight == 0


def test_group_adapter_filter_blocks_until_adapter_pod():
    g = Group()
    g.reconcile_endpoints({"a:1": set()})
    with pytest.raises(LoadBalancerTimeout):
        g.get_best_addr("LeastLoad", "lora1", "", timeout=0.05)
    g.reconcile_endpoints({"a:1": set(), "b:1": {"lora1"}})
    addr, done = g.get_best_addr("LeastLoad", "lora1", "", timeout=1)
    assert addr == "b:1"
    done()


# ---- full data path: openai server -> proxy -> fake engine -------------------


@pytest.fixture
def stack():
    """store + LB + proxy + openai server, with one Model backed by fakes."""
    store = KubeStore()
    lb = LoadBalancer(store, default_timeout=5)
    mc = ModelClient(store)
    server = OpenAIServer(ModelProxy(lb, mc), mc)
    server.start()
    engines: list[FakeEngine] = []

    def add_model(name="m1", engines_n=1, strategy="LeastLoad", adapters=None):
        m = Model(
            name=name,
            spec=ModelSpec(
                url="hf://org/x",
                engine="KubeAITPU",
                features=["TextGeneration"],
                autoscaling_disabled=True,
                replicas=engines_n,
                load_balancing=LoadBalancing(strategy=strategy),
            ),
        )
        if adapters:
            m.spec.adapters = adapters
        store.create(m.to_dict())
        for i in range(engines_n):
            eng = FakeEngine()
            engines.append(eng)
            store.create(
                {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {
                        "name": f"model-{name}-{i}",
                        "namespace": "default",
                        "labels": {"model": name},
                        "annotations": {
                            "model-pod-ip": "127.0.0.1",
                            "model-pod-port": str(eng.port),
                        },
                    },
                    "status": {
                        "conditions": [{"type": "Ready", "status": "True"}],
                        "podIP": "127.0.0.1",
                    },
                }
            )
        lb.sync_model(name)
        return engines

    yield store, lb, server, add_model, engines
    server.stop()
    lb.stop()
    for e in engines:
        e.stop()


def _post(server, path, payload):
    return http_post(server.address, path, payload, timeout=10)


def test_chat_completion_roundtrip(stack):
    _, _, server, add_model, _ = stack
    add_model()
    status, data = _post(
        server,
        "/openai/v1/chat/completions",
        {"model": "m1", "messages": [{"role": "user", "content": "hi"}]},
    )
    assert status == 200
    assert json.loads(data)["object"] == "chat.completion"


def test_unknown_model_404(stack):
    _, _, server, add_model, _ = stack
    add_model()
    status, data = _post(
        server, "/openai/v1/chat/completions", {"model": "nope", "messages": []}
    )
    assert status == 404


def test_retry_on_5xx_until_success(stack):
    """(reference: modelproxy/handler_test.go retry table)"""
    _, _, server, add_model, engines = stack
    add_model()
    eng = engines[0]
    calls = {"n": 0}

    def flaky(path, body):
        calls["n"] += 1
        if calls["n"] < 3:
            return 503, {"error": "overloaded"}
        return 200, {"ok": True}

    eng.behavior = flaky
    status, data = _post(
        server,
        "/openai/v1/completions",
        {"model": "m1", "prompt": "x"},
    )
    assert status == 200 and calls["n"] == 3


def test_retry_on_429_shed(stack):
    """An engine shedding with 429 + Retry-After is retried (the in-tree
    engine sheds when its admission queue is full), and the pause is
    honored before re-picking."""
    _, _, server, add_model, engines = stack
    add_model()
    eng = engines[0]
    calls = {"n": 0}

    def shedding(path, body):
        calls["n"] += 1
        if calls["n"] < 2:
            return 429, {"error": "engine queue full"}
        return 200, {"ok": True}

    eng.behavior = shedding
    t0 = time.monotonic()
    status, data = _post(
        server, "/openai/v1/completions", {"model": "m1", "prompt": "x"}
    )
    assert status == 200 and calls["n"] == 2


def test_5xx_details_stripped(stack):
    _, _, server, add_model, engines = stack
    add_model()
    engines[0].behavior = lambda p, b: (500, {"error": "secret internal details"})
    status, data = _post(
        server, "/openai/v1/completions", {"model": "m1", "prompt": "x"}
    )
    assert status == 500
    assert b"secret" not in data


def test_sse_streams_incrementally_through_proxy(stack):
    """A streaming response must reach the client chunk by chunk — the
    proxy may not buffer SSE (regression: read(n) on a chunked upstream
    blocked until n bytes accumulated, holding ~160 events back and
    destroying TTFT/ITL through the proxy)."""
    import http.client
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    store, lb, server, add_model, _ = stack
    release_rest = threading.Event()

    class StreamingEngine(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(p: bytes):
                self.wfile.write(f"{len(p):x}\r\n".encode() + p + b"\r\n")

            chunk(b"data: first\n\n")
            # Hold the rest until the CLIENT has observed chunk one: if
            # the proxy buffers, the client never sees it and the 5s
            # wait below fails the test.
            release_rest.wait(timeout=5)
            chunk(b"data: second\n\n")
            chunk(b"data: [DONE]\n\n")
            self.wfile.write(b"0\r\n\r\n")

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), StreamingEngine)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        add_model(name="mstream")
        # Point the pod at the streaming engine instead of the FakeEngine.
        pods = store.list("Pod", "default", {"model": "mstream"})
        pod = store.get("Pod", "default", pods[0]["metadata"]["name"])
        pod["metadata"]["annotations"]["model-pod-port"] = str(
            httpd.server_address[1]
        )
        store.update(pod)
        lb.sync_model("mstream")

        host, _, port = server.address.partition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.request(
            "POST", "/openai/v1/chat/completions",
            body=json.dumps(
                {"model": "mstream", "messages": [], "stream": True}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        got = resp.read1(16384)  # must yield BEFORE the engine finishes
        assert b"first" in got, got
        release_rest.set()
        rest = b""
        while b"[DONE]" not in rest:
            piece = resp.read1(16384)
            if not piece:
                break
            rest += piece
        assert b"second" in rest and b"[DONE]" in rest
        conn.close()
    finally:
        release_rest.set()
        httpd.shutdown()
        httpd.server_close()


def test_least_load_spreads_across_backends(stack):
    """Concurrent in-flight requests must spread by least-load (sequential
    requests legitimately may all pick one backend: loads are equal)."""
    _, _, server, add_model, engines = stack
    add_model(engines_n=2)
    for e in engines:
        orig = e.default

        def slow(path, body, orig=orig):
            time.sleep(0.3)
            return orig(path, body)

        e.behavior = slow
    seen = set()
    lock = threading.Lock()

    def call():
        status, data = _post(
            server, "/openai/v1/completions", {"model": "m1", "prompt": "x"}
        )
        assert status == 200
        with lock:
            seen.add(json.loads(data)["backend"])

    threads = [threading.Thread(target=call) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(seen) == 2


def test_prefix_hash_stickiness_through_stack(stack):
    _, _, server, add_model, engines = stack
    add_model(engines_n=2, strategy="PrefixHash")
    backends = set()
    for _ in range(5):
        status, data = _post(
            server,
            "/openai/v1/chat/completions",
            {
                "model": "m1",
                "messages": [{"role": "user", "content": "the same long prefix"}],
            },
        )
        assert status == 200
        backends.add(json.loads(data)["backend"])
    assert len(backends) == 1  # same prefix -> same backend


def test_models_listing_expands_adapters(stack):
    from kubeai_tpu.crd.model import Adapter
    import http.client

    _, _, server, add_model, _ = stack
    add_model(name="m2", adapters=[Adapter(name="fin", url="hf://a/b")])
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    conn.request("GET", "/openai/v1/models")
    resp = conn.getresponse()
    ids = {m["id"] for m in json.loads(resp.read())["data"]}
    conn.close()
    assert {"m2", "m2_fin"} <= ids


def test_scale_from_zero_via_proxy(stack):
    """Proxy bumps replicas 0->1 and blocks until a pod is ready
    (reference: test/integration/proxy_test.go:19-95)."""
    store, lb, server, add_model, engines = stack
    m = Model(
        name="m0",
        spec=ModelSpec(
            url="hf://org/x",
            engine="KubeAITPU",
            features=["TextGeneration"],
            min_replicas=0,
            max_replicas=2,
            replicas=0,
        ),
    )
    store.create(m.to_dict())

    result = {}

    def call():
        result["resp"] = _post(
            server, "/openai/v1/completions", {"model": "m0", "prompt": "x"}
        )

    t = threading.Thread(target=call)
    t.start()
    # The request must trigger 0->1 scale.
    deadline = time.time() + 5
    while time.time() < deadline:
        if (store.get("Model", "default", "m0")["spec"].get("replicas") or 0) == 1:
            break
        time.sleep(0.02)
    else:
        pytest.fail("proxy did not scale model from zero")
    assert "resp" not in result  # still blocked: no ready pod yet

    # Simulate the controller + kubelet: bring up a fake engine pod.
    eng = FakeEngine()
    engines.append(eng)
    store.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": "model-m0-0",
                "namespace": "default",
                "labels": {"model": "m0"},
                "annotations": {
                    "model-pod-ip": "127.0.0.1",
                    "model-pod-port": str(eng.port),
                },
            },
            "status": {
                "conditions": [{"type": "Ready", "status": "True"}],
                "podIP": "127.0.0.1",
            },
        }
    )
    lb.sync_model("m0")
    t.join(timeout=5)
    assert result["resp"][0] == 200


# ---- SLO-scheduling header propagation & shed backoff -----------------------


def test_scheduling_headers_forwarded_to_engine(stack):
    """X-Priority / X-Deadline-Ms / X-Client-Id ride through the proxy to
    the engine (which parses them for priority/deadline admission)."""
    _, _, server, add_model, engines = stack
    add_model()
    status, _ = http_post(
        server.address,
        "/openai/v1/completions",
        {"model": "m1", "prompt": "x"},
        headers={
            "X-Priority": "realtime",
            "X-Deadline-Ms": "1500",
            "X-Client-Id": "tenant-a",
        },
    )
    assert status == 200
    seen = engines[0].request_headers[-1]
    assert seen.get("x-priority") == "realtime"
    assert seen.get("x-deadline-ms") == "1500"
    assert seen.get("x-client-id") == "tenant-a"


def test_retry_after_sleep_is_jittered(stack, monkeypatch):
    """Shed backoff sleeps base*(0.5 + 0.5*jitter): concurrently-shed
    requests must NOT all sleep the same duration (synchronized re-pick
    stampede lands on the same replica under prefix-hash)."""
    from kubeai_tpu.routing import proxy as proxy_mod

    _, _, server, add_model, engines = stack
    add_model()
    eng = engines[0]
    sleeps: list[float] = []
    monkeypatch.setattr(
        proxy_mod.time, "sleep", lambda s: sleeps.append(s)
    )

    def run_once(jitter_value):
        calls = {"n": 0}

        def shedding(path, body):
            calls["n"] += 1
            if calls["n"] < 2:
                return 429, {"error": "shed"}, {"Retry-After": "2.0"}
            return 200, {"ok": True}

        eng.behavior = shedding
        monkeypatch.setattr(proxy_mod, "_jitter", lambda: jitter_value)
        status, _ = _post(
            server, "/openai/v1/completions", {"model": "m1", "prompt": "x"}
        )
        assert status == 200

    run_once(1.0)
    run_once(0.0)
    run_once(0.5)
    assert len(sleeps) == 3
    # Retry-After 2.0 capped at 2.0: jitter 1.0 -> full 2.0s, jitter 0.0
    # -> half, jitter 0.5 -> midpoint. Two shed requests with different
    # jitter draws sleep differently — no herd — and every sleep stays
    # inside the [0.5, 1.0]× band of the hint the replica asked for.
    assert sleeps[0] == pytest.approx(2.0)
    assert sleeps[1] == pytest.approx(1.0)
    assert sleeps[2] == pytest.approx(1.5)
    assert sleeps[0] != sleeps[1]
    for s in sleeps:
        assert 0.5 * 2.0 <= s <= 2.0


def test_retry_after_non_numeric_is_ignored(stack, monkeypatch):
    """RFC 7231 allows HTTP-date Retry-After values; the proxy must not
    crash on (or sleep for) anything it can't parse as seconds — it just
    re-picks immediately."""
    from kubeai_tpu.routing import proxy as proxy_mod

    _, _, server, add_model, engines = stack
    add_model()
    eng = engines[0]
    sleeps: list[float] = []
    monkeypatch.setattr(proxy_mod.time, "sleep", lambda s: sleeps.append(s))
    calls = {"n": 0}

    def shedding(path, body):
        calls["n"] += 1
        if calls["n"] < 2:
            return 429, {"error": "shed"}, {
                "Retry-After": "Wed, 21 Oct 2015 07:28:00 GMT"
            }
        return 200, {"ok": True}

    eng.behavior = shedding
    status, _ = _post(
        server, "/openai/v1/completions", {"model": "m1", "prompt": "x"}
    )
    assert status == 200 and calls["n"] == 2
    assert sleeps == []  # unparseable hint -> no backoff sleep


def test_429_body_passes_through_with_class_depths(stack, monkeypatch):
    """An engine that sheds on EVERY attempt: the final 429 passes
    through with its body intact — per-class queue depths and the
    computed retry_after_s reach the client, not a stripped shell."""
    from kubeai_tpu.routing import proxy as proxy_mod

    _, _, server, add_model, engines = stack
    add_model()
    monkeypatch.setattr(proxy_mod.time, "sleep", lambda s: None)
    shed_body = {
        "error": {"message": "engine queue full, retry later"},
        "queue": {
            "depths": {"realtime": 0, "standard": 7, "batch": 2},
            "retry_after_s": 1.25,
        },
    }
    engines[0].behavior = lambda p, b: (
        429, shed_body, {"Retry-After": "1.25"}
    )
    status, data = _post(
        server, "/openai/v1/completions", {"model": "m1", "prompt": "x"}
    )
    assert status == 429
    payload = json.loads(data)
    assert payload["queue"]["depths"] == {
        "realtime": 0, "standard": 7, "batch": 2
    }
    assert payload["queue"]["retry_after_s"] == 1.25
