"""Game-day harness: the composed cross-subsystem chaos trace (kills +
API partition + tenant flood + spot chip flip SIMULTANEOUSLY) against
the real reconciler/governor/planner/LB/tenant door under one FakeClock,
the continuous+terminal invariant set, the deterministic dump->replay
loop, the same-tick ordering contracts, and the governor budget-refund
regression — all tier-1."""

import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
sys.path.insert(0, REPO_ROOT)

from benchmarks.gameday_sim import (
    ALL_CHECKS,
    DEFAULT_TICKS,
    FAILING_STREAM_TOKENS,
    check_chaos_concurrency,
    check_door_chaos_was_real,
    check_failing_trace_fails,
    check_flood_was_real,
    check_no_violations,
    check_progress_under_chaos,
    check_tenant_isolation,
    extended_trace,
    failing_trace,
    fast_trace,
    replay,
    run_gameday,
    run_sim,
)
from kubeai_tpu.config.system import GovernorConfig
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.operator.governor import ActuationGovernor
from kubeai_tpu.testing import (
    ApiFault,
    ApiFaultPlan,
    ChaosKubeStore,
    FakeClock,
    Fault,
    FaultPlan,
    GameDayEvent,
    GameDayLog,
    GameDayTrace,
    Invariant,
    InvariantChecker,
)
from kubeai_tpu.testing.simkit import percentile, scrape_diff

pytestmark = pytest.mark.gameday


# ---- the composed game day (one run, many assertions) ------------------------


@pytest.fixture(scope="module")
def sim():
    return run_sim()


def test_chaos_kinds_concurrent(sim):
    check_chaos_concurrency(sim)


def test_all_invariants_hold(sim):
    check_no_violations(sim)


def test_progress_under_chaos(sim):
    check_progress_under_chaos(sim)


def test_tenant_isolation_under_composed_chaos(sim):
    check_tenant_isolation(sim)


def test_flood_was_real(sim):
    check_flood_was_real(sim)


def test_failing_trace_fails_deterministically(sim):
    check_failing_trace_fails(sim)


def test_door_chaos_was_real(sim):
    """The gossip plane was split mid-flood, a door shard crashed and
    was rebuilt from peers, and the flooder never exceeded one global
    budget + epsilon (the door_budget continuous invariant)."""
    check_door_chaos_was_real(sim)


def test_all_checks_is_complete(sim):
    # Belt and braces: every exported check runs against the one sim.
    for check in ALL_CHECKS:
        check(sim)


def test_control_plane_errors_were_absorbed(sim):
    """The partition + 5xx storm really hit the operator stack — and
    none of it surfaced as a client error or violation."""
    g = sim["gameday"]
    assert g["control_plane_errors"] > 0
    assert g["client_errors"] == 0


# ---- dump -> replay ----------------------------------------------------------


def test_replay_reproduces_first_violation(sim, tmp_path):
    """The replay contract end to end: dump the engineered failure, feed
    the dump back through `replay`, land on a byte-identical log and the
    SAME first violation."""
    failing = sim["failing"]
    path = tmp_path / "gameday_fail.jsonl"
    failing["log"].dump(str(path))

    header, fresh = replay(str(path))
    assert header["stream_tokens"] == FAILING_STREAM_TOKENS
    assert fresh["log"].lines == failing["log"].lines
    assert fresh["first_violation"] == failing["first_violation"]
    assert fresh["first_violation"]["invariant"] == "zero_stream_errors"


def test_log_round_trip(sim, tmp_path):
    """GameDayLog.load returns the header + typed records that dump
    wrote, and the header rebuilds the exact trace."""
    g = sim["gameday"]
    path = tmp_path / "gameday.jsonl"
    g["log"].dump(str(path))
    header, records = GameDayLog.load(str(path))
    assert header["kind"] == "gameday"
    assert header["ticks"] == sim["ticks"]
    rebuilt = GameDayTrace(
        [GameDayEvent.from_dict(d) for d in header["events"]],
        seed=int(header["seed"]),
    )
    assert rebuilt.to_jsonl() == fast_trace(sim["seed"]).to_jsonl()
    kinds = {r["record"] for r in records}
    assert {"event", "obs"} <= kinds


def test_load_rejects_non_gameday_file(tmp_path):
    path = tmp_path / "not_a_dump.jsonl"
    path.write_text('{"kind": "something_else"}\n')
    with pytest.raises(ValueError):
        GameDayLog.load(str(path))


# ---- trace determinism -------------------------------------------------------


def test_trace_same_tick_ordering_is_insertion_order():
    """Two events at the same instant apply in the order the author
    listed them (stable (t, seq) sort), and `due` is a deliver-once
    cursor."""
    a = GameDayEvent(5.0, "kill_pod", "rt")
    b = GameDayEvent(5.0, "api_partition", "", {"duration_s": 3.0})
    c = GameDayEvent(2.0, "tenant_flood", "flooder", {"duration_s": 1.0})
    trace = GameDayTrace([a, b, c])
    assert [ev.kind for ev in trace.events] == [
        "tenant_flood", "kill_pod", "api_partition",
    ]
    assert [ev.kind for ev in trace.due(2.0)] == ["tenant_flood"]
    assert [ev.kind for ev in trace.due(5.0)] == [
        "kill_pod", "api_partition",
    ]
    assert trace.due(100.0) == []


def test_trace_jsonl_round_trip():
    trace = fast_trace(7)
    again = GameDayTrace.from_jsonl(trace.to_jsonl(), seed=trace.seed)
    assert again.to_jsonl() == trace.to_jsonl()
    assert again.seed == 7


def test_trace_without_strips_kind_keeps_order():
    trace = fast_trace(0)
    calm = trace.without("tenant_flood")
    assert all(ev.kind != "tenant_flood" for ev in calm.events)
    kept = [ev.kind for ev in trace.events if ev.kind != "tenant_flood"]
    assert [ev.kind for ev in calm.events] == kept


def test_trace_rejects_unknown_kind():
    with pytest.raises(ValueError):
        GameDayEvent(1.0, "meteor_strike")


def test_last_event_t_includes_durations():
    trace = GameDayTrace([
        GameDayEvent(10.0, "kill_pod", "rt"),
        GameDayEvent(5.0, "api_partition", "", {"duration_s": 30.0}),
    ])
    assert trace.last_event_t == 35.0


# ---- fault-plan same-tick ordering -------------------------------------------


def test_faultplan_first_match_wins():
    """Two faults matching the same attempt resolve to the one listed
    first — the documented same-tick tie-break."""
    plan = FaultPlan([
        Fault("e:1", "timeout", start=1, end=None),
        Fault("e:1", "connect_error", start=1, end=None),
    ])
    f = plan.on_attempt("e:1")
    assert f is not None and f.kind == "timeout"
    # Reversed listing, fresh counters: the other one wins.
    plan2 = FaultPlan([
        Fault("e:1", "connect_error", start=1, end=None),
        Fault("e:1", "timeout", start=1, end=None),
    ])
    f2 = plan2.on_attempt("e:1")
    assert f2 is not None and f2.kind == "connect_error"


def test_api_faultplan_first_match_wins():
    plan = ApiFaultPlan([
        ApiFault(method="GET", plural="pods", kind="http", status=500),
        ApiFault(method="GET", plural="pods", kind="drop"),
    ])
    f = plan.on_request("GET", "pods")
    assert f is not None and f.kind == "http" and f.status == 500
    plan2 = ApiFaultPlan([
        ApiFault(method="GET", plural="pods", kind="drop"),
        ApiFault(method="GET", plural="pods", kind="http", status=500),
    ])
    f2 = plan2.on_request("GET", "pods")
    assert f2 is not None and f2.kind == "drop"


def test_fake_clock_rejects_negative_advance():
    clock = FakeClock(100.0)
    with pytest.raises(ValueError):
        clock.advance(-0.5)
    assert clock() == 100.0  # the failed advance moved nothing


# ---- invariant framework -----------------------------------------------------


def test_invariant_checker_records_first_violation():
    inv_ok = Invariant("always_ok", lambda w: None)
    inv_bad = Invariant("always_bad", lambda w: "broken")
    inv_crash = Invariant("crashes", lambda w: 1 / 0)
    checker = InvariantChecker([inv_ok, inv_bad, inv_crash])
    checker.check_continuous(object(), tick=3, t=1.5)
    assert checker.first_violation.invariant == "always_bad"
    assert checker.first_violation.tick == 3
    names = [v.invariant for v in checker.violations]
    assert names == ["always_bad", "crashes"]  # a crashing check IS one


def test_terminal_invariants_only_run_at_the_end():
    hits = []
    inv = Invariant(
        "term", lambda w: hits.append(1), kind="terminal",
    )
    checker = InvariantChecker([inv])
    checker.check_continuous(object(), tick=0, t=0.0)
    assert hits == []
    checker.check_terminal(object(), tick=9, t=9.0)
    assert hits == [1]


# ---- governor budget refund (regression) -------------------------------------


class _ExplodingStore:
    """`delete` fails the way an exhausted kube client surfaces an API
    partition; everything else is unused."""

    def delete(self, kind, namespace, name):
        raise ConnectionError("injected partition: DELETE pods")


class _OkStore:
    def delete(self, kind, namespace, name):
        return None


def _governor(clock):
    cfg = GovernorConfig(
        enabled=True, window_seconds=60.0,
        model_disruption_budget=2, cluster_disruption_budget=3,
    )
    return ActuationGovernor(cfg, metrics=Metrics(), clock=clock)


def test_failed_delete_refunds_disruption_budget():
    """Regression: a delete that never reached the API server must not
    consume a budget unit — otherwise an API partition or 5xx storm
    drains the disruption window with ZERO actual disruptions and
    stalls post-chaos convergence."""
    clock = FakeClock(100.0)
    gov = _governor(clock)
    before = gov.budget_remaining("m")
    for _ in range(5):  # well past both budgets if the refund leaked
        with pytest.raises(ConnectionError):
            gov.delete_pod(
                _ExplodingStore(), "default", "pod-x", model="m",
            )
    assert gov.budget_remaining("m") == before


def test_successful_delete_still_consumes_budget():
    clock = FakeClock(100.0)
    gov = _governor(clock)
    model_rem, cluster_rem = gov.budget_remaining("m")
    assert gov.delete_pod(_OkStore(), "default", "pod-x", model="m")
    assert gov.budget_remaining("m") == (model_rem - 1, cluster_rem - 1)


def test_refund_is_per_model():
    """The refund takes back the unit the FAILED delete paid for — a
    different model's successful disruption stays spent."""
    clock = FakeClock(100.0)
    gov = _governor(clock)
    assert gov.delete_pod(_OkStore(), "default", "pod-a", model="a")
    with pytest.raises(ConnectionError):
        gov.delete_pod(_ExplodingStore(), "default", "pod-b", model="b")
    a_rem, cluster_rem = gov.budget_remaining("a")
    assert a_rem == gov.cfg.model_disruption_budget - 1
    assert cluster_rem == gov.cfg.cluster_disruption_budget - 1
    assert gov.budget_remaining("b")[0] == gov.cfg.model_disruption_budget


# ---- chaos store -------------------------------------------------------------


def test_chaos_store_partition_switch():
    from kubeai_tpu.operator.k8s.store import KubeStore
    from kubeai_tpu.testing import ApiServerUnreachable

    store = ChaosKubeStore(KubeStore())
    store.create({"kind": "ConfigMap", "metadata": {"name": "cm"}})
    store.partitioned = True
    with pytest.raises(ApiServerUnreachable):
        store.get("ConfigMap", "default", "cm")
    store.partitioned = False
    assert store.get("ConfigMap", "default", "cm")["metadata"]["name"] == "cm"


# ---- shared sim scaffolding --------------------------------------------------


def test_percentile_nearest_rank():
    assert percentile([], 0.99) == 0.0
    assert percentile([3.0, 1.0, 2.0], 0.0) == 1.0
    assert percentile([3.0, 1.0, 2.0], 0.99) == 3.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 0.5) == 3.0


def test_scrape_diff_deltas():
    before = (
        'a_total{x="1"} 2\n'
        'b_total 5\n'
        'gone_total 1\n'
    )
    after = (
        'a_total{x="1"} 7\n'
        'b_total 5\n'
        'new_total 3\n'
    )
    diff = scrape_diff(before, after)
    moved = {name: delta for (name, _labels), delta in diff.items()}
    assert moved["a_total"] == 5.0
    assert moved["new_total"] == 3.0
    assert moved["gone_total"] == -1.0
    assert "b_total" not in moved


# ---- the long game day (slow tier) -------------------------------------------


@pytest.mark.slow
def test_extended_trace_holds_invariants():
    """The same composition plus a second, time-shifted wave — twice the
    ticks, same zero-violation bar."""
    result = run_gameday(
        extended_trace(0), DEFAULT_TICKS["extended"], seed=0,
    )
    assert result["violations"] == []
    assert result["client_errors"] == 0
    assert result["converged_final"]
