"""SQS driver against an in-process protocol fake (JSON protocol:
X-Amz-Target dispatch, receipt handles, visibility timeouts)."""

from __future__ import annotations

import base64
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubeai_tpu.routing.sqs import SQSBroker


class FakeSQS:
    """Single-endpoint SQS speaking the JSON protocol. Messages carry
    receipt handles and visibility timeouts; nack (visibility 0) makes
    them immediately receivable again."""

    def __init__(self):
        self.queues: dict[str, list[dict]] = {}  # path -> messages
        self.lock = threading.Lock()
        self.fail_next_receives = 0
        self.saw_auth: list[str] = []
        outer = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                target = self.headers.get("X-Amz-Target", "")
                auth = self.headers.get("Authorization")
                if auth:
                    outer.saw_auth.append(auth)
                action = target.split(".")[-1]
                code, payload = outer.handle(action, body)
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/x-amz-json-1.0")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    def _queue(self, queue_url: str) -> list[dict]:
        import urllib.parse

        path = urllib.parse.urlparse(queue_url).path
        return self.queues.setdefault(path, [])

    def handle(self, action: str, body: dict):
        with self.lock:
            q = self._queue(body.get("QueueUrl", "/"))
            if action == "SendMessage":
                q.append(
                    {
                        "Body": body["MessageBody"],
                        "ReceiptHandle": uuid.uuid4().hex,
                        "visible_at": 0.0,
                    }
                )
                return 200, {"MessageId": uuid.uuid4().hex}
            if action == "ReceiveMessage":
                if self.fail_next_receives > 0:
                    self.fail_next_receives -= 1
                    return 500, {"__type": "InternalFailure"}
                deadline = time.time() + min(
                    float(body.get("WaitTimeSeconds", 0)), 2.0
                )
                while True:
                    now = time.time()
                    ready = [m for m in q if m["visible_at"] <= now]
                    if ready or time.time() >= deadline:
                        break
                    self.lock.release()
                    try:
                        time.sleep(0.05)
                    finally:
                        self.lock.acquire()
                out = []
                for m in ready[: int(body.get("MaxNumberOfMessages", 1))]:
                    m["visible_at"] = now + 30.0  # in flight
                    out.append(
                        {
                            "Body": m["Body"],
                            "ReceiptHandle": m["ReceiptHandle"],
                        }
                    )
                return 200, ({"Messages": out} if out else {})
            if action == "DeleteMessage":
                handle = body["ReceiptHandle"]
                q[:] = [m for m in q if m["ReceiptHandle"] != handle]
                return 200, {}
            if action == "ChangeMessageVisibility":
                handle = body["ReceiptHandle"]
                for m in q:
                    if m["ReceiptHandle"] == handle:
                        m["visible_at"] = time.time() + float(
                            body.get("VisibilityTimeout", 0)
                        )
                return 200, {}
            return 400, {"__type": "InvalidAction"}


@pytest.fixture
def sqs():
    fake = FakeSQS()
    broker = SQSBroker(endpoint=fake.endpoint, wait_seconds=1)
    yield fake, broker
    broker.close()
    fake.close()


URL = "sqs://sqs.us-east-1.amazonaws.com/123456789/requests"


def test_factory_scheme():
    from kubeai_tpu.routing.brokers import make_broker

    b = make_broker(URL, endpoint="http://127.0.0.1:1")
    assert isinstance(b, SQSBroker)
    assert b.queue_url(URL) == "http://127.0.0.1:1/123456789/requests"
    # Without an endpoint override the stream URL IS the queue URL.
    assert SQSBroker(endpoint=None, access_key="", secret_key="").queue_url(
        URL
    ) == "https://sqs.us-east-1.amazonaws.com/123456789/requests"
    # The region rides the queue URL's host — signing must use it, not
    # the env default.
    b2 = make_broker(
        "sqs://sqs.ap-southeast-2.amazonaws.com/9/q",
        endpoint="http://127.0.0.1:1",
    )
    assert b2.region == "ap-southeast-2"


def test_publish_receive_ack_deletes(sqs):
    fake, broker = sqs
    broker.publish(URL, b"hello \x00 binary")
    msg = broker.receive(URL, timeout=10)
    assert msg is not None and msg.body == b"hello \x00 binary"
    msg.ack()
    deadline = time.time() + 5
    while time.time() < deadline:
        with fake.lock:
            if not fake._queue(broker.queue_url(URL)):
                break
        time.sleep(0.05)
    with fake.lock:
        assert fake._queue(broker.queue_url(URL)) == []  # DeleteMessage hit
    assert broker.receive(URL, timeout=0.3) is None


def test_nack_redelivers(sqs):
    fake, broker = sqs
    broker.publish(URL, b"retry-me")
    msg = broker.receive(URL, timeout=10)
    assert msg is not None
    msg.nack()  # visibility 0 -> immediately receivable again
    again = broker.receive(URL, timeout=10)
    assert again is not None and again.body == b"retry-me"
    again.ack()


def test_pull_survives_server_errors(sqs):
    fake, broker = sqs
    fake.fail_next_receives = 3
    broker.publish(URL, b"after-outage")
    msg = broker.receive(URL, timeout=20)
    assert msg is not None and msg.body == b"after-outage"
    assert fake.fail_next_receives == 0


def test_foreign_raw_body_passes_through(sqs):
    fake, broker = sqs
    with fake.lock:
        fake._queue(broker.queue_url(URL)).append(
            {
                "Body": "not base64!!",
                "ReceiptHandle": "h1",
                "visible_at": 0.0,
            }
        )
    msg = broker.receive(URL, timeout=10)
    assert msg is not None and msg.body == b"not base64!!"


def test_sigv4_headers_sent_when_credentialed(sqs):
    fake, _ = sqs
    broker = SQSBroker(
        endpoint=fake.endpoint, access_key="AKID", secret_key="SECRET",
        region="eu-west-1", wait_seconds=1,
    )
    try:
        broker.publish(URL, b"signed")
        assert fake.saw_auth, "no Authorization header reached the server"
        auth = fake.saw_auth[-1]
        assert "AWS4-HMAC-SHA256" in auth
        assert "eu-west-1/sqs/aws4_request" in auth
        assert "content-type;host;x-amz-date;x-amz-target" in auth
    finally:
        broker.close()


def test_base64_roundtrip_on_wire(sqs):
    fake, broker = sqs
    broker.publish(URL, b"\x01\x02")
    with fake.lock:
        stored = fake._queue(broker.queue_url(URL))[0]["Body"]
    assert base64.b64decode(stored) == b"\x01\x02"
