"""Engine request-lifecycle telemetry: TTFT/ITL/queue-wait/e2e histograms
and per-step gauges, driven through the real HTTP server with a fake
engine clock so the recorded latencies are deterministic."""

import json
import threading

import jax
import pytest

from testutil import http_get

from kubeai_tpu.engine import Engine, EngineConfig
from kubeai_tpu.engine import engine as engine_mod
from kubeai_tpu.engine.server import EngineServer
from kubeai_tpu.engine.tokenizer import ByteTokenizer
from kubeai_tpu.models import llama


class FakeClock:
    """Monotonic fake: every read advances 1ms, so consecutive lifecycle
    events are strictly ordered and every latency is a positive, exact
    multiple of the tick."""

    def __init__(self, tick: float = 0.001):
        self.t = 100.0
        self.tick = tick
        self._lock = threading.Lock()

    def __call__(self) -> float:
        with self._lock:
            self.t += self.tick
            return self.t


@pytest.fixture
def server(monkeypatch):
    monkeypatch.setattr(engine_mod, "_now", FakeClock())
    tok = ByteTokenizer()
    cfg = llama.LlamaConfig.tiny(vocab_size=tok.vocab_size)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    engine = Engine(
        "llama", cfg, params,
        cfg=EngineConfig(num_slots=2, max_seq_len=64, decode_chunk=4),
        eos_token_ids=tok.eos_token_ids,
    )
    srv = EngineServer(engine, tok, "tiny", host="127.0.0.1", port=0)
    srv.start()
    yield srv
    srv.stop()


def _stream_completion(port: int, body: dict) -> list[dict]:
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(
        "POST", "/v1/completions",
        body=json.dumps({**body, "stream": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    raw = resp.read().decode()
    conn.close()
    assert resp.status == 200
    return [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]


def test_streamed_request_populates_latency_histograms(server):
    n_tokens = 8
    events = _stream_completion(
        server.port,
        {"model": "tiny", "prompt": "hello", "max_tokens": n_tokens,
         "temperature": 0},
    )
    assert events[-1]["choices"][0]["finish_reason"] in ("length", "stop")
    # The serve loop drains engine timing after each step; /metrics also
    # syncs, so the scrape below is guaranteed current.
    status, body = http_get(f"127.0.0.1:{server.port}", "/metrics")
    assert status == 200
    m = server.metrics
    assert m.queue_wait.get() == 1
    assert m.prefill.get() == 1
    assert m.ttft.get() == 1
    assert m.e2e.get() == 1
    # One ITL gap per token after the first. (Greedy run to "length";
    # an early "stop" would emit fewer — bound instead of pin.)
    assert 1 <= m.itl.get() <= n_tokens - 1
    # Fake clock: every recorded latency is positive and finite.
    assert m.ttft.sum_for() > 0
    assert m.e2e.sum_for() > m.ttft.sum_for()  # e2e spans past first token


def test_metrics_exposition_has_nonzero_buckets_and_gauges(server):
    """Acceptance: /metrics exposes the four lifecycle histograms with
    nonzero bucket counts plus occupancy/KV-utilization gauges after a
    request runs through the server."""
    _stream_completion(
        server.port,
        {"model": "tiny", "prompt": "abc", "max_tokens": 4,
         "temperature": 0},
    )
    _, body = http_get(f"127.0.0.1:{server.port}", "/metrics")
    text = body.decode()
    from kubeai_tpu.metrics.registry import parse_prometheus_text

    parsed = parse_prometheus_text(text)
    for hist in (
        "kubeai_engine_ttft_seconds",
        "kubeai_engine_inter_token_latency_seconds",
        "kubeai_engine_queue_wait_seconds",
        "kubeai_engine_e2e_seconds",
        "kubeai_engine_prefill_seconds",
    ):
        assert parsed[(f"{hist}_count", ())] > 0, hist
        inf_bucket = parsed[(f"{hist}_bucket", (("le", "+Inf"),))]
        assert inf_bucket > 0, hist
    for gauge in (
        "kubeai_engine_batch_size",
        "kubeai_engine_kv_cache_utilization",
        "kubeai_engine_tokens_per_step",
        "kubeai_engine_step_duration_seconds",
        "kubeai_engine_slots_active",
        "kubeai_engine_requests_pending",
    ):
        assert f"# TYPE {gauge} gauge" in text, gauge


def test_step_stats_and_kv_utilization_move_during_decode(server):
    """kv_utilization and last_step_stats reflect live decode state."""
    eng = server.engine
    assert eng.kv_utilization() == 0.0
    _stream_completion(
        server.port,
        {"model": "tiny", "prompt": "xyz", "max_tokens": 6,
         "temperature": 0},
    )
    stats = eng.last_step_stats
    assert stats["tokens"] >= 1
    assert stats["duration_s"] > 0
    # All requests done: pool back to empty.
    assert eng.kv_utilization() == 0.0
    # The batch-size gauge saw the request while it ran.
    assert server.metrics.tokens_per_step.get() >= 0
    # The admin snapshot surfaces the same telemetry as JSON.
    _, body = http_get(f"127.0.0.1:{server.port}", "/v1/state")
    state = json.loads(body)
    assert "kv_utilization" in state
    assert state["last_step"]["tokens"] >= 1


def test_itl_records_match_fake_clock_ticks(monkeypatch):
    """Unit-level check against the fake clock, no HTTP: the engine's
    drained timing records carry exact fake-clock multiples."""
    clock = FakeClock(tick=0.001)
    monkeypatch.setattr(engine_mod, "_now", clock)
    tok = ByteTokenizer()
    cfg = llama.LlamaConfig.tiny(vocab_size=tok.vocab_size)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = Engine(
        "llama", cfg, params,
        cfg=EngineConfig(num_slots=2, max_seq_len=64, decode_chunk=2),
        eos_token_ids=tok.eos_token_ids,
    )
    from kubeai_tpu.engine.sampling import SamplingParams

    rid = eng.add_request(
        tok.encode("hi"), SamplingParams(temperature=0.0, max_tokens=5)
    )
    events = []
    while eng.has_work():
        events.extend(eng.step())
    timing: dict[str, list[float]] = {}
    exemplars: dict[str, list[str]] = {}
    for rec in eng.drain_timing():
        timing.setdefault(rec[0], []).append(rec[1])
        if len(rec) > 2:
            exemplars.setdefault(rec[0], []).append(rec[2])
    assert len(timing["queue_wait"]) == 1
    assert len(timing["prefill"]) == 1
    assert len(timing["ttft"]) == 1
    assert len(timing["e2e"]) == 1
    n_tokens = len([e for e in events if e.rid == rid])
    assert len(timing["itl"]) == n_tokens - 1
    # ttft = queue_wait + prefill under one clock.
    assert timing["ttft"][0] == pytest.approx(
        timing["queue_wait"][0] + timing["prefill"][0]
    )
    # Every value is a positive multiple of the tick (fake clock always
    # advances between lifecycle events).
    for kind, vals in timing.items():
        for v in vals:
            assert v >= 0, (kind, v)
    assert timing["e2e"][0] > timing["ttft"][0]
    # ttft/itl records carry the request's exemplar tag so the server's
    # histograms can map a bucket back to a request.
    assert exemplars["ttft"] == [f"rid-{rid}"]
    assert all(tag == f"rid-{rid}" for tag in exemplars["itl"])
    # A second drain is empty — records land exactly once.
    assert eng.drain_timing() == []
