"""The flight recorder's event/record vocabulary cannot drift from the
game-day replay schema: every kind the recorder emits must be one the
replay side understands, and vice versa. Tier-1 wiring for
scripts/check_incident_schema.py."""

import importlib.util
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def _load_checker():
    path = os.path.join(REPO_ROOT, "scripts", "check_incident_schema.py")
    spec = importlib.util.spec_from_file_location(
        "check_incident_schema", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_incident_schema_in_sync():
    checker = _load_checker()
    errors = checker.check()
    assert errors == [], "incident schema drift:\n" + "\n".join(errors)


def test_checker_detects_drift_both_ways(monkeypatch):
    """The gate itself must catch both rot directions: a recorder kind
    the replay side doesn't know, and a replay kind with no producer."""
    from kubeai_tpu.metrics import flightrecorder
    from kubeai_tpu.testing import chaos

    checker = _load_checker()
    monkeypatch.setattr(
        flightrecorder, "EVENT_KINDS",
        flightrecorder.EVENT_KINDS + ("brand_new_kind",),
    )
    errors = "\n".join(checker.check())
    assert "brand_new_kind" in errors
    monkeypatch.setattr(
        flightrecorder, "EVENT_KINDS", flightrecorder.EVENT_KINDS[:-2]
    )
    errors = "\n".join(checker.check())
    assert "no flight-recorder producer" in errors
    # Record-kind drift too.
    monkeypatch.setattr(
        flightrecorder, "EVENT_KINDS", chaos.FLIGHT_EVENT_KINDS
    )
    monkeypatch.setattr(
        flightrecorder, "RECORD_KINDS",
        flightrecorder.RECORD_KINDS + ("hologram",),
    )
    errors = "\n".join(checker.check())
    assert "hologram" in errors
