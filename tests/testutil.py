"""Shared test fakes: scripted engine backends, metrics servers, a fake
kubelet — the httptest.Server / markAllModelPodsReady equivalents
(reference: test/integration/utils_test.go)."""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeEngine:
    """Scripted engine backend. `behavior(path, body) -> (status, payload)`
    — or `(status, payload, headers)` — overrides the default echo
    response."""

    def __init__(self, behavior=None):
        fake = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req_body = self.rfile.read(n)
                fake.requests.append((self.path, req_body))
                fake.request_headers.append(
                    {k.lower(): v for k, v in self.headers.items()}
                )
                result = (fake.behavior or fake.default)(
                    self.path, req_body
                )
                status, payload = result[0], result[1]
                extra_headers = result[2] if len(result) > 2 else {}
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra_headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

        self.requests: list = []
        self.request_headers: list = []
        self.behavior = behavior
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def default(self, path, body):
        try:
            model = json.loads(body).get("model", "?")
        except json.JSONDecodeError:
            model = "?"
        return 200, {
            "object": "chat.completion",
            "model": model,
            "echo": model,
            "backend": self.port,
        }

    @property
    def port(self):
        return self.httpd.server_address[1]

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class FakeTelemetryEngine:
    """Scripted serving-endpoint telemetry: GET /metrics serves Prom
    text, GET /v1/state serves a JSON snapshot — what the fleet
    aggregator sweeps. `metrics_text`/`state` are mutable; set `dead`
    to make every request drop the connection (a dead endpoint the
    aggregator must flag stale, not merge)."""

    def __init__(self, metrics_text: str = "", state: dict | None = None):
        srv = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if srv.dead:
                    self.connection.close()
                    return
                if self.path == "/metrics":
                    body = srv.metrics_text.encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/v1/state":
                    body = json.dumps(srv.state).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.metrics_text = metrics_text
        self.state = state or {}
        self.dead = False
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.httpd.server_address[1]

    @property
    def addr(self):
        h, p = self.httpd.server_address[:2]
        return f"{h}:{p}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class FakeMetricsServer:
    """Static Prom-text server (reference: hack/vllm-mock-metrics/main.go)."""

    def __init__(self, text: str):
        srv = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                body = srv.text.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.text = text
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def addr(self):
        h, p = self.httpd.server_address[:2]
        return f"{h}:{p}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def http_post(address: str, path: str, payload: dict, timeout=30, headers=None):
    """POST JSON to host:port; returns (status, body_bytes)."""
    import http.client

    host, _, port = address.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    body = json.dumps(payload).encode()
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", path, body=body, headers=hdrs)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def http_get(address: str, path: str, timeout=10, headers=None):
    import http.client

    host, _, port = address.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("GET", path, headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def ready_pod_manifest(model: str, index: int, port: int, ip="127.0.0.1") -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"model-{model}-{index}",
            "namespace": "default",
            "labels": {"model": model},
            "annotations": {
                "model-pod-ip": ip,
                "model-pod-port": str(port),
            },
        },
        "status": {
            "conditions": [{"type": "Ready", "status": "True"}],
            "podIP": ip,
        },
    }


def mark_model_pods_ready(store, name: str | None = None):
    """Write Pod status by hand — no kubelet runs in these tests
    (reference: utils_test.go:118-132)."""
    selector = {"model": name} if name else None
    for pod in store.list("Pod", "default", selector):
        if "model" not in (pod["metadata"].get("labels") or {}):
            continue
        status = pod.setdefault("status", {})
        if any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in status.get("conditions", [])
        ):
            continue
        status["conditions"] = [
            {"type": "Ready", "status": "True"},
            {"type": "PodScheduled", "status": "True"},
        ]
        status["podIP"] = "10.0.0.9"
        try:
            store.update(pod)
        except Exception:
            pass


@contextmanager
def fake_kubelet(store, name: str | None = None, interval: float = 0.05):
    """Background thread continuously marking model pods ready."""
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            mark_model_pods_ready(store, name)
            time.sleep(interval)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    try:
        yield
    finally:
        stop.set()
        t.join(timeout=2)


def eventually(fn, timeout=10, interval=0.05, msg="condition"):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            result = fn()
            if result:
                return result
        except Exception as e:
            last = e
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg} (last error: {last})")
