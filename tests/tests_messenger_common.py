"""Shared messenger-world builder: a Messenger wired to an arbitrary
broker driver, with a fake engine send and a ready endpoint — so the
same behavioral suite runs over MemBroker, Pub/Sub, and NATS."""

import json

from kubeai_tpu.crd.model import Model, ModelSpec
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.loadbalancer import LoadBalancer
from kubeai_tpu.routing.messenger import Messenger
from kubeai_tpu.routing.modelclient import ModelClient


def build_messenger_world(broker, request_subscription, response_topic):
    store = KubeStore()
    mc = ModelClient(store)
    lb = LoadBalancer(store)
    sent = []

    def fake_send(addr, path, body):
        sent.append((addr, path, json.loads(body)))
        return 200, json.dumps({"ok": True}).encode()

    store.create(
        Model(
            name="m1",
            spec=ModelSpec(
                url="hf://org/x", engine="KubeAITPU",
                min_replicas=0, max_replicas=2, replicas=1,
            ),
        ).to_dict()
    )
    store.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": "model-m1-0",
                "namespace": "default",
                "labels": {"model": "m1"},
                "annotations": {
                    "model-pod-ip": "127.0.0.1",
                    "model-pod-port": "9000",
                },
            },
            "status": {
                "conditions": [{"type": "Ready", "status": "True"}],
                "podIP": "127.0.0.1",
            },
        }
    )
    lb.sync_model("m1")
    messenger = Messenger(
        broker, request_subscription, response_topic, lb, mc,
        http_send=fake_send,
    )
    messenger.start()
    return {
        "store": store,
        "lb": lb,
        "messenger": messenger,
        "sent": sent,
    }
