"""Deterministic SLO-incident simulation — no JAX, no sockets.

A three-replica model serves healthy traffic on a fake clock, then a
TTFT latency regression sets in while an abusive tenant hammers the
front door and a connect-failure storm trips every circuit breaker.
All of it flows through REAL components: scripted endpoint expositions
feed the real `FleetStateAggregator`, refusals come from the real
`TenantGovernor`, breaker transitions from the real LoadBalancer
`Group`, and the real `SLOEvaluator` judges every tick — wired to a
real `FlightRecorder` whose fast-burn page dumps the incident bundle.

Invariants (asserted in tier-1 by tests/unit/test_slo.py):

  * the TTFT fast-burn alert fires, and fires WITHIN the fast-burn
    window of the regression's onset — the multi-window rule pages
    fast, not after the slow window catches up;
  * the page dumps an incident bundle whose rings hold the door sheds,
    the breaker transitions, the all-circuits-open event, and the SLO
    transition that triggered it, plus metric deltas and trace-id
    exemplars;
  * the door flood produces a shed-rate SLOW burn only (a shed
    fraction can never reach the 14.4x fast threshold at a 10% shed
    objective — the objective algebra caps it at 10x);
  * replay is byte-identical: `replay(bundle)` re-runs the sim from
    the bundle's own header (sim/seed/ticks) and the fresh bundle
    matches the dumped one byte-for-byte, same first SLO violation —
    which is what `python -m benchmarks.gameday_sim --replay <bundle>`
    dispatches to when the header says `bundle: incident`.

Run directly for a human-readable report:

    python benchmarks/slo_incident_sim.py [--dump /tmp/incident.jsonl]
    python benchmarks/slo_incident_sim.py --replay /tmp/incident.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.config import System
from kubeai_tpu.fleet import FleetStateAggregator, SLOEvaluator, TenantGovernor
from kubeai_tpu.fleet.slo import STATE_FAST_BURN, STATE_SLOW_BURN
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.metrics import flightrecorder
from kubeai_tpu.metrics.flightrecorder import FlightRecorder
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.health import OUTCOME_CONNECT_ERROR
from kubeai_tpu.routing.loadbalancer import LoadBalancer, NoHealthyEndpoints
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.testing.clock import FakeClock
from kubeai_tpu.testing.simkit import mk_model, seeded_rng
from kubeai_tpu.utils import retryafter

SIM_NAME = "slo_incident"
MODEL = "m0"
REPLICAS = 3
TICK_S = 10.0
TICKS = 40
OBS_PER_TICK = 10          # TTFT observations per endpoint per tick
HEALTHY_TTFT = 0.2         # healthy observations land in the 0.25 bucket
REGRESSED_TTFT = 0.8       # regressed observations land in the 1.0 bucket
REGRESS_TICK = 15          # latency regression onset (0-based tick)
STORM_TICK = 18            # breaker storm: every circuit trips open
FLOOD_RPS_TICK = 6         # abusive tenant's requests per tick
USER_RPS_TICK = 1          # compliant tenant's requests per tick


def _slo_config() -> System:
    """Sim-scale SLO + tenancy config: same rule shapes as production
    defaults, windows shrunk so the whole incident fits in 40 ticks."""
    cfg = System()
    cfg.default_and_validate()
    cfg.slo.enabled = True
    cfg.slo.ttft_p95_seconds = 0.5
    cfg.slo.max_shed_rate = 0.10
    cfg.slo.budget_window_seconds = 1200.0
    cfg.slo.fast_burn_threshold = 14.4
    cfg.slo.fast_burn_window_seconds = 120.0
    cfg.slo.fast_burn_short_window_seconds = 30.0
    cfg.slo.slow_burn_threshold = 3.0
    cfg.slo.slow_burn_window_seconds = 600.0
    cfg.slo.min_incident_interval_seconds = 3600.0
    cfg.tenancy.enabled = True
    cfg.tenancy.requests_per_second = 0.2   # 2 tokens per 10s tick
    cfg.tenancy.request_burst = 2.0
    return cfg


class Endpoint:
    """One scripted serving endpoint: cumulative TTFT histogram rendered
    as real Prometheus exposition text, the way the aggregator scrapes
    it in production."""

    def __init__(self, addr: str):
        self.addr = addr
        self.good = 0    # observations <= 0.25s
        self.bad = 0     # observations in (0.5, 1.0]

    def advance(self, regressed: bool) -> None:
        if regressed:
            self.bad += OBS_PER_TICK
        else:
            self.good += OBS_PER_TICK

    def exposition(self) -> str:
        total = self.good + self.bad
        ttft_sum = self.good * HEALTHY_TTFT + self.bad * REGRESSED_TTFT
        return "\n".join([
            "# TYPE kubeai_engine_ttft_seconds histogram",
            f'kubeai_engine_ttft_seconds_bucket{{le="0.25"}} {self.good}',
            f'kubeai_engine_ttft_seconds_bucket{{le="0.5"}} {self.good}',
            f'kubeai_engine_ttft_seconds_bucket{{le="1"}} {total}',
            f'kubeai_engine_ttft_seconds_bucket{{le="+Inf"}} {total}',
            f"kubeai_engine_ttft_seconds_count {total}",
            f"kubeai_engine_ttft_seconds_sum {ttft_sum}",
            "kubeai_engine_queue_depth 2.0",
            "kubeai_engine_slots_active 4.0",
            "kubeai_engine_slot_capacity 32.0",
            "kubeai_engine_active_requests 4.0",
        ]) + "\n"

    def state(self) -> dict:
        return {"model": MODEL, "healthy": True, "draining": False,
                "role": "unified"}


def _pod(idx: int, addr: str) -> dict:
    ip, _, port = addr.partition(":")
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"model-{MODEL}-{idx}",
            "namespace": "default",
            "labels": {"model": MODEL},
            "annotations": {"model-pod-ip": ip, "model-pod-port": port},
        },
        "spec": {
            "containers": [{
                "name": "server",
                "resources": {
                    "requests": {"google.com/tpu": "4"},
                    "limits": {"google.com/tpu": "4"},
                },
            }],
        },
        "status": {
            "conditions": [{"type": "Ready", "status": "True"}],
            "podIP": ip,
        },
    }


def run_sim(seed: int = 0, ticks: int = TICKS) -> dict:
    """Run the full incident; returns measured facts for the tier-1
    invariant checks, including every bundle the recorder dumped."""
    rng = seeded_rng(seed)
    saved_jitter = retryafter._jitter
    retryafter._jitter = rng.random  # deterministic Retry-After hints
    try:
        return _run(seed, ticks)
    finally:
        retryafter._jitter = saved_jitter


def _run(seed: int, ticks: int) -> dict:
    clock = FakeClock(1000.0)
    cfg = _slo_config()
    store = KubeStore()
    metrics = Metrics()
    mc = ModelClient(store)
    lb = LoadBalancer(store, metrics=metrics)

    mk_model(store, name=MODEL, replicas=REPLICAS, max_replicas=REPLICAS)
    endpoints: dict[str, Endpoint] = {}
    for j in range(REPLICAS):
        addr = f"10.0.0.{j}:8000"
        endpoints[addr] = Endpoint(addr)
        store.create(_pod(j, addr))
    lb.sync_all()

    def fetch_metrics(addr: str, timeout: float) -> str:
        return endpoints[addr].exposition()

    def fetch_state(addr: str, timeout: float) -> dict:
        return endpoints[addr].state()

    aggregator = FleetStateAggregator(
        lb=lb,
        model_client=mc,
        store=store,
        namespace="default",
        metrics=metrics,
        interval_s=TICK_S,
        staleness_s=3 * TICK_S,
        fetch_metrics=fetch_metrics,
        fetch_state=fetch_state,
        clock=clock,
    )

    tick_box = {"tick": 0}
    recorder = FlightRecorder(
        clock=clock,
        tick_fn=lambda: tick_box["tick"],
        min_trigger_interval_s=cfg.slo.min_incident_interval_seconds,
    )
    recorder.replay_context = {
        "sim": SIM_NAME, "seed": seed, "ticks": ticks,
    }
    lb.set_recorder(recorder)

    door = TenantGovernor(
        cfg.tenancy, fleet=aggregator, model_client=mc,
        metrics=metrics, clock=clock,
    )
    door.recorder = recorder

    evaluator = SLOEvaluator(
        cfg=cfg.slo,
        aggregator=aggregator,
        model_client=mc,
        metrics=metrics,
        recorder=recorder,
        interval_s=TICK_S,
        clock=clock,
    )

    group = lb.group(MODEL)
    timeline: list[dict] = []
    first_violation: dict | None = None
    storm_raised = False

    for tick in range(ticks):
        tick_box["tick"] = tick
        clock.advance(TICK_S)
        regressed = tick >= REGRESS_TICK
        for ep in endpoints.values():
            ep.advance(regressed)
        aggregator.collect()

        # Front-door traffic: one compliant tenant, one flooder. The
        # flooder's bucket refills 2 requests per tick, so 4 of its 6
        # are refused (REASON_RATE -> door_shed flight events).
        ttft = REGRESSED_TTFT if regressed else HEALTHY_TTFT
        for i in range(FLOOD_RPS_TICK):
            if door.admit("flooder", MODEL, est_tokens=16) is None:
                metrics.request_ttft.observe(
                    ttft, exemplar=f"req-t{tick}-flood{i}", model=MODEL
                )
        for i in range(USER_RPS_TICK):
            if door.admit("user", MODEL, est_tokens=16) is None:
                metrics.request_ttft.observe(
                    ttft, exemplar=f"req-t{tick}-user{i}", model=MODEL
                )

        # Breaker storm: three consecutive connect failures per replica
        # trip every circuit; the next pick finds no healthy endpoint
        # and fires the all-circuits-open trigger.
        if tick == STORM_TICK:
            for addr in sorted(endpoints):
                for _ in range(3):
                    group.report_outcome(
                        addr, OUTCOME_CONNECT_ERROR, "connection refused"
                    )
            try:
                group.get_best_addr("", "", "", timeout=0.01)
            except NoHealthyEndpoints:
                storm_raised = True

        results = evaluator.tick()
        objectives = (
            results["models"].get(MODEL, {}).get("objectives", {})
        )
        row = {"tick": tick, "t": clock()}
        for kind, rec in objectives.items():
            row[kind] = {
                "state": rec["state"],
                "burn": rec["burn"],
                "budget": rec["budget"],
            }
            if first_violation is None and rec["state"] != "ok":
                first_violation = {
                    "tick": tick,
                    "t": clock(),
                    "model": MODEL,
                    "objective": kind,
                    "state": rec["state"],
                }
        timeline.append(row)

    return {
        "seed": seed,
        "ticks": ticks,
        "timeline": timeline,
        "first_violation": first_violation,
        "incidents": list(recorder.incidents),
        "storm_raised": storm_raised,
        "regress_t": 1000.0 + (REGRESS_TICK + 1) * TICK_S,
        "fast_window_s": cfg.slo.fast_burn_window_seconds,
        "evaluator": evaluator,
        "recorder": recorder,
        "metrics": metrics,
    }


def _fast_burn_ticks(result: dict) -> list[dict]:
    return [
        row for row in result["timeline"]
        if row.get("ttft_p95", {}).get("state") == "fast"
    ]


def _bundle(result: dict, reason: str) -> dict | None:
    for inc in result["incidents"]:
        if inc["reason"] == reason:
            return inc
    return None


# ---- invariant checks (imported by tests/unit/test_slo.py) -------------------


def check_fast_burn_within_window(result: dict) -> None:
    """The TTFT regression pages, and pages within the fast-burn window
    of its onset."""
    fast = _fast_burn_ticks(result)
    assert fast, "TTFT fast-burn alert never fired"
    onset_to_page = fast[0]["t"] - result["regress_t"]
    assert onset_to_page <= result["fast_window_s"], (
        f"fast burn took {onset_to_page}s > "
        f"{result['fast_window_s']}s window"
    )
    fv = result["first_violation"]
    assert fv is not None and fv["model"] == MODEL


def check_incident_bundle(result: dict) -> None:
    """The page dumped a bundle carrying the whole story: door sheds,
    breaker trips, the all-circuits-open event, the SLO transition,
    metric deltas, and trace-id exemplars."""
    inc = _bundle(result, flightrecorder.TRIGGER_FAST_BURN)
    assert inc is not None, "fast-burn page dumped no incident bundle"
    lines = inc["lines"]
    header = json.loads(lines[0])
    assert header["bundle"] == "incident"
    assert header["sim"] == SIM_NAME
    assert header["seed"] == result["seed"]
    assert header["ticks"] == result["ticks"]
    records = [json.loads(ln) for ln in lines[1:]]
    kinds = {r["kind"] for r in records if r["record"] == "flight"}
    for want in (
        flightrecorder.DOOR_SHED,
        flightrecorder.BREAKER,
        flightrecorder.LB_NO_ENDPOINTS,
        flightrecorder.SLO_ALERT,
    ):
        assert want in kinds, f"bundle missing {want} flight events"
    assert any(r["record"] == "metric_delta" for r in records), (
        "bundle carries no metric deltas"
    )
    assert any(r["record"] == "exemplar" for r in records), (
        "bundle carries no trace-id exemplars"
    )
    # Every line is canonical sorted-key JSON (the byte-identity basis).
    for ln in lines:
        assert json.dumps(json.loads(ln), sort_keys=True) == ln


def check_storm_recorded(result: dict) -> None:
    """The breaker storm really happened and was bundled on its own
    trigger too: one closed->open transition per replica, then the
    all-circuits-open page."""
    assert result["storm_raised"], "storm never hit NoHealthyEndpoints"
    inc = _bundle(result, flightrecorder.TRIGGER_ALL_CIRCUITS_OPEN)
    assert inc is not None, "all-circuits-open dumped no bundle"
    trips = [
        e for e in result["recorder"].events("lb")
        if e["kind"] == flightrecorder.BREAKER
        and e["detail"]["to_state"] == "open"
    ]
    assert len(trips) == REPLICAS, trips


def check_shed_slow_burn_only(result: dict) -> None:
    """The flood warns (slow burn) but can never page: a shed fraction
    is bounded by 1.0, so burn tops out at 1/0.10 = 10 < 14.4."""
    states = {
        row.get("shed_rate", {}).get("state")
        for row in result["timeline"]
    }
    assert "slow" in states, f"flood never reached slow burn: {states}"
    assert "fast" not in states, "shed objective must not fast-burn"


def check_exact_ledger(result: dict) -> None:
    """The budget ledger is exact arithmetic: for the final TTFT tick,
    remaining == allowed*total - bad as integers-and-fractions, and the
    exact string round-trips through Fraction."""
    from fractions import Fraction

    last = result["timeline"][-1]["ttft_p95"]["budget"]
    allowed = Fraction(last["allowed"])
    budget = allowed * last["total"]
    assert Fraction(last["budget"]) == budget
    assert Fraction(last["remaining"]) == budget - last["bad"]
    if budget > 0:
        assert Fraction(last["remaining_frac_exact"]) == (
            (budget - last["bad"]) / budget
        )
    assert last["exhausted"] == (budget - last["bad"] < 0)


ALL_CHECKS = (
    check_fast_burn_within_window,
    check_incident_bundle,
    check_storm_recorded,
    check_shed_slow_burn_only,
    check_exact_ledger,
)


# ---- replay ------------------------------------------------------------------


def replay(path: str) -> tuple[dict, dict]:
    """Re-run the incident byte-identically: read the bundle's header,
    re-drive the sim with the header's own (seed, ticks), and compare
    the fresh bundle for the same trigger line-for-line. Returns
    (header, comparison dict)."""
    with open(path) as fh:
        original = [ln.rstrip("\n") for ln in fh if ln.strip()]
    header = json.loads(original[0])
    if header.get("bundle") != "incident":
        raise ValueError(f"{path}: not an incident bundle")
    if header.get("sim") != SIM_NAME:
        raise ValueError(
            f"{path}: bundle was recorded by sim {header.get('sim')!r}, "
            f"not {SIM_NAME!r}"
        )
    result = run_sim(
        seed=int(header.get("seed", 0)),
        ticks=int(header.get("ticks", TICKS)),
    )
    inc = _bundle(result, header["reason"])
    fresh = inc["lines"] if inc else []
    return header, {
        "lines": fresh,
        "identical": fresh == original,
        "first_violation": result["first_violation"],
    }


def replay_main(path: str) -> int:
    """CLI replay entry (also dispatched to by
    `python -m benchmarks.gameday_sim --replay <incident bundle>`)."""
    header, cmp = replay(path)
    print(f"replayed incident bundle {path}: "
          f"{len(cmp['lines'])} bundle lines")
    print(f"trigger: {header['reason']} ({header.get('detail', '')})")
    print(f"byte-identical: {cmp['identical']}")
    print(f"first SLO violation: {cmp['first_violation']}")
    return 0 if cmp["identical"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ticks", type=int, default=TICKS)
    ap.add_argument("--dump", help="write the fast-burn incident bundle here")
    ap.add_argument("--replay", metavar="BUNDLE",
                    help="re-run a dumped incident bundle and compare")
    args = ap.parse_args(argv)

    if args.replay:
        return replay_main(args.replay)

    result = run_sim(seed=args.seed, ticks=args.ticks)
    for chk in ALL_CHECKS:
        chk(result)
        print(f"PASS {chk.__name__}")
    fast = _fast_burn_ticks(result)
    print(json.dumps(
        {
            "first_violation": result["first_violation"],
            "fast_burn_tick": fast[0]["tick"] if fast else None,
            "onset_to_page_s": (
                fast[0]["t"] - result["regress_t"] if fast else None
            ),
            "incidents": [
                {"reason": i["reason"], "t": i["t"], "lines": len(i["lines"])}
                for i in result["incidents"]
            ],
            "ticks": result["ticks"],
        },
        indent=2, sort_keys=True,
    ))
    if args.dump:
        inc = _bundle(result, flightrecorder.TRIGGER_FAST_BURN)
        with open(args.dump, "w") as fh:
            fh.write("\n".join(inc["lines"]) + "\n")
        print(f"bundle -> {args.dump}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
