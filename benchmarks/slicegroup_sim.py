#!/usr/bin/env python
"""Slice-group serving-plane simulation: multi-host replicas as atomic
units, proven against the REAL control plane under a fake clock.

The world runs the production `ModelReconciler`, `ActuationGovernor`,
`CapacityPlanner`, `FleetStateAggregator`, and `LoadBalancer` over a
deterministic in-memory `KubeStore`. One multi-host model
(`google-tpu-v5e-4x4:8` — two 8-chip host pods per replica) serves on
an inventory of whole 4x4 slices while a chaos trace kills individual
group member hosts (`kill_group_host` events). A simplified kubelet
boots rendered pods; everything above the pod is the real code.

Invariants (the PR's acceptance criteria):

  * `no_partial_group_routable` — the LB never routes to a group that
    is incomplete or has a broken member: every routable address
    belongs to a fully-Ready group's coordinator (host 0).
  * `aggregator_groups_truthful` — the fleet snapshot never reports
    more Ready groups than the store actually holds; a partial or
    broken group is never Ready.
  * `planner_whole_groups` — the capacity plan never allocates more
    chips than the slice inventory, per shape, and the multi-host
    model's allocation is always a whole number of groups.
  * `atomic_repair` (terminal) — every killed host produced EXACTLY one
    whole-group repair (one `kubeai_slicegroup_repairs_total`
    increment, `num_hosts` pod replacements), each within the repair
    backoff bound.
  * `convergence` (terminal) — the model ends the run with all its
    groups fully Ready and routable.

Run: python benchmarks/slicegroup_sim.py [--ticks N] [--dump PATH]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.config import System
from kubeai_tpu.config.system import GovernorConfig
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.fleet import CapacityPlanner, FleetStateAggregator
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.operator import slicegroup
from kubeai_tpu.operator.controller import ModelReconciler
from kubeai_tpu.operator.governor import ActuationGovernor
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.loadbalancer import Group, LoadBalancer
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.testing.chaos import (
    CONTINUOUS,
    EV_KILL_GROUP_HOST,
    TERMINAL,
    ChaosKubeStore,
    GameDayEvent,
    GameDayLog,
    GameDayTrace,
    Invariant,
    InvariantChecker,
)
from kubeai_tpu.testing.clock import FakeClock
from kubeai_tpu.testing.faults import ApiFaultPlan
from kubeai_tpu.testing.simkit import break_pod, mk_model, scrape_diff

ACCEL = "tpu-v5-lite-podslice"
TOPOLOGY = "4x4"
PROFILE = "google-tpu-v5e-4x4:8"   # 8 chips PER HOST, 2 hosts per replica
MODEL = "big"
NUM_HOSTS = 2
CHIPS_PER_HOST = 8
GROUP_CHIPS = NUM_HOSTS * CHIPS_PER_HOST
REPLICAS = 2
SLICES = 3                         # whole 4x4 slices in the inventory

TICK_S = 1.0
WARMUP_TICKS = 8                   # steady state before the trace's t=0
BOOT_TICKS = 2                     # created pod -> Ready
REPAIR_BOUND_TICKS = 4             # kill -> whole-group repair bound

REPAIRS_SERIES = "kubeai_slicegroup_repairs_total"
REPLACE_SERIES = "kubeai_controller_pod_replacements_total"


def _node(name: str) -> dict:
    """One 8-chip host VM of a 4x4 slice: the topology label prices the
    slice (16 chips), allocatable prices the VM."""
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {
                "cloud.google.com/gke-tpu-accelerator": ACCEL,
                "cloud.google.com/gke-tpu-topology": TOPOLOGY,
            },
        },
        "status": {"allocatable": {"google.com/tpu": str(CHIPS_PER_HOST)}},
    }


class SliceGroupWorld:
    """Real control plane + simulated kubelet around one multi-host
    model. The kubelet is deliberately dumb: assign an IP, flip Ready
    after BOOT_TICKS, never touch a broken pod — repair is the
    reconciler's job and the whole point of the run."""

    def __init__(self, trace: GameDayTrace, ticks: int):
        self.trace = trace
        self.ticks = int(ticks)
        self.clock = FakeClock(1000.0)
        self.wall = FakeClock(1_000_000.0)
        self.tick_no = 0
        self.t0 = self.clock() + WARMUP_TICKS * TICK_S

        self._name_counter = itertools.count()
        self.raw_store = KubeStore(
            namegen=lambda: f"{next(self._name_counter):06d}"
        )
        self.api = ChaosKubeStore(self.raw_store, ApiFaultPlan())
        self.metrics = Metrics()

        cfg = System()
        cfg.fixed_self_metric_addrs = ["self:1"]
        cfg.default_and_validate()
        self.cfg = cfg

        for s in range(SLICES):
            for h in range(NUM_HOSTS):
                self.raw_store.create(_node(f"node-s{s}-h{h}"))

        mk_model(
            self.raw_store, MODEL, replicas=REPLICAS,
            resource_profile=PROFILE, autoscaling_disabled=True,
        )

        self.lb = LoadBalancer(self.raw_store, metrics=self.metrics)
        self.lb._groups[MODEL] = Group(
            metrics=self.metrics, model=MODEL, clock=self.clock
        )

        self.mc_raw = ModelClient(self.raw_store)
        self.aggregator = FleetStateAggregator(
            lb=self.lb, model_client=self.mc_raw, store=self.raw_store,
            metrics=self.metrics, interval_s=1.0, staleness_s=2.5,
            fetch_metrics=self.fetch_metrics, fetch_state=self.fetch_state,
            clock=self.clock,
        )

        gcfg = GovernorConfig(
            window_seconds=20.0,
            model_disruption_budget=2,
            cluster_disruption_budget=3,
            min_telemetry_coverage=0.9,
        )
        self.governor = ActuationGovernor(
            cfg=gcfg, fleet=self.aggregator, store=self.api,
            metrics=self.metrics, clock=self.clock,
        )
        self.mc = ModelClient(self.api)
        self.mc.governor = self.governor
        self.reconciler = ModelReconciler(
            self.api, cfg, metrics=self.metrics, clock=self.clock,
            wall=self.wall, governor=self.governor,
        )
        self.planner = CapacityPlanner(
            fleet=self.aggregator, model_client=self.mc, store=self.api,
            cfg=cfg, metrics=self.metrics, interval_s=1.0, staleness_s=2.5,
            clock=self.clock,
        )

        self.addr_model: dict[str, str] = {}
        self.dead: set[str] = set()
        self.first_seen: dict[str, int] = {}
        self.ip_counter = 1
        self.last_plan: dict | None = None
        self.kill_ticks: list[int] = []
        self.repair_ticks: list[int] = []
        self.control_plane_errors = 0
        self._metrics_base: str | None = None

        self.log = GameDayLog(trace, ticks)
        self.checker = InvariantChecker(INVARIANTS, log=self.log)

    # ---- time / telemetry ----------------------------------------------

    def rel_now(self) -> float:
        return self.clock() - self.t0

    def fetch_metrics(self, addr: str, timeout: float = 5.0) -> str:
        if self.addr_model.get(addr) is None or addr in self.dead:
            raise ConnectionError(f"injected: {addr} unreachable")
        return "\n".join([
            'kubeai_engine_queue_depth{class="standard"} 0.0',
            "kubeai_engine_queue_oldest_wait_seconds 0.0",
            "kubeai_engine_kv_cache_utilization 0.0",
            "kubeai_engine_slots_active 0.0",
            "kubeai_engine_slot_capacity 4.0",
            "kubeai_engine_active_requests 0.0",
        ]) + "\n"

    def fetch_state(self, addr: str, timeout: float = 5.0) -> dict:
        model = self.addr_model.get(addr)
        if model is None or addr in self.dead:
            raise ConnectionError(f"injected: {addr} unreachable")
        return {"model": model, "healthy": True}

    # ---- pod bookkeeping -----------------------------------------------

    def pods(self) -> list[dict]:
        return sorted(
            self.raw_store.list("Pod", "default", {md.POD_MODEL_LABEL: MODEL}),
            key=lambda p: p["metadata"]["name"],
        )

    def groups(self) -> dict[int, list[dict]]:
        return slicegroup.group_pods(self.pods())

    def addr_of(self, pod: dict) -> str | None:
        ip = pod.get("status", {}).get("podIP")
        return f"{ip}:8000" if ip else None

    def ready_group_addrs(self) -> set[str]:
        """Coordinator addresses of groups that are fully Ready."""
        out: set[str] = set()
        for members in self.groups().values():
            if not slicegroup.group_ready(members, NUM_HOSTS):
                continue
            coord = slicegroup.coordinator_pod(members)
            addr = self.addr_of(coord) if coord else None
            if addr:
                out.add(addr)
        return out

    def counter_total(self, series: str) -> float:
        """Sum of a counter across labels since the post-warmup baseline,
        measured from the exposition text — the control plane is audited
        from the outside."""
        if self._metrics_base is None:
            return 0.0
        return sum(
            delta
            for (name, _labels), delta in scrape_diff(
                self._metrics_base, self.metrics.registry.expose()
            ).items()
            if name == series
        )

    # ---- chaos ----------------------------------------------------------

    def apply_event(self, ev: GameDayEvent) -> None:
        p = ev.params
        if ev.kind != EV_KILL_GROUP_HOST:
            raise ValueError(f"slicegroup sim only speaks {EV_KILL_GROUP_HOST!r}")
        group, host = int(p.get("group", 0)), int(p.get("host", 0))
        for pod in self.pods():
            if (slicegroup.group_index(pod) == group
                    and slicegroup.host_index(pod) == host):
                break_pod(self.raw_store, pod, p.get("mode", "preempt"))
                addr = self.addr_of(pod)
                if addr:
                    self.dead.add(addr)
                self.kill_ticks.append(self.tick_no)
                return

    # ---- kubelet ---------------------------------------------------------

    def _kubelet(self) -> None:
        for pod in self.pods():
            st = pod.get("status", {})
            if st.get("podIP"):
                continue
            if st.get("reason") == "Preempted" or st.get("containerStatuses"):
                continue
            uid = pod["metadata"].get("uid") or pod["metadata"]["name"]
            born = self.first_seen.setdefault(uid, self.tick_no)
            if self.tick_no - born < BOOT_TICKS:
                continue
            ip = f"10.88.0.{self.ip_counter}"
            self.ip_counter += 1
            fresh = self.raw_store.get("Pod", "default",
                                       pod["metadata"]["name"])
            fresh.setdefault("status", {})["podIP"] = ip
            fresh["status"]["phase"] = "Running"
            fresh["status"]["conditions"] = [
                {"type": "Ready", "status": "True"},
                {"type": "PodScheduled", "status": "True"},
            ]
            self.raw_store.update(fresh)
            self.addr_model[f"{ip}:8000"] = MODEL

    # ---- the tick --------------------------------------------------------

    def tick(self) -> None:
        self.tick_no += 1
        self.clock.advance(TICK_S)
        self.wall.advance(TICK_S)
        rel = self.rel_now()

        prev_repairs = self.counter_total(REPAIRS_SERIES)
        for ev in self.trace.due(rel):
            self.apply_event(ev)
            self.log.event(self.tick_no, ev)
        self._kubelet()
        self.lb.sync_all()
        try:
            self.aggregator.collect()
        except Exception:
            self.control_plane_errors += 1
        plan = self.planner.tick(force=True)
        if plan is not None:
            self.last_plan = plan
        try:
            self.reconciler.reconcile("default", MODEL)
        except Exception:
            self.control_plane_errors += 1
        # A repair deleted the group's pods AFTER this tick's LB sync;
        # re-sync so the routing view the invariants audit reflects the
        # store the reconciler just wrote.
        self.lb.sync_all()

        repairs = self.counter_total(REPAIRS_SERIES)
        if repairs > prev_repairs:
            self.repair_ticks.extend(
                [self.tick_no] * int(round(repairs - prev_repairs))
            )

        groups = self.groups()
        self.log.obs(
            self.tick_no,
            t=round(rel, 3),
            groups_ready=sum(
                1 for m in groups.values()
                if slicegroup.group_ready(m, NUM_HOSTS)
            ),
            groups_total=len(groups),
            routable=len(self.lb.group(MODEL).addresses()),
            repairs=repairs,
        )
        self.checker.check_continuous(self, self.tick_no, rel)

    def run(self) -> dict:
        for _ in range(WARMUP_TICKS):
            self.tick()
        # Baseline AFTER warmup: steady-state creation is not repair.
        self._metrics_base = self.metrics.registry.expose()
        for _ in range(self.ticks):
            self.tick()
        self.checker.check_terminal(self, self.tick_no, self.rel_now())
        fv = self.checker.first_violation
        groups = self.groups()
        return {
            "ticks": self.ticks,
            "trace_events": len(self.trace.events),
            "kills": len(self.kill_ticks),
            "repairs": len(self.repair_ticks),
            "groups_ready": sum(
                1 for m in groups.values()
                if slicegroup.group_ready(m, NUM_HOSTS)
            ),
            "routable": sorted(self.lb.group(MODEL).addresses()),
            "pod_replacements": self.counter_total(REPLACE_SERIES),
            "control_plane_errors": self.control_plane_errors,
            "violations": [
                {"tick": v.tick, "t": v.t, "invariant": v.invariant,
                 "detail": v.detail}
                for v in self.checker.violations
            ],
            "first_violation": None if fv is None else {
                "tick": fv.tick, "t": fv.t, "invariant": fv.invariant,
                "detail": fv.detail,
            },
            "log": self.log,
        }


# ---- invariants --------------------------------------------------------------


def _inv_no_partial_group_routable(world) -> str | None:
    routable = set(world.lb.group(MODEL).addresses())
    extra = routable - world.ready_group_addrs()
    if extra:
        return (
            f"routable address(es) {sorted(extra)} do not belong to a "
            "fully-Ready slice group's coordinator"
        )
    return None


def _inv_aggregator_groups_truthful(world) -> str | None:
    snap = world.aggregator.snapshot()
    if not snap:
        return None
    entry = (snap.get("models") or {}).get(MODEL) or {}
    groups = (entry.get("pods") or {}).get("groups")
    if not groups:
        return None
    actual = sum(
        1 for m in world.groups().values()
        if slicegroup.group_ready(m, NUM_HOSTS)
    )
    if groups["ready"] > actual:
        return (
            f"snapshot reports {groups['ready']} Ready groups, the store "
            f"holds {actual} — a partial/broken group was counted Ready"
        )
    return None


def _inv_planner_whole_groups(world) -> str | None:
    plan = world.last_plan
    if plan is None:
        return None
    if plan["allocated_chips"]["total"] > plan["budget"]["total"]:
        return (
            f"plan allocates {plan['allocated_chips']['total']} chips "
            f"with only {plan['budget']['total']} in inventory"
        )
    for shape, used in plan["allocated_chips"]["by_shape"].items():
        if used > plan["budget"]["by_shape"].get(shape, 0):
            return f"shape {shape} over-allocated: {used}"
    rec = plan["models"].get(MODEL)
    if rec and rec["chips_allocated"] % GROUP_CHIPS:
        return (
            f"model {MODEL} allocated {rec['chips_allocated']} chips — "
            f"not a whole number of {GROUP_CHIPS}-chip groups"
        )
    return None


def _inv_atomic_repair(world) -> str | None:
    kills, repairs = world.kill_ticks, world.repair_ticks
    if len(repairs) != len(kills):
        return (
            f"{len(kills)} host kill(s) produced {len(repairs)} "
            "whole-group repair(s) — want exactly one each"
        )
    for kill_t, repair_t in zip(kills, repairs):
        if repair_t - kill_t > REPAIR_BOUND_TICKS:
            return (
                f"repair lagged the kill by {repair_t - kill_t} ticks "
                f"(bound {REPAIR_BOUND_TICKS})"
            )
    replaced = world.counter_total(REPLACE_SERIES)
    if replaced != len(kills) * NUM_HOSTS:
        return (
            f"{replaced:.0f} pod replacements for {len(kills)} group "
            f"repair(s) — want {NUM_HOSTS} per group, whole groups only"
        )
    return None


def _inv_convergence(world) -> str | None:
    groups = world.groups()
    ready = sum(
        1 for m in groups.values()
        if slicegroup.group_ready(m, NUM_HOSTS)
    )
    if ready != REPLICAS:
        return f"{ready}/{REPLICAS} groups Ready at end of run"
    routable = world.lb.group(MODEL).addresses()
    if len(routable) != REPLICAS:
        return (
            f"{len(routable)} routable endpoint(s) for {REPLICAS} "
            "Ready groups"
        )
    return None


INVARIANTS = (
    Invariant("no_partial_group_routable", _inv_no_partial_group_routable,
              CONTINUOUS,
              "every routable address is a fully-Ready group's host 0"),
    Invariant("aggregator_groups_truthful", _inv_aggregator_groups_truthful,
              CONTINUOUS,
              "the fleet snapshot never counts a partial group Ready"),
    Invariant("planner_whole_groups", _inv_planner_whole_groups,
              CONTINUOUS,
              "plans fit the slice inventory in whole groups"),
    Invariant("atomic_repair", _inv_atomic_repair, TERMINAL,
              "one kill -> one whole-group repair, within backoff bounds"),
    Invariant("convergence", _inv_convergence, TERMINAL,
              "all groups Ready and routable at end of run"),
)


# ---- traces ------------------------------------------------------------------


def default_trace() -> GameDayTrace:
    """Two member-host kills, staggered: a preempted worker (host 1 of
    group 0), then a crash-looping coordinator (host 0 of group 1) —
    both must yield one atomic whole-group repair."""
    return GameDayTrace([
        GameDayEvent(3.0, EV_KILL_GROUP_HOST, MODEL,
                     {"group": 0, "host": 1, "mode": "preempt"}),
        GameDayEvent(10.0, EV_KILL_GROUP_HOST, MODEL,
                     {"group": 1, "host": 0, "mode": "crashloop"}),
    ])


def run(trace: GameDayTrace | None = None, ticks: int = 22) -> dict:
    return SliceGroupWorld(trace or default_trace(), ticks).run()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ticks", type=int, default=22)
    ap.add_argument("--dump", default="")
    args = ap.parse_args()
    result = run(ticks=args.ticks)
    log = result.pop("log")
    if args.dump:
        log.dump(args.dump)
    print(json.dumps(result, indent=2, sort_keys=True))
    return 1 if result["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
