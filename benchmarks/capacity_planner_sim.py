"""Deterministic capacity-planner simulation — no JAX, no sockets.

Builds a synthetic fleet on a fake clock — five models across the three
scheduling classes (a realtime model under growing SLO pressure, a
standard model, a batch model holding chips, a disaggregated
prefill/decode model, and a 1-chip "tiny" model) — over ONE
heterogeneous chip pool (1-, 4-, and 8-chip slice shapes from Node
allocatable capacity), and drives the REAL FleetStateAggregator,
CapacityPlanner, and Autoscaler over scripted Prometheus exposition.

Two scenarios share the model set:

  * ABUNDANT — the chip budget exceeds every desire: the plan must be a
    no-op (allocations equal the uncoordinated autoscaler's desires,
    nothing preempted or throttled) and the autoscaler must actually
    scale through the plan (`scaling_source: "planner"`).
  * CONSTRAINED — the budget cannot fit the sum of desires: batch-class
    replicas must be preempted (and their pods annotation-marked for
    pod_plan's deletion ordering) before the realtime model is ever
    throttled, replicas must be right-sized onto the cheapest feasible
    slice shape, the disagg pair must shrink jointly, and total
    allocated chips must never exceed the inventory.

Invariants (asserted in tier-1 by tests/unit/test_capacity_planner.py):

  (a) no realtime-class SLO violation persists while idle chips exist
      that could host a feasible replica;
  (b) batch-class models are preempted before realtime-class models are
      ever throttled;
  (c) total allocated chips never exceed the inventory (per shape too);
  (d) with an abundant chip budget the planner's allocations equal the
      uncoordinated autoscaler's desires (no-op equivalence);
  plus: stale-snapshot safety (the autoscaler falls back to its direct
      per-model path and the plan stops answering), preemption victims
      marked for pod_plan, and joint prefill/decode damping.

Run directly for a human-readable report:

    python benchmarks/capacity_planner_sim.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.autoscaler import Autoscaler
from kubeai_tpu.autoscaler.autoscaler import (
    scrape_queue_pressure,
    scrape_role_signals,
)
from kubeai_tpu.config import System
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import (
    Disaggregation,
    Model,
    ModelSpec,
    Scheduling,
)
from kubeai_tpu.fleet import CapacityPlanner, FleetStateAggregator
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.loadbalancer import LoadBalancer
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.testing.faults import FakeClock

ACCEL = "tpu-v5-lite-podslice"
SHAPE_1 = f"{ACCEL}/1x1"
SHAPE_4 = f"{ACCEL}/2x2"
SHAPE_8 = f"{ACCEL}/2x4"

TICKS = 5

# (shape, chips_per_node, node_count) — the heterogeneous pool.
CONSTRAINED_NODES = ((SHAPE_1, 1, 4), (SHAPE_4, 4, 4), (SHAPE_8, 8, 2))
ABUNDANT_NODES = ((SHAPE_1, 1, 8), (SHAPE_4, 4, 20), (SHAPE_8, 8, 6))


class Endpoint:
    """Scripted signals for one serving endpoint, rendered as real
    Prometheus exposition text (what a production scrape returns)."""

    def __init__(self, model: str, role: str = "unified"):
        self.model = model
        self.role = role
        self.signals = {
            "depth": 0.0,
            "oldest_wait_s": 0.0,
            "kv_utilization": 0.0,
            "slots_active": 0.0,
            "slot_capacity": 32.0,
            "ttft_sum": 0.0,
            "ttft_count": 0.0,
            "active": 0.0,
        }

    def advance(self, tick: int) -> None:
        s = self.signals
        if self.model == "rt":
            # Realtime pressure ramps: the active signal grows and the
            # oldest queued request ages past the 3s queue-pressure
            # bound — an SLO violation the planner must relieve.
            s["active"] = float(min(35, 5 + 10 * tick))
            s["depth"] = 3.0
            s["oldest_wait_s"] = 5.0
        elif self.model == "std":
            s["active"] = 8.0
        elif self.model == "batch":
            s["active"] = 10.0  # per endpoint; demand sustains current
        elif self.model == "tiny":
            s["active"] = 5.0
        elif self.role == "prefill":
            s["depth"] = 12.0
            s["oldest_wait_s"] = 5.0
            s["ttft_sum"] += 0.2
            s["ttft_count"] += 1.0
        elif self.role == "decode":
            s["kv_utilization"] = 0.9
            s["slots_active"] = 16.0

    def exposition(self) -> str:
        s = self.signals
        return "\n".join(
            [
                'kubeai_engine_queue_depth{class="standard"} '
                f"{s['depth']}",
                "kubeai_engine_queue_oldest_wait_seconds "
                f"{s['oldest_wait_s']}",
                f"kubeai_engine_kv_cache_utilization {s['kv_utilization']}",
                f"kubeai_engine_slots_active {s['slots_active']}",
                f"kubeai_engine_slot_capacity {s['slot_capacity']}",
                f"kubeai_engine_ttft_seconds_sum {s['ttft_sum']}",
                f"kubeai_engine_ttft_seconds_count {s['ttft_count']}",
                f"kubeai_engine_active_requests {s['active']}",
            ]
        ) + "\n"

    def state(self) -> dict:
        return {"model": self.model, "healthy": True, "role": self.role}


def _pod(model: str, idx: int, addr: str, role: str | None = None,
         chips: int = 4, topology: str = "2x2", created: float = 0.0) -> dict:
    ip, _, port = addr.partition(":")
    labels = {"model": model}
    if role:
        labels["model-role"] = role
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"model-{model}-{idx}" + (f"-{role}" if role else ""),
            "namespace": "default",
            "labels": labels,
            "annotations": {"model-pod-ip": ip, "model-pod-port": port},
            "creationTimestamp": created,
        },
        "spec": {
            "nodeSelector": {
                "cloud.google.com/gke-tpu-accelerator": ACCEL,
                "cloud.google.com/gke-tpu-topology": topology,
            },
            "containers": [{
                "name": "server",
                "resources": {"limits": {"google.com/tpu": str(chips)}},
            }],
        },
        "status": {
            "conditions": [{"type": "Ready", "status": "True"}],
            "podIP": ip,
        },
    }


def _node(name: str, shape_topology: str, chips: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {
                "cloud.google.com/gke-tpu-accelerator": ACCEL,
                "cloud.google.com/gke-tpu-topology": shape_topology,
            },
        },
        "status": {"allocatable": {"google.com/tpu": str(chips)}},
    }


class PlannerWorld:
    """One complete in-process fleet: store (+Nodes) + LB + models +
    scripted endpoints + aggregator (+ optionally the planner)."""

    def __init__(self, nodes=CONSTRAINED_NODES, with_planner: bool = True):
        self.clock = FakeClock(1000.0)
        self.store = KubeStore()
        self.cfg = System()
        self.cfg.fixed_self_metric_addrs = ["self:1"]
        # window == interval → the moving average IS the signal; the
        # scripted ramps translate 1:1 into desires.
        self.cfg.model_autoscaling.interval_seconds = 10.0
        self.cfg.model_autoscaling.time_window_seconds = 10.0
        self.cfg.default_and_validate()
        self.mc = ModelClient(self.store)
        self.lb = LoadBalancer(self.store)
        self.metrics = Metrics()
        self.endpoints: dict[str, Endpoint] = {}
        self.tick_no = 0

        for shape, chips, count in nodes:
            topo = shape.split("/", 1)[1]
            for i in range(count):
                self.store.create(
                    _node(f"node-{topo}-{i}", topo, chips)
                )

        common = dict(
            url="hf://org/x", engine="KubeAITPU",
            features=["TextGeneration"], min_replicas=0, max_replicas=10,
            target_requests=10, scale_down_delay_seconds=0,
        )

        def add_model(name, replicas, cls, chips=4, topology="2x2",
                      **extra):
            self.store.create(
                Model(
                    name=name,
                    spec=ModelSpec(
                        **common, replicas=replicas,
                        scheduling=Scheduling(default_priority=cls),
                        **extra,
                    ),
                ).to_dict()
            )
            for j in range(replicas):
                addr = f"10.{len(self.endpoints)}.0.{j}:8000"
                self.endpoints[addr] = Endpoint(name)
                self.store.create(
                    _pod(name, j, addr, chips=chips, topology=topology,
                         created=float(j))
                )

        add_model("rt", 1, "realtime")
        add_model("std", 1, "standard")
        add_model("batch", 3, "batch")
        add_model("tiny", 1, "standard", chips=1, topology="1x1")
        # Disaggregated standard-class model: one prefill + one decode.
        self.store.create(
            Model(
                name="dis",
                spec=ModelSpec(
                    **common, replicas=0,
                    scheduling=Scheduling(default_priority="standard"),
                    disaggregation=Disaggregation(
                        enabled=True, prefill_target_queue=4,
                        decode_target_utilization=0.8,
                    ),
                ),
            ).to_dict()
        )
        for j, role in ((0, "prefill"), (1, "decode")):
            addr = f"10.9.0.{j}:8000"
            self.endpoints[addr] = Endpoint("dis", role=role)
            self.store.create(
                _pod("dis", j, addr, role=role, created=float(j))
            )
        self.lb.sync_all()

        self.aggregator = FleetStateAggregator(
            lb=self.lb, model_client=self.mc, store=self.store,
            metrics=self.metrics, interval_s=1.0, staleness_s=2.5,
            fetch_metrics=self.fetch_metrics, fetch_state=self.fetch_state,
            clock=self.clock,
        )

        class AlwaysLeader:
            is_leader = True

        self.scaler = Autoscaler(
            self.store, self.cfg, self.mc, self.lb, AlwaysLeader(),
            metrics=self.metrics,
        )
        self.scaler.active_scraper = lambda addrs: self.active_totals()
        self.scaler.queue_scraper = lambda addrs: scrape_queue_pressure(
            addrs, fetch=self.fetch_metrics
        )
        self.scaler.role_scraper = lambda addrs: scrape_role_signals(
            addrs, fetch=self.fetch_metrics
        )
        self.scaler.fleet = self.aggregator

        self.planner = None
        if with_planner:
            self.planner = CapacityPlanner(
                fleet=self.aggregator, model_client=self.mc,
                store=self.store, cfg=self.cfg, metrics=self.metrics,
                interval_s=1.0, staleness_s=2.5, clock=self.clock,
            )
            self.planner.avg_lookup = self.scaler.current_average
            self.scaler.planner = self.planner

    # -- scripted transport ------------------------------------------------

    def fetch_metrics(self, addr: str, timeout: float = 5.0) -> str:
        return self.endpoints[addr].exposition()

    def fetch_state(self, addr: str, timeout: float = 5.0) -> dict:
        return self.endpoints[addr].state()

    def active_totals(self) -> dict[str, float]:
        totals: dict[str, float] = {}
        for ep in self.endpoints.values():
            totals[ep.model] = totals.get(ep.model, 0.0) + ep.signals["active"]
        return totals

    def advance(self) -> None:
        self.tick_no += 1
        self.clock.advance(1.0)
        for ep in self.endpoints.values():
            ep.advance(self.tick_no)

    def run_tick(self) -> dict | None:
        """One full control tick: sweep the fleet, scale (consulting the
        PREVIOUS plan, as in production), then re-plan on the fresh
        averages. Returns the new plan (None without a planner)."""
        self.advance()
        self.aggregator.collect()
        self.scaler.tick()
        if self.planner is not None:
            return self.planner.tick()
        return None


def run_sim(ticks: int = TICKS) -> dict:
    """Run both scenarios; returns measured facts for the tier-1
    invariant assertions (and the __main__ report)."""
    # -- abundant: planner world + an identical uncoordinated world ------
    abundant = PlannerWorld(nodes=ABUNDANT_NODES, with_planner=True)
    direct = PlannerWorld(nodes=ABUNDANT_NODES, with_planner=False)
    abundant_pairs = []  # (plan, direct last_decisions) per tick
    abundant_decisions = []
    for _ in range(ticks):
        plan = abundant.run_tick()
        direct.run_tick()
        abundant_pairs.append((plan, list(direct.scaler.last_decisions)))
        abundant_decisions.append(list(abundant.scaler.last_decisions))

    # -- constrained: same models, small heterogeneous pool --------------
    con = PlannerWorld(nodes=CONSTRAINED_NODES, with_planner=True)
    con_plans = []
    for _ in range(ticks):
        con_plans.append(con.run_tick())

    batch_pods = con.store.list("Pod", "default", {"model": "batch"})
    marked = sorted(
        p["metadata"]["name"] for p in batch_pods
        if k8sutils.get_annotation(p, md.PLANNER_PREEMPT_ANNOTATION)
    )

    # -- staleness: freeze the aggregator, age the clock past the bound --
    con.clock.advance(10.0)
    stale_plan_result = con.planner.tick()
    con.advance()  # signals move but nothing re-sweeps the fleet
    con.scaler.tick()
    stale_decisions = list(con.scaler.last_decisions)
    stale_alloc = con.planner.allocation_for("rt")

    return {
        "ticks": ticks,
        "abundant_pairs": abundant_pairs,
        "abundant_decisions": abundant_decisions,
        "abundant_budget": sum(c * n for _, c, n in ABUNDANT_NODES),
        "constrained_plans": con_plans,
        "constrained_budget": sum(c * n for _, c, n in CONSTRAINED_NODES),
        "batch_marked_pods": marked,
        "batch_pods": batch_pods,
        "stale_plan_result": stale_plan_result,
        "stale_decisions": stale_decisions,
        "stale_alloc": stale_alloc,
        "stale_ticks_metric": con.metrics.planner_stale_ticks.get(),
    }


# -- invariant checks (imported by tests/unit/test_capacity_planner.py) -------


def _feasible_free_chips(plan: dict, cpr: int) -> int:
    """Free chips on shapes that could actually host a cpr-chip replica."""
    slice_chips = plan["budget"]["slice_chips"]
    return sum(
        free for shape, free in plan["free_chips"]["by_shape"].items()
        if slice_chips.get(shape, 0) >= cpr
    )


def check_no_realtime_starvation(result: dict) -> None:
    """(a) A realtime model is only ever throttled when no idle chips
    could host one of its replicas — and in this scenario the budget
    always can, so its SLO pressure is fully relieved."""
    saw_pressure = False
    for plan in result["constrained_plans"]:
        if plan is None:
            continue
        for name, rec in plan["models"].items():
            if rec["kind"] == "fixed" or rec["class"] != "realtime":
                continue
            saw_pressure = saw_pressure or rec["slo_pressure"]
            if rec["throttled_replicas"] > 0:
                assert _feasible_free_chips(
                    plan, rec["chips_per_replica"]
                ) < rec["chips_per_replica"], (
                    f"{name} throttled while feasible chips sit idle"
                )
    final = result["constrained_plans"][-1]
    rt = final["models"]["rt"]
    assert saw_pressure, "scenario must exercise realtime SLO pressure"
    assert rt["allocated_replicas"] == rt["target_replicas"] > 1, (
        "realtime demand must be fully allocated under contention"
    )


def check_batch_preempted_first(result: dict) -> None:
    """(b) Whenever any realtime model is throttled, every batch model
    is already down to its floor; and the scenario actually preempts."""
    preempted = False
    for plan in result["constrained_plans"]:
        if plan is None:
            continue
        rt_throttled = any(
            rec["throttled_replicas"] > 0
            for rec in plan["models"].values()
            if rec["kind"] != "fixed" and rec["class"] == "realtime"
        )
        for name, rec in plan["models"].items():
            if rec["kind"] == "fixed" or rec["class"] != "batch":
                continue
            if rec["preempted_replicas"] > 0:
                preempted = True
            if rt_throttled:
                assert rec["allocated_replicas"] <= rec.get("floor", 0), (
                    f"{name} holds chips while realtime is throttled"
                )
        # Stronger: batch holds NOTHING while higher classes are
        # throttled at all.
        any_higher_throttled = any(
            rec["throttled_replicas"] > 0
            for rec in plan["models"].values()
            if rec["kind"] != "fixed"
            and rec["class"] in ("realtime", "standard")
        )
        if any_higher_throttled:
            for rec in plan["models"].values():
                if rec["kind"] != "fixed" and rec["class"] == "batch":
                    assert rec["allocated_replicas"] == 0
    final = result["constrained_plans"][-1]
    assert preempted, "scenario must actually preempt batch replicas"
    assert final["models"]["batch"]["preempted_replicas"] > 0
    rt = final["models"]["rt"]
    assert rt["allocated_replicas"] == rt["target_replicas"], (
        "preempted chips must reach the realtime model"
    )


def check_chip_budget_respected(result: dict) -> None:
    """(c) Total allocated chips never exceed the inventory — in both
    scenarios, per shape too."""
    for plans in (result["constrained_plans"],
                  [p for p, _ in result["abundant_pairs"]]):
        for plan in plans:
            if plan is None:
                continue
            assert (
                plan["allocated_chips"]["total"] <= plan["budget"]["total"]
            )
            for shape, used in plan["allocated_chips"]["by_shape"].items():
                assert used <= plan["budget"]["by_shape"][shape], shape
                assert plan["free_chips"]["by_shape"][shape] >= 0, shape


def check_noop_equivalence(result: dict) -> None:
    """(d) Abundant budget: the plan allocates exactly what the
    uncoordinated autoscaler desires — nothing throttled, nothing
    preempted — and the autoscaler really scales through the plan."""
    for tick, (plan, direct_decisions) in enumerate(
        result["abundant_pairs"]
    ):
        assert plan is not None, f"tick {tick}: no plan"
        by_model = {d["model"]: d for d in direct_decisions}
        for name, rec in plan["models"].items():
            if rec["kind"] == "fixed":
                continue
            assert rec["throttled_replicas"] == 0, (name, tick)
            assert rec["preempted_replicas"] == 0, (name, tick)
            d = by_model[name]
            if rec["kind"] == "disagg":
                for role in ("prefill", "decode"):
                    want = d["roles"][role]["computed_replicas"]
                    got = rec["allocated_roles"][role]
                    assert got == max(1, want), (
                        f"tick {tick}: {name}/{role} plan {got} != "
                        f"direct desire {want}"
                    )
            else:
                want = d["computed_replicas"]
                got = rec["allocated_replicas"]
                assert got == want, (
                    f"tick {tick}: {name} plan {got} != direct desire "
                    f"{want}"
                )
    # From the second tick on a fresh plan exists, so the autoscaler
    # must be applying it (planner as the scaling source).
    for decisions in result["abundant_decisions"][1:]:
        for d in decisions:
            assert d["scaling_source"] == "planner", d["model"]
            assert d["telemetry_source"] is not None


def check_right_sizing(result: dict) -> None:
    """Replicas land on the cheapest slice shape that can host them:
    the 1-chip model on the 1-chip shape (even with big slices free in
    the abundant world), 4-chip replicas never on the 1-chip shape."""
    for plans in ([p for p, _ in result["abundant_pairs"]],
                  result["constrained_plans"]):
        final = plans[-1]
        tiny = final["models"]["tiny"]
        assert set(tiny["shapes"]) == {SHAPE_1}, tiny["shapes"]
        for name, rec in final["models"].items():
            if rec["chips_per_replica"] > 1:
                assert SHAPE_1 not in rec["shapes"], (name, rec["shapes"])
    # Under contention the cheap 4-chip pool fills before the 8-chip
    # pool and infeasible 1-chip slices stay idle.
    final = result["constrained_plans"][-1]
    assert final["free_chips"]["by_shape"][SHAPE_4] == 0
    assert final["free_chips"]["by_shape"][SHAPE_1] > 0


def check_joint_disagg_damping(result: dict) -> None:
    """Under chip pressure the disagg pair shrinks jointly: both roles
    stay above their floors and share the shortfall instead of one role
    being chopped to make room for the other."""
    final = result["constrained_plans"][-1]
    dis = final["models"]["dis"]
    assert dis["kind"] == "disagg"
    pre, dec = dis["allocated_roles"]["prefill"], dis["allocated_roles"]["decode"]
    tp, td = dis["target_roles"]["prefill"], dis["target_roles"]["decode"]
    assert dis["throttled_replicas"] > 0, "scenario must squeeze disagg"
    assert pre >= 1 and dec >= 1, "both roles must keep their floor"
    assert pre < tp and dec < td, (
        "the shortfall must be shared across roles, not dumped on one"
    )
    # Fill fractions within one grant of each other (ratio damping).
    assert abs(pre / tp - dec / td) <= max(1 / tp, 1 / td) + 1e-9


def check_preemption_marks(result: dict) -> None:
    """Preemption picks are written onto pods for pod_plan: every
    deleted-beyond-allocation batch pod carries the annotation, and the
    deletion ordering puts marked pods first."""
    from kubeai_tpu.operator.pod_plan import sort_pods_by_deletion_order

    final = result["constrained_plans"][-1]
    batch = final["models"]["batch"]
    n_del = batch["current_replicas"] - batch["allocated_replicas"]
    assert len(result["batch_marked_pods"]) == n_del > 0
    pods = [dict(p) for p in result["batch_pods"]]
    ordered = sort_pods_by_deletion_order(pods, "whatever")
    first = {
        p["metadata"]["name"] for p in ordered[:len(result["batch_marked_pods"])]
    }
    assert first == set(result["batch_marked_pods"]), (
        "marked victims must sort to the front of the deletion order"
    )


def check_stale_snapshot_fallback(result: dict) -> None:
    """Planner staleness safety: a stale fleet snapshot stops the plan
    (stale-tick counter moves, allocation_for answers None) and the
    autoscaler falls back to its direct per-model path."""
    assert result["stale_plan_result"] is None
    assert result["stale_ticks_metric"] >= 1
    assert result["stale_alloc"] is None
    assert result["stale_decisions"], "stale tick must still decide"
    for d in result["stale_decisions"]:
        assert d["scaling_source"] == "direct", d["model"]
        # Aggregator stale → the telemetry came from a direct scrape.
        src = d["telemetry_source"]
        if isinstance(src, dict):
            assert set(src.values()) == {"scrape"}, src
        else:
            assert src == "scrape", src


def check_decision_records(result: dict) -> None:
    """Plan decision records mirror Autoscaler.last_decisions: one per
    model with ts + telemetry source + the allocation arithmetic."""
    final = result["constrained_plans"][-1]
    for name, rec in final["models"].items():
        assert rec["model"] == name
        assert rec["telemetry_source"] == "aggregator"
        assert "ts" in rec and "snapshot_age_s" in rec
        assert rec["class"] in ("realtime", "standard", "batch")


ALL_CHECKS = (
    check_no_realtime_starvation,
    check_batch_preempted_first,
    check_chip_budget_respected,
    check_noop_equivalence,
    check_right_sizing,
    check_joint_disagg_damping,
    check_preemption_marks,
    check_stale_snapshot_fallback,
    check_decision_records,
)


def main() -> int:
    result = run_sim()
    for chk in ALL_CHECKS:
        chk(result)
        print(f"PASS {chk.__name__}")
    final = result["constrained_plans"][-1]
    print(json.dumps(
        {
            "constrained_budget": final["budget"],
            "allocated": final["allocated_chips"],
            "free": final["free_chips"],
            "preemptions": final["preemptions"],
            "models": {
                name: {
                    k: rec[k]
                    for k in (
                        "class", "kind", "chips_allocated",
                    )
                }
                for name, rec in final["models"].items()
            },
            "batch_marked_pods": result["batch_marked_pods"],
            "ticks": result["ticks"],
        },
        indent=2, sort_keys=True,
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
