"""Deterministic cluster-KV-sharing simulation — no JAX, no sockets.

Plays the same multi-turn chat workload (conversations whose prompts
grow turn over turn and share a common system prefix) against the same
replica fleet twice on a fake clock:

  * BASELINE: classic CHWBL prefix-hash routing, per-replica prefix
    caches only. A request spilled off its hash target by the bounded
    load threshold lands on a replica that holds none of its pages and
    pays the full prefill; a conversation's history is re-prefilled on
    every replica it ever touches.
  * SHARING: the full cluster tier. Every replica advertises its held
    page-hash chains; routing goes through the REAL load-balancer
    Group's longest-held-prefix pick (same bounded-load threshold), and
    a serving replica missing pages fetches them from the deepest
    closed-circuit holder (the REAL `Group.kv_holder` gate) instead of
    recomputing — unless the request's deadline budget can't cover the
    transfer, in which case it recomputes locally.

Mid-run one replica's circuit is tripped open while its (still
advertised) holdings stay in the pushed map, so the sim exercises the
holder gate with a live temptation. A recurring slice of requests
carries a zero fetch budget, exercising the deadline gate.

Page-hash chains are the REAL `page_hash_chain` fold (bit-identical to
the engine's `_prefix_hashes`), capped at the engine's admission limit.

Invariants (asserted in tier-1 by tests/unit/test_kv_sharing_sim.py):

  * the sharing fleet prefills STRICTLY fewer tokens than baseline on
    the identical workload (the tier's reason to exist);
  * zero peer fetches issued to an open-circuit peer;
  * zero peer fetches issued past the request's deadline budget;
  * mean TTFT no worse than baseline (pages transfer faster than they
    recompute);
  * the run is deterministic: same inputs, byte-identical report.

Run directly for the full-size report:

    python benchmarks/kv_sharing_sim.py
"""

from __future__ import annotations

import json
import math
import os
import sys
from collections import OrderedDict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.crd.model import LB_STRATEGY_PREFIX_HASH
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.routing.health import STATE_CLOSED, BreakerPolicy
from kubeai_tpu.routing.loadbalancer import Group, NoHealthyEndpoints
from kubeai_tpu.routing.prefixchain import page_hash_chain
from kubeai_tpu.testing.faults import FakeClock

PAGE = 16  # tokens per KV page
PREFILL_RATE = 64  # tokens prefilled per tick
FETCH_PAGES_PER_TICK = 8  # peer-transfer bandwidth (pages/tick)
DECODE_TICKS = 4  # fixed decode tail per request
SYS_TOKENS = [7] * (4 * PAGE)  # shared system prefix: 4 full pages


def _user_turn(conv: int, turn: int) -> list[int]:
    return [(1009 * conv + 53 * turn + j) % 50021 for j in range(48)]


def _assistant_turn(conv: int, turn: int) -> list[int]:
    return [(7919 * conv + 97 * turn + j) % 50021 for j in range(32)]


class _Arrival:
    __slots__ = ("tick", "conv", "turn", "prompt_ids", "history_ids", "budget")

    def __init__(self, tick, conv, turn, prompt_ids, history_ids, budget):
        self.tick = tick
        self.conv = conv
        self.turn = turn
        self.prompt_ids = prompt_ids  # tokens the request prefills over
        self.history_ids = history_ids  # prompt + response: cached after
        self.budget = budget  # fetch-deadline budget in ticks


def _workload(
    n_convs: int, n_turns: int, turn_gap: int, tight_every: int
) -> list[_Arrival]:
    """Deterministic multi-turn arrivals: conversation c's turn t lands
    at `t*turn_gap + c`, so each round's requests overlap in flight and
    the bounded-load threshold actually bites. Every `tight_every`-th
    request (fleet-wide order) carries a zero fetch budget — its TTFT
    deadline leaves no room for a peer transfer."""
    arrivals: list[_Arrival] = []
    rid = 0
    for turn in range(n_turns):
        for conv in range(n_convs):
            history: list[int] = list(SYS_TOKENS)
            for prev in range(turn):
                history += _user_turn(conv, prev)
                history += _assistant_turn(conv, prev)
            prompt = history + _user_turn(conv, turn)
            after = prompt + _assistant_turn(conv, turn)
            budget = 0 if rid % tight_every == tight_every - 1 else 10
            arrivals.append(
                _Arrival(turn * turn_gap + conv, conv, turn, prompt, after,
                         budget)
            )
            rid += 1
    return arrivals


class _Replica:
    """One replica's prefix cache: an LRU of held page hashes, the same
    shape `PageAllocator` exposes through `holdings()`."""

    def __init__(self, addr: str, cache_pages: int):
        self.addr = addr
        self.cache_pages = cache_pages
        self.held: OrderedDict[str, bool] = OrderedDict()

    def held_depth(self, chain: list[str]) -> int:
        depth = 0
        for h in chain:
            if h not in self.held:
                break
            depth += 1
        return depth

    def insert(self, hashes: list[str]) -> None:
        for h in hashes:
            self.held[h] = True
            self.held.move_to_end(h)
        while len(self.held) > self.cache_pages:
            self.held.popitem(last=False)  # LRU eviction


def _run_fleet(
    arrivals: list[_Arrival],
    n_replicas: int,
    cache_pages: int,
    sharing: bool,
    trip_at: int,
) -> dict:
    clock = FakeClock()
    group = Group(
        metrics=Metrics(), model="sim",
        breaker=BreakerPolicy(consecutive_failures=1, open_seconds=1e9),
        clock=clock,
    )
    replicas = [
        _Replica(f"replica-{i}:1", cache_pages) for i in range(n_replicas)
    ]
    by_addr = {r.addr: r for r in replicas}
    group.reconcile_endpoints({r.addr: set() for r in replicas})

    dead_addr = replicas[0].addr if n_replicas > 1 else None
    tripped = False

    prefill_tokens = 0
    ttfts: list[int] = []
    fetch_attempts = 0
    fetched_pages = 0
    deadline_gated = 0
    fetches_past_deadline = 0
    fetches_to_open_circuit = 0
    open_circuit_picks = 0
    holder_route_picks = 0
    dead_holdings_advertised = False

    active: list[tuple[int, object, str]] = []  # (finish_tick, done, addr)
    queue = sorted(arrivals, key=lambda a: (a.tick, a.conv))
    ai = 0
    now = 0
    while ai < len(queue) or active:
        clock.advance(1.0)

        # Fleet-aggregator collect loop: push every replica's holdings
        # each tick (interval well inside the holdings TTL). The DEAD
        # replica keeps advertising — the holder gate, not the push,
        # must keep fetches away from it.
        if sharing:
            holdings = {r.addr: list(r.held) for r in replicas}
            group.set_kv_holdings(holdings)
            if tripped and dead_addr and holdings.get(dead_addr):
                dead_holdings_advertised = True

        if dead_addr is not None and not tripped and now == trip_at:
            addr, done = group.get_best_addr(
                "LeastLoad", "", "", timeout=0.0,
                exclude=[r.addr for r in replicas if r.addr != dead_addr],
            )
            done(outcome="connect_error", error="simulated replica death")
            tripped = True

        still: list[tuple[int, object, str]] = []
        for finish, done, addr in active:
            if finish <= now:
                # Streams that were mid-flight on the dead replica when
                # its circuit tripped finish without feeding the breaker
                # (their success must not half-close the open circuit).
                if tripped and addr == dead_addr:
                    done()
                else:
                    done(outcome="success")
            else:
                still.append((finish, done, addr))
        active = still

        while ai < len(queue) and queue[ai].tick <= now:
            req = queue[ai]
            ai += 1
            ids = req.prompt_ids
            full_chain = page_hash_chain(ids, PAGE)
            chain = full_chain[: max(0, (len(ids) - 1) // PAGE)]
            try:
                addr, done = group.get_best_addr(
                    LB_STRATEGY_PREFIX_HASH, "", f"conv-{req.conv}",
                    timeout=0.0, chain=chain if sharing else None,
                )
            except NoHealthyEndpoints:
                queue.append(req)  # retry next tick (keep sort stable)
                queue.sort(key=lambda a: (a.tick, a.conv))
                continue
            ep_state = group.snapshot()["endpoints"][addr]["state"]
            if ep_state != STATE_CLOSED:
                open_circuit_picks += 1
            replica = by_addr[addr]
            local = replica.held_depth(chain)
            if local > 0:
                holder_route_picks += 1

            covered = local
            fetch_cost = 0
            if sharing and local < len(chain):
                peer, depth = group.kv_holder(chain, exclude={addr})
                if peer is not None and depth > local:
                    pages = depth - local
                    cost = math.ceil(pages / FETCH_PAGES_PER_TICK)
                    if cost > req.budget:
                        # Deadline gate: the transfer won't land inside
                        # the request's TTFT budget — recompute locally.
                        deadline_gated += 1
                    else:
                        peer_state = (
                            group.snapshot()["endpoints"]
                            .get(peer, {"state": "gone"})["state"]
                        )
                        if peer_state != STATE_CLOSED:
                            fetches_to_open_circuit += 1
                        if cost > req.budget:
                            fetches_past_deadline += 1
                        fetch_attempts += 1
                        fetched_pages += pages
                        covered = depth
                        fetch_cost = cost

            tokens = len(ids) - covered * PAGE
            prefill_tokens += tokens
            prefill_ticks = math.ceil(tokens / PREFILL_RATE)
            ttfts.append(fetch_cost + prefill_ticks)
            # After serving, the replica holds every full page of the
            # post-response history (what the engine's prefix cache
            # registers as the stream retires).
            replica.insert(page_hash_chain(req.history_ids, PAGE))
            active.append(
                (now + fetch_cost + prefill_ticks + DECODE_TICKS, done, addr)
            )

        now += 1
        if now > 100_000:
            raise RuntimeError("kv-sharing sim did not converge")

    return {
        "completed": len(ttfts),
        "prefill_tokens": prefill_tokens,
        "mean_ttft": sum(ttfts) / max(1, len(ttfts)),
        "fetch_attempts": fetch_attempts,
        "fetched_pages": fetched_pages,
        "deadline_gated_fetches": deadline_gated,
        "fetches_past_deadline": fetches_past_deadline,
        "fetches_to_open_circuit": fetches_to_open_circuit,
        "open_circuit_picks": open_circuit_picks,
        "holder_route_picks": holder_route_picks,
        "circuit_tripped": tripped,
        "dead_holdings_advertised": dead_holdings_advertised,
    }


def run_sim(
    n_convs: int = 12,
    n_turns: int = 6,
    n_replicas: int = 4,
    cache_pages: int = 512,
    turn_gap: int = 14,
    tight_every: int = 3,
) -> dict:
    arrivals = _workload(n_convs, n_turns, turn_gap, tight_every)
    trip_at = (n_turns * turn_gap) // 2
    baseline = _run_fleet(
        arrivals, n_replicas, cache_pages, sharing=False, trip_at=trip_at
    )
    sharing = _run_fleet(
        arrivals, n_replicas, cache_pages, sharing=True, trip_at=trip_at
    )
    return {
        "params": {
            "n_convs": n_convs,
            "n_turns": n_turns,
            "n_replicas": n_replicas,
            "cache_pages": cache_pages,
            "turn_gap": turn_gap,
            "tight_every": tight_every,
            "page_size": PAGE,
        },
        "baseline": baseline,
        "sharing": sharing,
    }


def check_invariants(summary: dict) -> list[str]:
    """Empty list = every cluster-KV-sharing promise held."""
    errors: list[str] = []
    base, share = summary["baseline"], summary["sharing"]
    n = summary["params"]["n_convs"] * summary["params"]["n_turns"]
    for name, run in (("baseline", base), ("sharing", share)):
        if run["completed"] != n:
            errors.append(
                f"lost requests: {name} completed {run['completed']}/{n}"
            )
        if not run["circuit_tripped"]:
            errors.append(f"{name}: replica-death scenario never armed")
        if run["open_circuit_picks"] != 0:
            errors.append(
                f"{name}: {run['open_circuit_picks']} pick(s) routed to an "
                "open-circuit replica"
            )
    if share["prefill_tokens"] >= base["prefill_tokens"]:
        errors.append(
            "sharing did not reduce fleet prefill: "
            f"{share['prefill_tokens']} >= {base['prefill_tokens']} tokens"
        )
    if share["mean_ttft"] > base["mean_ttft"]:
        errors.append(
            f"TTFT regressed: sharing mean {share['mean_ttft']:.2f} > "
            f"baseline mean {base['mean_ttft']:.2f}"
        )
    if share["fetches_to_open_circuit"] != 0:
        errors.append(
            f"{share['fetches_to_open_circuit']} fetch(es) issued to an "
            "open-circuit peer"
        )
    if share["fetches_past_deadline"] != 0:
        errors.append(
            f"{share['fetches_past_deadline']} fetch(es) issued past the "
            "request deadline budget"
        )
    # Contrast guards: a sim that never tempts its gates proves nothing.
    if share["fetch_attempts"] == 0:
        errors.append("no peer fetches occurred — sim lost its contrast")
    if share["deadline_gated_fetches"] == 0:
        errors.append("the deadline gate was never exercised")
    if not share["dead_holdings_advertised"]:
        errors.append(
            "the dead replica's holdings were never advertised after the "
            "trip — the open-circuit holder gate went untested"
        )
    return errors


if __name__ == "__main__":
    summary = run_sim()
    print(json.dumps(summary, indent=2, sort_keys=True))
    problems = check_invariants(summary)
    if problems:
        print("\nINVARIANT VIOLATIONS:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        sys.exit(1)
    print("\nall invariants held")
