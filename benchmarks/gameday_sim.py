"""Game-day simulation — every chaos axis at once, one fake clock.

The prior sims each break ONE thing: resilience_sim breaks endpoints,
control_plane_chaos_sim breaks the API server, tenant_isolation_sim
floods a tenant, capacity_planner_sim squeezes the chip budget. This
harness composes all of them against the REAL components — reconciler +
actuation governor, autoscaler + capacity planner + fleet aggregator,
load balancer + circuit breakers, the tenant door, and a simulated
engine data plane — driven by one declarative, seeded
`GameDayTrace` (kubeai_tpu/testing/chaos.py) whose events can land on
the SAME tick:

    kill/spot-preempt a pod, wedge an engine's step loop, partition or
    storm the API server, flood a tenant, flip the spot chip budget,
    stale-out telemetry, drop a proxy->engine link.

Invariants split into two kinds:

  CONTINUOUS (checked every tick)
    * zero client-visible stream errors — every interrupted stream
      resumes within the proxy's resume budget;
    * budgeted pod deletions per sliding window stay within the
      governor's model AND cluster disruption budgets (measured from
      metric scrapes, not from the governor's own bookkeeping);
    * realtime traffic is NEVER door-shed, no matter the overload;
    * the capacity plan never allocates more chips than the inventory
      (per shape too);
    * the billing ledger exactly matches delivered work — no
      double-billing across stream resumes;
    * resumed streams deliver every token exactly once.

  TERMINAL (checked once, after the last chaos event)
    * the fleet converges back to a healthy steady state (ready ==
      spec, queues drained, door closed) within CONVERGE_BOUND_S.

Every run writes a JSONL `GameDayLog`; a failing run replays
byte-identically from its dump:

    python benchmarks/gameday_sim.py --trace failing --dump /tmp/g.jsonl
    python -m benchmarks.gameday_sim --replay /tmp/g.jsonl

Run directly for a human-readable report:

    python benchmarks/gameday_sim.py [--ticks N] [--seed N]
        [--trace fast|extended|failing]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from collections import deque

from kubeai_tpu.autoscaler import Autoscaler
from kubeai_tpu.autoscaler.autoscaler import (
    scrape_queue_pressure,
    scrape_role_signals,
)
from kubeai_tpu.config import System
from kubeai_tpu.config.system import GovernorConfig, TenancyConfig
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.fleet import CapacityPlanner, FleetStateAggregator
from kubeai_tpu.fleet.metering import UsageMeter
from kubeai_tpu.fleet.tenancy import TenantGovernor, build_door
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.operator.controller import ModelReconciler
from kubeai_tpu.operator.governor import ActuationGovernor
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.operator import slicegroup
from kubeai_tpu.operator.rollout import RolloutController
from kubeai_tpu.routing.loadbalancer import (
    Group,
    LoadBalancer,
    LoadBalancerTimeout,
    NoHealthyEndpoints,
)
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.testing.chaos import (
    CONTINUOUS,
    EV_API_PARTITION,
    EV_API_STORM,
    EV_BAD_ROLLOUT,
    EV_CHIP_FLIP,
    EV_CLUSTER_HEAL,
    EV_CLUSTER_PARTITION,
    EV_DOOR_CRASH,
    EV_DOOR_PARTITION,
    EV_KILL_GROUP_HOST,
    EV_KILL_POD,
    EV_LINK_DROP,
    EV_SPOT_PREEMPT,
    EV_TELEMETRY_STALE,
    EV_TENANT_FLOOD,
    EV_WEDGE_ENGINE,
    TERMINAL,
    ApiServerError,
    ApiServerUnreachable,
    ChaosKubeStore,
    GameDayEvent,
    GameDayLog,
    GameDayTrace,
    Invariant,
    InvariantChecker,
)
from kubeai_tpu.testing.clock import FakeClock
from kubeai_tpu.testing.faults import ApiFault, ApiFaultPlan, Fault, FaultPlan
from kubeai_tpu.testing.simkit import (
    break_pod,
    mk_model,
    percentile,
    scrape_diff,
    seeded_rng,
)

ACCEL = "tpu-v5-lite-podslice"

TICK_S = 1.0
WARMUP_TICKS = 8           # steady state before the trace's t=0
BOOT_TICKS = 2             # created pod -> Ready
SLOTS = 4                  # concurrent streams per endpoint
TOKENS_PER_TICK = 10
STREAM_TOKENS = 20
PROMPT_TOKENS = 16
MAX_ATTEMPTS = 3           # proxy retry budget per dispatch
MAX_STREAM_RESUMES = 3     # mid-stream continuation budget per stream
WEDGE_TICKS = 4            # wedged engine -> watchdog kill
CONVERGE_BOUND_S = 40.0
DOOR_SHARDS = 3            # in-process door shards behind one gossip plane

MODELS = ("rt", "std", "batch")
MODEL_CLASS = {"rt": "realtime", "std": "standard", "batch": "batch"}

GOVERNOR_WINDOW_S = 20.0
MODEL_DISRUPTION_BUDGET = 2
CLUSTER_DISRUPTION_BUDGET = 3

DELETE_SERIES = "kubeai_governor_actions_total"


class Stream:
    """One admitted client request: queue wait, token delivery, and the
    resume discipline across endpoint deaths."""

    __slots__ = ("tenant", "model", "cls", "t_arrive", "t_first",
                 "delivered", "need", "addr", "done", "failed", "resumes",
                 "billed")

    def __init__(self, tenant: str, model: str, cls: str, t_arrive: float,
                 need: int = STREAM_TOKENS):
        self.tenant = tenant
        self.model = model
        self.cls = cls
        self.t_arrive = t_arrive
        self.t_first: float | None = None
        self.delivered = 0
        self.need = need
        self.addr: str | None = None
        self.done = None
        self.failed: set[str] = set()
        self.resumes = 0
        self.billed = 0  # completion tokens actually billed (ledger cross-check)


def _node(name: str, chips: int = 1, spot: bool = False) -> dict:
    labels = {
        "cloud.google.com/gke-tpu-accelerator": ACCEL,
        "cloud.google.com/gke-tpu-topology": "1x1",
    }
    if spot:
        labels["cloud.google.com/gke-spot"] = "true"
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels},
        "status": {"allocatable": {"google.com/tpu": str(chips)}},
    }


class GameDayWorld:
    """The composed fleet: real control plane over a chaos-wrapped
    store, real routing, real tenant door, simulated engines."""

    def __init__(self, trace: GameDayTrace, ticks: int, seed: int = 0,
                 stream_tokens: int = STREAM_TOKENS):
        self.trace = trace
        self.ticks = int(ticks)
        self.seed = int(seed)
        self.stream_tokens = int(stream_tokens)
        self.rng = seeded_rng(seed)
        self.clock = FakeClock(1000.0)
        self.wall = FakeClock(1_000_000.0)
        self.tick_no = 0
        self.t0 = self.clock() + WARMUP_TICKS * TICK_S  # trace's t=0

        # Retry-After jitter is the one rng the door reaches for outside
        # our seam; pin it so a replayed run is byte-identical.
        from kubeai_tpu.utils import retryafter
        retryafter._jitter = lambda: 1.0

        # -- stores: raw for the data/telemetry plane, chaos-wrapped for
        # the control plane (the wrapper IS the API server's front door).
        # Deterministic generateName suffixes: a zero-padded counter, so
        # pod names sort in creation order and a replay in any process
        # (any PYTHONHASHSEED, no uuid entropy) picks identical victims.
        self._name_counter = itertools.count()
        self.raw_store = KubeStore(
            namegen=lambda: f"{next(self._name_counter):06d}"
        )
        self.api_plan = ApiFaultPlan()
        self.api = ChaosKubeStore(self.raw_store, self.api_plan)
        self.metrics = Metrics()

        cfg = System()
        cfg.fixed_self_metric_addrs = ["self:1"]
        cfg.model_autoscaling.interval_seconds = 10.0
        cfg.model_autoscaling.time_window_seconds = 10.0
        cfg.default_and_validate()
        self.cfg = cfg

        # -- inventory: on-demand + spot single-chip v5e nodes.
        self.spot_nodes: list[str] = []
        for i in range(10):
            self.raw_store.create(_node(f"node-od-{i}"))
        for i in range(4):
            name = f"node-spot-{i}"
            self.spot_nodes.append(name)
            self.raw_store.create(_node(name, spot=True))

        # -- models: one per scheduling class, autoscaler-owned.
        from kubeai_tpu.crd.model import Scheduling
        common = dict(
            target_requests=4, scale_down_delay_seconds=0,
        )
        mk_model(self.raw_store, "rt", replicas=3, min_replicas=2,
                 max_replicas=4,
                 scheduling=Scheduling(default_priority="realtime"),
                 **common)
        mk_model(self.raw_store, "std", replicas=2, min_replicas=1,
                 max_replicas=4,
                 scheduling=Scheduling(default_priority="standard"),
                 **common)
        mk_model(self.raw_store, "batch", replicas=2, min_replicas=1,
                 max_replicas=3,
                 scheduling=Scheduling(default_priority="batch"),
                 **common)

        # -- routing: groups pre-seeded on the fake clock so breaker
        # open/half-open timing is simulated time, not wall time.
        self.lb = LoadBalancer(self.raw_store, metrics=self.metrics)
        for name in MODELS:
            self.lb._groups[name] = Group(
                metrics=self.metrics, model=name, clock=self.clock
            )

        self.mc_raw = ModelClient(self.raw_store)
        self.aggregator = FleetStateAggregator(
            lb=self.lb, model_client=self.mc_raw, store=self.raw_store,
            metrics=self.metrics, interval_s=1.0, staleness_s=2.5,
            fetch_metrics=self.fetch_metrics, fetch_state=self.fetch_state,
            clock=self.clock,
        )

        # -- control plane, all of it behind the chaos store.
        class AlwaysLeader:
            is_leader = True

        gcfg = GovernorConfig(
            window_seconds=GOVERNOR_WINDOW_S,
            model_disruption_budget=MODEL_DISRUPTION_BUDGET,
            cluster_disruption_budget=CLUSTER_DISRUPTION_BUDGET,
            min_telemetry_coverage=0.9,
        )
        self.governor = ActuationGovernor(
            cfg=gcfg, fleet=self.aggregator, store=self.api,
            metrics=self.metrics, clock=self.clock,
        )
        self.gcfg = gcfg
        self.mc = ModelClient(self.api)
        self.mc.governor = self.governor
        self.reconciler = ModelReconciler(
            self.api, cfg, metrics=self.metrics, clock=self.clock,
            wall=self.wall, governor=self.governor,
        )
        # Progressive-delivery plane: paces the pod plan for models
        # carrying a rollout: block (the bad_rollout chaos event opts a
        # model in mid-run) and rolls a judged-bad hash back.
        self.rollout = RolloutController(
            store=self.api, lb=self.lb, fleet=self.aggregator,
            governor=self.governor, metrics=self.metrics,
            clock=self.clock,
        )
        self.reconciler.rollout = self.rollout
        self.scaler = Autoscaler(
            self.api, cfg, self.mc, self.lb, AlwaysLeader(),
            metrics=self.metrics,
        )
        self.scaler.active_scraper = lambda addrs: self.active_totals()
        self.scaler.queue_scraper = lambda addrs: scrape_queue_pressure(
            addrs, fetch=self.fetch_metrics
        )
        self.scaler.role_scraper = lambda addrs: scrape_role_signals(
            addrs, fetch=self.fetch_metrics
        )
        self.scaler.fleet = self.aggregator
        self.planner = CapacityPlanner(
            fleet=self.aggregator, model_client=self.mc, store=self.api,
            cfg=cfg, metrics=self.metrics, interval_s=1.0, staleness_s=2.5,
            clock=self.clock,
        )
        self.planner.avg_lookup = self.scaler.current_average
        self.scaler.planner = self.planner

        # -- tenant door + billing. The door is SHARDED: three
        # in-process governors sharing one gossiped CRDT state plane,
        # so the game day exercises partition-tolerant admission (one
        # shared UsageMeter keeps billing_exact a single ledger).
        # Rate 3.0 with compliant tenants at <=2 req/s: a partitioned
        # door charges a conservative split, so a tenant at exactly
        # 100% of its limit is at the margin by construction — the
        # no-compliant-refusals guarantee needs utilization headroom.
        self.usage = UsageMeter(metrics=self.metrics)
        self.door_cfg = TenancyConfig(
            enabled=True,
            requests_per_second=3.0,
            request_burst=4.0,
            overload_high_water=10.0,
            overload_low_water=5.0,
            tenant_idle_seconds=1e9,
            door_shards=DOOR_SHARDS,
            gossip_interval_seconds=1.0,
            gossip_stale_seconds=3.0,
        )
        self.door = build_door(
            self.door_cfg, usage=self.usage, metrics=self.metrics,
            clock=self.clock, pressure_fn=self.queue_pressure,
            pressure_ttl_s=0.0, seed=seed,
        )

        # -- data plane state.
        self.queues: dict[str, deque] = {m: deque() for m in MODELS}
        self.active: list[Stream] = []
        self.completed: list[Stream] = []
        self.errored: list[Stream] = []
        self.client_errors = 0
        self.addr_model: dict[str, str] = {}
        self.dead: set[str] = set()
        self.wedged: dict[str, int] = {}     # addr -> watchdog-fires tick
        self.first_seen: dict[str, int] = {}
        self.ip_counter = 1
        self.arrival_counter = {m: 0 for m in MODELS}

        # -- chaos state.
        self.link_plan = FaultPlan(seed=seed)
        self.active_links: list[dict] = []   # {"addr","fault","until"}
        self.floods: list[dict] = []         # {"tenant","model","rps","until"}
        self.partition_until = float("-inf")
        self.door_partition_until = float("-inf")
        self.door_crashes = 0                # crashed-and-rebuilt shards
        self.flood_t0: dict[str, float] = {}      # tenant -> first flood t
        self.flood_admitted: dict[str, int] = {}  # tenant -> admissions
        self.stale_until = float("-inf")
        self.spot_removed: list[dict] = []   # removed Node objects (restorable)
        # model -> {"mode", "good": {pre-event pod hashes}} for the
        # bad_rollout event: new-hash pods of a wedged revision never
        # boot, so the rollout judge must condemn them.
        self.bad_rollout: dict[str, dict] = {}

        # -- measurement.
        self.log = GameDayLog(
            trace, ticks,
            extra={"seed": seed, "stream_tokens": self.stream_tokens},
        )
        self.checker = InvariantChecker(INVARIANTS, log=self.log)
        self.metric_history: deque = deque()  # (t, exposition_text)
        self.refusals: list[tuple] = []       # (tenant, model, cls, reason)
        self.wait_samples: dict = {}          # (tenant, model) -> [wait_s]
        self.plans: list[dict] = []
        self.last_plan: dict | None = None
        self.control_plane_errors = 0
        self.kinds_timeline: list[list[str]] = []
        self.last_unconverged_tick: int | None = None
        self.converged_final = False

    # ---- time ----------------------------------------------------------

    def rel_now(self) -> float:
        """Trace-relative time: 0.0 at the first post-warmup tick."""
        return self.clock() - self.t0

    # ---- scripted transport (engine telemetry) -------------------------

    def fetch_metrics(self, addr: str, timeout: float = 5.0) -> str:
        model = self.addr_model.get(addr)
        if model is None or addr in self.dead:
            raise ConnectionError(f"injected: {addr} unreachable")
        q = self.queues[model]
        ready = max(1, len(self._ready_addrs(model)))
        depth = len(q) / ready
        oldest = (self.clock() - q[0].t_arrive) if q else 0.0
        active = sum(1 for s in self.active if s.addr == addr)
        return "\n".join([
            'kubeai_engine_queue_depth{class="standard"} ' + f"{depth}",
            f"kubeai_engine_queue_oldest_wait_seconds {oldest}",
            "kubeai_engine_kv_cache_utilization 0.0",
            f"kubeai_engine_slots_active {float(active)}",
            f"kubeai_engine_slot_capacity {float(SLOTS)}",
            "kubeai_engine_ttft_seconds_sum 0.0",
            "kubeai_engine_ttft_seconds_count 0.0",
            f"kubeai_engine_active_requests {float(active)}",
        ]) + "\n"

    def fetch_state(self, addr: str, timeout: float = 5.0) -> dict:
        model = self.addr_model.get(addr)
        if model is None or addr in self.dead:
            raise ConnectionError(f"injected: {addr} unreachable")
        return {"model": model, "healthy": True}

    def active_totals(self) -> dict[str, float]:
        totals = {m: float(len(self.queues[m])) for m in MODELS}
        for s in self.active:
            totals[s.model] += 1.0
        return totals

    def queue_pressure(self) -> dict:
        depth = sum(len(q) for q in self.queues.values())
        oldest = 0.0
        now = self.clock()
        for q in self.queues.values():
            if q:
                oldest = max(oldest, now - q[0].t_arrive)
        return {"depth": float(depth), "oldest_wait_s": oldest}

    # ---- pod/addr bookkeeping ------------------------------------------

    def _pods(self, model: str) -> list[dict]:
        return sorted(
            self.raw_store.list("Pod", "default", {md.POD_MODEL_LABEL: model}),
            key=lambda p: p["metadata"]["name"],
        )

    def _addr_of(self, pod: dict) -> str | None:
        ip = pod.get("status", {}).get("podIP")
        return f"{ip}:8000" if ip else None

    def _is_ready(self, pod: dict) -> bool:
        st = pod.get("status", {})
        if st.get("phase") == "Failed":
            return False
        return any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in st.get("conditions", [])
        )

    def _ready_addrs(self, model: str) -> list[str]:
        out = []
        for pod in self._pods(model):
            addr = self._addr_of(pod)
            if addr and self._is_ready(pod) and addr not in self.dead:
                out.append(addr)
        return out

    def _kubelet(self) -> None:
        """Boot rendered pods: assign a podIP and flip Ready after
        BOOT_TICKS. Broken pods stay broken — repair is the
        reconciler's job."""
        for model in MODELS:
            for pod in self._pods(model):
                st = pod.get("status", {})
                if st.get("podIP"):
                    continue
                if st.get("reason") == "Preempted" or st.get(
                    "containerStatuses"
                ):
                    continue
                br = self.bad_rollout.get(model)
                if br and br["mode"] == "wedged":
                    h = pod["metadata"].get("labels", {}).get(
                        md.POD_HASH_LABEL
                    )
                    if h and h not in br["good"]:
                        continue  # the bad revision never comes up
                name = pod["metadata"]["name"]
                born = self.first_seen.setdefault(name, self.tick_no)
                if self.tick_no - born < BOOT_TICKS:
                    continue
                fresh = self.raw_store.get("Pod", "default", name)
                ip = f"10.77.0.{self.ip_counter}"
                self.ip_counter += 1
                status = fresh.setdefault("status", {})
                status["podIP"] = ip
                status["phase"] = "Running"
                status["conditions"] = [
                    {"type": "Ready", "status": "True"},
                    {"type": "PodScheduled", "status": "True"},
                ]
                self.raw_store.update(fresh)
                self.addr_model[f"{ip}:8000"] = model

    # ---- chaos event application ---------------------------------------

    def apply_event(self, ev: GameDayEvent) -> None:
        p = ev.params
        if ev.kind in (EV_KILL_POD, EV_SPOT_PREEMPT):
            mode = p.get("mode", "preempt")
            for _ in range(int(p.get("count", 1))):
                self._kill_one(ev.target, mode, p.get("victim", ""))
        elif ev.kind == EV_KILL_GROUP_HOST:
            self._kill_group_host(
                ev.target, int(p.get("group", 0)), int(p.get("host", 0)),
                p.get("mode", "preempt"),
            )
        elif ev.kind == EV_WEDGE_ENGINE:
            addr = None
            if p.get("victim") == "most_resumed":
                # Chase one stream across its resumes: freeze whichever
                # bound stream has died the most (first pick: the one
                # with the most work left, so it can't just finish).
                bound = sorted(
                    (s for s in self.active if s.model == ev.target
                     and s.addr is not None),
                    key=lambda s: (-s.resumes, s.delivered - s.need,
                                   s.t_arrive, s.tenant),
                )
                if bound:
                    addr = bound[0].addr
            if addr is None:
                addrs = self._ready_addrs(ev.target)
                if addrs:
                    addr = addrs[int(p.get("index", 0)) % len(addrs)]
            if addr is not None:
                self.wedged[addr] = self.tick_no + WEDGE_TICKS
        elif ev.kind == EV_API_PARTITION:
            self.api.partitioned = True
            self.partition_until = self.rel_now() + float(
                p.get("duration_s", 5.0)
            )
        elif ev.kind == EV_CLUSTER_PARTITION:
            # Cluster-level promotion of api_partition: in this
            # single-cluster world, losing the WHOLE cluster's control
            # plane is an API partition plus a split door gossip plane
            # (the data plane keeps serving — exactly the failure the
            # federation planner fails over on, seen from inside).
            until = self.rel_now() + float(p.get("duration_s", 5.0))
            self.api.partitioned = True
            self.partition_until = until
            ss = getattr(self.door, "shard_set", None)
            if ss is not None:
                ss.partition([[n] for n in ss.names()])
                self.door_partition_until = until
        elif ev.kind == EV_CLUSTER_HEAL:
            self.api.partitioned = False
            self.partition_until = float("inf")
            ss = getattr(self.door, "shard_set", None)
            if ss is not None:
                ss.heal()
                self.door_partition_until = float("inf")
        elif ev.kind == EV_API_STORM:
            key = (p.get("method", "GET"), p.get("plural", "pods"), False)
            cur = self.api_plan.counts[key]
            self.api_plan.faults.append(ApiFault(
                method=key[0], plural=key[1], watch=False, kind="http",
                status=int(p.get("status", 500)),
                start=cur + 1, end=cur + int(p.get("count", 3)),
            ))
        elif ev.kind == EV_TENANT_FLOOD:
            tenant = ev.target or "flooder"
            self.floods.append({
                "tenant": tenant,
                "model": p.get("model", "std"),
                "rps": int(p.get("rps", 20)),
                "until": self.rel_now() + float(p.get("duration_s", 10.0)),
            })
            self.flood_t0.setdefault(tenant, self.rel_now())
        elif ev.kind == EV_DOOR_PARTITION:
            ss = getattr(self.door, "shard_set", None)
            if ss is not None:
                names = ss.names()
                half = max(1, len(names) // 2)
                ss.partition([names[:half], names[half:]])
                self.door_partition_until = self.rel_now() + float(
                    p.get("duration_s", 5.0)
                )
        elif ev.kind == EV_DOOR_CRASH:
            ss = getattr(self.door, "shard_set", None)
            if ss is not None:
                idx = int(p.get("shard", 0)) % len(ss.names())
                name = ss.names()[idx]
                ss.crash(name)
                self.door.replace_shard(idx, TenantGovernor(
                    cfg=self.door_cfg, usage=self.usage,
                    metrics=self.metrics, clock=self.clock,
                    pressure_fn=self.queue_pressure, pressure_ttl_s=0.0,
                    gossip=ss.node(name),
                ))
                self.door_crashes += 1
        elif ev.kind == EV_CHIP_FLIP:
            delta = int(p.get("delta", 0))
            if delta < 0:
                for _ in range(-delta):
                    if not self.spot_nodes:
                        break
                    name = self.spot_nodes.pop()
                    node = self.raw_store.get("Node", "default", name)
                    self.raw_store.delete("Node", "default", name)
                    self.spot_removed.append(node)
            else:
                for _ in range(delta):
                    if not self.spot_removed:
                        break
                    node = self.spot_removed.pop()
                    node["metadata"].pop("resourceVersion", None)
                    node["metadata"].pop("uid", None)
                    self.raw_store.create(node)
                    self.spot_nodes.append(node["metadata"]["name"])
        elif ev.kind == EV_BAD_ROLLOUT:
            self._ship_bad_rollout(ev.target or "rt", p)
        elif ev.kind == EV_TELEMETRY_STALE:
            self.stale_until = self.rel_now() + float(
                p.get("duration_s", 5.0)
            )
        elif ev.kind == EV_LINK_DROP:
            if p.get("mode") == "sever":
                # Instant mid-stream link cut: the pod stays healthy,
                # the stream(s) over the link die and must resume.
                if p.get("victim") == "most_resumed":
                    # Surgical: cut ONE stream's connection — the one
                    # that has died the most (first pick: the one with
                    # the most work left, so it can't just finish).
                    bound = sorted(
                        (s for s in self.active if s.model == ev.target
                         and s.addr is not None),
                        key=lambda s: (-s.resumes, s.delivered - s.need,
                                       s.t_arrive, s.tenant),
                    )
                    if bound:
                        self._sever_one(bound[0])
                    return
                addrs = self._ready_addrs(ev.target)
                if addrs:
                    self._sever_streams(
                        addrs[int(p.get("index", 0)) % len(addrs)]
                    )
                return
            addrs = self._ready_addrs(ev.target)
            if addrs:
                addr = addrs[int(p.get("index", 0)) % len(addrs)]
                cur = self.link_plan.counts[addr]
                fault = Fault(addr, "connect_error", start=cur + 1, end=None)
                self.link_plan.faults.append(fault)
                self.active_links.append({
                    "addr": addr, "fault": fault,
                    "until": self.rel_now() + float(p.get("duration_s", 3.0)),
                })

    def _ship_bad_rollout(self, model: str, p: dict) -> None:
        """An operator ships a bad spec revision: opt the model into a
        canary rollout and stamp a spec marker that changes the rendered
        pod hash. Mode "wedged" (default) keeps every new-hash pod from
        ever booting, so the judge's crashloop verdict must pin the old
        hash back — with zero client-visible impact meanwhile."""
        self.bad_rollout[model] = {
            "mode": p.get("mode", "wedged"),
            "good": {
                pod["metadata"].get("labels", {}).get(md.POD_HASH_LABEL)
                for pod in self._pods(model)
            },
        }
        obj = self.raw_store.get("Model", "default", model)
        spec = obj["spec"]
        spec["rollout"] = {
            "strategy": "canary",
            "canaryPercent": float(p.get("canary_percent", 40.0)),
            "stepSeconds": float(p.get("step_seconds", 4.0)),
            "judge": {"windowSeconds": float(p.get("window_s", 3.0))},
        }
        env = dict(spec.get("env") or {})
        env["BAD_ROLLOUT_REV"] = str(p.get("revision", 1))
        spec["env"] = env
        self.raw_store.update(obj)

    def _kill_one(self, model: str, mode: str, victim: str) -> None:
        pods = [p for p in self._pods(model) if self._is_ready(p)]
        if not pods:
            return
        pod = pods[0]
        if victim == "oldest_stream":
            bound = sorted(
                (s for s in self.active if s.model == model
                 and s.addr is not None),
                key=lambda s: (s.t_arrive, s.tenant),
            )
            if bound:
                target = bound[0].addr
                for p in pods:
                    if self._addr_of(p) == target:
                        pod = p
                        break
        break_pod(self.raw_store, pod, mode)
        addr = self._addr_of(pod)
        if addr:
            self._addr_died(addr)

    def _kill_group_host(self, model: str, group: int, host: int,
                         mode: str) -> None:
        """Break ONE member pod of a multi-host slice group. The whole
        group must stop being routable — that is the invariant the
        slice-group plane owes the fleet."""
        for pod in self._pods(model):
            if (slicegroup.group_index(pod) == group
                    and slicegroup.host_index(pod) == host):
                break_pod(self.raw_store, pod, mode)
                addr = self._addr_of(pod)
                if addr:
                    self._addr_died(addr)
                return

    def _addr_died(self, addr: str) -> None:
        """An endpoint is gone mid-flight: feed the breaker, resume or
        fail each bound stream per the proxy's continuation discipline."""
        self.dead.add(addr)
        self.wedged.pop(addr, None)
        self._sever_streams(addr)

    def _sever_streams(self, addr: str) -> None:
        """Cut every stream bound over `addr` (endpoint death or a
        mid-stream link cut — the pod itself may be fine)."""
        for s in [s for s in self.active if s.addr == addr]:
            self._sever_one(s)

    def _sever_one(self, s: Stream) -> None:
        """One stream's connection dies mid-flight: feed the breaker,
        then resume from the delivered position — or surface the error
        once the continuation budget is spent."""
        self.active.remove(s)
        if s.done is not None:
            s.done(outcome="midstream", error="stream connection died")
        s.failed.add(s.addr)
        s.addr = None
        s.done = None
        s.resumes += 1
        if s.resumes > MAX_STREAM_RESUMES:
            self.client_errors += 1
            self.errored.append(s)
        else:
            self.queues[s.model].appendleft(s)

    def _expire_timed_chaos(self) -> None:
        rel = self.rel_now()
        if self.api.partitioned and rel >= self.partition_until:
            self.api.partitioned = False
        ss = getattr(self.door, "shard_set", None)
        if (ss is not None and ss.partitioned()
                and rel >= self.door_partition_until):
            ss.heal()
        self.floods = [f for f in self.floods if rel < f["until"]]
        still = []
        for link in self.active_links:
            if rel >= link["until"]:
                # Seal the fault at the current attempt count: the link
                # is back, later attempts must pass.
                link["fault"].end = self.link_plan.counts[link["addr"]]
            else:
                still.append(link)
        self.active_links = still
        for addr, fires_at in list(self.wedged.items()):
            if self.tick_no >= fires_at:
                # Watchdog: a wedged engine is killed and replaced.
                for pod in self._pods(self.addr_model.get(addr, "")):
                    if self._addr_of(pod) == addr:
                        break_pod(self.raw_store, pod, "crashloop")
                        break
                self._addr_died(addr)

    # ---- data plane ----------------------------------------------------

    def _arrivals(self) -> None:
        now = self.clock()
        plan = [("user-rt", "rt", 2), ("user-std", "std", 1)]
        if self.tick_no % 2 == 0:
            plan.append(("user-batch", "batch", 1))
        rel = self.rel_now()
        for f in self.floods:
            if rel < f["until"]:
                plan.append((f["tenant"], f["model"], f["rps"]))
        for tenant, model, count in plan:
            cls = MODEL_CLASS[model]
            for _ in range(count):
                self.arrival_counter[model] += 1
                refusal = self.door.admit(
                    tenant, model, priority=cls,
                    est_tokens=PROMPT_TOKENS + self.stream_tokens,
                )
                if refusal is not None:
                    self.refusals.append(
                        (tenant, model, cls, refusal.reason)
                    )
                    continue
                if tenant in self.flood_t0:
                    self.flood_admitted[tenant] = (
                        self.flood_admitted.get(tenant, 0) + 1
                    )
                self.queues[model].append(
                    Stream(tenant, model, cls, now,
                           need=self.stream_tokens)
                )

    def _dispatch(self) -> None:
        for model in MODELS:
            group = self.lb.group(model)
            q = self.queues[model]
            guard = len(q)
            while q and guard > 0:
                guard -= 1
                s = q[0]
                bound = False
                slot_full: set[str] = set()
                for _ in range(MAX_ATTEMPTS):
                    try:
                        addr, done = group.get_best_addr(
                            "LeastLoad", "", "", timeout=0.02,
                            exclude=s.failed | slot_full,
                        )
                    except (NoHealthyEndpoints, LoadBalancerTimeout):
                        break
                    if addr in self.dead:
                        done(outcome="connect_error",
                             error="endpoint dead")
                        s.failed.add(addr)
                        continue
                    if sum(
                        1 for a in self.active if a.addr == addr
                    ) >= SLOTS:
                        # Engine at slot capacity isn't a fault — skip
                        # it for this pick, stop once every endpoint is
                        # full.
                        done()
                        if addr in slot_full:
                            break
                        slot_full.add(addr)
                        continue
                    if self.active_links and any(
                        link["addr"] == addr for link in self.active_links
                    ):
                        fault = self.link_plan.on_attempt(addr)
                        if fault is not None:
                            done(outcome="connect_error",
                                 error="link dropped")
                            s.failed.add(addr)
                            continue
                    s.addr = addr
                    s.done = done
                    bound = True
                    break
                if not bound:
                    # Nothing reachable for this stream right now: it
                    # stays queued; retries start fresh next tick (the
                    # exclude set only spans one dispatch cycle, like
                    # the proxy's).
                    s.failed.clear()
                    break
                q.popleft()
                self.active.append(s)

    def _serve(self) -> None:
        now = self.clock()
        finished = []
        for s in self.active:
            if s.addr in self.wedged:
                continue  # wedged engine: no tokens this tick
            if s.t_first is None:
                s.t_first = now
                self.wait_samples.setdefault(
                    (s.tenant, s.model), []
                ).append(now - s.t_arrive)
            s.delivered += TOKENS_PER_TICK
            if s.delivered >= s.need:
                finished.append(s)
        for s in finished:
            self.active.remove(s)
            s.done(outcome="success")
            s.done = None
            s.addr = None
            s.billed = s.need
            self.usage.record(
                s.tenant, s.model,
                prompt_tokens=PROMPT_TOKENS, completion_tokens=s.need,
                stream_seconds=now - s.t_arrive,
            )
            self.completed.append(s)

    # ---- control plane -------------------------------------------------

    def _control_plane(self) -> None:
        rel = self.rel_now()
        if rel >= self.stale_until:
            try:
                self.aggregator.collect()
            except Exception:
                self.control_plane_errors += 1
        for step in (self.scaler.tick, self._planner_tick,
                     self.rollout.tick):
            try:
                step()
            except (ApiServerUnreachable, ApiServerError):
                self.control_plane_errors += 1
        for model in MODELS:
            try:
                self.reconciler.reconcile("default", model)
            except (ApiServerUnreachable, ApiServerError):
                self.control_plane_errors += 1

    def _planner_tick(self) -> None:
        plan = self.planner.tick()
        if plan is not None:
            self.last_plan = plan
            self.plans.append(plan)

    # ---- convergence + observability -----------------------------------

    def active_chaos_kinds(self) -> list[str]:
        kinds = set()
        rel = self.rel_now()
        if self.api.partitioned:
            kinds.add("api_partition")
        if self.floods:
            kinds.add("tenant_flood")
        if self.active_links:
            kinds.add("link_drop")
        if self.wedged:
            kinds.add("wedge")
        if rel < self.stale_until:
            kinds.add("telemetry_stale")
        ss = getattr(self.door, "shard_set", None)
        if ss is not None and ss.partitioned():
            kinds.add("door_partition")
        if self.spot_removed:
            kinds.add("chip_flip")
        for model, br in self.bad_rollout.items():
            if any(
                pod["metadata"].get("labels", {}).get(md.POD_HASH_LABEL)
                not in br["good"]
                for pod in self._pods(model)
            ):
                kinds.add("bad_rollout")
                break
        for model in MODELS:
            spec = self.raw_store.get("Model", "default", model)["spec"]
            if len(self._ready_addrs(model)) < int(
                spec.get("replicas") or 0
            ):
                kinds.add("dead_pod")
                break
        return sorted(kinds)

    def is_converged(self) -> bool:
        if self.wedged or self.dead & set(
            a for m in MODELS for a in self._ready_addrs(m)
        ):
            return False
        now = self.clock()
        for model in MODELS:
            spec = self.raw_store.get("Model", "default", model)["spec"]
            want = int(spec.get("replicas") or 0)
            if len(self._ready_addrs(model)) != want:
                return False
            q = self.queues[model]
            if q and now - q[0].t_arrive > 3 * TICK_S:
                return False
        return not self.door.overload

    # ---- the tick ------------------------------------------------------

    def tick(self) -> None:
        self.tick_no += 1
        self.clock.advance(TICK_S)
        self.wall.advance(TICK_S)
        rel = self.rel_now()

        for ev in self.trace.due(rel):
            self.apply_event(ev)
            self.log.event(self.tick_no, ev)
        self._expire_timed_chaos()
        self._kubelet()
        self.lb.sync_all()
        self._arrivals()
        self._dispatch()
        self._serve()
        self._control_plane()

        self.metric_history.append(
            (self.clock(), self.metrics.registry.expose())
        )
        while (
            len(self.metric_history) > 2
            and self.metric_history[1][0]
            <= self.clock() - self.gcfg.window_seconds
        ):
            self.metric_history.popleft()

        kinds = self.active_chaos_kinds()
        self.kinds_timeline.append(kinds)
        self.log.obs(
            self.tick_no,
            t=round(rel, 3),
            chaos=kinds,
            queues={m: len(self.queues[m]) for m in MODELS},
            ready={m: len(self._ready_addrs(m)) for m in MODELS},
            active=len(self.active),
            errors=self.client_errors,
        )
        self.checker.check_continuous(self, self.tick_no, rel)
        if rel > self.trace.last_event_t and not self.is_converged():
            self.last_unconverged_tick = self.tick_no

    def run(self) -> dict:
        for _ in range(WARMUP_TICKS + self.ticks):
            self.tick()
        self.converged_final = self.is_converged()
        self.checker.check_terminal(self, self.tick_no, self.rel_now())
        return self.result()

    def result(self) -> dict:
        max_kinds = max((len(k) for k in self.kinds_timeline), default=0)
        at_max = next(
            (k for k in self.kinds_timeline if len(k) == max_kinds), []
        )
        fv = self.checker.first_violation
        return {
            "seed": self.seed,
            "ticks": self.ticks,
            "trace_events": len(self.trace.events),
            "last_event_t": self.trace.last_event_t,
            "client_errors": self.client_errors,
            "completed": len(self.completed),
            "arrivals": dict(self.arrival_counter),
            "refusals": list(self.refusals),
            "violations": [
                {"tick": v.tick, "t": v.t, "invariant": v.invariant,
                 "detail": v.detail}
                for v in self.checker.violations
            ],
            "first_violation": None if fv is None else {
                "tick": fv.tick, "t": fv.t, "invariant": fv.invariant,
                "detail": fv.detail,
            },
            "max_concurrent_kinds": max_kinds,
            "concurrent_kinds_at_max": at_max,
            "kinds_timeline": self.kinds_timeline,
            "converged_final": self.converged_final,
            "last_unconverged_tick": self.last_unconverged_tick,
            "converge_bound_s": CONVERGE_BOUND_S,
            "control_plane_errors": self.control_plane_errors,
            "plans_seen": len(self.plans),
            "usage_totals": self.usage.totals(),
            "flood_admitted": dict(self.flood_admitted),
            "door_shards": DOOR_SHARDS,
            "door_crashes": self.door_crashes,
            "wait_samples": {
                f"{t}/{m}": v for (t, m), v in self.wait_samples.items()
            },
            "log": self.log,
        }


# ---- invariants --------------------------------------------------------------


def _inv_zero_stream_errors(world) -> str | None:
    if world.client_errors:
        return (
            f"{world.client_errors} stream(s) exhausted the "
            f"{MAX_STREAM_RESUMES}-resume budget and surfaced to clients"
        )
    return None


def _inv_disruption_budget(world) -> str | None:
    """Budgeted deletions per sliding window, measured from SCRAPES —
    the governor is audited from the outside, not trusted."""
    hist = world.metric_history
    if len(hist) < 2:
        return None
    now = world.clock()
    base = None
    for t, text in hist:
        if t > now - world.gcfg.window_seconds:
            base = text
            break
    if base is None:
        return None
    per_model: dict[str, float] = {}
    for (name, labels), delta in scrape_diff(base, hist[-1][1]).items():
        if name != DELETE_SERIES:
            continue
        lab = dict(labels)
        if lab.get("action") != "delete":
            continue
        per_model[lab.get("model", "?")] = (
            per_model.get(lab.get("model", "?"), 0.0) + delta
        )
    for model, n in per_model.items():
        if n > MODEL_DISRUPTION_BUDGET + 1e-9:
            return (
                f"model {model}: {n:.0f} budgeted deletions in one "
                f"{world.gcfg.window_seconds:.0f}s window "
                f"(budget {MODEL_DISRUPTION_BUDGET})"
            )
    total = sum(per_model.values())
    if total > CLUSTER_DISRUPTION_BUDGET + 1e-9:
        return (
            f"cluster: {total:.0f} budgeted deletions in one window "
            f"(budget {CLUSTER_DISRUPTION_BUDGET})"
        )
    return None


def _inv_realtime_never_shed(world) -> str | None:
    for tenant, model, cls, reason in world.refusals:
        if cls == "realtime" and reason == "overload":
            return (
                f"realtime request ({tenant}/{model}) door-shed under "
                "overload — realtime must never be shed"
            )
    return None


def _inv_chip_budget(world) -> str | None:
    plan = world.last_plan
    if plan is None:
        return None
    if plan["allocated_chips"]["total"] > plan["budget"]["total"]:
        return (
            f"plan allocates {plan['allocated_chips']['total']} chips "
            f"with only {plan['budget']['total']} in inventory"
        )
    for shape, used in plan["allocated_chips"]["by_shape"].items():
        if used > plan["budget"]["by_shape"].get(shape, 0):
            return f"shape {shape} over-allocated: {used}"
    return None


def _inv_billing_exact(world) -> str | None:
    totals = world.usage.totals()
    want_completion = sum(s.billed for s in world.completed)
    want_prompt = PROMPT_TOKENS * len(world.completed)
    got_completion = int(totals.get("completion_tokens", 0))
    got_prompt = int(totals.get("prompt_tokens", 0))
    if (got_completion, got_prompt) != (want_completion, want_prompt):
        return (
            f"ledger says {got_prompt}+{got_completion} tokens, "
            f"delivered work is {want_prompt}+{want_completion} — "
            "billing drifted across resumes"
        )
    if int(totals.get("requests", 0)) != len(world.completed):
        return (
            f"ledger counts {totals.get('requests')} requests, "
            f"{len(world.completed)} streams completed"
        )
    return None


def door_budget_epsilon(world) -> float:
    """Admission slack the sharded door is ALLOWED over the single
    global budget: un-gossiped burst on N-1 peers, one gossip interval
    of rate on every shard, the degraded window's conservative-split
    residue on N-1 peers, and a fresh burst per crashed-and-rebuilt
    shard (the rebuilt bucket starts full)."""
    cfg = world.door_cfg
    n = DOOR_SHARDS
    return (
        (n - 1) * cfg.request_burst
        + n * cfg.requests_per_second * cfg.gossip_interval_seconds
        + (n - 1) * cfg.requests_per_second * cfg.gossip_stale_seconds
        + world.door_crashes * cfg.request_burst
        + 2.0
    )


def _inv_door_budget(world) -> str | None:
    """The flooder is held to ONE global token budget no matter how
    the door shards are split: cumulative admissions for any flooding
    tenant never exceed burst + rate*elapsed + epsilon — continuously,
    including mid-partition and mid-crash."""
    rel = world.rel_now()
    eps = door_budget_epsilon(world)
    cfg = world.door_cfg
    for tenant, t0 in world.flood_t0.items():
        elapsed = max(0.0, rel - t0)
        budget = cfg.request_burst + cfg.requests_per_second * elapsed
        got = world.flood_admitted.get(tenant, 0)
        if got > budget + eps:
            return (
                f"flood tenant {tenant}: {got} admissions in "
                f"{elapsed:.0f}s — global budget {budget:.0f} "
                f"(+{eps:.0f} epsilon) breached across "
                f"{DOOR_SHARDS} door shards"
            )
    return None


def _inv_token_continuity(world) -> str | None:
    for s in world.completed:
        if s.delivered != s.need:
            return (
                f"stream for {s.tenant}/{s.model} delivered "
                f"{s.delivered}/{s.need} tokens after {s.resumes} "
                "resume(s) — gap or duplication"
            )
    return None


def _inv_group_dead_member_not_routable(world) -> str | None:
    """A slice group with ANY broken member must not be routable: its
    coordinator address may never appear among the LB endpoints.
    Vacuous when the fleet has no group-labelled pods."""
    for model in MODELS:
        by_group: dict[int, list[dict]] = {}
        for pod in world._pods(model):
            g = slicegroup.group_index(pod)
            if g is not None:
                by_group.setdefault(g, []).append(pod)
        if not by_group:
            continue
        routable = set(world.lb.group(model).addresses())
        for g, members in sorted(by_group.items()):
            if slicegroup.expected_size(members) <= 1:
                continue
            if not any(slicegroup.member_broken(p) for p in members):
                continue
            coord = slicegroup.coordinator_pod(members)
            addr = world._addr_of(coord) if coord else None
            if addr and addr in routable:
                return (
                    f"group {model}/g{g} has a broken member but its "
                    f"coordinator {addr} is still routable"
                )
    return None


def _inv_convergence(world) -> str | None:
    if not world.converged_final:
        return (
            "fleet did not return to steady state by the end of the run "
            f"(queues={ {m: len(world.queues[m]) for m in MODELS} }, "
            f"wedged={sorted(world.wedged)}, "
            f"overload={world.door.overload})"
        )
    last = world.last_unconverged_tick
    if last is not None:
        settle = (last + 1 - WARMUP_TICKS) * TICK_S - world.trace.last_event_t
        if settle > CONVERGE_BOUND_S:
            return (
                f"converged {settle:.0f}s after the last chaos event "
                f"(bound {CONVERGE_BOUND_S:.0f}s)"
            )
    return None


INVARIANTS = (
    Invariant("zero_stream_errors", _inv_zero_stream_errors, CONTINUOUS,
              "no client ever sees a broken stream"),
    Invariant("disruption_budget", _inv_disruption_budget, CONTINUOUS,
              "budgeted deletions per window within model+cluster budgets"),
    Invariant("realtime_never_shed", _inv_realtime_never_shed, CONTINUOUS,
              "the door never sheds realtime traffic"),
    Invariant("chip_budget", _inv_chip_budget, CONTINUOUS,
              "the plan never allocates more chips than the inventory"),
    Invariant("billing_exact", _inv_billing_exact, CONTINUOUS,
              "the usage ledger equals delivered work exactly"),
    Invariant("token_continuity", _inv_token_continuity, CONTINUOUS,
              "resumed streams deliver every token exactly once"),
    Invariant("door_budget", _inv_door_budget, CONTINUOUS,
              "flooder admissions across all door shards within one "
              "global budget + epsilon"),
    Invariant("group_dead_member_not_routable",
              _inv_group_dead_member_not_routable, CONTINUOUS,
              "a slice group with a dead member is never routable"),
    Invariant("convergence", _inv_convergence, TERMINAL,
              "healthy steady state within CONVERGE_BOUND_S of last chaos"),
)


# ---- traces ------------------------------------------------------------------


def fast_trace(seed: int = 0) -> GameDayTrace:
    """The tier-1 game day: all four headline chaos kinds overlap around
    t=12-13 (flood + partition + spot flip + dead pod), with wedge,
    storm, staleness and a link drop layered on."""
    return GameDayTrace([
        GameDayEvent(5.0, EV_TENANT_FLOOD, "flooder",
                     {"model": "std", "rps": 30, "duration_s": 20.0}),
        GameDayEvent(7.0, EV_DOOR_PARTITION, "",
                     {"duration_s": 10.0}),
        GameDayEvent(8.0, EV_CHIP_FLIP, "",
                     {"delta": -4, "duration_s": 18.0}),
        GameDayEvent(8.0, EV_SPOT_PREEMPT, "batch", {"count": 1}),
        GameDayEvent(10.0, EV_API_PARTITION, "", {"duration_s": 8.0}),
        GameDayEvent(12.0, EV_KILL_POD, "rt",
                     {"count": 1, "mode": "preempt"}),
        GameDayEvent(14.0, EV_WEDGE_ENGINE, "std", {}),
        GameDayEvent(16.0, EV_TELEMETRY_STALE, "", {"duration_s": 6.0}),
        GameDayEvent(18.0, EV_LINK_DROP, "rt",
                     {"index": 0, "duration_s": 5.0}),
        GameDayEvent(20.0, EV_API_STORM, "",
                     {"method": "GET", "plural": "pods", "status": 500,
                      "count": 3}),
        GameDayEvent(22.0, EV_DOOR_CRASH, "", {"shard": 1}),
        GameDayEvent(26.0, EV_CHIP_FLIP, "", {"delta": 4}),
    ], seed=seed)


def extended_trace(seed: int = 0) -> GameDayTrace:
    """Two full chaos rounds back to back, capped by a cluster-level
    partition wave (api_partition promoted to the whole cluster: API
    dark AND the door gossip plane split at once) and a bad-rollout
    wave (a wedged spec revision ships through the progressive-delivery
    plane and must be rolled back) — the slow-tier soak."""
    base = fast_trace(seed).events
    second = [
        GameDayEvent(ev.t + 45.0, ev.kind, ev.target, dict(ev.params))
        for ev in base
    ]
    wave = [
        GameDayEvent(95.0, EV_CLUSTER_PARTITION, "",
                     {"duration_s": 30.0}),
        GameDayEvent(101.0, EV_CLUSTER_HEAL, "", {}),
        GameDayEvent(106.0, EV_BAD_ROLLOUT, "rt", {"mode": "wedged"}),
    ]
    return GameDayTrace(list(base) + second + wave, seed=seed)


def failing_trace(seed: int = 0) -> GameDayTrace:
    """A trace engineered to violate zero_stream_errors: every tick,
    the link under the MOST-RESUMED bound stream is severed (the pod
    stays healthy, so there's always somewhere to resume to — and the
    cut chases the stream wherever it lands). Run with
    stream_tokens=FAILING_STREAM_TOKENS so delivery can't outrun the
    cuts: the victim burns all MAX_STREAM_RESUMES continuations.
    Exists to prove the dump->replay loop lands on the same first
    violation."""
    events = list(fast_trace(seed).events)
    for i in range(6):
        events.append(GameDayEvent(
            30.0 + i, EV_LINK_DROP, "rt",
            {"mode": "sever", "victim": "most_resumed"},
        ))
    return GameDayTrace(events, seed=seed)


TRACES = {
    "fast": fast_trace,
    "extended": extended_trace,
    "failing": failing_trace,
}

DEFAULT_TICKS = {"fast": 70, "extended": 140, "failing": 70}


FAILING_STREAM_TOKENS = 50  # long enough that per-tick kills outpace delivery


def run_gameday(trace: GameDayTrace, ticks: int, seed: int = 0,
                stream_tokens: int = STREAM_TOKENS) -> dict:
    return GameDayWorld(
        trace, ticks, seed=seed, stream_tokens=stream_tokens
    ).run()


def run_sim(ticks: int = DEFAULT_TICKS["fast"], seed: int = 0) -> dict:
    """The tier-1 entry point: the full game day, the same day minus
    the flood (tenant-isolation baseline), and the engineered failure
    (replay fodder)."""
    gameday = run_gameday(fast_trace(seed), ticks, seed)
    baseline = run_gameday(
        fast_trace(seed).without(EV_TENANT_FLOOD), ticks, seed
    )
    failing = run_gameday(
        failing_trace(seed), ticks, seed,
        stream_tokens=FAILING_STREAM_TOKENS,
    )
    return {
        "ticks": ticks,
        "seed": seed,
        "gameday": gameday,
        "baseline": baseline,
        "failing": failing,
    }


# ---- result-level checks (imported by tests/unit/test_gameday.py) -----------


def check_chaos_concurrency(result: dict) -> None:
    """The headline composition really happened: flood + partition +
    chip flip + dead pod active on one tick."""
    g = result["gameday"]
    need = {"tenant_flood", "api_partition", "chip_flip", "dead_pod"}
    assert any(
        need <= set(kinds) for kinds in g["kinds_timeline"]
    ), f"never saw {need} concurrently; max was {g['concurrent_kinds_at_max']}"


def check_no_violations(result: dict) -> None:
    """The full game day holds every invariant, continuous AND
    terminal."""
    g = result["gameday"]
    assert g["violations"] == [], g["violations"]
    assert g["client_errors"] == 0
    assert g["converged_final"], "fleet did not converge"


def check_progress_under_chaos(result: dict) -> None:
    """Chaos must not deadlock the data plane: most admitted work
    completes, and every class completes some."""
    g = result["gameday"]
    assert g["completed"] > 0
    done_models = {s for k in g["wait_samples"] for s in [k.split("/")[1]]}
    assert done_models == set(MODELS), (
        f"classes that completed work: {sorted(done_models)}"
    )


def check_tenant_isolation(result: dict) -> None:
    """The flooding tenant cannot move a compliant tenant's p99 TTFT
    wait: full game day vs the identical day without the flood."""
    g, b = result["gameday"], result["baseline"]
    for key in ("user-rt/rt",):
        flooded = percentile(g["wait_samples"].get(key, []), 0.99)
        calm = percentile(b["wait_samples"].get(key, []), 0.99)
        assert flooded <= calm + 1.0 * TICK_S, (
            f"{key}: p99 wait {flooded:.2f}s with flood vs {calm:.2f}s "
            "without — isolation broken"
        )
    key = "user-std/std"
    flooded = percentile(g["wait_samples"].get(key, []), 0.99)
    calm = percentile(b["wait_samples"].get(key, []), 0.99)
    assert flooded <= calm + 4.0 * TICK_S, (
        f"{key}: p99 wait {flooded:.2f}s with flood vs {calm:.2f}s "
        "without — isolation broken"
    )


def check_flood_was_real(result: dict) -> None:
    """The abusive tenant was actually refused at the door (rate), and
    compliant realtime was never refused at all."""
    g = result["gameday"]
    flood_refusals = [r for r in g["refusals"] if r[0] == "flooder"]
    assert len(flood_refusals) > 100, len(flood_refusals)
    rt_refusals = [r for r in g["refusals"] if r[0] == "user-rt"]
    assert rt_refusals == [], rt_refusals


def check_door_chaos_was_real(result: dict) -> None:
    """The door shards really were split mid-flood (door_partition in
    the chaos timeline), a shard really crashed and was rebuilt, and
    the flooder still only ever got ONE global budget."""
    g = result["gameday"]
    assert any(
        "door_partition" in kinds for kinds in g["kinds_timeline"]
    ), "door_partition never active"
    assert any(
        {"door_partition", "tenant_flood"} <= set(kinds)
        for kinds in g["kinds_timeline"]
    ), "flood and door partition never overlapped"
    assert g["door_crashes"] == 1, g["door_crashes"]
    assert g["flood_admitted"].get("flooder", 0) > 0, (
        "flooder was never admitted at all — budget check is vacuous"
    )


def check_failing_trace_fails(result: dict) -> None:
    """The engineered trace produces a deterministic first violation of
    zero_stream_errors."""
    f = result["failing"]
    assert f["first_violation"] is not None
    assert f["first_violation"]["invariant"] == "zero_stream_errors"


ALL_CHECKS = (
    check_chaos_concurrency,
    check_no_violations,
    check_progress_under_chaos,
    check_tenant_isolation,
    check_flood_was_real,
    check_door_chaos_was_real,
    check_failing_trace_fails,
)


# ---- replay ------------------------------------------------------------------


def replay(path: str) -> tuple[dict, dict]:
    """Re-run a dumped game day byte-identically: rebuild the trace from
    the dump's header and drive a fresh world with the same seed and
    tick count. Returns (header, fresh result)."""
    header, _records = GameDayLog.load(path)
    trace = GameDayTrace(
        [GameDayEvent.from_dict(d) for d in header["events"]],
        seed=int(header["seed"]),
    )
    result = run_gameday(
        trace, int(header["ticks"]), seed=int(header["seed"]),
        stream_tokens=int(header.get("stream_tokens", STREAM_TOKENS)),
    )
    return header, result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", choices=sorted(TRACES), default="fast")
    ap.add_argument("--ticks", type=int, default=0,
                    help="simulated ticks after warmup (default: per trace)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dump", help="write the JSONL event log here")
    ap.add_argument("--replay", metavar="DUMP",
                    help="re-run a dumped game day and compare")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.replay:
        with open(args.replay) as fh:
            original = [line.rstrip("\n") for line in fh if line.strip()]
        # Flight-recorder incident bundles share the GameDayLog format
        # but replay through the sim named in their header, not the
        # game-day trace machinery.
        header = json.loads(original[0])
        if header.get("bundle") == "incident":
            if header.get("sim") == "rollout_sim":
                from benchmarks import rollout_sim

                return rollout_sim.replay_main(args.replay)
            from benchmarks import slo_incident_sim

            return slo_incident_sim.replay_main(args.replay)
        header, result = replay(args.replay)
        fresh = result["log"].lines
        identical = fresh == original
        fv = result["first_violation"]
        print(f"replayed {args.replay}: {len(original)} log lines")
        print(f"byte-identical: {identical}")
        print(f"first violation: {fv}")
        return 0 if identical else 1

    trace = TRACES[args.trace](args.seed)
    ticks = args.ticks or DEFAULT_TICKS[args.trace]
    stream_tokens = (
        FAILING_STREAM_TOKENS if args.trace == "failing" else STREAM_TOKENS
    )
    result = run_gameday(
        trace, ticks, seed=args.seed, stream_tokens=stream_tokens
    )
    if args.dump:
        result["log"].dump(args.dump)
        print(f"log -> {args.dump}")

    if args.json:
        slim = {k: v for k, v in result.items()
                if k not in ("log", "kinds_timeline", "wait_samples")}
        print(json.dumps(slim, indent=2, default=str))
        return 0

    print(f"game day [{args.trace}]: seed={args.seed} ticks={ticks} "
          f"events={result['trace_events']}")
    print(f"  completed={result['completed']} "
          f"client_errors={result['client_errors']} "
          f"refusals={len(result['refusals'])}")
    print(f"  max concurrent chaos kinds: {result['max_concurrent_kinds']} "
          f"{result['concurrent_kinds_at_max']}")
    print(f"  control-plane errors absorbed: "
          f"{result['control_plane_errors']}")
    print(f"  converged: {result['converged_final']}")
    if result["violations"]:
        print(f"  VIOLATIONS ({len(result['violations'])}):")
        for v in result["violations"][:10]:
            print(f"    tick {v['tick']} [{v['invariant']}] {v['detail']}")
    else:
        print("  all invariants held")
    return 0 if not result["violations"] or args.trace == "failing" else 1


if __name__ == "__main__":
    sys.exit(main())
