"""Deterministic preemption-chaos simulation — no JAX, no sockets.

Spot/preemptible TPU slices make replica loss the steady state. This sim
drives the three layers that make replica death invisible through their
failure schedules on a fake clock and reports the invariants the
preemption-tolerance work promises:

  * stream resume: with >= 2 replicas and single-replica preemption,
    ZERO client-visible stream errors — every mid-stream death resumes
    on another endpoint (proxy discipline: breaker exclude-set, bounded
    resume count) and the delivered token sequence has no gap and no
    duplicate;
  * self-healing: every preempted / crash-looping pod is delete-and-
    replaced by the REAL `ModelReconciler` pod-health pass within the
    repair-backoff bound (fake monotonic + wall clocks injected);
  * watchdog wins the race: a wedged-but-accepting engine is ejected
    from the LB via the step watchdog (flip /health → kubelet restart →
    pod replacement) strictly before the proxy's circuit breaker could
    even theoretically open on response-header timeouts.

`tests/unit/test_preemption.py::test_preemption_simulation_invariants`
asserts these on a small configuration in tier-1. Run directly for the
full-size report:

    python benchmarks/preemption_sim.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.config import System
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.operator.controller import ModelReconciler
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.health import BreakerPolicy
from kubeai_tpu.routing.loadbalancer import (
    Group,
    LoadBalancerTimeout,
    NoHealthyEndpoints,
)
from kubeai_tpu.testing.faults import FakeClock
from kubeai_tpu.testing.simkit import break_pod, mark_ready, mk_model

MAX_STREAM_RESUMES = 3  # mirrors proxy.MAX_STREAM_RESUMES


# ---- phase 1: transparent stream resume --------------------------------------


def run_stream_phase(
    n_endpoints: int = 3,
    n_streams: int = 90,
    tokens_per_stream: int = 40,
    kill_every: int = 5,
    kill_at_token: int = 17,
    down_seconds: float = 3.0,
    dt: float = 0.2,
) -> dict:
    """Every `kill_every`-th stream has its serving replica preempted
    mid-generation (the replica then stays down `down_seconds` — the
    operator's repair window). The client model follows the proxy's
    resume discipline: record the midstream outcome against the breaker,
    exclude the dead address, re-dispatch a continuation from the exact
    token where the stream died, bounded by MAX_STREAM_RESUMES."""
    clock = FakeClock()
    group = Group(
        metrics=Metrics(), model="sim", clock=clock,
        breaker=BreakerPolicy(
            window=10, consecutive_failures=3, failure_rate=0.5,
            min_samples=5, open_seconds=2.0,
        ),
    )
    endpoints = [f"ep{i}:1" for i in range(n_endpoints)]
    group.reconcile_endpoints({e: set() for e in endpoints})
    down_until = {e: -1.0 for e in endpoints}

    client_errors = 0
    resumed_streams = 0
    broken_sequences = 0
    resumes_used_max = 0
    for s in range(n_streams):
        delivered: list[int] = []
        failed: set[str] = set()
        pos = 0
        dispatches = 0
        killed_once = False
        ok = False
        while dispatches < 1 + MAX_STREAM_RESUMES:
            try:
                addr, done = group.get_best_addr(
                    "LeastLoad", "", "", timeout=0.2, exclude=failed
                )
            except (NoHealthyEndpoints, LoadBalancerTimeout):
                break
            dispatches += 1
            if down_until[addr] > clock():
                # Replica is gone but the breaker hasn't ejected it yet:
                # the attempt fails before any byte (pre-stream retry).
                done(outcome="connect_error", error="replica preempted")
                failed.add(addr)
                continue
            kill_here = (
                s % kill_every == 0
                and not killed_once
                and pos <= kill_at_token < tokens_per_stream
                # Single-replica preemption at a time — the phase's
                # premise: never take a second replica while one is
                # still down.
                and all(du <= clock() for du in down_until.values())
            )
            stop_at = kill_at_token if kill_here else tokens_per_stream
            while pos < stop_at:
                delivered.append(pos)
                pos += 1
            if kill_here:
                # Mid-stream death: replica preempted while decoding.
                done(outcome="midstream", error="injected preemption")
                down_until[addr] = clock() + down_seconds
                failed.add(addr)
                killed_once = True
                resumed_streams += 1
                continue  # continuation re-dispatch from `pos`
            done(outcome="success")
            ok = True
            break
        resumes_used_max = max(resumes_used_max, dispatches - 1)
        if not ok:
            client_errors += 1
        elif delivered != list(range(tokens_per_stream)):
            broken_sequences += 1
        clock.advance(dt)
    return {
        "streams": n_streams,
        "client_errors": client_errors,
        "resumed_streams": resumed_streams,
        "broken_sequences": broken_sequences,
        "resumes_used_max": resumes_used_max,
    }


# ---- phase 2: self-healing operator repair -----------------------------------


# Model factory and pod breakage live in kubeai_tpu.testing.simkit now,
# shared with every other sim and the game-day harness.


def run_repair_phase(
    replicas: int = 3, rounds: int = 6, step_s: float = 1.0
) -> dict:
    """Alternating preemption / crash-loop kills against a live replica
    set, repaired by the REAL reconciler pod-health pass on fake clocks.
    Measures how long each broken pod survives (clock time between the
    break and its replacement) against the repair-backoff bound."""
    store = KubeStore()
    cfg = System()
    cfg.allow_pod_address_override = True
    cfg.default_and_validate()
    clock = FakeClock(100.0)  # monotonic-ish: repair backoff spacing
    wall = FakeClock(1_000_000.0)  # wall-ish: pod age comparisons
    metrics = Metrics()
    rec = ModelReconciler(
        store, cfg, metrics=metrics, clock=clock, wall=wall
    )
    mk_model(store, replicas=replicas, autoscaling_disabled=True)
    rec.reconcile("default", "sim")
    for pod in store.list("Pod", "default", {"model": "sim"}):
        mark_ready(store, pod)
    rec.reconcile("default", "sim")

    bound_s = cfg.resilience.repair_backoff_max_seconds + step_s
    repair_delays: list[float] = []
    unrepaired = 0
    for rnd in range(rounds):
        pods = store.list("Pod", "default", {"model": "sim"})
        victim = pods[rnd % len(pods)]
        victim_name = victim["metadata"]["name"]
        break_pod(store, victim, "preempt" if rnd % 2 == 0 else "crashloop")
        t0 = clock()
        # The watch would requeue on the pod MODIFIED event; the sim
        # drives reconcile directly, advancing the clocks until the
        # victim is gone (repair backoff may defer a pass or two).
        for _ in range(int(bound_s / step_s) + 2):
            rec.reconcile("default", "sim")
            names = {
                p["metadata"]["name"]
                for p in store.list("Pod", "default", {"model": "sim"})
            }
            if victim_name not in names:
                break
            clock.advance(step_s)
            wall.advance(step_s)
        names = {
            p["metadata"]["name"]
            for p in store.list("Pod", "default", {"model": "sim"})
        }
        if victim_name in names:
            unrepaired += 1
            continue
        repair_delays.append(clock() - t0)
        # Fresh replacements come up Ready before the next round.
        for pod in store.list("Pod", "default", {"model": "sim"}):
            mark_ready(store, pod)
        rec.reconcile("default", "sim")
        clock.advance(step_s)
        wall.advance(step_s)

    model = store.get("Model", "default", "sim")
    conds = {
        c["type"]: c for c in model["status"].get("conditions", [])
    }
    return {
        "rounds": rounds,
        "unrepaired": unrepaired,
        "repair_delays_s": repair_delays,
        "max_repair_delay_s": max(repair_delays, default=0.0),
        "backoff_bound_s": bound_s,
        "replacements_total": sum(
            metrics.controller_pod_replacements.get(
                model="sim", reason=reason
            )
            for reason in ("SpotPreemption", "CrashLoopBackOff")
        ),
        "final_conditions": {
            t: {"status": c["status"], "reason": c["reason"]}
            for t, c in conds.items()
        },
    }


# ---- phase 3: watchdog beats the breaker -------------------------------------


def run_watchdog_phase(reconcile_notice_s: float = 10.0) -> dict:
    """A WEDGED engine (accepts connections, never produces response
    headers) is the breaker's worst case: every proxy attempt fails only
    after the response-header timeout, so even fully parallel attempts
    cannot open the circuit before ONE header timeout elapses (and a
    serial client takes consecutive_failures of them). The step watchdog
    must eject the pod — /health flip, nonzero exit, kubelet restart,
    LB watch removal — strictly before that earliest opening."""
    r = System().resilience
    watchdog_fire_s = r.watchdog_timeout_seconds
    lb_eject_s = watchdog_fire_s + reconcile_notice_s
    breaker_open_earliest_s = r.response_header_timeout_seconds
    breaker_open_serial_s = (
        r.breaker_consecutive_failures * r.response_header_timeout_seconds
    )
    # Mechanism check on a fake-clocked Group: dropping the endpoint at
    # lb_eject_s leaves the breaker still closed (it never saw an
    # outcome — the wedged attempts are still waiting on headers).
    clock = FakeClock()
    group = Group(metrics=Metrics(), model="sim-wedge", clock=clock)
    group.reconcile_endpoints({"wedged:1": set(), "ok:1": set()})
    clock.advance(lb_eject_s)
    group.reconcile_endpoints({"ok:1": set()})  # operator replaced the pod
    ejected = "wedged:1" not in group.snapshot()["endpoints"]
    return {
        "watchdog_fire_s": watchdog_fire_s,
        "lb_eject_s": lb_eject_s,
        "breaker_open_earliest_s": breaker_open_earliest_s,
        "breaker_open_serial_s": breaker_open_serial_s,
        "ejected_before_breaker": (
            ejected and lb_eject_s < breaker_open_earliest_s
        ),
    }


# ---- invariants --------------------------------------------------------------


def run_sim(**kw) -> dict:
    return {
        "streams": run_stream_phase(
            **{k: v for k, v in kw.items() if k in (
                "n_endpoints", "n_streams", "tokens_per_stream",
                "kill_every", "kill_at_token", "down_seconds", "dt",
            )}
        ),
        "repair": run_repair_phase(
            **{k: v for k, v in kw.items() if k in (
                "replicas", "rounds", "step_s",
            )}
        ),
        "watchdog": run_watchdog_phase(),
    }


def check_invariants(summary: dict) -> list[str]:
    """Returns a list of violated invariants (empty = all hold)."""
    errors = []
    st = summary["streams"]
    if st["client_errors"] != 0:
        errors.append(
            f"stream resume: {st['client_errors']} client-visible stream "
            "error(s) under single-replica preemption with >= 2 replicas"
        )
    if st["broken_sequences"] != 0:
        errors.append(
            f"stream resume: {st['broken_sequences']} stream(s) had token "
            "gaps or duplicates after resume"
        )
    if st["resumed_streams"] == 0:
        errors.append("stream resume: the kill schedule never fired "
                      "(sim is not exercising resume)")
    rp = summary["repair"]
    if rp["unrepaired"] != 0:
        errors.append(
            f"self-healing: {rp['unrepaired']} broken pod(s) were never "
            "replaced"
        )
    if rp["max_repair_delay_s"] > rp["backoff_bound_s"]:
        errors.append(
            "self-healing: a repair took "
            f"{rp['max_repair_delay_s']:.1f}s > backoff bound "
            f"{rp['backoff_bound_s']:.1f}s"
        )
    if rp["replacements_total"] < rp["rounds"] - rp["unrepaired"]:
        errors.append(
            "self-healing: kubeai_controller_pod_replacements_total "
            f"({rp['replacements_total']}) undercounts repairs"
        )
    ready = rp["final_conditions"].get("Ready", {})
    if ready.get("status") != "True":
        errors.append(
            f"self-healing: Model Ready condition is {ready} after the "
            "last repair round (want True/AllReplicasReady)"
        )
    wd = summary["watchdog"]
    if not wd["ejected_before_breaker"]:
        errors.append(
            "watchdog: LB ejection at "
            f"{wd['lb_eject_s']:.0f}s does not beat the breaker's "
            f"earliest opening at {wd['breaker_open_earliest_s']:.0f}s"
        )
    return errors


def main() -> int:
    summary = run_sim()
    errors = check_invariants(summary)
    print(json.dumps({"summary": summary, "violations": errors}, indent=2))
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
