"""Deterministic scheduling-fairness simulation — no JAX, no sockets.

Drives a `RequestScheduler` with a fake clock through an oversubscribed
synthetic workload (one single-slot server draining at a fixed service
rate, four clients across three priority bands) and reports the summary
invariants the queue discipline promises:

  * strict precedence: realtime waits < standard waits < batch waits;
  * WFQ: two backlogged same-band clients with 2:1 weights dispatch 2:1;
  * anti-starvation: with a configured queue share, the batch band still
    receives at least ~its share of dispatches under sustained
    higher-priority load;
  * admission control: infeasible deadlines are shed at enqueue and every
    shed carries a COMPUTED Retry-After (the hint varies with queue
    depth — a constant would mean the math is broken).

`tests/unit/test_scheduling.py::test_fairness_simulation_invariants`
asserts these on a small configuration, so fairness regressions fail
tier-1 instead of only showing up under production load. Run directly
for the full-size report:

    python benchmarks/scheduling_fairness.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.scheduling import (
    DeadlineInfeasible,
    RequestScheduler,
    SchedulingPolicy,
)

# Synthetic workload: (client, class, WFQ weight, arrival period in
# rounds). One dispatch happens per round, so realtime at period 4 uses a
# quarter of capacity (and must see near-zero waits), while the standard
# pair (2/round combined) and batch (1 every 2 rounds) oversubscribe the
# remainder and stay backlogged — the regime fairness is for.
CLIENTS = (
    ("rt-a", "realtime", 1.0, 4),
    ("std-a", "standard", 2.0, 1),
    ("std-b", "standard", 1.0, 1),
    ("batch-a", "batch", 1.0, 2),
)


class _Item:
    __slots__ = ("client", "cls", "t_submit")

    def __init__(self, client, cls, t_submit):
        self.client = client
        self.cls = cls
        self.t_submit = t_submit


def run_sim(
    rounds: int = 2000,
    batch_share: float = 0.1,
    service_rate: float = 10.0,
    deadline_every: int = 7,
    deadline_ms: float = 400.0,
) -> dict:
    """One simulation: `rounds` rounds of (arrivals, one dispatch), fake
    clock advancing 1/service_rate per round. Every `deadline_every`-th
    round an extra standard request arrives carrying `deadline_ms` — as
    the backlog grows these become infeasible and must be shed with a
    computed hint."""
    clock = [0.0]
    sched = RequestScheduler(
        SchedulingPolicy(queue_shares={"batch": batch_share}),
        clock=lambda: clock[0],
    )
    dt = 1.0 / service_rate
    dispatched: dict[str, int] = {c[0]: 0 for c in CLIENTS}
    class_dispatched: dict[str, int] = {"realtime": 0, "standard": 0, "batch": 0}
    wait_sums = {"realtime": 0.0, "standard": 0.0, "batch": 0.0}
    sheds = 0
    retry_hints: list[float] = []

    for r in range(rounds):
        for client, cls, weight, period in CLIENTS:
            if r % period == 0:
                sched.submit(
                    _Item(client, cls, clock[0]),
                    priority=cls, client=client, weight=weight,
                )
        if r % deadline_every == 0:
            try:
                sched.submit(
                    _Item("slo-probe", "standard", clock[0]),
                    priority="standard", client="slo-probe",
                    deadline_ms=deadline_ms,
                )
            except DeadlineInfeasible as e:
                sheds += 1
                retry_hints.append(e.retry_after)
        item = sched.pop()
        clock[0] += dt
        sched.observe_service(1.0, dt)
        if item is not None:
            dispatched[item.client] = dispatched.get(item.client, 0) + 1
            class_dispatched[item.cls] += 1
            wait_sums[item.cls] += clock[0] - item.t_submit

    mean_waits = {
        cls: (wait_sums[cls] / n if (n := class_dispatched[cls]) else None)
        for cls in class_dispatched
    }
    return {
        "rounds": rounds,
        "dispatched_by_client": dispatched,
        "dispatched_by_class": class_dispatched,
        "mean_wait_s_by_class": mean_waits,
        "wfq_ratio_std_a_over_std_b": (
            dispatched["std-a"] / dispatched["std-b"]
            if dispatched["std-b"] else None
        ),
        "batch_dispatch_share": class_dispatched["batch"] / rounds,
        "configured_batch_share": batch_share,
        "deadline_sheds": sheds,
        "retry_hints_distinct": len(set(retry_hints)),
        "retry_hint_min": min(retry_hints) if retry_hints else None,
        "retry_hint_max": max(retry_hints) if retry_hints else None,
        "queue_snapshot": sched.snapshot(),
    }


def check_invariants(summary: dict) -> list[str]:
    """Returns a list of violated invariants (empty = all hold)."""
    errors = []
    waits = summary["mean_wait_s_by_class"]
    if not waits["realtime"] < waits["standard"]:
        errors.append(
            f"precedence: realtime mean wait {waits['realtime']} !< "
            f"standard {waits['standard']}"
        )
    if not waits["standard"] < waits["batch"]:
        errors.append(
            f"precedence: standard mean wait {waits['standard']} !< "
            f"batch {waits['batch']}"
        )
    ratio = summary["wfq_ratio_std_a_over_std_b"]
    if ratio is None or not 1.7 <= ratio <= 2.3:
        errors.append(f"wfq: std-a/std-b dispatch ratio {ratio} not ~2.0")
    share = summary["batch_dispatch_share"]
    want = summary["configured_batch_share"]
    if share < 0.8 * want:
        errors.append(
            f"starvation: batch got {share:.3f} of dispatches, "
            f"configured share {want}"
        )
    if summary["deadline_sheds"] == 0:
        errors.append("admission: no deadline sheds in an oversubscribed sim")
    if summary["deadline_sheds"] > 1 and summary["retry_hints_distinct"] < 2:
        errors.append(
            "admission: every shed returned the SAME Retry-After — the "
            "hint is not being computed from queue state"
        )
    return errors


def main() -> int:
    summary = run_sim()
    errors = check_invariants(summary)
    print(json.dumps({"summary": summary, "violations": errors}, indent=2))
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
