"""Load-balancing strategy comparison: LeastLoad vs PrefixHash through the
REAL serving stack (operator manager -> OpenAI front door -> retrying proxy
-> CHWBL/LeastLoad load balancer) against N simulated engine replicas.

This is the repo's version of the reference's headline benchmark
(reference: docs/benchmarks/prefix-aware-load-balancing.md — 8x vLLM/L4
replicas, multi-turn ShareGPT, 800-8000 concurrency). Everything between
the client and the engines is the production code path; the engines
themselves are SIMULATED (this repo's CI box has no 8-GPU pool):

  - per-replica prefix cache: a request's prompt is a message-boundary
    chain; the uncached tail costs prefill time per character (vLLM-style
    automatic prefix caching, where a replica that has seen the
    conversation's earlier turns re-prefills only the newest turn)
  - bounded prefill concurrency per replica (semaphore queue, the
    saturation regime the reference tables show at 800+ concurrency)
  - token streaming at a fixed inter-token latency OUTSIDE the prefill
    semaphore (continuous batching: decode capacity is shared)

What the comparison measures is therefore the ROUTING QUALITY of the two
production strategies — how often each lands a conversation on the replica
that already holds its history — not raw engine speed.

Usage:
  python benchmarks/lb_comparison.py [--threads 800] [--replicas 8]
      [--turns 4] [--json-out PATH]
"""

from __future__ import annotations

import argparse
import hashlib
import importlib.util
import json
import os
import resource
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.config.system import System
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import (
    LoadBalancing,
    Model,
    ModelSpec,
    PrefixHash,
)
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.operator.manager import Manager


def _load_client_module():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "multi_turn_chat.py")
    spec = importlib.util.spec_from_file_location("multi_turn_chat", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# Engine cost-model defaults — the single source for SimEngine, run_one,
# and the CLI (drifting copies would make the script and direct SimEngine
# use silently simulate different engines).
DEFAULT_ENGINE_CONCURRENCY = 16
DEFAULT_BASE_PREFILL_MS = 20.0
DEFAULT_PER_CHAR_US = 50.0
DEFAULT_ITL_S = 0.003


class SimEngine:
    """Simulated OpenAI-compatible engine replica with prefix caching.

    Prefill cost model: base_prefill_s + per_char_s * uncached_chars,
    where uncached_chars counts message content after the longest
    message-boundary prefix this replica has already served. Prefill holds
    the replica's admission semaphore (bounded concurrency -> queueing);
    decode streams outside it at itl_s per token."""

    def __init__(
        self,
        concurrency: int = DEFAULT_ENGINE_CONCURRENCY,
        base_prefill_s: float = DEFAULT_BASE_PREFILL_MS / 1e3,
        per_char_s: float = DEFAULT_PER_CHAR_US / 1e6,
        itl_s: float = DEFAULT_ITL_S,
    ):
        eng = self
        self.sem = threading.Semaphore(concurrency)
        self.base_prefill_s = base_prefill_s
        self.per_char_s = per_char_s
        self.itl_s = itl_s
        self.seen: set[str] = set()
        self.seen_lock = threading.Lock()
        self.requests = 0
        self.cached_chars = 0
        self.total_chars = 0

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(n))
                except json.JSONDecodeError:
                    body = {}
                eng.serve(self, body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    @staticmethod
    def _boundaries(messages) -> list[tuple[str, int]]:
        """(key, cumulative_chars) after each message."""
        h = hashlib.sha1()
        out = []
        total = 0
        for m in messages:
            h.update(
                json.dumps(
                    [m.get("role", ""), m.get("content", "")]
                ).encode()
            )
            total += len(m.get("content", ""))
            out.append((h.hexdigest(), total))
        return out

    def serve(self, handler, body):
        messages = body.get("messages", [])
        max_tokens = int(body.get("max_tokens", 32))
        bounds = self._boundaries(messages)
        total_chars = bounds[-1][1] if bounds else 0
        with self.seen_lock:
            cached = 0
            for key, chars in bounds:
                if key in self.seen:
                    cached = chars
            self.requests += 1
            self.cached_chars += cached
            self.total_chars += total_chars
        prefill_s = self.base_prefill_s + self.per_char_s * (
            total_chars - cached
        )
        with self.sem:  # queue behind other prefills on this replica
            time.sleep(prefill_s)
            with self.seen_lock:
                for key, _ in bounds:
                    self.seen.add(key)

        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        def chunk(payload: bytes):
            handler.wfile.write(
                f"{len(payload):x}\r\n".encode() + payload + b"\r\n"
            )

        try:
            for i in range(max_tokens):
                ev = {
                    "object": "chat.completion.chunk",
                    "choices": [
                        {"index": 0, "delta": {"content": f"tok{i} "}}
                    ],
                }
                chunk(b"data: " + json.dumps(ev).encode() + b"\n\n")
                time.sleep(self.itl_s)
            chunk(b"data: [DONE]\n\n")
            handler.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass  # client gone


class RealEngineReplica:
    """A REAL in-tree engine replica (tiny Llama, byte tokenizer, CPU)
    behind the same pod-annotation wiring SimEngine uses — the throughput
    axis of the comparison (round-5 verdict #8): with real engines the
    tok/s and TTFT columns measure the production serving path end to
    end (front door → proxy → LB → EngineServer → continuous batching),
    not a cost model. Exposes the same counters SimEngine does; prefix
    counters stay 0 (the in-tree engine has no automatic prefix cache —
    CHWBL affinity exists for engines that do, reference:
    docs/benchmarks/prefix-aware-load-balancing.md)."""

    # The governing knobs of a real replica (recorded in the report in
    # place of the simulator's cost model). Byte tokenizer ⇒ one token
    # per character: a 4-turn conversation (system + growing history)
    # runs ~1k tokens, hence the max_seq_len.
    NUM_SLOTS = 8
    MAX_SEQ_LEN = 2048
    DECODE_CHUNK = 8

    # Real replicas run with automatic prefix caching ON (the production
    # config; the reference's benchmark replicas ran vLLM's APC) — this
    # is what lets PrefixHash routing translate into skipped prefill.
    PREFILL_CHUNK = 128

    def __init__(self, shared=None):
        import jax

        jax.config.update("jax_platforms", "cpu")
        from kubeai_tpu.engine import Engine, EngineConfig
        from kubeai_tpu.engine.server import EngineServer
        from kubeai_tpu.engine.tokenizer import ByteTokenizer
        from kubeai_tpu.models import llama

        if shared is None:
            tok = ByteTokenizer()
            cfg = llama.LlamaConfig.tiny(vocab_size=tok.vocab_size)
            shared = (tok, cfg, llama.init_params(cfg))
        self.shared = shared
        tok, cfg, params = shared
        self._srv = EngineServer(
            Engine(
                "llama", cfg, params,
                cfg=EngineConfig(
                    num_slots=self.NUM_SLOTS,
                    max_seq_len=self.MAX_SEQ_LEN,
                    decode_chunk=self.DECODE_CHUNK,
                    prefill_chunk=self.PREFILL_CHUNK,
                    prefix_cache=True,
                ),
                eos_token_ids=tok.eos_token_ids,
            ),
            tok, "sim", host="127.0.0.1", port=0,
        )
        self._srv.start()

    @property
    def port(self) -> int:
        return self._srv.port

    def _metric(self, name: str) -> float:
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{self.port}/metrics", timeout=10
        ) as r:
            for line in r.read().decode().splitlines():
                if line.startswith(name):
                    try:
                        return float(line.rpartition(" ")[2])
                    except ValueError:
                        pass
        return 0.0

    @property
    def requests(self) -> int:
        return int(self._metric("kubeai_engine_requests_total"))

    @property
    def generated_tokens(self) -> int:
        return int(self._metric("kubeai_engine_generated_tokens_total"))

    @property
    def cached_chars(self) -> int:
        # Byte tokenizer: tokens == chars, so the engine's prefix-cache
        # counters drop into SimEngine's hit-rate accounting directly.
        return int(self._metric("kubeai_engine_prefix_cached_tokens_total"))

    @property
    def total_chars(self) -> int:
        return int(self._metric("kubeai_engine_prefix_prompt_tokens_total"))

    def stop(self):
        self._srv.stop()


def _mk_world(n_replicas: int, strategy: str, engines: list):
    store = KubeStore()
    cfg = System()
    cfg.allow_pod_address_override = True
    mgr = Manager(store, cfg)
    mgr.start()
    spec = ModelSpec(
        url="hf://org/sim",
        engine="KubeAITPU",
        features=["TextGeneration"],
        resource_profile="cpu:1",
        autoscaling_disabled=True,
        replicas=n_replicas,
        load_balancing=LoadBalancing(
            strategy=strategy, prefix_hash=PrefixHash()
        ),
    )
    store.create(Model(name="sim", spec=spec).to_dict())
    # The manager's watch loop reconciles; wait for the pod set to settle.
    deadline = time.time() + 15
    pods: list[dict] = []
    while time.time() < deadline:
        pods = store.list("Pod", "default", {md.POD_MODEL_LABEL: "sim"})
        if len(pods) == n_replicas:
            break
        time.sleep(0.1)
    assert len(pods) == n_replicas, len(pods)
    for pod, eng in zip(sorted(pods, key=lambda p: p["metadata"]["name"]),
                        engines):
        fresh = store.get("Pod", "default", pod["metadata"]["name"])
        fresh["metadata"].setdefault("annotations", {}).update(
            {
                md.MODEL_POD_IP_ANNOTATION: "127.0.0.1",
                md.MODEL_POD_PORT_ANNOTATION: str(eng.port),
            }
        )
        fresh.setdefault("status", {})["conditions"] = [
            {"type": "Ready", "status": "True"},
            {"type": "PodScheduled", "status": "True"},
        ]
        fresh["status"]["podIP"] = "127.0.0.1"
        store.update(fresh)
    mgr.lb.sync_all()
    deadline = time.time() + 10
    while time.time() < deadline:
        if len(mgr.lb.group("sim").addresses()) == n_replicas:
            break
        time.sleep(0.05)
    else:
        raise RuntimeError("LB endpoints never became ready")
    return store, mgr


def run_one(
    strategy: str, threads: int, replicas: int, turns: int,
    max_tokens: int, client, *,
    ramp_s: float = 0.0, per_char_us: float = DEFAULT_PER_CHAR_US,
    base_prefill_ms: float = DEFAULT_BASE_PREFILL_MS,
    engine_concurrency: int = DEFAULT_ENGINE_CONCURRENCY,
    real_engines: bool = False,
) -> dict:
    if real_engines:
        engines = []
        shared = None
        for _ in range(replicas):
            e = RealEngineReplica(shared)
            shared = e.shared
            engines.append(e)
    else:
        engines = [
            SimEngine(
                concurrency=engine_concurrency,
                base_prefill_s=base_prefill_ms / 1e3,
                per_char_s=per_char_us / 1e6,
            )
            for _ in range(replicas)
        ]
    store, mgr = _mk_world(replicas, strategy, engines)
    tokens_baseline = 0
    if real_engines:
        # Warm each replica's compile caches (prefill buckets + decode
        # chunk) with one same-shaped conversation DIRECTLY at its port,
        # so the timed phase measures serving, not XLA compilation — the
        # production analog is the readiness-probe warm-up window.
        warm = {"ttft": [], "itl": [], "out_chars": 0, "requests": 0,
                "errors": 0}
        wlock = threading.Lock()
        for i, e in enumerate(engines):
            client.run_conversation(
                f"http://127.0.0.1:{e.port}", "sim", turns, max_tokens,
                7000 + i, warm, wlock,
            )
        if warm["errors"]:
            # A failed warm-up would silently leave XLA compilation inside
            # the timed numbers the report claims exclude it.
            raise RuntimeError(
                f"{warm['errors']} warm-up request(s) failed; timed phase "
                "would measure compilation"
            )
        tokens_baseline = sum(e.generated_tokens for e in engines)
        requests_baseline = [e.requests for e in engines]
        cached_baseline = sum(e.cached_chars for e in engines)
        total_baseline = sum(e.total_chars for e in engines)
    results = {"ttft": [], "itl": [], "out_chars": 0, "requests": 0,
               "errors": 0}
    lock = threading.Lock()
    base_url = f"http://{mgr.api_address}/openai"

    def convo(i: int):
        # Stagger arrivals across the ramp window: an all-at-t=0 herd
        # measures queue-drain, not routing quality (the reference's
        # client sustains arrivals over minutes).
        if ramp_s > 0:
            time.sleep(ramp_s * i / max(1, threads - 1))
        client.run_conversation(
            base_url, "sim", turns, max_tokens, 1000 + i, results, lock
        )

    t0 = time.perf_counter()
    ts = [threading.Thread(target=convo, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    mgr.stop()

    def pct(xs, p):
        if not xs:
            return 0.0
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    per_engine = [e.requests for e in engines]
    cached = sum(e.cached_chars for e in engines)
    total = sum(e.total_chars for e in engines)
    if real_engines:
        # Warm-up traffic went directly to each port, not through the LB —
        # exclude it from the routing spread like the token counters do.
        per_engine = [
            n - base for n, base in zip(per_engine, requests_baseline)
        ]
        cached -= cached_baseline
        total -= total_baseline
        # Byte tokenizer: the engines' own generated-token counters are
        # exact (and match out_chars 1:1); warm-up tokens excluded.
        out_tokens = sum(e.generated_tokens for e in engines) - tokens_baseline
    else:
        # Tokens are synthetic ("tokN "): chars/5.6 approximates the count.
        out_tokens = results["out_chars"] / 5.6
    report = {
        "strategy": strategy,
        "engines": "real" if real_engines else "simulated",
        "concurrency": threads,
        "replicas": replicas,
        "turns": turns,
        # Full engine parameters + load shape, so a committed JSON alone
        # is enough to reproduce the run: the simulator's cost model in
        # sim mode, the real replica's governing knobs in real mode (the
        # cost-model kwargs are ignored there and would mislead).
        "max_tokens": max_tokens,
        "ramp_s": ramp_s,
        **(
            {
                "num_slots": RealEngineReplica.NUM_SLOTS,
                "max_seq_len": RealEngineReplica.MAX_SEQ_LEN,
                "decode_chunk": RealEngineReplica.DECODE_CHUNK,
            }
            if real_engines
            else {
                "per_char_us": per_char_us,
                "base_prefill_ms": base_prefill_ms,
                "engine_concurrency": engine_concurrency,
            }
        ),
        "requests": results["requests"],
        "errors": results["errors"],
        "wall_s": round(wall, 2),
        "mean_ttft_ms": round(
            sum(results["ttft"]) / max(1, len(results["ttft"])) * 1e3, 2
        ),
        "p50_ttft_ms": round(pct(results["ttft"], 0.5) * 1e3, 2),
        "p90_ttft_ms": round(pct(results["ttft"], 0.9) * 1e3, 2),
        "p99_ttft_ms": round(pct(results["ttft"], 0.99) * 1e3, 2),
        "mean_itl_ms": round(
            sum(results["itl"]) / max(1, len(results["itl"])) * 1e3, 2
        ),
        # NOTE: with a ramp this is arrival-limited (most of `wall` IS
        # the ramp window) — compare TTFT and cache-hit columns across
        # runs, not this.
        "output_tok_per_s": round(out_tokens / wall, 1),
        "prefix_cache_hit_pct": round(100.0 * cached / max(1, total), 1),
        "per_engine_requests": per_engine,
    }
    for e in engines:
        e.stop()
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=800)
    ap.add_argument("--replicas", type=int, default=8)
    ap.add_argument("--turns", type=int, default=4)
    ap.add_argument("--max-tokens", type=int, default=32)
    ap.add_argument(
        "--ramp-s", type=float, default=0.0,
        help="stagger conversation starts across this window (0 = all at "
        "once; an all-at-t=0 herd measures queue drain, not routing)",
    )
    ap.add_argument(
        "--per-char-us", type=float, default=DEFAULT_PER_CHAR_US,
        help="simulated prefill cost per uncached character (µs); raise "
        "to model prefill-dominated engines (long-context regime)",
    )
    ap.add_argument(
        "--base-prefill-ms", type=float, default=DEFAULT_BASE_PREFILL_MS
    )
    ap.add_argument(
        "--engine-concurrency", type=int,
        default=DEFAULT_ENGINE_CONCURRENCY,
        help="bounded prefill admission per simulated replica",
    )
    ap.add_argument(
        "--real-engines", action="store_true",
        help="back the proxy tier with REAL in-tree engine replicas "
        "(tiny Llama, CPU) instead of the cost model: tok/s and TTFT "
        "then measure the production serving path end to end. Size "
        "--threads to the host (each replica really decodes)",
    )
    ap.add_argument("--json-out", default="")
    args = ap.parse_args()

    # 800 streams -> ~3x that in sockets (client + proxy upstream).
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    resource.setrlimit(
        resource.RLIMIT_NOFILE, (min(hard, 65535), hard)
    )

    client = _load_client_module()
    reports = []
    for strategy in ("LeastLoad", "PrefixHash"):
        rep = run_one(
            strategy, args.threads, args.replicas, args.turns,
            args.max_tokens, client,
            ramp_s=args.ramp_s, per_char_us=args.per_char_us,
            base_prefill_ms=args.base_prefill_ms,
            engine_concurrency=args.engine_concurrency,
            real_engines=args.real_engines,
        )
        reports.append(rep)
        print(json.dumps(rep), flush=True)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(reports, f, indent=2)


if __name__ == "__main__":
    main()
