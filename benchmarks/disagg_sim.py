"""Deterministic disaggregation simulation — no JAX, no sockets.

Plays the same mixed workload (long-prefill bursts interleaved with
short-decode streams) against two equal-chip-count topologies on a fake
clock:

  * UNIFIED: N monolithic replicas. A replica runs ONE phase per tick:
    admitting a queued prefill blocks every co-batched stream's decode
    step for the prefill's full duration (the co-batching stall this
    subsystem exists to remove).
  * DISAGGREGATED: N/2 prefill + N/2 decode replicas. Prefill replicas
    chew the prefill queue; finished prefills pay a fixed transfer tick
    and then stream from decode replicas whose steps are never blocked.
    Handoff routing goes through the REAL load-balancer Group with role
    labels and circuit breakers on the fake clock, so the sim also
    exercises the role-pick machinery end to end (one decode endpoint
    is wired to a dead breaker mid-run).

Invariants (asserted in tier-1 by tests/unit/test_disagg.py):

  * no decode-step stall from a prefill burst: the maximum inter-token
    gap of any disaggregated stream stays at the decode tick, while the
    unified topology's worst gap grows to at least one prefill duration;
  * TTFT no worse than unified at equal chip count (mean over completed
    requests, transfer cost included);
  * zero handoffs routed to open-circuit decode endpoints.

Run directly for the full-size report:

    python benchmarks/disagg_sim.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.routing.health import STATE_CLOSED, BreakerPolicy
from kubeai_tpu.routing.loadbalancer import Group, NoHealthyEndpoints
from kubeai_tpu.testing.faults import FakeClock


class _Request:
    __slots__ = (
        "rid", "arrive", "prefill_ticks", "decode_tokens",
        "ttft", "token_times", "done",
    )

    def __init__(self, rid, arrive, prefill_ticks, decode_tokens):
        self.rid = rid
        self.arrive = arrive
        self.prefill_ticks = prefill_ticks
        self.decode_tokens = decode_tokens
        self.ttft = None
        self.token_times: list[int] = []
        self.done = False


def _workload(
    n_requests: int, burst_every: int, burst_prefill_ticks: int
) -> list[_Request]:
    """Deterministic arrivals at 1.5 requests/tick: a steady stream of
    short-prefill requests with a LONG-prefill burst request every
    `burst_every` arrivals."""
    reqs = []
    for i in range(n_requests):
        long_p = i % burst_every == burst_every - 1
        reqs.append(
            _Request(
                rid=i,
                arrive=(2 * i) // 3,
                prefill_ticks=burst_prefill_ticks if long_p else 1,
                decode_tokens=12,
            )
        )
    return reqs


class _UnifiedReplica:
    """One monolithic replica modelling the real engine's serving cycle:
    an admission runs a NON-PREEMPTIBLE prefill iteration (its full
    duration stalls every co-batched stream — the whole-prompt bucketed
    prefill of the in-tree engine), and between admissions the engine
    must run a decode chunk for its active streams (`decode_ticks` engine
    iterations), so queued prefills also wait behind decode work. That
    coupling is exactly what disaggregation removes in both directions."""

    DECODE_TICKS_PER_CYCLE = 2

    def __init__(self, slots: int):
        self.slots = slots
        self.active: list[_Request] = []
        self.busy_until = 0  # current prefill runs until this tick
        self.pending_admit: _Request | None = None
        self.decode_owed = 0  # decode-chunk ticks owed before next admit

    def tick(self, now: int, queue: list[_Request]) -> None:
        if now < self.busy_until:
            return  # mid-prefill iteration: decode streams are stalled
        if self.pending_admit is not None:
            req = self.pending_admit
            self.pending_admit = None
            req.ttft = now - req.arrive
            req.token_times.append(now)
            self.active.append(req)
            # The decode chunk the engine owes its streams before the
            # next admission can dispatch.
            self.decode_owed = self.DECODE_TICKS_PER_CYCLE
        # One decode iteration: every active stream advances one token.
        for req in list(self.active):
            req.token_times.append(now)
            if len(req.token_times) >= req.decode_tokens:
                req.done = True
                self.active.remove(req)
        if self.decode_owed > 0:
            self.decode_owed -= 1
            return
        if queue and len(self.active) < self.slots:
            req = queue.pop(0)
            self.busy_until = now + req.prefill_ticks
            self.pending_admit = req


class _PrefillReplica:
    def __init__(self):
        self.busy_until = 0
        self.current: _Request | None = None

    def tick(self, now: int, queue: list[_Request], finished: list[_Request]):
        if self.current is not None and now >= self.busy_until:
            finished.append(self.current)
            self.current = None
        if self.current is None and queue:
            req = queue.pop(0)
            self.current = req
            self.busy_until = now + req.prefill_ticks


class _DecodeReplica:
    def __init__(self, addr: str, slots: int):
        self.addr = addr
        self.slots = slots
        self.active: list[_Request] = []

    def tick(self, now: int) -> None:
        for req in list(self.active):
            req.token_times.append(now)
            if len(req.token_times) >= req.decode_tokens:
                req.done = True
                self.active.remove(req)


def run_sim(
    n_requests: int = 240,
    prefill_replicas: int = 4,
    decode_replicas: int = 2,
    slots: int = 16,
    burst_every: int = 6,
    burst_prefill_ticks: int = 10,
    transfer_ticks: int = 1,
) -> dict:
    # EQUAL chip count: the unified pool gets every chip the two role
    # pools get. Decode batches all its streams into one iteration, so
    # the split skews toward prefill — the economics disaggregation buys.
    replicas = prefill_replicas + decode_replicas

    # ---- unified topology ---------------------------------------------------
    reqs_u = _workload(n_requests, burst_every, burst_prefill_ticks)
    unified = [_UnifiedReplica(slots) for _ in range(replicas)]
    queue_u: list[_Request] = []
    now = 0
    arrivals = sorted(reqs_u, key=lambda r: r.arrive)
    ai = 0
    while (
        ai < len(arrivals)
        or queue_u
        or any(r.active or r.pending_admit or now < r.busy_until
               for r in unified)
    ):
        while ai < len(arrivals) and arrivals[ai].arrive <= now:
            queue_u.append(arrivals[ai])
            ai += 1
        # Least-loaded replica admits first (LeastLoad discipline).
        for rep in sorted(unified, key=lambda r: len(r.active)):
            rep.tick(now, queue_u)
        now += 1
        if now > 100_000:
            raise RuntimeError("unified sim did not converge")

    # ---- disaggregated topology --------------------------------------------
    reqs_d = _workload(n_requests, burst_every, burst_prefill_ticks)
    prefills = [_PrefillReplica() for _ in range(prefill_replicas)]
    decodes = [
        _DecodeReplica(f"decode-{i}:1", slots * 4)
        for i in range(decode_replicas)
    ]

    # Handoff routing through the REAL role-aware Group on a fake clock,
    # with one decode endpoint's circuit held open mid-run: the sim
    # proves open circuits never receive a handoff.
    clock = FakeClock()
    group = Group(
        metrics=Metrics(), model="sim",
        breaker=BreakerPolicy(consecutive_failures=1, open_seconds=10_000.0),
        clock=clock,
    )
    group.reconcile_endpoints(
        {d.addr: set() for d in decodes},
        roles={d.addr: md.ROLE_DECODE for d in decodes},
    )
    dead_addr = decodes[0].addr if decode_replicas > 1 else None
    open_circuit_handoffs = 0
    fail_fast_picks = 0

    queue_d: list[_Request] = []
    transfers: list[tuple[int, _Request]] = []  # (ready_at, req)
    now = 0
    arrivals = sorted(reqs_d, key=lambda r: r.arrive)
    ai = 0
    tripped = False
    while (
        ai < len(arrivals) or queue_d or transfers
        or any(p.current for p in prefills)
        or any(d.active for d in decodes)
    ):
        clock.advance(1.0)
        if dead_addr is not None and not tripped and now == n_requests // 2:
            # Mid-run: one decode endpoint starts failing; its breaker
            # trips on the first recorded failure and stays open for the
            # rest of the run (open_seconds is beyond the horizon).
            addr, done = group.get_best_addr(
                "LeastLoad", "", "", timeout=0.0, role=md.ROLE_DECODE,
                exclude=[d.addr for d in decodes if d.addr != dead_addr],
            )
            done(outcome="connect_error", error="simulated death")
            tripped = True
        while ai < len(arrivals) and arrivals[ai].arrive <= now:
            queue_d.append(arrivals[ai])
            ai += 1
        finished: list[_Request] = []
        for p in prefills:
            p.tick(now, queue_d, finished)
        for req in finished:
            transfers.append((now + transfer_ticks, req))
        ready = [t for t in transfers if t[0] <= now]
        transfers = [t for t in transfers if t[0] > now]
        for _, req in ready:
            try:
                addr, done = group.get_best_addr(
                    "LeastLoad", "", "", timeout=0.0, role=md.ROLE_DECODE,
                )
            except NoHealthyEndpoints:
                fail_fast_picks += 1
                transfers.append((now + 1, req))  # retry next tick
                continue
            ep_state = group.snapshot()["endpoints"][addr]["state"]
            if ep_state != STATE_CLOSED:
                open_circuit_handoffs += 1
            target = next(d for d in decodes if d.addr == addr)
            target.active.append(req)
            req.ttft = now - req.arrive
            req.token_times.append(now)
            done(outcome="success")
        for d in decodes:
            d.tick(now)
        now += 1
        if now > 100_000:
            raise RuntimeError("disagg sim did not converge")

    def _summarize(reqs: list[_Request]) -> dict:
        done = [r for r in reqs if r.done and r.ttft is not None]
        gaps = []
        for r in done:
            for a, b in zip(r.token_times, r.token_times[1:]):
                gaps.append(b - a)
        # Decode-stall metric: worst gap over SHORT-prefill streams only
        # (the victims of co-batched bursts; burst requests own their
        # prefill time).
        short = [r for r in done if r.prefill_ticks == 1]
        short_gaps = [
            b - a
            for r in short
            for a, b in zip(r.token_times, r.token_times[1:])
        ]
        return {
            "completed": len(done),
            "mean_ttft": sum(r.ttft for r in done) / max(1, len(done)),
            "p_max_itl": max(gaps) if gaps else 0,
            "short_stream_max_itl": max(short_gaps) if short_gaps else 0,
        }

    return {
        "params": {
            "n_requests": n_requests,
            "replicas": replicas,
            "prefill_replicas": prefill_replicas,
            "decode_replicas": decode_replicas,
            "burst_every": burst_every,
            "burst_prefill_ticks": burst_prefill_ticks,
            "transfer_ticks": transfer_ticks,
        },
        "unified": _summarize(reqs_u),
        "disagg": _summarize(reqs_d),
        "open_circuit_handoffs": open_circuit_handoffs,
        "fail_fast_picks": fail_fast_picks,
        "decode_circuit_tripped": tripped,
    }


def check_invariants(summary: dict) -> list[str]:
    """Empty list = all disaggregation promises held."""
    errors: list[str] = []
    uni, dis = summary["unified"], summary["disagg"]
    n = summary["params"]["n_requests"]
    if uni["completed"] != n or dis["completed"] != n:
        errors.append(
            f"lost requests: unified {uni['completed']}/{n}, "
            f"disagg {dis['completed']}/{n}"
        )
    burst = summary["params"]["burst_prefill_ticks"]
    if dis["short_stream_max_itl"] > 2:
        errors.append(
            "decode stalled under a prefill burst: disagg short-stream "
            f"max inter-token gap {dis['short_stream_max_itl']} ticks "
            "(expected <= 2: steps never wait on prefill)"
        )
    if uni["short_stream_max_itl"] < burst:
        errors.append(
            "sim lost its contrast: unified short-stream max gap "
            f"{uni['short_stream_max_itl']} < burst prefill {burst} — "
            "the co-batching stall the subsystem removes did not appear"
        )
    if dis["mean_ttft"] > uni["mean_ttft"]:
        errors.append(
            f"TTFT regressed: disagg mean {dis['mean_ttft']:.2f} > "
            f"unified mean {uni['mean_ttft']:.2f} at equal chip count"
        )
    if summary["open_circuit_handoffs"] != 0:
        errors.append(
            f"{summary['open_circuit_handoffs']} handoff(s) routed to an "
            "open-circuit decode endpoint"
        )
    if not summary["decode_circuit_tripped"]:
        errors.append("the decode-death scenario never armed its breaker")
    return errors


if __name__ == "__main__":
    summary = run_sim()
    print(json.dumps(summary, indent=2, sort_keys=True))
    problems = check_invariants(summary)
    if problems:
        print("\nINVARIANT VIOLATIONS:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        sys.exit(1)
    print("\nall invariants held")
