"""Deterministic resilience simulation — no JAX, no sockets.

Drives a real load-balancer `Group` (circuit breakers on a fake clock)
through a 3-endpoint kill / recover / flap schedule using the proxy's
retry discipline (≤3 attempts, exclude-set on retry, concurrent request
waves so LeastLoad actually spreads), and reports the invariants the
resilience layer promises:

  * breaker correctness: zero requests are ever routed to an endpoint
    whose circuit is open;
  * availability floor: with 1 of 3 endpoints hard-down, ≥ 99% of
    requests succeed using at most one extra attempt each;
  * fail-fast: when EVERY endpoint's circuit is open, the pick raises
    `NoHealthyEndpoints` immediately (with per-endpoint error context)
    instead of hanging to the scale-from-zero timeout;
  * half-open probes are singular: while one probe is in flight, no
    second request reaches the recovering endpoint;
  * recovery: a recovered endpoint rejoins the rotation after one
    successful probe, and a flapping endpoint keeps overall availability
    at the floor.

`tests/unit/test_resilience.py::test_resilience_simulation_invariants`
asserts these on a small configuration, so breaker regressions fail
tier-1 instead of only showing up during a production incident. Run
directly for the full-size report:

    python benchmarks/resilience_sim.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.metrics import Metrics
from kubeai_tpu.routing.health import BreakerPolicy
from kubeai_tpu.routing.loadbalancer import (
    Group,
    LoadBalancerTimeout,
    NoHealthyEndpoints,
)
from kubeai_tpu.testing.faults import FakeClock, Fault, FaultPlan

ENDPOINTS = ("a:1", "b:1", "c:1")
MAX_ATTEMPTS = 3  # mirrors proxy.MAX_RETRIES


def _run_wave(group, is_down, sporadic, concurrency, dispatches):
    """One wave of `concurrency` concurrent requests, each following the
    proxy's retry discipline: pick (excluding already-failed addresses),
    hold the in-flight slot while the whole wave picks (this is what
    spreads LeastLoad), then resolve outcomes and retry the failures.

    Returns (ok_flags, attempts_used, open_picks, fail_fasts)."""
    ok = [False] * concurrency
    attempts_used = [0] * concurrency
    open_picks = 0
    fail_fasts = 0
    pending: list[tuple[int, set]] = [(i, set()) for i in range(concurrency)]
    for _wave in range(MAX_ATTEMPTS):
        picks = []
        for i, failed in pending:
            try:
                addr, done = group.get_best_addr(
                    "LeastLoad", "", "", timeout=0.5, exclude=failed,
                )
            except NoHealthyEndpoints:
                fail_fasts += 1
                continue
            if group.snapshot()["endpoints"][addr]["state"] == "open":
                open_picks += 1  # invariant violation: recorded, not raised
            attempts_used[i] += 1
            picks.append((i, failed, addr, done))
        retry: list[tuple[int, set]] = []
        for i, failed, addr, done in picks:
            fault = None
            if is_down(addr):
                fault = "connect_error"
            elif sporadic is not None and sporadic.on_attempt(addr) is not None:
                fault = "5xx"
            if fault is None:
                done(outcome="success")
                ok[i] = True
                dispatches[addr] = dispatches.get(addr, 0) + 1
            else:
                done(outcome=fault, error=f"injected {fault} at {addr}")
                failed.add(addr)
                retry.append((i, failed))
        pending = retry
        if not pending:
            break
    return ok, attempts_used, open_picks, fail_fasts


def run_sim(
    waves_per_phase: int = 200,
    concurrency: int = 3,
    dt: float = 0.05,
    open_seconds: float = 5.0,
    flap_period: int = 20,
    seed: int = 7,
) -> dict:
    """Three phases of `waves_per_phase` waves (each `concurrency`
    concurrent requests), clock advancing `dt` per wave:

      one_down — endpoint b refuses every connection (crashed replica);
      recovered — all endpoints healthy, plus a sporadic 503 on endpoint
                  a every 29th attempt (blips that must NOT trip the
                  breaker);
      flap — endpoint c alternates dead/alive every `flap_period` waves
             (crash-looping replica).
    """
    clock = FakeClock()
    policy = BreakerPolicy(
        window=10,
        consecutive_failures=3,
        failure_rate=0.5,
        min_samples=5,
        open_seconds=open_seconds,
    )
    group = Group(
        metrics=Metrics(), model="sim", breaker=policy, clock=clock,
    )
    group.reconcile_endpoints({ep: set() for ep in ENDPOINTS})

    phases = ("one_down", "recovered", "flap")
    stats = {
        p: {
            "requests": 0, "success": 0, "fail_fasts": 0,
            "attempts_hist": {1: 0, 2: 0, 3: 0},
            "dispatches": {},
        }
        for p in phases
    }
    open_picks_total = 0
    sporadic = FaultPlan(
        [Fault("a:1", "http", every=29, status=503)], seed=seed
    )

    for phase in phases:
        for w in range(waves_per_phase):
            if phase == "one_down":
                def is_down(addr):
                    return addr == "b:1"
            elif phase == "recovered":
                def is_down(addr):
                    return False
            else:
                flapping = (w // flap_period) % 2 == 0
                def is_down(addr, flapping=flapping):
                    return addr == "c:1" and flapping
            ok, attempts, open_picks, fail_fasts = _run_wave(
                group, is_down,
                sporadic if phase == "recovered" else None,
                concurrency, stats[phase]["dispatches"],
            )
            st = stats[phase]
            st["requests"] += concurrency
            st["success"] += sum(ok)
            st["fail_fasts"] += fail_fasts
            for a in attempts:
                if a:
                    st["attempts_hist"][a] += 1
            open_picks_total += open_picks
            clock.advance(dt)

    summary = {
        "phases": {
            p: {
                "requests": st["requests"],
                "success_rate": st["success"] / st["requests"],
                "fail_fasts": st["fail_fasts"],
                "attempts_hist": st["attempts_hist"],
                "max_attempts": max(
                    (a for a, n in st["attempts_hist"].items() if n),
                    default=0,
                ),
                "dispatches": st["dispatches"],
            }
            for p, st in stats.items()
        },
        "open_circuit_picks": open_picks_total,
        "b_state_after_recovery": (
            group.snapshot()["endpoints"]["b:1"]["state"]
        ),
        "b_serves_after_recovery": (
            stats["recovered"]["dispatches"].get("b:1", 0)
        ),
        "fail_fast": _check_fail_fast(open_seconds),
        "probe_singular": _check_probe_singularity(open_seconds),
        "snapshot": group.snapshot(),
    }
    return summary


def _check_fail_fast(open_seconds: float) -> dict:
    """All three endpoints down: once the breakers trip, the pick must
    raise NoHealthyEndpoints IMMEDIATELY (with per-endpoint error
    context), never hang to the LoadBalancerTimeout."""
    clock = FakeClock()
    group = Group(
        metrics=Metrics(), model="sim-alldown",
        breaker=BreakerPolicy(consecutive_failures=2, open_seconds=open_seconds),
        clock=clock,
    )
    group.reconcile_endpoints({ep: set() for ep in ENDPOINTS})
    # Trip every breaker.
    for _ in range(2):
        for ep in ENDPOINTS:
            failed = set(ENDPOINTS) - {ep}
            addr, done = group.get_best_addr(
                "LeastLoad", "", "", timeout=0.5, exclude=failed
            )
            done(outcome="connect_error", error=f"injected: {addr} refused")
    result = {"raised": False, "has_context": False, "hung": False}
    import time as _time

    t0 = _time.monotonic()
    try:
        # A generous timeout that fail-fast must NOT consume.
        group.get_best_addr("LeastLoad", "", "", timeout=30.0)
    except NoHealthyEndpoints as e:
        result["raised"] = True
        result["has_context"] = all(ep in str(e) for ep in ENDPOINTS)
    except LoadBalancerTimeout:
        pass
    result["hung"] = (_time.monotonic() - t0) > 1.0
    return result


def _check_probe_singularity(open_seconds: float) -> dict:
    """An open circuit past its backoff admits exactly ONE probe: while
    the probe is in flight no other request may reach the endpoint, and
    the probe's outcome decides re-admission."""
    clock = FakeClock()
    group = Group(
        metrics=Metrics(), model="sim-probe",
        breaker=BreakerPolicy(consecutive_failures=2, open_seconds=open_seconds),
        clock=clock,
    )
    group.reconcile_endpoints({"a:1": set(), "b:1": set()})
    # Trip b.
    for _ in range(2):
        addr, done = group.get_best_addr(
            "LeastLoad", "", "", timeout=0.5, exclude={"a:1"}
        )
        done(outcome="connect_error", error="injected")
    # Hold one request on a so the recovering b is the LeastLoad choice
    # once its backoff elapses.
    _a_addr, a_done = group.get_best_addr("LeastLoad", "", "", timeout=0.5)
    clock.advance(open_seconds + 0.1)  # backoff elapsed → probe eligible
    probe_addr, probe_done = group.get_best_addr(
        "LeastLoad", "", "", timeout=0.5
    )
    singular = True
    # While the probe is in flight, 20 more picks: none may reach b.
    for _ in range(20):
        addr, done = group.get_best_addr("LeastLoad", "", "", timeout=0.5)
        if addr == "b:1":
            singular = False
        done(outcome="success")
    state_during = group.snapshot()["endpoints"]["b:1"]["state"]
    probe_done(outcome="success")  # probe succeeds → circuit closes
    a_done(outcome="success")
    state_after = group.snapshot()["endpoints"]["b:1"]["state"]
    return {
        "probe_went_to_open_endpoint": probe_addr == "b:1",
        "singular": singular,
        "state_during_probe": state_during,
        "closed_after_probe_success": state_after == "closed",
    }


def check_invariants(summary: dict) -> list[str]:
    """Returns a list of violated invariants (empty = all hold)."""
    errors = []
    if summary["open_circuit_picks"] != 0:
        errors.append(
            f"routing: {summary['open_circuit_picks']} request(s) were "
            "routed to an open-circuit endpoint"
        )
    one_down = summary["phases"]["one_down"]
    if one_down["success_rate"] < 0.99:
        errors.append(
            "availability: 1-of-3 hard-down success rate "
            f"{one_down['success_rate']:.4f} < 0.99"
        )
    if one_down["max_attempts"] > 2:
        errors.append(
            "availability: a request under 1-of-3 loss needed "
            f"{one_down['max_attempts']} attempts (> one extra)"
        )
    for phase in ("recovered", "flap"):
        rate = summary["phases"][phase]["success_rate"]
        if rate < 0.99:
            errors.append(f"{phase}: success rate {rate:.4f} < 0.99")
    if summary["b_serves_after_recovery"] == 0:
        errors.append(
            "recovery: endpoint b never rejoined the rotation after "
            "its circuit should have re-closed"
        )
    if summary["b_state_after_recovery"] != "closed":
        errors.append(
            "recovery: endpoint b's circuit is "
            f"{summary['b_state_after_recovery']!r} after the recovered "
            "phase (want closed)"
        )
    ff = summary["fail_fast"]
    if not ff["raised"]:
        errors.append("fail-fast: all-endpoints-open did not raise "
                      "NoHealthyEndpoints")
    if not ff["has_context"]:
        errors.append("fail-fast: the 503 context is missing per-endpoint "
                      "last-seen errors")
    if ff["hung"]:
        errors.append("fail-fast: the pick blocked instead of failing "
                      "immediately")
    ps = summary["probe_singular"]
    if not ps["probe_went_to_open_endpoint"]:
        errors.append("half-open: the post-backoff probe did not go to the "
                      "recovering endpoint")
    if not ps["singular"]:
        errors.append("half-open: a second request reached the endpoint "
                      "while the probe was in flight")
    if not ps["closed_after_probe_success"]:
        errors.append("half-open: a successful probe did not close the "
                      "circuit")
    return errors


def main() -> int:
    summary = run_sim()
    errors = check_invariants(summary)
    print(json.dumps({"summary": summary, "violations": errors}, indent=2))
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
