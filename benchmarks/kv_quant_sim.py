"""Deterministic int8-KV capacity/bytes simulation — no JAX, no sockets.

Answers the quantized-paged-KV tier's three promises with measured
numbers on a fake clock, at the geometry the feature actually targets
(Llama-8B-class KV heads: head_dim 128 — NOT the tiny tier-1 proxy,
whose head_dim 16 caps the capacity factor at 1.6):

  * CAPACITY — at an identical HBM budget, the int8 page pool (1-byte
    values + per-token-per-head f32 scales) holds >= 1.9x the tokens and
    >= 1.9x the decode slots of the bf16 pool. The exact factor is
    2D/(D+4) = 1.9394 at D=128 (ops/kv_quant.kv_capacity_factor; the
    tier-1 test pins this module's constant to the real function).
  * WIRE BYTES — replaying ONE identical disagg/sharing/spill trace
    through the REAL KVH1/KVP1 serializers (disagg/handoff.py) in both
    dtypes, the int8 arm ships strictly fewer bytes in every category
    (prefill->decode handoffs, peer prefix-page fetches, spill-store
    writes), and every int8 blob round-trips byte-identically
    (serialize -> deserialize -> serialize) — re-quantization on the
    wire would show up here as a diff.
  * DECODE PHASE — a memory-bandwidth cost model of the paged-attention
    read (the decode step is HBM-bound; int8 halves the bytes but adds a
    dequant multiply per element) driven through the REAL StepProfiler
    and the `kubeai_engine_step_phase_seconds` histogram: the int8 arm's
    decode phase must not regress over the identical step schedule.

Plus the control-plane consequence: two REAL CapacityPlanner worlds
(fleet/planner.py) over the same 12-chip budget and the same resident
load, differing only in the advertised KV capacity. The bf16 replica's
KV-utilization signal demands a decode replica the budget cannot host
(throttled); the int8 replica's halved utilization fits exactly — the
plan's decision records show the int8 replica fitting where bf16 did
not.

Invariants (asserted in tier-1 by tests/unit/test_kv_quant_sim.py):

  * token and slot capacity ratios >= 1.9 at equal HBM;
  * int8 wire bytes strictly below bf16 in every category;
  * int8 blobs byte-identical across a wire round-trip;
  * no decode-phase regression in kubeai_engine_step_phase_seconds;
  * planner: bf16 throttled > 0, int8 throttled == 0 with allocation ==
    target, chip budget respected in both worlds;
  * the run is deterministic: same inputs, byte-identical report.

Run directly for the full JSON report:

    python benchmarks/kv_quant_sim.py
"""

from __future__ import annotations

import json
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.disagg.handoff import (
    KVHandoff,
    KVPageExport,
    deserialize,
    deserialize_pages,
    serialize,
    serialize_pages,
)
from kubeai_tpu.fleet.planner import CapacityPlanner
from kubeai_tpu.fleet.profiler import StepProfiler
from kubeai_tpu.metrics.registry import Gauge, Histogram, Registry
from kubeai_tpu.testing.faults import FakeClock

# ---- target geometry (Llama-8B-class KV: GQA 8 heads x 128 dims) -------------

NUM_LAYERS = 32
KV_HEADS = 8
HEAD_DIM = 128
PAGE = 16  # tokens per KV page
MAX_SEQ_LEN = 4096  # one decode slot's page-table reservation
HBM_KV_BUDGET = 6 * 2**30  # bytes of HBM granted to the KV pool
SCALE_BYTES = 4  # one f32 scale per (token, head)

# 2D/(D+scale_bytes): pinned to ops/kv_quant.kv_capacity_factor by the
# tier-1 test (the sim itself stays JAX-free).
CAPACITY_FACTOR = 2 * HEAD_DIM / (HEAD_DIM + SCALE_BYTES)

# ---- decode-phase cost model -------------------------------------------------

HBM_BW_BYTES_PER_S = 819e9  # v5e HBM bandwidth
DEQUANT_S_PER_ELEM = 2e-13  # int8->bf16 multiply, amortized per element
DECODE_STEPS = 48
DECODE_BATCH = 12  # resident sequences during the phase comparison

# ---- wire-trace geometry (small arrays, real serializers) --------------------

WIRE_NL = 4
WIRE_KVH = 2

# The sim's own instrument bundle, mirroring the engine gauges the
# /v1/state consumers read (EngineMetrics in engine/server.py). Declared
# with the engine metric names so scripts/check_metric_catalogue.py —
# whose static scan covers benchmarks/ — pins them to the catalogue.
SIM_REGISTRY = Registry()
KV_CACHE_BYTES = Gauge(
    "kubeai_engine_kv_cache_bytes",
    "Resident KV page-pool bytes (values + quantization scales)",
    SIM_REGISTRY,
)
KV_QUANT_ENABLED = Gauge(
    "kubeai_engine_kv_quant_enabled",
    "1 when the paged KV cache stores int8 pages, else 0",
    SIM_REGISTRY,
)
KV_QUANT_CAPACITY_FACTOR = Gauge(
    "kubeai_engine_kv_quant_capacity_factor",
    "Token capacity multiplier of the configured KV dtype vs bf16",
    SIM_REGISTRY,
)
STEP_PHASE_SECONDS = Histogram(
    "kubeai_engine_step_phase_seconds",
    "Modeled engine step phase durations (sim arms labeled by kv dtype)",
    SIM_REGISTRY,
)


def bytes_per_token(dtype: str) -> int:
    """Resident bytes one token's K+V rows cost across all layers."""
    values = 2 * NUM_LAYERS * KV_HEADS * HEAD_DIM  # K and V
    if dtype == "int8":
        return values + 2 * NUM_LAYERS * KV_HEADS * SCALE_BYTES
    return values * 2  # bf16


def pool_capacity(dtype: str) -> dict:
    """Whole-page pool capacity at the fixed HBM budget — the same
    arithmetic Engine.kv_cache_info reports from a live pool."""
    page_bytes = PAGE * bytes_per_token(dtype)
    num_pages = HBM_KV_BUDGET // page_bytes
    tokens = num_pages * PAGE
    return {
        "dtype": dtype,
        "bytes_per_token": bytes_per_token(dtype),
        "num_pages": int(num_pages),
        "token_capacity": int(tokens),
        "slot_capacity": int(tokens // MAX_SEQ_LEN),
        "pool_bytes": int(num_pages * page_bytes),
    }


# ---- wire trace --------------------------------------------------------------


def _trace_events() -> list[tuple[str, int]]:
    """One deterministic disagg/sharing/spill trace: (kind, size) where
    size is prompt tokens for handoffs and page counts for fetch/spill."""
    events: list[tuple[str, int]] = []
    for i in range(8):
        events.append(("handoff", 96 + 32 * (i % 4) + 7 * i))
    for i in range(6):
        events.append(("fetch", 2 + (i % 3)))
    for i in range(4):
        events.append(("spill", 3 + (i % 2)))
    return events


def _wire_arrays(dtype: str, n_pages: int, seed: int):
    """Deterministic page content for one blob: (k, v, k_scales,
    v_scales). bf16 lives in ml_dtypes (what np.asarray(jax_array)
    yields), so the trace exercises the exact dtype the engine ships."""
    shape = (WIRE_NL, n_pages, PAGE, WIRE_KVH, HEAD_DIM)
    n = int(np.prod(shape))
    base = (np.arange(n, dtype=np.int64) * 2654435761 + seed * 40503) % 255
    if dtype == "int8":
        k = (base.reshape(shape) - 127).astype(np.int8)
        v = ((254 - base).reshape(shape) - 127).astype(np.int8)
        sshape = shape[:-1]
        sn = int(np.prod(sshape))
        sbase = (np.arange(sn, dtype=np.int64) * 69069 + seed) % 1000
        ks = (sbase.reshape(sshape).astype(np.float32) + 1.0) / 1024.0
        vs = (999 - sbase).reshape(sshape).astype(np.float32) / 1024.0 + 0.001
        return k, v, ks, vs
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    k = ((base.reshape(shape) - 127) / 16.0).astype(bf16)
    v = ((127 - base.reshape(shape)) / 16.0).astype(bf16)
    return k, v, None, None


def _handoff_blob(dtype: str, plen: int, seed: int) -> bytes:
    n_pages = math.ceil(plen / PAGE)
    k, v, ks, vs = _wire_arrays(dtype, n_pages, seed)
    h = KVHandoff(
        token_ids=[(seed * 131 + j) % 50021 for j in range(plen)],
        first_token=(seed * 17) % 50021,
        first_finish="",
        page_size=PAGE,
        dtype=dtype,
        k_pages=k,
        v_pages=v,
        seed=seed,
        temperature=0.0,
        top_k=0,
        top_p=1.0,
        max_tokens=64,
        model="sim",
        k_scales=ks,
        v_scales=vs,
    )
    return serialize(h)


def _pages_blob(dtype: str, n_pages: int, seed: int) -> bytes:
    k, v, ks, vs = _wire_arrays(dtype, n_pages, seed)
    e = KVPageExport(
        prefix_hashes=tuple(f"{seed:08x}{p:08x}" for p in range(n_pages)),
        page_size=PAGE,
        dtype=dtype,
        k_pages=k,
        v_pages=v,
        model="sim",
        k_scales=ks,
        v_scales=vs,
    )
    return serialize_pages(e)


def run_wire_trace(dtype: str) -> dict:
    """Replay the trace through the real serializers; verify every blob
    survives a wire round-trip byte-identically (for int8 this is the
    no-re-quantization guarantee: values and scales ship verbatim)."""
    totals = {"handoff": 0, "fetch": 0, "spill": 0}
    counts = {"handoff": 0, "fetch": 0, "spill": 0}
    roundtrip_ok = True
    for seed, (kind, size) in enumerate(_trace_events()):
        if kind == "handoff":
            blob = _handoff_blob(dtype, size, seed)
            h2 = deserialize(blob)
            again = serialize(h2)
        else:
            blob = _pages_blob(dtype, size, seed)
            e2 = deserialize_pages(blob)
            again = serialize_pages(e2)
        roundtrip_ok = roundtrip_ok and (again == blob)
        totals[kind] += len(blob)
        counts[kind] += 1
    return {
        "dtype": dtype,
        "bytes": totals,
        "events": counts,
        "total_bytes": sum(totals.values()),
        "roundtrip_byte_identical": roundtrip_ok,
    }


# ---- decode-phase model ------------------------------------------------------


def run_decode_phases(dtype: str) -> dict:
    """Drive the REAL StepProfiler over an identical step schedule in
    both arms. Per step, the paged-attention read streams every resident
    token's K+V rows from HBM (the decode step's bound); the int8 arm
    reads ~half the bytes but pays a dequant multiply per element."""
    clock = FakeClock(1000.0)
    prof = StepProfiler(maxlen=DECODE_STEPS, wall=clock)
    quant = dtype == "int8"
    values_per_token = 2 * NUM_LAYERS * KV_HEADS * HEAD_DIM
    for step in range(DECODE_STEPS):
        resident = DECODE_BATCH * (256 + 16 * step)  # growing sequences
        read_bytes = resident * bytes_per_token(dtype)
        decode_s = read_bytes / HBM_BW_BYTES_PER_S
        if quant:
            decode_s += resident * values_per_token * DEQUANT_S_PER_ELEM
        phases = {
            "schedule": 0.0002,
            "decode": decode_s,
            "overlap_idle": 0.0001,
            "readback": 0.0003,
            "sample": 0.0003,
        }
        prof.observe_step(
            phases, tokens=DECODE_BATCH, batch=DECODE_BATCH,
            duration_s=sum(phases.values()),
        )
        clock.advance(sum(phases.values()))
    for phase, seconds in prof.drain():
        STEP_PHASE_SECONDS.observe(seconds, phase=phase, kv_dtype=dtype)
    records = prof.recent()
    return {
        "dtype": dtype,
        "steps": len(records),
        "decode_phase_total_s": round(
            sum(r["phases_s"]["decode"] for r in records), 9
        ),
        "decode_phase_per_step_s": [
            r["phases_s"]["decode"] for r in records
        ],
    }


# ---- planner worlds ----------------------------------------------------------

SHAPE = "tpu-v5-lite-podslice/2x2"
CHIP_BUDGET = 12
CHIPS_PER_REPLICA = 4
N_PREFILL = 1
N_DECODE = 2
RESIDENT_TOKENS = 88_000  # fleet-wide resident KV load, both worlds


def _sim_model(name: str):
    from kubeai_tpu.crd.model import Disaggregation, Model, ModelSpec

    return Model(
        name=name,
        spec=ModelSpec(
            url="hf://org/x",
            engine="KubeAITPU",
            features=["TextGeneration"],
            min_replicas=0,
            max_replicas=10,
            target_requests=10,
            disaggregation=Disaggregation(
                enabled=True,
                prefill_target_queue=4,
                decode_target_utilization=0.8,
            ),
        ),
    )


class _FakeFleet:
    """Minimal FleetStateAggregator stand-in: one fresh snapshot whose
    decode-role signals carry the KV capacity the engine advertises."""

    def __init__(self, clock, model: str, cap: dict):
        self._clock = clock
        self._model = model
        self._cap = cap

    def snapshot(self) -> dict:
        slot_capacity = N_DECODE * self._cap["slot_capacity"]
        kv_util = RESIDENT_TOKENS / (
            N_DECODE * self._cap["token_capacity"]
        )
        # Active sequences sized so slot occupancy stays below the KV
        # signal: decode replicas die by running out of pages first.
        slots_active = min(slot_capacity * 0.5, 10.0)
        decode_sig = {
            "endpoints": N_DECODE,
            "depth": 0.0,
            "oldest_wait_s": 0.0,
            "kv_utilization": kv_util,
            "slots_active": slots_active,
            "slot_capacity": float(slot_capacity),
            "ttft_mean_s": 0.1,
        }
        prefill_sig = {
            "endpoints": N_PREFILL,
            "depth": 2.0,
            "oldest_wait_s": 0.5,
            "kv_utilization": 0.0,
            "slots_active": 0.0,
            "slot_capacity": 0.0,
            "ttft_mean_s": 0.1,
        }
        total = N_PREFILL + N_DECODE
        return {
            "ts": self._clock(),
            "models": {
                self._model: {
                    "replicas": {
                        "prefill": N_PREFILL, "decode": N_DECODE,
                    },
                    "roles": {
                        "prefill": prefill_sig, "decode": decode_sig,
                    },
                    "pods": {
                        "total": total,
                        "chips": total * CHIPS_PER_REPLICA,
                        "by_role": {
                            "prefill": N_PREFILL, "decode": N_DECODE,
                        },
                    },
                },
            },
        }


def run_planner_world(dtype: str) -> dict:
    """One REAL CapacityPlanner tick over the fixed chip budget, fed the
    KV capacity this dtype's pool advertises. Returns the model's plan
    decision record plus the budget accounting."""
    from kubeai_tpu.metrics.registry import Metrics

    clock = FakeClock(2000.0)
    cap = pool_capacity(dtype)
    name = f"chat-{dtype}"
    model = _sim_model(name)
    fleet = _FakeFleet(clock, name, cap)

    class _Models:
        def list_all_models(self):
            return [model]

    planner = CapacityPlanner(
        fleet=fleet,
        model_client=_Models(),
        metrics=Metrics(),
        interval_s=1.0,
        preemption_enabled=False,
        budget_override={
            SHAPE: {
                "chips": CHIP_BUDGET, "slice_chips": CHIPS_PER_REPLICA,
            },
        },
        clock=clock,
    )
    plan = planner.tick(force=True)
    rec = plan["models"][name]
    return {
        "dtype": dtype,
        "kv_utilization": rec["kv_utilization"],
        "slot_capacity": N_DECODE * cap["slot_capacity"],
        "desired_roles": rec["desired_roles"],
        "target_roles": rec["target_roles"],
        "allocated_roles": rec["allocated_roles"],
        "throttled_replicas": rec["throttled_replicas"],
        "chips_allocated": plan["allocated_chips"]["total"],
        "chip_budget": plan["budget"]["total"],
        "decision_record": rec,
    }


# ---- the full sim ------------------------------------------------------------


def run_sim() -> dict:
    capacity = {d: pool_capacity(d) for d in ("bfloat16", "int8")}
    for d, cap in capacity.items():
        KV_CACHE_BYTES.set(cap["pool_bytes"], kv_dtype=d)
        KV_QUANT_ENABLED.set(1.0 if d == "int8" else 0.0, kv_dtype=d)
        KV_QUANT_CAPACITY_FACTOR.set(
            CAPACITY_FACTOR if d == "int8" else 1.0, kv_dtype=d
        )
    wire = {d: run_wire_trace(d) for d in ("bfloat16", "int8")}
    phases = {d: run_decode_phases(d) for d in ("bfloat16", "int8")}
    planner = {d: run_planner_world(d) for d in ("bfloat16", "int8")}
    return {
        "geometry": {
            "num_layers": NUM_LAYERS,
            "kv_heads": KV_HEADS,
            "head_dim": HEAD_DIM,
            "page_size": PAGE,
            "max_seq_len": MAX_SEQ_LEN,
            "hbm_kv_budget_bytes": HBM_KV_BUDGET,
            "capacity_factor": CAPACITY_FACTOR,
        },
        "capacity": capacity,
        "wire": wire,
        "decode_phases": phases,
        "planner": planner,
    }


def check_invariants(summary: dict) -> list[str]:
    """Empty list = every quantized-KV promise held."""
    errors: list[str] = []
    bf, q8 = summary["capacity"]["bfloat16"], summary["capacity"]["int8"]

    token_ratio = q8["token_capacity"] / bf["token_capacity"]
    slot_ratio = q8["slot_capacity"] / bf["slot_capacity"]
    if token_ratio < 1.9:
        errors.append(
            f"token capacity ratio {token_ratio:.4f} < 1.9 at equal HBM"
        )
    if slot_ratio < 1.9:
        errors.append(
            f"slot capacity ratio {slot_ratio:.4f} < 1.9 at equal HBM"
        )
    for cap in (bf, q8):
        if cap["pool_bytes"] > HBM_KV_BUDGET:
            errors.append(
                f"{cap['dtype']} pool overruns the HBM budget: "
                f"{cap['pool_bytes']} > {HBM_KV_BUDGET}"
            )

    wbf, wq8 = summary["wire"]["bfloat16"], summary["wire"]["int8"]
    if wbf["events"] != wq8["events"]:
        errors.append("wire arms replayed different traces")
    for kind, n in wq8["events"].items():
        if n == 0:
            errors.append(f"wire trace has no {kind} events — no contrast")
        if wq8["bytes"][kind] >= wbf["bytes"][kind]:
            errors.append(
                f"int8 did not reduce {kind} bytes: "
                f"{wq8['bytes'][kind]} >= {wbf['bytes'][kind]}"
            )
    for arm in (wbf, wq8):
        if not arm["roundtrip_byte_identical"]:
            errors.append(
                f"{arm['dtype']} blobs did not survive the wire "
                "round-trip byte-identically"
            )

    pbf = summary["decode_phases"]["bfloat16"]
    pq8 = summary["decode_phases"]["int8"]
    if pq8["decode_phase_total_s"] > pbf["decode_phase_total_s"]:
        errors.append(
            "decode phase regressed under int8: "
            f"{pq8['decode_phase_total_s']} > {pbf['decode_phase_total_s']}"
        )
    worse_steps = sum(
        1
        for a, b in zip(
            pq8["decode_phase_per_step_s"], pbf["decode_phase_per_step_s"]
        )
        if a > b
    )
    if worse_steps:
        errors.append(
            f"{worse_steps} step(s) slower under int8 on the identical "
            "schedule"
        )

    plbf, plq8 = summary["planner"]["bfloat16"], summary["planner"]["int8"]
    if plbf["throttled_replicas"] <= 0:
        errors.append(
            "bf16 world was never throttled — the planner scenario lost "
            "its contrast"
        )
    if plq8["throttled_replicas"] != 0:
        errors.append(
            f"int8 replica did not fit: {plq8['throttled_replicas']} "
            "replica(s) throttled"
        )
    if plq8["allocated_roles"] != plq8["target_roles"]:
        errors.append(
            "int8 allocation fell short of target: "
            f"{plq8['allocated_roles']} != {plq8['target_roles']}"
        )
    if plq8["slot_capacity"] < 1.9 * plbf["slot_capacity"]:
        errors.append(
            "planner did not see the doubled slot capacity: "
            f"{plq8['slot_capacity']} vs {plbf['slot_capacity']}"
        )
    for world in (plbf, plq8):
        if world["chips_allocated"] > world["chip_budget"]:
            errors.append(
                f"{world['dtype']} plan overran the chip budget: "
                f"{world['chips_allocated']} > {world['chip_budget']}"
            )
    return errors


if __name__ == "__main__":
    summary = run_sim()
    print(json.dumps(summary, indent=2, sort_keys=True))
    problems = check_invariants(summary)
    if problems:
        print("\nINVARIANT VIOLATIONS:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        sys.exit(1)
    print("\nall invariants held")
