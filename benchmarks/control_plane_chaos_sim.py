"""Deterministic control-plane chaos simulation — fake clocks, no JAX.

PR 3/5 proved the data path survives endpoint death; this sim proves the
CONTROL PLANE survives its own failure modes. Four phases drive the real
operator components (ModelReconciler, ModelClient, ActuationGovernor,
LeaderElection, RestKubeClient against FakeKubeApiServer) through
scheduled chaos and report the invariants the fault-tolerance work
promises:

  * split-brain: two operators share one store; leadership hands over
    mid-flight. ZERO duplicate actuations — the fenced (expired) leader
    creates and deletes nothing, ever;
  * corrupt/stale telemetry: a scale request driven by a corrupt fleet
    snapshot can never scale a model to zero, and healthy-pod deletions
    never exceed the per-model/cluster disruption budget per window;
    with the snapshot fully stale, static stability holds — zero
    healthy pods die;
  * API-server storms: the reconciler converges through a 409 conflict
    storm and a 429 rate-limit storm (Retry-After honored) within the
    client's bounded retry budget, over real HTTP;
  * crash/restart: an operator restart with stale telemetry rehydrates
    last-known-good state from cluster annotations and deletes ZERO
    healthy pods, and an in-flight repair backoff survives the restart
    (no duplicate repairs).

`tests/unit/test_control_plane.py` asserts these invariants in tier-1.
Run directly for the full report:

    python benchmarks/control_plane_chaos_sim.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.config import System
from kubeai_tpu.config.system import GovernorConfig
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.autoscaler.leader import LeaderElection
from kubeai_tpu.operator.controller import ModelReconciler
from kubeai_tpu.operator.governor import ActuationGovernor, NotLeader
from kubeai_tpu.operator.k8s import rest as rest_mod
from kubeai_tpu.operator.k8s.envtest import FakeKubeApiServer
from kubeai_tpu.operator.k8s.rest import RestKubeClient
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.testing.faults import ApiFault, ApiFaultPlan, FakeClock
from kubeai_tpu.testing.simkit import mark_all_ready, mk_model, pod_names


class StubFleet:
    """Controllable telemetry-coverage source with the aggregator's
    `model_coverage` contract: (coverage fraction, snapshot_fresh)."""

    def __init__(self, coverage: float = 1.0, fresh: bool = True):
        self.coverage = coverage
        self.fresh = fresh

    def model_coverage(self, model: str):
        return (self.coverage, self.fresh)


def _mk_model(
    store, name: str = "sim", replicas: int = 2, min_replicas: int = 0
) -> None:
    mk_model(
        store, name=name, replicas=replicas,
        autoscaling_disabled=False, min_replicas=min_replicas,
        scale_down_delay_seconds=0,
    )


# Ready flips and pod-name sets come from the shared sim scaffolding.
_mark_all_ready = mark_all_ready
_pod_names = pod_names


# ---- phase 1: dual-operator split-brain --------------------------------------


def run_split_brain_phase(replicas: int = 2) -> dict:
    """Two full reconcile stacks (A and B) on one store, each fenced by
    its own LeaderElection against the SAME Lease. A holds leadership
    and actuates; then A is partitioned (stops renewing), B takes the
    lease over, and both keep reconciling. Every create/delete is
    counted per operator: the handover must produce exactly one
    operator's worth of actuation — zero duplicates."""
    store = KubeStore()
    cfg = System()
    cfg.default_and_validate()
    mono = FakeClock(100.0)
    wall = FakeClock(1_000_000.0)

    def mk_operator(identity: str):
        metrics = Metrics()
        leader = LeaderElection(
            store, identity, lease_duration=15.0, retry_period=2.0,
            renew_deadline=10.0, metrics=metrics, clock=mono, wall=wall,
        )
        gov = ActuationGovernor(
            cfg=GovernorConfig(), leader=leader, store=store,
            metrics=metrics, clock=mono,
        )
        rec = ModelReconciler(
            store, cfg, metrics=metrics, clock=mono, wall=wall,
            governor=gov,
        )
        return leader, gov, rec, metrics

    leader_a, _gov_a, rec_a, metrics_a = mk_operator("op-a")
    leader_b, _gov_b, rec_b, metrics_b = mk_operator("op-b")

    def reconcile(rec) -> bool:
        """True when the pass actuated (not fenced)."""
        try:
            rec.reconcile("default", "sim")
            return True
        except NotLeader:
            return False

    fenced_attempts = 0
    _mk_model(store, replicas=replicas)
    # A wins the election and actuates; B is standby and must not.
    leader_a._try_acquire_or_renew()
    leader_b._try_acquire_or_renew()
    assert leader_a.is_leader and not leader_b.is_leader
    if not reconcile(rec_b):
        fenced_attempts += 1
    reconcile(rec_a)
    _mark_all_ready(store)
    reconcile(rec_a)

    # Partition A: it stops renewing. Clocks advance past the lease
    # duration; B takes over; A's local fence expires strictly before
    # B could have acquired (renew_deadline < lease_duration).
    mono.advance(16.0)
    wall.advance(16.0)
    leader_b._try_acquire_or_renew()
    handover_ok = leader_b.is_leader and not leader_a.fence_valid()

    # Both keep reconciling the converged world — and then a rollback
    # temptation: A (stale leader) also tries to act on a model whose
    # pods B already manages. A must be fenced on every attempt.
    for _ in range(3):
        if not reconcile(rec_a):
            fenced_attempts += 1
        reconcile(rec_b)

    def count(metrics, action):
        return metrics.governor_actions.get(action=action, model="sim")

    creates = count(metrics_a, "create") + count(metrics_b, "create")
    deletes = count(metrics_a, "delete") + count(metrics_b, "delete")
    return {
        "replicas_desired": replicas,
        "pods_final": len(_pod_names(store)),
        "creates_total": int(creates),
        "creates_by_stale_leader": int(count(metrics_a, "create")) if (
            not leader_a.is_leader
        ) else int(count(metrics_b, "create")),
        "deletes_total": int(deletes),
        "fenced_attempts": fenced_attempts,
        "fenced_writes_metric": int(
            metrics_a.leader_fenced_writes.get()
            + metrics_b.leader_fenced_writes.get()
        ),
        "handover_ok": bool(handover_ok),
        "duplicate_actuations": int(creates) - replicas + int(deletes),
    }


# ---- phase 2: corrupt / stale telemetry vs. budgets --------------------------


def run_telemetry_phase(
    start_replicas: int = 6,
    model_budget: int = 2,
    cluster_budget: int = 3,
    window_s: float = 60.0,
) -> dict:
    """A corrupt fleet snapshot (coverage ~0, but 'fresh') drives a
    scale-to-zero request: the governor must clamp it to one replica,
    and the reconciler's healthy-pod deletions must never exceed the
    per-model budget per window (convergence happens across windows).
    Then the snapshot goes fully STALE: static stability — zero healthy
    pods deleted. Finally two models under one cluster budget: their
    combined deletions per window respect the cluster bound."""
    cfg = System()
    cfg.default_and_validate()
    mono = FakeClock(100.0)
    wall = FakeClock(1_000_000.0)
    gcfg = GovernorConfig(
        window_seconds=window_s,
        model_disruption_budget=model_budget,
        cluster_disruption_budget=cluster_budget,
        min_telemetry_coverage=0.9,
    )

    store = KubeStore()
    fleet = StubFleet(coverage=1.0, fresh=True)
    metrics = Metrics()
    gov = ActuationGovernor(
        cfg=gcfg, fleet=fleet, store=store, metrics=metrics, clock=mono,
    )
    rec = ModelReconciler(
        store, cfg, metrics=metrics, clock=mono, wall=wall, governor=gov,
    )
    client = ModelClient(store)
    client.governor = gov

    _mk_model(store, replicas=start_replicas)
    rec.reconcile("default", "sim")
    _mark_all_ready(store)
    rec.reconcile("default", "sim")
    assert len(_pod_names(store)) == start_replicas

    # Corrupt snapshot: telemetry coverage collapses but the snapshot
    # itself is fresh — a plausible mass-scale-down trigger.
    fleet.coverage = 0.05
    applied = client.scale("sim", 0)
    spec_replicas = store.get("Model", "default", "sim")["spec"]["replicas"]

    deletions_per_window: list[int] = []
    pods_trace: list[int] = [len(_pod_names(store))]
    min_pods_seen = len(_pod_names(store))
    for _ in range(4):
        before = len(_pod_names(store))
        # Several reconcile passes within ONE window share the budget.
        for _ in range(3):
            rec.reconcile("default", "sim")
            min_pods_seen = min(min_pods_seen, len(_pod_names(store)))
        after = len(_pod_names(store))
        deletions_per_window.append(before - after)
        pods_trace.append(after)
        mono.advance(window_s + 1.0)
        wall.advance(window_s + 1.0)

    converged_pods = len(_pod_names(store))

    # Fully stale snapshot: static stability. Rebuild a fresh world and
    # try the same scale-down with telemetry gone dark.
    store2 = KubeStore()
    fleet2 = StubFleet(coverage=1.0, fresh=True)
    metrics2 = Metrics()
    mono2 = FakeClock(100.0)
    wall2 = FakeClock(1_000_000.0)
    gov2 = ActuationGovernor(
        cfg=gcfg, fleet=fleet2, store=store2, metrics=metrics2,
        clock=mono2,
    )
    rec2 = ModelReconciler(
        store2, cfg, metrics=metrics2, clock=mono2, wall=wall2,
        governor=gov2,
    )
    client2 = ModelClient(store2)
    client2.governor = gov2
    _mk_model(store2, replicas=start_replicas)
    rec2.reconcile("default", "sim")
    _mark_all_ready(store2)
    rec2.reconcile("default", "sim")
    fleet2.fresh = False  # aggregator dead: no snapshot at all
    stale_applied = client2.scale("sim", 1)
    stale_spec = store2.get("Model", "default", "sim")["spec"]["replicas"]
    for _ in range(3):
        rec2.reconcile("default", "sim")
    stale_pods = len(_pod_names(store2))
    static_holds = int(metrics2.governor_static_holds.get(model="sim"))

    # Cluster budget across two models in one window.
    store3 = KubeStore()
    fleet3 = StubFleet(coverage=1.0, fresh=True)
    metrics3 = Metrics()
    mono3 = FakeClock(100.0)
    wall3 = FakeClock(1_000_000.0)
    gov3 = ActuationGovernor(
        cfg=GovernorConfig(
            window_seconds=window_s,
            model_disruption_budget=10,
            cluster_disruption_budget=cluster_budget,
            min_telemetry_coverage=0.9,
        ),
        fleet=fleet3, store=store3, metrics=metrics3, clock=mono3,
    )
    rec3 = ModelReconciler(
        store3, cfg, metrics=metrics3, clock=mono3, wall=wall3,
        governor=gov3,
    )
    client3 = ModelClient(store3)
    client3.governor = gov3
    for name in ("ma", "mb"):
        _mk_model(store3, name=name, replicas=4)
        rec3.reconcile("default", name)
        _mark_all_ready(store3, name)
        rec3.reconcile("default", name)
    for name in ("ma", "mb"):
        client3.scale(name, 1)
        rec3.reconcile("default", name)
    cluster_deletions = sum(
        4 - len(_pod_names(store3, name)) for name in ("ma", "mb")
    )

    return {
        "start_replicas": start_replicas,
        "model_budget": model_budget,
        "cluster_budget": cluster_budget,
        "scale_to_zero_applied": applied,
        "spec_after_corrupt_scale": spec_replicas,
        "deletions_per_window": deletions_per_window,
        "pods_trace": pods_trace,
        "min_pods_seen": min_pods_seen,
        "converged_pods": converged_pods,
        "stale_scale_applied": stale_applied,
        "stale_spec_replicas": stale_spec,
        "stale_pods_final": stale_pods,
        "stale_static_holds": static_holds,
        "cluster_deletions_one_window": cluster_deletions,
    }


# ---- phase 3: API-server conflict + rate-limit storms ------------------------


def run_storm_phase(
    replicas: int = 2,
    conflict_storm: int = 3,
    storm_429: int = 2,
    storm_5xx: int = 2,
) -> dict:
    """The real reconciler drives the real RestKubeClient over real HTTP
    against the conformance fake API server, which 409s the first
    `conflict_storm` status PATCHes, 429s (with Retry-After) the first
    `storm_429` requests per pod verb, and 500s the first `storm_5xx`
    pod LISTs. The reconciler must converge to the desired replica set
    within the client's bounded retry budget — no retry exhaustion, no
    unbounded sleeps."""
    plan = ApiFaultPlan(
        [
            ApiFault(
                method="PATCH", plural="models", kind="http", status=409,
                reason="Conflict", message="injected conflict storm",
                start=1, end=conflict_storm,
            ),
            ApiFault(
                method="POST", plural="pods", kind="http", status=429,
                reason="TooManyRequests", headers={"Retry-After": "0.01"},
                start=1, end=storm_429,
            ),
            ApiFault(
                method="GET", plural="pods", watch=False, kind="http",
                status=500, reason="InternalError",
                start=1, end=storm_5xx,
            ),
        ]
    )
    srv = FakeKubeApiServer(fault_plan=plan)
    delays: list[float] = []
    client = RestKubeClient(
        srv.url, token="t", max_attempts=5,
        backoff_base=0.01, backoff_max=0.05,
    )
    client.metrics = Metrics()
    client._sleep = lambda s: delays.append(s)
    prev_jitter = rest_mod._jitter
    rest_mod._jitter = lambda: 1.0  # deterministic backoff
    try:
        cfg = System()
        cfg.default_and_validate()
        rec = ModelReconciler(client, cfg, metrics=Metrics())
        _mk_model(client, replicas=replicas)
        rec.reconcile("default", "sim")
        pods = len(_pod_names(client))
    finally:
        rest_mod._jitter = prev_jitter
        srv.close()
    m = client.metrics
    return {
        "replicas_desired": replicas,
        "pods_final": pods,
        "retries_conflict": int(
            m.kubeclient_retries.get(verb="PATCH", reason="conflict")
        ),
        "retries_429": int(
            m.kubeclient_retries.get(verb="POST", reason="429")
        ),
        "retries_5xx": int(
            m.kubeclient_retries.get(verb="GET", reason="5xx")
        ),
        "retry_exhausted": int(
            sum(
                m.kubeclient_retry_exhausted.get(verb=v)
                for v in ("GET", "POST", "PUT", "PATCH", "DELETE")
            )
        ),
        "sleeps": delays,
        "max_sleep_s": max(delays, default=0.0),
        "backoff_cap_s": 0.05,
        "retry_after_honored": 0.01 in delays,
    }


# ---- phase 4: operator crash / restart ---------------------------------------


def run_restart_phase(replicas: int = 3) -> dict:
    """Operator 1 runs a healthy model (telemetry fresh), applying a
    scale and recording last-known-good state on the cluster; it also
    starts a repair-backoff streak. Then it CRASHES — every in-memory
    structure is gone. Operator 2 boots against the same store with
    telemetry now STALE: it must rehydrate last-known-good from
    annotations, hold all scale-downs, delete zero healthy pods, and
    honor the persisted repair backoff instead of issuing a duplicate
    repair."""
    cfg = System()
    cfg.default_and_validate()
    gcfg = GovernorConfig(min_telemetry_coverage=0.9)
    store = KubeStore()
    wall = FakeClock(1_000_000.0)

    # ---- operator 1 (healthy life) ----
    mono1 = FakeClock(100.0)
    fleet1 = StubFleet(coverage=1.0, fresh=True)
    metrics1 = Metrics()
    gov1 = ActuationGovernor(
        cfg=gcfg, fleet=fleet1, store=store, metrics=metrics1, clock=mono1,
    )
    rec1 = ModelReconciler(
        store, cfg, metrics=metrics1, clock=mono1, wall=wall, governor=gov1,
    )
    client1 = ModelClient(store)
    client1.governor = gov1
    _mk_model(store, replicas=1)
    client1.scale("sim", replicas)  # healthy apply → lkg annotation
    rec1.reconcile("default", "sim")
    _mark_all_ready(store)
    rec1.reconcile("default", "sim")

    # Start a repair streak: one pod breaks; op1 repairs it (streak=1,
    # persisted), and its replacement breaks again just before the crash.
    victim = sorted(_pod_names(store))[0]
    pod = store.get("Pod", "default", victim)
    pod["status"] = {
        "phase": "Failed", "reason": "Preempted",
        "conditions": [{"type": "Ready", "status": "False"}],
    }
    store.update(pod)
    rec1.reconcile("default", "sim")
    repairs_op1 = int(
        metrics1.controller_pod_replacements.get(
            model="sim", reason="SpotPreemption"
        )
    )
    _mark_all_ready(store)
    new_victim = sorted(_pod_names(store))[0]
    pod = store.get("Pod", "default", new_victim)
    pod["status"] = {
        "phase": "Failed", "reason": "Preempted",
        "conditions": [{"type": "Ready", "status": "False"}],
    }
    store.update(pod)
    wall.advance(1.0)

    # ---- CRASH: operator 2 boots; telemetry is stale ----
    mono2 = FakeClock(5000.0)  # fresh process: unrelated monotonic origin
    fleet2 = StubFleet(coverage=0.0, fresh=False)
    metrics2 = Metrics()
    gov2 = ActuationGovernor(
        cfg=gcfg, fleet=fleet2, store=store, metrics=metrics2, clock=mono2,
    )
    rehydrated = gov2.rehydrate()
    rec2 = ModelReconciler(
        store, cfg, metrics=metrics2, clock=mono2, wall=wall, governor=gov2,
    )
    client2 = ModelClient(store)
    client2.governor = gov2

    healthy_before = _pod_names(store) - {new_victim}
    # A cold autoscaler (empty moving average) would want zero.
    client2.scale("sim", 0)
    rec2.reconcile("default", "sim")
    repairs_immediately_after_restart = int(
        metrics2.controller_pod_replacements.get(
            model="sim", reason="SpotPreemption"
        )
    )
    healthy_after = _pod_names(store) - {new_victim}
    spec_after = store.get("Model", "default", "sim")["spec"]["replicas"]

    # Past the persisted backoff the repair proceeds (still zero healthy
    # deletions — repair is exempt from budgets but not from sanity).
    mono2.advance(60.0)
    wall.advance(60.0)
    rec2.reconcile("default", "sim")
    repairs_after_backoff = int(
        metrics2.controller_pod_replacements.get(
            model="sim", reason="SpotPreemption"
        )
    )
    healthy_deleted = len(healthy_before - _pod_names(store))
    return {
        "replicas": replicas,
        "lkg_rehydrated_models": rehydrated,
        "lkg_entry": gov2._lkg.get("sim"),
        "repairs_op1": repairs_op1,
        "repairs_immediately_after_restart": repairs_immediately_after_restart,
        "repairs_after_backoff": repairs_after_backoff,
        "healthy_pods_deleted_after_restart": healthy_deleted,
        "spec_after_restart_scale_attempt": spec_after,
        "budgeted_deletes_after_restart": int(
            metrics2.governor_actions.get(action="delete", model="sim")
        ),
    }


# ---- harness -----------------------------------------------------------------


def run_sim(**kw) -> dict:
    return {
        "split_brain": run_split_brain_phase(
            **{k: v for k, v in kw.items() if k in ("replicas",)}
        ),
        "telemetry": run_telemetry_phase(),
        "storms": run_storm_phase(),
        "restart": run_restart_phase(),
    }


def check_invariants(summary: dict) -> list[str]:
    """Returns a list of violated invariants (empty = all hold)."""
    errors: list[str] = []
    sb = summary["split_brain"]
    if not sb["handover_ok"]:
        errors.append("split-brain: leadership handover did not complete")
    if sb["duplicate_actuations"] != 0:
        errors.append(
            f"split-brain: {sb['duplicate_actuations']} duplicate "
            "actuation(s) — a fenced operator wrote"
        )
    if sb["creates_by_stale_leader"] != 0:
        errors.append(
            "split-brain: the non-leader/stale operator created pods"
        )
    if sb["pods_final"] != sb["replicas_desired"]:
        errors.append(
            f"split-brain: {sb['pods_final']} pods != desired "
            f"{sb['replicas_desired']}"
        )
    if sb["fenced_attempts"] == 0 or sb["fenced_writes_metric"] == 0:
        errors.append("split-brain: fencing never fired (sim inert)")

    tl = summary["telemetry"]
    if tl["spec_after_corrupt_scale"] < 1:
        errors.append(
            "telemetry: a corrupt snapshot scaled the model to zero"
        )
    if tl["min_pods_seen"] < 1:
        errors.append("telemetry: the pod set hit zero under corrupt scale")
    if any(d > tl["model_budget"] for d in tl["deletions_per_window"]):
        errors.append(
            "telemetry: per-window deletions "
            f"{tl['deletions_per_window']} exceed the model budget "
            f"{tl['model_budget']}"
        )
    if tl["converged_pods"] != 1:
        errors.append(
            f"telemetry: converged at {tl['converged_pods']} pods, want 1 "
            "(budget must rate-limit, not block forever)"
        )
    if tl["stale_pods_final"] != tl["start_replicas"]:
        errors.append(
            "telemetry: static stability failed — stale snapshot deleted "
            f"{tl['start_replicas'] - tl['stale_pods_final']} pod(s)"
        )
    if tl["stale_spec_replicas"] != tl["start_replicas"]:
        errors.append(
            "telemetry: a stale snapshot changed spec.replicas "
            f"({tl['stale_spec_replicas']})"
        )
    if tl["stale_static_holds"] == 0:
        errors.append("telemetry: static-stability hold never fired")
    if tl["cluster_deletions_one_window"] > tl["cluster_budget"]:
        errors.append(
            "telemetry: cluster-wide deletions "
            f"{tl['cluster_deletions_one_window']} exceed the cluster "
            f"budget {tl['cluster_budget']}"
        )

    st = summary["storms"]
    if st["pods_final"] != st["replicas_desired"]:
        errors.append(
            f"storms: reconciler did not converge ({st['pods_final']} "
            f"pods != {st['replicas_desired']})"
        )
    if st["retry_exhausted"] != 0:
        errors.append(
            f"storms: {st['retry_exhausted']} request(s) exhausted the "
            "retry budget"
        )
    if not (st["retries_conflict"] and st["retries_429"] and st["retries_5xx"]):
        errors.append("storms: a storm never fired (sim inert)")
    if st["max_sleep_s"] > st["backoff_cap_s"]:
        errors.append(
            f"storms: a backoff sleep ({st['max_sleep_s']}s) exceeded "
            f"the cap ({st['backoff_cap_s']}s)"
        )
    if not st["retry_after_honored"]:
        errors.append("storms: the 429 Retry-After header was not honored")

    rs = summary["restart"]
    if rs["healthy_pods_deleted_after_restart"] != 0:
        errors.append(
            "restart: "
            f"{rs['healthy_pods_deleted_after_restart']} healthy pod(s) "
            "deleted after operator crash/restart"
        )
    if rs["budgeted_deletes_after_restart"] != 0:
        errors.append("restart: budgeted deletions fired while blind")
    if rs["spec_after_restart_scale_attempt"] != rs["replicas"]:
        errors.append(
            "restart: a blind restart changed spec.replicas to "
            f"{rs['spec_after_restart_scale_attempt']}"
        )
    if rs["lkg_rehydrated_models"] < 1 or rs["lkg_entry"] != {
        "replicas": rs["replicas"]
    }:
        errors.append(
            f"restart: last-known-good not rehydrated ({rs['lkg_entry']})"
        )
    if rs["repairs_immediately_after_restart"] != 0:
        errors.append(
            "restart: duplicate repair issued inside the persisted "
            "backoff window"
        )
    if rs["repairs_after_backoff"] < 1:
        errors.append(
            "restart: the repair never proceeded after the backoff"
        )
    return errors


def main() -> int:
    summary = run_sim()
    errors = check_invariants(summary)
    print(json.dumps({"summary": summary, "violations": errors}, indent=2))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
