"""Multi-turn chat load generator — the reference's headline benchmark
client (reference: benchmarks/chat-py/benchmark_serving.py + benchmarks/
multi-turn-chat-go): N concurrent conversation threads, each holding a
growing message history (shared prefix per thread — what PrefixHash
exploits), streaming requests, reporting TTFT / ITL / token throughput.

Usage:
  python benchmarks/multi_turn_chat.py --base-url http://HOST:PORT/openai \
      --model MODEL --threads 32 --turns 4 --max-tokens 64

Prints a JSON report (mean/p50/p90 TTFT ms, mean ITL ms, output tok/s).
"""

from __future__ import annotations

import argparse
import json
import random
import statistics
import string
import threading
import time
import urllib.request


def _rand_text(rng: random.Random, words: int) -> str:
    return " ".join(
        "".join(rng.choices(string.ascii_lowercase, k=rng.randint(3, 9)))
        for _ in range(words)
    )


def run_conversation(base_url, model, turns, max_tokens, seed, results, lock):
    rng = random.Random(seed)
    messages = [
        {"role": "system", "content": f"conversation-{seed}: " + _rand_text(rng, 30)}
    ]
    for _turn in range(turns):
        messages.append({"role": "user", "content": _rand_text(rng, 20)})
        body = json.dumps(
            {
                "model": model,
                "messages": messages,
                "max_tokens": max_tokens,
                "temperature": 0.7,
                "stream": True,
            }
        ).encode()
        req = urllib.request.Request(
            f"{base_url}/v1/chat/completions",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        ttft = None
        chunk_times = []
        text_parts = []
        try:
            with urllib.request.urlopen(req, timeout=600) as resp:
                for line in resp:
                    line = line.strip()
                    if not line.startswith(b"data: ") or line == b"data: [DONE]":
                        continue
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    chunk_times.append(now)
                    try:
                        ev = json.loads(line[len(b"data: "):])
                        delta = ev["choices"][0].get("delta", {}).get(
                            "content"
                        ) or ev["choices"][0].get("text", "")
                        if delta:
                            text_parts.append(delta)
                    except (json.JSONDecodeError, KeyError, IndexError):
                        pass
        except OSError as e:
            with lock:
                results["errors"] += 1
            return
        text = "".join(text_parts)
        messages.append({"role": "assistant", "content": text})
        itls = [
            b - a for a, b in zip(chunk_times, chunk_times[1:])
        ]
        with lock:
            if ttft is not None:
                results["ttft"].append(ttft)
            results["itl"].extend(itls)
            results["out_chars"] += len(text)
            results["requests"] += 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-url", default="http://127.0.0.1:8000/openai")
    ap.add_argument("--model", required=True)
    ap.add_argument("--threads", type=int, default=16)
    ap.add_argument("--turns", type=int, default=4)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    results = {"ttft": [], "itl": [], "out_chars": 0, "requests": 0, "errors": 0}
    lock = threading.Lock()
    t0 = time.perf_counter()
    threads = [
        threading.Thread(
            target=run_conversation,
            args=(args.base_url, args.model, args.turns, args.max_tokens,
                  args.seed * 1000 + i, results, lock),
        )
        for i in range(args.threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    def pct(xs, p):
        if not xs:
            return None
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    report = {
        "requests": results["requests"],
        "errors": results["errors"],
        "wall_s": round(wall, 2),
        "mean_ttft_ms": round(statistics.mean(results["ttft"]) * 1e3, 2)
        if results["ttft"] else None,
        "p50_ttft_ms": round(pct(results["ttft"], 0.5) * 1e3, 2)
        if results["ttft"] else None,
        "p90_ttft_ms": round(pct(results["ttft"], 0.9) * 1e3, 2)
        if results["ttft"] else None,
        "mean_itl_ms": round(statistics.mean(results["itl"]) * 1e3, 2)
        if results["itl"] else None,
        "output_chars_per_s": round(results["out_chars"] / wall, 1),
    }
    print(json.dumps(report))


if __name__ == "__main__":
    main()
