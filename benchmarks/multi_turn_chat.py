"""Multi-turn chat load generator — the reference's headline benchmark
client (reference: benchmarks/chat-py/benchmark_serving.py + benchmarks/
multi-turn-chat-go): N concurrent conversation threads, each holding a
growing message history (shared prefix per thread — what PrefixHash
exploits), streaming requests, reporting TTFT / ITL / token throughput.

Usage:
  python benchmarks/multi_turn_chat.py --base-url http://HOST:PORT/openai \
      --model MODEL --threads 32 --turns 4 --max-tokens 64

Prints a JSON report (mean/p50/p90 TTFT ms, mean ITL ms, output tok/s).

A/B mode for the cluster KV-sharing tier: point `--ab-base-url` at a
second, sharing-disabled deployment of the same model and the harness
replays the IDENTICAL seeded workload against both fleets back to back.
With `--engine-urls` / `--ab-engine-urls` (comma-separated direct
engine addresses) it also scrapes each fleet's engine /metrics before
and after its run and reports FLEET PREFILL TOKENS — prompt tokens
actually prefilled, net of prefix-cache hits — plus the peer-fetch
counters, the numbers the sharing tier exists to move:

  python benchmarks/multi_turn_chat.py --model M \
      --base-url http://sharing-lb:8000/openai \
      --engine-urls http://eng-a:9000,http://eng-b:9000 \
      --ab-base-url http://baseline-lb:8000/openai \
      --ab-engine-urls http://base-a:9000,http://base-b:9000
"""

from __future__ import annotations

import argparse
import json
import random
import statistics
import string
import threading
import time
import urllib.request


def _rand_text(rng: random.Random, words: int) -> str:
    return " ".join(
        "".join(rng.choices(string.ascii_lowercase, k=rng.randint(3, 9)))
        for _ in range(words)
    )


def run_conversation(base_url, model, turns, max_tokens, seed, results, lock):
    rng = random.Random(seed)
    messages = [
        {"role": "system", "content": f"conversation-{seed}: " + _rand_text(rng, 30)}
    ]
    for _turn in range(turns):
        messages.append({"role": "user", "content": _rand_text(rng, 20)})
        body = json.dumps(
            {
                "model": model,
                "messages": messages,
                "max_tokens": max_tokens,
                "temperature": 0.7,
                "stream": True,
            }
        ).encode()
        req = urllib.request.Request(
            f"{base_url}/v1/chat/completions",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        ttft = None
        chunk_times = []
        text_parts = []
        try:
            with urllib.request.urlopen(req, timeout=600) as resp:
                for line in resp:
                    line = line.strip()
                    if not line.startswith(b"data: ") or line == b"data: [DONE]":
                        continue
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    chunk_times.append(now)
                    try:
                        ev = json.loads(line[len(b"data: "):])
                        delta = ev["choices"][0].get("delta", {}).get(
                            "content"
                        ) or ev["choices"][0].get("text", "")
                        if delta:
                            text_parts.append(delta)
                    except (json.JSONDecodeError, KeyError, IndexError):
                        pass
        except OSError as e:
            with lock:
                results["errors"] += 1
            return
        text = "".join(text_parts)
        messages.append({"role": "assistant", "content": text})
        itls = [
            b - a for a, b in zip(chunk_times, chunk_times[1:])
        ]
        with lock:
            if ttft is not None:
                results["ttft"].append(ttft)
            results["itl"].extend(itls)
            results["out_chars"] += len(text)
            results["requests"] += 1


# Engine counters the A/B report diffs per arm (summed across engines
# and label sets): prompt tokens minus prefix-cache-hit tokens = tokens
# actually prefilled; the kv_fetch family sizes the peer-transfer work
# that replaced recompute.
_FLEET_COUNTERS = (
    "kubeai_engine_prompt_tokens_total",
    "kubeai_engine_prefix_cached_tokens_total",
    "kubeai_kv_fetch_attempts_total",
    "kubeai_kv_fetch_bytes_total",
    "kubeai_kv_fetch_failures_total",
)


def _scrape_counters(engine_urls: list[str]) -> dict[str, float]:
    totals = dict.fromkeys(_FLEET_COUNTERS, 0.0)
    for url in engine_urls:
        req = urllib.request.Request(f"{url.rstrip('/')}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            for line in resp.read().decode("utf-8", "replace").splitlines():
                if line.startswith("#"):
                    continue
                for name in _FLEET_COUNTERS:
                    if line.startswith(name) and (
                        line[len(name)] in ("{", " ")
                    ):
                        try:
                            totals[name] += float(line.rsplit(" ", 1)[1])
                        except (ValueError, IndexError):
                            pass
    return totals


def _pct(xs, p):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(p * len(xs)))]


def run_arm(base_url: str, engine_urls: list[str], args) -> dict:
    """One load run against one fleet. The same --seed produces the
    byte-identical conversation workload on every arm."""
    before = _scrape_counters(engine_urls) if engine_urls else None
    results = {"ttft": [], "itl": [], "out_chars": 0, "requests": 0, "errors": 0}
    lock = threading.Lock()
    t0 = time.perf_counter()
    threads = [
        threading.Thread(
            target=run_conversation,
            args=(base_url, args.model, args.turns, args.max_tokens,
                  args.seed * 1000 + i, results, lock),
        )
        for i in range(args.threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    report = {
        "base_url": base_url,
        "requests": results["requests"],
        "errors": results["errors"],
        "wall_s": round(wall, 2),
        "mean_ttft_ms": round(statistics.mean(results["ttft"]) * 1e3, 2)
        if results["ttft"] else None,
        "p50_ttft_ms": round(_pct(results["ttft"], 0.5) * 1e3, 2)
        if results["ttft"] else None,
        "p90_ttft_ms": round(_pct(results["ttft"], 0.9) * 1e3, 2)
        if results["ttft"] else None,
        "mean_itl_ms": round(statistics.mean(results["itl"]) * 1e3, 2)
        if results["itl"] else None,
        "output_chars_per_s": round(results["out_chars"] / wall, 1),
    }
    if before is not None:
        after = _scrape_counters(engine_urls)
        delta = {k: after[k] - before[k] for k in _FLEET_COUNTERS}
        prompt = delta["kubeai_engine_prompt_tokens_total"]
        cached = delta["kubeai_engine_prefix_cached_tokens_total"]
        report["fleet_prompt_tokens"] = int(prompt)
        report["fleet_prefix_cached_tokens"] = int(cached)
        report["fleet_prefill_tokens"] = int(prompt - cached)
        report["kv_fetch_attempts"] = int(
            delta["kubeai_kv_fetch_attempts_total"]
        )
        report["kv_fetch_bytes"] = int(delta["kubeai_kv_fetch_bytes_total"])
        report["kv_fetch_failures"] = int(
            delta["kubeai_kv_fetch_failures_total"]
        )
    return report


def _urls(csv: str) -> list[str]:
    return [u.strip() for u in csv.split(",") if u.strip()]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-url", default="http://127.0.0.1:8000/openai")
    ap.add_argument("--model", required=True)
    ap.add_argument("--threads", type=int, default=16)
    ap.add_argument("--turns", type=int, default=4)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--engine-urls", default="",
        help="comma-separated direct engine addresses behind --base-url; "
        "enables the fleet prefill-token / kv-fetch counter diff",
    )
    ap.add_argument(
        "--ab-base-url", default="",
        help="second fleet (sharing disabled) to replay the identical "
        "seeded workload against — enables the A/B report",
    )
    ap.add_argument(
        "--ab-engine-urls", default="",
        help="engine addresses behind --ab-base-url",
    )
    args = ap.parse_args()

    sharing = run_arm(args.base_url, _urls(args.engine_urls), args)
    if not args.ab_base_url:
        print(json.dumps(sharing))
        return

    baseline = run_arm(args.ab_base_url, _urls(args.ab_engine_urls), args)
    report = {"sharing": sharing, "baseline": baseline}
    if "fleet_prefill_tokens" in sharing and "fleet_prefill_tokens" in baseline:
        saved = (
            baseline["fleet_prefill_tokens"] - sharing["fleet_prefill_tokens"]
        )
        report["prefill_tokens_saved"] = saved
        report["prefill_tokens_saved_pct"] = round(
            100.0 * saved / baseline["fleet_prefill_tokens"], 2
        ) if baseline["fleet_prefill_tokens"] else None
    if sharing["mean_ttft_ms"] and baseline["mean_ttft_ms"]:
        report["ttft_delta_ms"] = round(
            sharing["mean_ttft_ms"] - baseline["mean_ttft_ms"], 2
        )
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
