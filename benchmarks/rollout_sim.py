#!/usr/bin/env python
"""Deterministic progressive-rollout simulation — no JAX, no sockets.

A model opted into `rollout: {strategy: canary}` takes a spec edit on a
fake clock, and the REAL control plane carries it end to end: the
`ModelReconciler` renders the new pod hash, `RolloutController` paces
`calculate_pod_plan(max_new=...)` through canary -> ramp -> complete,
the `LoadBalancer` enforces the canary's traffic share at routing time,
scripted per-endpoint TTFT expositions feed the real
`FleetStateAggregator` (whose per-version split is the judge's
evidence), every step asks the real `ActuationGovernor`, and a judged
failure pins the last-good hash back via `kubeai.org/rollout-pinned-hash`
while the real `FlightRecorder` dumps a replayable `rollout_rollback`
incident bundle.

Four scenarios, each a one-event `bad_rollout` chaos trace:

  clean     — the new revision is healthy: the rollout completes, every
              replica ends on the new hash, zero rollbacks.
  latency   — the new revision's TTFT is regressed: the comparative
              judge condemns it, and the rollback lands before the bad
              version ever serves more than its canary traffic share.
  crashloop — the new revision never becomes Ready: the judge's
              crashloop verdict rolls back a version that never served
              a single request.
  group     — a multi-host model (slice groups) rolls ONE group per
              stepSeconds, each group recreated atomically.

Invariants (asserted in tier-1 by tests/unit/test_rollout_sim.py):

  * zero client-visible stream errors in every scenario — old-hash
    capacity keeps serving throughout;
  * the bad version's measured traffic share never exceeds
    canaryPercent + epsilon (and a crash-looping canary serves NOTHING);
  * auto-rollback lands within judge.windowSeconds + stepSeconds +
    slack of the bad revision shipping;
  * the clean rollout reaches 100% new-hash and forgets itself;
  * worlds the rollout plane must NOT touch (single replica, or no
    `rollout:` block) produce byte-identical pod plans with and without
    the controller wired — the classic surge path is regression-pinned;
  * dump -> replay is byte-identical, for both the run log and the
    `rollout_rollback` incident bundle (which is what
    `python -m benchmarks.gameday_sim --replay <bundle>` dispatches to
    when the bundle header names this sim).

Run directly for a human-readable report:

    python benchmarks/rollout_sim.py [--scenario all|clean|latency|...]
    python benchmarks/rollout_sim.py --scenario latency --dump-bundle /tmp/rb.jsonl
    python -m benchmarks.gameday_sim --replay /tmp/rb.jsonl
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.config import System
from kubeai_tpu.config.system import GovernorConfig
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import Rollout, RolloutJudge
from kubeai_tpu.fleet import FleetStateAggregator
from kubeai_tpu.metrics import Metrics, flightrecorder
from kubeai_tpu.metrics.flightrecorder import FlightRecorder
from kubeai_tpu.operator import slicegroup
from kubeai_tpu.operator.controller import ModelReconciler
from kubeai_tpu.operator.governor import ActuationGovernor
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.operator.rollout import RolloutController
from kubeai_tpu.routing.health import OUTCOME_SUCCESS
from kubeai_tpu.routing.loadbalancer import (
    Group,
    LoadBalancer,
    LoadBalancerTimeout,
    NoHealthyEndpoints,
)
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.testing.chaos import (
    CONTINUOUS,
    EV_BAD_ROLLOUT,
    TERMINAL,
    GameDayEvent,
    GameDayLog,
    GameDayTrace,
    Invariant,
    InvariantChecker,
)
from kubeai_tpu.testing.clock import FakeClock
from kubeai_tpu.testing.simkit import mk_model

SIM_NAME = "rollout_sim"
MODEL = "m0"
REPLICAS = 4
CANARY_PERCENT = 25.0          # -> a one-replica canary step
STEP_SECONDS = 6.0
JUDGE_WINDOW_S = 4.0
TTFT_RATIO = 1.5

TICK_S = 1.0
WARMUP_TICKS = 8               # steady state before the trace's t=0
BOOT_TICKS = 2                 # created pod -> Ready
MUTATE_T = 2.0                 # when the bad revision ships (rel time)
REQS_PER_TICK = 20             # synthetic client picks through the LB
OBS_PER_TICK = 6               # TTFT observations per endpoint per tick
HEALTHY_TTFT = 0.2             # lands in the 0.25 bucket (p95 0.25s)
REGRESSED_TTFT = 0.8           # lands in the 1.0 bucket (p95 1.0s)

SHARE_EPS = 0.05               # integer-rounding slack on the share cap
# Mutation -> rollback deadline: one judge window after the canary
# step, plus the step dwell, plus boot/scrape/tick latency slack.
ROLLBACK_SLACK_S = 8.0
ROLLBACK_BOUND_S = JUDGE_WINDOW_S + STEP_SECONDS + ROLLBACK_SLACK_S

# Multi-host (slice group) scenario: two 2-host groups on 4x4 slices.
ACCEL = "tpu-v5-lite-podslice"
TOPOLOGY = "4x4"
GROUP_PROFILE = "google-tpu-v5e-4x4:8"
NUM_HOSTS = 2
CHIPS_PER_HOST = 8
GROUP_REPLICAS = 2
SLICES = 3

SCENARIOS = ("clean", "latency", "crashloop", "group")
DEFAULT_TICKS = {"clean": 45, "latency": 30, "crashloop": 30, "group": 30}


def scenario_trace(scenario: str, seed: int = 0) -> GameDayTrace:
    """One bad_rollout event: a spec revision ships at MUTATE_T. The
    mode rides the event so a dumped log replays the same failure."""
    return GameDayTrace([
        GameDayEvent(MUTATE_T, EV_BAD_ROLLOUT, MODEL,
                     {"mode": scenario}),
    ], seed=seed)


def _rollout_spec() -> Rollout:
    return Rollout(
        strategy="canary",
        canary_percent=CANARY_PERCENT,
        step_seconds=STEP_SECONDS,
        judge=RolloutJudge(
            window_seconds=JUDGE_WINDOW_S,
            ttft_p95_ratio=TTFT_RATIO,
            max_breaker_trips=0,
        ),
    )


def _node(name: str) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {
                "cloud.google.com/gke-tpu-accelerator": ACCEL,
                "cloud.google.com/gke-tpu-topology": TOPOLOGY,
            },
        },
        "status": {"allocatable": {"google.com/tpu": str(CHIPS_PER_HOST)}},
    }


def _pod_hash_of(pod: dict) -> str:
    return pod["metadata"].get("labels", {}).get(md.POD_HASH_LABEL) or ""


class RolloutWorld:
    """Real control plane + scripted engines around one rolling model.
    The kubelet is deliberately dumb: assign an IP, flip Ready after
    BOOT_TICKS — and in the crashloop scenario, never boot a new-hash
    pod at all."""

    def __init__(self, scenario: str, ticks: int, seed: int = 0):
        if scenario not in SCENARIOS:
            raise ValueError(f"unknown scenario {scenario!r}")
        self.scenario = scenario
        self.multi = scenario == "group"
        self.replicas = GROUP_REPLICAS if self.multi else REPLICAS
        # pods per replica differs: a slice-group replica is NUM_HOSTS pods
        self.expected_pods = self.replicas * (NUM_HOSTS if self.multi else 1)
        self.ticks = int(ticks)
        self.seed = int(seed)
        self.trace = scenario_trace(scenario, seed)
        self.clock = FakeClock(1000.0)
        self.wall = FakeClock(1_000_000.0)
        self.tick_no = 0
        self.t0 = self.clock() + WARMUP_TICKS * TICK_S

        self._name_counter = itertools.count()
        self.store = KubeStore(
            namegen=lambda: f"{next(self._name_counter):06d}"
        )
        self.metrics = Metrics()

        cfg = System()
        cfg.fixed_self_metric_addrs = ["self:1"]
        cfg.default_and_validate()
        self.cfg = cfg

        if self.multi:
            for s in range(SLICES):
                for h in range(NUM_HOSTS):
                    self.store.create(_node(f"node-s{s}-h{h}"))
            mk_model(
                self.store, MODEL, replicas=self.replicas,
                resource_profile=GROUP_PROFILE,
                autoscaling_disabled=True, rollout=_rollout_spec(),
            )
        else:
            mk_model(
                self.store, MODEL, replicas=self.replicas,
                autoscaling_disabled=True, rollout=_rollout_spec(),
            )

        self.lb = LoadBalancer(self.store, metrics=self.metrics)
        self.lb._groups[MODEL] = Group(
            metrics=self.metrics, model=MODEL, clock=self.clock
        )

        self.mc = ModelClient(self.store)
        self.aggregator = FleetStateAggregator(
            lb=self.lb, model_client=self.mc, store=self.store,
            metrics=self.metrics, interval_s=1.0, staleness_s=2.5,
            fetch_metrics=self.fetch_metrics, fetch_state=self.fetch_state,
            clock=self.clock,
        )

        gcfg = GovernorConfig(
            window_seconds=10.0,
            model_disruption_budget=6,
            cluster_disruption_budget=12,
            min_telemetry_coverage=0.9,
        )
        self.governor = ActuationGovernor(
            cfg=gcfg, fleet=self.aggregator, store=self.store,
            metrics=self.metrics, clock=self.clock,
        )

        self.recorder = FlightRecorder(
            clock=self.clock,
            tick_fn=lambda: self.tick_no,
            min_trigger_interval_s=300.0,
        )
        self.recorder.replay_context = {
            "sim": SIM_NAME, "seed": self.seed, "ticks": self.ticks,
            "scenario": scenario,
        }
        self.lb.set_recorder(self.recorder)

        self.reconciler = ModelReconciler(
            self.store, cfg, metrics=self.metrics, clock=self.clock,
            wall=self.wall, governor=self.governor,
        )
        self.rollout = RolloutController(
            store=self.store, lb=self.lb, fleet=self.aggregator,
            governor=self.governor, recorder=self.recorder,
            metrics=self.metrics, clock=self.clock,
        )
        self.reconciler.rollout = self.rollout

        # -- scripted data plane.
        self.addr_model: dict[str, str] = {}
        self.addr_hash: dict[str, str] = {}
        self.obs: dict[str, dict] = {}       # addr -> {"good","bad"}
        self.first_seen: dict[str, int] = {}
        self.ip_counter = 1

        # -- measured facts.
        self.mode: str | None = None         # set by the trace event
        self.good_hashes: set[str] = set()
        self.mutate_rel: float | None = None
        self.rollback_rel: float | None = None
        self.total_picks = 0
        self.bad_picks = 0
        self.client_errors = 0

        self.log = GameDayLog(
            self.trace, ticks,
            extra={"sim": SIM_NAME, "scenario": scenario, "seed": self.seed},
        )
        self.checker = InvariantChecker(
            invariants_for(scenario), log=self.log
        )

    # ---- time / telemetry ----------------------------------------------

    def rel_now(self) -> float:
        return self.clock() - self.t0

    def _regressed(self, addr: str) -> bool:
        return (
            self.mode == "latency"
            and self.addr_hash.get(addr, "") not in self.good_hashes
        )

    def fetch_metrics(self, addr: str, timeout: float = 5.0) -> str:
        rec = self.obs.get(addr)
        if rec is None:
            raise ConnectionError(f"injected: {addr} unreachable")
        good, bad = rec["good"], rec["bad"]
        total = good + bad
        ttft_sum = good * HEALTHY_TTFT + bad * REGRESSED_TTFT
        return "\n".join([
            "# TYPE kubeai_engine_ttft_seconds histogram",
            f'kubeai_engine_ttft_seconds_bucket{{le="0.25"}} {good}',
            f'kubeai_engine_ttft_seconds_bucket{{le="0.5"}} {good}',
            f'kubeai_engine_ttft_seconds_bucket{{le="1"}} {total}',
            f'kubeai_engine_ttft_seconds_bucket{{le="+Inf"}} {total}',
            f"kubeai_engine_ttft_seconds_count {total}",
            f"kubeai_engine_ttft_seconds_sum {ttft_sum}",
            "kubeai_engine_queue_depth 0.0",
            "kubeai_engine_queue_oldest_wait_seconds 0.0",
            "kubeai_engine_kv_cache_utilization 0.0",
            "kubeai_engine_slots_active 0.0",
            "kubeai_engine_slot_capacity 4.0",
            "kubeai_engine_active_requests 0.0",
        ]) + "\n"

    def fetch_state(self, addr: str, timeout: float = 5.0) -> dict:
        if addr not in self.obs:
            raise ConnectionError(f"injected: {addr} unreachable")
        return {"model": MODEL, "healthy": True}

    # ---- pod bookkeeping ------------------------------------------------

    def pods(self) -> list[dict]:
        return sorted(
            self.store.list("Pod", "default", {md.POD_MODEL_LABEL: MODEL}),
            key=lambda p: p["metadata"]["name"],
        )

    def _is_ready(self, pod: dict) -> bool:
        st = pod.get("status", {})
        if st.get("phase") == "Failed":
            return False
        return any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in st.get("conditions", [])
        )

    def pod_split(self) -> dict:
        """Counts the invariants and the log read every tick."""
        out = {"old": 0, "new": 0, "old_ready": 0, "new_ready": 0}
        for pod in self.pods():
            h = _pod_hash_of(pod)
            side = (
                "old" if not self.good_hashes or h in self.good_hashes
                else "new"
            )
            out[side] += 1
            if self._is_ready(pod):
                out[side + "_ready"] += 1
        return out

    def groups_not_ready(self) -> int:
        groups = slicegroup.group_pods(self.pods())
        return sum(
            1 for members in groups.values()
            if not slicegroup.group_ready(members, NUM_HOSTS)
        )

    # ---- the bad revision ----------------------------------------------

    def apply_event(self, ev: GameDayEvent) -> None:
        if ev.kind != EV_BAD_ROLLOUT:
            raise ValueError(f"rollout sim only speaks {EV_BAD_ROLLOUT!r}")
        self.mode = ev.params.get("mode", "latency")
        self.good_hashes = {_pod_hash_of(p) for p in self.pods()}
        self.mutate_rel = self.rel_now()
        obj = self.store.get("Model", "default", MODEL)
        env = dict(obj["spec"].get("env") or {})
        env["ROLLOUT_REV"] = "2"
        obj["spec"]["env"] = env
        self.store.update(obj)

    # ---- kubelet ---------------------------------------------------------

    def _kubelet(self) -> None:
        for pod in self.pods():
            st = pod.get("status", {})
            if st.get("podIP"):
                continue
            if st.get("reason") == "Preempted" or st.get("containerStatuses"):
                continue
            if (
                self.mode == "crashloop"
                and _pod_hash_of(pod) not in self.good_hashes
            ):
                continue  # the bad revision never comes up
            uid = pod["metadata"].get("uid") or pod["metadata"]["name"]
            born = self.first_seen.setdefault(uid, self.tick_no)
            if self.tick_no - born < BOOT_TICKS:
                continue
            ip = f"10.88.0.{self.ip_counter}"
            self.ip_counter += 1
            fresh = self.store.get("Pod", "default",
                                   pod["metadata"]["name"])
            fresh.setdefault("status", {})["podIP"] = ip
            fresh["status"]["phase"] = "Running"
            fresh["status"]["conditions"] = [
                {"type": "Ready", "status": "True"},
                {"type": "PodScheduled", "status": "True"},
            ]
            self.store.update(fresh)
            addr = f"{ip}:8000"
            self.addr_model[addr] = MODEL
            self.addr_hash[addr] = _pod_hash_of(pod)
            self.obs[addr] = {"good": 0, "bad": 0}

    def _advance_observations(self) -> None:
        """Every Ready endpoint observes OBS_PER_TICK requests' TTFT —
        regressed on new-hash endpoints in the latency scenario."""
        for pod in self.pods():
            ip = pod.get("status", {}).get("podIP")
            if not ip or not self._is_ready(pod):
                continue
            addr = f"{ip}:8000"
            rec = self.obs.get(addr)
            if rec is None:
                continue
            if self._regressed(addr):
                rec["bad"] += OBS_PER_TICK
            else:
                rec["good"] += OBS_PER_TICK

    # ---- client traffic --------------------------------------------------

    def _traffic(self) -> None:
        """REQS_PER_TICK synthetic picks through the real LB — this is
        where the canary share cap is MEASURED, from the outside."""
        group = self.lb.group(MODEL)
        dones = []
        for _ in range(REQS_PER_TICK):
            try:
                addr, done = group.get_best_addr("", "", "", timeout=0.01)
            except (NoHealthyEndpoints, LoadBalancerTimeout):
                self.client_errors += 1
                continue
            dones.append(done)
            if self.mutate_rel is not None:
                self.total_picks += 1
                if self.addr_hash.get(addr, "") not in self.good_hashes:
                    self.bad_picks += 1
        # Requests stay in flight for the rest of the tick so the
        # least-load pick actually spreads — otherwise every endpoint
        # sits at zero and the canary would never be measured.
        for done in dones:
            done(OUTCOME_SUCCESS)

    def bad_share(self) -> float:
        if not self.total_picks:
            return 0.0
        return self.bad_picks / self.total_picks

    # ---- the tick --------------------------------------------------------

    def tick(self) -> None:
        self.tick_no += 1
        self.clock.advance(TICK_S)
        self.wall.advance(TICK_S)
        rel = self.rel_now()

        for ev in self.trace.due(rel):
            self.apply_event(ev)
            self.log.event(self.tick_no, ev)
        self._kubelet()
        self.lb.sync_all()
        self._advance_observations()
        self.aggregator.collect()
        self.rollout.tick()
        self.reconciler.reconcile("default", MODEL)
        # The plan may have replaced pods after this tick's sync; the
        # routing view the traffic and invariants see must reflect it.
        self.lb.sync_all()
        if rel >= 0:
            self._traffic()

        if self.rollback_rel is None and any(
            inc["reason"] == flightrecorder.TRIGGER_ROLLBACK
            for inc in self.recorder.incidents
        ):
            self.rollback_rel = rel

        split = self.pod_split()
        self.log.obs(
            self.tick_no,
            t=round(rel, 3),
            pods=split,
            bad_share=round(self.bad_share(), 4),
            picks=self.total_picks,
            errors=self.client_errors,
            rollbacks=len([
                i for i in self.recorder.incidents
                if i["reason"] == flightrecorder.TRIGGER_ROLLBACK
            ]),
        )
        self.checker.check_continuous(self, self.tick_no, rel)

    def run(self) -> dict:
        for _ in range(WARMUP_TICKS + self.ticks):
            self.tick()
        self.checker.check_terminal(self, self.tick_no, self.rel_now())
        fv = self.checker.first_violation
        rollback_decisions = [
            e for e in self.recorder.events("rollout")
            if e["detail"].get("decision") == "rollback"
        ] if self.recorder.events("rollout") else []
        return {
            "sim": SIM_NAME,
            "scenario": self.scenario,
            "seed": self.seed,
            "ticks": self.ticks,
            "client_errors": self.client_errors,
            "bad_share": round(self.bad_share(), 4),
            "total_picks": self.total_picks,
            "bad_picks": self.bad_picks,
            "mutate_rel": self.mutate_rel,
            "rollback_rel": self.rollback_rel,
            "rollback": (
                {
                    "verdict": rollback_decisions[0]["detail"].get("verdict"),
                    "pinned": rollback_decisions[0]["detail"].get("pinned"),
                    "condemned": rollback_decisions[0]["detail"].get(
                        "condemned"
                    ),
                }
                if rollback_decisions else None
            ),
            "pods": self.pod_split(),
            "violations": [
                {"tick": v.tick, "t": v.t, "invariant": v.invariant,
                 "detail": v.detail}
                for v in self.checker.violations
            ],
            "first_violation": None if fv is None else {
                "tick": fv.tick, "t": fv.t, "invariant": fv.invariant,
                "detail": fv.detail,
            },
            "incidents": list(self.recorder.incidents),
            "log": self.log,
            "world": self,
        }


# ---- invariants --------------------------------------------------------------


def _inv_zero_stream_errors(world) -> str | None:
    if world.client_errors:
        return f"{world.client_errors} client pick(s) found no endpoint"
    return None


def _inv_share_bounded(world) -> str | None:
    """The bad version never exceeds its canary traffic share — and a
    crash-looping canary never serves at all."""
    if world.scenario == "crashloop":
        if world.bad_picks:
            return (
                f"{world.bad_picks} request(s) routed to a version that "
                "never became Ready"
            )
        return None
    cap = CANARY_PERCENT / 100.0 + SHARE_EPS
    if world.total_picks >= REQS_PER_TICK and world.bad_share() > cap:
        return (
            f"bad-version traffic share {world.bad_share():.3f} exceeds "
            f"canary cap {cap:.3f}"
        )
    return None


def _inv_single_group_in_flight(world) -> str | None:
    """Slice groups roll one at a time: at most one group may be
    partial/not-Ready at any tick (post-warmup)."""
    if world.rel_now() < 0:
        return None
    broken = world.groups_not_ready()
    if broken > 1:
        return f"{broken} slice groups simultaneously not Ready"
    return None


def _inv_rolled_back(world) -> str | None:
    """Terminal for the failing scenarios: the rollback landed in time
    and the fleet converged back onto the last-good hash."""
    if world.rollback_rel is None:
        return "the bad revision was never rolled back"
    lag = world.rollback_rel - world.mutate_rel
    if lag > ROLLBACK_BOUND_S:
        return (
            f"rollback took {lag:.1f}s > bound {ROLLBACK_BOUND_S:.1f}s "
            "after the bad revision shipped"
        )
    split = world.pod_split()
    if split["new"]:
        return f"{split['new']} condemned-hash pod(s) still present"
    if split["old_ready"] != world.expected_pods:
        return (
            f"{split['old_ready']}/{world.expected_pods} last-good pods "
            "Ready at end of run"
        )
    obj = world.store.get("Model", "default", MODEL)
    pin = (obj["metadata"].get("annotations") or {}).get(
        md.ROLLOUT_PINNED_HASH_ANNOTATION
    )
    if not pin:
        return "rollback left no pinned-hash annotation on the Model"
    return None


def _inv_completed(world) -> str | None:
    """Terminal for the healthy scenarios: the rollout finished — every
    replica on the new hash, no rollback, no lingering state."""
    if world.rollback_rel is not None:
        return "a healthy revision was rolled back"
    split = world.pod_split()
    if split["old"]:
        return f"{split['old']} old-hash pod(s) still present"
    if split["new_ready"] != world.expected_pods:
        return (
            f"{split['new_ready']}/{world.expected_pods} new-hash pods "
            "Ready at end of run"
        )
    state = world.rollout.state_payload()
    if state["rollouts"] or state["condemned"]:
        return f"rollout state not forgotten: {state}"
    return None


def _inv_groups_paced(world) -> str | None:
    """Terminal for the group scenario: one group_roll per group, each
    at least stepSeconds apart."""
    rolls = [
        e for e in world.recorder.events("rollout")
        if e["detail"].get("decision") == "group_roll"
    ]
    if len(rolls) != GROUP_REPLICAS:
        return (
            f"{len(rolls)} group roll(s) for {GROUP_REPLICAS} stale "
            "groups — want exactly one each"
        )
    times = [e["t"] for e in rolls]
    for a, b in zip(times, times[1:]):
        if b - a < STEP_SECONDS - 1e-6:
            return (
                f"group rolls {b - a:.1f}s apart — pacing floor is "
                f"{STEP_SECONDS:g}s"
            )
    return None


def invariants_for(scenario: str) -> tuple:
    invs = [
        Invariant("zero_stream_errors", _inv_zero_stream_errors, CONTINUOUS,
                  "clients never see an error while a rollout is judged"),
    ]
    if scenario in ("latency", "crashloop"):
        invs.append(Invariant(
            "canary_share_bounded", _inv_share_bounded, CONTINUOUS,
            "the bad version never exceeds its canary traffic share"))
        invs.append(Invariant(
            "rolled_back_in_time", _inv_rolled_back, TERMINAL,
            "auto-rollback lands within window + step + slack"))
    if scenario in ("clean", "group"):
        invs.append(Invariant(
            "rollout_completes", _inv_completed, TERMINAL,
            "a healthy revision reaches 100% new-hash"))
    if scenario == "group":
        invs.append(Invariant(
            "single_group_in_flight", _inv_single_group_in_flight,
            CONTINUOUS, "slice groups roll one at a time"))
        invs.append(Invariant(
            "groups_paced", _inv_groups_paced, TERMINAL,
            "one atomic roll per group, stepSeconds apart"))
    return tuple(invs)


# ---- entry points ------------------------------------------------------------


def run_sim(scenario: str, seed: int = 0, ticks: int | None = None) -> dict:
    return RolloutWorld(
        scenario, ticks if ticks is not None else DEFAULT_TICKS[scenario],
        seed=seed,
    ).run()


def run_all(seed: int = 0) -> dict:
    return {s: run_sim(s, seed=seed) for s in SCENARIOS}


# ---- result-level checks (imported by tests/unit/test_rollout_sim.py) --------


def check_no_violations(results: dict) -> None:
    for scenario, result in results.items():
        assert not result["violations"], (
            scenario, result["first_violation"]
        )


def check_clean_completes(results: dict) -> None:
    r = results["clean"]
    assert r["rollback_rel"] is None
    assert r["pods"]["old"] == 0 and r["pods"]["new_ready"] == REPLICAS
    # The ramp really was progressive: the canary share was enforced
    # sub-100% for a while (picks landed while the cap was partial).
    assert 0 < r["bad_picks"] < r["total_picks"]


def check_latency_rolls_back(results: dict) -> None:
    r = results["latency"]
    assert r["rollback"] is not None
    assert r["rollback"]["verdict"] == "ttft_regression"
    assert r["rollback_rel"] - r["mutate_rel"] <= ROLLBACK_BOUND_S
    assert r["bad_share"] <= CANARY_PERCENT / 100.0 + SHARE_EPS
    assert r["client_errors"] == 0


def check_crashloop_rolls_back(results: dict) -> None:
    r = results["crashloop"]
    assert r["rollback"] is not None
    assert r["rollback"]["verdict"] == "crashloop"
    assert r["bad_picks"] == 0, "a never-Ready version served traffic"
    assert r["client_errors"] == 0


def check_group_rolls_atomically(results: dict) -> None:
    r = results["group"]
    assert r["pods"]["old"] == 0
    assert r["pods"]["new_ready"] == GROUP_REPLICAS * NUM_HOSTS


def check_rollback_bundle(results: dict) -> None:
    """The latency rollback dumped a replayable incident bundle naming
    this sim, carrying the rollout decisions and the canonical-JSON
    byte-identity basis."""
    r = results["latency"]
    bundles = [
        i for i in r["incidents"]
        if i["reason"] == flightrecorder.TRIGGER_ROLLBACK
    ]
    assert bundles, "rollback fired no rollout_rollback trigger"
    lines = bundles[0]["lines"]
    header = json.loads(lines[0])
    assert header["bundle"] == "incident"
    assert header["sim"] == SIM_NAME
    assert header["scenario"] == "latency"
    assert header["seed"] == r["seed"]
    assert header["ticks"] == r["ticks"]
    records = [json.loads(ln) for ln in lines[1:]]
    kinds = {rec["kind"] for rec in records if rec["record"] == "flight"}
    assert flightrecorder.ROLLOUT_DECISION in kinds
    for ln in lines:
        assert json.dumps(json.loads(ln), sort_keys=True) == ln


ALL_CHECKS = (
    check_no_violations,
    check_clean_completes,
    check_latency_rolls_back,
    check_crashloop_rolls_back,
    check_group_rolls_atomically,
    check_rollback_bundle,
)


# ---- the classic-plan regression pin ----------------------------------------


def _drive_classic(replicas: int, with_rollout_block: bool,
                   wire_controller: bool) -> list[str]:
    """Reconcile a world through a spec change and return a canonical
    dump of every pod decision the plan made, tick by tick."""
    counter = itertools.count()
    store = KubeStore(namegen=lambda: f"{next(counter):06d}")
    clock = FakeClock(1000.0)
    wall = FakeClock(1_000_000.0)
    metrics = Metrics()
    cfg = System()
    cfg.fixed_self_metric_addrs = ["self:1"]
    cfg.default_and_validate()
    kwargs = {"rollout": _rollout_spec()} if with_rollout_block else {}
    mk_model(store, MODEL, replicas=replicas, autoscaling_disabled=True,
             **kwargs)
    reconciler = ModelReconciler(
        store, cfg, metrics=metrics, clock=clock, wall=wall,
    )
    if wire_controller:
        reconciler.rollout = RolloutController(
            store=store, metrics=metrics, clock=clock,
        )
    timeline: list[str] = []

    def snap() -> None:
        timeline.append(json.dumps(
            sorted(
                (p["metadata"]["name"], _pod_hash_of(p),
                 bool(p.get("status", {}).get("conditions")))
                for p in store.list(
                    "Pod", "default", {md.POD_MODEL_LABEL: MODEL}
                )
            ),
            sort_keys=True,
        ))

    def mark_all_ready() -> None:
        for pod in store.list("Pod", "default", {md.POD_MODEL_LABEL: MODEL}):
            fresh = store.get("Pod", "default", pod["metadata"]["name"])
            fresh.setdefault("status", {})["phase"] = "Running"
            fresh["status"]["conditions"] = [
                {"type": "Ready", "status": "True"},
            ]
            store.update(fresh)

    for step in range(8):
        clock.advance(1.0)
        wall.advance(1.0)
        if step == 3:
            obj = store.get("Model", "default", MODEL)
            obj["spec"]["env"] = {"ROLLOUT_REV": "2"}
            store.update(obj)
        reconciler.reconcile("default", MODEL)
        mark_all_ready()
        snap()
    return timeline


def check_classic_plan_unchanged() -> None:
    """Worlds the rollout plane must not touch plan byte-identically
    with and without the controller wired: a single-replica model even
    WITH a rollout block, and a multi-replica model without one."""
    for replicas, with_block in ((1, True), (3, False)):
        bare = _drive_classic(replicas, with_block, wire_controller=False)
        wired = _drive_classic(replicas, with_block, wire_controller=True)
        assert bare == wired, (
            f"replicas={replicas} rollout_block={with_block}: the wired "
            "controller changed the classic surge plan"
        )


# ---- replay ------------------------------------------------------------------


def replay(path: str) -> tuple[dict, dict]:
    """Re-run a dump byte-identically from its own header. Handles both
    artifact kinds this sim produces: a full run log (GameDayLog) and a
    `rollout_rollback` flight-recorder incident bundle."""
    with open(path) as fh:
        original = [ln.rstrip("\n") for ln in fh if ln.strip()]
    header = json.loads(original[0])
    if header.get("sim") != SIM_NAME:
        raise ValueError(
            f"{path}: dump was recorded by sim {header.get('sim')!r}, "
            f"not {SIM_NAME!r}"
        )
    scenario = header.get("scenario", "latency")
    result = run_sim(
        scenario,
        seed=int(header.get("seed", 0)),
        ticks=int(header.get("ticks", DEFAULT_TICKS[scenario])),
    )
    if header.get("bundle") == "incident":
        fresh = next(
            (i["lines"] for i in result["incidents"]
             if i["reason"] == header["reason"]),
            [],
        )
    else:
        fresh = result["log"].lines
    return header, {
        "lines": fresh,
        "identical": fresh == original,
        "first_violation": result["first_violation"],
        "rollback": result["rollback"],
    }


def replay_main(path: str) -> int:
    """CLI replay entry (also dispatched to by
    `python -m benchmarks.gameday_sim --replay <bundle>` when the
    bundle header names this sim)."""
    header, cmp = replay(path)
    what = "incident bundle" if header.get("bundle") == "incident" else "log"
    print(f"replayed rollout {what} {path}: {len(cmp['lines'])} lines "
          f"(scenario {header.get('scenario')})")
    print(f"byte-identical: {cmp['identical']}")
    print(f"rollback: {cmp['rollback']}")
    print(f"first violation: {cmp['first_violation']}")
    return 0 if cmp["identical"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", choices=("all",) + SCENARIOS,
                    default="all")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ticks", type=int, default=0,
                    help="simulated ticks after warmup (default: per scenario)")
    ap.add_argument("--dump", help="write the run's JSONL log here")
    ap.add_argument("--dump-bundle",
                    help="write the rollout_rollback incident bundle here")
    ap.add_argument("--replay", metavar="DUMP",
                    help="re-run a dumped log or incident bundle and compare")
    args = ap.parse_args(argv)

    if args.replay:
        return replay_main(args.replay)

    if args.scenario == "all":
        results = run_all(seed=args.seed)
        check_classic_plan_unchanged()
        print("PASS check_classic_plan_unchanged")
        for chk in ALL_CHECKS:
            chk(results)
            print(f"PASS {chk.__name__}")
        summary = {
            s: {
                "rollback": r["rollback"],
                "bad_share": r["bad_share"],
                "client_errors": r["client_errors"],
                "pods": r["pods"],
                "violations": len(r["violations"]),
            }
            for s, r in results.items()
        }
        print(json.dumps(summary, indent=2, sort_keys=True))
        return 0

    result = run_sim(args.scenario, seed=args.seed,
                     ticks=args.ticks or None)
    if args.dump:
        result["log"].dump(args.dump)
        print(f"log -> {args.dump}")
    if args.dump_bundle:
        bundle = next(
            (i for i in result["incidents"]
             if i["reason"] == flightrecorder.TRIGGER_ROLLBACK),
            None,
        )
        if bundle is None:
            print("no rollout_rollback bundle was dumped this run")
            return 1
        with open(args.dump_bundle, "w") as fh:
            fh.write("\n".join(bundle["lines"]) + "\n")
        print(f"bundle -> {args.dump_bundle}")
    slim = {k: v for k, v in result.items()
            if k not in ("log", "incidents", "world")}
    print(json.dumps(slim, indent=2, sort_keys=True, default=str))
    return 1 if result["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
