"""Deterministic tenant-isolation (abuse) simulation — no JAX, no
sockets.

Drives the REAL `TenantGovernor` (kubeai_tpu/fleet/tenancy) on a fake
clock with a trace of thousands of compliant tenants plus ONE flooding
abuser, in front of a deterministic FIFO service model, and measures
what every tenant experiences at the door and in the queue.

Invariants (asserted in tier-1 by tests/unit/test_tenancy.py):

  * the abuser's excess is rejected AT THE DOOR with correct
    Retry-After values: retrying one tick before the hint is still
    refused, retrying exactly at the hint is admitted — for both the
    token-bucket refill and the quota window reset;
  * compliant tenants are ISOLATED: their p99 TTFT and queue-wait under
    abuse stay within an epsilon of the no-abuser baseline (while the
    same abuse with the door disabled blows the queue up by orders of
    magnitude — the control that proves the sim can tell the
    difference);
  * overload sheds lowest-class-first: batch sheds at the high-water
    mark, standard at the standard-factor, and realtime is NEVER shed
    while batch traffic remains (realtime degrades last);
  * tenancy disabled (the default) is a NO-OP: every request admits,
    no `kubeai_door_*` series appear, and the measured waits are
    byte-identical to a world with no governor at all.

Sharded-door invariants (same tier-1 wiring), driving `build_door`
with three governors behind one gossiped CRDT state plane:

  * the flooder is held to ONE global budget within a declared epsilon
    no matter how its traffic is split across shards (round-robin,
    all-on-one, alternating), through a gossip partition, and through
    a shard crash;
  * compliant p99 wait/TTFT through the sharded door stays within the
    isolation epsilon of the single-door run, with zero compliant
    refusals;
  * partition-then-heal CONVERGES: after quiescing, every shard's
    CRDT state digest is byte-identical;
  * a crashed shard is rebuilt empty and reconstructs its own
    consumption components from peer replicas;
  * single-shard mode (`doorShards: 1`) is sample-for-sample identical
    to the classic TenantGovernor run.

Run directly for a human-readable report (``--users 1000000`` for the
million-user trace, ``--shards N`` to vary the shard count):

    python benchmarks/tenant_isolation_sim.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.config.system import TenancyConfig
from kubeai_tpu.fleet.metering import UsageMeter
from kubeai_tpu.fleet.tenancy import TenantGovernor, build_door
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.routing.gossip import NS_REQ
from kubeai_tpu.testing.faults import FakeClock
from kubeai_tpu.testing.simkit import percentile
from kubeai_tpu.utils import retryafter

MODEL = "m0"
N_TENANTS = 2000            # compliant tenants, one request each
RUN_S = 100.0               # trace length
ABUSER = "flooder"
ABUSER_INTERVAL_S = 0.02    # 50 req/s — far over any per-tenant limit
SERVICE_TIME_S = 1.0 / 30.0  # FIFO server drains 30 req/s
EPSILON_S = 0.05            # isolation tolerance vs baseline


def _policy() -> TenancyConfig:
    return TenancyConfig(
        enabled=True,
        requests_per_second=2.0,
        request_burst=4.0,
        # Keep idle cleanup out of the measurement window: 2000 tenants
        # sending one request each must not churn mid-trace.
        tenant_idle_seconds=10 * RUN_S,
    )


def _pin_jitter():
    """Pin the shared jitter to its upper bound: jittered(x) == clamp(x),
    so every hint in the sim is the exact computed wait."""
    retryafter._jitter = lambda: 1.0


# Nearest-rank percentile comes from the shared sim scaffolding — same
# definition, so the asserted thresholds carry over unchanged.
_percentile = percentile


def _run_trace(enabled: bool, abuse: bool, governor_present: bool = True):
    """One deterministic pass: merge the compliant trace (tenant i
    arrives at i * RUN_S/N_TENANTS) with the abuser's flood, admit each
    arrival through the governor, and push admitted work through a FIFO
    single-server queue. Returns per-population wait/TTFT samples plus
    door tallies."""
    clock = FakeClock(1000.0)
    metrics = Metrics()
    governor = None
    if governor_present:
        governor = TenantGovernor(
            _policy() if enabled else TenancyConfig(enabled=False),
            metrics=metrics,
            clock=clock,
        )
    arrivals: list[tuple[float, str]] = [
        (i * (RUN_S / N_TENANTS), f"tenant-{i}") for i in range(N_TENANTS)
    ]
    if abuse:
        n_flood = int(RUN_S / ABUSER_INTERVAL_S)
        arrivals += [(j * ABUSER_INTERVAL_S, ABUSER) for j in range(n_flood)]
    arrivals.sort()

    t0 = clock()
    last_finish = t0
    waits: dict[str, list[float]] = {"compliant": [], "abuser": []}
    ttfts: dict[str, list[float]] = {"compliant": [], "abuser": []}
    door = {"admitted": 0, "refused": 0, "abuser_refused": 0,
            "compliant_refused": 0, "refusals": []}
    for offset, tenant in arrivals:
        now = t0 + offset
        clock.advance(now - clock())
        refusal = (
            governor.admit(tenant, MODEL) if governor is not None else None
        )
        if refusal is not None:
            door["refused"] += 1
            door["refusals"].append(refusal)
            if tenant == ABUSER:
                door["abuser_refused"] += 1
            else:
                door["compliant_refused"] += 1
            continue
        door["admitted"] += 1
        start = max(now, last_finish)
        last_finish = start + SERVICE_TIME_S
        pop = "abuser" if tenant == ABUSER else "compliant"
        waits[pop].append(start - now)
        ttfts[pop].append(last_finish - now)
    return {
        "waits": waits,
        "ttfts": ttfts,
        "door": door,
        "metrics": metrics,
        "p99_wait_compliant": _percentile(waits["compliant"], 0.99),
        "p99_ttft_compliant": _percentile(ttfts["compliant"], 0.99),
    }


def _run_hint_honesty():
    """Bucket-refill and window-reset Retry-After correctness: a client
    that retries exactly at the hint is admitted; one tick earlier is
    still refused."""
    clock = FakeClock(1000.0)
    cfg = TenancyConfig(
        enabled=True, requests_per_second=1.0, request_burst=2.0,
        window_seconds=60.0, window_token_budget=500,
        tenant_idle_seconds=3600.0,
    )
    usage = UsageMeter(metrics=Metrics())
    g = TenantGovernor(cfg, usage=usage, metrics=Metrics(),
                       clock=clock)
    out = {}

    # -- bucket refill: burst of 2, then a refusal whose hint is the
    # exact refill time (jitter pinned to the identity).
    assert g.admit(ABUSER, MODEL) is None
    assert g.admit(ABUSER, MODEL) is None
    refusal = g.admit(ABUSER, MODEL)
    out["bucket_refusal"] = refusal
    if refusal is not None:
        hint = refusal.retry_after_s
        clock.advance(hint - 1e-3)
        out["bucket_retry_early"] = g.admit(ABUSER, MODEL)
        clock.advance(1e-3)
        out["bucket_retry_on_time"] = g.admit(ABUSER, MODEL)

    # -- window reset: fresh governor, no rate limit, tight budget. The
    # ledger (fed like the real door feeds it: record AFTER completion)
    # crosses the budget mid-window; the refusal hint is the time to the
    # window reset, and retrying at the reset admits.
    clock2 = FakeClock(5000.0)
    cfg2 = TenancyConfig(
        enabled=True, window_seconds=60.0, window_token_budget=500,
        tenant_idle_seconds=3600.0,
    )
    usage2 = UsageMeter(metrics=Metrics())
    g2 = TenantGovernor(cfg2, usage=usage2, metrics=Metrics(),
                        clock=clock2)
    assert g2.admit(ABUSER, MODEL) is None  # opens the window at t=0
    usage2.record(ABUSER, MODEL, prompt_tokens=400, completion_tokens=200)
    clock2.advance(10.0)
    refusal2 = g2.admit(ABUSER, MODEL)
    out["quota_refusal"] = refusal2
    out["quota_expected_reset_s"] = 50.0  # window opened 10s ago of 60s
    if refusal2 is not None:
        clock2.advance(refusal2.retry_after_s - 1e-3)
        out["quota_retry_early"] = g2.admit(ABUSER, MODEL)
        clock2.advance(1e-3)
        out["quota_retry_on_time"] = g2.admit(ABUSER, MODEL)
    return out


def _run_overload():
    """Class-aware overload shedding against an injected pressure ramp:
    record which classes shed at each pressure level."""
    clock = FakeClock(1000.0)
    cfg = TenancyConfig(
        enabled=True, overload_high_water=100.0,
        overload_standard_factor=2.0, tenant_idle_seconds=3600.0,
    )
    pressure = {"depth": 0.0, "oldest_wait_s": 0.0}
    g = TenantGovernor(
        cfg, metrics=Metrics(), clock=clock,
        pressure_fn=lambda: dict(pressure),
        pressure_ttl_s=0.0,
    )
    levels = (0.0, 50.0, 100.0, 150.0, 199.0, 200.0, 500.0, 90.0, 79.0)
    timeline = []
    for depth in levels:
        pressure["depth"] = depth
        pressure["oldest_wait_s"] = depth / 30.0
        clock.advance(1.0)
        shed = {
            cls: g.admit(f"t-{cls}", MODEL, priority=cls) is not None
            for cls in ("realtime", "standard", "batch")
        }
        timeline.append({"depth": depth, "shed": shed})
    return timeline


# -- the sharded door --------------------------------------------------------

DOOR_SHARDS = 3
GOSSIP_INTERVAL_S = 0.5
GOSSIP_STALE_S = 2.0
PARTITION_T = (30.0, 60.0)   # trace-relative gossip-split window
CRASH_T = 50.0               # trace-relative shard-crash instant
CRASH_IDX = 1                # which shard dies


def _sharded_policy(shards: int = DOOR_SHARDS) -> TenancyConfig:
    cfg = _policy()
    cfg.door_shards = shards
    cfg.gossip_interval_seconds = GOSSIP_INTERVAL_S
    cfg.gossip_stale_seconds = GOSSIP_STALE_S
    return cfg


def sharded_budget_epsilon(shards: int, crashes: int = 0) -> float:
    """Transient admission slack a sharded door is ALLOWED over the
    single global budget: un-gossiped burst on N-1 peers, one gossip
    interval of rate on every shard, the stale-detection window on N-1
    peers, the banked conservative reserve (at most one burst per
    shard), and a fresh full bucket per crashed-and-rebuilt shard."""
    cfg = _sharded_policy(shards)
    return (
        (shards - 1) * cfg.request_burst
        + shards * cfg.requests_per_second * cfg.gossip_interval_seconds
        + (shards - 1) * cfg.requests_per_second * cfg.gossip_stale_seconds
        + shards * cfg.request_burst
        + crashes * cfg.request_burst
        + 2.0
    )


def _run_sharded_trace(
    shards: int = DOOR_SHARDS,
    flood_split: str = "rr",
    partition: bool = False,
    crash: bool = False,
    users: int = N_TENANTS,
) -> dict:
    """The abuse trace through ``build_door``: compliant tenants always
    round-robin across shards; the flooder's split is the scenario knob
    (``rr`` everywhere, ``one`` hammers shard 0, ``alt`` alternates two
    shards). Optional mid-trace gossip partition (healed at
    PARTITION_T[1]) and shard crash+rebuild. After the trace the plane
    is quiesced and every shard's CRDT digest is byte-compared."""
    clock = FakeClock(1000.0)
    metrics = Metrics()
    door = build_door(
        _sharded_policy(shards), metrics=metrics, clock=clock, seed=7
    )
    shard_set = getattr(door, "shard_set", None)

    service_time = (
        SERVICE_TIME_S if users <= N_TENANTS else RUN_S / (1.5 * users)
    )
    arrivals: list[tuple[float, str]] = [
        (i * (RUN_S / users), f"tenant-{i}") for i in range(users)
    ]
    n_flood = int(RUN_S / ABUSER_INTERVAL_S)
    arrivals += [(j * ABUSER_INTERVAL_S, ABUSER) for j in range(n_flood)]
    arrivals.sort()

    t0 = clock()
    last_finish = t0
    waits: dict[str, list[float]] = {"compliant": [], "abuser": []}
    ttfts: dict[str, list[float]] = {"compliant": [], "abuser": []}
    door_tally = {"admitted": 0, "refused": 0, "abuser_refused": 0,
                  "abuser_admitted": 0, "compliant_refused": 0}
    rr = 0
    flood_i = 0
    did_partition = did_heal = did_crash = False
    pre_crash_component = 0.0
    crashed_name = ""
    for offset, tenant in arrivals:
        now = t0 + offset
        clock.advance(now - clock())
        if shard_set is not None:
            if partition and not did_partition and offset >= PARTITION_T[0]:
                names = list(shard_set.names())
                shard_set.partition([names[:1], names[1:]])
                did_partition = True
            if did_partition and not did_heal and offset >= PARTITION_T[1]:
                shard_set.heal()
                did_heal = True
            if crash and not did_crash and offset >= CRASH_T:
                crashed_name = shard_set.names()[CRASH_IDX]
                node = shard_set.node(crashed_name)
                entry = node.state.get(NS_REQ, f"{ABUSER}|{MODEL}")
                pre_crash_component = (
                    entry.of(crashed_name) if entry is not None else 0.0
                )
                shard_set.crash(crashed_name)
                door.replace_shard(CRASH_IDX, TenantGovernor(
                    _sharded_policy(shards), metrics=metrics, clock=clock,
                    gossip=shard_set.node(crashed_name),
                ))
                did_crash = True
            shard_set.maybe_step(now)
            if tenant == ABUSER and flood_split == "one":
                idx = 0
            elif tenant == ABUSER and flood_split == "alt":
                idx = flood_i % min(2, shards)
                flood_i += 1
            else:
                idx = rr % shards
                rr += 1
            gov = door.shards[idx]
        else:
            gov = door
        refusal = gov.admit(tenant, MODEL)
        if refusal is not None:
            door_tally["refused"] += 1
            if tenant == ABUSER:
                door_tally["abuser_refused"] += 1
            else:
                door_tally["compliant_refused"] += 1
            continue
        door_tally["admitted"] += 1
        if tenant == ABUSER:
            door_tally["abuser_admitted"] += 1
        start = max(now, last_finish)
        last_finish = start + service_time
        pop = "abuser" if tenant == ABUSER else "compliant"
        waits[pop].append(start - now)
        ttfts[pop].append(last_finish - now)

    # Quiesce: no more admissions, just anti-entropy rounds until every
    # shard's state digest agrees (byte-compared), bounded.
    converged = True
    digests: dict[str, str] = {}
    post_crash_component = 0.0
    if shard_set is not None:
        if did_partition and not did_heal:
            shard_set.heal()
        for _ in range(20 * shards):
            clock.advance(GOSSIP_INTERVAL_S)
            shard_set.step(clock())
            if shard_set.converged():
                break
        converged = shard_set.converged()
        digests = shard_set.digests()
        if crashed_name:
            entry = shard_set.node(crashed_name).state.get(
                NS_REQ, f"{ABUSER}|{MODEL}"
            )
            post_crash_component = (
                entry.of(crashed_name) if entry is not None else 0.0
            )
    return {
        "shards": shards,
        "users": users,
        "waits": waits,
        "ttfts": ttfts,
        "door": door_tally,
        "n_flood": n_flood,
        "converged": converged,
        "digests": digests,
        "pre_crash_component": pre_crash_component,
        "post_crash_component": post_crash_component,
        "p99_wait_compliant": _percentile(waits["compliant"], 0.99),
        "p99_ttft_compliant": _percentile(ttfts["compliant"], 0.99),
    }


def run_sim(users: int = N_TENANTS, shards: int = DOOR_SHARDS) -> dict:
    _pin_jitter()
    return {
        "baseline": _run_trace(enabled=True, abuse=False),
        "abuse_guarded": _run_trace(enabled=True, abuse=True),
        "abuse_open": _run_trace(enabled=False, abuse=True),
        "abuse_no_governor": _run_trace(
            enabled=False, abuse=True, governor_present=False
        ),
        "hints": _run_hint_honesty(),
        "overload": _run_overload(),
        "sharded_rr": _run_sharded_trace(shards=shards, users=users),
        "sharded_one": _run_sharded_trace(
            shards=shards, flood_split="one", users=users
        ),
        "sharded_alt": _run_sharded_trace(
            shards=shards, flood_split="alt", users=users
        ),
        "sharded_partition": _run_sharded_trace(
            shards=shards, partition=True, users=users
        ),
        "sharded_crash": _run_sharded_trace(
            shards=shards, crash=True, users=users
        ),
        "sharded_single": _run_sharded_trace(shards=1, users=users),
    }


# -- invariants (tier-1 asserts these via tests/unit/test_tenancy.py) --------

def check_abuser_rejected_with_correct_retry_after(result: dict) -> None:
    door = result["abuse_guarded"]["door"]
    n_flood = int(RUN_S / ABUSER_INTERVAL_S)
    # Excess = flood minus the bucket's honest allowance (burst + rate).
    allowance = 4.0 + 2.0 * RUN_S
    assert door["abuser_refused"] >= n_flood - allowance - 1, door
    assert door["compliant_refused"] == 0, door
    for refusal in door["refusals"]:
        assert refusal.tenant == ABUSER
        assert refusal.reason == "rate"
        assert 0.25 <= refusal.retry_after_s <= 300.0

    hints = result["hints"]
    bucket = hints["bucket_refusal"]
    assert bucket is not None and bucket.reason == "rate"
    # rate 1/s, burst 2, bucket empty: the third request's deficit is
    # exactly one token -> 1.0 s to refill (jitter pinned).
    assert abs(bucket.retry_after_s - 1.0) < 1e-9, bucket.retry_after_s
    assert hints["bucket_retry_early"] is not None      # 1 ms early: no
    assert hints["bucket_retry_on_time"] is None        # at the hint: yes

    quota = hints["quota_refusal"]
    assert quota is not None and quota.reason == "quota"
    assert abs(
        quota.retry_after_s - hints["quota_expected_reset_s"]
    ) < 1e-6, quota.retry_after_s
    assert hints["quota_retry_early"] is not None
    assert hints["quota_retry_on_time"] is None


def check_compliant_isolation(result: dict) -> None:
    base = result["baseline"]
    guarded = result["abuse_guarded"]
    open_ = result["abuse_open"]
    assert (
        guarded["p99_ttft_compliant"]
        <= base["p99_ttft_compliant"] + EPSILON_S
    ), (guarded["p99_ttft_compliant"], base["p99_ttft_compliant"])
    assert (
        guarded["p99_wait_compliant"]
        <= base["p99_wait_compliant"] + EPSILON_S
    ), (guarded["p99_wait_compliant"], base["p99_wait_compliant"])
    # The control: the same abuse with the door open must visibly wreck
    # compliant latency, or this sim couldn't detect a broken door.
    assert open_["p99_wait_compliant"] > 10 * (
        base["p99_wait_compliant"] + EPSILON_S
    ), open_["p99_wait_compliant"]


def check_realtime_sheds_last(result: dict) -> None:
    saw_batch_shed = False
    for entry in result["overload"]:
        shed = entry["shed"]
        assert not shed["realtime"], entry    # realtime NEVER door-sheds
        if shed["standard"]:
            assert shed["batch"], entry       # never standard before batch
        if shed["batch"]:
            saw_batch_shed = True
    assert saw_batch_shed
    by_depth = {e["depth"]: e["shed"] for e in result["overload"]}
    assert not by_depth[50.0]["batch"]        # below high water: admit all
    assert by_depth[100.0]["batch"]           # at high water: batch sheds
    assert not by_depth[199.0]["standard"]    # below factor x high
    assert by_depth[200.0]["standard"]        # at factor x high
    assert by_depth[90.0]["batch"]            # hysteresis: still latched
    assert not by_depth[79.0]["batch"]        # below low water: released


def check_disabled_is_noop(result: dict) -> None:
    disabled = result["abuse_open"]
    bare = result["abuse_no_governor"]
    assert disabled["door"]["refused"] == 0
    # Identical experiences, sample for sample: a disabled governor is
    # indistinguishable from no governor at all.
    assert disabled["waits"] == bare["waits"]
    assert disabled["ttfts"] == bare["ttfts"]
    # And it never touches a kubeai_door_* series: the only exposed
    # door lines are the registry's untouched-metric `name 0`
    # placeholders — no labels, no counts, no buckets.
    exposition = disabled["metrics"].registry.expose()
    for line in exposition.splitlines():
        if line.startswith("#") or not line.startswith("kubeai_door_"):
            continue
        name, _, value = line.partition(" ")
        if "{" in name or value.strip() not in ("0", "0.0"):
            raise AssertionError(f"disabled door emitted: {line}")


_SHARDED_SCENARIOS = (
    ("sharded_rr", 0),
    ("sharded_one", 0),
    ("sharded_alt", 0),
    ("sharded_partition", 0),
    ("sharded_crash", 1),
)


def check_sharded_global_budget(result: dict) -> None:
    """The flooder gets ONE global budget within epsilon no matter how
    its traffic is split across shards — including through a gossip
    partition and a shard crash — and the flood is still mostly
    refused (enforcement is real, not vacuous)."""
    allowance = 4.0 + 2.0 * RUN_S
    for name, crashes in _SHARDED_SCENARIOS:
        run = result[name]
        eps = sharded_budget_epsilon(run["shards"], crashes)
        got = run["door"]["abuser_admitted"]
        assert got <= allowance + eps, (
            f"{name}: flooder admitted {got} > global budget "
            f"{allowance:.0f} + epsilon {eps:.0f}"
        )
        assert run["door"]["abuser_refused"] >= run["n_flood"] - allowance - eps, (
            name, run["door"],
        )


def check_sharded_compliant_p99(result: dict) -> None:
    """Sharding the door must not move compliant latency: p99 wait and
    TTFT through 3 shards stay within the isolation epsilon of the
    single-door run, and no compliant request is ever refused."""
    single = result["sharded_single"]
    for name, _ in _SHARDED_SCENARIOS:
        run = result[name]
        assert run["door"]["compliant_refused"] == 0, (name, run["door"])
    multi = result["sharded_rr"]
    assert (
        multi["p99_wait_compliant"]
        <= single["p99_wait_compliant"] + EPSILON_S
    ), (multi["p99_wait_compliant"], single["p99_wait_compliant"])
    assert (
        multi["p99_ttft_compliant"]
        <= single["p99_ttft_compliant"] + EPSILON_S
    ), (multi["p99_ttft_compliant"], single["p99_ttft_compliant"])


def check_sharded_partition_heals(result: dict) -> None:
    """Partition-then-heal converges: after quiescing, every shard's
    CRDT state digest is byte-identical — in every scenario."""
    for name, _ in _SHARDED_SCENARIOS:
        run = result[name]
        assert run["converged"], f"{name}: gossip plane never converged"
        assert len(set(run["digests"].values())) == 1, (
            f"{name}: shard digests diverge: {run['digests']}"
        )


def check_sharded_crash_reconstructed(result: dict) -> None:
    """A crashed shard rebuilt empty reconstructs its own consumption
    component from peer replicas: the flooder's pre-crash counter
    reappears on the fresh node (minus at most one gossip interval of
    un-replicated tail)."""
    run = result["sharded_crash"]
    assert run["pre_crash_component"] > 0.0, run
    assert run["post_crash_component"] >= run["pre_crash_component"] - 3.0, (
        run["pre_crash_component"], run["post_crash_component"],
    )


def check_sharded_single_is_classic(result: dict) -> None:
    """doorShards: 1 IS the classic TenantGovernor — sample for sample:
    identical waits, TTFTs, and door tallies to the pre-sharding run."""
    s = result["sharded_single"]
    c = result["abuse_guarded"]
    assert s["waits"] == c["waits"]
    assert s["ttfts"] == c["ttfts"]
    assert s["door"]["admitted"] == c["door"]["admitted"]
    assert s["door"]["refused"] == c["door"]["refused"]
    assert s["door"]["abuser_refused"] == c["door"]["abuser_refused"]


ALL_CHECKS = (
    check_abuser_rejected_with_correct_retry_after,
    check_compliant_isolation,
    check_realtime_sheds_last,
    check_disabled_is_noop,
    check_sharded_global_budget,
    check_sharded_compliant_p99,
    check_sharded_partition_heals,
    check_sharded_crash_reconstructed,
    check_sharded_single_is_classic,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--users", type=int, default=N_TENANTS,
                    help="compliant tenants in the sharded runs "
                         "(1000000 for the million-user trace)")
    ap.add_argument("--shards", type=int, default=DOOR_SHARDS,
                    help="door shards behind the gossip plane (>= 2)")
    args = ap.parse_args(argv)
    result = run_sim(users=args.users, shards=args.shards)
    base = result["baseline"]
    guarded = result["abuse_guarded"]
    open_ = result["abuse_open"]
    print(f"tenants={N_TENANTS} + 1 abuser @ {1/ABUSER_INTERVAL_S:.0f} "
          f"req/s over {RUN_S:.0f}s, service={1/SERVICE_TIME_S:.0f} req/s")
    print(f"baseline      p99 wait={base['p99_wait_compliant']*1e3:8.2f} ms  "
          f"p99 ttft={base['p99_ttft_compliant']*1e3:8.2f} ms")
    print(f"abuse+door    p99 wait={guarded['p99_wait_compliant']*1e3:8.2f} ms  "
          f"p99 ttft={guarded['p99_ttft_compliant']*1e3:8.2f} ms  "
          f"(abuser refused {guarded['door']['abuser_refused']})")
    print(f"abuse, no door p99 wait={open_['p99_wait_compliant']*1e3:8.2f} ms "
          f" (the world the door prevents)")
    allowance = 4.0 + 2.0 * RUN_S
    print(f"sharded door: {args.shards} shards, {args.users} users, "
          f"global budget {allowance:.0f}")
    for name, crashes in _SHARDED_SCENARIOS:
        run = result[name]
        print(f"  {name:20s} flooder admitted "
              f"{run['door']['abuser_admitted']:4d} "
              f"(eps {sharded_budget_epsilon(run['shards'], crashes):.0f})  "
              f"p99 wait={run['p99_wait_compliant']*1e3:8.2f} ms  "
              f"converged={run['converged']}")
    for chk in ALL_CHECKS:
        chk(result)
        print(f"PASS {chk.__name__}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
