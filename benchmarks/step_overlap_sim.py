"""Deterministic overlapped-step-pipeline simulation — fake device
clock, no JAX, no sockets.

Models the engine's step loop (`kubeai_tpu/engine/engine.py
Engine.step`) against a virtual device whose compute time is a modelled
constant per decode chunk, and replays the SAME barrier rules the real
engine enforces:

  * SYNC loop   — dispatch chunk N, wait for the device, read tokens
                  back, run host work (sample / detokenize / SSE), then
                  dispatch chunk N+1. The device idles through the
                  whole host window.
  * OVERLAP loop — dispatch chunk N+1 BEFORE reaping chunk N: the
                  host's readback + sample window runs concurrently
                  with chunk N+1's device compute. Barriers mirror the
                  engine's: a pending admission or a drain forces a
                  reap before state mutates.

Tokens come from a deterministic function of (seed, rid, position) —
exactly the property the real device has (same state in, same token
out) — so any divergence between the sync and overlap streams can only
come from the LOOP's ordering/barrier logic, which is what the
invariants pin:

  (a) SPEEDUP — with modelled host time >= 30% of the synchronous step,
      the overlapped loop decodes >= 1.3x the synchronous throughput;
  (b) TOKEN IDENTITY — byte-identical per-request token streams,
      overlap on vs off, for greedy AND seeded sampling, across the
      paged / slot / chunked-prefill admission models;
  (c) BARRIERS — mid-run arrivals (admission barrier) and a mid-run
      drain (drain barrier) both force a reap and still produce
      identical streams;
  (d) PHASE ACCOUNTING — the overlap win is visible in the phase
      vocabulary: overlap_idle (host blocked on device compute)
      shrinks under overlap while sync pays ~the full device time.

Run directly for a human-readable report:

    python benchmarks/step_overlap_sim.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ---- modelled step timings ---------------------------------------------------
#
# One decode chunk (DECODE_CHUNK fused model steps) costs DEVICE_CHUNK_S
# on the virtual device. The host pays DISPATCH_S to stage inputs,
# READBACK_S to transfer the chunk's tokens, and HOST_CHUNK_S of
# sample/detokenize/SSE work per chunk. Host share of the synchronous
# step = (DISPATCH_S + READBACK_S + HOST_CHUNK_S) / sync step — the
# >= 30% premise the speedup invariant requires (asserted below, so
# retuning the model retunes the assertion input, not the check).

DECODE_CHUNK = 8
DEVICE_CHUNK_S = 0.70
DISPATCH_S = 0.02
READBACK_S = 0.05
HOST_CHUNK_S = 0.33
PREFILL_S_PER_CHUNK = 0.08  # one prefill call (whole bucket or one chunk)
PREFILL_CHUNK = 32  # chunked-prefill mode: prompt tokens per prefill call

HOST_S = DISPATCH_S + READBACK_S + HOST_CHUNK_S
SYNC_STEP_S = HOST_S + DEVICE_CHUNK_S
HOST_SHARE = HOST_S / SYNC_STEP_S

VOCAB = 50257


def _token(seed: int, rid: int, position: int) -> int:
    """The virtual device: same (sampler seed, request, position) in,
    same token out — mode- and loop-independent by construction."""
    return (seed * 1000003 + rid * 7919 + (position + 1) * 104729) % VOCAB


class _Request:
    def __init__(self, rid: int, arrival_step: int, prompt_len: int,
                 seed: int, max_tokens: int):
        self.rid = rid
        self.arrival_step = arrival_step  # admitted once this many steps ran
        self.prompt_len = prompt_len
        self.seed = seed
        self.max_tokens = max_tokens
        self.position = prompt_len
        self.out: list[int] = []
        self.done = False


class _Device:
    """Virtual accelerator: a busy-until horizon on the sim clock.
    dispatch() queues work behind whatever is already in flight (the
    data dependency the real engine gets from donated buffers)."""

    def __init__(self):
        self.busy_until = 0.0

    def dispatch(self, now: float, work_s: float) -> float:
        start = max(now, self.busy_until)
        self.busy_until = start + work_s
        return self.busy_until  # ready_at


class _SimEngine:
    """The step loop under test. `mode` picks the admission model
    (paged = batched whole-prompt, slot = serial whole-prompt,
    chunked = per-PREFILL_CHUNK prefill calls); `overlap` picks the
    loop shape. Barrier rules mirror Engine.step/_barrier_locked."""

    def __init__(self, requests, mode: str = "paged",
                 overlap: bool = False, num_slots: int = 4,
                 drain_after_step: int | None = None):
        assert mode in ("paged", "slot", "chunked")
        self.mode = mode
        self.overlap = overlap
        self.num_slots = num_slots
        self.pending = sorted(requests, key=lambda r: r.rid)
        self.active: dict[int, _Request] = {}
        self.free_slots = list(range(num_slots))
        self.now = 0.0
        self.device = _Device()
        self.inflight = None  # (ready_at, [(slot, req, position0)], len)
        self.steps = 0
        self.draining = False
        self.drain_after_step = drain_after_step
        self.barrier_reaps = 0
        self.phases = {
            "prefill": 0.0, "schedule": 0.0, "dispatch": 0.0,
            "overlap_idle": 0.0, "readback": 0.0, "sample": 0.0,
        }
        self.streams: dict[int, list[int]] = {r.rid: [] for r in requests}

    # -- pieces ---------------------------------------------------------------

    def _arrivals_due(self):
        return [
            r for r in self.pending
            if r.arrival_step <= self.steps and not self.draining
        ]

    def _reap(self, inflight, barrier: bool = False) -> None:
        ready_at, riders, chunk_len = inflight
        if barrier:
            self.barrier_reaps += 1
        idle = max(0.0, ready_at - self.now)
        self.now += idle
        self.phases["overlap_idle"] += idle
        self.now += READBACK_S
        self.phases["readback"] += READBACK_S
        self.now += HOST_CHUNK_S
        self.phases["sample"] += HOST_CHUNK_S
        for k in range(chunk_len):
            for slot, req, pos0 in riders:
                if req.done:
                    continue  # surplus chunk tokens discarded
                tok = _token(req.seed, req.rid, pos0 + k)
                req.out.append(tok)
                req.position += 1
                self.streams[req.rid].append(tok)
                if len(req.out) >= req.max_tokens:
                    req.done = True
                    self.free_slots.append(slot)
                    self.active.pop(slot, None)

    def _barrier(self) -> None:
        if self.inflight is not None:
            inflight, self.inflight = self.inflight, None
            self._reap(inflight, barrier=True)

    def _admit(self) -> None:
        due = self._arrivals_due()
        batch = []
        while due and self.free_slots:
            req = due.pop(0)
            self.pending.remove(req)
            slot = self.free_slots.pop()
            self.active[slot] = req
            batch.append(req)
        if not batch:
            return
        if self.mode == "paged":
            # Batched admission: same-bucket prompts share one call.
            calls = 1
        elif self.mode == "slot":
            calls = len(batch)
        else:  # chunked prefill: one call per PREFILL_CHUNK tokens
            calls = sum(
                -(-r.prompt_len // PREFILL_CHUNK) for r in batch
            )
        cost = calls * PREFILL_S_PER_CHUNK
        self.now += cost
        self.device.busy_until = max(self.device.busy_until, self.now)
        self.phases["prefill"] += cost
        for req in batch:  # prefill samples the first token
            tok = _token(req.seed, req.rid, req.position)
            req.out.append(tok)
            req.position += 1
            self.streams[req.rid].append(tok)

    # -- the loop -------------------------------------------------------------

    def step(self) -> None:
        if (
            self.drain_after_step is not None
            and self.steps == self.drain_after_step
            and not self.draining
        ):
            # Drain barrier: reap before the drain decision mutates
            # admission state (mirrors Engine.begin_drain).
            self._barrier()
            self.draining = True
        if self.inflight is not None and self._arrivals_due() and self.free_slots:
            # Admission barrier: the slot/page grant must observe the
            # in-flight chunk's stop-driven frees.
            self._barrier()
        self._admit()
        prev, self.inflight = self.inflight, None
        current = None
        if self.active:
            self.now += DISPATCH_S
            self.phases["dispatch"] += DISPATCH_S
            riders = [
                (slot, req, req.position + (prev[2] if prev else 0))
                for slot, req in sorted(self.active.items())
            ]
            ready_at = self.device.dispatch(self.now, DEVICE_CHUNK_S)
            current = (ready_at, riders, DECODE_CHUNK)
            if self.overlap:
                self.inflight = current
                current = None
        self.steps += 1
        if prev is not None:
            self._reap(prev)
        if current is not None:
            self._reap(current)

    def has_work(self) -> bool:
        return bool(self.pending or self.active or self.inflight)

    def run(self) -> dict:
        guard = 0
        while self.has_work():
            # A drained sim stops admitting; pending arrivals are shed.
            if self.draining:
                self.pending = []
            self.step()
            guard += 1
            assert guard < 10_000, "sim did not converge"
        tokens = sum(len(s) for s in self.streams.values())
        return {
            "tokens": tokens,
            "wall_s": round(self.now, 9),
            "tokens_per_s": round(tokens / self.now, 9) if self.now else 0.0,
            "steps": self.steps,
            "barrier_reaps": self.barrier_reaps,
            "phases_s": {k: round(v, 9) for k, v in self.phases.items()},
            "streams": {rid: list(s) for rid, s in self.streams.items()},
        }


# ---- workloads ---------------------------------------------------------------


def _workload(seeded: bool):
    """Six requests, two arriving mid-run (they exercise the admission
    barrier under overlap). Greedy = seed 0 (argmax stands in); seeded
    = per-request sampler seeds."""
    specs = [
        # (rid, arrival_step, prompt_len, max_tokens)
        (0, 0, 64, 128),
        (1, 0, 48, 120),
        (2, 0, 96, 128),
        (3, 0, 32, 112),
        (4, 5, 64, 96),  # mid-run arrival: admission barrier
        (5, 8, 80, 96),  # second wave
    ]
    return [
        _Request(
            rid, arrival, plen,
            seed=(0 if not seeded else 0x9E3779B1 ^ (rid * 2654435761)),
            max_tokens=mt,
        )
        for rid, arrival, plen, mt in specs
    ]


MODES = ("paged", "slot", "chunked")


def run_sim() -> dict:
    """Run every (mode x sampling x loop) cell plus the drain scenario;
    purely virtual clock, so the result is bit-deterministic."""
    cells: dict = {}
    for mode in MODES:
        for sampling in ("greedy", "seeded"):
            seeded = sampling == "seeded"
            sync = _SimEngine(
                _workload(seeded), mode=mode, overlap=False
            ).run()
            over = _SimEngine(
                _workload(seeded), mode=mode, overlap=True
            ).run()
            cells[f"{mode}/{sampling}"] = {"sync": sync, "overlap": over}
    # Drain-while-in-flight: barrier reap mid-run, streams of the
    # already-admitted requests still identical between loops.
    drain_sync = _SimEngine(
        _workload(False), mode="paged", overlap=False, drain_after_step=4
    ).run()
    drain_over = _SimEngine(
        _workload(False), mode="paged", overlap=True, drain_after_step=4
    ).run()
    base = cells["paged/greedy"]
    return {
        "host_share": round(HOST_SHARE, 9),
        "speedup": round(
            base["overlap"]["tokens_per_s"] / base["sync"]["tokens_per_s"], 9
        ),
        "cells": cells,
        "drain": {"sync": drain_sync, "overlap": drain_over},
    }


# ---- invariants (tier-1: tests/unit/test_step_overlap_sim.py) ----------------


def check_host_share_premise(result: dict) -> None:
    # The >= 1.3x claim is conditional on host time >= 30% of the sync
    # step; the timing model must actually satisfy the premise.
    assert result["host_share"] >= 0.30, result["host_share"]


def check_overlap_speedup(result: dict) -> None:
    assert result["speedup"] >= 1.3, (
        f"overlap speedup {result['speedup']:.3f} < 1.3x "
        f"(host share {result['host_share']:.2f})"
    )
    # Every cell, not just the headline one, must come out ahead.
    for name, cell in result["cells"].items():
        ratio = cell["overlap"]["tokens_per_s"] / cell["sync"]["tokens_per_s"]
        assert ratio >= 1.2, f"{name}: {ratio:.3f}"


def check_token_identity(result: dict) -> None:
    # Byte-identical streams, overlap on vs off, greedy AND seeded,
    # across all three admission models.
    for name, cell in result["cells"].items():
        assert cell["sync"]["streams"] == cell["overlap"]["streams"], name
        for rid, s in cell["sync"]["streams"].items():
            assert len(s) > 0, (name, rid)


def check_barriers_fire(result: dict) -> None:
    # Mid-run arrivals force admission-barrier reaps under overlap
    # (and none in the sync loop, which never holds a chunk).
    for name, cell in result["cells"].items():
        assert cell["overlap"]["barrier_reaps"] >= 1, name
        assert cell["sync"]["barrier_reaps"] == 0, name
    # The drain scenario reaps at the drain barrier and still matches.
    d = result["drain"]
    assert d["sync"]["streams"] == d["overlap"]["streams"]


def check_phase_accounting(result: dict) -> None:
    # The win is visible in the phase split: sync pays ~the whole
    # device time as overlap_idle; overlap hides most of it.
    cell = result["cells"]["paged/greedy"]
    sync_idle = cell["sync"]["phases_s"]["overlap_idle"]
    over_idle = cell["overlap"]["phases_s"]["overlap_idle"]
    assert over_idle < 0.75 * sync_idle, (sync_idle, over_idle)
    # readback is per-chunk constant work — both loops pay it.
    assert cell["overlap"]["phases_s"]["readback"] > 0
    assert cell["sync"]["phases_s"]["readback"] > 0


ALL_CHECKS = (
    check_host_share_premise,
    check_overlap_speedup,
    check_token_identity,
    check_barriers_fire,
    check_phase_accounting,
)


def main() -> int:
    result = run_sim()
    for chk in ALL_CHECKS:
        chk(result)
        print(f"  PASS {chk.__name__}")
    print(
        f"\nhost share of sync step: {result['host_share']:.1%}"
        f"\noverlap speedup (paged/greedy): {result['speedup']:.2f}x"
    )
    for name, cell in result["cells"].items():
        print(
            f"  {name:16s} sync {cell['sync']['tokens_per_s']:8.2f} tok/s"
            f"  overlap {cell['overlap']['tokens_per_s']:8.2f} tok/s"
            f"  ({cell['overlap']['tokens_per_s'] / cell['sync']['tokens_per_s']:.2f}x,"
            f" {cell['overlap']['barrier_reaps']} barrier reaps)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
