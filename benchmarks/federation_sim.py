"""Federation sim — two fake clusters, one fake clock, the real stack.

Two clusters ("west" hosts giant+hot, "east" hosts hot+m-east) each run
the REAL components — `FleetStateAggregator`, `CapacityPlanner`,
`ActuationGovernor`, a gossiped `TenantGovernor` door — and the
federation plane on top: `FederationAggregator` joining the peer's
snapshot (staleness flagged, never merged), `FederationRouter`
spilling admitted requests to the peer door on local chip exhaustion
(cost-ranked: queue wait vs RTT + MEASURED boot cost), and
`FederationPlanner` failing whole models over through the governor
when a cluster partitions. Cross-cluster links are closures over the
peer's in-process objects; cutting them IS the partition.

Invariants:

  CONTINUOUS (checked every tick)
    * spillover fires ONLY on exhaustion (`throttled_replicas > 0`)
      and only when the peer is genuinely cheaper — and the 240 s-boot
      "giant" model never spills to a cluster that would cold-boot it;
    * the flooding tenant's admissions ACROSS BOTH cluster doors stay
      within ONE token-bucket budget (+ the gossip epsilon) — quota
      cannot be laundered by hopping clusters;
    * compliant tenants are never refused at either door;
    * each cluster's billing ledger exactly equals its delivered work,
      spilled requests billed where they were served;
    * a partitioned peer is FLAGGED stale, never merged: its last-good
      snapshot stays visible, its models never leak into the local
      snapshot;
    * a spilled request is never re-spilled (no ping-pong);
    * the partitioned cluster itself never actuates a takeover.

  TERMINAL (checked once, after the last event)
    * the partitioned cluster's models fail over within the bounded
      window (staleness + failover window + slack), only models the
      survivor also deploys, and fail BACK within the slack of heal;
    * the cross-cluster KV fill script hit exactly its expected
      fill/refusal/recompute counts (dtype mismatch refuses, a
      truncated blob refuses — never casts);
    * every queue drains (spillover helped, not hurt).

Every run writes a JSONL `GameDayLog`; dump -> replay is
byte-identical:

    python benchmarks/federation_sim.py --dump /tmp/f.jsonl
    python -m benchmarks.federation_sim --replay /tmp/f.jsonl
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from collections import deque

import numpy as np

from kubeai_tpu.config import System
from kubeai_tpu.config.system import (
    GovernorConfig,
    PeerClusterConfig,
    TenancyConfig,
)
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.disagg.handoff import KVPageExport, serialize_pages
from kubeai_tpu.federation import (
    FederationAggregator,
    FederationKVFiller,
    FederationPlanner,
    FederationRouter,
)
from kubeai_tpu.federation.router import SERVED_BY_HEADER
from kubeai_tpu.fleet import CapacityPlanner, FleetStateAggregator
from kubeai_tpu.fleet.metering import UsageMeter
from kubeai_tpu.fleet.tenancy import TenantGovernor
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.objstore import KVSpillStore
from kubeai_tpu.operator.governor import ActuationGovernor
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.gossip import DoorShardSet
from kubeai_tpu.routing.loadbalancer import Group, LoadBalancer
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.routing.proxy import ProxyResult
from kubeai_tpu.testing.chaos import (
    CONTINUOUS,
    EV_CLUSTER_HEAL,
    EV_CLUSTER_PARTITION,
    EV_TENANT_FLOOD,
    TERMINAL,
    ChaosKubeStore,
    GameDayEvent,
    GameDayLog,
    GameDayTrace,
    Invariant,
    InvariantChecker,
)
from kubeai_tpu.testing.clock import FakeClock
from kubeai_tpu.testing.faults import ApiFaultPlan
from kubeai_tpu.testing.simkit import mk_model

TICK_S = 1.0
WARMUP_TICKS = 6
DEFAULT_TICKS = 48

PROMPT_TOKENS = 16
COMPLETION_TOKENS = 8

# Federation timing: a peer is flagged stale STALENESS_S after its last
# successful fetch; a flagged peer is failed over FAILOVER_WINDOW_S
# after the flag; the sim allows FAILOVER_SLACK_S of tick quantization
# on top of both.
STALENESS_S = 3.0
FAILOVER_WINDOW_S = 5.0
FAILOVER_SLACK_S = 4.0
RTT_S = 0.05
QUEUE_WAIT_PER_REQ_S = 0.5

# Measured boot costs the planner surfaces in its plan records
# (coldstart_cost_s) — the router prices spillover with these. "giant"
# is the 70B-class model whose four-minute boot must price it OUT of
# spilling to a cluster that would have to cold-boot it.
BOOT_COSTS = {"hot": 6.0, "giant": 240.0, "m-east": 6.0}
SERVE_RATE = {"hot": 3, "giant": 1, "m-east": 3}

CLUSTER_MODELS = {"west": ("giant", "hot"), "east": ("hot", "m-east")}
CLUSTERS = ("east", "west")  # deterministic iteration order everywhere

# Two chips per cluster: two single-chip models fit exactly, so ANY
# queue-driven extra desire is throttled demand (chip exhaustion).
BUDGET_OVERRIDE = {"tpu-v5-lite-podslice/1x1": {"chips": 2, "slice_chips": 1}}

# One federation-wide tenant budget, enforced by the gossiped door.
DOOR_RATE = 3.0
DOOR_BURST = 4.0
GOSSIP_INTERVAL_S = 1.0
GOSSIP_STALE_S = 3.0


def door_budget_epsilon() -> float:
    """Worst-case over-admission of the 2-door gossip plane (same
    bound the game-day sim derives): peers' unseen bursts + in-flight
    gossip intervals + the staleness window, plus tick slack."""
    n = len(CLUSTERS)
    return (
        (n - 1) * DOOR_BURST
        + n * DOOR_RATE * GOSSIP_INTERVAL_S
        + (n - 1) * DOOR_RATE * GOSSIP_STALE_S
        + 2.0
    )


class _Forecast:
    """The forecast surface the planner prices with."""

    def __init__(self, coldstart_cost_s: float):
        self.coldstart_cost_s = coldstart_cost_s
        self.warm_trigger = False  # no prewarm in this sim
        self.trigger = ""
        self.spot_disruptions = 0

    def payload(self) -> dict:
        return {
            "current": 0.0,
            "predicted": 0.0,
            "coldstart_cost_s": self.coldstart_cost_s,
        }


class BootCostBook:
    """Stands in for the demand forecaster: per-model MEASURED boot
    costs (the planner would learn these from observed boots)."""

    def forecast(self, model: str):
        cost = BOOT_COSTS.get(model)
        return _Forecast(cost) if cost is not None else None


class _Req:
    __slots__ = ("tenant", "model", "t_arrive")

    def __init__(self, tenant: str, model: str, t_arrive: float):
        self.tenant = tenant
        self.model = model
        self.t_arrive = t_arrive


class SimCluster:
    """One cluster's full stack: store, models, telemetry, planner,
    governor, door shard, and the federation trio."""

    def __init__(self, name: str, peer_name: str, world: "FederationWorld"):
        self.name = name
        self.peer_name = peer_name
        self.world = world
        clock = world.clock

        cfg = System()
        cfg.cluster.name = name
        cfg.cluster.peers = [
            PeerClusterConfig(
                name=peer_name,
                door_url=f"http://door.{peer_name}.example:8000",
                spill_url="",  # the sim injects in-memory spill stores
                rtt_seconds=RTT_S,
            )
        ]
        cfg.federation.enabled = True
        cfg.federation.interval_seconds = 1.0
        cfg.federation.staleness_seconds = STALENESS_S
        cfg.federation.failover_window_seconds = FAILOVER_WINDOW_S
        cfg.federation.queue_wait_per_request_seconds = QUEUE_WAIT_PER_REQ_S
        cfg.default_and_validate()
        self.cfg = cfg

        self._name_counter = itertools.count()
        self.raw = KubeStore(
            namegen=lambda: f"{next(self._name_counter):06d}"
        )
        self.api = ChaosKubeStore(self.raw, ApiFaultPlan())
        self.metrics = Metrics()

        # -- models + one hand-made Ready pod per model (the data plane
        # is static here: federation is a control/routing-plane sim).
        self.queues: dict[str, deque] = {}
        self.addr_model: dict[str, str] = {}
        subnet = 10 + sorted(CLUSTERS).index(name)
        for i, model in enumerate(CLUSTER_MODELS[name]):
            mk_model(self.raw, model, replicas=1, min_replicas=1,
                     max_replicas=4, target_requests=1,
                     scale_down_delay_seconds=0)
            ip = f"10.{subnet}.0.{i + 1}"
            self.raw.create({
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": f"{model}-0",
                    "namespace": "default",
                    "labels": {md.POD_MODEL_LABEL: model},
                },
                "status": {
                    "phase": "Running",
                    "podIP": ip,
                    "conditions": [{"type": "Ready", "status": "True"}],
                },
            })
            self.addr_model[f"{ip}:8000"] = model
            self.queues[model] = deque()

        self.lb = LoadBalancer(self.raw, metrics=self.metrics)
        for model in CLUSTER_MODELS[name]:
            self.lb._groups[model] = Group(
                metrics=self.metrics, model=model, clock=clock
            )

        self.aggregator = FleetStateAggregator(
            lb=self.lb, model_client=ModelClient(self.raw), store=self.raw,
            metrics=self.metrics, interval_s=1.0, staleness_s=2.5,
            fetch_metrics=self._fetch_metrics, fetch_state=self._fetch_state,
            clock=clock, cluster=name,
        )

        gcfg = GovernorConfig(
            window_seconds=20.0,
            model_disruption_budget=2,
            cluster_disruption_budget=3,
            min_telemetry_coverage=0.9,
        )
        self.governor = ActuationGovernor(
            cfg=gcfg, fleet=self.aggregator, store=self.api,
            metrics=self.metrics, clock=clock,
        )
        self.planner = CapacityPlanner(
            fleet=self.aggregator, model_client=ModelClient(self.api),
            store=None, cfg=cfg, metrics=self.metrics, interval_s=1.0,
            staleness_s=2.5, clock=clock, governor=self.governor,
            forecaster=BootCostBook(), budget_override=BUDGET_OVERRIDE,
        )
        self.planner.avg_lookup = (
            lambda m: float(len(self.queues[m])) if m in self.queues else 0.0
        )

        # -- tenant door: one shard of the FEDERATION-wide gossip plane.
        self.usage = UsageMeter(metrics=self.metrics)
        self.door = TenantGovernor(
            cfg=TenancyConfig(
                enabled=True,
                requests_per_second=DOOR_RATE,
                request_burst=DOOR_BURST,
                overload_high_water=5e7,
                overload_low_water=1e7,
                tenant_idle_seconds=1e9,
                gossip_interval_seconds=GOSSIP_INTERVAL_S,
                gossip_stale_seconds=GOSSIP_STALE_S,
            ),
            usage=self.usage, metrics=self.metrics, clock=clock,
            pressure_fn=self._pressure, pressure_ttl_s=0.0,
            gossip=world.ss.node(name),
        )

        # -- the federation trio.
        self.federation = FederationAggregator(
            cfg, self.aggregator, metrics=self.metrics, clock=clock,
            fetch_snapshot=world.mk_fetch_snapshot(name, peer_name),
        )
        self.router = FederationRouter(
            cfg, planner=self.planner, federation=self.federation,
            metrics=self.metrics, clock=clock,
            dispatch=world.mk_dispatch(name),
        )
        self.fed_planner = FederationPlanner(
            cfg, federation=self.federation, store=self.api,
            governor=self.governor, metrics=self.metrics, clock=clock,
        )

        # -- bookkeeping.
        self.served: dict[str, int] = {m: 0 for m in CLUSTER_MODELS[name]}
        self.spills: list[dict] = []      # origin-side spill records
        self.refusals: list[tuple] = []   # (tick, tenant, model, reason)
        self.denied = 0                   # governor-denied failovers
        self.control_errors = 0

    # -- injected engine telemetry ---------------------------------------

    def _fetch_metrics(self, addr: str, timeout: float = 5.0) -> str:
        model = self.addr_model.get(addr)
        if model is None:
            raise ConnectionError(f"injected: {addr} unreachable")
        q = self.queues[model]
        depth = float(len(q))
        oldest = (self.world.clock() - q[0].t_arrive) if q else 0.0
        return "\n".join([
            'kubeai_engine_queue_depth{class="standard"} ' + f"{depth}",
            f"kubeai_engine_queue_oldest_wait_seconds {oldest}",
            "kubeai_engine_kv_cache_utilization 0.0",
            f"kubeai_engine_slots_active {depth}",
            "kubeai_engine_slot_capacity 4.0",
            "kubeai_engine_ttft_seconds_sum 0.0",
            "kubeai_engine_ttft_seconds_count 0.0",
            f"kubeai_engine_active_requests {depth}",
        ]) + "\n"

    def _fetch_state(self, addr: str, timeout: float = 5.0) -> dict:
        model = self.addr_model.get(addr)
        if model is None:
            raise ConnectionError(f"injected: {addr} unreachable")
        return {"model": model, "healthy": True}

    def _pressure(self) -> dict:
        depth = sum(len(q) for q in self.queues.values())
        oldest = 0.0
        now = self.world.clock()
        for q in self.queues.values():
            if q:
                oldest = max(oldest, now - q[0].t_arrive)
        return {"depth": float(depth), "oldest_wait_s": oldest}


class FederationWorld:
    """Two `SimCluster`s on one `FakeClock`, one chaos trace, one
    federation-wide door gossip plane."""

    def __init__(self, trace: GameDayTrace, ticks: int, seed: int = 0):
        self.trace = trace
        self.ticks = int(ticks)
        self.seed = int(seed)
        self.clock = FakeClock(1000.0)
        self.tick_no = 0
        self.t0 = self.clock() + WARMUP_TICKS * TICK_S

        from kubeai_tpu.utils import retryafter
        retryafter._jitter = lambda: 1.0  # byte-identical replays

        # The door shard set spans CLUSTERS, not in-process shards: each
        # cluster's door is one shard of a federation-wide gossip plane,
        # which is exactly what makes the tenant budget global.
        self.ss = DoorShardSet(
            CLUSTERS, self.clock, seed=seed,
            interval_s=GOSSIP_INTERVAL_S, stale_after_s=GOSSIP_STALE_S,
        )

        self.clusters = {
            "west": SimCluster("west", "east", self),
            "east": SimCluster("east", "west", self),
        }

        # -- chaos state.
        self.partitioned_cluster: str | None = None
        self.partition_until = float("inf")
        self.partition_t: float | None = None
        self.heal_t: float | None = None
        self.floods: list[dict] = []
        self.flood_t0: dict[str, float] = {}
        self.flood_admitted: dict[str, int] = {}

        # -- observation state.
        self.ping_pongs = 0
        self.giant_priced_out = 0
        self.failover_seen_t: float | None = None
        self.failback_seen_t: float | None = None
        self.failed_over_peak: dict[str, str] = {}
        self.east_seen_once = False
        self.kv_done = False
        self.kv_counts: dict | None = None

        self.log = GameDayLog(
            trace, ticks, extra={"seed": seed, "sim": "federation"},
        )
        self.checker = InvariantChecker(INVARIANTS, log=self.log)
        self.converged_final = False

    def rel_now(self) -> float:
        return self.clock() - self.t0

    def comms_cut(self, a: str, b: str) -> bool:
        return self.partitioned_cluster in (a, b)

    # -- cross-cluster links (closures over the peer's objects) ----------

    def mk_fetch_snapshot(self, src: str, dst: str):
        def fetch(peer):
            if self.comms_cut(src, dst):
                raise ConnectionError(
                    f"cluster partition: {src} cannot reach {dst}"
                )
            agg = self.clusters[dst].aggregator
            snap = agg.snapshot()
            return snap if snap is not None else agg.collect()
        return fetch

    def mk_dispatch(self, src: str):
        """Spill transport: admit at the peer's door (tenancy headers
        intact — the gossiped budget stays global), then enqueue on the
        peer's data plane. A refusal there fails the dispatch, which
        the router degrades to serving locally."""
        def dispatch(peer, path, body, headers):
            dst = peer.name
            if self.comms_cut(src, dst):
                raise ConnectionError(
                    f"cluster partition: {src} cannot reach {dst}"
                )
            c = self.clusters[dst]
            model = FederationRouter.model_of(body)
            # Anti-ping-pong audit: the peer router must decline to
            # re-spill a request already stamped as spilled.
            if c.router.maybe_spill(model, path, body, list(headers)) is not None:
                self.ping_pongs += 1
            hdrs = {str(k).lower(): v for k, v in headers}
            tenant = hdrs.get("x-kubeai-tenant", "")
            refusal = c.door.admit(
                tenant, model, priority="standard",
                est_tokens=PROMPT_TOKENS + COMPLETION_TOKENS,
            )
            if refusal is not None:
                raise RuntimeError(
                    f"peer door refused spill: {refusal.reason}"
                )
            c.queues[model].append(_Req(tenant, model, self.clock()))
            return ProxyResult(
                200, [("content-type", "application/json")], iter(())
            )
        return dispatch

    # -- chaos -----------------------------------------------------------

    def apply_event(self, ev: GameDayEvent, rel: float) -> None:
        p = ev.params
        if ev.kind == EV_TENANT_FLOOD:
            tenant = ev.target or "flooder"
            self.floods.append({
                "tenant": tenant,
                "cluster": p.get("cluster", "west"),
                "model": p.get("model", "hot"),
                "rps": int(p.get("rps", 10)),
                "until": rel + float(p.get("duration_s", 10.0)),
            })
            self.flood_t0.setdefault(tenant, rel)
        elif ev.kind == EV_CLUSTER_PARTITION:
            name = ev.target or "east"
            self.partitioned_cluster = name
            self.partition_until = rel + float(p.get("duration_s", 1e9))
            if self.partition_t is None:
                self.partition_t = rel
            self.clusters[name].api.partitioned = True
            self.ss.partition([[n] for n in self.ss.names()])
        elif ev.kind == EV_CLUSTER_HEAL:
            if self.partitioned_cluster == (ev.target or
                                            self.partitioned_cluster):
                self._heal(rel)

    def _heal(self, rel: float) -> None:
        if self.partitioned_cluster is None:
            return
        self.clusters[self.partitioned_cluster].api.partitioned = False
        self.ss.heal()
        self.partitioned_cluster = None
        self.partition_until = float("inf")
        if self.heal_t is None:
            self.heal_t = rel

    # -- per-tick phases -------------------------------------------------

    def control(self) -> None:
        """Each cluster's control plane: telemetry sweep, capacity
        plan, federation join, failover pass. A partitioned cluster's
        planner errors are absorbed — that IS the promoted
        api_partition scenario."""
        for name in CLUSTERS:
            c = self.clusters[name]
            c.lb.sync_all()
            try:
                c.aggregator.collect()
            except Exception:  # noqa: BLE001 — chaos-injected
                c.control_errors += 1
            try:
                c.planner.tick(force=True)
            except Exception:  # noqa: BLE001 — chaos-injected
                c.control_errors += 1
            c.federation.join()
            actions = c.fed_planner.tick()
            c.denied += len(actions["denied"])
        self.ss.step(self.clock())

    def arrivals(self, rel: float) -> None:
        now = self.clock()
        offered: list[tuple[str, str, str, int]] = [
            ("west", "user-west", "hot", 1),
            ("east", "user-east", "hot", 1),
        ]
        if self.tick_no % 2 == 0:
            offered.append(("east", "user-m", "m-east", 1))
        self.floods = [f for f in self.floods if rel < f["until"]]
        for f in self.floods:
            offered.append((f["cluster"], f["tenant"], f["model"], f["rps"]))
        for cname, tenant, model, n in offered:
            c = self.clusters[cname]
            for _ in range(n):
                refusal = c.door.admit(
                    tenant, model, priority="standard",
                    est_tokens=PROMPT_TOKENS + COMPLETION_TOKENS,
                )
                if refusal is not None:
                    c.refusals.append(
                        (self.tick_no, tenant, model, refusal.reason)
                    )
                    continue
                if tenant in self.flood_t0:
                    self.flood_admitted[tenant] = (
                        self.flood_admitted.get(tenant, 0) + 1
                    )
                self._route(c, tenant, model, now)

    def _route(self, c: SimCluster, tenant: str, model: str,
               now: float) -> None:
        plan = c.planner.current_plan() or {}
        rec = (plan.get("models") or {}).get(model) or {}
        body = json.dumps({"model": model}).encode()
        result = c.router.maybe_spill(
            model, "/v1/chat/completions", body,
            [("x-kubeai-tenant", tenant)],
        )
        if result is not None:
            ranked = c.router.rank(model, rec)
            c.spills.append({
                "tick": self.tick_no,
                "tenant": tenant,
                "model": model,
                "to": dict(result.headers).get(SERVED_BY_HEADER, ""),
                "throttled": int(rec.get("throttled_replicas") or 0),
                "local_cost": FederationRouter.local_cost(
                    rec, QUEUE_WAIT_PER_REQ_S
                ),
                "remote_cost": ranked[0][0] if ranked else None,
            })
            return
        c.queues[model].append(_Req(tenant, model, now))

    def serve(self) -> None:
        for name in CLUSTERS:
            c = self.clusters[name]
            for model in CLUSTER_MODELS[name]:
                q = c.queues[model]
                for _ in range(min(len(q), SERVE_RATE[model])):
                    req = q.popleft()
                    c.usage.record(
                        req.tenant, model,
                        prompt_tokens=PROMPT_TOKENS,
                        completion_tokens=COMPLETION_TOKENS,
                        requests=1,
                    )
                    c.served[model] += 1

    def _kv_script(self) -> None:
        """The cross-cluster KVP1 fill drill, run once: a good fill
        from the peer's spill store, a dtype-mismatch refusal, and a
        truncated (mid-transfer death) refusal — both degrade to a
        counted recompute (miss), never a cast."""
        store = KVSpillStore("")  # east's in-memory spill leg
        shape = (2, 1, 4, 2, 4)  # [NL, n_pages, page, KVH, D]
        k = np.arange(int(np.prod(shape)), dtype=np.float32).reshape(shape)
        h_good = "ab" * 16
        h_trunc = "cd" * 16
        blob = serialize_pages(KVPageExport(
            prefix_hashes=(h_good,), page_size=4, dtype="float32",
            k_pages=k, v_pages=k + 0.5, model="hot",
        ))
        store.put(h_good, blob)
        store.put(h_trunc, blob[: len(blob) // 2])
        west = self.clusters["west"]
        filler = FederationKVFiller(
            west.cfg, metrics=west.metrics, stores={"east": store},
        )
        got = filler.fill(h_good, expect_dtype="float32")
        ok = (
            got is not None
            and got.dtype == "float32"
            and got.prefix_hashes == (h_good,)
            and np.array_equal(got.k_pages, k)
        )
        refused_dtype = filler.fill(h_good, expect_dtype="int8") is None
        refused_trunc = filler.fill(h_trunc, expect_dtype="float32") is None
        self.kv_counts = {
            "fills": filler.fills,
            "refusals": filler.refusals,
            "misses": filler.misses,
            "verified": bool(ok),
            "refused_dtype": refused_dtype,
            "refused_trunc": refused_trunc,
        }

    def observe(self, rel: float) -> None:
        west = self.clusters["west"]
        # The durable record of the takeover: the annotation on the
        # survivor's local Model (read via the RAW store — observation
        # must not depend on the chaos wrapper).
        ann = None
        try:
            m = west.raw.get("Model", "default", "hot")
            ann = ((m.get("metadata") or {}).get("annotations") or {}).get(
                md.FEDERATION_FAILOVER_ANNOTATION
            )
        except Exception:  # noqa: BLE001
            ann = None
        if ann:
            if self.failover_seen_t is None:
                self.failover_seen_t = rel
        elif (
            self.failover_seen_t is not None
            and self.heal_t is not None
            and self.failback_seen_t is None
        ):
            self.failback_seen_t = rel
        for model, src in west.fed_planner.failed_over.items():
            self.failed_over_peak[model] = src
        if "m-east" in west.federation.peer_models("east"):
            self.east_seen_once = True
        # "giant" priced out: exhausted AND a fresh peer exists, but
        # its boot cost keeps the peer from being cheaper.
        plan = west.planner.current_plan() or {}
        rec = (plan.get("models") or {}).get("giant")
        if rec and int(rec.get("throttled_replicas") or 0) > 0:
            ranked = west.router.rank("giant", rec)
            if ranked:
                local = FederationRouter.local_cost(
                    rec, QUEUE_WAIT_PER_REQ_S
                )
                if local > RTT_S and ranked[0][0] >= local:
                    self.giant_priced_out += 1

    # -- the loop --------------------------------------------------------

    def tick(self) -> None:
        self.tick_no += 1
        self.clock.advance(TICK_S)
        rel = self.rel_now()
        for ev in self.trace.due(rel):
            self.apply_event(ev, rel)
            self.log.event(self.tick_no, ev)
        if self.partitioned_cluster is not None and rel >= self.partition_until:
            self._heal(rel)
        self.control()
        self.arrivals(rel)
        self.serve()
        if not self.kv_done and rel >= 4.0:
            self._kv_script()
            self.kv_done = True
        self.observe(rel)
        self.log.obs(
            self.tick_no,
            t=round(rel, 3),
            queues={n: {m: len(q) for m, q in sorted(
                self.clusters[n].queues.items())} for n in CLUSTERS},
            served={n: dict(sorted(self.clusters[n].served.items()))
                    for n in CLUSTERS},
            spills={n: len(self.clusters[n].spills) for n in CLUSTERS},
            refusals={n: len(self.clusters[n].refusals) for n in CLUSTERS},
            stale={n: self.clusters[n].federation.cluster_stale(
                self.clusters[n].peer_name) for n in CLUSTERS},
            failed_over={n: dict(sorted(
                self.clusters[n].fed_planner.failed_over.items()))
                for n in CLUSTERS},
            flood_admitted=dict(sorted(self.flood_admitted.items())),
            partitioned=self.partitioned_cluster or "",
        )
        self.checker.check_continuous(self, self.tick_no, rel)

    def run(self) -> dict:
        for _ in range(WARMUP_TICKS + self.ticks):
            self.tick()
        self.converged_final = (
            self.partitioned_cluster is None
            and all(
                not q
                for c in self.clusters.values()
                for q in c.queues.values()
            )
        )
        self.checker.check_terminal(self, self.tick_no, self.rel_now())
        return self.result()

    def result(self) -> dict:
        first = self.checker.first_violation
        return {
            "ticks": self.ticks,
            "seed": self.seed,
            "trace_events": len(self.trace.events),
            "violations": [
                {"tick": v.tick, "t": v.t, "invariant": v.invariant,
                 "detail": v.detail}
                for v in self.checker.violations
            ],
            "first_violation": (
                None if first is None else
                {"tick": first.tick, "invariant": first.invariant,
                 "detail": first.detail}
            ),
            "spills": {n: list(self.clusters[n].spills) for n in CLUSTERS},
            "spill_total": sum(
                len(self.clusters[n].spills) for n in CLUSTERS
            ),
            "refusal_total": sum(
                len(self.clusters[n].refusals) for n in CLUSTERS
            ),
            "served": {n: dict(self.clusters[n].served) for n in CLUSTERS},
            "billing": {
                n: self.clusters[n].usage.totals() for n in CLUSTERS
            },
            "flood_admitted": dict(self.flood_admitted),
            "giant_priced_out": self.giant_priced_out,
            "ping_pongs": self.ping_pongs,
            "denied": {n: self.clusters[n].denied for n in CLUSTERS},
            "control_errors": {
                n: self.clusters[n].control_errors for n in CLUSTERS
            },
            "failover": {
                "partition_t": self.partition_t,
                "heal_t": self.heal_t,
                "failover_seen_t": self.failover_seen_t,
                "failback_seen_t": self.failback_seen_t,
                "peak": dict(self.failed_over_peak),
            },
            "kv": self.kv_counts,
            "converged_final": self.converged_final,
            "log": self.log,
        }


# ---- invariants --------------------------------------------------------------


def _inv_spill_exhaustion_cost(world) -> str | None:
    """Every spill happened under exhaustion, with the peer strictly
    cheaper — and the 240 s-boot model never spills at all."""
    for name in CLUSTERS:
        for s in world.clusters[name].spills:
            if s["model"] == "giant":
                return (
                    f"{name} spilled 'giant' (boot cost "
                    f"{BOOT_COSTS['giant']}s) at tick {s['tick']} — "
                    "boot-cost pricing failed"
                )
            if s["throttled"] <= 0:
                return (
                    f"{name} spilled {s['model']} at tick {s['tick']} "
                    "without chip exhaustion (throttled_replicas=0)"
                )
            if s["remote_cost"] is None or s["remote_cost"] >= s["local_cost"]:
                return (
                    f"{name} spilled {s['model']} at tick {s['tick']} "
                    f"with remote {s['remote_cost']} >= local "
                    f"{s['local_cost']} — not cost-ranked"
                )
    return None


def _inv_federation_budget(world) -> str | None:
    """A flooding tenant's admissions ACROSS BOTH cluster doors stay
    within one token-bucket budget plus the gossip epsilon."""
    rel = world.rel_now()
    eps = door_budget_epsilon()
    for tenant, t0 in world.flood_t0.items():
        elapsed = max(0.0, rel - t0)
        bound = DOOR_BURST + DOOR_RATE * elapsed + eps
        got = world.flood_admitted.get(tenant, 0)
        if got > bound:
            return (
                f"{tenant}: {got} admissions across both doors > "
                f"global budget {bound:.1f} ({elapsed:.0f}s elapsed, "
                f"eps {eps:.1f}) — the federation budget leaked"
            )
    return None


def _inv_compliant_never_refused(world) -> str | None:
    for name in CLUSTERS:
        for tick, tenant, model, reason in world.clusters[name].refusals:
            if not tenant.startswith("user-"):
                continue
            return (
                f"compliant tenant {tenant} refused at {name} door "
                f"(tick {tick}, model {model}, reason {reason})"
            )
    return None


def _inv_billing_exact(world) -> str | None:
    """Each cluster's ledger equals its delivered work exactly —
    spilled requests are billed once, where they were served."""
    for name in CLUSTERS:
        c = world.clusters[name]
        served = sum(c.served.values())
        t = c.usage.totals()
        want = {
            "requests": served,
            "prompt_tokens": served * PROMPT_TOKENS,
            "completion_tokens": served * COMPLETION_TOKENS,
        }
        for k, v in want.items():
            if int(t.get(k, 0)) != v:
                return (
                    f"{name}: ledger {k}={t.get(k)} != delivered {v} "
                    f"(served={served})"
                )
    return None


def _inv_staleness_flagged_not_merged(world) -> str | None:
    """The peer's models never merge into the local snapshot; a
    partitioned peer is flagged stale while its last-good snapshot
    stays visible (what failover plans from)."""
    west = world.clusters["west"]
    snap = west.federation.snapshot()
    if snap is None:
        return None
    local = (snap["clusters"]["west"].get("snapshot") or {})
    if "m-east" in (local.get("models") or {}):
        return "east's m-east leaked into west's LOCAL snapshot (merged)"
    if world.east_seen_once and "m-east" not in west.federation.peer_models(
        "east"
    ):
        return "east's last-good snapshot lost m-east (flagging dropped it)"
    if world.partitioned_cluster == "east" and world.partition_t is not None:
        active = world.rel_now() - world.partition_t
        east_entry = snap["clusters"].get("east") or {}
        if active > STALENESS_S + 1.5 * TICK_S and not east_entry.get("stale"):
            return (
                f"east partitioned {active:.0f}s but not flagged stale "
                f"(staleness bound {STALENESS_S}s)"
            )
    return None


def _inv_no_ping_pong(world) -> str | None:
    if world.ping_pongs:
        return (
            f"{world.ping_pongs} spilled request(s) were re-spilled by "
            "the peer router — the one-hop stamp failed"
        )
    return None


def _inv_partitioned_never_actuates(world) -> str | None:
    """The cluster that lost its API server must not take over anyone's
    models: it cannot even see its own store."""
    east = world.clusters["east"]
    if east.fed_planner.failed_over:
        return (
            f"partitioned east actuated takeovers: "
            f"{dict(east.fed_planner.failed_over)}"
        )
    return None


def _inv_failover_bounded(world) -> str | None:
    if world.partition_t is None:
        return "trace never partitioned a cluster"
    if world.failover_seen_t is None:
        return "east partitioned but west never failed its models over"
    bound = STALENESS_S + FAILOVER_WINDOW_S + FAILOVER_SLACK_S
    took = world.failover_seen_t - world.partition_t
    if took > bound:
        return f"failover took {took:.0f}s > bound {bound:.0f}s"
    if world.failed_over_peak != {"hot": "east"}:
        return (
            f"expected exactly hot<-east failed over; got "
            f"{dict(world.failed_over_peak)} (m-east is not deployed on "
            "west and must never be taken over)"
        )
    return None


def _inv_failback_on_heal(world) -> str | None:
    if world.heal_t is None:
        return "trace never healed the partition"
    if world.failback_seen_t is None:
        return "east healed but the takeover was never reversed"
    took = world.failback_seen_t - world.heal_t
    if took > FAILOVER_SLACK_S:
        return f"failback took {took:.0f}s > slack {FAILOVER_SLACK_S:.0f}s"
    if world.clusters["west"].fed_planner.failed_over:
        return (
            f"failed_over not empty after heal: "
            f"{dict(world.clusters['west'].fed_planner.failed_over)}"
        )
    return None


def _inv_kv_fill_discipline(world) -> str | None:
    kc = world.kv_counts
    if kc is None:
        return "the KV fill script never ran"
    want = {"fills": 1, "refusals": 2, "misses": 2}
    got = {k: kc[k] for k in want}
    if got != want:
        return f"KV fill counts {got} != expected {want}"
    if not (kc["verified"] and kc["refused_dtype"] and kc["refused_trunc"]):
        return f"KV fill outcomes wrong: {kc}"
    return None


def _inv_queues_drained(world) -> str | None:
    if not world.converged_final:
        leftover = {
            n: {m: len(q) for m, q in world.clusters[n].queues.items() if q}
            for n in CLUSTERS
        }
        return (
            f"queues not drained / partition not healed by end: "
            f"{leftover}, partitioned={world.partitioned_cluster}"
        )
    return None


INVARIANTS = (
    Invariant("spill_exhaustion_cost", _inv_spill_exhaustion_cost,
              CONTINUOUS,
              "spillover only on exhaustion, only when the peer is "
              "cheaper; boot cost prices 'giant' out"),
    Invariant("federation_budget", _inv_federation_budget, CONTINUOUS,
              "one tenant budget across both cluster doors"),
    Invariant("compliant_never_refused", _inv_compliant_never_refused,
              CONTINUOUS, "compliant tenants never refused"),
    Invariant("billing_exact", _inv_billing_exact, CONTINUOUS,
              "each cluster's ledger equals its delivered work"),
    Invariant("staleness_flagged_not_merged",
              _inv_staleness_flagged_not_merged, CONTINUOUS,
              "a stale peer is flagged, never merged"),
    Invariant("no_ping_pong", _inv_no_ping_pong, CONTINUOUS,
              "a spilled request is never re-spilled"),
    Invariant("partitioned_never_actuates",
              _inv_partitioned_never_actuates, CONTINUOUS,
              "the partitioned cluster never takes over models"),
    Invariant("failover_bounded", _inv_failover_bounded, TERMINAL,
              "partitioned models fail over within the bounded window"),
    Invariant("failback_on_heal", _inv_failback_on_heal, TERMINAL,
              "the takeover reverses when the peer heals"),
    Invariant("kv_fill_discipline", _inv_kv_fill_discipline, TERMINAL,
              "cross-cluster KV fills verify; mismatches refuse"),
    Invariant("queues_drained", _inv_queues_drained, TERMINAL,
              "both clusters drain by the end of the run"),
)


# ---- the trace ---------------------------------------------------------------


def federation_trace(seed: int = 0) -> GameDayTrace:
    """Flood both doors into exhaustion (spillover + global budget),
    flood the giant model (boot-cost pricing), partition east mid-run
    (failover), flood again DURING the partition (split-door budget),
    then heal (failback)."""
    return GameDayTrace([
        GameDayEvent(2.0, EV_TENANT_FLOOD, "flooder",
                     {"cluster": "west", "model": "hot", "rps": 20,
                      "duration_s": 14.0}),
        GameDayEvent(2.0, EV_TENANT_FLOOD, "flooder",
                     {"cluster": "east", "model": "hot", "rps": 20,
                      "duration_s": 14.0}),
        GameDayEvent(3.0, EV_TENANT_FLOOD, "flood-giant",
                     {"cluster": "west", "model": "giant", "rps": 10,
                      "duration_s": 6.0}),
        GameDayEvent(20.0, EV_CLUSTER_PARTITION, "east",
                     {"duration_s": 30.0}),
        GameDayEvent(24.0, EV_TENANT_FLOOD, "flooder",
                     {"cluster": "west", "model": "hot", "rps": 10,
                      "duration_s": 6.0}),
        GameDayEvent(24.0, EV_TENANT_FLOOD, "flooder",
                     {"cluster": "east", "model": "hot", "rps": 10,
                      "duration_s": 6.0}),
        GameDayEvent(34.0, EV_CLUSTER_HEAL, "east", {}),
    ], seed=seed)


def run_federation(trace: GameDayTrace, ticks: int, seed: int = 0) -> dict:
    return FederationWorld(trace, ticks, seed=seed).run()


def run_sim(ticks: int = DEFAULT_TICKS, seed: int = 0) -> dict:
    """Tier-1 entry point: the full federation day plus the same day
    without the floods (spillover must be exhaustion-only: a calm
    federation never spills)."""
    federation = run_federation(federation_trace(seed), ticks, seed)
    baseline = run_federation(
        federation_trace(seed).without(EV_TENANT_FLOOD), ticks, seed
    )
    return {
        "ticks": ticks,
        "seed": seed,
        "federation": federation,
        "baseline": baseline,
    }


# ---- result-level checks (imported by tests/unit/test_federation.py) ---------


def check_no_violations(result: dict) -> None:
    """Both runs hold every invariant, continuous AND terminal."""
    for key in ("federation", "baseline"):
        assert result[key]["violations"] == [], (
            key, result[key]["violations"],
        )
        assert result[key]["converged_final"], f"{key} did not converge"


def check_spillover_real(result: dict) -> None:
    """Spillover actually fired under the flood — hot spilled from
    west to east — and NEVER without the flood (exhaustion-only), and
    the giant model was priced out by its measured boot cost."""
    fed, base = result["federation"], result["baseline"]
    west_hot = [
        s for s in fed["spills"]["west"]
        if s["model"] == "hot" and s["to"] == "east"
    ]
    assert west_hot, "west never spilled hot to east under the flood"
    assert base["spill_total"] == 0, (
        f"baseline (no flood) spilled {base['spill_total']} times — "
        "spillover is not exhaustion-gated"
    )
    assert fed["giant_priced_out"] > 0, (
        "giant was never exhausted-but-priced-out — the boot-cost "
        "pricing path was not exercised"
    )
    giant_spills = [
        s for n in CLUSTERS for s in fed["spills"][n]
        if s["model"] == "giant"
    ]
    assert giant_spills == [], giant_spills


def check_failover_cycle(result: dict) -> None:
    """Partition -> bounded failover of exactly the co-deployed model
    -> failback on heal, in BOTH runs (failover is flood-independent)."""
    for key in ("federation", "baseline"):
        fo = result[key]["failover"]
        assert fo["failover_seen_t"] is not None, (key, fo)
        assert fo["failback_seen_t"] is not None, (key, fo)
        assert fo["peak"] == {"hot": "east"}, (key, fo)


def check_flood_budget_nonvacuous(result: dict) -> None:
    """The budget invariant had teeth: the flooder was admitted some
    (the bound is not vacuously satisfied at 0) AND refused a lot."""
    fed = result["federation"]
    assert fed["flood_admitted"].get("flooder", 0) > 0
    assert fed["refusal_total"] > 100, fed["refusal_total"]


def check_kv_counts(result: dict) -> None:
    kc = result["federation"]["kv"]
    assert kc is not None
    assert (kc["fills"], kc["refusals"], kc["misses"]) == (1, 2, 2), kc


ALL_CHECKS = (
    check_no_violations,
    check_spillover_real,
    check_failover_cycle,
    check_flood_budget_nonvacuous,
    check_kv_counts,
)


# ---- replay ------------------------------------------------------------------


def replay(path: str) -> tuple[dict, dict]:
    """Re-run a dumped federation day byte-identically from its own
    header (trace + seed + ticks)."""
    header, _records = GameDayLog.load(path)
    trace = GameDayTrace(
        [GameDayEvent.from_dict(d) for d in header["events"]],
        seed=int(header["seed"]),
    )
    result = run_federation(
        trace, int(header["ticks"]), seed=int(header["seed"])
    )
    return header, result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ticks", type=int, default=DEFAULT_TICKS)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dump", help="write the JSONL event log here")
    ap.add_argument("--replay", metavar="DUMP",
                    help="re-run a dumped federation day and compare")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.replay:
        with open(args.replay) as fh:
            original = [ln.rstrip("\n") for ln in fh if ln.strip()]
        header, result = replay(args.replay)
        fresh = result["log"].lines
        identical = fresh == original
        print(f"replayed {args.replay}: {len(original)} log lines")
        print(f"byte-identical: {identical}")
        print(f"first violation: {result['first_violation']}")
        return 0 if identical else 1

    result = run_federation(
        federation_trace(args.seed), args.ticks, seed=args.seed
    )
    if args.dump:
        result["log"].dump(args.dump)
        print(f"log -> {args.dump}")

    if args.json:
        slim = {k: v for k, v in result.items() if k not in ("log", "spills")}
        print(json.dumps(slim, indent=2, default=str))
        return 0

    print(f"federation day: seed={args.seed} ticks={args.ticks} "
          f"events={result['trace_events']}")
    print(f"  spills={result['spill_total']} "
          f"refusals={result['refusal_total']} "
          f"flood_admitted={result['flood_admitted']}")
    print(f"  giant priced out on {result['giant_priced_out']} ticks; "
          f"ping_pongs={result['ping_pongs']}")
    print(f"  failover: {result['failover']}")
    print(f"  kv: {result['kv']}")
    print(f"  served: {result['served']}")
    print(f"  control errors absorbed: {result['control_errors']}")
    print(f"  converged: {result['converged_final']}")
    if result["violations"]:
        print(f"  VIOLATIONS ({len(result['violations'])}):")
        for v in result["violations"][:10]:
            print(f"    tick {v['tick']} [{v['invariant']}] {v['detail']}")
    else:
        print("  all invariants held")
    return 0 if not result["violations"] else 1


if __name__ == "__main__":
    sys.exit(main())
