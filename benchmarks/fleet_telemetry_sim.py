"""Deterministic fleet-telemetry simulation — no JAX, no sockets.

Builds a synthetic fleet (N unified models × M replicas plus one
disaggregated prefill/decode model) on a fake clock, renders each
endpoint's scripted signals as REAL Prometheus exposition text (with
trailing timestamps and +Inf buckets, exactly what a production scrape
returns), and drives the REAL FleetStateAggregator, UsageMeter, and
Autoscaler over it. One endpoint is DEAD (never answers) and one goes
STALE mid-run (answers, then stops).

Invariants (asserted in tier-1 by tests/unit/test_fleet_telemetry.py):

  * snapshot coverage & convergence: every live endpoint of every model
    (≥ 2 models) appears in the snapshot with per-role signals and chip
    inventory; two sweeps over frozen signals produce identical
    per-model views;
  * staleness is FLAGGED, never silently merged: the dead endpoint and
    the gone-stale endpoint appear with `stale: true` + the scrape
    error, and the per-model aggregates exclude them exactly;
  * tenant token accounting is EXACT: the usage ledger equals the
    synthetic token emission integer-for-integer;
  * aggregator-fed autoscaler decisions EQUAL direct-scrape decisions
    for every model (unified boost path and per-role disagg path), with
    the aggregator world actually reading the aggregator.

Run directly for a human-readable report:

    python benchmarks/fleet_telemetry_sim.py
"""

from __future__ import annotations

import copy
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.autoscaler import Autoscaler
from kubeai_tpu.autoscaler.autoscaler import (
    scrape_queue_pressure,
    scrape_role_signals,
)
from kubeai_tpu.config import System
from kubeai_tpu.crd.model import Disaggregation, Model, ModelSpec
from kubeai_tpu.fleet import FleetStateAggregator, UsageMeter
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.routing.loadbalancer import LoadBalancer
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.testing.faults import FakeClock

N_MODELS = 2           # unified models m0, m1
REPLICAS = 3           # endpoints per unified model
DEAD_ADDR = "10.0.0.0:8000"      # m0 replica 0: never answers
STALE_ADDR = "10.0.1.0:8000"     # m1 replica 0: answers, then stops
STALE_AFTER_TICK = 4
TICKS = 8


class Endpoint:
    """Scripted signals for one serving endpoint, rendered as exposition
    text the way a real engine's /metrics does — including trailing
    sample timestamps and a histogram +Inf bucket, which the hardened
    parser must swallow."""

    def __init__(self, model: str, idx: int, role: str = "unified"):
        self.model = model
        self.idx = idx
        self.role = role
        self.signals = {
            "depth_standard": 0.0,
            "depth_batch": 0.0,
            "oldest_wait_s": 0.0,
            "kv_utilization": 0.0,
            "slots_active": 0.0,
            "slot_capacity": 32.0,
            "ttft_sum": 0.0,
            "ttft_count": 0.0,
            "active": 0.0,
        }

    def advance(self, tick: int) -> None:
        s = self.signals
        base = (self.idx + 1) * (tick + 1)
        if self.role == "prefill":
            # Prefill pressure grows with the tick: queued prefills and
            # mean TTFT climb so the role autoscaler has to act.
            s["depth_standard"] = float(3 * (tick + 1))
            s["oldest_wait_s"] = 0.5 * tick
            s["ttft_sum"] += 0.4 * (tick + 1)
            s["ttft_count"] += 1.0
        elif self.role == "decode":
            s["kv_utilization"] = min(0.95, 0.2 + 0.1 * tick)
            s["slots_active"] = float(min(30, 4 * (tick + 1)))
        else:
            s["depth_standard"] = float(base % 7)
            s["depth_batch"] = float(base % 3)
            # m1 ages past the 3s queue-pressure bound mid-run so the
            # unified boost path fires and must agree across worlds.
            s["oldest_wait_s"] = (
                4.0 + tick if self.model == "m1" else 0.5
            )
            s["kv_utilization"] = (base % 10) / 10.0
            s["slots_active"] = float(base % 32)
            s["ttft_sum"] += 0.05 * base
            s["ttft_count"] += 2.0
            s["active"] = float(base % 5)

    def exposition(self) -> str:
        s = self.signals
        ts = " 1722772800000"  # trailing timestamp: must be tolerated
        lines = [
            "# TYPE kubeai_engine_queue_depth gauge",
            f'kubeai_engine_queue_depth{{class="standard"}} '
            f"{s['depth_standard']}{ts}",
            f'kubeai_engine_queue_depth{{class="batch"}} '
            f"{s['depth_batch']}",
            f'kubeai_engine_queue_oldest_wait_seconds{{class="standard"}} '
            f"{s['oldest_wait_s']}",
            f"kubeai_engine_kv_cache_utilization {s['kv_utilization']}",
            f"kubeai_engine_slots_active {s['slots_active']}",
            f"kubeai_engine_slot_capacity {s['slot_capacity']}",
            f"kubeai_engine_ttft_seconds_sum {s['ttft_sum']}",
            f"kubeai_engine_ttft_seconds_count {s['ttft_count']}",
            f'kubeai_engine_ttft_seconds_bucket{{le="0.25"}} '
            f"{s['ttft_count'] * 0.5}",
            f'kubeai_engine_ttft_seconds_bucket{{le="+Inf"}} '
            f"{s['ttft_count']}{ts}",
            f"kubeai_engine_active_requests {s['active']}",
        ]
        return "\n".join(lines) + "\n"

    def state(self) -> dict:
        return {
            "model": self.model,
            "healthy": True,
            "draining": False,
            "role": self.role,
        }


def _pod(model: str, idx: int, addr: str, role: str | None = None,
         chips: int = 4, topology: str = "2x2") -> dict:
    ip, _, port = addr.partition(":")
    labels = {"model": model}
    if role:
        labels["model-role"] = role
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"model-{model}-{idx}" + (f"-{role}" if role else ""),
            "namespace": "default",
            "labels": labels,
            "annotations": {
                "model-pod-ip": ip,
                "model-pod-port": port,
            },
        },
        "spec": {
            "nodeSelector": {
                "cloud.google.com/gke-tpu-accelerator":
                    "tpu-v5-lite-podslice",
                "cloud.google.com/gke-tpu-topology": topology,
            },
            "containers": [{
                "name": "server",
                "resources": {
                    "requests": {"google.com/tpu": str(chips)},
                    "limits": {"google.com/tpu": str(chips)},
                },
            }],
        },
        "status": {
            "conditions": [{"type": "Ready", "status": "True"}],
            "podIP": ip,
        },
    }


class FleetWorld:
    """One complete in-process fleet: store + LB + models + scripted
    endpoints. Built identically for the aggregator-fed and the
    direct-scrape autoscaler worlds so their decisions are comparable."""

    def __init__(self):
        self.clock = FakeClock(1000.0)
        self.store = KubeStore()
        self.cfg = System()
        self.cfg.fixed_self_metric_addrs = ["self:1"]
        self.cfg.default_and_validate()
        self.mc = ModelClient(self.store)
        self.lb = LoadBalancer(self.store)
        self.metrics = Metrics()
        self.endpoints: dict[str, Endpoint] = {}
        self.tick_no = 0

        spec_common = dict(
            url="hf://org/x", engine="KubeAITPU",
            features=["TextGeneration"], min_replicas=0, max_replicas=10,
            replicas=REPLICAS, target_requests=10,
            scale_down_delay_seconds=0,
        )
        for i in range(N_MODELS):
            name = f"m{i}"
            self.store.create(
                Model(name=name, spec=ModelSpec(**spec_common)).to_dict()
            )
            for j in range(REPLICAS):
                addr = f"10.0.{i}.{j}:8000"
                self.endpoints[addr] = Endpoint(name, j)
                self.store.create(_pod(name, j, addr))
        # One disaggregated model with explicit prefill/decode pools.
        self.store.create(
            Model(
                name="m-disagg",
                spec=ModelSpec(
                    **{**spec_common, "replicas": 0},
                    disaggregation=Disaggregation(
                        enabled=True,
                        prefill_target_queue=4,
                        prefill_target_ttft_seconds=0.5,
                        decode_target_utilization=0.8,
                    ),
                ),
            ).to_dict()
        )
        for j, role in ((0, "prefill"), (1, "prefill"),
                        (2, "decode"), (3, "decode")):
            addr = f"10.0.9.{j}:8000"
            self.endpoints[addr] = Endpoint("m-disagg", j, role=role)
            self.store.create(
                _pod("m-disagg", j, addr, role=role, chips=8,
                     topology="2x4")
            )
        self.lb.sync_all()

    # -- scripted fetch (the no-sockets transport) -------------------------

    def _reachable(self, addr: str) -> bool:
        if addr == DEAD_ADDR:
            return False
        if addr == STALE_ADDR and self.tick_no >= STALE_AFTER_TICK:
            return False
        return True

    def fetch_metrics(self, addr: str, timeout: float) -> str:
        if not self._reachable(addr):
            raise ConnectionRefusedError(f"{addr} is down")
        return self.endpoints[addr].exposition()

    def fetch_state(self, addr: str, timeout: float) -> dict:
        if not self._reachable(addr):
            raise ConnectionRefusedError(f"{addr} is down")
        return self.endpoints[addr].state()

    def advance(self) -> None:
        self.tick_no += 1
        self.clock.advance(1.0)
        for ep in self.endpoints.values():
            ep.advance(self.tick_no)

    def active_totals(self) -> dict[str, float]:
        totals: dict[str, float] = {}
        for addr, ep in self.endpoints.items():
            if not self._reachable(addr):
                continue
            totals[ep.model] = (
                totals.get(ep.model, 0.0) + ep.signals["active"]
            )
        return totals

    def make_autoscaler(self, fleet=None) -> Autoscaler:
        class AlwaysLeader:
            is_leader = True

        a = Autoscaler(
            self.store, self.cfg, self.mc, self.lb, AlwaysLeader(),
            metrics=self.metrics,
        )
        a.active_scraper = lambda addrs: self.active_totals()
        a.queue_scraper = lambda addrs: scrape_queue_pressure(
            addrs, fetch=self.fetch_metrics
        )
        a.role_scraper = lambda addrs: scrape_role_signals(
            addrs, fetch=self.fetch_metrics
        )
        a.fleet = fleet
        return a


def _strip_volatile(decisions: list[dict]) -> list[dict]:
    out = []
    for d in decisions:
        d = copy.deepcopy(d)
        d.pop("ts", None)
        d.pop("scrape_duration_s", None)
        d.pop("telemetry_source", None)
        out.append(d)
    return sorted(out, key=lambda d: d["model"])


def run_sim(ticks: int = TICKS) -> dict:
    """Run the full scenario; returns measured facts for the tier-1
    invariant assertions (and the __main__ report)."""
    # -- two identical worlds: aggregator-fed vs direct-scrape ----------
    agg_world = FleetWorld()
    direct_world = FleetWorld()
    usage = UsageMeter(metrics=agg_world.metrics)
    aggregator = FleetStateAggregator(
        lb=agg_world.lb,
        model_client=agg_world.mc,
        store=agg_world.store,
        namespace="default",
        metrics=agg_world.metrics,
        usage=usage,
        interval_s=1.0,
        staleness_s=2.5,
        fetch_metrics=agg_world.fetch_metrics,
        fetch_state=agg_world.fetch_state,
        clock=agg_world.clock,
    )
    scaler_agg = agg_world.make_autoscaler(fleet=aggregator)
    scaler_direct = direct_world.make_autoscaler(fleet=None)

    # -- synthetic tenant traffic (exact-integer ledger check) ----------
    emitted: dict[tuple[str, str], dict] = {}
    decision_pairs: list[tuple[list[dict], list[dict]]] = []
    snapshots: list[dict] = []
    for _ in range(ticks):
        agg_world.advance()
        direct_world.advance()
        snap = aggregator.collect()
        snapshots.append(snap)
        # Tenant traffic: deterministic token counts per tenant×model.
        t = agg_world.tick_no
        for tenant, model, p, c in (
            ("acme", "m0", 100 + t, 10 * t),
            ("acme", "m1", 7, 3),
            ("globex", "m0", 55, 5 + t),
        ):
            usage.record(
                tenant, model, prompt_tokens=p, completion_tokens=c,
                stream_seconds=0.25, shed=(t % 3 == 0),
            )
            e = emitted.setdefault(
                (tenant, model),
                {"requests": 0, "prompt_tokens": 0,
                 "completion_tokens": 0, "shed": 0},
            )
            e["requests"] += 1
            e["prompt_tokens"] += p
            e["completion_tokens"] += c
            e["shed"] += 1 if t % 3 == 0 else 0
        scaler_agg.tick()
        scaler_direct.tick()
        decision_pairs.append(
            (
                _strip_volatile(scaler_agg.last_decisions),
                _strip_volatile(scaler_direct.last_decisions),
            )
        )

    # Convergence probe: two sweeps over frozen signals must agree on
    # every per-model view (ts and duration legitimately differ).
    snap_a = aggregator.collect()
    snap_b = aggregator.collect()

    return {
        "snapshots": snapshots,
        "final": snap_b,
        "frozen_pair": (snap_a, snap_b),
        "decision_pairs": decision_pairs,
        "agg_sources": [
            d.get("telemetry_source")
            for d in scaler_agg.last_decisions
        ],
        "usage_summary": usage.summary(),
        "emitted": emitted,
        "ticks": ticks,
    }


# -- invariant checks (imported by tests/unit/test_fleet_telemetry.py) --------


def check_coverage(result: dict) -> None:
    snap = result["final"]
    assert len(snap["models"]) >= 2, "needs >= 2 models"
    assert set(snap["models"]) == {"m0", "m1", "m-disagg"}
    for name, entry in snap["models"].items():
        live = [
            a for a, e in entry["endpoints"].items() if not e["stale"]
        ]
        assert entry["endpoints"], f"{name}: no endpoints in snapshot"
        for addr, e in entry["endpoints"].items():
            if not e["stale"]:
                assert "queue_depth" in e and "kv_utilization" in e, (
                    f"{name}/{addr}: missing per-endpoint signals"
                )
        assert live, f"{name}: no live endpoints"
    # Per-role signals + chip inventory present.
    dis = snap["models"]["m-disagg"]
    assert set(dis["replicas"]) == {"prefill", "decode"}
    assert set(dis["roles"]) == {"prefill", "decode"}
    assert dis["roles"]["decode"]["kv_utilization"] > 0
    assert snap["chips"]["total"] == (
        N_MODELS * REPLICAS * 4 + 4 * 8
    ), "chip inventory must sum pod google.com/tpu requests"
    assert "tpu-v5-lite-podslice/2x2" in snap["chips"]["by_shape"]
    assert "tpu-v5-lite-podslice/2x4" in snap["chips"]["by_shape"]


def check_convergence(result: dict) -> None:
    a, b = result["frozen_pair"]
    va = {m: e for m, e in a["models"].items()}
    vb = {m: e for m, e in b["models"].items()}
    # age_s moves with the clock only if the clock moved — it didn't.
    assert va == vb, "frozen signals must produce identical model views"


def check_staleness(result: dict) -> None:
    snap = result["final"]
    m0 = snap["models"]["m0"]
    dead = m0["endpoints"][DEAD_ADDR]
    assert dead["stale"] is True and dead["error"], (
        "dead endpoint must be flagged stale with its error"
    )
    assert DEAD_ADDR in m0["stale_endpoints"]
    # Aggregates exclude it EXACTLY: depth == sum over its live peers.
    live_depth = sum(
        e["queue_depth"] for a, e in m0["endpoints"].items()
        if not e["stale"]
    )
    assert m0["queue"]["depth"] == live_depth
    # The endpoint that died mid-run: fresh before, stale after.
    first = result["snapshots"][0]
    assert first["models"]["m1"]["endpoints"][STALE_ADDR]["stale"] is False
    m1 = snap["models"]["m1"]
    assert m1["endpoints"][STALE_ADDR]["stale"] is True
    assert STALE_ADDR in m1["stale_endpoints"]
    assert snap["stale_total"] >= 2


def check_tenant_accounting(result: dict) -> None:
    summary = result["usage_summary"]
    for (tenant, model), want in result["emitted"].items():
        got = summary["tenants"][tenant]["models"][model]
        for key in ("requests", "prompt_tokens", "completion_tokens",
                    "shed"):
            assert got[key] == want[key], (
                f"{tenant}/{model}.{key}: ledger {got[key]} != emitted "
                f"{want[key]}"
            )
    total_tokens = sum(
        w["prompt_tokens"] + w["completion_tokens"]
        for w in result["emitted"].values()
    )
    got_total = (
        summary["totals"]["prompt_tokens"]
        + summary["totals"]["completion_tokens"]
    )
    assert got_total == total_tokens, "ledger total must match emission"


def check_autoscaler_equivalence(result: dict) -> None:
    for i, (agg, direct) in enumerate(result["decision_pairs"]):
        assert agg == direct, (
            f"tick {i}: aggregator-fed decisions diverge from "
            f"direct-scrape:\n{json.dumps(agg, indent=1, sort_keys=True)}"
            f"\nvs\n{json.dumps(direct, indent=1, sort_keys=True)}"
        )
    # And the aggregator world really read the aggregator (no silent
    # fallback making the equality vacuous).
    for src in result["agg_sources"]:
        if isinstance(src, dict):  # disagg: per-role sources
            assert set(src.values()) == {"aggregator"}, src
        else:
            assert src == "aggregator", src


ALL_CHECKS = (
    check_coverage,
    check_convergence,
    check_staleness,
    check_tenant_accounting,
    check_autoscaler_equivalence,
)


def main() -> int:
    result = run_sim()
    for chk in ALL_CHECKS:
        chk(result)
        print(f"PASS {chk.__name__}")
    snap = result["final"]
    print(json.dumps(
        {
            "models": list(snap["models"]),
            "endpoints_total": snap["endpoints_total"],
            "stale_total": snap["stale_total"],
            "chips": snap["chips"],
            "tenant_totals": result["usage_summary"]["totals"],
            "ticks": result["ticks"],
        },
        indent=2, sort_keys=True,
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
