"""Deterministic cold-start simulation — fake clock, no sockets, no
device work.

Exercises the serverless-grade cold-start loop end to end with the REAL
components (`ColdStartTracker`, `ColdStartManager` + `SnapshotStore`
over a file:// bucket, `DemandForecaster`, `CapacityPlanner`,
`ActuationGovernor`) on a `FakeClock`:

  * BOOT PHASE MODEL — a full-load boot (HF conversion + XLA compile)
    vs a snapshot-restore boot, phase-timed through `ColdStartTracker`
    exactly as `engine/server.py` times them.
  * WARM vs COLD WORLD — one realtime model behind a demand ramp. Both
    worlds run the real planner over a scripted fleet snapshot ring;
    the WARM world wires the forecaster (restore-path boots), the COLD
    world scales reactively (full-load boots). Replicas ordered by the
    plan become Ready one boot-time later; capacity deficits register
    as realtime queue-pressure breaches.
  * SPOT TRIGGER — a rising SpotPreemption bucket orders replacement
    prewarms before the trend fit could notice.
  * MISMATCH — a published snapshot whose manifest is tampered to carry
    a different fingerprint: `fetch` must raise, the manager must fall
    back to the full load, and the mismatched tree must never serve.
  * GOVERNOR — a fenced (invalid-lease) governor must zero every
    prewarm grant; stale telemetry coverage must deny too.
  * PRICING — under a tight chip budget, demand chips flow to the
    expensive-to-boot model first, so preemption lands on the model
    whose replicas restore in seconds.

Invariants (asserted in tier-1 by tests/unit/test_coldstart_sim.py):

  (a) a snapshot-restore boot is >= 5x faster than the full-load boot
      in the phase model;
  (b) the prewarmed replica is Ready BEFORE the forecast spike lands
      (the tick where the cold world first breaches), and the warm
      world sees ZERO realtime queue-pressure breaches while the cold
      world breaches from the spike to the end of the run;
  (c) a fingerprint-mismatched snapshot is NEVER served — boot falls
      back to the full-load path (absent snapshots likewise);
  (d) prewarm actuations respect the governor: a fenced lease or stale
      telemetry zeroes the grant and lands in
      kubeai_prewarm_denied_total.

Run directly for a human-readable report:

    python benchmarks/coldstart_sim.py
"""

from __future__ import annotations

import glob as globmod
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeai_tpu.config.system import GovernorConfig
from kubeai_tpu.crd.model import ColdStart, Model, ModelSpec, Scheduling
from kubeai_tpu.engine.coldstart import ColdStartManager, ColdStartTracker
from kubeai_tpu.fleet import CapacityPlanner, DemandForecaster
from kubeai_tpu.metrics.registry import Metrics
from kubeai_tpu.objstore import SnapshotMismatch, SnapshotStore
from kubeai_tpu.operator import governor as governor_mod
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.testing.faults import FakeClock

# ---- boot phase model --------------------------------------------------------
#
# Durations picked to match the feature's premise (and the restore
# budget the renderer grants): a full load pays weight conversion plus
# XLA compilation; a restore pays a streamed fetch plus a cache-warm
# compile. The 5x invariant is asserted against whatever these sum to,
# so retuning the model retunes the assertion input, not the check.

FULL_PHASES = (("load", 310.0), ("compile", 170.0), ("warmup", 20.0))
RESTORE_PHASES = (
    ("fetch", 12.0), ("restore", 7.0), ("compile", 6.0), ("warmup", 10.0),
)
BOOT_FULL_S = sum(d for _, d in FULL_PHASES)        # 500s
BOOT_RESTORE_S = sum(d for _, d in RESTORE_PHASES)  # 35s

# ---- world constants ---------------------------------------------------------

TICK_S = 10.0
TICKS = 40
TARGET_REQUESTS = 10
MAX_REPLICAS = 8
CHIPS_PER_REPLICA = 4
PLATEAU = 50.0
QUEUE_WAIT_BOUND_S = 3.0  # the realtime queue-pressure SLO


def demand_at(tick: int) -> float:
    """Flat base load, then a linear ramp to a plateau — the 'spike is
    building' trajectory the trend trigger exists for."""
    if tick <= 3:
        return 8.0
    return min(PLATEAU, 8.0 + 2.0 * (tick - 3))


def _boot(phases, *, restored: bool):
    """One engine boot through the real tracker on a fake clock."""
    clock = FakeClock(50.0)
    tr = ColdStartTracker(clock)
    for name, dur in phases:
        with tr.phase(name):
            clock.advance(dur)
    tr.restored = restored
    tr.event("restored" if restored else "published")
    total = tr.finish()
    return total, tr.snapshot()


# ---- scripted fleet ----------------------------------------------------------


class ScriptedFleet:
    """Stands in for FleetStateAggregator: a snapshot ring the world
    appends to. `history()` / `snapshot()` are the only reads the
    forecaster and planner make; `model_coverage` answers the
    governor."""

    def __init__(self, clock, coverage=(1.0, True)):
        self._ring: list[dict] = []
        self._clock = clock
        self._coverage = coverage

    def push(self, models: dict) -> None:
        self._ring.append({"ts": self._clock(), "models": models})
        del self._ring[:-32]

    def snapshot(self):
        return self._ring[-1] if self._ring else None

    def history(self, n=None):
        return self._ring[-n:] if n else list(self._ring)

    def model_coverage(self, model):
        return self._coverage


class _Models:
    def __init__(self, *models):
        self._models = list(models)

    def list_all_models(self):
        return list(self._models)


class _FencedLease:
    """A leadership lease that fails its fence check: writes (including
    prewarm pod orders) must be refused."""

    is_leader = True

    def fence_valid(self) -> bool:
        return False


def _rt_model() -> Model:
    m = Model(
        name="rt",
        spec=ModelSpec(
            url="hf://org/rt",
            engine="KubeAITPU",
            features=["TextGeneration"],
            min_replicas=2,
            max_replicas=MAX_REPLICAS,
            target_requests=TARGET_REQUESTS,
            scheduling=Scheduling(default_priority="realtime"),
            cold_start=ColdStart(
                enabled=True, snapshot_url="gs://snaps/rt"
            ),
        ),
    )
    m.validate()
    return m


# ---- warm / cold worlds ------------------------------------------------------


class ColdStartWorld:
    """One realtime model under the demand ramp, scaled by the real
    planner. `prewarm=True` wires the forecaster and boots replicas
    through the restore path; `prewarm=False` is the reactive baseline
    paying the full load on every boot. `fence=True` additionally wires
    a governor whose lease fails its fence check."""

    def __init__(self, *, prewarm: bool, fence: bool = False):
        self.clock = FakeClock(1000.0)
        self.metrics = Metrics()
        self.fleet = ScriptedFleet(self.clock)
        self.prewarm = prewarm
        self.boot_s = BOOT_RESTORE_S if prewarm else BOOT_FULL_S
        self.model = _rt_model()
        governor = None
        if fence:
            governor = governor_mod.ActuationGovernor(
                leader=_FencedLease(), metrics=self.metrics,
                clock=self.clock,
            )
        self.planner = CapacityPlanner(
            self.fleet,
            _Models(self.model),
            budget_override={
                "v5e-2x2": {
                    "chips": 64, "slice_chips": CHIPS_PER_REPLICA,
                },
            },
            metrics=self.metrics,
            interval_s=TICK_S,
            clock=self.clock,
            governor=governor,
            forecaster=DemandForecaster(self.fleet) if prewarm else None,
        )
        now = self.clock()
        self.ready: list[float] = [now] * self.model.spec.min_replicas
        self.booting: list[float] = []
        self.breach_ticks: list[int] = []
        self.trajectory: list[dict] = []
        self.first_prewarm: dict | None = None
        self.last_record: dict | None = None

    def step(self, tick: int) -> None:
        self.clock.advance(TICK_S)
        now = self.clock()
        # Boots ordered one boot-time ago become Ready.
        self.ready += [t for t in self.booting if t <= now]
        self.booting = [t for t in self.booting if t > now]
        demand = demand_at(tick)
        capacity = float(TARGET_REQUESTS * len(self.ready))
        unserved = max(0.0, demand - capacity)
        if unserved > 0:
            # Requests the ready pool cannot absorb queue past the
            # realtime wait bound within the tick: an SLO breach.
            self.breach_ticks.append(tick)
        served = demand - unserved
        n = len(self.ready)
        endpoints = {
            f"10.0.0.{i + 1}:8000": {
                "active_requests": served / n,
                "stale": False,
                "cold_start": {
                    "total_s": self.boot_s,
                    "restored": self.prewarm,
                },
            }
            for i in range(n)
        }
        total_pods = n + len(self.booting)
        self.fleet.push({
            "rt": {
                "queue": {
                    "depth": unserved,
                    "oldest_wait_s": (
                        QUEUE_WAIT_BOUND_S + 2.0 if unserved else 0.0
                    ),
                    "per_class": {},
                },
                "endpoints": endpoints,
                "pods": {
                    "total": total_pods,
                    "chips": CHIPS_PER_REPLICA * total_pods,
                    "by_disruption": {},
                },
                "replicas": {"unified": n},
            },
        })
        plan = self.planner.tick(force=True)
        rec = plan["models"]["rt"]
        self.last_record = rec
        orders = rec["allocated_replicas"] - total_pods
        for _ in range(max(0, orders)):
            self.booting.append(now + self.boot_s)
        if orders > 0 and rec["prewarm_replicas"] and not self.first_prewarm:
            self.first_prewarm = {
                "tick": tick,
                "ordered_at": now,
                "ready_at": now + self.boot_s,
                "trigger": rec["prewarm_trigger"],
            }
        self.trajectory.append({
            "tick": tick,
            "demand": demand,
            "capacity": capacity,
            "unserved": unserved,
            "allocated": rec["allocated_replicas"],
            "prewarm": rec["prewarm_replicas"],
        })

    def facts(self) -> dict:
        m = self.metrics
        return {
            "breach_ticks": list(self.breach_ticks),
            "trajectory": self.trajectory,
            "first_prewarm": self.first_prewarm,
            "last_record": self.last_record,
            "prewarm_orders_trend": m.prewarm_orders.get(
                model="rt", trigger="trend"
            ),
            "prewarm_denied": m.prewarm_denied.get(model="rt"),
            "fenced_writes": m.leader_fenced_writes.get(),
            "denied_lease": m.governor_denied.get(
                action=governor_mod.ACTION_PREWARM, model="rt",
                reason=governor_mod.DENY_LEASE,
            ),
        }


# ---- spot-trigger scenario ---------------------------------------------------


def run_spot_scenario() -> dict:
    """Two spot preemptions land in the pod inventory: the planner must
    prewarm one replacement per disrupted pod with the 'spot' trigger
    (the early warning outranks the trend fit)."""
    clock = FakeClock(2000.0)
    metrics = Metrics()
    fleet = ScriptedFleet(clock)
    model = _rt_model()
    planner = CapacityPlanner(
        fleet,
        _Models(model),
        budget_override={
            "v5e-2x2": {"chips": 64, "slice_chips": CHIPS_PER_REPLICA},
        },
        metrics=metrics,
        interval_s=TICK_S,
        clock=clock,
        forecaster=DemandForecaster(fleet),
    )
    for disruptions in (0, 0, 2):
        clock.advance(TICK_S)
        fleet.push({
            "rt": {
                "queue": {
                    "depth": 0.0, "oldest_wait_s": 0.0, "per_class": {},
                },
                "endpoints": {
                    "10.0.0.1:8000": {
                        "active_requests": 5.0,
                        "stale": False,
                        "cold_start": {
                            "total_s": BOOT_RESTORE_S, "restored": True,
                        },
                    },
                },
                "pods": {
                    "total": 2,
                    "chips": 2 * CHIPS_PER_REPLICA,
                    "by_disruption": {
                        k8sutils.REASON_SPOT_PREEMPTION: disruptions,
                    },
                },
                "replicas": {"unified": 2},
            },
        })
    plan = planner.tick(force=True)
    rec = plan["models"]["rt"]
    return {
        "record": rec,
        "orders_metric": metrics.prewarm_orders.get(
            model="rt", trigger="spot"
        ),
    }


# ---- mismatch scenario -------------------------------------------------------


class _Mesh:
    shape = {"data": 1, "model": 1}


def run_mismatch_scenario() -> dict:
    """Publish a snapshot over a file:// bucket, then tamper the
    manifest to claim a different fingerprint (a stale overwrite or
    corruption). The store must raise, and the manager must serve the
    full-load params — the mismatched tree never serves. A clean
    config-drift lookup (different fingerprint, nothing published
    there) reads as absent and full-loads too."""
    root = tempfile.mkdtemp(prefix="coldstart-sim-")
    try:
        url = "file://" + os.path.join(root, "snaps")
        store = SnapshotStore(url)
        ecfg = {"num_slots": 8, "max_seq_len": 512}
        mgr = ColdStartManager(
            url, "rt", ecfg, _Mesh(),
            work_dir=os.path.join(root, "boot1"),
            clock=FakeClock(0.0), store=store,
        )
        stage = os.path.join(root, "stage")
        os.makedirs(os.path.join(stage, "params"))
        with open(os.path.join(stage, "params", "arr0.bin"), "wb") as f:
            f.write(b"\x00" * 64)
        store.publish("rt", mgr.fingerprint, stage)
        [man_path] = globmod.glob(
            os.path.join(root, "snaps", "**", "MANIFEST.json"),
            recursive=True,
        )
        with open(man_path) as f:
            man = json.load(f)
        man["fingerprint"] = "deadbeefdeadbeef"
        with open(man_path, "w") as f:
            json.dump(man, f)

        fetch_raised = False
        try:
            store.fetch("rt", mgr.fingerprint, os.path.join(root, "dl"))
        except SnapshotMismatch:
            fetch_raised = True

        sentinel = object()
        served = mgr.acquire_params(lambda: sentinel)

        drift = ColdStartManager(
            url, "rt", {**ecfg, "num_slots": 16}, _Mesh(),
            work_dir=os.path.join(root, "boot2"),
            clock=FakeClock(0.0), store=store,
        )
        served_drift = drift.acquire_params(lambda: sentinel)
        return {
            "fetch_raised": fetch_raised,
            "mismatch_events": list(mgr.tracker.events),
            "mismatch_full_load": served is sentinel,
            "mismatch_restored": mgr.tracker.restored,
            "drift_events": list(drift.tracker.events),
            "drift_full_load": served_drift is sentinel,
            "fingerprints_differ": mgr.fingerprint != drift.fingerprint,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)
        # acquire_params pointed JAX's persistent compilation cache at
        # the (now deleted) work dir; detach it so nothing later in the
        # process tries to write there.
        import contextlib

        with contextlib.suppress(Exception):
            import jax

            jax.config.update("jax_compilation_cache_dir", None)


# ---- governor stale-telemetry denial -----------------------------------------


def run_stale_governor_scenario() -> dict:
    """An armed governor over a stale snapshot ring: a blind forecaster
    must not spend chips."""
    metrics = Metrics()
    gov = governor_mod.ActuationGovernor(
        cfg=GovernorConfig(min_telemetry_coverage=0.5),
        fleet=ScriptedFleet(FakeClock(0.0), coverage=(1.0, False)),
        metrics=metrics,
        clock=FakeClock(0.0),
    )
    return {
        "allowed": gov.allow_prewarm("rt"),
        "denied": metrics.prewarm_denied.get(model="rt"),
        "denied_stale": metrics.governor_denied.get(
            action=governor_mod.ACTION_PREWARM, model="rt",
            reason=governor_mod.DENY_STALE,
        ),
    }


# ---- cold-start-priced preemption --------------------------------------------


def run_pricing_scenario() -> dict:
    """Two standard-class models, identical demand, a budget one chip
    short: the demand fill must favor the expensive-to-boot model so
    the shortfall (throttle -> preemption) lands on the model whose
    replicas restore from a snapshot in seconds."""
    clock = FakeClock(3000.0)
    metrics = Metrics()
    fleet = ScriptedFleet(clock)

    def mk(name: str) -> Model:
        m = Model(
            name=name,
            spec=ModelSpec(
                url=f"hf://org/{name}",
                engine="KubeAITPU",
                features=["TextGeneration"],
                min_replicas=0,
                max_replicas=8,
                target_requests=TARGET_REQUESTS,
                cold_start=ColdStart(
                    enabled=True, snapshot_url="gs://snaps/x"
                ),
            ),
        )
        m.validate()
        return m

    def entry(cost: float, restored: bool) -> dict:
        return {
            "queue": {"depth": 0.0, "oldest_wait_s": 0.0, "per_class": {}},
            "endpoints": {
                "10.0.0.1:8000": {
                    "active_requests": 20.0,
                    "stale": False,
                    "cold_start": {"total_s": cost, "restored": restored},
                },
            },
            "pods": {"total": 2, "chips": 2, "by_disruption": {}},
            "replicas": {"unified": 2},
        }

    planner = CapacityPlanner(
        fleet,
        _Models(mk("cheap"), mk("exp")),
        budget_override={"v5e-1x1": {"chips": 3, "slice_chips": 1}},
        metrics=metrics,
        interval_s=TICK_S,
        clock=clock,
        forecaster=DemandForecaster(fleet),
    )
    clock.advance(1.0)
    fleet.push({
        "cheap": entry(28.0, True),   # restores in seconds
        "exp": entry(420.0, False),   # recompiles for minutes
    })
    plan = planner.tick(force=True)
    return {
        "cheap": plan["models"]["cheap"],
        "exp": plan["models"]["exp"],
    }


# ---- sim driver --------------------------------------------------------------


def run_sim(ticks: int = TICKS) -> dict:
    full_s, full_snap = _boot(FULL_PHASES, restored=False)
    restore_s, restore_snap = _boot(RESTORE_PHASES, restored=True)
    warm = ColdStartWorld(prewarm=True)
    cold = ColdStartWorld(prewarm=False)
    fenced = ColdStartWorld(prewarm=True, fence=True)
    for t in range(ticks):
        warm.step(t)
        cold.step(t)
        fenced.step(t)
    return {
        "ticks": ticks,
        "boot": {
            "full_s": full_s,
            "restore_s": restore_s,
            "full_snapshot": full_snap,
            "restore_snapshot": restore_snap,
        },
        "warm": warm.facts(),
        "cold": cold.facts(),
        "fenced": fenced.facts(),
        "spot": run_spot_scenario(),
        "mismatch": run_mismatch_scenario(),
        "stale_governor": run_stale_governor_scenario(),
        "pricing": run_pricing_scenario(),
    }


# ---- invariant checks (imported by tests/unit/test_coldstart_sim.py) ---------


def check_restore_speedup(result: dict) -> None:
    """(a) Restore-path boot >= 5x faster than full load in the phase
    model, with both boots fully phase-timed by the real tracker."""
    boot = result["boot"]
    assert boot["restore_s"] > 0
    assert boot["full_s"] >= 5.0 * boot["restore_s"], (
        boot["full_s"], boot["restore_s"],
    )
    assert boot["full_snapshot"]["phases"] == dict(FULL_PHASES)
    assert boot["restore_snapshot"]["phases"] == dict(RESTORE_PHASES)
    assert boot["restore_snapshot"]["restored"] is True
    assert boot["full_snapshot"]["restored"] is False
    assert boot["full_snapshot"]["total_s"] == sum(
        d for _, d in FULL_PHASES
    )


def check_prewarm_beats_spike(result: dict) -> None:
    """(b) The warm world's first prewarmed replica is Ready before the
    spike lands (the cold world's first breach tick), the warm world
    never breaches the realtime queue-pressure bound, and the cold
    world breaches from the spike to the end of the run."""
    warm, cold = result["warm"], result["cold"]
    assert warm["breach_ticks"] == [], warm["breach_ticks"]
    assert cold["breach_ticks"], "reactive baseline must breach"
    spike_tick = cold["breach_ticks"][0]
    # The full-load boot never matures inside the run: once demand
    # outruns capacity the baseline stays underwater.
    assert cold["breach_ticks"] == list(
        range(spike_tick, result["ticks"])
    )
    fp = warm["first_prewarm"]
    assert fp is not None, "the trend trigger must order a prewarm"
    assert fp["trigger"] == "trend"
    assert fp["tick"] < spike_tick
    spike_clock = 1000.0 + TICK_S * (spike_tick + 1)
    assert fp["ready_at"] < spike_clock, (fp, spike_clock)
    assert warm["prewarm_orders_trend"] >= 1
    rec = warm["last_record"]
    assert rec["forecast"]["model"] == "rt"
    assert rec["coldstart_cost_s"] == BOOT_RESTORE_S
    # Clamps hold throughout: maxReplicas and the chip budget.
    for point in warm["trajectory"]:
        assert point["allocated"] <= MAX_REPLICAS
        assert point["allocated"] * CHIPS_PER_REPLICA <= 64


def check_spot_trigger(result: dict) -> None:
    """Rising spot preemptions order one replacement per disrupted pod,
    labelled with the 'spot' trigger."""
    rec = result["spot"]["record"]
    assert rec["prewarm_trigger"] == "spot"
    assert rec["prewarm_replicas"] == 2
    assert rec["forecast"]["trigger"] == "spot"
    assert result["spot"]["orders_metric"] == 2


def check_mismatch_never_serves(result: dict) -> None:
    """(c) A fingerprint-mismatched snapshot raises at the store and
    full-loads at the manager; a clean different-fingerprint lookup
    reads as absent and full-loads too. Neither path ever serves a
    restored tree."""
    mm = result["mismatch"]
    assert mm["fetch_raised"] is True
    assert "mismatch" in mm["mismatch_events"]
    assert "restored" not in mm["mismatch_events"]
    assert mm["mismatch_full_load"] is True
    assert mm["mismatch_restored"] is False
    assert mm["fingerprints_differ"] is True
    assert "absent" in mm["drift_events"]
    assert mm["drift_full_load"] is True


def check_governor_gates_prewarm(result: dict) -> None:
    """(d) A fenced lease zeroes every prewarm grant and lands the
    denial in the prewarm-denied and governor counters; stale telemetry
    coverage denies too; the permissive default (warm world) grants."""
    fenced = result["fenced"]
    for point in fenced["trajectory"]:
        assert point["prewarm"] == 0, point
    assert fenced["prewarm_orders_trend"] == 0
    assert fenced["prewarm_denied"] >= 1
    assert fenced["fenced_writes"] >= 1
    assert fenced["denied_lease"] >= 1
    stale = result["stale_governor"]
    assert stale["allowed"] is False
    assert stale["denied"] >= 1 and stale["denied_stale"] >= 1
    assert result["warm"]["prewarm_orders_trend"] >= 1


def check_priced_preemption(result: dict) -> None:
    """Cold-start pricing: the expensive-to-boot model keeps its
    replicas; the cheap-restore model absorbs the shortfall."""
    cheap, exp = result["pricing"]["cheap"], result["pricing"]["exp"]
    assert exp["coldstart_cost_s"] > cheap["coldstart_cost_s"]
    assert exp["allocated_replicas"] == 2
    assert exp["preempted_replicas"] == 0
    assert cheap["allocated_replicas"] == 1
    assert cheap["preempted_replicas"] == 1
    assert cheap["forecast"]["restore_available"] is True
    assert exp["forecast"]["restore_available"] is False


ALL_CHECKS = (
    check_restore_speedup,
    check_prewarm_beats_spike,
    check_spot_trigger,
    check_mismatch_never_serves,
    check_governor_gates_prewarm,
    check_priced_preemption,
)


def main() -> int:
    result = run_sim()
    for chk in ALL_CHECKS:
        chk(result)
        print(f"PASS {chk.__name__}")
    warm, cold = result["warm"], result["cold"]
    print(json.dumps(
        {
            "boot": {
                "full_s": result["boot"]["full_s"],
                "restore_s": result["boot"]["restore_s"],
                "speedup": round(
                    result["boot"]["full_s"]
                    / result["boot"]["restore_s"], 2
                ),
            },
            "warm_breach_ticks": warm["breach_ticks"],
            "cold_breach_ticks": cold["breach_ticks"],
            "first_prewarm": warm["first_prewarm"],
            "prewarm_orders": warm["prewarm_orders_trend"],
            "fenced_denials": result["fenced"]["prewarm_denied"],
            "pricing": {
                name: {
                    "allocated": rec["allocated_replicas"],
                    "preempted": rec["preempted_replicas"],
                    "coldstart_cost_s": rec["coldstart_cost_s"],
                }
                for name, rec in result["pricing"].items()
            },
            "ticks": result["ticks"],
        },
        indent=2, sort_keys=True,
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
