"""Operator binary entrypoint (reference: cmd/main.go:28-53).

    python -m kubeai_tpu [--config PATH]

Reads the system config from --config / $CONFIG_PATH (default
./config.yaml, matching the reference), connects to the Kubernetes API
(in-cluster service account when available, else an in-memory store for
local development), and runs the Manager until SIGINT/SIGTERM.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="kubeai-tpu")
    ap.add_argument(
        "--config",
        default=os.environ.get("CONFIG_PATH", "./config.yaml"),
        help="system config file (default $CONFIG_PATH or ./config.yaml)",
    )
    ap.add_argument("--api-host", default="0.0.0.0")
    ap.add_argument("--api-port", type=int, default=8000)
    ap.add_argument("--namespace", default=os.environ.get("POD_NAMESPACE", "default"))
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    log = logging.getLogger("kubeai-tpu")

    from kubeai_tpu.config import System, load_config_file
    from kubeai_tpu.operator.k8s.store import KubeStore
    from kubeai_tpu.operator.manager import Manager

    if os.path.exists(args.config):
        cfg = load_config_file(args.config)
        log.info("loaded config from %s", args.config)
    else:
        cfg = System()
        log.warning("config file %s not found; using defaults", args.config)

    # K8s API: in-cluster REST when a service account is mounted, else the
    # in-memory store (local development / demo mode).
    sa_token = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    if os.path.exists(sa_token):
        try:
            from kubeai_tpu.operator.k8s.rest import RestKubeClient

            store = RestKubeClient.in_cluster()
            log.info("connected to in-cluster Kubernetes API")
        except Exception as e:
            log.error("in-cluster API connection failed: %s", e)
            return 1
    else:
        store = KubeStore()
        log.warning("no in-cluster credentials; running with in-memory store")

    mgr = Manager(
        store,
        cfg,
        api_host=args.api_host,
        api_port=args.api_port,
        namespace=args.namespace,
    )
    mgr.start()
    log.info("kubeai-tpu operator serving on %s", mgr.api_address)

    stop = threading.Event()

    def _sig(_s, _f):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    stop.wait()
    log.info("shutting down")
    mgr.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
