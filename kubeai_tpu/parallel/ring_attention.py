"""Ring attention: causal self-attention with the sequence sharded over the
`sp` mesh axis — the long-context mechanism the reference lacks entirely
(SURVEY.md §5.7: "ring attention / context parallel ... absent"; sequence
length there is just an engine arg, charts/models/values.yaml:117).

Design (blockwise attention + ring K/V rotation — the standard TPU recipe):
  - each device holds a contiguous sequence shard of q, k, v;
  - sp_size steps: compute blockwise attention of the LOCAL q shard against
    the currently-held K/V shard with online-softmax accumulation, then
    rotate K/V one hop around the ring with `jax.lax.ppermute` (XLA lowers
    this onto ICI; compute of step i overlaps the DMA of step i+1);
  - causal masking is by GLOBAL position: a K/V shard entirely in the
    future contributes nothing (fully masked block), so the mask math
    handles it without control flow.

Exposed as `ring_causal_attention` (shard_map-ready: operates on the local
shards inside a mesh context) and `ring_attention_sharded` (wraps
shard_map over a Mesh for whole-array inputs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeai_tpu.parallel.mesh import AXIS_SEQ

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, scale):
    """One blockwise attention step with running-softmax stats.

    q: [B, Sq, H, D]; k/v: [B, Sk, KVH, D]; positions are global indices.
    Returns (scores_max [B,H,Sq,1], exp_sums, weighted_values) for online
    combination.
    """
    B, Sq, H, D = q.shape
    KVH = k.shape[2]
    qg = (q * scale).reshape(B, Sq, KVH, H // KVH, D)
    logits = jnp.einsum(
        "bqkgd,bskd->bkgqs",
        qg.astype(jnp.float32),
        k.astype(jnp.float32),
    )  # [B, KVH, G, Sq, Sk]
    mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]
    logits = jnp.where(mask[None, None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)  # [B, KVH, G, Sq, 1]
    p = jnp.exp(logits - m)
    # Fully-masked rows: m = NEG_INF -> p = exp(0) = 1 would pollute; zero
    # them via the mask instead.
    p = jnp.where(mask[None, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bkgqs,bskd->bkgqd", p, v.astype(jnp.float32))
    return m, l, o


def ring_causal_attention(
    q: jnp.ndarray,  # [B, S_local, H, D] — this device's sequence shard
    k: jnp.ndarray,  # [B, S_local, KVH, D]
    v: jnp.ndarray,
    *,
    axis_name: str = AXIS_SEQ,
    scale: float | None = None,
) -> jnp.ndarray:
    """Runs INSIDE shard_map over the sp axis. Returns the local q shard's
    attention output [B, S_local, H, D]."""
    B, S, H, D = q.shape
    KVH = k.shape[2]
    G = H // KVH
    scale = scale if scale is not None else D ** -0.5
    sp = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    q_pos = my * S + jnp.arange(S)

    m_acc = jnp.full((B, KVH, G, S, 1), NEG_INF, jnp.float32)
    l_acc = jnp.zeros((B, KVH, G, S, 1), jnp.float32)
    o_acc = jnp.zeros((B, KVH, G, S, D), jnp.float32)

    def step(i, carry):
        m_acc, l_acc, o_acc, k_cur, v_cur = carry
        # The shard we hold at step i originated on device (my - i) mod sp.
        src = (my - i) % sp
        k_pos = src * S + jnp.arange(S)
        m, l, o = _block_attend(q, k_cur, v_cur, q_pos, k_pos, scale)
        m_new = jnp.maximum(m_acc, m)
        a_old = jnp.exp(m_acc - m_new)
        a_blk = jnp.exp(m - m_new)
        l_new = l_acc * a_old + l * a_blk
        o_new = o_acc * a_old + o * a_blk
        # Rotate K/V one hop: device d sends to d+1 (ring over ICI).
        perm = [(j, (j + 1) % sp) for j in range(sp)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, o_new, k_nxt, v_nxt

    m_acc, l_acc, o_acc, _, _ = jax.lax.fori_loop(
        0, sp, step, (m_acc, l_acc, o_acc, k, v)
    )
    out = o_acc / jnp.maximum(l_acc, 1e-30)
    return out.reshape(B, KVH * G, S, D).transpose(0, 2, 1, 3).astype(q.dtype)

def ring_attention_sharded(
    q: jnp.ndarray,  # [B, S, H, D] global arrays
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = AXIS_SEQ,
) -> jnp.ndarray:
    """Whole-array convenience wrapper: shards the sequence over `axis_name`
    via shard_map and runs the ring. S must divide by the axis size."""
    fn = functools.partial(ring_causal_attention, axis_name=axis_name)
    spec = P(None, axis_name, None, None)
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
