"""Pipeline parallelism (PP): GPipe-style microbatched layer stages.

SURVEY §2's TPU-equivalents list calls for TP/DP(/PP for >8B). TP shards
every matmul; PP shards the LAYER STACK: stage s owns layers
[s*L/P, (s+1)*L/P) and activations hop stage-to-stage over ICI/DCN with
`lax.ppermute` inside a `shard_map` over the `pp` mesh axis — no
hand-written NCCL analog, just XLA collectives (reference has no PP at
all; its engines are single-Pod, internal/modelcontroller/pod_plan.go).

Schedule: classic GPipe fill/drain. With M microbatches and P stages the
loop runs M + P - 1 ticks; at tick t stage s works on microbatch t - s.
Stages run identical programs (SPMD): off-schedule ticks compute on
padding and their results are discarded. Steady-state utilization is
M / (M + P - 1) — pick M >= P.

The stacked-layer model layout ([num_layers, ...] leading axis on every
layer param — see models/llama.py) makes PP a pure RESHARDING choice:
the same param tree pipelines by sharding its leading axis over `pp`.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeai_tpu.parallel.mesh import AXIS_PIPELINE


def pipeline_forward(
    layer_fn: Callable,  # (x [mb, ...], layer_params) -> x
    stacked_params,  # pytree, every leaf [num_layers, ...]
    x: jnp.ndarray,  # [batch, ...] activations
    mesh: Mesh,
    microbatches: int,
) -> jnp.ndarray:
    """Run x through all layers, layer stack sharded over the pp axis.

    Semantically identical to `lax.scan(layer_fn, x, stacked_params)`
    (tested against it); the difference is WHERE layers live: each pp
    stage holds only its slice of every layer param.
    """
    n_stages = mesh.shape[AXIS_PIPELINE]
    batch = x.shape[0]
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    if batch % microbatches:
        raise ValueError(f"batch {batch} not divisible by M={microbatches}")
    num_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if num_layers % n_stages:
        raise ValueError(
            f"{num_layers} layers not divisible by {n_stages} pp stages"
        )
    if n_stages == 1:
        return jax.lax.scan(
            lambda h, p: (layer_fn(h, p), None), x, stacked_params
        )[0]

    mb = batch // microbatches
    x_mb = x.reshape(microbatches, mb, *x.shape[1:])
    ticks = microbatches + n_stages - 1

    # Params: leading layer axis sharded over pp; everything else of the
    # computation is replicated across pp (tp/sp sharding inside
    # layer_fn would need shard_map nesting — one axis at a time here).
    param_specs = jax.tree_util.tree_map(
        lambda _: P(AXIS_PIPELINE), stacked_params
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    def run(local_params, x_mb):
        stage = jax.lax.axis_index(AXIS_PIPELINE)
        last = n_stages - 1
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def local_layers(h):
            return jax.lax.scan(
                lambda c, p: (layer_fn(c, p), None), h, local_params
            )[0]

        def tick(carry, t):
            buf, out = carry
            # Stage 0 injects microbatch t (clamped; off-schedule ticks
            # recompute a stale microbatch and the result is ignored).
            inject = x_mb[jnp.clip(t, 0, microbatches - 1)]
            h = jnp.where(stage == 0, inject, buf)
            y = local_layers(h)
            mb_idx = t - last
            store = (stage == last) & (mb_idx >= 0)
            out = jnp.where(
                store,
                out.at[jnp.clip(mb_idx, 0, microbatches - 1)].set(y),
                out,
            )
            buf_next = jax.lax.ppermute(y, AXIS_PIPELINE, fwd)
            return (buf_next, out), None

        zero = jnp.zeros_like(x_mb[0])
        out0 = jnp.zeros_like(x_mb)
        (_, out), _ = jax.lax.scan(
            tick, (zero, out0), jnp.arange(ticks)
        )
        # Only the last stage holds real outputs; replicate them.
        out = jnp.where(stage == last, out, jnp.zeros_like(out))
        return jax.lax.psum(out, AXIS_PIPELINE)

    out = run(stacked_params, x_mb)
    return out.reshape(batch, *x.shape[1:])
