"""Device-mesh construction from TPU slice topologies.

The reference exposes TPU topology only as GKE nodeSelectors on resource
profiles (reference: charts/kubeai/values-gke.yaml:18-41,
`google-tpu-v5e-1x1|2x2|2x4` with `gke-tpu-topology: 2x2` etc.). Here the
same topology string drives an actual `jax.sharding.Mesh`: within a slice,
axes map onto ICI; across slices/hosts, the data axis rides DCN.

Axes (logical):
  dp  — data parallel (whole-request replication; across slices → DCN)
  pp  — pipeline parallel (layer stages; see parallel/pipeline.py)
  tp  — tensor parallel (weight sharding; within slice → ICI)
  sp  — sequence parallel (ring attention for long context; ICI)
  ep  — expert parallel (MoE; ICI)

`ep` is folded over the same devices as `tp` via mesh axis reuse: MoE layers
reinterpret the tensor axis as the expert axis (common TPU practice — keeps
one physical mesh).
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "dp"
AXIS_PIPELINE = "pp"
AXIS_TENSOR = "tp"
AXIS_SEQ = "sp"
AXIS_EXPERT = "ep"

# Standard mesh axis order. tp innermost: adjacent devices share the fastest
# ICI links, and tensor-parallel collectives (psum of partial matmul results)
# are the most latency-sensitive. pp outermost after dp: stage hops are
# point-to-point and the least latency-sensitive.
MESH_AXES = (AXIS_DATA, AXIS_PIPELINE, AXIS_SEQ, AXIS_TENSOR)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Product must equal the device count."""

    dp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp

    def axis_sizes(self) -> tuple[int, int, int, int]:
        return (self.dp, self.pp, self.sp, self.tp)


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse a GKE-style TPU topology string like '2x2' or '2x2x4'.

    Mirrors the `gke-tpu-topology` nodeSelector values the reference's TPU
    resource profiles use (reference: charts/kubeai/values-gke.yaml:26-41).
    """
    if not re.fullmatch(r"\d+(x\d+)*", topology):
        raise ValueError(f"invalid TPU topology {topology!r}")
    return tuple(int(p) for p in topology.split("x"))


def topology_num_chips(topology: str) -> int:
    return math.prod(parse_topology(topology))


def mesh_from_topology(
    topology: str,
    *,
    tp: int | None = None,
    sp: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh for one TPU slice described by a topology string.

    By default the whole slice is tensor-parallel (tp = chip count), matching
    the reference's catalog choice of `--tensor-parallel-size=<chips>`
    (reference: charts/models/values.yaml:128).
    """
    n = topology_num_chips(topology)
    if tp is None:
        tp = n // sp
    cfg = MeshConfig(dp=n // (tp * sp), sp=sp, tp=tp)
    return build_mesh(cfg, devices=devices)


def build_mesh(
    cfg: MeshConfig, *, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a Mesh with axes (dp, pp, sp, tp) over the given devices."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if cfg.num_devices != len(devices):
        raise ValueError(
            f"mesh {cfg} needs {cfg.num_devices} devices, got {len(devices)}"
        )
    arr = np.asarray(devices).reshape(cfg.axis_sizes())
    return Mesh(arr, MESH_AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    if device is None:
        device = jax.devices()[0]
    return build_mesh(MeshConfig(), devices=[device])
