"""Logical-axis sharding rules (GSPMD) for model parameters and activations.

The reference has no sharding code at all — tensor parallelism is an opaque
`--tensor-parallel-size` engine arg (reference: charts/models/values.yaml:128,
SURVEY.md §2 "Parallelism accounting"). Here it is explicit: every parameter
and activation carries *logical* axis names, and a `ShardingRules` table maps
them to physical mesh axes. Megatron-style TP for transformers:

  - attn qkv / mlp up+gate: column-parallel (shard output feature dim on tp)
  - attn out / mlp down:    row-parallel    (shard input feature dim on tp)
  - embeddings:             shard vocab on tp
  - activations:            batch on dp, optionally sequence on sp

XLA inserts the psum/all-gather collectives over ICI; we never write NCCL-
style comms by hand.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeai_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_PIPELINE,
    AXIS_SEQ,
    AXIS_TENSOR,
)

# Logical axis names used across models.
BATCH = "batch"
LAYERS = "layers"  # stacked-layer axis (pipeline stages shard it)
SEQUENCE = "sequence"
VOCAB = "vocab"
EMBED = "embed"
HEADS = "heads"
KV_HEADS = "kv_heads"
HEAD_DIM = "head_dim"
MLP = "mlp"
EXPERT = "expert"
KV_SLOTS = "kv_slots"  # KV-cache slot (request) axis
LORA_RANK = "lora_rank"


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Map logical axis name -> physical mesh axis (or None = replicate)."""

    rules: tuple[tuple[str, str | None], ...] = (
        (LAYERS, AXIS_PIPELINE),  # pp=1 meshes: axis size 1 → replicated
        (BATCH, AXIS_DATA),
        (SEQUENCE, AXIS_SEQ),
        (VOCAB, AXIS_TENSOR),
        (EMBED, None),
        (HEADS, AXIS_TENSOR),
        (KV_HEADS, AXIS_TENSOR),
        (HEAD_DIM, None),
        (MLP, AXIS_TENSOR),
        (EXPERT, AXIS_TENSOR),  # MoE experts reuse the tp axis (see mesh.py)
        (KV_SLOTS, AXIS_DATA),
        (LORA_RANK, None),
    )

    def physical(self, logical_axis: str | None) -> str | None:
        if logical_axis is None:
            return None
        for name, phys in self.rules:
            if name == logical_axis:
                return phys
        raise KeyError(f"no sharding rule for logical axis {logical_axis!r}")

    def spec(self, logical_axes: tuple[str | None, ...]) -> P:
        return P(*(self.physical(a) for a in logical_axes))


DEFAULT_RULES = ShardingRules()


def logical_to_physical(
    logical_axes: tuple[str | None, ...],
    rules: ShardingRules = DEFAULT_RULES,
) -> P:
    return rules.spec(logical_axes)


def named_sharding(
    mesh: Mesh,
    logical_axes: tuple[str | None, ...],
    rules: ShardingRules = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(logical_axes))


def shard_params(
    params: Any,
    logical_specs: Any,
    mesh: Mesh,
    rules: ShardingRules = DEFAULT_RULES,
) -> Any:
    """Device-put a param pytree according to a matching pytree of logical
    axis tuples. Works for host → sharded-device transfer (weight loading)."""

    def _put(x, axes):
        return jax.device_put(x, named_sharding(mesh, axes, rules))

    return jax.tree.map(_put, params, logical_specs)


def param_shardings(
    logical_specs: Any, mesh: Mesh, rules: ShardingRules = DEFAULT_RULES
) -> Any:
    """Pytree of NamedShardings (for jit in_shardings/out_shardings)."""
    return jax.tree.map(
        lambda axes: named_sharding(mesh, axes, rules),
        logical_specs,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(a, (str, type(None))) for a in x),
    )
