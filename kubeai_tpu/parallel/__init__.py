"""Parallelism layer: device meshes, sharding rules, collectives.

TPU-native equivalent of what the reference delegates to vLLM's Ray/NCCL
executor (reference: charts/models/values.yaml:131-140 — `--tensor-parallel-size=4`
passed as engine args). Here parallelism is first-class: a `jax.sharding.Mesh`
built from the TPU slice topology, with GSPMD/pjit inserting XLA collectives
over ICI.
"""

from kubeai_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    mesh_from_topology,
    AXIS_DATA,
    AXIS_TENSOR,
    AXIS_SEQ,
    AXIS_EXPERT,
)
from kubeai_tpu.parallel.sharding import (
    ShardingRules,
    logical_to_physical,
    shard_params,
)
