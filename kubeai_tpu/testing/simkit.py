"""Shared scaffolding for the deterministic `benchmarks/*_sim.py` fleet.

Every sim used to re-implement the same five helpers (a percentile, a
seeded RNG, a `Model` factory, pod Ready/broken status flips, a metric
scrape diff). They live here now so a new sim — and the game-day
harness that composes several sims' worth of chaos — builds on one
audited version of each.

Nothing here touches real time, sockets, or jax: these are pure
store/str manipulations safe to import from tier-1.
"""

from __future__ import annotations

import random

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import Model, ModelSpec
from kubeai_tpu.metrics.registry import parse_prometheus_text

__all__ = [
    "break_pod",
    "mark_all_ready",
    "mark_ready",
    "mk_model",
    "percentile",
    "pod_names",
    "scrape_diff",
    "seeded_rng",
]


def seeded_rng(seed: int = 0) -> random.Random:
    """The one RNG seam sims draw from: all randomness flows from the
    seed, so a failing run is reproducible from its (seed, trace)."""
    return random.Random(seed)


def percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 1]) of an unsorted sample;
    0.0 for an empty one. Matches the tenant-isolation sim's original
    definition so its asserted thresholds carry over unchanged."""
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[idx]


def scrape_diff(before: str, after: str) -> dict:
    """Per-series numeric delta between two Prometheus expositions:
    {(metric_name, ((label, value), ...)): after - before}, keeping only
    series that moved. Series absent from `before` count from 0.0, so a
    counter's first increment shows up as its value."""
    b = parse_prometheus_text(before)
    a = parse_prometheus_text(after)
    out: dict = {}
    for key, av in a.items():
        delta = av - b.get(key, 0.0)
        if delta != 0.0:
            out[key] = delta
    for key, bv in b.items():
        if key not in a and bv != 0.0:
            out[key] = -bv
    return out


# ---- k8s-store scaffolding ---------------------------------------------------


def mk_model(store, name: str = "sim", replicas: int = 2, **spec_overrides):
    """Create a validated minimal `Model` in the store. The base spec is
    the one every sim used; keyword overrides (min_replicas,
    autoscaling_disabled, scale_down_delay_seconds, ...) layer on top so
    each sim keeps its exact original spec."""
    spec = dict(
        url="hf://org/model",
        engine="KubeAITPU",
        features=["TextGeneration"],
        resource_profile="google-tpu-v5e-1x1:1",
        replicas=replicas,
    )
    spec.update(spec_overrides)
    m = Model(name=name, spec=ModelSpec(**spec))
    m.validate()
    store.create(m.to_dict())
    return m


def mark_ready(store, pod: dict) -> None:
    """Flip one pod to Running/Ready (the sim's kubelet)."""
    fresh = store.get(
        "Pod", pod["metadata"].get("namespace", "default"),
        pod["metadata"]["name"],
    )
    fresh.setdefault("status", {})["conditions"] = [
        {"type": "Ready", "status": "True"},
        {"type": "PodScheduled", "status": "True"},
    ]
    fresh["status"]["phase"] = "Running"
    store.update(fresh)


def mark_all_ready(store, model: str = "sim", namespace: str = "default") -> None:
    for pod in store.list("Pod", namespace, {md.POD_MODEL_LABEL: model}):
        mark_ready(store, pod)


def break_pod(store, pod: dict, mode: str) -> None:
    """Break one pod the way the classifier expects to see it:
    `preempt` -> Failed/Preempted (spot reclaim), `crashloop` ->
    Running + CrashLoopBackOff container state."""
    fresh = store.get(
        "Pod", pod["metadata"].get("namespace", "default"),
        pod["metadata"]["name"],
    )
    status = fresh.setdefault("status", {})
    if mode == "preempt":
        status["phase"] = "Failed"
        status["reason"] = "Preempted"
        status["conditions"] = [{"type": "Ready", "status": "False"}]
    elif mode == "crashloop":
        status["phase"] = "Running"
        status["conditions"] = [{"type": "Ready", "status": "False"}]
        status["containerStatuses"] = [
            {
                "name": "server",
                "restartCount": 7,
                "state": {"waiting": {"reason": "CrashLoopBackOff"}},
            }
        ]
    else:
        raise ValueError(f"unknown break mode {mode!r}")
    store.update(fresh)


def pod_names(store, model: str = "sim", namespace: str = "default") -> set[str]:
    return {
        p["metadata"]["name"]
        for p in store.list("Pod", namespace, {md.POD_MODEL_LABEL: model})
    }
