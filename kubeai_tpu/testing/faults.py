"""Deterministic fault injection for the serving path.

A `FaultPlan` is a seeded, schedule-driven description of what breaks
when — "endpoint B refuses connections for attempts 2–5", "endpoint A
503s every 3rd request", "die after 7 SSE chunks", "stall 10 s before
headers" — consulted once per proxy attempt. Two consumption modes:

  * `faulty_send(plan, real_send)` wraps the proxy's `_send` so unit
    tests drive the REAL retry/breaker path over real sockets, with the
    plan deciding which attempts fail and how
    (`monkeypatch.setattr(proxy_mod, "_send", faulty_send(plan, _send))`);
  * the fast-tier simulation (`benchmarks/resilience_sim.py`) consults
    `plan.on_attempt` directly against a fake-clock `Group`, no sockets.

Everything is deterministic: the schedule is positional (per-endpoint
attempt counters), and the only randomness flows from the plan's seed.
The plan records every decision in `plan.log` so a failing test can
print exactly which attempt hit which fault.
"""

from __future__ import annotations

import dataclasses
import json
import time
from collections import defaultdict

# FakeClock lives in kubeai_tpu/testing/clock.py now; re-exported here
# because every sim historically imported it from this module.
from kubeai_tpu.testing.clock import FakeClock  # noqa: F401

FAULT_CONNECT_ERROR = "connect_error"
FAULT_TIMEOUT = "timeout"
FAULT_HTTP = "http"
FAULT_DIE_MID_STREAM = "die_mid_stream"
FAULT_STALL = "stall"

FAULT_KINDS = (
    FAULT_CONNECT_ERROR,
    FAULT_TIMEOUT,
    FAULT_HTTP,
    FAULT_DIE_MID_STREAM,
    FAULT_STALL,
)


@dataclasses.dataclass
class Fault:
    """One scheduled failure mode for one endpoint.

    Matching is positional over the endpoint's attempt counter (1-based):
    either a `start..end` range (end=None → forever) or `every` (fire on
    every Nth attempt; overrides the range). `endpoint="*"` matches all.
    """

    endpoint: str
    kind: str
    start: int = 1
    end: int | None = None
    every: int = 0
    status: int = 503            # kind="http": response status
    body: dict | None = None     # kind="http": JSON body (default error)
    headers: dict | None = None  # kind="http": extra response headers
    after_chunks: int = 1        # kind="die_mid_stream": chunks before death
    # kind="die_mid_stream": die at an SSE EVENT boundary after exactly
    # this many complete `\n\n`-terminated events (overrides
    # after_chunks). Deterministic regardless of TCP segmentation — the
    # read-counting after_chunks mode can deliver a whole fast stream in
    # one read and never fire.
    after_events: int = 0
    stall_s: float = 0.0         # kind="stall": pre-header stall

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")

    def matches(self, n: int) -> bool:
        if self.every:
            return n % self.every == 0
        return self.start <= n and (self.end is None or n <= self.end)


class FaultPlan:
    """Schedule of faults + per-endpoint attempt counters + decision log."""

    def __init__(self, faults: list[Fault] | tuple[Fault, ...] = (), seed: int = 0):
        import random

        self.faults = list(faults)
        self.rng = random.Random(seed)
        self.counts: dict[str, int] = defaultdict(int)
        # (endpoint, attempt_number_at_endpoint, fault_kind_or_None)
        self.log: list[tuple[str, int, str | None]] = []

    def on_attempt(self, endpoint: str) -> Fault | None:
        """Advance the endpoint's attempt counter and return the fault
        this attempt should suffer, if any.

        Tie-break when several faults match the same attempt: FIRST
        MATCH IN LIST ORDER WINS — `self.faults` order is the priority
        order, and it is stable across runs. Same-tick determinism in
        every sim rests on this: two faults scheduled for the same
        attempt always resolve to the one listed first."""
        self.counts[endpoint] += 1
        n = self.counts[endpoint]
        for f in self.faults:
            if f.endpoint not in ("*", endpoint):
                continue
            if f.matches(n):
                self.log.append((endpoint, n, f.kind))
                return f
        self.log.append((endpoint, n, None))
        return None


# ---- API-server fault plan ---------------------------------------------------
#
# The control-plane analog of FaultPlan: a deterministic, schedule-driven
# description of what the (fake) kube-apiserver does to which requests —
# "the first 3 PATCHes to models 409", "every pod LIST 429s with
# Retry-After: 0.05 for attempts 1-10", "watch GETs stall 5 s". Consumed
# by FakeKubeApiServer (kubeai_tpu/operator/k8s/envtest.py) so
# RestKubeClient's retry/backoff/conflict-retry paths are exercised
# against real HTTP, and by benchmarks/control_plane_chaos_sim.py.

API_FAULT_HTTP = "http"       # respond with `status` (+ headers/message)
API_FAULT_DROP = "drop"       # close the connection without responding
API_FAULT_STALL = "stall"     # sleep stall_s, then handle normally

API_FAULT_KINDS = (API_FAULT_HTTP, API_FAULT_DROP, API_FAULT_STALL)


@dataclasses.dataclass
class ApiFault:
    """One scheduled failure mode for one (method, resource) pair.

    Matching is positional over the (method, plural, watch?) request
    counter (1-based), `start..end` range (end=None → forever) or
    `every` Nth. `method="*"` / `plural="*"` match all; `watch` narrows
    to watch GETs (True), non-watch requests (False), or both (None).
    """

    method: str = "*"
    plural: str = "*"
    watch: bool | None = None
    kind: str = API_FAULT_HTTP
    status: int = 500
    headers: dict | None = None   # e.g. {"Retry-After": "0.05"}
    message: str = "injected fault"
    reason: str = "InternalError"
    start: int = 1
    end: int | None = None
    every: int = 0
    stall_s: float = 0.0

    def __post_init__(self):
        if self.kind not in API_FAULT_KINDS:
            raise ValueError(f"unknown API fault kind {self.kind!r}")

    def matches_request(self, method: str, plural: str, watch: bool) -> bool:
        if self.method not in ("*", method):
            return False
        if self.plural not in ("*", plural):
            return False
        if self.watch is not None and self.watch != watch:
            return False
        return True

    def matches_count(self, n: int) -> bool:
        if self.every:
            return n % self.every == 0
        return self.start <= n and (self.end is None or n <= self.end)


class ApiFaultPlan:
    """Schedule of API faults + per-(method, plural, watch) request
    counters + decision log — deterministic, like FaultPlan."""

    def __init__(self, faults: list[ApiFault] | tuple[ApiFault, ...] = ()):
        self.faults = list(faults)
        self.counts: dict[tuple[str, str, bool], int] = defaultdict(int)
        # (method, plural, watch, count, fault_kind_or_None)
        self.log: list[tuple[str, str, bool, int, str | None]] = []

    def on_request(
        self, method: str, plural: str, watch: bool = False
    ) -> ApiFault | None:
        """Advance the (method, plural, watch) request counter and
        return the fault this request should suffer, if any.

        Tie-break mirrors `FaultPlan.on_attempt`: when several faults
        match the same request, the FIRST MATCH IN LIST ORDER wins —
        deterministic same-tick ordering for free."""
        key = (method, plural, bool(watch))
        self.counts[key] += 1
        n = self.counts[key]
        for f in self.faults:
            if f.matches_request(method, plural, bool(watch)) and (
                f.matches_count(n)
            ):
                self.log.append((method, plural, bool(watch), n, f.kind))
                return f
        self.log.append((method, plural, bool(watch), n, None))
        return None


# ---- proxy-send wrapper ------------------------------------------------------


class _FakeConn:
    def close(self) -> None:
        pass


class _FakeResponse:
    """Just enough of http.client.HTTPResponse for the proxy."""

    def __init__(self, status: int, body: bytes, headers: dict[str, str]):
        self.status = status
        self._body = body
        self._headers = dict(headers)
        self._read = False

    def getheader(self, name: str, default=None):
        for k, v in self._headers.items():
            if k.lower() == name.lower():
                return v
        return default

    def getheaders(self):
        return list(self._headers.items())

    def read(self, n: int = -1) -> bytes:
        if self._read:
            return b""
        self._read = True
        return self._body

    read1 = read


class _DyingResponse:
    """Wraps a real response; its body read raises after N chunks — the
    injected mid-stream connection death."""

    def __init__(self, resp, after_chunks: int):
        self._resp = resp
        self._left = after_chunks

    def __getattr__(self, name):
        return getattr(self._resp, name)

    def _dying_read(self, inner, n: int = -1) -> bytes:
        if self._left <= 0:
            raise ConnectionResetError("injected mid-stream death")
        chunk = inner(n)
        if chunk:
            self._left -= 1
        return chunk

    def read(self, n: int = -1) -> bytes:
        return self._dying_read(self._resp.read, n)

    def read1(self, n: int = -1) -> bytes:
        inner = getattr(self._resp, "read1", self._resp.read)
        return self._dying_read(inner, n)


class _EventDyingResponse:
    """Wraps a real SSE response; body reads return ONE complete
    `\\n\\n`-terminated event at a time and raise once `after_events`
    events have been delivered — a deterministic mid-stream death at an
    event boundary, independent of how TCP segmented the stream."""

    def __init__(self, resp, after_events: int):
        self._resp = resp
        self._left = after_events
        self._buf = b""

    def __getattr__(self, name):
        return getattr(self._resp, name)

    def _read_event(self, inner) -> bytes:
        if self._left <= 0:
            raise ConnectionResetError("injected mid-stream death")
        while b"\n\n" not in self._buf:
            chunk = inner(16384)
            if not chunk:
                # Upstream finished before the quota: flush the tail.
                out, self._buf = self._buf, b""
                return out
            self._buf += chunk
        idx = self._buf.index(b"\n\n") + 2
        out, self._buf = self._buf[:idx], self._buf[idx:]
        self._left -= 1
        return out

    def read(self, n: int = -1) -> bytes:
        return self._read_event(self._resp.read)

    def read1(self, n: int = -1) -> bytes:
        inner = getattr(self._resp, "read1", None) or self._resp.read
        return self._read_event(inner)


def faulty_send(plan: FaultPlan, real_send, clock=time.sleep):
    """Wrap the proxy's `_send` with the plan. Attempts the plan leaves
    alone pass through untouched; faulted attempts raise/respond the way
    the real failure would, so the proxy's classification, breaker
    feeding, and retry behavior are exercised for real."""

    def send(addr: str, path: str, preq, headers: dict, **kw):
        f = plan.on_attempt(addr)
        if f is None:
            return real_send(addr, path, preq, headers, **kw)
        if f.kind == FAULT_CONNECT_ERROR:
            raise ConnectionRefusedError(f"injected: {addr} refused connection")
        if f.kind == FAULT_TIMEOUT:
            raise TimeoutError(f"injected: {addr} timed out before headers")
        if f.kind == FAULT_STALL:
            clock(f.stall_s)
            return real_send(addr, path, preq, headers, **kw)
        if f.kind == FAULT_HTTP:
            body = json.dumps(
                f.body
                if f.body is not None
                else {"error": {"message": f"injected HTTP {f.status}"}}
            ).encode()
            resp = _FakeResponse(
                f.status, body,
                {
                    "Content-Type": "application/json",
                    "Content-Length": str(len(body)),
                    **(f.headers or {}),
                },
            )
            return resp, _FakeConn()
        # die_mid_stream: real connection, poisoned body.
        resp, conn = real_send(addr, path, preq, headers, **kw)
        if f.after_events:
            return _EventDyingResponse(resp, f.after_events), conn
        return _DyingResponse(resp, f.after_chunks), conn

    return send
