"""Deterministic test doubles for the resilience suite."""

from kubeai_tpu.testing.chaos import (
    CONTINUOUS,
    EVENT_KINDS,
    TERMINAL,
    ApiServerError,
    ApiServerUnreachable,
    ChaosKubeStore,
    GameDayEvent,
    GameDayLog,
    GameDayTrace,
    Invariant,
    InvariantChecker,
    Violation,
)
from kubeai_tpu.testing.clock import FakeClock
from kubeai_tpu.testing.faults import (
    API_FAULT_DROP,
    API_FAULT_HTTP,
    API_FAULT_STALL,
    FAULT_CONNECT_ERROR,
    FAULT_DIE_MID_STREAM,
    FAULT_HTTP,
    FAULT_STALL,
    FAULT_TIMEOUT,
    ApiFault,
    ApiFaultPlan,
    Fault,
    FaultPlan,
    faulty_send,
)
from kubeai_tpu.testing.simkit import (
    break_pod,
    mark_all_ready,
    mark_ready,
    mk_model,
    percentile,
    pod_names,
    scrape_diff,
    seeded_rng,
)
