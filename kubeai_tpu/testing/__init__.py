"""Deterministic test doubles for the resilience suite."""

from kubeai_tpu.testing.faults import (
    API_FAULT_DROP,
    API_FAULT_HTTP,
    API_FAULT_STALL,
    FAULT_CONNECT_ERROR,
    FAULT_DIE_MID_STREAM,
    FAULT_HTTP,
    FAULT_STALL,
    FAULT_TIMEOUT,
    ApiFault,
    ApiFaultPlan,
    FakeClock,
    Fault,
    FaultPlan,
    faulty_send,
)
