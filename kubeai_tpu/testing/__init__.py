"""Deterministic test doubles for the resilience suite."""

from kubeai_tpu.testing.faults import (
    FAULT_CONNECT_ERROR,
    FAULT_DIE_MID_STREAM,
    FAULT_HTTP,
    FAULT_STALL,
    FAULT_TIMEOUT,
    FakeClock,
    Fault,
    FaultPlan,
    faulty_send,
)
