"""The unified chaos plane: one declarative, seeded schedule of typed
chaos events driving REAL components under one shared `FakeClock`.

PRs 1-14 each proved one subsystem under its own private fault plan;
this module is the composition layer the game-day harness
(`benchmarks/gameday_sim.py`) is built on:

  * `GameDayTrace` — a single time-ordered schedule of typed events
    (kill/spot-preempt a pod, wedge an engine step, partition or storm
    the API server, flood a tenant, flip the chip budget, stale-out
    telemetry, drop a proxy->engine link). Same-tick ordering is
    deterministic: events are stably sorted by (time, insertion order),
    so two events at the same instant always apply in the order the
    trace author wrote them — the same first-listed-wins discipline as
    `FaultPlan`/`ApiFaultPlan`.
  * `GameDayLog` — a JSONL event/observation/violation log with a
    header carrying (seed, ticks, trace), so any failing run replays
    byte-identically from its dump: the trace IS the input, the log IS
    the evidence.
  * `Invariant`/`InvariantChecker` — CONTINUOUS invariants are checked
    every tick (zero client-visible stream errors, budgets respected,
    realtime never door-shed, allocated <= inventory, billing exact);
    TERMINAL invariants are checked once chaos has ended (convergence
    to a healthy steady state within a bound). The checker records the
    FIRST violation with its tick so a dump pinpoints the instant the
    world went wrong.
  * `ChaosKubeStore` — the API-server chaos seam: wraps a `KubeStore`
    and consults an `ApiFaultPlan` per operation (plus a hard
    `partitioned` switch), raising `ApiServerUnreachable` /
    `ApiServerError` exactly where a real client would see its retries
    exhaust. The operator stack is pointed at the wrapper; the sim's
    own "kubelet"/infrastructure hands stay on the raw store — a
    partition severs the control plane, not physics.
"""

from __future__ import annotations

import dataclasses
import json

from kubeai_tpu.testing.faults import (
    API_FAULT_DROP,
    API_FAULT_HTTP,
    ApiFaultPlan,
)

# ---- event vocabulary --------------------------------------------------------

EV_KILL_POD = "kill_pod"            # params: model, count, mode
EV_SPOT_PREEMPT = "spot_preempt"    # params: model, count
EV_WEDGE_ENGINE = "wedge_engine"    # params: model
EV_API_PARTITION = "api_partition"  # params: duration_s
EV_API_STORM = "api_storm"          # params: method, plural, status, count
EV_TENANT_FLOOD = "tenant_flood"    # params: tenant, model, rps, duration_s
EV_CHIP_FLIP = "chip_flip"          # params: delta (spot nodes +/-)
EV_TELEMETRY_STALE = "telemetry_stale"  # params: duration_s
EV_LINK_DROP = "link_drop"          # params: model, index, duration_s
EV_KILL_GROUP_HOST = "kill_group_host"  # params: model, group, host, mode
EV_DOOR_PARTITION = "door_partition"  # params: duration_s (splits the door shard set into two halves)
EV_DOOR_CRASH = "door_crash"        # params: shard (index; state reconstructed from peers)
# Cluster-level partition: api_partition promoted one level — target
# names the cluster whose entire control plane AND door go dark; the
# federation planner fails its models over within the bounded window.
EV_CLUSTER_PARTITION = "cluster_partition"  # params: duration_s; target: cluster
EV_CLUSTER_HEAL = "cluster_heal"    # target: cluster (explicit heal; else duration_s)
# A bad deploy: mutate the target model's spec so its pod-hash drifts,
# with every new-hash pod born broken — `mode` picks how ("wedged": the
# pod never goes Ready; "latency": it serves with TTFT inflated by
# `ttft_factor`). The rollout judge must condemn the hash and pin the
# old one before the canary burns budget the stable version doesn't.
EV_BAD_ROLLOUT = "bad_rollout"      # params: mode (wedged|latency), ttft_factor; target: model

EVENT_KINDS = (
    EV_KILL_POD,
    EV_SPOT_PREEMPT,
    EV_WEDGE_ENGINE,
    EV_API_PARTITION,
    EV_API_STORM,
    EV_TENANT_FLOOD,
    EV_CHIP_FLIP,
    EV_TELEMETRY_STALE,
    EV_LINK_DROP,
    EV_KILL_GROUP_HOST,
    EV_DOOR_PARTITION,
    EV_DOOR_CRASH,
    EV_CLUSTER_PARTITION,
    EV_CLUSTER_HEAL,
    EV_BAD_ROLLOUT,
)

# ---- shared incident/flight schema -------------------------------------------
# The flight recorder (kubeai_tpu/metrics/flightrecorder.py) embeds
# bounded decision-event rings in the live subsystems and dumps them as
# GameDayLog-format JSONL incident bundles. This block is the ONE schema
# both sides speak: the recorder may only emit record kinds and flight
# event kinds declared here, so `gameday_sim --replay` never meets a
# record it silently drops. scripts/check_incident_schema.py gates the
# subset relation in tier-1.

# Every `record` field value a GameDayLog-format JSONL line may carry.
LOG_RECORD_KINDS = (
    "event",        # a chaos-trace event applied (game-day runs)
    "obs",          # a per-tick observation
    "violation",    # an invariant / SLO violation
    "flight",       # a flight-recorder decision event
    "span",         # a recent span snapshotted into an incident bundle
    "metric_delta", # a metric series' movement across the capture window
    "exemplar",     # last trace-id exemplars of a latency histogram
)

# The decision-event vocabulary the replay side understands. The flight
# recorder's own accepted kinds must stay a subset of this tuple.
FLIGHT_DOOR_SHED = "door_shed"              # door refusal (rate/overload)
FLIGHT_DOOR_QUOTA = "door_quota"            # door refusal (token quota)
FLIGHT_BREAKER = "breaker_transition"       # circuit state change
FLIGHT_LB_NO_ENDPOINTS = "lb_no_healthy_endpoints"
FLIGHT_GOVERNOR_DENY = "governor_denial"    # actuation refused
FLIGHT_SCHED_ADMIT = "scheduler_admit"      # engine queue admission
FLIGHT_SCHED_SHED = "scheduler_shed"        # deadline-infeasible refusal
FLIGHT_SCHED_PREEMPT = "scheduler_preempt"  # running request preempted
FLIGHT_PLANNER_PREEMPT = "planner_preempt_mark"
FLIGHT_WATCHDOG = "engine_watchdog"         # wedged-step detection
FLIGHT_STEP_ANOMALY = "engine_step_anomaly"
FLIGHT_SLO_ALERT = "slo_alert"              # burn-rate state transition
FLIGHT_ROLLOUT_DECISION = "rollout_decision"  # promotion / rollback verdict

FLIGHT_EVENT_KINDS = (
    FLIGHT_DOOR_SHED,
    FLIGHT_DOOR_QUOTA,
    FLIGHT_BREAKER,
    FLIGHT_LB_NO_ENDPOINTS,
    FLIGHT_GOVERNOR_DENY,
    FLIGHT_SCHED_ADMIT,
    FLIGHT_SCHED_SHED,
    FLIGHT_SCHED_PREEMPT,
    FLIGHT_PLANNER_PREEMPT,
    FLIGHT_WATCHDOG,
    FLIGHT_STEP_ANOMALY,
    FLIGHT_SLO_ALERT,
    FLIGHT_ROLLOUT_DECISION,
)


@dataclasses.dataclass
class GameDayEvent:
    """One scheduled chaos event. `seq` is the insertion index the
    trace assigns — the documented same-tick tie-break."""

    t: float
    kind: str
    target: str = ""
    params: dict = dataclasses.field(default_factory=dict)
    seq: int = -1

    def __post_init__(self):
        if self.kind not in EVENT_KINDS:
            raise ValueError(f"unknown game-day event kind {self.kind!r}")

    def to_dict(self) -> dict:
        return {
            "t": self.t, "kind": self.kind, "target": self.target,
            "params": self.params, "seq": self.seq,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "GameDayEvent":
        return cls(
            t=float(d["t"]), kind=str(d["kind"]),
            target=str(d.get("target", "")),
            params=dict(d.get("params") or {}),
            seq=int(d.get("seq", -1)),
        )


class GameDayTrace:
    """A seeded, time-ordered schedule of `GameDayEvent`s.

    Determinism contract: events are stably sorted by (t, seq) where
    seq is insertion order, so same-tick events apply in the order the
    author listed them; the only randomness available to a consumer is
    `self.seed` (the consumer seeds its own RNG from it). `due(now)`
    is a cursor — each event is delivered exactly once, in order."""

    def __init__(self, events, seed: int = 0):
        self.seed = int(seed)
        self.events: list[GameDayEvent] = []
        for i, ev in enumerate(events):
            if ev.seq < 0:
                ev = dataclasses.replace(ev, seq=i)
            self.events.append(ev)
        self.events.sort(key=lambda e: (e.t, e.seq))
        self._cursor = 0

    def reset(self) -> None:
        self._cursor = 0

    def due(self, now: float) -> list[GameDayEvent]:
        """Pop every not-yet-delivered event with t <= now, in order."""
        out = []
        while (
            self._cursor < len(self.events)
            and self.events[self._cursor].t <= now
        ):
            out.append(self.events[self._cursor])
            self._cursor += 1
        return out

    @property
    def last_event_t(self) -> float:
        """When scheduled chaos ends (instantaneous event times plus
        their durations) — the terminal-invariant clock starts here."""
        t = 0.0
        for ev in self.events:
            t = max(t, ev.t + float(ev.params.get("duration_s", 0.0)))
        return t

    def without(self, *kinds: str) -> "GameDayTrace":
        """A copy of this trace with the given event kinds removed —
        the baseline-comparison seam (e.g. the same chaos minus the
        tenant flood, to measure what the flood alone moved)."""
        return GameDayTrace(
            [
                dataclasses.replace(ev)
                for ev in self.events
                if ev.kind not in kinds
            ],
            seed=self.seed,
        )

    def to_jsonl(self) -> list[str]:
        return [
            json.dumps(ev.to_dict(), sort_keys=True) for ev in self.events
        ]

    @classmethod
    def from_jsonl(cls, lines, seed: int = 0) -> "GameDayTrace":
        events = [
            GameDayEvent.from_dict(json.loads(line))
            for line in lines
            if line.strip()
        ]
        return cls(events, seed=seed)


# ---- JSONL run log -----------------------------------------------------------


class GameDayLog:
    """Append-only JSONL run log. Line 1 is the header (seed, ticks,
    the full trace); every subsequent line is a typed record
    (`event` | `obs` | `violation`). Records are serialized with sorted
    keys so two runs of the same (trace, seed) produce byte-identical
    logs — the replay contract."""

    def __init__(self, trace: GameDayTrace, ticks: int, extra: dict | None = None):
        self.header = {
            "kind": "gameday",
            "seed": trace.seed,
            "ticks": int(ticks),
            "events": [ev.to_dict() for ev in trace.events],
        }
        if extra:
            self.header.update(extra)
        self.lines: list[str] = [json.dumps(self.header, sort_keys=True)]

    def record(self, record_kind: str, tick: int, **payload) -> None:
        entry = {"record": record_kind, "tick": int(tick)}
        entry.update(payload)
        self.lines.append(json.dumps(entry, sort_keys=True))

    def event(self, tick: int, ev: GameDayEvent) -> None:
        self.record("event", tick, **ev.to_dict())

    def obs(self, tick: int, **payload) -> None:
        self.record("obs", tick, **payload)

    def violation(self, tick: int, invariant: str, detail: str) -> None:
        self.record("violation", tick, invariant=invariant, detail=detail)

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write("\n".join(self.lines) + "\n")

    @staticmethod
    def load(path: str) -> tuple[dict, list[dict]]:
        """(header, records) from a dumped log."""
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if not lines:
            raise ValueError(f"{path}: empty game-day dump")
        header = json.loads(lines[0])
        if header.get("kind") != "gameday":
            raise ValueError(f"{path}: not a game-day dump")
        return header, [json.loads(ln) for ln in lines[1:]]


# ---- invariant framework -----------------------------------------------------

CONTINUOUS = "continuous"
TERMINAL = "terminal"


@dataclasses.dataclass(frozen=True)
class Violation:
    tick: int
    t: float
    invariant: str
    detail: str


@dataclasses.dataclass(frozen=True)
class Invariant:
    """One named check. `check(world) -> None | str` returns a human
    detail string on violation. CONTINUOUS invariants run every tick;
    TERMINAL ones run once chaos has ended (convergence-style)."""

    name: str
    check: object  # callable(world) -> str | None
    kind: str = CONTINUOUS
    doc: str = ""

    def __post_init__(self):
        if self.kind not in (CONTINUOUS, TERMINAL):
            raise ValueError(f"unknown invariant kind {self.kind!r}")


class InvariantChecker:
    """Runs the invariant set against the world, recording every
    violation (and logging it) — `first_violation` is the debugging
    anchor a dumped trace replays to."""

    def __init__(self, invariants, log: GameDayLog | None = None):
        self.invariants = list(invariants)
        self.log = log
        self.violations: list[Violation] = []

    @property
    def first_violation(self) -> Violation | None:
        return self.violations[0] if self.violations else None

    def _run(self, kinds, world, tick: int, t: float) -> None:
        for inv in self.invariants:
            if inv.kind not in kinds:
                continue
            try:
                detail = inv.check(world)
            except Exception as exc:  # a crashing check IS a violation
                detail = f"invariant check raised: {exc!r}"
            if detail:
                self.violations.append(
                    Violation(tick=tick, t=t, invariant=inv.name,
                              detail=str(detail))
                )
                if self.log is not None:
                    self.log.violation(tick, inv.name, str(detail))

    def check_continuous(self, world, tick: int, t: float) -> None:
        self._run((CONTINUOUS,), world, tick, t)

    def check_terminal(self, world, tick: int, t: float) -> None:
        self._run((TERMINAL,), world, tick, t)


# ---- API-server chaos seam ---------------------------------------------------


class ApiServerUnreachable(ConnectionError):
    """The wrapped store's answer to a partition / dropped connection:
    what a real kube client surfaces once its retries exhaust."""


class ApiServerError(RuntimeError):
    """An injected non-conflict HTTP error the client could not retry
    through (5xx storm outlasting the retry budget)."""

    def __init__(self, status: int, message: str):
        super().__init__(f"injected API server {status}: {message}")
        self.status = status


_KIND_PLURALS = {
    "Pod": "pods",
    "Model": "models",
    "Node": "nodes",
    "Lease": "leases",
    "ConfigMap": "configmaps",
}


def _plural(kind: str) -> str:
    return _KIND_PLURALS.get(kind, kind.lower() + "s")


class ChaosKubeStore:
    """`KubeStore` front gated by an `ApiFaultPlan` + a partition switch.

    Every verb consults `plan.on_request(METHOD, plural)` first (one
    consult per operation — the positional schedule maps 1:1 onto
    operations); `partitioned=True` fails everything unconditionally.
    HTTP faults map onto the store's own exception vocabulary where one
    exists (404 -> NotFound, 409 -> Conflict) so callers exercise their
    real handling; other statuses raise `ApiServerError`. `stall`
    faults pass through — fake-clock sims have no wall to stall
    against, and the decision still lands in `plan.log`.

    Watches and validators pass through un-gated: the LB watch queue is
    process-local plumbing, not an API-server round trip, and the sim
    partitions the CONTROL plane, not the process."""

    def __init__(self, inner, plan: ApiFaultPlan | None = None):
        self.inner = inner
        self.plan = plan if plan is not None else ApiFaultPlan()
        self.partitioned = False

    def _gate(self, method: str, kind: str, watch: bool = False) -> None:
        if self.partitioned:
            raise ApiServerUnreachable(
                f"injected partition: {method} {_plural(kind)} unreachable"
            )
        f = self.plan.on_request(method, _plural(kind), watch)
        if f is None:
            return
        if f.kind == API_FAULT_DROP:
            raise ApiServerUnreachable(
                f"injected drop: {method} {_plural(kind)}"
            )
        if f.kind == API_FAULT_HTTP:
            if f.status == 404:
                from kubeai_tpu.operator.k8s.store import NotFound

                raise NotFound(f"injected 404: {f.message}")
            if f.status == 409:
                from kubeai_tpu.operator.k8s.store import Conflict

                raise Conflict(f"injected 409: {f.message}")
            raise ApiServerError(f.status, f.message)
        # API_FAULT_STALL: logged by the plan, no wall clock to stall.

    # -- gated verbs (the kube API surface the operator stack uses) ----------

    def create(self, obj: dict) -> dict:
        self._gate("POST", obj.get("kind", ""))
        return self.inner.create(obj)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        self._gate("GET", kind)
        return self.inner.get(kind, namespace, name)

    def try_get(self, kind: str, namespace: str, name: str) -> dict | None:
        self._gate("GET", kind)
        return self.inner.try_get(kind, namespace, name)

    def list(self, kind: str, *args, **kwargs) -> list:
        self._gate("GET", kind)
        return self.inner.list(kind, *args, **kwargs)

    def update(self, obj: dict) -> dict:
        self._gate("PUT", obj.get("kind", ""))
        return self.inner.update(obj)

    def patch_merge(self, kind: str, *args, **kwargs) -> dict:
        self._gate("PATCH", kind)
        return self.inner.patch_merge(kind, *args, **kwargs)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._gate("DELETE", kind)
        return self.inner.delete(kind, namespace, name)

    def delete_all_of(self, kind: str, *args, **kwargs):
        self._gate("DELETE", kind)
        return self.inner.delete_all_of(kind, *args, **kwargs)

    # -- pass-throughs --------------------------------------------------------

    def __getattr__(self, name: str):
        return getattr(self.inner, name)
