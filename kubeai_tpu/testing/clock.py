"""The shared fake clock every deterministic sim runs on.

Hoisted from `kubeai_tpu/testing/faults.py` (where it is still
re-exported for back-compat): one injectable monotonic clock shared by
breakers, backoffs, leases, budget windows, and the game-day harness, so
a whole fleet of real components experiences the same instant.
"""

from __future__ import annotations


class FakeClock:
    """Injectable monotonic clock for breaker/backoff determinism.

    Monotonicity is enforced: `advance` refuses a negative delta instead
    of silently rewinding time — a sim that rewound its clock would
    corrupt every sliding window (disruption budgets, breaker windows,
    lease deadlines) built on the assumption that time only moves
    forward, and the corruption would surface ticks later as an
    unrelated-looking invariant violation.
    """

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError(
                f"FakeClock.advance({dt!r}): a fake clock never rewinds"
            )
        self.t += dt
