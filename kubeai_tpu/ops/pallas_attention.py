"""Pallas TPU flash attention for prefill.

Online-softmax attention computed block-by-block so the [S, S] logits
matrix never materializes in HBM — the prefill hot op for long context.
Grid: (batch, q-head, q-block); the kernel loops over k-blocks up to the
causal frontier (skipping fully-masked blocks entirely).

GQA: the q-head grid axis maps each q head onto its kv head (h // group).

Numerics: fp32 accumulation in VMEM scratch; bf16 in/out. Falls back to
kubeai_tpu.ops.attention.causal_prefill_attention when shapes don't meet
TPU tiling constraints (head_dim padded to 128 lanes; q/k blocks of 128).

Usage: flash_causal_prefill(q, k, v) — same contract as the jnp reference;
`interpret=True` runs on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU for interpret mode
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from kubeai_tpu.ops.attention import causal_prefill_attention

NEG_INF = -1e30


def _flash_kernel(
    q_ref,  # [1, 1, BQ, D]
    k_ref,  # [1, 1, S, D]
    v_ref,  # [1, 1, S, D]
    o_ref,  # [1, 1, BQ, D]
    *,
    block_q: int,
    block_k: int,
    seq_len: int,
    scale: float,
):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [BQ, D]

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros_like(q)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    # Causal frontier: k blocks strictly after this q block are all masked.
    num_k = (qi + 1) * block_q // block_k
    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m, l, acc))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "interpret", "scale", "group"),
)
def _flash_bhsd(
    q: jnp.ndarray,  # [B, H, S, D]
    k: jnp.ndarray,  # [B, KVH, S, D] — NOT expanded; the q-head grid axis
    v: jnp.ndarray,  #                 maps h -> kv head h // group in the
    block_q: int = 128,  #              BlockSpec, so GQA costs no extra HBM
    block_k: int = 128,
    interpret: bool = False,
    scale: float = 1.0,
    group: int = 1,
) -> jnp.ndarray:
    B, H, S, D = q.shape
    grid = (B, H, S // block_q)
    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        seq_len=S,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)
            ),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // group, 0, 0)),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=interpret,
    )(q, k, v)


def flash_supported(seq_len: int, head_dim: int, block: int = 128) -> bool:
    return seq_len % block == 0 and seq_len >= block


def flash_causal_prefill(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, KVH, D]
    v: jnp.ndarray,
    *,
    block: int = 128,
    interpret: bool = False,
    force: bool = False,
) -> jnp.ndarray:
    """Flash attention with the causal_prefill_attention contract."""
    B, S, H, D = q.shape
    KVH = k.shape[2]
    if not force and not flash_supported(S, D, block):
        return causal_prefill_attention(q, k, v)

    group = H // KVH
    # [B, S, H, D] -> [B, H, S, D]. K/V keep their KVH heads — the kernel's
    # q-head grid axis maps onto kv head h // group in the BlockSpec, so
    # GQA never materializes ×group KV in HBM.
    qt = jnp.moveaxis(q, 1, 2)
    kt = jnp.moveaxis(k, 1, 2)
    vt = jnp.moveaxis(v, 1, 2)

    # Pad head_dim to the 128-lane tile.
    Dp = max(128, ((D + 127) // 128) * 128)
    if Dp != D:
        pad = [(0, 0), (0, 0), (0, 0), (0, Dp - D)]
        qt, kt, vt = (jnp.pad(x, pad) for x in (qt, kt, vt))

    out = _flash_bhsd(
        qt, kt, vt, block_q=block, block_k=block, interpret=interpret,
        scale=D ** -0.5, group=group,
    )
    if Dp != D:
        out = out[..., :D]
    return jnp.moveaxis(out, 1, 2)  # [B, S, H, D]
