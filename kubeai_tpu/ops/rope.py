"""Rotary position embeddings (RoPE), including Llama-3 frequency scaling.

Computed with static shapes and position indices passed as arrays so the
same jitted graph serves any batch of positions (prefill ranges and decode
single-steps) without retracing.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float = 500000.0,
    scaling: dict | None = None,
    max_position_embeddings: int | None = None,
) -> np.ndarray:
    """Per-pair inverse frequencies with optional context-extension
    scaling. `scaling` mirrors HF `rope_scaling`; supported rope_type:

      llama3  — banded rescale (Llama 3.1+)
      linear  — uniform position interpolation (inv_freq / factor)
      dynamic — NTK-aware theta rescale at the serving context length
      yarn    — banded NTK-by-parts (Qwen/DeepSeek long-context); its
                attention temperature rides `rope_attention_scaling`

    `max_position_embeddings` is the model config's context length — HF
    reads the pre-extension length from there when rope_scaling omits
    original_max_position_embeddings (dynamic/yarn). HF's "dynamic"
    grows with the running sequence; a serving engine compiles static
    shapes, so it is applied once at the extended context
    (original * factor) — exact for sequences that reach it,
    conservative below.
    """
    inv_freq = 1.0 / (
        theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim)
    )
    rope_type = (scaling or {}).get(
        "rope_type", (scaling or {}).get("type", "")
    )
    if rope_type == "llama3":
        factor = scaling["factor"]
        low = scaling["low_freq_factor"]
        high = scaling["high_freq_factor"]
        orig = scaling["original_max_position_embeddings"]
        wavelen = 2 * np.pi / inv_freq
        smooth = (orig / wavelen - low) / (high - low)
        mid = (1 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = np.where(
            wavelen > orig / low,  # low-frequency band: fully rescaled
            inv_freq / factor,
            np.where(wavelen < orig / high, inv_freq, mid),
        )
    elif rope_type == "linear":
        inv_freq = inv_freq / scaling["factor"]
    elif rope_type == "dynamic":
        factor = scaling["factor"]
        orig = scaling.get(
            "original_max_position_embeddings", max_position_embeddings
        )
        if orig is None:
            raise ValueError(
                "dynamic rope_scaling needs original_max_position_embeddings "
                "or the model's max_position_embeddings"
            )
        max_pos = scaling.get("max_position_embeddings") or int(orig * factor)
        if max_pos > orig:
            # NTK-aware base rescale at the target length (HF dynamic
            # formula with seq_len = serving context).
            base = theta * (
                factor * max_pos / orig - (factor - 1)
            ) ** (head_dim / (head_dim - 2))
            inv_freq = 1.0 / (
                base
                ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim)
            )
    elif rope_type == "yarn":
        factor = scaling["factor"]
        orig = scaling.get(
            "original_max_position_embeddings", max_position_embeddings
        )
        if orig is None:
            raise ValueError(
                "yarn rope_scaling needs original_max_position_embeddings "
                "or the model's max_position_embeddings"
            )
        beta_fast = scaling.get("beta_fast", 32.0)
        beta_slow = scaling.get("beta_slow", 1.0)

        def find_dim(num_rot):
            return (
                head_dim
                * np.log(orig / (num_rot * 2 * np.pi))
            ) / (2 * np.log(theta))

        low = max(np.floor(find_dim(beta_fast)), 0)
        high = min(np.ceil(find_dim(beta_slow)), head_dim - 1)
        dims = np.arange(0, head_dim, 2, dtype=np.float64) / 2
        ramp = np.clip((dims - low) / max(high - low, 1e-3), 0, 1)
        extrap = 1 - ramp  # 1 = keep original freq (fast dims)
        inv_freq = inv_freq / factor * (1 - extrap) + inv_freq * extrap
    elif rope_type and rope_type != "default":
        # "default" is HF's explicit no-scaling marker.
        raise ValueError(f"unsupported rope_scaling type {rope_type!r}")
    return inv_freq.astype(np.float32)


def rope_attention_scaling(scaling: dict | None) -> float:
    """YaRN attention temperature: cos/sin are scaled by this factor
    (HF convention — logits end up scaled by its square). 1.0 for every
    other rope type. Mirrors transformers' _compute_yarn_parameters:
    explicit attention_factor wins; DeepSeek-style mscale/mscale_all_dim
    use get_mscale(factor, m)/get_mscale(factor, m_all); otherwise
    0.1*ln(factor)+1, with factor <= 1 clamped to 1.0."""
    rope_type = (scaling or {}).get(
        "rope_type", (scaling or {}).get("type", "")
    )
    if rope_type != "yarn":
        return 1.0
    if scaling.get("attention_factor") is not None:
        return float(scaling["attention_factor"])
    factor = float(scaling["factor"])

    def get_mscale(scale: float, m: float = 1.0) -> float:
        if scale <= 1.0:
            return 1.0
        return 0.1 * m * np.log(scale) + 1.0

    mscale = scaling.get("mscale")
    if mscale is not None:
        return float(
            get_mscale(factor, float(mscale))
            / get_mscale(factor, float(scaling.get("mscale_all_dim", 0.0)))
        )
    return float(get_mscale(factor))


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
    mscale: float = 1.0,  # YaRN attention scaling (rope_attention_scaling)
) -> jnp.ndarray:
    """Rotate q or k. x: [..., seq, heads, head_dim]; positions: [..., seq]."""
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # [..., S, D/2]
    cos = jnp.cos(angles)[..., :, None, :] * mscale  # [..., S, 1, D/2]
    sin = jnp.sin(angles)[..., :, None, :] * mscale
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
