"""Rotary position embeddings (RoPE), including Llama-3 frequency scaling.

Computed with static shapes and position indices passed as arrays so the
same jitted graph serves any batch of positions (prefill ranges and decode
single-steps) without retracing.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float = 500000.0,
    scaling: dict | None = None,
) -> np.ndarray:
    """Per-pair inverse frequencies, with optional Llama-3.1-style scaling.

    `scaling` mirrors HF config `rope_scaling` with rope_type="llama3":
    {factor, low_freq_factor, high_freq_factor, original_max_position_embeddings}.
    """
    inv_freq = 1.0 / (
        theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim)
    )
    if scaling and scaling.get("rope_type", scaling.get("type")) == "llama3":
        factor = scaling["factor"]
        low = scaling["low_freq_factor"]
        high = scaling["high_freq_factor"]
        orig = scaling["original_max_position_embeddings"]
        wavelen = 2 * np.pi / inv_freq
        smooth = (orig / wavelen - low) / (high - low)
        mid = (1 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = np.where(
            wavelen > orig / low,  # low-frequency band: fully rescaled
            inv_freq / factor,
            np.where(wavelen < orig / high, inv_freq, mid),
        )
    return inv_freq.astype(np.float32)


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate q or k. x: [..., seq, heads, head_dim]; positions: [..., seq]."""
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # [..., S, D/2]
    cos = jnp.cos(angles)[..., :, None, :]  # [..., S, 1, D/2]
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
