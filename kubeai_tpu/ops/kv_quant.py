"""Int8 KV-cache quantization: per-token-per-head symmetric scales.

The paged KV pool is the serving-time HBM ceiling (weights are already
int8-able via engine/quantization.py); storing pages as int8 halves KV
bytes on chip AND on the wire — every disagg handoff, peer prefix fetch
and objstore spill ships the quantized pages verbatim.

Layout. A quantized pool leaf is a dict — the same dispatch idiom the
weight quantizer uses ({"w8", "scale"} leaves):

    {"q8":    int8    [..., page, KVH, D]   quantized pages
     "scale": float32 [..., page, KVH]      per-token-per-head scales}

Scale granularity is per (token, kv-head): each token's K (or V) row of
D values quantizes independently,

    scale = max(|row|) / 127   (clamped to SCALE_FLOOR)
    q8    = round(row / scale) ∈ [-127, 127]

which is what makes the pool APPEND-ONLY under quantization: a decode
step writes one new token's rows without ever re-scaling resident
tokens, so pages are immutable once written — the property the prefix
cache's content-hash chains and the disagg byte-identity guarantee
depend on. Coarser per-page scales would halve the scale overhead but
force a page re-quantize on every append, breaking both.

Capacity math (the sim in benchmarks/kv_quant_sim.py asserts it): one
token-layer costs 2*KVH*D*2 bytes in bf16 and 2*KVH*(D + 4) in int8
(+4 = the f32 scale), a 2D/(D+4) capacity factor — 1.94x at D=128.

Dequantization happens inside the attention read (the reference path
multiplies the gathered int8 pages by their gathered scales in f32);
the Pallas kernels stay bf16-only, so a quantized pool always takes the
reference path — acceptable because int8 KV targets capacity, and the
ref path is the tier-1/CPU path anyway. A fused int8 Pallas kernel is
the natural upgrade once validated on hardware.
"""

from __future__ import annotations

import jax.numpy as jnp

# Scales below this clamp to it: a zero-variance row (all-zero K/V, e.g.
# scratch pages) quantizes to zeros and dequantizes back to exact zeros.
SCALE_FLOOR = 1e-8

# Engine-facing dtype names (EngineConfig.kv_dtype / --kv-dtype / CRD
# kvCache.dtype). "" means unset and resolves to bfloat16.
KV_DTYPES = ("bfloat16", "int8")


def resolve_kv_dtype(name: str) -> str:
    """Normalize a kv-dtype knob; raises ValueError on unknown names."""
    name = (name or "").strip().lower()
    if name == "":
        return "bfloat16"
    if name not in KV_DTYPES:
        raise ValueError(
            f"kv dtype {name!r} not in {KV_DTYPES}"
        )
    return name


def is_quantized_kv(pool) -> bool:
    """True for a quantized pool leaf ({"q8", "scale"} dict)."""
    return isinstance(pool, dict) and "q8" in pool and "scale" in pool


def kv_pages_shape(pool) -> tuple:
    """The page-array shape regardless of quantization."""
    return (pool["q8"] if is_quantized_kv(pool) else pool).shape


def kv_pool_nbytes(pool) -> int:
    """Resident bytes of one pool leaf (pages + scales when quantized)."""
    if is_quantized_kv(pool):
        return int(pool["q8"].nbytes + pool["scale"].nbytes)
    return int(pool.nbytes)


def make_quantized_pool(shape, scale_dtype=jnp.float32) -> dict:
    """Zeroed quantized pool: pages [..., page, KVH, D] int8 + scales
    [..., page, KVH] f32 (zero scale is fine — rows are written before
    they are ever read, and masked junk dequantizes to 0)."""
    return {
        "q8": jnp.zeros(shape, jnp.int8),
        "scale": jnp.zeros(shape[:-1], scale_dtype),
    }


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[..., D] -> (int8 [..., D], f32 scales [...]): symmetric per-row
    quantization over the last (head_dim) axis."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.maximum(amax / 127.0, SCALE_FLOOR)
    q8 = jnp.clip(
        jnp.round(x32 / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q8, scale


def dequantize_kv(
    q8: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16
) -> jnp.ndarray:
    """(int8 [..., D], scales [...]) -> [..., D] in `dtype`."""
    return (q8.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
            ).astype(dtype)


def kv_capacity_factor(head_dim: int, scale_bytes: int = 4) -> float:
    """Slot-capacity multiplier of int8 KV vs bf16 at equal HBM budget:
    bytes-per-token-per-head 2*D (bf16) over D + scale_bytes (int8)."""
    return (2.0 * head_dim) / (head_dim + scale_bytes)
