"""Attention ops for prefill and decode against a slot-based KV cache.

TPU-first design notes:
  - Static shapes everywhere: the KV cache is a fixed [slots, max_len, ...]
    buffer; per-sequence lengths arrive as arrays and become masks, never
    Python control flow — one compiled graph serves all requests.
  - GQA is expressed by reshaping q to [kv_heads, group, ...] so the MXU
    sees large batched matmuls instead of head-repeated memory traffic.
  - Softmax in float32; logits never materialize wider than [*, S] blocks.
  - A Pallas flash-attention kernel (kubeai_tpu.ops.pallas_attention) is
    used for long-prefill when available; these jnp versions are the
    reference semantics and the CPU/test fallback.

The reference has no attention code at all — it runs vLLM images
(reference: internal/modelcontroller/engine_vllm.go:12-167 renders the Pod;
the kernels live in the external image). This module is the TPU-native
replacement for that delegated compute.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _gqa_reshape(q: jnp.ndarray, num_kv_heads: int) -> jnp.ndarray:
    """[B, S, H, D] -> [B, S, KVH, G, D]."""
    b, s, h, d = q.shape
    return q.reshape(b, s, num_kv_heads, h // num_kv_heads, d)


def causal_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, KVH, D]
    v: jnp.ndarray,  # [B, S, KVH, D]
    *,
    q_offset: jnp.ndarray | int = 0,  # positions of q within the sequence
    scale: float | None = None,
    logit_softcap: float | None = None,  # Gemma-2 tanh capping
    window: jnp.ndarray | int | None = None,  # sliding window; traced OK,
    #   <= 0 disables (lets a layer scan alternate local/global layers)
) -> jnp.ndarray:
    """Causal self-attention over a freshly computed prompt segment.

    `q_offset` supports chunked prefill: q tokens are at absolute positions
    offset..offset+S-1 while k/v cover positions 0..S-1 of the same buffer.
    """
    b, s, h, d = q.shape
    kvh = k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    qg = _gqa_reshape(q * scale, kvh)  # [B, S, KVH, G, D]
    # [B, KVH, G, Sq, Sk]
    logits = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg.astype(jnp.float32), k.astype(jnp.float32)
    )
    if logit_softcap is not None:
        logits = jnp.tanh(logits / logit_softcap) * logit_softcap
    q_pos = jnp.arange(s) + q_offset
    k_pos = jnp.arange(k.shape[1])
    mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]
    if window is not None:
        win = jnp.asarray(window, jnp.int32)
        mask = mask & (
            (win <= 0) | (q_pos[:, None] - k_pos[None, :] < win)
        )
    logits = jnp.where(mask[None, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, s, h, d).astype(q.dtype)


def chunked_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, D] — the new chunk's queries
    k_cache: jnp.ndarray,  # [B, L, KVH, D] — cache already containing the chunk
    v_cache: jnp.ndarray,  # [B, L, KVH, D]
    chunk_start: jnp.ndarray,  # [B] absolute position of q[:, 0]
    *,
    scale: float | None = None,
    logit_softcap: float | None = None,  # Gemma-2 tanh capping
    window: jnp.ndarray | int | None = None,  # sliding window; <= 0 = off
) -> jnp.ndarray:
    """Attention of a prefill chunk against the full cache prefix (causal).

    Softcap/window follow the same order as causal_prefill_attention /
    decode_attention (cap the raw logits, then mask), so a chunked Gemma
    prefill is bit-consistent with the whole-prompt path."""
    b, s, h, d = q.shape
    kvh = k_cache.shape[2]
    scale = scale if scale is not None else d ** -0.5
    qg = _gqa_reshape(q * scale, kvh)
    logits = jnp.einsum(
        "bqkgd,blkd->bkgql", qg.astype(jnp.float32), k_cache.astype(jnp.float32)
    )
    if logit_softcap is not None:
        logits = jnp.tanh(logits / logit_softcap) * logit_softcap
    q_pos = chunk_start[:, None] + jnp.arange(s)[None, :]  # [B, Sq]
    l_pos = jnp.arange(k_cache.shape[1])  # [L]
    mask = q_pos[:, :, None] >= l_pos[None, None, :]  # [B, Sq, L]
    if window is not None:
        win = jnp.asarray(window, jnp.int32)
        mask = mask & (
            (win <= 0)
            | (q_pos[:, :, None] - l_pos[None, None, :] < win)
        )
    logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgql,blkd->bqkgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(b, s, h, d).astype(q.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, H, D] — one new token per slot
    k_cache: jnp.ndarray,  # [B, L, KVH, D]
    v_cache: jnp.ndarray,  # [B, L, KVH, D]
    lengths: jnp.ndarray,  # [B] valid cache length per slot (incl. new token)
    *,
    scale: float | None = None,
    logit_softcap: float | None = None,  # Gemma-2 tanh capping
    window: jnp.ndarray | int | None = None,  # sliding window; <= 0 = off
) -> jnp.ndarray:
    """Single-token decode attention against the slot cache with length mask."""
    b, h, d = q.shape
    kvh = k_cache.shape[2]
    scale = scale if scale is not None else d ** -0.5
    qg = (q * scale).reshape(b, kvh, h // kvh, d)  # [B, KVH, G, D]
    logits = jnp.einsum(
        "bkgd,blkd->bkgl", qg.astype(jnp.float32), k_cache.astype(jnp.float32)
    )
    if logit_softcap is not None:
        logits = jnp.tanh(logits / logit_softcap) * logit_softcap
    l_pos = jnp.arange(k_cache.shape[1])
    mask = l_pos[None, :] < lengths[:, None]  # [B, L]
    if window is not None:
        win = jnp.asarray(window, jnp.int32)
        mask = mask & ((win <= 0) | (l_pos[None, :] >= lengths[:, None] - win))
    logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgl,blkd->bkgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(b, h, d).astype(q.dtype)
