"""Paged decode attention: block-table paging, ragged lengths, TPU kernel.

The decode hot op. The slot cache reads O(B * max_seq_len) of KV per step
regardless of true lengths; paging reads only the pages a sequence
actually occupies. Two implementations with one contract:

  ref_paged_decode_attention — jnp gather-through-block-tables reference
      (CPU/tests; also the fallback when kernel constraints aren't met).
  paged_decode_attention     — Pallas TPU kernel. Grid (slots, max_pages);
      each DMA carries a full page across ALL kv heads (the block's last
      two dims are the full (KVH, D) — a Mosaic tiling requirement) and a
      static in-kernel unroll attends each head. Block tables + lengths
      are SCALAR-PREFETCHED so the BlockSpec index_map selects each
      slot's next real page for DMA. Pages past a slot's length re-map
      to the slot's LAST valid page — consecutive grid steps with an
      unchanged block index elide the copy, so HBM traffic ≈
      sum(ceil(len/page)) pages, not B*max_pages (the revisiting trick;
      compute for those steps is skipped with pl.when).

Sliding-window (Gemma-2) and logit softcap are supported in both paths:
window masks keys at positions < length - window.

Int8 KV (ops/kv_quant.py): a pool passed as a {"q8", "scale"} dict is
a quantized pool. The reference path gathers pages AND scales through
the block tables and dequantizes in f32 before attention; the write
helpers quantize each new token's rows on append. The Pallas kernels
are bf16-only, so quantized pools always dispatch to the reference path
(int8 KV buys capacity, not kernel speed — see kv_quant module docs).

The reference operator has no attention code — it runs vLLM images whose
PagedAttention this replaces TPU-natively (reference:
internal/modelcontroller/engine_vllm.go:12-167 renders the Pod; kernels
live in the external image; charts/kubeai/values.yaml:45).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fine on CPU (needed for interpret-mode tests)
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30

# Which decode-attention layout model families use when the caller doesn't
# say. "per_layer" = scatter-then-attend inside the layer scan through
# paged_decode_attention — the hardware-validated path (1975.5 tok/s/chip,
# bs=64, 1B proxy, measured round 2). "fused" = stacked-pool kernel with a
# deferred scatter (paged_decode_attention_fused) — roofline-better on
# paper, but its first on-chip dispatch hung in round 3, so it stays
# selectable-not-default until a real-TPU A/B validates it.
DECODE_KERNEL_ENV = "KUBEAI_TPU_DECODE_KERNEL"
_DECODE_KERNELS = ("per_layer", "fused")


def default_decode_kernel() -> str:
    mode = os.environ.get(DECODE_KERNEL_ENV, "").strip().lower()
    return mode if mode in _DECODE_KERNELS else "per_layer"


def resolve_decode_kernel(requested: str | None) -> str:
    """Validate an explicit kernel choice; None/"" defers to the env var."""
    if not requested:
        return default_decode_kernel()
    if requested not in _DECODE_KERNELS:
        raise ValueError(
            f"decode kernel {requested!r} not in {_DECODE_KERNELS}"
        )
    return requested


def _accum_head(
    q_ref, k_ref, v_ref, valid, m_ref, l_ref, acc_ref, kh,
    *, scale, logit_softcap, zero_masked_p,
):
    """One kv head's online-softmax update over the current page block.
    Shared by the decode and verify kernels; `zero_masked_p` guards rows
    that can be FULLY masked (verify: speculative rows past a window).
    Scratch refs are [KVH, rows, ...] — indexing the LEADING dim keeps
    every VMEM access tile-aligned regardless of the per-head row count."""
    q = q_ref[0, kh].astype(jnp.float32) * scale  # [rows, D]
    k = k_ref[0, :, kh].astype(jnp.float32)  # [page, D]
    v = v_ref[0, :, kh].astype(jnp.float32)
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [rows, page]
    if logit_softcap is not None:
        s = jnp.tanh(s / logit_softcap) * logit_softcap
    s = jnp.where(valid, s, NEG_INF)
    m_prev = m_ref[kh]
    l_prev = l_ref[kh]
    acc_prev = acc_ref[kh]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    if zero_masked_p:
        # Fully-masked rows keep m = NEG_INF; zero their contributions.
        p = jnp.where(valid, p, 0.0)
    alpha = jnp.exp(m_prev - m_new)
    m_ref[kh] = m_new
    l_ref[kh] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[kh] = acc_prev * alpha + jnp.dot(
        p, v, preferred_element_type=jnp.float32
    )


# ---- functional reference ----------------------------------------------------


def ref_paged_decode_attention(
    q: jnp.ndarray,  # [B, H, D] one new token per slot
    k_pages: jnp.ndarray,  # [P, page, KVH, D] this layer's page pool
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP] page ids, -1 = unallocated
    lengths: jnp.ndarray,  # [B] valid tokens per slot (incl. the new one)
    *,
    scale: float | None = None,
    logit_softcap: float | None = None,
    window: jnp.ndarray | int | None = None,  # sliding window (Gemma-2);
    #   traced scalars OK, <= 0 disables — layer scans alternate
    #   local/global layers with one compiled graph
) -> jnp.ndarray:
    """Gather pages into a virtual contiguous view, then masked attention.
    Semantics oracle for the kernel; CPU/test fallback path. Accepts
    quantized {"q8", "scale"} pools — pages and scales gather through
    the same block tables and dequantize in f32."""
    from kubeai_tpu.ops.kv_quant import is_quantized_kv

    b, h, d = q.shape
    bt = jnp.maximum(block_tables, 0)  # -1 -> scratch page 0 (masked below)
    if is_quantized_kv(k_pages):
        kvh = k_pages["q8"].shape[2]
        k = k_pages["q8"][bt].astype(jnp.float32)  # [B, MP, page, KVH, D]
        v = v_pages["q8"][bt].astype(jnp.float32)
        k = k * k_pages["scale"][bt].astype(jnp.float32)[..., None]
        v = v * v_pages["scale"][bt].astype(jnp.float32)[..., None]
    else:
        kvh = k_pages.shape[2]
        k = k_pages[bt].astype(jnp.float32)
        v = v_pages[bt].astype(jnp.float32)
    mp, page = k.shape[1], k.shape[2]
    k = k.reshape(b, mp * page, kvh, d)
    v = v.reshape(b, mp * page, kvh, d)
    scale = scale if scale is not None else d ** -0.5
    qg = (q * scale).reshape(b, kvh, h // kvh, d)
    logits = jnp.einsum(
        "bkgd,blkd->bkgl", qg.astype(jnp.float32), k
    )
    if logit_softcap is not None:
        logits = jnp.tanh(logits / logit_softcap) * logit_softcap
    pos = jnp.arange(mp * page)
    mask = pos[None, :] < lengths[:, None]  # [B, L]
    if window is not None:
        win = jnp.asarray(window, jnp.int32)
        mask = mask & (
            (win <= 0) | (pos[None, :] >= lengths[:, None] - win)
        )
    logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgl,blkd->bkgd", probs, v)
    return out.reshape(b, h, d).astype(q.dtype)


# ---- Pallas kernel -----------------------------------------------------------


def _paged_kernel(
    # scalar-prefetch
    bt_ref,  # [B, MP] int32 block tables
    len_ref,  # [B] int32 lengths
    win_ref,  # [1] int32 sliding window (<= 0 = disabled)
    # blocks
    q_ref,  # [1, KVH, G, D]
    k_ref,  # [1, page, KVH, D] — the page selected by the index_map
    v_ref,  # [1, page, KVH, D]
    o_ref,  # [1, KVH, G, D]
    # scratch (carried across the page grid dimension)
    m_ref,  # [KVH, G, 1] f32
    l_ref,  # [KVH, G, 1] f32
    acc_ref,  # [KVH, G, D] f32
    *,
    page_size: int,
    kvh: int,
    group: int,
    scale: float,
    logit_softcap: float | None,
):
    # Grid is (slots, pages): one DMA per (slot, page) carries ALL kv
    # heads of that page — Mosaic requires the block's last two dims to
    # be full (KVH, D) here, and the single fetch serves every head.
    b = pl.program_id(0)
    i = pl.program_id(1)
    mp = pl.num_programs(1)

    length = len_ref[b]
    win = win_ref[0]
    n_pages = pl.cdiv(length, page_size)
    # First page holding in-window keys (0 when the window is off):
    # pages below it contribute nothing and their compute is skipped
    # (their DMA is elided by the index_map clamp).
    first = jnp.where(
        win > 0, jnp.maximum(length - win, 0) // page_size, 0
    )

    @pl.when(i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when((i >= first) & (i < n_pages))
    def _attend():
        pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (group, page_size), 1
        )
        valid = pos < length
        valid = valid & ((win <= 0) | (pos >= length - win))
        for kh in range(kvh):  # static unroll: one [G,page] dot per head
            _accum_head(
                q_ref, k_ref, v_ref, valid, m_ref, l_ref, acc_ref, kh,
                scale=scale, logit_softcap=logit_softcap,
                zero_masked_p=False,
            )

    @pl.when(i == mp - 1)
    def _finalize():
        out = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)  # [KVH, G, D]
        o_ref[0] = out.astype(o_ref.dtype)


def _page_index(b, i, bt_ref, len_ref, win_ref, *, page_size):
    """Index map for k/v pages: slot b's i-th page. Outside the live range
    (past the last page, or below the sliding window's first page), KEEP
    RETURNING the nearest live page — an unchanged block index between
    consecutive grid steps elides the DMA entirely."""
    length = len_ref[b]
    win = win_ref[0]
    last = jnp.maximum(pl.cdiv(length, page_size) - 1, 0)
    first = jnp.where(
        win > 0, jnp.maximum(length - win, 0) // page_size, 0
    )
    clamped = jnp.clip(i, first, last)
    page_id = jnp.maximum(bt_ref[b, clamped], 0)
    return page_id, 0, 0, 0


@functools.partial(
    jax.jit,
    static_argnames=("scale", "logit_softcap", "interpret"),
)
def _paged_pallas(
    q,  # [B, KVH, G, D]
    k_pages,  # [P, page, KVH, D]
    v_pages,
    block_tables,  # [B, MP]
    lengths,  # [B]
    window,  # [1] int32, <= 0 disables
    *,
    scale: float,
    logit_softcap: float | None,
    interpret: bool,
):
    b, kvh, g, d = q.shape
    p, page, _, _ = k_pages.shape
    mp = block_tables.shape[1]

    kernel = functools.partial(
        _paged_kernel,
        page_size=page,
        kvh=kvh,
        group=g,
        scale=scale,
        logit_softcap=logit_softcap,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, mp),
        in_specs=[
            pl.BlockSpec(
                (1, kvh, g, d),
                lambda b_, i_, bt, ln, wn: (b_, 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, page, kvh, d),
                functools.partial(_page_index, page_size=page),
            ),
            pl.BlockSpec(
                (1, page, kvh, d),
                functools.partial(_page_index, page_size=page),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, kvh, g, d),
            lambda b_, i_, bt, ln, wn: (b_, 0, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((kvh, g, 1), jnp.float32),
            pltpu.VMEM((kvh, g, 1), jnp.float32),
            pltpu.VMEM((kvh, g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, g, d), q.dtype),
        interpret=interpret,
    )(block_tables, lengths, window, q, k_pages, v_pages)
    return out.reshape(b, kvh * g, d)


def paged_supported(head_dim: int, page_size: int) -> bool:
    """Kernel constraints. The k/v block is (1, page, KVH, D) — its last
    two dims are the FULL array dims, so the BLOCK shape itself imposes
    no divisibility rule; but in-kernel values still use `page` as a
    sublane/lane dim ([page, D] loads, [rows, page] logits), so keep the
    f32 sublane tile divisibility until odd sizes are validated on real
    hardware (non-conforming pools use the jnp reference path)."""
    return page_size % 8 == 0


def paged_decode_attention(
    q: jnp.ndarray,  # [B, H, D]
    k_pages: jnp.ndarray,  # [P, page, KVH, D]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP]
    lengths: jnp.ndarray,  # [B]
    *,
    scale: float | None = None,
    logit_softcap: float | None = None,
    window: jnp.ndarray | int | None = None,
    use_pallas: bool | None = None,  # None = auto (TPU backend only)
    interpret: bool = False,
) -> jnp.ndarray:
    """Paged decode attention with automatic kernel/reference dispatch.
    Quantized {"q8", "scale"} pools always take the reference path (the
    Pallas kernel is bf16-only)."""
    from kubeai_tpu.ops.kv_quant import is_quantized_kv

    b, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    if is_quantized_kv(k_pages):
        return ref_paged_decode_attention(
            q, k_pages, v_pages, block_tables, lengths,
            scale=scale, logit_softcap=logit_softcap, window=window,
        )
    kvh = k_pages.shape[2]
    if use_pallas is None:
        use_pallas = (
            _HAS_PLTPU
            and not interpret
            and jax.default_backend() not in ("cpu",)
            and paged_supported(d, k_pages.shape[1])
        )
    if not use_pallas and not interpret:
        return ref_paged_decode_attention(
            q, k_pages, v_pages, block_tables, lengths,
            scale=scale, logit_softcap=logit_softcap, window=window,
        )
    win_arr = jnp.asarray(
        [0 if window is None else window], jnp.int32
    ).reshape(1)
    qg = q.reshape(b, kvh, h // kvh, d)
    out = _paged_pallas(
        qg, k_pages, v_pages, block_tables, lengths, win_arr,
        scale=scale, logit_softcap=logit_softcap,
        interpret=interpret,
    )
    return out.reshape(b, h, d)


def ref_paged_verify_attention(
    q: jnp.ndarray,  # [B, K, H, D] — K speculative positions per slot
    k_pages: jnp.ndarray,  # [P, page, KVH, D]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP]
    positions: jnp.ndarray,  # [B] absolute position of query 0
    *,
    scale: float | None = None,
    logit_softcap: float | None = None,
    window: jnp.ndarray | int | None = None,
) -> jnp.ndarray:
    """Multi-query paged attention for SPECULATIVE VERIFY: query k sits at
    absolute position positions+k and attends keys at cols <= positions+k
    (the K window's KV is already scattered into the pages). Gather-based
    reference — speculative windows are small (K <= 8), so the extra HBM
    read vs a dedicated kernel is bounded; a multi-query Pallas kernel is
    the upgrade path."""
    b, kq, h, d = q.shape
    kvh = k_pages.shape[2]
    bt = jnp.maximum(block_tables, 0)
    k = k_pages[bt]
    v = v_pages[bt]
    mp, page = k.shape[1], k.shape[2]
    L = mp * page
    k = k.reshape(b, L, kvh, d)
    v = v.reshape(b, L, kvh, d)
    scale = scale if scale is not None else d ** -0.5
    qg = (q * scale).reshape(b, kq, kvh, h // kvh, d)
    logits = jnp.einsum(
        "bqkgd,blkd->bkgql", qg.astype(jnp.float32), k.astype(jnp.float32)
    )  # [B, KVH, G, K, L]
    if logit_softcap is not None:
        logits = jnp.tanh(logits / logit_softcap) * logit_softcap
    col = jnp.arange(L)
    q_abs = positions[:, None] + jnp.arange(kq)[None, :]  # [B, K]
    mask = col[None, None, :] <= q_abs[:, :, None]  # [B, K, L]
    if window is not None:
        win = jnp.asarray(window, jnp.int32)
        mask = mask & (
            (win <= 0) | (col[None, None, :] > q_abs[:, :, None] - win)
        )
    logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgql,blkd->bqkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, kq, h, d).astype(q.dtype)


def _paged_verify_kernel(
    # scalar-prefetch
    bt_ref,  # [B, MP]
    pos_ref,  # [B] absolute position of query 0
    win_ref,  # [1] sliding window (<= 0 off)
    # blocks
    q_ref,  # [1, KVH, K*G, D]
    k_ref,  # [1, page, KVH, D]
    v_ref,  # [1, page, KVH, D]
    o_ref,  # [1, KVH, K*G, D]
    # scratch
    m_ref,  # [KVH, K*G, 1] f32
    l_ref,  # [KVH, K*G, 1] f32
    acc_ref,  # [KVH, K*G, D] f32
    *,
    page_size: int,
    kvh: int,
    scale: float,
    spec_k: int,
    group: int,
    logit_softcap: float | None,
):
    # Grid (slots, pages); every kv head of a page rides one DMA (the
    # block's last two dims must be the full (KVH, D) on TPU).
    b = pl.program_id(0)
    i = pl.program_id(1)
    mp = pl.num_programs(1)
    pos = pos_ref[b]
    win = win_ref[0]
    kq = spec_k * group
    # Keys exist up to absolute position pos + spec_k - 1.
    n_pages = pl.cdiv(pos + spec_k, page_size)
    # First page with any in-window key (query 0 is the lowest row);
    # pages below it are provably all-masked — skip their compute (the
    # index_map clamp already elides their DMA).
    first = jnp.where(
        win > 0, jnp.maximum(pos - win + 1, 0) // page_size, 0
    )

    @pl.when(i == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when((i >= first) & (i < n_pages))
    def _attend():
        col = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (kq, page_size), 1
        )
        row_pos = pos + (
            jax.lax.broadcasted_iota(jnp.int32, (kq, page_size), 0) // group
        )
        valid = col <= row_pos
        valid = valid & ((win <= 0) | (col > row_pos - win))
        for kh in range(kvh):  # static unroll: one [KQ,page] dot per head
            _accum_head(
                q_ref, k_ref, v_ref, valid, m_ref, l_ref, acc_ref, kh,
                scale=scale, logit_softcap=logit_softcap,
                zero_masked_p=True,
            )

    @pl.when(i == mp - 1)
    def _finalize():
        out = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)  # [KVH, KQ, D]
        o_ref[0] = out.astype(o_ref.dtype)


def _verify_page_index(b, i, bt_ref, pos_ref, win_ref, *, page_size, spec_k):
    """Clamp to the slot's live page range so out-of-range grid steps
    revisit a live page (DMA elided)."""
    pos = pos_ref[b]
    win = win_ref[0]
    last = jnp.maximum(pl.cdiv(pos + spec_k, page_size) - 1, 0)
    first = jnp.where(
        win > 0, jnp.maximum(pos - win + 1, 0) // page_size, 0
    )
    clamped = jnp.clip(i, first, last)
    return jnp.maximum(bt_ref[b, clamped], 0), 0, 0, 0


@functools.partial(
    jax.jit,
    static_argnames=(
        "spec_k", "group", "scale", "logit_softcap", "interpret",
    ),
)
def _paged_verify_pallas(
    q,  # [B, KVH, K*G, D]
    k_pages,
    v_pages,
    block_tables,
    positions,  # [B]
    window,  # [1] int32
    spec_k: int,
    group: int,
    *,
    scale: float,
    logit_softcap: float | None,
    interpret: bool,
):
    b, kvh, kq, d = q.shape
    page = k_pages.shape[1]
    mp = block_tables.shape[1]
    kernel = functools.partial(
        _paged_verify_kernel,
        page_size=page,
        kvh=kvh,
        scale=scale,
        spec_k=int(spec_k),
        group=int(group),
        logit_softcap=logit_softcap,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, mp),
        in_specs=[
            pl.BlockSpec(
                (1, kvh, kq, d),
                lambda b_, i_, bt, ps, wn: (b_, 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, page, kvh, d),
                functools.partial(
                    _verify_page_index, page_size=page, spec_k=int(spec_k)
                ),
            ),
            pl.BlockSpec(
                (1, page, kvh, d),
                functools.partial(
                    _verify_page_index, page_size=page, spec_k=int(spec_k)
                ),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, kvh, kq, d),
            lambda b_, i_, bt, ps, wn: (b_, 0, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((kvh, kq, 1), jnp.float32),
            pltpu.VMEM((kvh, kq, 1), jnp.float32),
            pltpu.VMEM((kvh, kq, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, kq, d), q.dtype),
        interpret=interpret,
    )(block_tables, positions, window, q, k_pages, v_pages)


def paged_verify_attention(
    q: jnp.ndarray,  # [B, K, H, D]
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,
    positions: jnp.ndarray,  # [B]
    *,
    scale: float | None = None,
    logit_softcap: float | None = None,
    window: jnp.ndarray | int | None = None,
    use_pallas: bool | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Multi-query paged verify attention with kernel/reference dispatch
    (speculative decoding's verify pass; see ref_paged_verify_attention
    for semantics)."""
    b, spec_k, h, d = q.shape
    kvh = k_pages.shape[2]
    group = h // kvh
    scale = scale if scale is not None else d ** -0.5
    if use_pallas is None:
        use_pallas = (
            _HAS_PLTPU
            and not interpret
            and jax.default_backend() not in ("cpu",)
            and paged_supported(d, k_pages.shape[1])
        )
    if not use_pallas and not interpret:
        return ref_paged_verify_attention(
            q, k_pages, v_pages, block_tables, positions,
            scale=scale, logit_softcap=logit_softcap, window=window,
        )
    win_arr = jnp.asarray(
        [0 if window is None else window], jnp.int32
    ).reshape(1)
    # [B, K, H, D] -> [B, KVH, K*G, D]: row r = query r//G, q-head-in-group
    # r%G, so the kernel's row//group recovers the query index.
    qk = jnp.moveaxis(
        q.reshape(b, spec_k, kvh, group, d), 1, 2
    ).reshape(b, kvh, spec_k * group, d)
    out = _paged_verify_pallas(
        qk, k_pages, v_pages, block_tables, positions, win_arr,
        spec_k, group,
        scale=scale, logit_softcap=logit_softcap, interpret=interpret,
    )
    out = jnp.moveaxis(
        out.reshape(b, kvh, spec_k, group, d), 2, 1
    )  # [B, K, KVH, G, D]
    return out.reshape(b, spec_k, h, d)


# ---- fused decode kernel (stacked pools, deferred scatter) -------------------
#
# The decode-step redesign that closes the roofline gap (ROADMAP round-3
# item 1). Three wastes in the original scatter-then-attend layer loop:
#   1. lax.scan sliced each layer's [P, page, KVH, D] pool out of the
#      stacked array and re-stacked the updated slice — a full KV-pool
#      round-trip through HBM every decode step (~2 GB at bs=64/1B) even
#      though only B tokens/layer actually change.
#   2. pallas_call is opaque to XLA, so the sliced operand MATERIALIZES
#      (no fusion into the kernel).
#   3. Grid (slots, pages) ran one small page DMA per step — latency-
#      bound, not bandwidth-bound.
# The fused kernel fixes all three: it takes the FULL [NL, ...] pool plus
# a scalar-prefetched layer index (the index map adds the layer offset —
# no slicing, no materialization), attends the NEW token as an explicit
# extra column merged at finalize (so the pool stays read-only and the
# scatter defers to ONE batched write after the layer scan), and DMAs a
# STRIP of pages per grid step with the slot dimension megacore-parallel.


def _fused_attend_page(
    q_ref, k_ref, valid, m_ref, l_ref, acc_ref, v_ref,
    *, scale, logit_softcap, kvh,
):
    """Online-softmax update of all kv heads over one [page] block.
    k_ref/v_ref are [1, 1, page, KVH, D] strip blocks."""
    for kh in range(kvh):
        q = q_ref[0, kh].astype(jnp.float32) * scale  # [G, D]
        k = k_ref[0, 0, :, kh].astype(jnp.float32)  # [page, D]
        v = v_ref[0, 0, :, kh].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [G, page]
        if logit_softcap is not None:
            s = jnp.tanh(s / logit_softcap) * logit_softcap
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_ref[kh]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[kh] = m_new
        l_ref[kh] = l_ref[kh] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[kh] = acc_ref[kh] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )


def _paged_fused_kernel(
    # scalar-prefetch
    bt_ref,  # [B, MP] int32 block tables
    pos_ref,  # [B] int32 OLD lengths (the new token's position)
    win_ref,  # [1] int32 sliding window (<= 0 = disabled)
    layer_ref,  # [1] int32 layer index into the stacked pool
    # blocks
    q_ref,  # [1, KVH, G, D]
    kn_ref,  # [1, KVH, D] the new token's K (not yet in the pool)
    vn_ref,  # [1, KVH, D]
    *refs,  # strip k blocks, strip v blocks [1, 1, page, KVH, D], then o_ref
    # (scratch appended by pallas: m, l, acc)
    page_size: int,
    kvh: int,
    group: int,
    strip: int,
    scale: float,
    logit_softcap: float | None,
):
    k_refs = refs[:strip]
    v_refs = refs[strip:2 * strip]
    o_ref = refs[2 * strip]  # [1, KVH, G, D]
    m_ref, l_ref, acc_ref = refs[2 * strip + 1:2 * strip + 4]

    b = pl.program_id(0)
    s = pl.program_id(1)
    ns = pl.num_programs(1)
    pos = pos_ref[b]
    win = win_ref[0]
    length = pos + 1  # including the new token
    n_pages = pl.cdiv(pos, page_size)  # pages holding OLD tokens
    first = jnp.where(
        win > 0, jnp.maximum(length - win, 0) // page_size, 0
    )

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    for t in range(strip):
        i = s * strip + t

        @pl.when((i >= first) & (i < n_pages))
        def _attend(i=i, t=t):
            pcol = i * page_size + jax.lax.broadcasted_iota(
                jnp.int32, (group, page_size), 1
            )
            valid = pcol < pos  # old tokens only; new token merged below
            valid = valid & ((win <= 0) | (pcol >= length - win))
            _fused_attend_page(
                q_ref, k_refs[t], valid, m_ref, l_ref, acc_ref, v_refs[t],
                scale=scale, logit_softcap=logit_softcap, kvh=kvh,
            )

    @pl.when(s == ns - 1)
    def _finalize():
        # Merge the new token as one extra column (always valid — it is
        # the query's own position, inside any window), then normalize.
        q = q_ref[0].astype(jnp.float32) * scale  # [KVH, G, D]
        kn = kn_ref[0].astype(jnp.float32)  # [KVH, D]
        vn = vn_ref[0].astype(jnp.float32)
        s_new = jnp.sum(q * kn[:, None, :], axis=-1)  # [KVH, G]
        if logit_softcap is not None:
            s_new = jnp.tanh(s_new / logit_softcap) * logit_softcap
        s_new = s_new[..., None]  # [KVH, G, 1]
        m_prev = m_ref[:]
        m_fin = jnp.maximum(m_prev, s_new)
        p = jnp.exp(s_new - m_fin)
        alpha = jnp.exp(m_prev - m_fin)
        l_fin = l_ref[:] * alpha + p
        acc_fin = acc_ref[:] * alpha + p * vn[:, None, :]
        out = acc_fin / jnp.maximum(l_fin, 1e-30)  # [KVH, G, D]
        o_ref[0] = out.astype(o_ref.dtype)


def _fused_page_index(
    b, s, bt_ref, pos_ref, win_ref, layer_ref, *, page_size, strip, t
):
    """Index map for strip member t: slot b's (s*strip + t)-th page of
    layer layer_ref[0]. Outside the live range the index clamps to the
    nearest live page so an unchanged block index elides the DMA."""
    pos = pos_ref[b]
    win = win_ref[0]
    last = jnp.maximum(pl.cdiv(pos, page_size) - 1, 0)
    first = jnp.where(
        win > 0, jnp.maximum(pos + 1 - win, 0) // page_size, 0
    )
    clamped = jnp.clip(s * strip + t, first, last)
    page_id = jnp.maximum(bt_ref[b, clamped], 0)
    return layer_ref[0], page_id, 0, 0, 0


# Pages fetched per grid step. 4 × 64-token pages ≈ 512 KB of K+V per
# step at KVH=8/D=64/bf16 — enough DMA in flight to be bandwidth-bound
# instead of latency-bound, without blowing VMEM.
FUSED_STRIP = 4


@functools.partial(
    jax.jit,
    static_argnames=("scale", "logit_softcap", "interpret"),
)
def _paged_fused_pallas(
    q,  # [B, KVH, G, D]
    k_pages,  # [NL, P, page, KVH, D] FULL stacked pool
    v_pages,
    k_new,  # [B, KVH, D]
    v_new,
    block_tables,  # [B, MP]
    positions,  # [B] old lengths
    window,  # [1] int32
    layer,  # [1] int32
    *,
    scale: float,
    logit_softcap: float | None,
    interpret: bool,
):
    b, kvh, g, d = q.shape
    _, p, page, _, _ = k_pages.shape
    mp = block_tables.shape[1]
    strip = min(FUSED_STRIP, mp)
    ns = -(-mp // strip)

    kernel = functools.partial(
        _paged_fused_kernel,
        page_size=page,
        kvh=kvh,
        group=g,
        strip=strip,
        scale=scale,
        logit_softcap=logit_softcap,
    )
    page_spec = [
        pl.BlockSpec(
            (1, 1, page, kvh, d),
            functools.partial(
                _fused_page_index, page_size=page, strip=strip, t=t
            ),
        )
        for t in range(strip)
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, ns),
        in_specs=[
            pl.BlockSpec(
                (1, kvh, g, d), lambda b_, s_, *refs: (b_, 0, 0, 0)
            ),
            pl.BlockSpec((1, kvh, d), lambda b_, s_, *refs: (b_, 0, 0)),
            pl.BlockSpec((1, kvh, d), lambda b_, s_, *refs: (b_, 0, 0)),
            *page_spec,  # k strip
            *page_spec,  # v strip (same index maps)
        ],
        out_specs=pl.BlockSpec(
            (1, kvh, g, d), lambda b_, s_, *refs: (b_, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((kvh, g, 1), jnp.float32),
            pltpu.VMEM((kvh, g, 1), jnp.float32),
            pltpu.VMEM((kvh, g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, g, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            # Slots are independent (scratch re-inits at s == 0 per slot):
            # split them across the two TensorCores.
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        block_tables, positions, window, layer,
        q, k_new, v_new,
        *([k_pages] * strip), *([v_pages] * strip),
    )
    return out


def ref_paged_decode_attention_fused(
    q: jnp.ndarray,  # [B, H, D]
    k_pages: jnp.ndarray,  # [NL, P, page, KVH, D] stacked pools
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, KVH, D]
    v_new: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP]
    positions: jnp.ndarray,  # [B] OLD lengths (new token's position)
    layer: jnp.ndarray,  # scalar int32
    *,
    scale: float | None = None,
    logit_softcap: float | None = None,
    window: jnp.ndarray | int | None = None,
) -> jnp.ndarray:
    """Reference semantics for the fused kernel: attention over the
    resident pages of `layer` PLUS the new token as an explicit extra
    column at position `positions`. Bit-equivalent (up to fp reorder) to
    scatter-then-attend with lengths = positions + 1."""
    b, h, d = q.shape
    kvh = k_pages.shape[3]
    kp = jax.lax.dynamic_index_in_dim(
        k_pages, layer, axis=0, keepdims=False
    )
    vp = jax.lax.dynamic_index_in_dim(
        v_pages, layer, axis=0, keepdims=False
    )
    bt = jnp.maximum(block_tables, 0)
    k = kp[bt]  # [B, MP, page, KVH, D]
    v = vp[bt]
    mp, page = k.shape[1], k.shape[2]
    L = mp * page
    k = k.reshape(b, L, kvh, d)
    v = v.reshape(b, L, kvh, d)
    # Append the new token as column L.
    k = jnp.concatenate([k, k_new[:, None].astype(k.dtype)], axis=1)
    v = jnp.concatenate([v, v_new[:, None].astype(v.dtype)], axis=1)
    scale = scale if scale is not None else d ** -0.5
    qg = (q * scale).reshape(b, kvh, h // kvh, d)
    logits = jnp.einsum(
        "bkgd,blkd->bkgl", qg.astype(jnp.float32), k.astype(jnp.float32)
    )
    if logit_softcap is not None:
        logits = jnp.tanh(logits / logit_softcap) * logit_softcap
    col = jnp.arange(L + 1)
    # Columns < positions are old tokens; column L is the new token.
    mask = (col[None, :] < positions[:, None]) | (col[None, :] == L)
    if window is not None:
        win = jnp.asarray(window, jnp.int32)
        lengths = positions + 1
        in_win = (win <= 0) | (col[None, :] >= lengths[:, None] - win)
        mask = mask & (in_win | (col[None, :] == L))
    logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgl,blkd->bkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, h, d).astype(q.dtype)


def paged_decode_attention_fused(
    q: jnp.ndarray,  # [B, H, D]
    k_pages: jnp.ndarray,  # [NL, P, page, KVH, D] stacked pools
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, KVH, D]
    v_new: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP]
    positions: jnp.ndarray,  # [B] OLD lengths
    layer: jnp.ndarray | int,  # layer index into the stacked pool
    *,
    scale: float | None = None,
    logit_softcap: float | None = None,
    window: jnp.ndarray | int | None = None,
    use_pallas: bool | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused paged decode attention: reads the layer's resident pages
    straight out of the STACKED pool (no per-layer slice materialization)
    and folds the not-yet-scattered new token in as an extra column, so
    the caller can defer all KV-cache writes to one batched scatter after
    the layer scan. See module docstring for why this is the fast path."""
    b, h, d = q.shape
    kvh = k_pages.shape[3]
    scale = scale if scale is not None else d ** -0.5
    layer_arr = jnp.asarray(layer, jnp.int32)
    if use_pallas is None:
        use_pallas = (
            _HAS_PLTPU
            and not interpret
            and jax.default_backend() not in ("cpu",)
            and paged_supported(d, k_pages.shape[2])
        )
    if not use_pallas and not interpret:
        return ref_paged_decode_attention_fused(
            q, k_pages, v_pages, k_new, v_new, block_tables, positions,
            layer_arr, scale=scale, logit_softcap=logit_softcap,
            window=window,
        )
    win_arr = jnp.asarray(
        [0 if window is None else window], jnp.int32
    ).reshape(1)
    qg = q.reshape(b, kvh, h // kvh, d)
    out = _paged_fused_pallas(
        qg, k_pages, v_pages, k_new, v_new, block_tables, positions,
        win_arr, layer_arr.reshape(1),
        scale=scale, logit_softcap=logit_softcap, interpret=interpret,
    )
    return out.reshape(b, h, d)


# ---- paged cache writes (decode + admission) ---------------------------------


def token_page_coords(
    block_tables: jnp.ndarray,  # [B, MP]
    positions: jnp.ndarray,  # [B] absolute position of the new token
    page_size: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(page_ids [B], offsets [B]) for one new token per slot. Unallocated
    entries (-1) AND positions past the block table (a speculative window
    can poke beyond max_seq_len near the context end — jnp gather CLAMPS
    out-of-bounds indices, which would silently hit a live page) map to
    the reserved scratch page 0."""
    mp = block_tables.shape[1]
    slot_idx = jnp.arange(block_tables.shape[0])
    pidx = positions // page_size
    page_ids = block_tables[slot_idx, jnp.minimum(pidx, mp - 1)]
    page_ids = jnp.where(pidx < mp, page_ids, -1)
    return jnp.maximum(page_ids, 0), positions % page_size


def scatter_decode_token(
    k_pages: jnp.ndarray,  # [P, page, KVH, D] (one layer)
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, KVH, D]
    v_new: jnp.ndarray,
    page_ids: jnp.ndarray,  # [B]
    offsets: jnp.ndarray,  # [B]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write one token per slot through the block tables (decode step).
    Quantized pools quantize-on-append: each new row gets its own scale,
    so resident tokens are never re-scaled (pages stay immutable)."""
    from kubeai_tpu.ops.kv_quant import is_quantized_kv, quantize_kv

    if is_quantized_kv(k_pages):
        k8, ks = quantize_kv(k_new)
        v8, vs = quantize_kv(v_new)
        return (
            {
                "q8": k_pages["q8"].at[page_ids, offsets].set(k8),
                "scale": k_pages["scale"].at[page_ids, offsets].set(ks),
            },
            {
                "q8": v_pages["q8"].at[page_ids, offsets].set(v8),
                "scale": v_pages["scale"].at[page_ids, offsets].set(vs),
            },
        )
    k_pages = k_pages.at[page_ids, offsets].set(k_new.astype(k_pages.dtype))
    v_pages = v_pages.at[page_ids, offsets].set(v_new.astype(v_pages.dtype))
    return k_pages, v_pages


def batched_sequence_page_coords(
    bt_rows: jnp.ndarray,  # [A, MP] block-table rows (one per admission)
    lengths: jnp.ndarray,  # [A] true lengths
    seq_len: int,  # padded (bucket) length
    page_size: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(page_ids [A, S], offsets [A, S]) for prefilled sequences. Padded
    tail positions (>= length) and unallocated entries (-1) write into
    the reserved scratch page 0."""
    pos = jnp.arange(seq_len)
    page_ids = jnp.maximum(bt_rows[:, pos // page_size], 0)
    page_ids = jnp.where(pos[None, :] < lengths[:, None], page_ids, 0)
    return page_ids, jnp.broadcast_to(pos % page_size, page_ids.shape)


def batched_scatter_sequence(
    k_pages: jnp.ndarray,  # [NL, P, page, KVH, D]
    v_pages: jnp.ndarray,
    k_seq: jnp.ndarray,  # [NL, A, S, KVH, D]
    v_seq: jnp.ndarray,
    page_ids: jnp.ndarray,  # [A, S]
    offsets: jnp.ndarray,  # [A, S]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write A prefilled sequences through their block tables in one
    static-shape scatter (batched admission). Quantized pools quantize
    each token row on the way in (prefill output is bf16)."""
    from kubeai_tpu.ops.kv_quant import is_quantized_kv, quantize_kv

    if is_quantized_kv(k_pages):
        k8, ks = quantize_kv(k_seq)
        v8, vs = quantize_kv(v_seq)
        return (
            {
                "q8": k_pages["q8"].at[:, page_ids, offsets].set(k8),
                "scale": k_pages["scale"].at[:, page_ids, offsets].set(ks),
            },
            {
                "q8": v_pages["q8"].at[:, page_ids, offsets].set(v8),
                "scale": v_pages["scale"].at[:, page_ids, offsets].set(vs),
            },
        )
    k_pages = k_pages.at[:, page_ids, offsets].set(
        k_seq.astype(k_pages.dtype)
    )
    v_pages = v_pages.at[:, page_ids, offsets].set(
        v_seq.astype(v_pages.dtype)
    )
    return k_pages, v_pages


def sequence_page_coords(
    bt_row: jnp.ndarray,  # [MP] the slot's block-table row
    length: jnp.ndarray,  # scalar true length
    seq_len: int,  # padded (bucket) length
    page_size: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Single-sequence view of batched_sequence_page_coords."""
    ids, offs = batched_sequence_page_coords(
        bt_row[None], jnp.asarray(length)[None], seq_len, page_size
    )
    return ids[0], offs[0]


def scatter_sequence(
    k_pages: jnp.ndarray,  # [NL, P, page, KVH, D]
    v_pages: jnp.ndarray,
    k_seq: jnp.ndarray,  # [NL, S, KVH, D]
    v_seq: jnp.ndarray,
    page_ids: jnp.ndarray,  # [S]
    offsets: jnp.ndarray,  # [S]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Single-sequence view of batched_scatter_sequence."""
    return batched_scatter_sequence(
        k_pages, v_pages, k_seq[:, None], v_seq[:, None],
        page_ids[None], offsets[None],
    )


def scatter_sequence_prequantized(
    k_pages: dict,  # quantized pools {"q8", "scale"}
    v_pages: dict,
    k8_seq: jnp.ndarray,  # [NL, S, KVH, D] int8 — wire bytes, verbatim
    ks_seq: jnp.ndarray,  # [NL, S, KVH] f32 scales
    v8_seq: jnp.ndarray,
    vs_seq: jnp.ndarray,
    page_ids: jnp.ndarray,  # [S]
    offsets: jnp.ndarray,  # [S]
) -> tuple[dict, dict]:
    """Scatter ALREADY-QUANTIZED rows (a KV handoff import): the int8
    values and their scales pass through untouched — re-quantizing would
    break the byte-identity a quantized handoff round-trip guarantees."""
    return (
        {
            "q8": k_pages["q8"].at[:, page_ids, offsets].set(k8_seq),
            "scale": k_pages["scale"].at[:, page_ids, offsets].set(ks_seq),
        },
        {
            "q8": v_pages["q8"].at[:, page_ids, offsets].set(v8_seq),
            "scale": v_pages["scale"].at[:, page_ids, offsets].set(vs_seq),
        },
    )
