"""Normalization ops.

RMSNorm computed in float32 for numerical stability, cast back to the input
dtype — XLA fuses this into neighbouring elementwise work so it stays HBM-
bandwidth-bound, not an extra kernel.
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * weight.astype(jnp.float32)).astype(dtype)
