"""Core TPU ops: norms, rotary embeddings, attention.

These are the hot ops of the serving engine the reference outsources to
vLLM's CUDA kernels (reference: charts/kubeai/values.yaml:45-48 pulls
`vllm/vllm-openai` images). Implemented here as XLA-friendly JAX with
optional Pallas TPU kernels (kubeai_tpu.ops.pallas_attention) for the
attention inner loops.
"""

from kubeai_tpu.ops.norms import rms_norm
from kubeai_tpu.ops.rope import apply_rope, rope_frequencies
from kubeai_tpu.ops.attention import (
    causal_prefill_attention,
    decode_attention,
    chunked_prefill_attention,
)
