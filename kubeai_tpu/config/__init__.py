"""System configuration (reference: internal/config/system.go)."""

from kubeai_tpu.config.system import (
    System,
    ResourceProfile,
    CacheProfile,
    ModelAutoscaling,
    ModelRollouts,
    ModelServerPods,
    Messaging,
    MessageStream,
    LeaderElectionConfig,
    Resilience,
    load_config_file,
)
