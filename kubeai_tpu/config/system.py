"""System-level configuration, loaded from a single YAML/JSON file.

TPU-native rebuild of the reference's `config.System`
(reference: internal/config/system.go:13-260): resource profiles carry TPU
topology (`google.com/tpu` resources + `gke-tpu-accelerator`/`gke-tpu-topology`
node selectors, as the reference's GKE values do —
reference: charts/kubeai/values-gke.yaml:18-41), engine image matrices
include the in-tree TPU engine, and defaulting/validation mirrors
`DefaultAndValidate` (reference: internal/config/system.go:49-85).

Parsing uses a small strict loader (no external YAML dep needed for tests:
JSON is valid YAML; a minimal YAML subset parser handles the common config
shapes when PyYAML is unavailable).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
from typing import Any


class ConfigError(ValueError):
    pass


# RFC 1123 DNS label: what a cluster (or peer) name must be so it can
# ride in metric labels, snapshot keys, and k8s object names unchanged.
_DNS_LABEL = re.compile(r"^[a-z0-9]([a-z0-9-]*[a-z0-9])?$")


def is_dns_label(name: str) -> bool:
    return bool(name) and len(name) <= 63 and bool(_DNS_LABEL.match(name))


# GKE TPU node labels (reference: charts/kubeai/values-gke.yaml:18-41).
TPU_ACCELERATOR_SELECTOR = "cloud.google.com/gke-tpu-accelerator"
TPU_TOPOLOGY_SELECTOR = "cloud.google.com/gke-tpu-topology"


@dataclasses.dataclass
class ResourceProfile:
    """Compute class multiplied by `resourceProfile: name:count`
    (reference: internal/config/system.go:191-200)."""

    image_name: str = ""
    requests: dict[str, str] = dataclasses.field(default_factory=dict)
    limits: dict[str, str] = dataclasses.field(default_factory=dict)
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    affinity: dict | None = None
    tolerations: list[dict] = dataclasses.field(default_factory=list)
    scheduler_name: str = ""
    runtime_class_name: str = ""
    # Hosts per replica: >1 for TPU slices spanning hosts (e.g. v5e-4x4 =
    # 16 chips = 2 hosts); requests/limits describe ONE host's share. The
    # operator renders one Pod per host behind a headless Service.
    num_hosts: int = 1

    @property
    def tpu_topology(self) -> str | None:
        return self.node_selector.get(TPU_TOPOLOGY_SELECTOR)

    @property
    def tpu_accelerator(self) -> str | None:
        return self.node_selector.get(TPU_ACCELERATOR_SELECTOR)


@dataclasses.dataclass
class CacheProfile:
    """Shared-filesystem model cache (reference: internal/config/system.go:202-212)."""

    shared_filesystem: dict | None = None  # {storageClassName|persistentVolumeName}


@dataclasses.dataclass
class ModelAutoscaling:
    """(reference: internal/config/system.go:119-146)"""

    interval_seconds: float = 10.0
    time_window_seconds: float = 600.0
    state_configmap_name: str = "kubeai-autoscaler-state"
    # Queue-pressure boost (kubeai_tpu/scheduling): when a model's oldest
    # queued request is at least this old (seconds), the engines' queued
    # depth counts as unmet demand on top of the active-request average —
    # a saturated-but-steady replica set stops looking "done scaling".
    # 0 disables the boost.
    queue_pressure_max_wait_seconds: float = 3.0

    @property
    def average_window_count(self) -> int:
        # reference: internal/config/system.go AverageWindowCount()
        return int(math.ceil(self.time_window_seconds / self.interval_seconds))

    def required_consecutive_scale_downs(self, scale_down_delay_seconds: float) -> int:
        # reference: internal/config/system.go:131-137
        return int(math.ceil(scale_down_delay_seconds / self.interval_seconds))


@dataclasses.dataclass
class CapacityPlanning:
    """Cluster-wide coordinated capacity planner
    (kubeai_tpu/fleet/planner; no reference analog — the reference
    scales every model independently). When enabled, the planner
    bin-packs every model's desired replicas onto the cluster chip
    budget by scheduling class and the autoscaler applies the plan's
    allocations instead of its solo desires (direct scaling remains the
    stale-plan fallback)."""

    enabled: bool = True
    # Planning cadence. 0 = follow modelAutoscaling.interval.
    interval_seconds: float = 0.0
    # Whether the planner marks preemption-victim pods
    # (kubeai.org/planner-preempt) for pod_plan's deletion ordering.
    preemption: bool = True


@dataclasses.dataclass
class GovernorConfig:
    """Actuation safety governor (kubeai_tpu/operator/governor; no
    reference analog — the reference trusts its own control loop).
    Every destructive control-plane action (healthy-pod deletion,
    scale-down, planner preemption marks) flows through the governor,
    which enforces per-model and cluster-wide disruption budgets per
    sliding time window, refuses scale-to-zero and preemption when
    fleet-telemetry coverage is below `minTelemetryCoverage`, and holds
    last-known-good replica counts (static stability) while telemetry
    is absent or stale."""

    enabled: bool = True
    # Sliding budget window. Budgets are BUDGETED (healthy/ready) pod
    # disruptions only — replacing pods that are already broken is
    # repair, not disruption, and is never budget-limited.
    window_seconds: float = 60.0
    # Max healthy-pod disruptions per model per window.
    model_disruption_budget: int = 10
    # Max healthy-pod disruptions cluster-wide per window.
    cluster_disruption_budget: int = 50
    # Minimum fraction of a model's endpoints with fresh telemetry
    # required before the governor allows scale-to-zero or planner
    # preemption of that model. 0 disarms the coverage gate (and the
    # static-stability hold that rides on it) — the compatible default;
    # fleets that run the aggregator set e.g. 0.5.
    min_telemetry_coverage: float = 0.0


@dataclasses.dataclass
class TenancyConfig:
    """Front-door tenant admission (kubeai_tpu/fleet/tenancy; no
    reference analog — the reference admits everything and lets engines
    drown). System-wide defaults for per-tenant token-bucket rate
    limits, rolling-window token-budget quotas, and the global overload
    door; per-model CRD `tenancy:` blocks override the per-tenant
    limits. Door state only — none of this renders into engine flags or
    pod specs. Disabled by default: the governor is then never
    constructed and the serving path is identical to a build without
    it."""

    enabled: bool = False
    # Per-tenant token buckets, keyed tenant×model. 0 = unlimited.
    requests_per_second: float = 0.0
    request_burst: float = 0.0     # 0 -> max(rate, 1)
    tokens_per_second: float = 0.0
    token_burst: float = 0.0       # 0 -> max(rate, 1)
    # Rolling-window token budget fed by the UsageMeter ledger.
    # 0 for either disables the quota check.
    window_seconds: float = 0.0
    window_token_budget: int = 0
    # Global overload door: fleet-wide queue depth (aggregator
    # snapshot, direct-scrape fallback) at which the door starts
    # shedding batch-class work; standard sheds at
    # overload_standard_factor x high water; realtime never door-sheds.
    # 0 disables overload shedding. Low water (hysteresis release)
    # defaults to 0.8 x high water when unset.
    overload_high_water: float = 0.0
    overload_low_water: float = 0.0
    overload_standard_factor: float = 2.0
    # Retry-After clamp band for door refusals.
    min_retry_after_seconds: float = 0.25
    max_retry_after_seconds: float = 300.0
    # Metric-cardinality cap: distinct tenant label values on
    # kubeai_tenant_* / kubeai_door_* series (overflow -> 'other').
    max_tenant_series: int = 512
    # Tenants idle this long have their door state and metric series
    # expired (label-churn pass).
    tenant_idle_seconds: float = 600.0
    # Horizontal door sharding: number of in-process door shards behind
    # the round-robin shard picker. 1 = the classic single door
    # (byte-identical arithmetic). >1 wires the gossiped CRDT state
    # plane (routing/gossip) so N shards enforce ONE global budget.
    door_shards: int = 1
    # Anti-entropy cadence: seconds between gossip rounds (driven
    # lazily from the admission path on the injected clock).
    gossip_interval_seconds: float = 1.0
    # A peer unheard-from for this long counts as partitioned; the
    # shard degrades to local-view enforcement with a conservative
    # budget split until the peer is heard again.
    gossip_stale_seconds: float = 5.0


@dataclasses.dataclass
class PeerClusterConfig:
    """One peer cluster this cluster may spill to / fail over toward.
    `door_url` is the peer's front-door base URL (its OpenAIServer);
    `spill_url` optionally names the peer's KV spill store so prefix
    pages can be filled cross-cluster instead of recomputed;
    `rtt_seconds` is the operator-measured network round trip used by
    the federation router's cost ranking."""

    name: str = ""
    door_url: str = ""
    spill_url: str = ""
    rtt_seconds: float = 0.05


@dataclasses.dataclass
class ClusterConfig:
    """This cluster's identity in a federation (kubeai_tpu/federation;
    no reference analog — the reference is single-cluster). The name is
    stamped on every fleet snapshot so a federation join can tell whose
    telemetry it is looking at; peers list the clusters requests may
    spill to. Defaults to a standalone cluster named "local" with no
    peers — byte-identical behavior to a build without this block."""

    name: str = "local"
    region: str = ""
    peers: list[PeerClusterConfig] = dataclasses.field(
        default_factory=list
    )

    def peer(self, name: str) -> PeerClusterConfig | None:
        for p in self.peers:
            if p.name == name:
                return p
        return None


@dataclasses.dataclass
class FederationConfig:
    """Federation plane (kubeai_tpu/federation). When enabled, the
    manager wires a FederationAggregator (joined multi-cluster
    snapshots), a FederationRouter in the front door (cost-ranked
    spillover to peer doors on local chip exhaustion), and a
    FederationPlanner pass (whole-model failover when a peer cluster
    partitions, every actuation governor-gated). Disabled by default:
    nothing is constructed and the serving path is identical to a
    single-cluster build."""

    enabled: bool = False
    # Join cadence. 0 = follow modelAutoscaling.interval.
    interval_seconds: float = 0.0
    # A peer snapshot older than this is flagged stale and excluded
    # from routing/failover decisions. 0 = 3 x interval.
    staleness_seconds: float = 0.0
    # A peer must be unreachable/stale this long before the federation
    # planner fails its models over (bounded-window failover, and the
    # heal path reverses it once the peer reports fresh again).
    failover_window_seconds: float = 30.0
    # Cost model: estimated local wait = queue oldest wait + depth x
    # this per-request service estimate; remote cost = peer RTT
    # (+ measured model boot cost when the peer would cold-start it).
    queue_wait_per_request_seconds: float = 0.1


@dataclasses.dataclass
class SLOConfig:
    """SLO plane (kubeai_tpu/fleet/slo; no reference analog — the
    reference emits metrics and lets the operator's humans judge them).
    System-wide default objectives per scheduling class ride here;
    per-model CRD `slo:` blocks override the targets. The evaluator
    judges every objective each tick from fleet-aggregator snapshots
    with multi-window multi-burn-rate logic (Google SRE workbook shape):
    fast burn pages when BOTH the short and long fast windows burn above
    `fastBurnThreshold`; slow burn warns on the slow window alone. A
    page fires the flight recorder's incident bundling. Disabled by
    default: the evaluator is never constructed and nothing changes."""

    enabled: bool = False
    # Evaluation cadence. 0 = follow modelAutoscaling.interval.
    interval_seconds: float = 0.0
    # Default objective targets (0 disables that objective).
    ttft_p95_seconds: float = 0.0   # 95% of requests see TTFT <= this
    itl_p99_seconds: float = 0.0    # 99% of tokens see ITL <= this
    availability: float = 0.0       # e.g. 0.999 request success target
    max_shed_rate: float = 0.0      # max fraction door-shed, e.g. 0.05
    # Error-budget ledger horizon (rolling).
    budget_window_seconds: float = 3600.0
    # Burn-rate alert rules.
    fast_burn_threshold: float = 14.4
    fast_burn_window_seconds: float = 300.0
    fast_burn_short_window_seconds: float = 60.0
    slow_burn_threshold: float = 3.0
    slow_burn_window_seconds: float = 1800.0
    # Incident bundles land here ("" = retained in memory only).
    incident_dir: str = ""
    # Per-trigger debounce between bundles.
    min_incident_interval_seconds: float = 300.0


@dataclasses.dataclass
class ModelRollouts:
    """Surge pods during rollout (reference: internal/config/system.go:114-117)."""

    surge: int = 1


@dataclasses.dataclass
class ModelServerPods:
    """Cluster-wide pod settings (reference: internal/config/system.go:243-260)."""

    service_account_name: str = ""
    security_context: dict | None = None
    container_security_context: dict | None = None
    image_pull_secrets: list[str] = dataclasses.field(default_factory=list)
    json_patches: list[dict] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class MessageStream:
    """(reference: internal/config/system.go:214-220)"""

    request_subscription: str = ""
    response_topic: str = ""
    max_handlers: int = 1000


@dataclasses.dataclass
class Messaging:
    error_max_backoff_seconds: float = 30.0
    streams: list[MessageStream] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class LeaderElectionConfig:
    lease_duration_seconds: float = 15.0
    renew_deadline_seconds: float = 10.0
    retry_period_seconds: float = 2.0


@dataclasses.dataclass
class Resilience:
    """Fault-handling defaults for the serving path (no reference analog
    — the reference's proxy retries blind with a hardcoded 300s timeout,
    internal/modelproxy/handler.go). Per-model overrides live on the
    Model CRD (`loadBalancing.circuitBreaker`, `drainTimeoutSeconds`)."""

    # Proxy attempt timeouts: TCP connect, then first response header.
    connect_timeout_seconds: float = 2.0
    response_header_timeout_seconds: float = 300.0
    # Circuit-breaker defaults (kubeai_tpu/routing/health.BreakerPolicy).
    breaker_window: int = 20
    breaker_consecutive_failures: int = 3
    breaker_failure_rate: float = 0.5
    breaker_min_samples: int = 5
    breaker_open_seconds: float = 10.0
    # Engine graceful-drain budget (SIGTERM → in-flight completion).
    drain_timeout_seconds: float = 30.0
    # Engine step watchdog: with work active and no step progress for
    # this long, the engine flips /health and exits nonzero so kubelet
    # restarts the pod. Must stay well under the time the circuit
    # breaker would need to notice a wedged-but-accepting engine
    # (breaker_consecutive_failures × response_header_timeout).
    watchdog_timeout_seconds: float = 120.0
    # Self-healing pod reconciliation: a Pending pod unscheduled past
    # this deadline is delete-and-replaced (fresh scheduling dice after
    # a spot-node reclaim) ...
    pod_pending_deadline_seconds: float = 300.0
    # ... a container at/over this restart count counts as crash-looping
    # even before kubelet labels it CrashLoopBackOff ...
    pod_restart_threshold: int = 3
    # ... and repeated repairs of one model back off exponentially
    # (base × 2^n, capped) so a poisoned spec can't thrash pods.
    repair_backoff_base_seconds: float = 5.0
    repair_backoff_max_seconds: float = 300.0
    # Kube API client retries (RestKubeClient): transient 5xx/429 and
    # connection errors retry with capped exponential backoff + jitter
    # (Retry-After honored when the server sends one).
    kubeclient_max_attempts: int = 5
    kubeclient_backoff_base_seconds: float = 0.2
    kubeclient_backoff_max_seconds: float = 5.0


DEFAULT_MODEL_SERVERS: dict[str, dict[str, str]] = {
    # engine -> imageName -> image (reference: charts/kubeai/values.yaml:40-60).
    # The TPU engine serves from this repo's image; CPU variant for e2e tests.
    "KubeAITPU": {
        "default": "kubeai-tpu/engine:latest",
        "google-tpu": "kubeai-tpu/engine:latest-tpu",
        "cpu": "kubeai-tpu/engine:latest-cpu",
    },
    # Hardware-specific vLLM builds (reference: charts/kubeai/
    # values.yaml:45-54): the CUDA default cannot serve CPU-only, arm64
    # GH200, or ROCm nodes — profiles name the build they need and
    # engines without that key fall back to their default.
    "VLLM": {
        "default": "vllm/vllm-openai:v0.8.3",
        "nvidia-gpu": "vllm/vllm-openai:v0.8.3",
        "cpu": "substratusai/vllm:v0.6.3.post1-cpu",
        "google-tpu": "substratusai/vllm:v0.6.4.post1-tpu",
        "gh200": "substratusai/vllm-gh200:v0.8.3",
        "amd-gpu": "substratusai/vllm-rocm:nightly_main_20250120",
    },
    "OLlama": {"default": "ollama/ollama:latest"},
    "FasterWhisper": {
        "default": "fedirz/faster-whisper-server:latest-cpu"
    },
    "Infinity": {
        "default": "michaelf34/infinity:latest"
    },
}


@dataclasses.dataclass
class System:
    """The full system config (reference: internal/config/system.go:13-47)."""

    secret_names: dict[str, str] = dataclasses.field(
        default_factory=lambda: {"huggingface": "kubeai-huggingface"}
    )
    model_servers: dict[str, dict[str, str]] = dataclasses.field(
        default_factory=lambda: {
            k: dict(v) for k, v in DEFAULT_MODEL_SERVERS.items()
        }
    )
    model_loading_image: str = "kubeai-tpu/model-loader:latest"
    resource_profiles: dict[str, ResourceProfile] = dataclasses.field(
        default_factory=dict
    )
    cache_profiles: dict[str, CacheProfile] = dataclasses.field(
        default_factory=dict
    )
    model_autoscaling: ModelAutoscaling = dataclasses.field(
        default_factory=ModelAutoscaling
    )
    capacity_planning: CapacityPlanning = dataclasses.field(
        default_factory=CapacityPlanning
    )
    governor: GovernorConfig = dataclasses.field(
        default_factory=GovernorConfig
    )
    tenancy: TenancyConfig = dataclasses.field(
        default_factory=TenancyConfig
    )
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)
    cluster: ClusterConfig = dataclasses.field(
        default_factory=ClusterConfig
    )
    federation: FederationConfig = dataclasses.field(
        default_factory=FederationConfig
    )
    model_rollouts: ModelRollouts = dataclasses.field(
        default_factory=ModelRollouts
    )
    model_server_pods: ModelServerPods = dataclasses.field(
        default_factory=ModelServerPods
    )
    messaging: Messaging = dataclasses.field(default_factory=Messaging)
    leader_election: LeaderElectionConfig = dataclasses.field(
        default_factory=LeaderElectionConfig
    )
    resilience: Resilience = dataclasses.field(default_factory=Resilience)
    metrics_addr: str = ":8080"
    api_addr: str = ":8000"
    allow_pod_address_override: bool = False  # test hook (reference: main_test.go:258)
    fixed_self_metric_addrs: list[str] = dataclasses.field(default_factory=list)

    def default_and_validate(self) -> "System":
        """Apply defaults and validate (reference: internal/config/system.go:49-85)."""
        if not self.resource_profiles:
            self.resource_profiles = default_resource_profiles()
        if "cpu" not in self.resource_profiles:
            self.resource_profiles["cpu"] = default_resource_profiles()["cpu"]
        if self.model_autoscaling.interval_seconds <= 0:
            raise ConfigError("modelAutoscaling.interval must be > 0")
        if self.model_autoscaling.time_window_seconds < self.model_autoscaling.interval_seconds:
            raise ConfigError("modelAutoscaling.timeWindow must be >= interval")
        if self.model_autoscaling.queue_pressure_max_wait_seconds < 0:
            raise ConfigError("modelAutoscaling.queuePressureMaxWait must be >= 0")
        if self.capacity_planning.interval_seconds < 0:
            raise ConfigError("capacityPlanning.interval must be >= 0")
        g = self.governor
        if g.window_seconds <= 0:
            raise ConfigError("governor.window must be > 0")
        if g.model_disruption_budget < 0:
            raise ConfigError("governor.modelDisruptionBudget must be >= 0")
        if g.cluster_disruption_budget < 0:
            raise ConfigError(
                "governor.clusterDisruptionBudget must be >= 0"
            )
        if not 0.0 <= g.min_telemetry_coverage <= 1.0:
            raise ConfigError(
                "governor.minTelemetryCoverage must be in [0, 1]"
            )
        t = self.tenancy
        for field, value in (
            ("requestsPerSecond", t.requests_per_second),
            ("requestBurst", t.request_burst),
            ("tokensPerSecond", t.tokens_per_second),
            ("tokenBurst", t.token_burst),
            ("window", t.window_seconds),
            ("windowTokenBudget", t.window_token_budget),
            ("overloadHighWater", t.overload_high_water),
            ("overloadLowWater", t.overload_low_water),
        ):
            if value < 0:
                raise ConfigError(f"tenancy.{field} must be >= 0")
        if t.window_token_budget > 0 and t.window_seconds <= 0:
            raise ConfigError(
                "tenancy.windowTokenBudget needs tenancy.window > 0"
            )
        if (
            t.overload_low_water > 0
            and t.overload_high_water > 0
            and t.overload_low_water > t.overload_high_water
        ):
            raise ConfigError(
                "tenancy.overloadLowWater must be <= overloadHighWater"
            )
        if t.overload_standard_factor < 1.0:
            raise ConfigError("tenancy.overloadStandardFactor must be >= 1")
        if t.min_retry_after_seconds <= 0:
            raise ConfigError("tenancy.minRetryAfter must be > 0")
        if t.max_retry_after_seconds < t.min_retry_after_seconds:
            raise ConfigError(
                "tenancy.maxRetryAfter must be >= minRetryAfter"
            )
        if t.max_tenant_series < 1:
            raise ConfigError("tenancy.maxTenantSeries must be >= 1")
        if t.tenant_idle_seconds <= 0:
            raise ConfigError("tenancy.tenantIdle must be > 0")
        if t.door_shards < 1:
            raise ConfigError("tenancy.doorShards must be >= 1")
        if t.gossip_interval_seconds <= 0:
            raise ConfigError("tenancy.gossipInterval must be > 0")
        if t.gossip_stale_seconds < t.gossip_interval_seconds:
            raise ConfigError(
                "tenancy.gossipStaleAfter must be >= gossipInterval"
            )
        s = self.slo
        if s.interval_seconds < 0:
            raise ConfigError("slo.interval must be >= 0")
        if s.ttft_p95_seconds < 0:
            raise ConfigError("slo.ttftP95 must be >= 0")
        if s.itl_p99_seconds < 0:
            raise ConfigError("slo.itlP99 must be >= 0")
        if not 0.0 <= s.availability < 1.0:
            raise ConfigError("slo.availability must be in [0, 1)")
        if not 0.0 <= s.max_shed_rate < 1.0:
            raise ConfigError("slo.maxShedRate must be in [0, 1)")
        if s.budget_window_seconds <= 0:
            raise ConfigError("slo.budgetWindow must be > 0")
        if s.fast_burn_threshold <= 0 or s.slow_burn_threshold <= 0:
            raise ConfigError("slo burn thresholds must be > 0")
        if s.fast_burn_short_window_seconds <= 0:
            raise ConfigError("slo.fastBurnShortWindow must be > 0")
        if s.fast_burn_window_seconds < s.fast_burn_short_window_seconds:
            raise ConfigError(
                "slo.fastBurnWindow must be >= fastBurnShortWindow"
            )
        if s.slow_burn_window_seconds < s.fast_burn_window_seconds:
            raise ConfigError(
                "slo.slowBurnWindow must be >= fastBurnWindow"
            )
        if s.budget_window_seconds < s.slow_burn_window_seconds:
            raise ConfigError(
                "slo.budgetWindow must be >= slowBurnWindow"
            )
        if s.min_incident_interval_seconds < 0:
            raise ConfigError("slo.minIncidentInterval must be >= 0")
        c = self.cluster
        if not is_dns_label(c.name):
            raise ConfigError(
                "cluster.name must be a DNS label (lowercase "
                "alphanumerics and '-', <= 63 chars)"
            )
        if len(c.region) > 63:
            raise ConfigError("cluster.region must be <= 63 chars")
        seen_peers: set[str] = set()
        for p in c.peers:
            if not is_dns_label(p.name):
                raise ConfigError(
                    f"cluster.peers[].name {p.name!r} must be a DNS label"
                )
            if p.name == c.name:
                raise ConfigError(
                    f"cluster.peers[].name {p.name!r} shadows cluster.name"
                )
            if p.name in seen_peers:
                raise ConfigError(
                    f"cluster.peers[].name {p.name!r} is duplicated"
                )
            seen_peers.add(p.name)
            if not p.door_url:
                raise ConfigError(
                    f"cluster.peers[{p.name}].doorUrl is required"
                )
            if p.rtt_seconds < 0:
                raise ConfigError(
                    f"cluster.peers[{p.name}].rtt must be >= 0"
                )
        f = self.federation
        if f.interval_seconds < 0:
            raise ConfigError("federation.interval must be >= 0")
        if f.staleness_seconds < 0:
            raise ConfigError("federation.stalenessAfter must be >= 0")
        if f.failover_window_seconds <= 0:
            raise ConfigError("federation.failoverWindow must be > 0")
        if f.queue_wait_per_request_seconds < 0:
            raise ConfigError(
                "federation.queueWaitPerRequest must be >= 0"
            )
        if self.model_rollouts.surge < 0:
            raise ConfigError("modelRollouts.surge must be >= 0")
        r = self.resilience
        if r.connect_timeout_seconds <= 0:
            raise ConfigError("resilience.connectTimeout must be > 0")
        if r.response_header_timeout_seconds <= 0:
            raise ConfigError("resilience.responseHeaderTimeout must be > 0")
        if r.breaker_window < 1:
            raise ConfigError("resilience.breakerWindow must be >= 1")
        if r.breaker_consecutive_failures < 0:
            raise ConfigError(
                "resilience.breakerConsecutiveFailures must be >= 0"
            )
        if not 0.0 < r.breaker_failure_rate:
            raise ConfigError("resilience.breakerFailureRate must be > 0")
        if r.breaker_min_samples < 1:
            raise ConfigError("resilience.breakerMinSamples must be >= 1")
        if r.breaker_open_seconds <= 0:
            raise ConfigError("resilience.breakerOpenSeconds must be > 0")
        if r.drain_timeout_seconds <= 0:
            raise ConfigError("resilience.drainTimeout must be > 0")
        if r.watchdog_timeout_seconds < 0:
            raise ConfigError("resilience.watchdogTimeout must be >= 0")
        if r.pod_pending_deadline_seconds < 0:
            raise ConfigError(
                "resilience.podPendingDeadline must be >= 0"
            )
        if r.pod_restart_threshold < 0:
            raise ConfigError(
                "resilience.podRestartThreshold must be >= 0"
            )
        if r.repair_backoff_base_seconds <= 0:
            raise ConfigError("resilience.repairBackoffBase must be > 0")
        if r.repair_backoff_max_seconds < r.repair_backoff_base_seconds:
            raise ConfigError(
                "resilience.repairBackoffMax must be >= repairBackoffBase"
            )
        if r.kubeclient_max_attempts < 1:
            raise ConfigError("resilience.kubeclientMaxAttempts must be >= 1")
        if r.kubeclient_backoff_base_seconds <= 0:
            raise ConfigError("resilience.kubeclientBackoffBase must be > 0")
        if r.kubeclient_backoff_max_seconds < r.kubeclient_backoff_base_seconds:
            raise ConfigError(
                "resilience.kubeclientBackoffMax must be >= "
                "kubeclientBackoffBase"
            )
        for name, prof in self.resource_profiles.items():
            if not isinstance(prof, ResourceProfile):
                raise ConfigError(f"resourceProfiles[{name}] invalid")
        for eng, images in self.model_servers.items():
            if "default" not in images:
                raise ConfigError(f"modelServers[{eng}] needs a 'default' image")
        for stream in self.messaging.streams:
            if not stream.request_subscription or not stream.response_topic:
                raise ConfigError(
                    "messaging.streams entries need requestSubscription and responseTopic"
                )
            from kubeai_tpu.routing.brokers import SUPPORTED_SCHEMES, scheme_of

            req_s = scheme_of(stream.request_subscription)
            resp_s = scheme_of(stream.response_topic)
            if req_s != resp_s:
                raise ConfigError(
                    f"messaging stream mixes schemes: {req_s} vs {resp_s}"
                )
            if req_s not in SUPPORTED_SCHEMES:
                raise ConfigError(
                    f"unsupported messaging scheme {req_s!r} "
                    f"(supported: {', '.join(SUPPORTED_SCHEMES)})"
                )
        return self


def default_resource_profiles() -> dict[str, ResourceProfile]:
    """TPU-first resource profiles (reference: charts/kubeai/values-gke.yaml:18-41
    for the GKE TPU profiles; charts/kubeai/values.yaml for cpu/gpu)."""
    profiles = {
        "cpu": ResourceProfile(
            image_name="cpu",
            requests={"cpu": "1", "memory": "2Gi"},
            limits={},
        ),
        "nvidia-gpu-l4": ResourceProfile(
            image_name="default",
            requests={"nvidia.com/gpu": "1"},
            limits={"nvidia.com/gpu": "1"},
            node_selector={"cloud.google.com/gke-accelerator": "nvidia-l4"},
        ),
    }
    # The reference catalog's other GPU tiers (reference:
    # charts/models/values.yaml resourceProfile usage) — same one-
    # accelerator-per-unit semantics as nvidia-gpu-l4.
    for name, image, selector in (
        (
            "nvidia-gpu-h100", "nvidia-gpu",
            {"cloud.google.com/gke-accelerator": "nvidia-h100-80gb"},
        ),
        (
            "nvidia-gpu-a100-80gb", "nvidia-gpu",
            {"cloud.google.com/gke-accelerator": "nvidia-a100-80gb"},
        ),
        # GH200 is arm64 (Grace): needs the aarch64 CUDA build, and the
        # arch selector keeps it OFF x86 Hopper (H100 shares the
        # gpu.family=hopper feature label).
        (
            "nvidia-gpu-gh200", "gh200",
            {
                "nvidia.com/gpu.family": "hopper",
                "kubernetes.io/arch": "arm64",
            },
        ),
        ("nvidia-gpu-rtx4070-8gb", "nvidia-gpu", {}),
    ):
        profiles[name] = ResourceProfile(
            image_name=image,
            requests={"nvidia.com/gpu": "1"},
            limits={"nvidia.com/gpu": "1"},
            node_selector=selector,
        )
    profiles["amd-gpu-mi300x"] = ResourceProfile(
        image_name="amd-gpu",  # ROCm build
        requests={"amd.com/gpu": "1"},
        limits={"amd.com/gpu": "1"},
    )
    # One chip per profile unit: `resourceProfile: google-tpu-v5e-2x2:4`
    # multiplies to the slice's 4 chips (reference semantics,
    # charts/kubeai/values-gke.yaml:18-41 + charts/models/values.yaml:128).
    for topo in ("1x1", "2x2", "2x4"):
        profiles[f"google-tpu-v5e-{topo}"] = ResourceProfile(
            image_name="google-tpu",
            requests={"google.com/tpu": "1"},
            limits={"google.com/tpu": "1"},
            node_selector={
                TPU_ACCELERATOR_SELECTOR: "tpu-v5-lite-podslice",
                TPU_TOPOLOGY_SELECTOR: topo,
            },
        )
    # Multi-host slices: >8 v5e chips span hosts (8 chips/host). The
    # profile is PER HOST — `google-tpu-v5e-4x4:8` gives each of the two
    # host Pods 8 chips; the operator renders num_hosts Pods per replica.
    for topo, hosts in (("4x4", 2), ("4x8", 4)):
        profiles[f"google-tpu-v5e-{topo}"] = ResourceProfile(
            image_name="google-tpu",
            requests={"google.com/tpu": "1"},
            limits={"google.com/tpu": "1"},
            node_selector={
                TPU_ACCELERATOR_SELECTOR: "tpu-v5-lite-podslice",
                TPU_TOPOLOGY_SELECTOR: topo,
            },
            num_hosts=hosts,
        )
    return profiles


# ---- file loading -----------------------------------------------------------


def load_config_file(path: str) -> System:
    """Load the system config file (reference: internal/manager/configure.go:10-21).

    Accepts JSON or a simple YAML subset (maps, lists, scalars)."""
    with open(path) as f:
        text = f.read()
    data = _parse_config_text(text)
    return system_from_dict(data).default_and_validate()


def _parse_config_text(text: str) -> dict:
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    try:
        import yaml  # type: ignore

        return yaml.safe_load(text) or {}
    except ImportError:
        return _mini_yaml(text)


def _mini_yaml(text: str) -> dict:
    """Minimal YAML subset: nested maps, `- ` lists, scalar values."""

    lines = [
        l for l in text.splitlines()
        if l.strip() and not l.strip().startswith("#")
    ]

    def parse_block(idx: int, indent: int):
        result: Any = None
        while idx < len(lines):
            line = lines[idx]
            cur = len(line) - len(line.lstrip())
            if cur < indent:
                break
            stripped = line.strip()
            if stripped.startswith("- "):
                if result is None:
                    result = []
                item_text = stripped[2:]
                if ":" in item_text and not item_text.split(":", 1)[1].strip():
                    sub, idx = parse_block(idx + 1, cur + 2)
                    result.append({item_text.split(":")[0]: sub})
                elif ":" in item_text:
                    # inline map start on the list item line
                    k, v = item_text.split(":", 1)
                    item = {k.strip(): _scalar(v.strip())}
                    nxt, idx = parse_block(idx + 1, cur + 2)
                    if isinstance(nxt, dict):
                        item.update(nxt)
                    result.append(item)
                else:
                    result.append(_scalar(item_text))
                    idx += 1
            else:
                if result is None:
                    result = {}
                key, _, val = stripped.partition(":")
                val = val.strip()
                if val:
                    result[key.strip()] = _scalar(val)
                    idx += 1
                else:
                    sub, idx = parse_block(idx + 1, cur + 1)
                    result[key.strip()] = sub if sub is not None else {}
        return result, idx

    out, _ = parse_block(0, 0)
    return out or {}


def _scalar(s: str):
    if s in ("true", "True"):
        return True
    if s in ("false", "False"):
        return False
    if s in ("null", "~", ""):
        return None
    if s.startswith('"') and s.endswith('"') or s.startswith("'") and s.endswith("'"):
        return s[1:-1]
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


def _snake(d: dict) -> dict:
    def conv(k: str) -> str:
        out = []
        for ch in k:
            if ch.isupper():
                out.append("_")
                out.append(ch.lower())
            else:
                out.append(ch)
        return "".join(out)

    return {conv(k): v for k, v in d.items()}


def system_from_dict(data: dict) -> System:
    """Build a System from a camelCase config dict (file format parity with
    the reference's YAML keys, e.g. `resourceProfiles`, `modelServers`)."""
    data = data or {}
    sys_obj = System()
    if "secretNames" in data:
        sys_obj.secret_names = dict(data["secretNames"])
    if "modelServers" in data:
        ms = {}
        for eng, spec in data["modelServers"].items():
            images = spec.get("images", spec) if isinstance(spec, dict) else {}
            ms[eng] = dict(images)
        sys_obj.model_servers = ms
    if "modelLoading" in data:
        sys_obj.model_loading_image = data["modelLoading"].get(
            "image", sys_obj.model_loading_image
        )
    if "resourceProfiles" in data:
        sys_obj.resource_profiles = {
            name: ResourceProfile(
                image_name=p.get("imageName", ""),
                requests={k: str(v) for k, v in (p.get("requests") or {}).items()},
                limits={k: str(v) for k, v in (p.get("limits") or {}).items()},
                node_selector=dict(p.get("nodeSelector") or {}),
                affinity=p.get("affinity"),
                tolerations=list(p.get("tolerations") or []),
                scheduler_name=p.get("schedulerName", ""),
                runtime_class_name=p.get("runtimeClassName", ""),
                num_hosts=int(p.get("numHosts", 1)),
            )
            for name, p in data["resourceProfiles"].items()
        }
    if "cacheProfiles" in data:
        sys_obj.cache_profiles = {
            name: CacheProfile(shared_filesystem=p.get("sharedFilesystem"))
            for name, p in data["cacheProfiles"].items()
        }
    if "modelAutoscaling" in data:
        a = data["modelAutoscaling"]
        sys_obj.model_autoscaling = ModelAutoscaling(
            interval_seconds=_seconds(a.get("interval", 10)),
            time_window_seconds=_seconds(a.get("timeWindow", 600)),
            state_configmap_name=a.get(
                "stateConfigMapName", "kubeai-autoscaler-state"
            ),
            queue_pressure_max_wait_seconds=_seconds(
                a.get("queuePressureMaxWait", 3)
            ),
        )
    if "capacityPlanning" in data:
        cp = data["capacityPlanning"]
        sys_obj.capacity_planning = CapacityPlanning(
            enabled=bool(cp.get("enabled", True)),
            interval_seconds=_seconds(cp.get("interval", 0)),
            preemption=bool(cp.get("preemption", True)),
        )
    if "governor" in data:
        g = data["governor"]
        sys_obj.governor = GovernorConfig(
            enabled=bool(g.get("enabled", True)),
            window_seconds=_seconds(g.get("window", 60)),
            model_disruption_budget=int(g.get("modelDisruptionBudget", 10)),
            cluster_disruption_budget=int(
                g.get("clusterDisruptionBudget", 50)
            ),
            min_telemetry_coverage=float(
                g.get("minTelemetryCoverage", 0.0)
            ),
        )
    if "tenancy" in data:
        t = data["tenancy"]
        sys_obj.tenancy = TenancyConfig(
            enabled=bool(t.get("enabled", False)),
            requests_per_second=float(t.get("requestsPerSecond", 0.0)),
            request_burst=float(t.get("requestBurst", 0.0)),
            tokens_per_second=float(t.get("tokensPerSecond", 0.0)),
            token_burst=float(t.get("tokenBurst", 0.0)),
            window_seconds=_seconds(t.get("window", 0)),
            window_token_budget=int(t.get("windowTokenBudget", 0)),
            overload_high_water=float(t.get("overloadHighWater", 0.0)),
            overload_low_water=float(t.get("overloadLowWater", 0.0)),
            overload_standard_factor=float(
                t.get("overloadStandardFactor", 2.0)
            ),
            min_retry_after_seconds=_seconds(t.get("minRetryAfter", 0.25)),
            max_retry_after_seconds=_seconds(t.get("maxRetryAfter", 300)),
            max_tenant_series=int(t.get("maxTenantSeries", 512)),
            tenant_idle_seconds=_seconds(t.get("tenantIdle", 600)),
            door_shards=int(t.get("doorShards", 1)),
            gossip_interval_seconds=_seconds(t.get("gossipInterval", 1)),
            gossip_stale_seconds=_seconds(t.get("gossipStaleAfter", 5)),
        )
    if "slo" in data:
        s = data["slo"]
        sys_obj.slo = SLOConfig(
            enabled=bool(s.get("enabled", False)),
            interval_seconds=_seconds(s.get("interval", 0)),
            ttft_p95_seconds=_seconds(s.get("ttftP95", 0)),
            itl_p99_seconds=_seconds(s.get("itlP99", 0)),
            availability=float(s.get("availability", 0.0)),
            max_shed_rate=float(s.get("maxShedRate", 0.0)),
            budget_window_seconds=_seconds(s.get("budgetWindow", 3600)),
            fast_burn_threshold=float(s.get("fastBurnThreshold", 14.4)),
            fast_burn_window_seconds=_seconds(s.get("fastBurnWindow", 300)),
            fast_burn_short_window_seconds=_seconds(
                s.get("fastBurnShortWindow", 60)
            ),
            slow_burn_threshold=float(s.get("slowBurnThreshold", 3.0)),
            slow_burn_window_seconds=_seconds(s.get("slowBurnWindow", 1800)),
            incident_dir=str(s.get("incidentDir", "")),
            min_incident_interval_seconds=_seconds(
                s.get("minIncidentInterval", 300)
            ),
        )
    if "cluster" in data:
        c = data["cluster"]
        sys_obj.cluster = ClusterConfig(
            name=str(c.get("name", "local")),
            region=str(c.get("region", "")),
            peers=[
                PeerClusterConfig(
                    name=str(p.get("name", "")),
                    door_url=str(p.get("doorUrl", "")),
                    spill_url=str(p.get("spillUrl", "")),
                    rtt_seconds=_seconds(p.get("rtt", 0.05)),
                )
                for p in (c.get("peers") or [])
            ],
        )
    if "federation" in data:
        f = data["federation"]
        sys_obj.federation = FederationConfig(
            enabled=bool(f.get("enabled", False)),
            interval_seconds=_seconds(f.get("interval", 0)),
            staleness_seconds=_seconds(f.get("stalenessAfter", 0)),
            failover_window_seconds=_seconds(f.get("failoverWindow", 30)),
            queue_wait_per_request_seconds=_seconds(
                f.get("queueWaitPerRequest", 0.1)
            ),
        )
    if "modelRollouts" in data:
        sys_obj.model_rollouts = ModelRollouts(
            surge=int(data["modelRollouts"].get("surge", 1))
        )
    if "modelServerPods" in data:
        p = data["modelServerPods"]
        sys_obj.model_server_pods = ModelServerPods(
            service_account_name=p.get("serviceAccountName", ""),
            security_context=p.get("podSecurityContext"),
            container_security_context=p.get("securityContext"),
            image_pull_secrets=[
                s["name"] if isinstance(s, dict) else s
                for s in (p.get("imagePullSecrets") or [])
            ],
            json_patches=list(p.get("jsonPatches") or []),
        )
    if "messaging" in data:
        m = data["messaging"]
        sys_obj.messaging = Messaging(
            error_max_backoff_seconds=_seconds(m.get("errorMaxBackoff", 30)),
            streams=[
                MessageStream(
                    request_subscription=s.get("requestSubscription", ""),
                    response_topic=s.get("responseTopic", ""),
                    max_handlers=int(s.get("maxHandlers", 1000)),
                )
                for s in (m.get("streams") or [])
            ],
        )
    if "leaderElection" in data:
        le = data["leaderElection"]
        sys_obj.leader_election = LeaderElectionConfig(
            lease_duration_seconds=_seconds(le.get("leaseDuration", 15)),
            renew_deadline_seconds=_seconds(le.get("renewDeadline", 10)),
            retry_period_seconds=_seconds(le.get("retryPeriod", 2)),
        )
    if "resilience" in data:
        r = data["resilience"]
        sys_obj.resilience = Resilience(
            connect_timeout_seconds=_seconds(r.get("connectTimeout", 2)),
            response_header_timeout_seconds=_seconds(
                r.get("responseHeaderTimeout", 300)
            ),
            breaker_window=int(r.get("breakerWindow", 20)),
            breaker_consecutive_failures=int(
                r.get("breakerConsecutiveFailures", 3)
            ),
            breaker_failure_rate=float(r.get("breakerFailureRate", 0.5)),
            breaker_min_samples=int(r.get("breakerMinSamples", 5)),
            breaker_open_seconds=_seconds(r.get("breakerOpenSeconds", 10)),
            drain_timeout_seconds=_seconds(r.get("drainTimeout", 30)),
            watchdog_timeout_seconds=_seconds(r.get("watchdogTimeout", 120)),
            pod_pending_deadline_seconds=_seconds(
                r.get("podPendingDeadline", 300)
            ),
            pod_restart_threshold=int(r.get("podRestartThreshold", 3)),
            repair_backoff_base_seconds=_seconds(
                r.get("repairBackoffBase", 5)
            ),
            repair_backoff_max_seconds=_seconds(
                r.get("repairBackoffMax", 300)
            ),
            kubeclient_max_attempts=int(r.get("kubeclientMaxAttempts", 5)),
            kubeclient_backoff_base_seconds=_seconds(
                r.get("kubeclientBackoffBase", 0.2)
            ),
            kubeclient_backoff_max_seconds=_seconds(
                r.get("kubeclientBackoffMax", 5)
            ),
        )
    if "metricsAddr" in data:
        sys_obj.metrics_addr = data["metricsAddr"]
    if "apiAddr" in data:
        sys_obj.api_addr = data["apiAddr"]
    if "allowPodAddressOverride" in data:
        sys_obj.allow_pod_address_override = bool(data["allowPodAddressOverride"])
    if "fixedSelfMetricAddrs" in data:
        sys_obj.fixed_self_metric_addrs = list(data["fixedSelfMetricAddrs"])
    return sys_obj


from kubeai_tpu.utils.units import parse_duration_seconds as _seconds  # noqa: E402
