"""Minimal Prometheus-compatible metrics (text exposition format 0.0.4)."""

from __future__ import annotations

import math
import re
import threading
from collections import defaultdict


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92)*2).replace(chr(34), chr(92)+chr(34))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    """Exposition value: integral floats render bare (`25`, not `25.0`);
    everything else uses repr's shortest round-trip form so large counters
    survive expose() → parse (the %g default truncates past 6 digits)."""
    if v == int(v) and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def _fmt_le(bound: float) -> str:
    """Canonical `le` label value: `%g`-style (`0.005`, `1`, `+Inf`) so
    int and float bucket bounds render identically."""
    b = float(bound)
    if b == float("inf"):
        return "+Inf"
    return f"{b:g}"


class _Metric:
    def __init__(self, name: str, help_: str, registry: "Registry | None"):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = defaultdict(float)
        self._label_keys: dict[tuple, dict] = {}
        if registry is not None:
            registry.register(self)

    def _key(self, labels: dict[str, str]) -> tuple:
        k = tuple(sorted(labels.items()))
        self._label_keys[k] = labels
        return k

    def get(self, **labels) -> float:
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def samples(self) -> list[tuple[dict, float]]:
        """Every (labels, value) series of this instrument, sorted by
        label key — the read path for consumers that aggregate across
        label sets (the SLO evaluator sums rejections over tenants and
        reasons). Histograms don't populate scalar values; use their
        get()/sum_for() instead."""
        with self._lock:
            return [
                (dict(self._label_keys[k]), v)
                for k, v in sorted(self._values.items())
            ]

    def remove(self, **labels) -> None:
        """Drop one label-set's series (endpoint churn would otherwise
        accrete stale series forever on long-lived registries)."""
        with self._lock:
            k = tuple(sorted(labels.items()))
            self._values.pop(k, None)
            self._label_keys.pop(k, None)

    def collect(self) -> list[str]:
        with self._lock:
            lines = [
                f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.TYPE}",
            ]
            if not self._values:
                lines.append(f"{self.name} 0")
            for k, v in sorted(self._values.items()):
                lines.append(
                    f"{self.name}{_fmt_labels(self._label_keys[k])} "
                    f"{_fmt_value(v)}"
                )
            return lines


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] += amount


class TracingDroppedSpans(Counter):
    """Live view of the process tracer's dropped-span count (export
    queue full, or exporter thread dead). Synced at collect time so
    every registry in the process (operator bundle, engine bundle)
    exposes the same truth without the tracer knowing about registries."""

    def collect(self) -> list[str]:
        from kubeai_tpu.metrics import tracing

        t = tracing._default
        dropped = float(t.dropped) if t is not None else 0.0
        with self._lock:
            self._values[self._key({})] = dropped
        return super().collect()


class ObjstoreRetries(Counter):
    """Live view of the object-store layer's transient-failure retry
    count (5xx/429, connection resets, short reads). Synced from
    `objstore.RETRIES` at collect time — same pattern as
    TracingDroppedSpans, so the operator bundle and the engine bundle
    both expose the process's one true count."""

    def collect(self) -> list[str]:
        from kubeai_tpu import objstore

        with self._lock:
            self._values[self._key({})] = float(objstore.RETRIES["total"])
        return super().collect()


class Gauge(_Metric):
    TYPE = "gauge"

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = value


class Histogram(_Metric):
    TYPE = "histogram"
    BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)

    def __init__(self, name, help_, registry, buckets=None):
        super().__init__(name, help_, registry)
        self.buckets = tuple(buckets or self.BUCKETS)
        self._bucket_counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._counts: dict[tuple, int] = defaultdict(int)
        # Last exemplar (trace/request id) per bucket per label set —
        # index len(buckets) is the +Inf overflow bucket. Deliberately
        # NOT emitted in the 0.0.4 text exposition (parsers here and in
        # the fleet would choke on OpenMetrics `# {...}` suffixes);
        # consumers read them via exemplars() / the admin state payloads.
        self._exemplars: dict[tuple, dict[int, str]] = {}

    def observe(self, value: float, exemplar: str | None = None,
                **labels) -> None:
        with self._lock:
            k = self._key(labels)
            if k not in self._bucket_counts:
                self._bucket_counts[k] = [0] * len(self.buckets)
            # Per-bucket (non-cumulative) counts: only the first bucket
            # that fits increments; collect() produces the cumulative
            # `le` series. Incrementing every bucket >= value here would
            # double-cumulate at collect time.
            idx = len(self.buckets)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._bucket_counts[k][i] += 1
                    idx = i
                    break
            self._sums[k] += value
            self._counts[k] += 1
            if exemplar:
                self._exemplars.setdefault(k, {})[idx] = str(exemplar)

    def exemplars(self, **labels) -> dict[str, str]:
        """Last exemplar per bucket for the label set, keyed by the
        bucket's canonical `le` string (`+Inf` for the overflow bucket)."""
        with self._lock:
            per_idx = self._exemplars.get(tuple(sorted(labels.items())), {})
            out: dict[str, str] = {}
            for idx, ex in sorted(per_idx.items()):
                bound = (
                    "+Inf" if idx >= len(self.buckets)
                    else _fmt_le(self.buckets[idx])
                )
                out[bound] = ex
            return out

    def get(self, **labels) -> float:
        """Observation COUNT for the label set (the scalar `_Metric.get`
        would silently read the unused `_values` dict and always say 0)."""
        with self._lock:
            return float(self._counts.get(tuple(sorted(labels.items())), 0))

    def remove(self, **labels) -> None:
        """Drop one label-set's series INCLUDING its bucket/sum/count
        state — the base remove only clears `_values`, which histograms
        don't use, so label churn would accrete series forever."""
        with self._lock:
            k = tuple(sorted(labels.items()))
            self._values.pop(k, None)
            self._label_keys.pop(k, None)
            self._bucket_counts.pop(k, None)
            self._sums.pop(k, None)
            self._counts.pop(k, None)
            self._exemplars.pop(k, None)

    def sum_for(self, **labels) -> float:
        """Sum of observed values for the label set."""
        with self._lock:
            return float(self._sums.get(tuple(sorted(labels.items())), 0.0))

    def collect(self) -> list[str]:
        with self._lock:
            lines = [
                f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} histogram",
            ]
            for k in sorted(self._counts):
                labels = self._label_keys[k]
                cum = 0
                for i, b in enumerate(self.buckets):
                    cum += self._bucket_counts[k][i]
                    lines.append(
                        f"{self.name}_bucket"
                        f"{_fmt_labels({**labels, 'le': _fmt_le(b)})} {cum}"
                    )
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**labels, 'le': '+Inf'})} {self._counts[k]}"
                )
                lines.append(
                    f"{self.name}_sum{_fmt_labels(labels)} "
                    f"{_fmt_value(self._sums[k])}"
                )
                lines.append(
                    f"{self.name}_count{_fmt_labels(labels)} {self._counts[k]}"
                )
            return lines


class Registry:
    def __init__(self):
        self._metrics: list[_Metric] = []
        self._lock = threading.Lock()

    def register(self, m: _Metric) -> None:
        with self._lock:
            self._metrics.append(m)

    @property
    def metrics(self) -> list[_Metric]:
        with self._lock:
            return list(self._metrics)

    def expose(self) -> str:
        out: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            out.extend(m.collect())
        return "\n".join(out) + "\n"


_METRIC_NAME_RE = re.compile(r"^kubeai_[a-z0-9_]+$")


def lint_registry(registry: Registry) -> list[str]:
    """Metric-name hygiene for one registry: names match
    `^kubeai_[a-z0-9_]+$` and are unique, counters end in `_total`,
    histograms in `_seconds`. Returns human-readable violations (empty =
    clean); a unit test walks every instrument bundle through this so new
    instruments can't silently drift from the naming scheme."""
    errors: list[str] = []
    seen: set[str] = set()
    for m in registry.metrics:
        if not _METRIC_NAME_RE.match(m.name):
            errors.append(
                f"{m.name}: does not match ^kubeai_[a-z0-9_]+$"
            )
        if m.name in seen:
            errors.append(f"{m.name}: duplicate metric name in registry")
        seen.add(m.name)
        if isinstance(m, Histogram):
            if not m.name.endswith("_seconds"):
                errors.append(f"{m.name}: histogram must end in _seconds")
        elif isinstance(m, Counter):
            if not m.name.endswith("_total"):
                errors.append(f"{m.name}: counter must end in _total")
    return errors


# -- shared bucket-quantile estimator ---------------------------------------
# One estimator for every consumer of cumulative histogram buckets: the
# fleet aggregator's per-endpoint TTFT/ITL quantiles and the SLO
# evaluator's burn-rate math both read scraped `le` series, and they must
# agree on what "p95" means or an SLO breach and the signal that scaled
# for it would disagree about the same data.


def hist_buckets(
    parsed: dict, name: str
) -> tuple[list[tuple[float, float]], float, float]:
    """Extract one histogram's cumulative buckets from a parsed scrape:
    (sorted [(upper_bound, cumulative_count)], total_count, total_sum).
    Labels beyond `le` are ignored (one endpoint exposes one series per
    histogram); unparseable `le` values are skipped."""
    buckets: list[tuple[float, float]] = []
    total = 0.0
    total_sum = 0.0
    for (metric, labels), value in parsed.items():
        if metric == f"{name}_bucket":
            le = dict(labels).get("le", "")
            try:
                bound = float(le)
            except ValueError:
                continue
            buckets.append((bound, value))
        elif metric == f"{name}_count":
            total = value
        elif metric == f"{name}_sum":
            total_sum = value
    buckets.sort(key=lambda b: b[0])
    return buckets, total, total_sum


def quantiles_from_buckets(
    buckets: list[tuple[float, float]],
    total: float,
    total_sum: float,
    qs: tuple[float, ...] = (0.5, 0.95, 0.99),
) -> dict:
    """Approximate quantiles from cumulative histogram buckets (each
    quantile reports its bucket's upper bound — the standard
    Prometheus-side estimate). `buckets` must be sorted ascending by
    bound. Returns {} when the histogram has no observations or no
    buckets; a quantile landing in the +Inf bucket reports the largest
    finite bound (a meaningless +Inf estimate helps nobody), or +Inf
    when the histogram is a single +Inf bucket."""
    if total <= 0 or not buckets:
        return {}
    out = {
        "count": total,
        "mean_s": round(total_sum / total, 9),
    }
    for q in qs:
        target = q * total
        est = buckets[-1][0]
        for bound, cum in buckets:
            if cum >= target:
                est = bound
                break
        if math.isinf(est):
            finite = [b for b, _ in buckets if not math.isinf(b)]
            est = finite[-1] if finite else float("inf")
        out[f"p{int(q * 100)}_s"] = est
    return out


def count_over_threshold(
    buckets: list[tuple[float, float]], total: float, threshold: float
) -> float:
    """Observations strictly above `threshold`, from cumulative buckets.
    Conservative toward the service: observations in the bucket that
    CONTAINS the threshold count as good (they may be below it), so the
    bound used is the smallest bucket bound >= threshold. A threshold
    past every finite bound yields 0 — the buckets cannot distinguish
    violations up there, and guessing badness would page on rounding."""
    if total <= 0 or not buckets:
        return 0.0
    for bound, cum in buckets:
        if bound >= threshold:
            return max(0.0, total - cum)
    return 0.0


# Request-latency buckets: sub-ms (cache hits, tiny models) through the
# proxy's 600s request budget — an LLM completion legitimately runs minutes.
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)


class Metrics:
    """One operator replica's instrument bundle. Each Manager owns its own
    Metrics so multiple replicas embedded in one process (virtual HA,
    integration tests) don't share counters — sharing would double-count the
    autoscaling signal when the leader scrapes every replica."""

    def __init__(self):
        self.registry = Registry()
        # The autoscaling signal (reference: internal/metrics/metrics.go:16-20;
        # Prom name mapping metrics.go:81-87).
        self.inference_requests_active = Gauge(
            "kubeai_inference_requests_active",
            "Number of in-flight inference requests per model.",
            self.registry,
        )
        self.inference_requests_total = Counter(
            "kubeai_inference_requests_total",
            "Total inference requests per model.",
            self.registry,
        )
        self.chwbl_lookups = Counter(
            "kubeai_chwbl_lookups_total",
            "CHWBL address lookups.",
            self.registry,
        )
        self.chwbl_displacements = Counter(
            "kubeai_chwbl_displacements_total",
            "CHWBL lookups displaced past the hashed endpoint by the bounded-load rule.",
            self.registry,
        )
        # -- cluster KV-sharing: longest-held-prefix routing ----------------
        # Route-time PREDICTION counters; compare against the engine's
        # kubeai_engine_prefix_cached_tokens_total (actual admission hits)
        # to measure how honest the fleet holdings map is.
        self.lb_prefix_route_hits = Counter(
            "kubeai_lb_prefix_route_hits_total",
            "Picks routed to an endpoint advertising at least one held "
            "page of the request's chain (predicted prefix hit), per "
            "model.",
            self.registry,
        )
        self.lb_prefix_route_misses = Counter(
            "kubeai_lb_prefix_route_misses_total",
            "Chain-carrying picks that fell back to classic CHWBL "
            "(stale/empty holdings map or no load-bounded holder), per "
            "model.",
            self.registry,
        )
        # -- front-door request lifecycle (per model) ----------------------
        self.request_duration = Histogram(
            "kubeai_inference_request_duration_seconds",
            "End-to-end front-door request duration per model (receipt to "
            "last body byte).",
            self.registry,
            buckets=LATENCY_BUCKETS_S,
        )
        self.request_ttft = Histogram(
            "kubeai_inference_ttft_seconds",
            "Time from front-door receipt to the first response body chunk "
            "per model (streaming time-to-first-token).",
            self.registry,
            buckets=LATENCY_BUCKETS_S,
        )
        self.proxy_attempts = Counter(
            "kubeai_proxy_attempts_total",
            "Proxy attempts per model (retries make this exceed requests).",
            self.registry,
        )
        self.proxy_retries = Counter(
            "kubeai_proxy_retries_total",
            "Proxy attempts that failed and were retried on another "
            "endpoint, per model.",
            self.registry,
        )
        # -- resilience: circuit breaker + fault accounting ----------------
        self.lb_circuit_state = Gauge(
            "kubeai_lb_circuit_state",
            "Per-endpoint circuit breaker state: 0 closed, 1 half-open, "
            "2 open.",
            self.registry,
        )
        self.lb_circuit_ejections = Counter(
            "kubeai_lb_circuit_ejections_total",
            "Times an endpoint's circuit tripped open (ejected from the "
            "load-balancer candidate set).",
            self.registry,
        )
        self.proxy_midstream_failures = Counter(
            "kubeai_proxy_midstream_failures_total",
            "Streams whose upstream connection died after headers were "
            "sent (each one is either resumed on another endpoint or "
            "terminated with the SSE error event), per model.",
            self.registry,
        )
        self.proxy_stream_resumes = Counter(
            "kubeai_proxy_stream_resumes_total",
            "Mid-stream deaths transparently resumed on another endpoint "
            "via a continuation request (client saw one uninterrupted "
            "stream), per model.",
            self.registry,
        )
        self.proxy_stream_resume_failures = Counter(
            "kubeai_proxy_stream_resume_failures_total",
            "Mid-stream deaths whose resume budget or endpoint pool ran "
            "dry — the client got the terminal SSE error event, per "
            "model.",
            self.registry,
        )
        self.proxy_deadline_exhausted = Counter(
            "kubeai_proxy_deadline_exhausted_total",
            "Requests whose X-Deadline-Ms budget ran out before a retry "
            "could be attempted, per model.",
            self.registry,
        )
        # -- disaggregated serving (two-hop prefill→decode) ----------------
        self.proxy_disagg_requests = Counter(
            "kubeai_proxy_disagg_requests_total",
            "Requests served via the two-hop prefill→decode flow, per "
            "model.",
            self.registry,
        )
        self.proxy_disagg_fallback = Counter(
            "kubeai_proxy_disagg_fallback_total",
            "Disaggregation-enabled requests that fell back to the "
            "unified pool (no role endpoints, open circuits, or a failed "
            "hop), per model.",
            self.registry,
        )
        # -- controller repair / failure observability ---------------------
        self.controller_consecutive_failures = Gauge(
            "kubeai_controller_consecutive_failures",
            "Consecutive reconcile failures per model (0 after a clean "
            "pass) — the backoff-requeue exponent.",
            self.registry,
        )
        self.controller_pod_replacements = Counter(
            "kubeai_controller_pod_replacements_total",
            "Pods delete-and-replaced by the self-healing pod-health "
            "pass, per model and classification reason.",
            self.registry,
        )
        # -- slice groups (multi-host replicas, operator/slicegroup) --------
        self.slicegroup_groups = Gauge(
            "kubeai_slicegroup_groups",
            "Slice groups per model and state (ready|partial|broken) at "
            "the fleet aggregator's last collection — a partial or "
            "broken group is never serving capacity.",
            self.registry,
        )
        self.slicegroup_repairs = Counter(
            "kubeai_slicegroup_repairs_total",
            "Whole-group atomic repairs issued by the group-health "
            "pass, per model and the first broken member's "
            "classification reason.",
            self.registry,
        )
        self.slicegroup_ejections = Counter(
            "kubeai_slicegroup_ejections_total",
            "Slice groups ejected from load-balancer rotation because a "
            "member pod was not ready, disrupted, or terminating while "
            "the coordinator still looked routable, per model.",
            self.registry,
        )
        # -- actuation safety governor (operator/governor) -----------------
        self.governor_actions = Counter(
            "kubeai_governor_actions_total",
            "Destructive control-plane actions authorized by the "
            "governor, per action kind and model.",
            self.registry,
        )
        self.governor_denied = Counter(
            "kubeai_governor_denied_total",
            "Destructive control-plane actions the governor refused, per "
            "action kind, model, and denial reason (budget exhaustion, "
            "stale telemetry, coverage below threshold, invalid lease).",
            self.registry,
        )
        self.governor_budget_remaining = Gauge(
            "kubeai_governor_budget_remaining",
            "Healthy-pod disruptions still allowed in the current "
            "sliding window (scope=cluster), updated on every budget "
            "consultation.",
            self.registry,
        )
        self.governor_telemetry_coverage = Gauge(
            "kubeai_governor_telemetry_coverage",
            "Fraction of the model's endpoints with fresh fleet "
            "telemetry at the governor's last coverage check.",
            self.registry,
        )
        self.governor_static_holds = Counter(
            "kubeai_governor_static_stability_holds_total",
            "Scale-downs held at the last-known-good replica count "
            "because fleet telemetry was absent or stale, per model.",
            self.registry,
        )
        # -- leader election / actuation fencing ---------------------------
        self.leader_is_leader = Gauge(
            "kubeai_leader_is_leader",
            "1 while this replica holds the leadership lease, else 0.",
            self.registry,
        )
        self.leader_transitions = Counter(
            "kubeai_leader_transitions_total",
            "Leadership acquisitions and losses observed by this "
            "replica (direction label: acquired|lost).",
            self.registry,
        )
        self.leader_fenced_writes = Counter(
            "kubeai_leader_fenced_writes_total",
            "Actuation batches dropped because the leadership lease was "
            "expired or not held at write time (split-brain fencing).",
            self.registry,
        )
        # -- kube API client retries (operator/k8s/rest) -------------------
        self.kubeclient_retries = Counter(
            "kubeai_kubeclient_retry_attempts_total",
            "Kube API requests retried after a transient failure, per "
            "HTTP verb and failure reason (429, 5xx, connection error, "
            "conflict).",
            self.registry,
        )
        self.kubeclient_retry_exhausted = Counter(
            "kubeai_kubeclient_retry_exhausted_total",
            "Kube API requests that failed after exhausting the retry "
            "budget, per HTTP verb.",
            self.registry,
        )
        self.kubeclient_watch_reconnects = Counter(
            "kubeai_kubeclient_watch_reconnects_total",
            "Watch stream reconnects per kind (each reconnect waits a "
            "capped exponential backoff with jitter).",
            self.registry,
        )
        # -- autoscaler decision telemetry ---------------------------------
        self.autoscaler_ticks = Counter(
            "kubeai_autoscaler_ticks_total",
            "Completed autoscaler ticks on this replica (leader only).",
            self.registry,
        )
        self.autoscaler_scrape_duration = Histogram(
            "kubeai_autoscaler_scrape_duration_seconds",
            "Wall time of one tick's metrics scrape across all operator "
            "replicas.",
            self.registry,
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0),
        )
        self.autoscaler_signal = Gauge(
            "kubeai_autoscaler_active_requests",
            "Aggregated active-request signal per model at the last tick.",
            self.registry,
        )
        self.autoscaler_average = Gauge(
            "kubeai_autoscaler_average_active_requests",
            "Moving average of the active-request signal per model.",
            self.registry,
        )
        self.autoscaler_desired_replicas = Gauge(
            "kubeai_autoscaler_desired_replicas",
            "Replicas computed from the moving average (before hysteresis "
            "and min/max clamping).",
            self.registry,
        )
        self.autoscaler_applied_replicas = Gauge(
            "kubeai_autoscaler_applied_replicas",
            "Replicas actually applied to the Model spec at the last tick.",
            self.registry,
        )
        self.autoscaler_scale_down_votes = Gauge(
            "kubeai_autoscaler_consecutive_scale_downs",
            "Consecutive scale-down votes pending per model (hysteresis "
            "state; resets on apply or on any non-down tick).",
            self.registry,
        )
        self.autoscaler_queue_depth = Gauge(
            "kubeai_autoscaler_queue_depth",
            "Total requests waiting in the model's engine schedulers at "
            "the last tick (queue-pressure demand signal).",
            self.registry,
        )
        self.autoscaler_queue_oldest_wait = Gauge(
            "kubeai_autoscaler_queue_oldest_wait_seconds",
            "Age of the oldest queued request across the model's engines "
            "at the last tick (queue-pressure staleness signal).",
            self.registry,
        )
        # -- per-role autoscaling (disaggregated prefill/decode groups) ----
        self.autoscaler_role_desired_replicas = Gauge(
            "kubeai_autoscaler_role_desired_replicas",
            "Desired replicas per disaggregated role computed at the last "
            "tick (prefill from queue/TTFT pressure, decode from KV and "
            "slot occupancy), before hysteresis/clamping.",
            self.registry,
        )
        self.autoscaler_role_applied_replicas = Gauge(
            "kubeai_autoscaler_role_applied_replicas",
            "Replicas actually applied to the role's replica annotation "
            "at the last tick.",
            self.registry,
        )
        self.autoscaler_role_signal = Gauge(
            "kubeai_autoscaler_role_signal",
            "The role's raw bottleneck signal at the last tick: queued "
            "prefills (prefill role) or pool utilization fraction "
            "(decode role).",
            self.registry,
        )
        # -- fleet telemetry plane (kubeai_tpu/fleet) -----------------------
        self.fleet_collections = Counter(
            "kubeai_fleet_collections_total",
            "Completed fleet-state aggregation sweeps.",
            self.registry,
        )
        self.fleet_collection_duration = Histogram(
            "kubeai_fleet_collection_duration_seconds",
            "Wall time of one fleet sweep (all endpoints scraped "
            "concurrently, so this tracks the slowest endpoint).",
            self.registry,
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0),
        )
        self.fleet_endpoints = Gauge(
            "kubeai_fleet_endpoints",
            "Live serving endpoints at the last fleet sweep, per model "
            "and role.",
            self.registry,
        )
        self.fleet_stale_endpoints = Gauge(
            "kubeai_fleet_stale_endpoints",
            "Endpoints whose telemetry is stale (scrape failed or data "
            "older than the staleness bound) at the last sweep, per "
            "model — stale endpoints are flagged and excluded from "
            "aggregates, never silently merged.",
            self.registry,
        )
        self.fleet_queue_depth = Gauge(
            "kubeai_fleet_queue_depth",
            "Fleet-aggregated scheduler queue depth per model (fresh "
            "endpoints only) at the last sweep.",
            self.registry,
        )
        self.fleet_kv_utilization = Gauge(
            "kubeai_fleet_kv_utilization",
            "Mean KV-cache utilization per model and role at the last "
            "sweep.",
            self.registry,
        )
        self.fleet_chips = Gauge(
            "kubeai_fleet_chips",
            "Cluster chip inventory by slice shape (from the pods' "
            "google.com/tpu requests), at the last sweep.",
            self.registry,
        )
        self.fleet_snapshot_ts = Gauge(
            "kubeai_fleet_snapshot_timestamp_seconds",
            "Unix timestamp of the latest fleet snapshot (scrape-side "
            "age = now - this).",
            self.registry,
        )
        self.fleet_endpoint_staleness = Gauge(
            "kubeai_fleet_endpoint_staleness_seconds",
            "Age of each endpoint's last successful telemetry scrape at "
            "the last sweep, per model and endpoint (never-scraped "
            "endpoints export no series — absence is not zero age).",
            self.registry,
        )
        # -- SLO plane (kubeai_tpu/fleet/slo) --------------------------------
        self.slo_evaluations = Counter(
            "kubeai_slo_evaluations_total",
            "Completed SLO evaluation ticks (a fresh fleet snapshot was "
            "judged against every configured objective).",
            self.registry,
        )
        self.slo_skipped_ticks = Counter(
            "kubeai_slo_skipped_ticks_total",
            "SLO evaluation ticks refused per model and reason "
            "(coverage = telemetry coverage below the governor's "
            "minTelemetryCoverage, stale = no fresh fleet snapshot) — a "
            "refused tick judges nothing rather than judging blind.",
            self.registry,
        )
        self.slo_burn_rate = Gauge(
            "kubeai_slo_burn_rate",
            "Error-budget burn rate per model, objective, and window "
            "(1.0 = burning exactly the budget the objective allows).",
            self.registry,
        )
        self.slo_error_budget_remaining = Gauge(
            "kubeai_slo_error_budget_remaining",
            "Fraction of the rolling error budget still unspent per "
            "model and objective (exact ledger arithmetic; negative = "
            "budget exhausted).",
            self.registry,
        )
        self.slo_alert_state = Gauge(
            "kubeai_slo_alert_state",
            "Burn-rate alert state per model and objective: 0 ok, "
            "1 slow burn (warn), 2 fast burn (page).",
            self.registry,
        )
        self.slo_alerts = Counter(
            "kubeai_slo_alerts_total",
            "Burn-rate alert transitions fired per model, objective, and "
            "severity (slow|fast) — increments on entry, not per tick.",
            self.registry,
        )
        self.slo_events = Counter(
            "kubeai_slo_events_total",
            "SLI events judged per model and objective (the ledger's "
            "denominator).",
            self.registry,
        )
        self.slo_bad_events = Counter(
            "kubeai_slo_bad_events_total",
            "SLI events that violated the objective per model and "
            "objective (the ledger's numerator).",
            self.registry,
        )
        # -- cluster capacity planner (kubeai_tpu/fleet/planner) ------------
        self.planner_ticks = Counter(
            "kubeai_planner_ticks_total",
            "Completed capacity-planning ticks (a fresh fleet snapshot "
            "was bin-packed into a plan).",
            self.registry,
        )
        self.planner_stale_ticks = Counter(
            "kubeai_planner_stale_ticks_total",
            "Planning ticks skipped because the fleet snapshot was stale "
            "or missing (the autoscaler falls back to direct per-model "
            "scaling while this grows).",
            self.registry,
        )
        self.planner_preemptions = Counter(
            "kubeai_planner_preemptions_total",
            "Replicas preempted by the capacity plan per model (chips "
            "reclaimed for a higher scheduling class).",
            self.registry,
        )
        self.planner_desired_replicas = Gauge(
            "kubeai_planner_desired_replicas",
            "Unconstrained desired replicas per model and role in the "
            "latest plan (what the model wants before the chip budget).",
            self.registry,
        )
        self.planner_allocated_replicas = Gauge(
            "kubeai_planner_allocated_replicas",
            "Replicas the latest plan allocated per model and role under "
            "the chip budget (the autoscaler's override target).",
            self.registry,
        )
        self.planner_throttled_replicas = Gauge(
            "kubeai_planner_throttled_replicas",
            "Desired-but-unallocated replicas per model in the latest "
            "plan (demand the chip budget could not fit).",
            self.registry,
        )
        self.planner_preempted_replicas = Gauge(
            "kubeai_planner_preempted_replicas",
            "Currently-running replicas the latest plan takes away from "
            "this model despite remaining demand (preemption picks).",
            self.registry,
        )
        self.planner_chips_allocated = Gauge(
            "kubeai_planner_chips_allocated",
            "Chips the latest plan allocated per slice shape.",
            self.registry,
        )
        self.planner_chips_free = Gauge(
            "kubeai_planner_chips_free",
            "Chips the latest plan left idle per slice shape.",
            self.registry,
        )
        self.planner_plan_ts = Gauge(
            "kubeai_planner_plan_timestamp_seconds",
            "Unix timestamp of the latest capacity plan (plan age = "
            "now - this; the autoscaler ignores plans past the "
            "staleness bound).",
            self.registry,
        )
        # -- predictive prewarm (kubeai_tpu/fleet/forecaster) ----------------
        self.prewarm_forecast_demand = Gauge(
            "kubeai_prewarm_forecast_demand",
            "Forecast demand (requests in flight + queued) per model at "
            "the forecast horizon, from the demand forecaster's fit over "
            "the snapshot ring.",
            self.registry,
        )
        self.prewarm_replicas = Gauge(
            "kubeai_prewarm_replicas",
            "Extra replicas the latest plan prewarms per model ahead of "
            "forecast demand (granted from spare chips, actuated through "
            "the governor like any scale-up).",
            self.registry,
        )
        self.prewarm_orders = Counter(
            "kubeai_prewarm_orders_total",
            "Prewarm replica grants ordered by the planner per model and "
            "trigger (trend = rising request-rate fit, spot = "
            "spot-preemption early warning).",
            self.registry,
        )
        self.prewarm_denied = Counter(
            "kubeai_prewarm_denied_total",
            "Prewarm grants the actuation governor refused per model "
            "(fencing or telemetry-coverage gate).",
            self.registry,
        )
        self.prewarm_coldstart_cost = Gauge(
            "kubeai_prewarm_coldstart_cost_seconds",
            "Measured cold-start cost per model (replica-reported boot "
            "total; restore-path replicas report the cheap figure) — "
            "what the planner prices into preemption choices.",
            self.registry,
        )
        self.objstore_retries = ObjstoreRetries(
            "kubeai_objstore_retries_total",
            "Object-store requests retried after a transient failure "
            "(5xx/429, connection reset, short read) across every "
            "client in the process.",
            self.registry,
        )
        # -- per-tenant usage metering (kubeai_tpu/fleet/metering) ----------
        self.tenant_requests = Counter(
            "kubeai_tenant_requests_total",
            "Requests attributed per tenant and model (X-Client-Id, "
            "API-key principal digest, or 'anonymous').",
            self.registry,
        )
        self.tenant_prompt_tokens = Counter(
            "kubeai_tenant_prompt_tokens_total",
            "Prompt tokens consumed per tenant and model.",
            self.registry,
        )
        self.tenant_completion_tokens = Counter(
            "kubeai_tenant_completion_tokens_total",
            "Completion tokens generated per tenant and model.",
            self.registry,
        )
        self.tenant_stream_seconds = Counter(
            "kubeai_tenant_stream_seconds_total",
            "Seconds of open SSE stream time per tenant and model.",
            self.registry,
        )
        self.tenant_shed = Counter(
            "kubeai_tenant_shed_total",
            "Requests answered 429 (shed/rate-limited) per tenant and "
            "model.",
            self.registry,
        )
        # -- front-door tenant admission (kubeai_tpu/fleet/tenancy) ---------
        self.door_admitted = Counter(
            "kubeai_door_admitted_total",
            "Requests the tenant admission layer admitted per model "
            "(the front door's pre-queue gate).",
            self.registry,
        )
        self.door_rejections = Counter(
            "kubeai_door_rejections_total",
            "Requests refused at the door per tenant (label capped; "
            "overflow aggregates into 'other'), model, and reason "
            "(rate | tokens | quota | overload).",
            self.registry,
        )
        self.door_retry_after = Histogram(
            "kubeai_door_retry_after_seconds",
            "Computed Retry-After values handed out with door 429s "
            "(post-jitter).",
            self.registry,
            buckets=(0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                     300.0),
        )
        self.door_overload = Gauge(
            "kubeai_door_overload",
            "1 while the door's global overload latch is engaged (fleet "
            "queue pressure crossed the high-water mark; clears at the "
            "low-water mark).",
            self.registry,
        )
        self.door_queue_pressure = Gauge(
            "kubeai_door_queue_pressure",
            "Fleet-wide queue depth the door last observed (aggregator "
            "snapshot, or a direct scrape when the snapshot is stale).",
            self.registry,
        )
        self.door_shedding = Gauge(
            "kubeai_door_shedding",
            "1 while the door is shedding the given scheduling class "
            "(priority label; batch sheds first, realtime never).",
            self.registry,
        )
        self.door_tenants_tracked = Gauge(
            "kubeai_door_tenants_tracked",
            "Tenants with live admission state at the door (buckets and "
            "quota windows; idle tenants expire).",
            self.registry,
        )
        # -- door-shard gossip state plane (kubeai_tpu/routing/gossip) ------
        self.gossip_rounds = Counter(
            "kubeai_gossip_rounds_total",
            "Anti-entropy rounds run by the door shard set (each round "
            "push-pulls every shard with one rotated peer).",
            self.registry,
        )
        self.gossip_syncs = Counter(
            "kubeai_gossip_syncs_total",
            "Per-shard pairwise sync attempts by result: ok (state "
            "exchanged), skip (digests already equal), unreachable "
            "(link severed by a partition).",
            self.registry,
        )
        self.gossip_entries_sent = Counter(
            "kubeai_gossip_entries_sent_total",
            "CRDT entries shipped between door shards (delta-state "
            "sync; full state only after crash/heal/churn).",
            self.registry,
        )
        self.gossip_merges = Counter(
            "kubeai_gossip_merges_total",
            "CRDT entries that actually changed when merged (idempotent "
            "re-deliveries do not count).",
            self.registry,
        )
        self.gossip_state_entries = Gauge(
            "kubeai_gossip_state_entries",
            "CRDT entries held in each door shard's replicated state "
            "(shard label).",
            self.registry,
        )
        self.gossip_peer_staleness = Gauge(
            "kubeai_gossip_peer_staleness_seconds",
            "Seconds since each door shard last exchanged state with "
            "each peer (shard, peer labels); the partition detector's "
            "input.",
            self.registry,
        )
        self.gossip_degraded = Gauge(
            "kubeai_gossip_degraded",
            "1 while the door shard is partitioned from at least one "
            "peer and enforcing the conservative local budget split.",
            self.registry,
        )
        self.gossip_breaker_adoptions = Counter(
            "kubeai_gossip_breaker_adoptions_total",
            "Breaker opens adopted from peer door shards via gossip "
            "per model — failures this shard never had to pay for "
            "itself.",
            self.registry,
        )
        # -- federation plane (kubeai_tpu/federation) ------------------------
        self.federation_joins = Counter(
            "kubeai_federation_joins_total",
            "Federation join sweeps: per-cluster fleet snapshots merged "
            "into one federation snapshot (staleness flagged per "
            "cluster, never silently merged).",
            self.registry,
        )
        self.federation_snapshot_ts = Gauge(
            "kubeai_federation_snapshot_timestamp_seconds",
            "Unix timestamp of the latest federation snapshot.",
            self.registry,
        )
        self.federation_cluster_stale = Gauge(
            "kubeai_federation_cluster_stale",
            "1 while the named peer cluster's snapshot is stale or "
            "unreachable (cluster label) — the failover window's input.",
            self.registry,
        )
        self.federation_spillovers = Counter(
            "kubeai_federation_spillovers_total",
            "Requests the federation router spilled to a peer cluster's "
            "door per model and cluster (fires only on local chip "
            "exhaustion, cost-ranked, tenancy headers forwarded intact).",
            self.registry,
        )
        self.federation_spill_errors = Counter(
            "kubeai_federation_spill_errors_total",
            "Spillover dispatches that failed at the peer door per "
            "cluster (the request then falls back to the local queue).",
            self.registry,
        )
        self.federation_failovers = Counter(
            "kubeai_federation_failovers_total",
            "Whole-model failovers the federation planner actuated per "
            "model and (partitioned source) cluster, governor-gated.",
            self.registry,
        )
        self.federation_failbacks = Counter(
            "kubeai_federation_failbacks_total",
            "Failovers reversed after the partitioned cluster healed, "
            "per model and cluster.",
            self.registry,
        )
        self.federation_failover_denied = Counter(
            "kubeai_federation_failover_denied_total",
            "Federation failovers the actuation governor refused per "
            "model (fencing or telemetry-coverage gate).",
            self.registry,
        )
        self.federation_kv_fills = Counter(
            "kubeai_federation_kv_fills_total",
            "KVP1 prefix fills served from a peer cluster's spill store "
            "per cluster (pages adopted instead of recomputed).",
            self.registry,
        )
        self.federation_kv_refusals = Counter(
            "kubeai_federation_kv_refusals_total",
            "Cross-cluster KVP1 fills refused by the quant-header "
            "protocol per cluster (dtype/scheme mismatch — refused, "
            "never cast; the request recomputes locally).",
            self.registry,
        )
        # -- progressive rollouts (kubeai_tpu/operator/rollout) --------------
        self.rollout_phase = Gauge(
            "kubeai_rollout_phase",
            "Rollout phase per model: 0 idle, 1 canary, 2 ramp, "
            "3 rolling back (pin written, condemned hash draining).",
            self.registry,
        )
        self.rollout_canary_share = Gauge(
            "kubeai_rollout_canary_share",
            "Traffic share the load balancer currently allows the "
            "new-hash endpoints of an in-flight rollout per model "
            "(0..1; absent outside a rollout).",
            self.registry,
        )
        self.rollout_steps = Counter(
            "kubeai_rollout_steps_total",
            "Rollout steps taken per model and step kind (start / "
            "widen / promote), each one governor-budgeted.",
            self.registry,
        )
        self.rollout_verdicts = Counter(
            "kubeai_rollout_verdicts_total",
            "Comparative judge verdicts per model and verdict (pass, or "
            "the failing signal: ttft_regression / breaker_trips / "
            "crashloop) — one per judged tick of an in-flight rollout.",
            self.registry,
        )
        self.rollout_rollbacks = Counter(
            "kubeai_rollout_rollbacks_total",
            "Automatic rollbacks per model and reason: the judge "
            "condemned the new hash and pinned the last-good one.",
            self.registry,
        )
        self.rollout_denied = Counter(
            "kubeai_rollout_denied_total",
            "Rollout steps or rollbacks the actuation governor refused "
            "per model and action (fencing, budget, or coverage gate).",
            self.registry,
        )
        # -- tracing export health ------------------------------------------
        self.tracing_dropped_spans = TracingDroppedSpans(
            "kubeai_tracing_dropped_spans_total",
            "Spans dropped by the OTLP exporter (queue full or exporter "
            "thread dead) instead of blocking the request path.",
            self.registry,
        )


# Process-default bundle (single-replica processes, ad-hoc use).
DEFAULT_METRICS = Metrics()
REGISTRY = DEFAULT_METRICS.registry
INFERENCE_REQUESTS_ACTIVE = DEFAULT_METRICS.inference_requests_active
INFERENCE_REQUESTS_TOTAL = DEFAULT_METRICS.inference_requests_total
CHWBL_LOOKUPS = DEFAULT_METRICS.chwbl_lookups
CHWBL_DISPLACEMENTS = DEFAULT_METRICS.chwbl_displacements


def parse_prometheus_text(text: str) -> dict[tuple[str, tuple], float]:
    """Parse exposition text into {(metric, ((label,val),...)): value} —
    the scrape decoder behind the autoscaler and the fleet aggregator
    (reference: modelautoscaler/metrics.go).

    Tolerates real-world exposition the aggregator will meet on the
    wire: `+Inf`/`NaN` sample values, exponent-format floats, trailing
    millisecond timestamps after the value, and `}`/whitespace inside
    quoted label values. Unparseable lines are skipped, never raised —
    one weird family must not blind the whole scrape."""
    out: dict[tuple[str, tuple], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        labels: list[tuple[str, str]] = []
        brace = line.find("{")
        if brace != -1 and (
            " " not in line[:brace] and "\t" not in line[:brace]
        ):
            name = line[:brace]
            closed = _find_label_close(line, brace + 1)
            if closed < 0:
                continue  # unterminated label block
            for pair in _split_label_pairs(line[brace + 1:closed]):
                if "=" not in pair:
                    continue
                k, v = pair.split("=", 1)
                labels.append((k.strip(), _unquote_label_value(v)))
            tail = line[closed + 1:]
        else:
            name, _, tail = line.partition(" ")
        parts = tail.split()
        if not name or not parts:
            continue
        try:
            # float() natively accepts +Inf/-Inf/NaN and exponent forms.
            value = float(parts[0])
        except ValueError:
            continue
        # parts[1], when present, is the optional sample timestamp — it
        # must not be mistaken for the value (the old rsplit was).
        out[(name, tuple(sorted(labels)))] = value
    return out


def _find_label_close(line: str, start: int) -> int:
    """Index of the `}` closing the label block opened before `start`,
    honoring quotes and backslash escapes (a quoted label value may
    legally contain `}`). -1 when unterminated."""
    in_q = esc = False
    for i in range(start, len(line)):
        ch = line[i]
        if esc:
            esc = False
        elif ch == "\\" and in_q:
            esc = True
        elif ch == '"':
            in_q = not in_q
        elif ch == "}" and not in_q:
            return i
    return -1


def _split_label_pairs(s: str) -> list[str]:
    """Split `k1="v1",k2="v2"` on commas outside quoted values. Tracks
    the backslash escape state: an escaped quote (`\\"`) inside a value —
    which `_fmt_labels`'s own escaping produces — must NOT toggle the
    in-quotes flag, or every value containing a quote fails to
    round-trip through `parse_prometheus_text`."""
    pairs, cur, in_q, esc = [], "", False, False
    for ch in s:
        if esc:
            cur += ch
            esc = False
        elif ch == "\\" and in_q:
            cur += ch
            esc = True
        elif ch == '"':
            in_q = not in_q
            cur += ch
        elif ch == "," and not in_q:
            pairs.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        pairs.append(cur)
    return pairs


def _unquote_label_value(v: str) -> str:
    """Strip one layer of quotes and undo exposition-format escaping
    (`\\\\` → `\\`, `\\"` → `"`, `\\n` → newline)."""
    if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
        v = v[1:-1]
    out, i = [], 0
    while i < len(v):
        ch = v[i]
        if ch == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)
