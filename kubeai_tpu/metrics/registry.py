"""Minimal Prometheus-compatible metrics (text exposition format 0.0.4)."""

from __future__ import annotations

import threading
from collections import defaultdict


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92)*2).replace(chr(34), chr(92)+chr(34))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Metric:
    def __init__(self, name: str, help_: str, registry: "Registry | None"):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = defaultdict(float)
        self._label_keys: dict[tuple, dict] = {}
        if registry is not None:
            registry.register(self)

    def _key(self, labels: dict[str, str]) -> tuple:
        k = tuple(sorted(labels.items()))
        self._label_keys[k] = labels
        return k

    def get(self, **labels) -> float:
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def collect(self) -> list[str]:
        with self._lock:
            lines = [
                f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} {self.TYPE}",
            ]
            if not self._values:
                lines.append(f"{self.name} 0")
            for k, v in sorted(self._values.items()):
                lines.append(
                    f"{self.name}{_fmt_labels(self._label_keys[k])} {v:g}"
                )
            return lines


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] += amount


class Gauge(_Metric):
    TYPE = "gauge"

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = value


class Histogram(_Metric):
    TYPE = "histogram"
    BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)

    def __init__(self, name, help_, registry, buckets=None):
        super().__init__(name, help_, registry)
        self.buckets = tuple(buckets or self.BUCKETS)
        self._bucket_counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._counts: dict[tuple, int] = defaultdict(int)

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            if k not in self._bucket_counts:
                self._bucket_counts[k] = [0] * len(self.buckets)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._bucket_counts[k][i] += 1
            self._sums[k] += value
            self._counts[k] += 1

    def collect(self) -> list[str]:
        with self._lock:
            lines = [
                f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} histogram",
            ]
            for k in sorted(self._counts):
                labels = self._label_keys[k]
                cum = 0
                for i, b in enumerate(self.buckets):
                    cum += self._bucket_counts[k][i]
                    lines.append(
                        f"{self.name}_bucket{_fmt_labels({**labels, 'le': b})} {cum}"
                    )
                lines.append(
                    f"{self.name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} {self._counts[k]}"
                )
                lines.append(f"{self.name}_sum{_fmt_labels(labels)} {self._sums[k]:g}")
                lines.append(f"{self.name}_count{_fmt_labels(labels)} {self._counts[k]}")
            return lines


class Registry:
    def __init__(self):
        self._metrics: list[_Metric] = []
        self._lock = threading.Lock()

    def register(self, m: _Metric) -> None:
        with self._lock:
            self._metrics.append(m)

    def expose(self) -> str:
        out: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            out.extend(m.collect())
        return "\n".join(out) + "\n"


class Metrics:
    """One operator replica's instrument bundle. Each Manager owns its own
    Metrics so multiple replicas embedded in one process (virtual HA,
    integration tests) don't share counters — sharing would double-count the
    autoscaling signal when the leader scrapes every replica."""

    def __init__(self):
        self.registry = Registry()
        # The autoscaling signal (reference: internal/metrics/metrics.go:16-20;
        # Prom name mapping metrics.go:81-87).
        self.inference_requests_active = Gauge(
            "kubeai_inference_requests_active",
            "Number of in-flight inference requests per model.",
            self.registry,
        )
        self.inference_requests_total = Counter(
            "kubeai_inference_requests_total",
            "Total inference requests per model.",
            self.registry,
        )
        self.chwbl_lookups = Counter(
            "kubeai_chwbl_lookups_total",
            "CHWBL address lookups.",
            self.registry,
        )
        self.chwbl_displacements = Counter(
            "kubeai_chwbl_displacements_total",
            "CHWBL lookups displaced past the hashed endpoint by the bounded-load rule.",
            self.registry,
        )


# Process-default bundle (single-replica processes, ad-hoc use).
DEFAULT_METRICS = Metrics()
REGISTRY = DEFAULT_METRICS.registry
INFERENCE_REQUESTS_ACTIVE = DEFAULT_METRICS.inference_requests_active
INFERENCE_REQUESTS_TOTAL = DEFAULT_METRICS.inference_requests_total
CHWBL_LOOKUPS = DEFAULT_METRICS.chwbl_lookups
CHWBL_DISPLACEMENTS = DEFAULT_METRICS.chwbl_displacements


def parse_prometheus_text(text: str) -> dict[tuple[str, tuple], float]:
    """Parse exposition text into {(metric, ((label,val),...)): value} —
    the autoscaler's scrape decoder (reference: modelautoscaler/metrics.go)."""
    out: dict[tuple[str, tuple], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value_s = line.rsplit(" ", 1)
            value = float(value_s)
        except ValueError:
            continue
        if "{" in name_part:
            name, rest = name_part.split("{", 1)
            rest = rest.rstrip("}")
            labels = []
            for pair in _split_label_pairs(rest):
                if "=" not in pair:
                    continue
                k, v = pair.split("=", 1)
                labels.append((k, v.strip('"')))
            out[(name, tuple(sorted(labels)))] = value
        else:
            out[(name_part, ())] = value
    return out


def _split_label_pairs(s: str) -> list[str]:
    pairs, cur, in_q = [], "", False
    for ch in s:
        if ch == '"':
            in_q = not in_q
            cur += ch
        elif ch == "," and not in_q:
            pairs.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        pairs.append(cur)
    return pairs
