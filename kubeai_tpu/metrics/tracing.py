"""OTel-compatible distributed tracing, stdlib-only.

The reference wires the OTel SDK at startup but keeps tracing dormant —
only the meter provider is live (reference: internal/manager/otel.go:16-73,
tracing commented out at otel.go:40-47; HTTP route tagging via otelhttp,
internal/openaiserver/handler.go:28-31). Here tracing is live end-to-end
without the SDK (zero-egress image, no pip installs):

  - W3C `traceparent` context propagation: the front door continues an
    incoming trace or starts one, the proxy forwards context to the engine
    Pod, the engine server continues it — one trace across the stack.
  - Spans export as OTLP/HTTP **JSON** (the protobuf-JSON mapping every
    OpenTelemetry collector accepts on /v1/traces) from a background
    batcher. Endpoint from `OTEL_EXPORTER_OTLP_ENDPOINT` (standard env) or
    `configure()`; without one, span objects are still created so
    propagation headers flow, but nothing is buffered or sent.

Span timestamps are unix-epoch nanoseconds, ids are random per the W3C
spec (16-hex span / 32-hex trace, non-zero).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import random
import re
import threading
import time
import urllib.request

logger = logging.getLogger(__name__)

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

# OTLP span kinds (opentelemetry-proto trace.proto).
KIND_INTERNAL = 1
KIND_SERVER = 2
KIND_CLIENT = 3

_STATUS_UNSET = 0
_STATUS_OK = 1
_STATUS_ERROR = 2


# Module-private PRNG seeded from the OS: the global `random` is vulnerable
# to user `random.seed()` calls, which would yield colliding trace/span ids
# across processes. Forked children re-seed (a module-level Random is
# otherwise duplicated across fork just like the global one).
_id_rng = random.Random(int.from_bytes(os.urandom(16), "big"))
if hasattr(os, "register_at_fork"):  # not on Windows
    os.register_at_fork(
        after_in_child=lambda: _id_rng.seed(
            int.from_bytes(os.urandom(16), "big")
        )
    )


def _rand_hex(nbytes: int) -> str:
    # PRNG (not uuid4) — cheap, and the spec only wants non-zero random.
    while True:
        h = _id_rng.getrandbits(nbytes * 8)
        if h:
            return format(h, "0{}x".format(nbytes * 2))


class SpanContext:
    """W3C trace context: ids + sampled flag."""

    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id: str, span_id: str, flags: int = 1):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = flags

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"


def parse_traceparent(header: str | None) -> SpanContext | None:
    """Parse an incoming `traceparent`; None on absence/malformation (the
    spec says restart the trace rather than guess)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id, int(flags, 16))


class Span:
    __slots__ = (
        "name", "context", "parent_span_id", "kind", "start_ns", "end_ns",
        "attributes", "status", "_tracer",
    )

    def __init__(self, tracer, name, context, parent_span_id, kind, attrs):
        self._tracer = tracer
        self.name = name
        self.context = context
        self.parent_span_id = parent_span_id
        self.kind = kind
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attributes = dict(attrs or {})
        self.status = _STATUS_UNSET

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def end(self, error: str | None = None) -> None:
        if self.end_ns:
            return  # idempotent
        self.end_ns = time.time_ns()
        if error is not None:
            self.status = _STATUS_ERROR
            self.attributes.setdefault("error.message", error)
        else:
            self.status = _STATUS_OK
        self._tracer._record(self)

    # context-manager sugar: ends with ERROR on exception.
    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        self.end(error=str(ev) if ev is not None else None)
        return False


def _otlp_value(v) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


class Tracer:
    """Creates spans and exports them as OTLP/HTTP JSON batches.

    Thread-safe; the exporter is one daemon thread. Spans are dropped (and
    counted) rather than blocking the request path when the buffer is
    full or the collector is down."""

    def __init__(
        self,
        service_name: str = "kubeai-tpu",
        endpoint: str | None = None,
        flush_interval_s: float = 2.0,
        max_buffer: int = 2048,
        max_batch: int = 512,
    ):
        self.service_name = service_name
        self.endpoint = endpoint
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        self.dropped = 0
        self._q: queue.Queue[Span] = queue.Queue(maxsize=max_buffer)
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._err_logged = 0.0
        if self.endpoint:
            self._thread = threading.Thread(
                target=self._export_loop, daemon=True
            )
            self._thread.start()

    @property
    def exporting(self) -> bool:
        return self.endpoint is not None

    def start_span(
        self,
        name: str,
        parent: SpanContext | None = None,
        kind: int = KIND_INTERNAL,
        attributes: dict | None = None,
    ) -> Span:
        if parent is not None:
            ctx = SpanContext(parent.trace_id, _rand_hex(8), parent.flags)
            parent_id = parent.span_id
        else:
            ctx = SpanContext(_rand_hex(16), _rand_hex(8))
            parent_id = ""
        return Span(self, name, ctx, parent_id, kind, attributes)

    def _record(self, span: Span) -> None:
        if not self.endpoint:
            return
        t = self._thread
        if t is None or not t.is_alive():
            # Nothing will ever drain the queue: enqueueing would just
            # strand the span (and eventually wedge flush callers on a
            # growing task counter). Count it as dropped — the
            # kubeai_tracing_dropped_spans_total counter surfaces the
            # dead exporter instead of silence.
            self.dropped += 1
            return
        try:
            self._q.put_nowait(span)
        except queue.Full:
            self.dropped += 1

    # -- export ----------------------------------------------------------------

    def _drain(self) -> list[Span]:
        out = []
        try:
            while len(out) < self.max_batch:
                out.append(self._q.get_nowait())
        except queue.Empty:
            pass
        return out

    def _export_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.flush_interval_s)
            self._wake.clear()
            batch = self._drain()
            if batch:
                self._send(batch)
        for batch in iter(self._drain, []):  # final flush
            self._send(batch)

    def _ack(self, batch: list[Span]) -> None:
        """Mark drained spans done on the queue's task counter — what
        flush() joins on."""
        for _ in batch:
            self._q.task_done()

    def _payload(self, batch: list[Span]) -> dict:
        return {
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "kubeai_tpu.metrics.tracing"},
                    "spans": [{
                        "traceId": s.context.trace_id,
                        "spanId": s.context.span_id,
                        **(
                            {"parentSpanId": s.parent_span_id}
                            if s.parent_span_id else {}
                        ),
                        "name": s.name,
                        "kind": s.kind,
                        "startTimeUnixNano": str(s.start_ns),
                        "endTimeUnixNano": str(s.end_ns),
                        "attributes": [
                            {"key": k, "value": _otlp_value(v)}
                            for k, v in s.attributes.items()
                        ],
                        "status": (
                            {"code": s.status}
                            if s.status != _STATUS_UNSET else {}
                        ),
                    } for s in batch],
                }],
            }]
        }

    def _send(self, batch: list[Span]) -> None:
        body = json.dumps(self._payload(batch)).encode()
        req = urllib.request.Request(
            self.endpoint.rstrip("/") + "/v1/traces",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
        except Exception as e:
            # Broad on purpose: a misconfigured endpoint raises ValueError
            # (not OSError) from urlopen, and an escaped exception would
            # kill the exporter thread permanently — export must degrade
            # to counted drops, never die.
            self.dropped += len(batch)
            now = time.monotonic()
            if now - self._err_logged > 60:  # throttle
                self._err_logged = now
                logger.warning("OTLP trace export failed: %s", e)
        finally:
            self._ack(batch)

    def flush(self, timeout_s: float = 5.0) -> None:
        """Push buffered spans out now (tests, shutdown).

        Returns immediately when no exporter thread is alive — nothing
        will ever drain the queue, so spinning on it could only burn the
        whole timeout. Otherwise waits on the queue's task counter
        (Queue.join with a deadline) instead of sleep-polling emptiness:
        empty() flips before the last batch is SENT, and polling wakes
        20ms late per batch where the condition wakes exactly when the
        exporter acks."""
        t = self._thread
        if t is None or not t.is_alive():
            return
        deadline = time.monotonic() + timeout_s
        self._wake.set()
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._wake.set()
                self._q.all_tasks_done.wait(min(remaining, 0.1))

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=5)


# -- module default -----------------------------------------------------------

_default: Tracer | None = None
_default_lock = threading.Lock()


def configure(
    endpoint: str | None = None, service_name: str = "kubeai-tpu", **kw
) -> Tracer:
    """Install the process-wide tracer. Endpoint resolution order:
    explicit arg → OTEL_EXPORTER_OTLP_TRACES_ENDPOINT →
    OTEL_EXPORTER_OTLP_ENDPOINT → no export (propagation only)."""
    global _default
    endpoint = (
        endpoint
        or os.environ.get("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT")
        or os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT")
    )
    with _default_lock:
        if _default is not None:
            _default.shutdown()
        _default = Tracer(service_name=service_name, endpoint=endpoint, **kw)
        return _default


def tracer() -> Tracer:
    global _default
    # Lock-free fast path: this sits on every request of all three
    # servers; after first initialization the lock would only serialize a
    # read.
    d = _default
    if d is not None:
        return d
    with _default_lock:
        if _default is None:
            _default = Tracer(
                endpoint=os.environ.get("OTEL_EXPORTER_OTLP_TRACES_ENDPOINT")
                or os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT")
            )
        return _default
