"""Metrics registry (reference: internal/metrics/metrics.go).

Prometheus-text-format instruments with no external deps. Metrics are not
just observability here: the autoscaler scrapes
`kubeai_inference_requests_active` from every operator replica — metrics
are the autoscaling transport (reference: internal/modelautoscaler/metrics.go:15-71).
"""

from kubeai_tpu.metrics.registry import (
    Metrics,
    DEFAULT_METRICS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
    INFERENCE_REQUESTS_ACTIVE,
    INFERENCE_REQUESTS_TOTAL,
    CHWBL_LOOKUPS,
    CHWBL_DISPLACEMENTS,
    LATENCY_BUCKETS_S,
    lint_registry,
    parse_prometheus_text,
)
