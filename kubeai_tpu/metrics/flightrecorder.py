"""Always-on flight recorder: bounded decision-event rings + triggered
incident bundles.

Every subsystem that makes a discrete, consequential decision — the
door shedding a tenant, a circuit tripping open, the governor refusing
an actuation, the engine scheduler shedding or preempting, the planner
marking preemption victims, the watchdog catching a wedged step, the
SLO plane firing a burn-rate alert — drops a structured `FlightEvent`
into its ring here. The rings are small, lock-cheap, and always on:
recording is a deque append, never I/O.

When a trigger rule fires (fast-burn page, watchdog wedge, every
circuit open, telemetry coverage collapse), `trigger()` atomically
snapshots every ring plus the recent-span ring and the metric-capture
deltas into a sorted-key JSONL **incident bundle** in GameDayLog format
(header line + typed records), so `python -m benchmarks.gameday_sim
--replay <bundle>` can re-drive the deterministic sim named in the
bundle's header and reproduce the incident byte-identically.

Schema discipline: the event kinds and record kinds declared HERE must
stay a subset of the game-day schema in `kubeai_tpu/testing/chaos.py`
(`FLIGHT_EVENT_KINDS` / `LOG_RECORD_KINDS`) — deliberately duplicated,
not imported, so `scripts/check_incident_schema.py` can gate the drift
in tier-1: a new kind added here without teaching the replay side is a
build failure, not a silently dropped record.

Determinism: the recorder touches the clock only through the injected
`clock` callable, assigns a process-monotonic `seq` to every event for
stable same-instant ordering, and filters known wall-clock-derived
metric series out of bundle deltas — a FakeClock sim that dumps a
bundle twice gets the same bytes twice.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

from kubeai_tpu.metrics.registry import (
    Counter,
    Gauge,
    Registry,
    parse_prometheus_text,
)

logger = logging.getLogger(__name__)

# Decision-event kinds this recorder accepts. MUST stay a subset of
# chaos.FLIGHT_EVENT_KINDS (gated by scripts/check_incident_schema.py).
DOOR_SHED = "door_shed"
DOOR_QUOTA = "door_quota"
BREAKER = "breaker_transition"
LB_NO_ENDPOINTS = "lb_no_healthy_endpoints"
GOVERNOR_DENY = "governor_denial"
SCHED_ADMIT = "scheduler_admit"
SCHED_SHED = "scheduler_shed"
SCHED_PREEMPT = "scheduler_preempt"
PLANNER_PREEMPT = "planner_preempt_mark"
WATCHDOG = "engine_watchdog"
STEP_ANOMALY = "engine_step_anomaly"
SLO_ALERT = "slo_alert"
ROLLOUT_DECISION = "rollout_decision"

EVENT_KINDS = (
    DOOR_SHED,
    DOOR_QUOTA,
    BREAKER,
    LB_NO_ENDPOINTS,
    GOVERNOR_DENY,
    SCHED_ADMIT,
    SCHED_SHED,
    SCHED_PREEMPT,
    PLANNER_PREEMPT,
    WATCHDOG,
    STEP_ANOMALY,
    SLO_ALERT,
    ROLLOUT_DECISION,
)

# Record kinds incident bundles emit. MUST stay a subset of
# chaos.LOG_RECORD_KINDS (same gate).
RECORD_KINDS = ("flight", "span", "metric_delta", "exemplar")

# Trigger rule names (the `trigger` label on kubeai_flight_incidents_total
# and the `reason` field in bundle headers).
TRIGGER_FAST_BURN = "fast_burn_page"
TRIGGER_WATCHDOG = "watchdog_wedge"
TRIGGER_ALL_CIRCUITS_OPEN = "all_circuits_open"
TRIGGER_COVERAGE_COLLAPSE = "coverage_collapse"
TRIGGER_ROLLBACK = "rollout_rollback"

# Metric series derived from the host wall clock even under a FakeClock
# (they time real work with time.monotonic). Excluded from bundle
# deltas: their values differ run-to-run and would break the
# byte-identical replay contract.
NONDETERMINISTIC_METRICS = frozenset({
    "kubeai_fleet_collection_duration_seconds",
    "kubeai_autoscaler_scrape_duration_seconds",
})


def _deterministic_series(series: str) -> bool:
    name = series.split("{", 1)[0]
    for nd in NONDETERMINISTIC_METRICS:
        if name.startswith(nd):
            return False
    return True


class FlightRecorderMetrics:
    """The recorder's own instrument bundle (own registry: the recorder
    is wired into subsystems that carry different Metrics bundles, and
    its health must be observable regardless of which one scrapes)."""

    def __init__(self):
        self.registry = Registry()
        self.events = Counter(
            "kubeai_flight_events_total",
            "Decision events recorded per flight-recorder ring.",
            self.registry,
        )
        self.dropped = Counter(
            "kubeai_flight_dropped_events_total",
            "Events evicted from a full flight-recorder ring (the ring "
            "keeps the newest; eviction is normal steady-state behavior, "
            "a spike means the window shrank during an incident).",
            self.registry,
        )
        self.incidents = Counter(
            "kubeai_flight_incidents_total",
            "Incident bundles dumped per trigger rule.",
            self.registry,
        )
        self.suppressed = Counter(
            "kubeai_flight_suppressed_triggers_total",
            "Trigger firings suppressed by the per-rule debounce "
            "interval (the first bundle of a storm is the evidence; "
            "the next hundred would be noise).",
            self.registry,
        )
        self.last_incident_ts = Gauge(
            "kubeai_flight_last_incident_timestamp_seconds",
            "Timestamp of the most recent incident bundle dump.",
            self.registry,
        )


class FlightRecorder:
    """Bounded per-subsystem decision rings + incident bundling.

    `clock` is injectable (FakeClock in sims); `tick_fn` optionally
    maps the clock to a sim tick for bundle records (defaults to 0 —
    live processes have no tick). `sink_dir` is where bundles land;
    without one, `trigger()` still builds and retains the bundle lines
    in memory (`self.incidents`)."""

    def __init__(
        self,
        clock=time.time,
        ring_size: int = 256,
        span_ring_size: int = 128,
        metric_captures: int = 8,
        min_trigger_interval_s: float = 300.0,
        sink_dir: str | None = None,
        metrics: FlightRecorderMetrics | None = None,
        tick_fn=None,
    ):
        self._clock = clock
        self.ring_size = int(ring_size)
        self.min_trigger_interval_s = float(min_trigger_interval_s)
        self.sink_dir = sink_dir
        self.metrics = metrics if metrics is not None else FlightRecorderMetrics()
        self.tick_fn = tick_fn
        self._lock = threading.Lock()
        self._rings: dict[str, deque] = {}
        self._seq = 0
        self._spans: deque = deque(maxlen=int(span_ring_size))
        self._captures: deque = deque(maxlen=max(2, int(metric_captures)))
        self._exemplars: dict[str, dict] = {}
        self._last_trigger: dict[str, float] = {}
        # What a bundle needs to be replayable: the owning sim stamps
        # {"sim": ..., "seed": ..., "ticks": ...} here before running.
        self.replay_context: dict = {}
        # [(reason, path_or_None, lines)] of every bundle this recorder
        # produced — the in-process view /v1/slo exposes.
        self.incidents: list[dict] = []

    # -- recording (the always-on hot path) ---------------------------------

    def record(
        self,
        kind: str,
        subsystem: str,
        target: str = "",
        trace_id: str = "",
        **detail,
    ) -> None:
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown flight event kind {kind!r}")
        ev = {
            "t": self._clock(),
            "kind": kind,
            "subsystem": subsystem,
            "target": target,
        }
        if trace_id:
            ev["trace_id"] = trace_id
        if detail:
            ev["detail"] = detail
        with self._lock:
            ring = self._rings.get(subsystem)
            if ring is None:
                ring = self._rings[subsystem] = deque(maxlen=self.ring_size)
            ev["seq"] = self._seq
            self._seq += 1
            if len(ring) == ring.maxlen:
                self.metrics.dropped.inc(ring=subsystem)
            ring.append(ev)
        self.metrics.events.inc(ring=subsystem)

    def events(self, subsystem: str | None = None) -> list[dict]:
        """Current ring contents (all rings merged when subsystem is
        None), in global decision order."""
        with self._lock:
            if subsystem is not None:
                return [dict(e) for e in self._rings.get(subsystem, ())]
            merged = [e for ring in self._rings.values() for e in ring]
        merged.sort(key=lambda e: (e["t"], e["seq"]))
        return [dict(e) for e in merged]

    def note_span(self, span: dict) -> None:
        """Keep a recent-span ring for bundles (the tracer exports and
        forgets; the recorder remembers the last few)."""
        with self._lock:
            self._spans.append(dict(span))

    def note_exemplars(self, source: str, exemplars: dict) -> None:
        """Latest per-bucket trace-id exemplars for one histogram
        source (e.g. 'door_ttft/<model>') — stamped into bundles so a
        burn-rate breach links straight to example traces."""
        if exemplars:
            with self._lock:
                self._exemplars[source] = dict(exemplars)

    def capture_metrics(self, registry) -> None:
        """Snapshot a registry's series values (called each SLO tick).
        Bundles report the per-series delta between the oldest and
        newest retained capture — the movement across the incident's
        lead-up, not absolute counters."""
        text = registry.expose() if hasattr(registry, "expose") else registry
        parsed = parse_prometheus_text(text)
        flat = {}
        for (name, labels), value in parsed.items():
            series = name + (
                "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"
                if labels else ""
            )
            if _deterministic_series(series):
                flat[series] = value
        with self._lock:
            self._captures.append((self._clock(), flat))

    # -- triggers / bundling -------------------------------------------------

    def trigger(
        self, reason: str, detail: str = "", extra_header: dict | None = None
    ) -> str | None:
        """Fire a trigger rule: debounce, then atomically snapshot every
        ring + spans + metric deltas into an incident bundle. Returns
        the bundle path (or None when debounced / no sink_dir — the
        bundle lines are still retained in self.incidents)."""
        now = self._clock()
        with self._lock:
            last = self._last_trigger.get(reason)
            if last is not None and now - last < self.min_trigger_interval_s:
                self.metrics.suppressed.inc(trigger=reason)
                return None
            self._last_trigger[reason] = now
        lines = self.bundle_lines(reason, detail, extra_header)
        path = None
        if self.sink_dir:
            import os

            os.makedirs(self.sink_dir, exist_ok=True)
            fname = f"incident-{reason}-{int(now)}.jsonl"
            path = os.path.join(self.sink_dir, fname)
            with open(path, "w") as f:
                f.write("\n".join(lines) + "\n")
            logger.warning(
                "flight recorder dumped incident bundle %s (%s)",
                path, detail or reason,
            )
        self.incidents.append(
            {"t": now, "reason": reason, "detail": detail, "path": path,
             "lines": lines}
        )
        self.metrics.incidents.inc(trigger=reason)
        self.metrics.last_incident_ts.set(now)
        return path

    def bundle_lines(
        self, reason: str, detail: str = "",
        extra_header: dict | None = None,
    ) -> list[str]:
        """The incident bundle as sorted-key JSONL lines: a GameDayLog
        header (kind=gameday, bundle=incident, plus the replay context)
        followed by flight / span / metric_delta / exemplar records."""
        now = self._clock()
        tick = int(self.tick_fn()) if self.tick_fn is not None else 0
        with self._lock:
            events = [e for ring in self._rings.values() for e in ring]
            events = [dict(e) for e in events]
            spans = [dict(s) for s in self._spans]
            captures = list(self._captures)
            exemplars = {k: dict(v) for k, v in self._exemplars.items()}
        events.sort(key=lambda e: (e["t"], e["seq"]))
        header = {
            "kind": "gameday",
            "bundle": "incident",
            "reason": reason,
            "detail": detail,
            "t": now,
            "seed": 0,
            "ticks": 0,
            "events": [],
        }
        header.update(self.replay_context)
        if extra_header:
            header.update(extra_header)
        lines = [json.dumps(header, sort_keys=True)]
        for ev in events:
            rec = {"record": "flight", "tick": tick}
            rec.update(ev)
            lines.append(json.dumps(rec, sort_keys=True))
        for span in spans:
            rec = {"record": "span", "tick": tick}
            rec.update(span)
            lines.append(json.dumps(rec, sort_keys=True))
        if len(captures) >= 2:
            t0, base = captures[0]
            t1, cur = captures[-1]
            for series in sorted(set(base) | set(cur)):
                v0 = base.get(series, 0.0)
                v1 = cur.get(series, 0.0)
                if v1 != v0:
                    lines.append(json.dumps(
                        {
                            "record": "metric_delta", "tick": tick,
                            "series": series, "from": v0, "to": v1,
                            "delta": v1 - v0, "window_s": t1 - t0,
                        },
                        sort_keys=True,
                    ))
        for source in sorted(exemplars):
            lines.append(json.dumps(
                {
                    "record": "exemplar", "tick": tick, "source": source,
                    "exemplars": exemplars[source],
                },
                sort_keys=True,
            ))
        return lines

    # -- admin view ----------------------------------------------------------

    def state_payload(self) -> dict:
        with self._lock:
            rings = {name: len(ring) for name, ring in self._rings.items()}
            exemplars = {k: dict(v) for k, v in self._exemplars.items()}
        return {
            "rings": rings,
            "spans": len(self._spans),
            "metric_captures": len(self._captures),
            "exemplars": exemplars,
            "incidents": [
                {k: v for k, v in inc.items() if k != "lines"}
                for inc in self.incidents
            ],
        }
