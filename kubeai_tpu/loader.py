"""Model-artifact loader: `python -m kubeai_tpu.loader load <src> <dst>`.

The in-tree equivalent of the reference's loader container
(reference: components/model-loader/load.sh:1-67, used by cache Jobs at
internal/modelcontroller/cache.go:310-372 and the adapter sidecar). Same
contract: download <src> (hf/s3/gs/oss) into <dst>; when <dst> is itself
a URL, download to a temp dir then upload. The operator's cache Job
renders exactly `["load", <model url>, <cache dir>]`
(kubeai_tpu/operator/cache.py), with this module as the image
entrypoint. No cloud CLIs — kubeai_tpu.objstore speaks the wire
protocols directly.
"""

from __future__ import annotations

import argparse
import logging
import os
import shutil
import sys
import tempfile

from kubeai_tpu import objstore

logger = logging.getLogger("kubeai-tpu-loader")


class UnsupportedSchemeError(objstore.ObjStoreError):
    """Source/destination URL scheme the loader cannot speak."""


def _download_hf(repo_ref: str, dest: str) -> None:
    repo = repo_ref.split("?")[0]
    from huggingface_hub import snapshot_download

    snapshot_download(repo, local_dir=dest)
    # Parity with load.sh: drop the hub cache metadata from the artifact.
    cache = os.path.join(dest, ".cache")
    if os.path.isdir(cache):
        shutil.rmtree(cache, ignore_errors=True)


def download(src: str, dest_dir: str) -> None:
    os.makedirs(dest_dir, exist_ok=True)
    if src.startswith("hf://"):
        _download_hf(src[len("hf://"):], dest_dir)
    elif src.split("://")[0] in ("s3", "gs", "oss"):
        objstore.download_prefix(src, dest_dir)
    elif os.path.isdir(src):  # local-to-local (tests, pvc copies)
        shutil.copytree(src, dest_dir, dirs_exist_ok=True)
    else:
        raise UnsupportedSchemeError(f"Unsupported source url: {src}")


def upload(src_dir: str, dest: str) -> None:
    if dest.split("://")[0] in ("s3", "gs", "oss"):
        objstore.upload_dir(src_dir, dest)
    else:
        raise UnsupportedSchemeError(f"Unsupported destination url: {dest}")


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(prog="kubeai-tpu-loader")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("load", help="load <src> <dst>")
    p.add_argument("src")
    p.add_argument("dst")
    args = ap.parse_args(argv)

    try:
        if "://" in args.dst:
            with tempfile.TemporaryDirectory() as tmp:
                download(args.src, tmp)
                upload(tmp, args.dst)
        else:
            download(args.src, args.dst)
    except UnsupportedSchemeError as e:
        logger.error("%s", e)
        return 1
    logger.info("load complete: %s -> %s", args.src, args.dst)
    return 0


if __name__ == "__main__":
    sys.exit(main())
