"""Llama-family decoder (Llama 2/3/3.1) — the flagship text-generation model.

TPU-first choices:
  - Layers are *stacked* ([num_layers, ...] leading axis) and iterated with
    `lax.scan`: compile time is O(1) in depth (matters for 70B/80-layer),
    and XLA pipelines the per-layer HBM streaming.
  - Pure functional: params are a flat dict pytree; every leaf has a logical
    sharding spec (see `param_specs`) consumed by kubeai_tpu.parallel.
  - bfloat16 params/activations, float32 softmax/norm accumulations — MXU
    native precision.
  - GQA: q reshaped to [kv_heads, group] (see ops.attention), never repeated.

Capability parity: this replaces the Llama presets the reference serves via
vLLM images, e.g. `llama-3.1-8b-instruct-tpu` with --tensor-parallel-size=4
on google-tpu-v5e-2x2 (reference: charts/models/values.yaml:119-131). Here
TP is the `tp` mesh axis and XLA's collectives, not an engine flag.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kubeai_tpu.ops.norms import rms_norm
from kubeai_tpu.ops.rope import (
    apply_rope,
    rope_attention_scaling,
    rope_frequencies,
)
from kubeai_tpu.ops.attention import (
    causal_prefill_attention,
    decode_attention,
)
from kubeai_tpu.engine.quantization import dequantize as _w
from kubeai_tpu.parallel import sharding as sh


def _prefill_attention(q, k, v):
    """Pick the Pallas flash kernel on TPU for aligned long sequences; the
    jnp reference path otherwise (CPU tests, short/unaligned shapes)."""
    S = q.shape[1]
    if jax.default_backend() == "tpu" and S >= 256 and S % 128 == 0:
        from kubeai_tpu.ops.pallas_attention import flash_causal_prefill

        return flash_causal_prefill(q, k, v)
    return causal_prefill_attention(q, k, v)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int | None = None
    rope_theta: float = 500000.0
    rope_scaling: dict | None = None
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 131072
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # Qwen2-style q/k/v biases
    dtype: Any = jnp.bfloat16

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @staticmethod
    def from_hf_dict(d: dict) -> "LlamaConfig":
        """Build from a HuggingFace config.json dict (architectures Llama*)."""
        return LlamaConfig(
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_layers=d["num_hidden_layers"],
            num_heads=d["num_attention_heads"],
            num_kv_heads=d.get("num_key_value_heads", d["num_attention_heads"]),
            head_dim=d.get("head_dim"),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=d.get("rope_scaling"),
            rms_norm_eps=d.get("rms_norm_eps", 1e-5),
            max_position_embeddings=d.get("max_position_embeddings", 8192),
            tie_word_embeddings=d.get("tie_word_embeddings", False),
            # Qwen2 always uses qkv biases; HF exposes attention_bias on
            # both configs (Qwen2 defaults true, Llama false).
            attention_bias=d.get(
                "attention_bias",
                d.get("model_type") == "qwen2",
            ),
        )

    @staticmethod
    def tiny(vocab_size: int = 512) -> "LlamaConfig":
        """A test-sized config (runs in ms on CPU)."""
        return LlamaConfig(
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            rope_theta=10000.0,
            max_position_embeddings=1024,
        )


def param_specs(cfg: LlamaConfig) -> dict:
    """Logical sharding axes per parameter (leading axis = stacked layers,
    sharded over the pp mesh axis when it exists — replicated otherwise)."""
    L = sh.LAYERS
    layers = {
        "input_norm": (L, sh.EMBED),
        "wq": (L, sh.EMBED, sh.HEADS),
        "wk": (L, sh.EMBED, sh.KV_HEADS),
        "wv": (L, sh.EMBED, sh.KV_HEADS),
        "wo": (L, sh.HEADS, sh.EMBED),
        "post_attn_norm": (L, sh.EMBED),
        "w_gate": (L, sh.EMBED, sh.MLP),
        "w_up": (L, sh.EMBED, sh.MLP),
        "w_down": (L, sh.MLP, sh.EMBED),
    }
    if cfg.attention_bias:
        layers["bq"] = (L, sh.HEADS)
        layers["bk"] = (L, sh.KV_HEADS)
        layers["bv"] = (L, sh.KV_HEADS)
    return {
        "embed": (sh.VOCAB, sh.EMBED),
        "layers": layers,
        "final_norm": (sh.EMBED,),
        "lm_head": (sh.VOCAB, sh.EMBED),
    }


def init_params(cfg: LlamaConfig, key: jax.Array | None = None) -> dict:
    """Random init (for tests and benchmarks; real weights come from
    kubeai_tpu.engine.weights loaders)."""
    if key is None:
        key = jax.random.PRNGKey(0)
    E, H, KVH, D, M, V, NL = (
        cfg.hidden_size,
        cfg.num_heads,
        cfg.num_kv_heads,
        cfg.head_size,
        cfg.intermediate_size,
        cfg.vocab_size,
        cfg.num_layers,
    )
    ks = jax.random.split(key, 10)
    scale = 0.02
    dt = cfg.dtype

    def rnd(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    layers = {
        "input_norm": jnp.ones((NL, E), dt),
        "wq": rnd(ks[1], (NL, E, H * D)),
        "wk": rnd(ks[2], (NL, E, KVH * D)),
        "wv": rnd(ks[3], (NL, E, KVH * D)),
        "wo": rnd(ks[4], (NL, H * D, E)),
        "post_attn_norm": jnp.ones((NL, E), dt),
        "w_gate": rnd(ks[5], (NL, E, M)),
        "w_up": rnd(ks[6], (NL, E, M)),
        "w_down": rnd(ks[7], (NL, M, E)),
    }
    if cfg.attention_bias:
        layers["bq"] = rnd(ks[9], (NL, H * D))
        layers["bk"] = jnp.zeros((NL, KVH * D), dt)
        layers["bv"] = jnp.zeros((NL, KVH * D), dt)
    params = {
        "embed": rnd(ks[0], (V, E)),
        "layers": layers,
        "final_norm": jnp.ones((E,), dt),
        "lm_head": rnd(ks[8], (V, E)),
    }
    if cfg.tie_word_embeddings:
        params["lm_head"] = params["embed"]
    return params


def _mlp(x, gate, up, down):
    return jnp.einsum(
        "bsm,me->bse", jax.nn.silu(jnp.einsum("bse,em->bsm", x, _w(gate)))
        * jnp.einsum("bse,em->bsm", x, _w(up)),
        _w(down),
    )


# ---- LoRA (hot-swappable, batched) ------------------------------------------
#
# Adapter weights live in fixed-shape stacked buffers so loading/unloading an
# adapter is a buffer update, never a recompile (the hot-swap requirement the
# reference meets through vLLM's dynamic LoRA API —
# reference: internal/vllmclient/client.go:30-73, adapters.go:24-118):
#
#   A[target]: [n_adapters, NL, E, r_max]    B[target]: [n_adapters, NL, r_max, out]
#
# Adapter index 0 is reserved as all-zeros ("no adapter"); per-request
# adapter selection is a gather over the adapter axis, so one batched decode
# serves a mix of adapters (punica-style batching, MXU-friendly).

LORA_TARGETS = ("wq", "wk", "wv", "wo")


def init_lora_buffers(
    cfg: LlamaConfig, n_adapters: int, max_rank: int, dtype=None
) -> dict:
    dtype = dtype or cfg.dtype
    E, H, KVH, D = (
        cfg.hidden_size,
        cfg.num_heads,
        cfg.num_kv_heads,
        cfg.head_size,
    )
    NL = cfg.num_layers
    out_dims = {"wq": H * D, "wk": KVH * D, "wv": KVH * D, "wo": E}
    in_dims = {"wq": E, "wk": E, "wv": E, "wo": H * D}
    bufs = {}
    for t in LORA_TARGETS:
        bufs[t] = {
            "A": jnp.zeros((n_adapters, NL, in_dims[t], max_rank), dtype),
            "B": jnp.zeros((n_adapters, NL, max_rank, out_dims[t]), dtype),
        }
    return bufs


def _lora_delta(x, A, B, idx):
    """x: [B, S, in] (or [B, in]); A: [n, in, r], B: [n, r, out] for ONE
    layer; idx: [B] adapter index per row. Returns the low-rank delta."""
    Ag = A[idx]  # [B, in, r]
    Bg = B[idx]  # [B, r, out]
    if x.ndim == 2:
        xa = jnp.einsum("be,ber->br", x, Ag)
        return jnp.einsum("br,bro->bo", xa, Bg)
    xa = jnp.einsum("bse,ber->bsr", x, Ag)
    return jnp.einsum("bsr,bro->bso", xa, Bg)


def _scan_xs(params: dict, lora: dict | None):
    """Build scan inputs: per-layer params plus (optionally) per-layer LoRA
    slices. Adapter axis moves behind the layer axis so lax.scan slices
    layers: [n, NL, ...] -> [NL, n, ...]."""
    if lora is None:
        return {"p": params["layers"]}
    return {
        "p": params["layers"],
        "l": {
            t: {
                "A": jnp.moveaxis(lora[t]["A"], 1, 0),
                "B": jnp.moveaxis(lora[t]["B"], 1, 0),
            }
            for t in LORA_TARGETS
        },
    }


def prefill(
    params: dict,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B, S] int32, right-padded
    lengths: jnp.ndarray,  # [B] true prompt lengths
    lora: dict | None = None,  # stacked adapter buffers (init_lora_buffers)
    lora_idx: jnp.ndarray | None = None,  # [B] adapter index (0 = none)
    mesh=None,  # Mesh with an sp axis > 1 → ring-attention prefill
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full-prompt forward. Returns (last_token_logits [B, V],
    k_all [NL, B, S, KVH, D], v_all [NL, B, S, KVH, D]).

    The caller inserts the returned KV into the slot cache
    (kubeai_tpu.engine.kvcache.insert_sequence).

    Long-context serving: when `mesh` carries an sp axis of size > 1 (and
    the padded length divides by it), prefill attention runs as RING
    ATTENTION with the sequence sharded over sp — each device holds S/sp
    of the prompt and K/V rotate over ICI (parallel/ring_attention.py).
    The engine passes its mesh automatically, making sp a serving-path
    knob rather than a demo.
    """
    B, S = tokens.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    use_ring = sp > 1 and S % sp == 0 and (S // sp) >= 1
    if use_ring:
        from kubeai_tpu.parallel.ring_attention import ring_attention_sharded

        def attend(q, k, v):
            return ring_attention_sharded(q, k, v, mesh)
    else:
        attend = _prefill_attention
    inv_freq = jnp.asarray(
        rope_frequencies(
            D, cfg.rope_theta, cfg.rope_scaling,
            cfg.max_position_embeddings,
        )
    )
    msc = rope_attention_scaling(cfg.rope_scaling)
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    x = params["embed"][tokens]  # gather: [B, S, E]

    def layer(x, scanned):
        lp = scanned["p"]
        lor = scanned.get("l")

        def proj(h, w, target, bias=None):
            out = jnp.einsum("bse,eh->bsh", h, _w(w))
            if bias is not None:
                out = out + bias
            if lor is not None:
                out = out + _lora_delta(
                    h, lor[target]["A"], lor[target]["B"], lora_idx
                )
            return out

        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = proj(h, lp["wq"], "wq", lp.get("bq")).reshape(B, S, H, D)
        k = proj(h, lp["wk"], "wk", lp.get("bk")).reshape(B, S, KVH, D)
        v = proj(h, lp["wv"], "wv", lp.get("bv")).reshape(B, S, KVH, D)
        q = apply_rope(q, positions, inv_freq, msc)
        k = apply_rope(k, positions, inv_freq, msc)
        attn = attend(q, k, v)
        x = x + proj(attn.reshape(B, S, H * D), lp["wo"], "wo")
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(layer, x, _scan_xs(params, lora))
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    # Logits only for each sequence's final real token.
    idx = jnp.clip(lengths - 1, 0, S - 1)
    last = jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]  # [B, E]
    # bf16 matmul, fp32 accumulation: MXU-native, no fp32 weight copy.
    logits = jnp.einsum(
        "be,ve->bv", last, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_all, v_all


def decode_step(
    params: dict,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B] one token per slot
    positions: jnp.ndarray,  # [B] absolute position of each token
    k_cache: jnp.ndarray,  # [NL, B, L, KVH, D]
    v_cache: jnp.ndarray,  # [NL, B, L, KVH, D]
    lora: dict | None = None,  # stacked adapter buffers
    lora_idx: jnp.ndarray | None = None,  # [B] adapter index per slot
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decode step for every active slot. Writes the new token's KV into
    the cache (functional update) and returns (logits [B, V], k_cache, v_cache).
    """
    B = tokens.shape[0]
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(
        rope_frequencies(
            D, cfg.rope_theta, cfg.rope_scaling,
            cfg.max_position_embeddings,
        )
    )
    msc = rope_attention_scaling(cfg.rope_scaling)
    x = params["embed"][tokens]  # [B, E]
    pos1 = positions[:, None]  # [B, 1]
    lengths = positions + 1  # cache valid length incl. this token
    slot_idx = jnp.arange(B)

    def layer(carry, scanned):
        x = carry
        lp = scanned["p"]
        lor = scanned.get("l")
        kc, vc = scanned["kc"], scanned["vc"]

        def proj(h, w, target, bias=None):
            out = jnp.einsum("be,eh->bh", h, _w(w))
            if bias is not None:
                out = out + bias
            if lor is not None:
                out = out + _lora_delta(
                    h, lor[target]["A"], lor[target]["B"], lora_idx
                )
            return out

        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = proj(h, lp["wq"], "wq", lp.get("bq")).reshape(B, 1, H, D)
        k = proj(h, lp["wk"], "wk", lp.get("bk")).reshape(B, 1, KVH, D)
        v = proj(h, lp["wv"], "wv", lp.get("bv")).reshape(B, 1, KVH, D)
        q = apply_rope(q, pos1, inv_freq, msc)[:, 0]  # [B, H, D]
        k = apply_rope(k, pos1, inv_freq, msc)[:, 0]  # [B, KVH, D]
        v = v[:, 0]
        # Scatter the new token's K/V into each slot at its position.
        kc = kc.at[slot_idx, positions].set(k.astype(kc.dtype))
        vc = vc.at[slot_idx, positions].set(v.astype(vc.dtype))
        attn = decode_attention(q, kc, vc, lengths)  # [B, H, D]
        x = x + proj(attn.reshape(B, H * D), lp["wo"], "wo")
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h2[:, None], lp["w_gate"], lp["w_up"], lp["w_down"])[:, 0]
        return x, (kc, vc)

    xs = _scan_xs(params, lora)
    xs["kc"] = k_cache
    xs["vc"] = v_cache
    x, (k_cache, v_cache) = jax.lax.scan(layer, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "be,ve->bv", x, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_cache, v_cache


def _decode_layer_qkv(x, lp, lor, cfg, inv_freq, msc, pos1, lora_idx):
    """Shared decode-layer front half: norm, QKV projection (+bias/LoRA),
    rope. Returns (q [B,H,D], k [B,KVH,D], v [B,KVH,D], proj) where proj
    is reused for the output projection. One body for every paged decode
    layout — decode_step_paged's fused AND per_layer branches, and the
    pipeline path (_paged_decode_layer) — so the projection/LoRA math
    cannot drift between them."""
    B = x.shape[0]
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size

    def proj(h, w, target, bias=None):
        out = jnp.einsum("be,eh->bh", h, _w(w))
        if bias is not None:
            out = out + bias
        if lor is not None:
            out = out + _lora_delta(
                h, lor[target]["A"], lor[target]["B"], lora_idx
            )
        return out

    h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
    q = proj(h, lp["wq"], "wq", lp.get("bq")).reshape(B, 1, H, D)
    k = proj(h, lp["wk"], "wk", lp.get("bk")).reshape(B, 1, KVH, D)
    v = proj(h, lp["wv"], "wv", lp.get("bv")).reshape(B, 1, KVH, D)
    q = apply_rope(q, pos1, inv_freq, msc)[:, 0]  # [B, H, D]
    k = apply_rope(k, pos1, inv_freq, msc)[:, 0]  # [B, KVH, D]
    return q, k, v[:, 0], proj


def _decode_layer_finish(x, attn, lp, proj, cfg):
    """Shared decode-layer back half: output projection, residual, MLP."""
    B = x.shape[0]
    H, D = cfg.num_heads, cfg.head_size
    x = x + proj(attn.reshape(B, H * D), lp["wo"], "wo")
    h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
    x = x + _mlp(h2[:, None], lp["w_gate"], lp["w_up"], lp["w_down"])[:, 0]
    return x


def _paged_decode_layer(
    x, scanned, cfg, inv_freq, msc, positions, lengths,
    page_ids, offsets, block_tables, lora_idx,
):
    """One decode layer against per-layer page pools: project, rope,
    scatter the new token's K/V through the block tables, attend over
    resident pages, MLP. Used by decode_step_paged's "per_layer" layout
    (pools ride the layer scan as xs/ys) and by decode_step_paged_pp
    (stage-local scan inside the GPipe shard_map, pools are stage-local
    scan carries); the fused layout shares the projection/MLP halves via
    _decode_layer_qkv/_decode_layer_finish but attends through the fused
    kernel with a deferred scatter."""
    from kubeai_tpu.ops.paged_attention import (
        paged_decode_attention,
        scatter_decode_token,
    )

    lp = scanned["p"]
    lor = scanned.get("l")
    kp, vp = scanned["kp"], scanned["vp"]
    q, k, v, proj = _decode_layer_qkv(
        x, lp, lor, cfg, inv_freq, msc, positions[:, None], lora_idx
    )
    kp, vp = scatter_decode_token(kp, vp, k, v, page_ids, offsets)
    attn = paged_decode_attention(q, kp, vp, block_tables, lengths)
    x = _decode_layer_finish(x, attn, lp, proj, cfg)
    return x, (kp, vp)


def decode_step_paged(
    params: dict,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B] one token per slot
    positions: jnp.ndarray,  # [B] absolute position of each token
    k_pages: jnp.ndarray,  # [NL, P, page, KVH, D] page pools
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP] page ids per slot (-1 = free)
    lora: dict | None = None,
    lora_idx: jnp.ndarray | None = None,
    *,
    attn_kernel: str | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Decode step against the PAGED cache. Two attention layouts,
    selected by `attn_kernel` (None = $KUBEAI_TPU_DECODE_KERNEL, default
    "per_layer"; see ops.paged_attention.default_decode_kernel):

    "per_layer" — scatter-then-attend inside the layer scan: the stacked
    pools ride the scan as xs/ys and each layer runs the per-layer Pallas
    kernel (paged_decode_attention). Hardware-validated: 1975.5 tok/s/chip
    at bs=64 on the 1B proxy (round 2).

    "fused" — the stacked [NL, ...] page pools stay OUTSIDE the layer scan
    and are read by the fused Pallas kernel straight from HBM via a
    scalar-prefetched layer index — the per-layer layout round-trips the
    entire pool (GBs) through slice + re-stack every decode step and
    materializes each slice to feed the opaque pallas_call. The new
    token's K/V is folded in as an extra attention column (it is NOT in
    the pool yet), collected per layer, and written back in ONE batched
    scatter after the scan — per-step cache write traffic is O(NL * B)
    tokens, and read traffic is only each slot's resident pages.
    Roofline-better, but not yet validated on real hardware (its first
    on-chip dispatch hung) — it stays opt-in until a real-TPU A/B clears
    it.

    Both layouts share _decode_layer_qkv/_decode_layer_finish, so the
    projection/LoRA/MLP math cannot drift between them."""
    from kubeai_tpu.ops.kv_quant import is_quantized_kv, kv_pages_shape
    from kubeai_tpu.ops.paged_attention import (
        batched_scatter_sequence,
        paged_decode_attention_fused,
        resolve_decode_kernel,
        token_page_coords,
    )

    attn_kernel = resolve_decode_kernel(attn_kernel)
    if is_quantized_kv(k_pages) and attn_kernel != "per_layer":
        raise ValueError(
            "quantized KV pools require attn_kernel='per_layer' (the "
            "fused kernel reads a raw bf16 pool)"
        )
    inv_freq = jnp.asarray(
        rope_frequencies(
            cfg.head_size, cfg.rope_theta, cfg.rope_scaling,
            cfg.max_position_embeddings,
        )
    )
    msc = rope_attention_scaling(cfg.rope_scaling)
    page_size = kv_pages_shape(k_pages)[2]
    x = params["embed"][tokens]  # [B, E]
    page_ids, offsets = token_page_coords(block_tables, positions, page_size)
    pos1 = positions[:, None]
    xs = _scan_xs(params, lora)

    if attn_kernel == "per_layer":
        lengths = positions + 1

        def layer_pl(carry, scanned):
            return _paged_decode_layer(
                carry, scanned, cfg, inv_freq, msc, positions, lengths,
                page_ids, offsets, block_tables, lora_idx,
            )

        xs["kp"] = k_pages
        xs["vp"] = v_pages
        x, (k_pages, v_pages) = jax.lax.scan(layer_pl, x, xs)
    else:

        def layer(carry, scanned):
            x = carry
            lp = scanned["p"]
            lor = scanned.get("l")
            q, k, v, proj = _decode_layer_qkv(
                x, lp, lor, cfg, inv_freq, msc, pos1, lora_idx
            )
            attn = paged_decode_attention_fused(
                q, k_pages, v_pages, k, v, block_tables, positions,
                scanned["li"],
            )
            x = _decode_layer_finish(x, attn, lp, proj, cfg)
            return x, (k, v)

        xs["li"] = jnp.arange(cfg.num_layers, dtype=jnp.int32)
        x, (k_all, v_all) = jax.lax.scan(layer, x, xs)
        # One batched write for every layer's new token ([NL, B, KVH, D]).
        k_pages, v_pages = batched_scatter_sequence(
            k_pages, v_pages, k_all[:, :, None], v_all[:, :, None],
            page_ids[:, None], offsets[:, None],
        )

    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "be,ve->bv", x, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_pages, v_pages


def decode_step_paged_pp(
    params: dict,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B] one token per slot
    positions: jnp.ndarray,  # [B]
    k_pages: jnp.ndarray,  # [NL, P, page, KVH, D], layer axis sharded on pp
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP]
    lora: dict | None = None,
    lora_idx: jnp.ndarray | None = None,
    *,
    mesh,
    microbatches: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipeline-parallel paged decode: GPipe microbatching over the pp
    mesh axis with STAGE-LOCAL KV. Stage s owns layers [s*NL/P, (s+1)*NL/P)
    — both their weights and their page pools (the [NL, ...] leading axis
    of params["layers"] and the pools shards over pp, see param_specs /
    Engine pool_sharding) — so cache reads/writes never cross stages;
    only [mb, E] activations hop stage-to-stage via ppermute.

    Numerics are identical to decode_step_paged (tested): same per-layer
    math, same scatter-before-attend ordering per microbatch; off-schedule
    ticks compute on clamped duplicate microbatches and their cache writes
    are redirected to reserved scratch page 0 (the same sink
    token_page_coords uses for unallocated entries).

    The reference has no PP anywhere (engines are single-Pod opaque,
    internal/modelcontroller/pod_plan.go:28-156); SURVEY §2's
    TPU-equivalents list makes PP for >8B this repo's obligation.
    """
    from jax.sharding import PartitionSpec as P

    from kubeai_tpu.ops.paged_attention import token_page_coords
    from kubeai_tpu.parallel.mesh import AXIS_PIPELINE

    B = tokens.shape[0]
    M = microbatches
    if M < 1 or B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    n_stages = mesh.shape[AXIS_PIPELINE]
    NL = k_pages.shape[0]
    if NL % n_stages:
        raise ValueError(f"{NL} layers not divisible by {n_stages} pp stages")
    page_size = k_pages.shape[2]
    inv_freq = jnp.asarray(
        rope_frequencies(
            cfg.head_size, cfg.rope_theta, cfg.rope_scaling,
            cfg.max_position_embeddings,
        )
    )
    msc = rope_attention_scaling(cfg.rope_scaling)
    lengths = positions + 1
    page_ids, offsets = token_page_coords(block_tables, positions, page_size)
    if lora_idx is None:
        lora_idx = jnp.zeros((B,), jnp.int32)

    mb = B // M

    def mbt(a):
        return a.reshape(M, mb, *a.shape[1:])

    x_mb = mbt(params["embed"][tokens])  # [M, mb, E]
    pos_mb, len_mb = mbt(positions), mbt(lengths)
    pid_mb, off_mb = mbt(page_ids), mbt(offsets)
    bt_mb, lidx_mb = mbt(block_tables), mbt(lora_idx)

    xs = _scan_xs(params, lora)
    xs_specs = jax.tree_util.tree_map(lambda _: P(AXIS_PIPELINE), xs)
    rep = P()

    # tp > 1 composes via PARTIAL-manual shard_map: manual collectives
    # over pp only, while tp (Megatron-sharded projections and KV heads)
    # stays under GSPMD, which keeps inserting its own collectives inside
    # the stage body — this is what lets pp compose with tp (the
    # 70B-on-v5e-8 plan: pp=2 × tp=4) without hand-writing the
    # tensor-parallel psums. With tp == 1 the shard_map stays FULLY
    # manual (the pre-composition behavior): partial-manual changes XLA's
    # fusion choices inside the body, which reorders bf16 rounding enough
    # to flip near-tie samples vs the single-device engine — keep pure-pp
    # deployments bit-stable.
    # NOTE: no jax.lax.psum over pp in the body — psum over the manual
    # axis of a partial-manual shard_map crashes XLA's partitioners (both
    # Shardy and GSPMD, jax 0.9: "Invalid binary instruction opcode
    # copy"); the stage outputs are stacked via out_specs instead and the
    # last stage selected outside.
    tp_size = mesh.shape.get("tp", 1)
    manual_kw = (
        {"axis_names": {AXIS_PIPELINE}, "check_vma": True}
        if tp_size > 1 else {"check_vma": False}
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            xs_specs, P(AXIS_PIPELINE), P(AXIS_PIPELINE),
            rep, rep, rep, rep, rep, rep, rep,
        ),
        out_specs=(
            P(AXIS_PIPELINE), P(AXIS_PIPELINE), P(AXIS_PIPELINE),
        ),
        **manual_kw,
    )
    def run(xs, kp, vp, x_mb, pos_mb, len_mb, pid_mb, off_mb, bt_mb, lidx_mb):
        stage = jax.lax.axis_index(AXIS_PIPELINE)
        last = n_stages - 1
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def local_layers(h, kp, vp, pos, lens, pid, off, bt, lidx):
            """One pass through this stage's layer slice; returns updated
            local pools. Same per-layer body as decode_step_paged
            (_paged_decode_layer), so the paths cannot drift."""

            def layer(carry, scanned):
                return _paged_decode_layer(
                    carry, scanned, cfg, inv_freq, msc, pos, lens,
                    pid, off, bt, lidx,
                )

            xs_l = dict(xs)
            xs_l["kp"] = kp
            xs_l["vp"] = vp
            y, (kp, vp) = jax.lax.scan(layer, h, xs_l)
            return y, kp, vp

        ticks = M + n_stages - 1

        def tick(carry, t):
            buf, kp, vp, out = carry
            idx = jnp.clip(t - stage, 0, M - 1)
            active = (t - stage >= 0) & (t - stage < M)
            h = jnp.where(stage == 0, x_mb[jnp.clip(t, 0, M - 1)], buf)
            # Off-schedule ticks recompute a clamped duplicate microbatch;
            # their K/V writes sink into reserved scratch page 0.
            pid = jnp.where(active, pid_mb[idx], 0)
            off = jnp.where(active, off_mb[idx], 0)
            y, kp, vp = local_layers(
                h, kp, vp, pos_mb[idx], len_mb[idx], pid, off,
                bt_mb[idx], lidx_mb[idx],
            )
            mb_out = t - last
            store = (stage == last) & (mb_out >= 0)
            out = jnp.where(
                store, out.at[jnp.clip(mb_out, 0, M - 1)].set(y), out
            )
            buf = jax.lax.ppermute(y, AXIS_PIPELINE, fwd)
            return (buf, kp, vp, out), None

        # The activation buffer and output accumulator START identical on
        # every stage but become stage-varying inside the scan (ppermute /
        # stage-gated writes): mark them varying over pp up front so the
        # scan carry types are stable under vma tracking.
        zero = jax.lax.pcast(
            jnp.zeros_like(x_mb[0]), AXIS_PIPELINE, to="varying"
        )
        out0 = jax.lax.pcast(
            jnp.zeros_like(x_mb), AXIS_PIPELINE, to="varying"
        )
        (_, kp, vp, out), _ = jax.lax.scan(
            tick, (zero, kp, vp, out0), jnp.arange(ticks)
        )
        return out[None], kp, vp  # [1, M, mb, E] per stage

    hidden, k_pages, v_pages = run(
        xs, k_pages, v_pages, x_mb, pos_mb, len_mb, pid_mb, off_mb,
        bt_mb, lidx_mb,
    )
    # hidden is [n_stages, M, mb, E]; only the LAST stage stored real
    # microbatch outputs (the other stages' accumulators are zeros).
    x = hidden[-1].reshape(B, -1)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "be,ve->bv", x, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_pages, v_pages


def trunk_layer(x: jnp.ndarray, lp: dict, cfg: LlamaConfig) -> jnp.ndarray:
    """One trunk layer [B, S, E] -> [B, S, E] (per-layer params `lp`).
    Module-level (not a closure) so pipeline parallelism can stage it
    (parallel/pipeline.py shards the stacked layer axis over pp)."""
    B, S, _ = x.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(rope_frequencies(
        D, cfg.rope_theta, cfg.rope_scaling, cfg.max_position_embeddings,
    ))
    msc = rope_attention_scaling(cfg.rope_scaling)
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
    q = jnp.einsum("bse,eh->bsh", h, _w(lp["wq"]))
    if "bq" in lp:
        q = q + lp["bq"]
    k = jnp.einsum("bse,eh->bsh", h, _w(lp["wk"]))
    if "bk" in lp:
        k = k + lp["bk"]
    v = jnp.einsum("bse,eh->bsh", h, _w(lp["wv"]))
    if "bv" in lp:
        v = v + lp["bv"]
    q = apply_rope(q.reshape(B, S, H, D), positions, inv_freq, msc)
    k = apply_rope(k.reshape(B, S, KVH, D), positions, inv_freq, msc)
    attn = _prefill_attention(q, k, v.reshape(B, S, KVH, D))
    x = x + jnp.einsum("bsh,he->bse", attn.reshape(B, S, H * D), _w(lp["wo"]))
    h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
    return x + _mlp(h2, lp["w_gate"], lp["w_up"], lp["w_down"])


def _verify_page_coords(block_tables, positions, K, page_size):
    """Page coords for all K window positions per slot: ([B, K], [B, K])."""
    from kubeai_tpu.ops.paged_attention import token_page_coords

    ids_list, offs_list = [], []
    for k_i in range(K):
        ids, offs = token_page_coords(
            block_tables, positions + k_i, page_size
        )
        ids_list.append(ids)
        offs_list.append(offs)
    return jnp.stack(ids_list, axis=1), jnp.stack(offs_list, axis=1)


def _paged_verify_layer(
    carry, scanned, cfg, inv_freq, msc, pos_k, page_ids, offsets,
    block_tables, positions, lora_idx,
):
    """One verify layer over a [B, K, E] window against the paged cache.
    Shared by decode_verify_paged (layer scan over the full stack) and
    decode_verify_paged_pp (stage-local layer scans) so the speculative
    math cannot drift between the single-mesh and pipeline paths — the
    same anti-drift guarantee _paged_decode_layer gives vanilla decode."""
    from kubeai_tpu.ops.paged_attention import paged_verify_attention

    x = carry
    B, K, _ = x.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    lp = scanned["p"]
    lor = scanned.get("l")
    kp, vp = scanned["kp"], scanned["vp"]

    def proj(h, w, target, bias=None):
        out = jnp.einsum("bke,eh->bkh", h, _w(w))
        if bias is not None:
            out = out + bias
        if lor is not None:
            out = out + _lora_delta(
                h, lor[target]["A"], lor[target]["B"], lora_idx
            )
        return out

    h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
    q = proj(h, lp["wq"], "wq", lp.get("bq")).reshape(B, K, H, D)
    k = proj(h, lp["wk"], "wk", lp.get("bk")).reshape(B, K, KVH, D)
    v = proj(h, lp["wv"], "wv", lp.get("bv")).reshape(B, K, KVH, D)
    q = apply_rope(q, pos_k, inv_freq, msc)
    k = apply_rope(k, pos_k, inv_freq, msc)
    kp = kp.at[page_ids, offsets].set(k.astype(kp.dtype))
    vp = vp.at[page_ids, offsets].set(v.astype(vp.dtype))
    attn = paged_verify_attention(q, kp, vp, block_tables, positions)
    x = x + proj(attn.reshape(B, K, H * D), lp["wo"], "wo")
    h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
    x = x + _mlp(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
    return x, (kp, vp)


def decode_verify_paged(
    params: dict,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B, K] — last emitted token + K-1 proposals
    positions: jnp.ndarray,  # [B] absolute position of tokens[:, 0]
    k_pages: jnp.ndarray,  # [NL, P, page, KVH, D]
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP]
    lora: dict | None = None,
    lora_idx: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """SPECULATIVE VERIFY: one forward over a K-token window per slot
    against the paged cache. Writes the window's KV through the block
    tables (rejected tail positions hold garbage that the per-slot
    position pointer masks and later steps overwrite) and returns logits
    for EVERY window position [B, K, V] so the engine can accept the
    longest matching proposal prefix (engine.py speculative mode).
    Attention dispatches to the multi-query paged Pallas kernel on TPU,
    gather reference elsewhere (ops/paged_attention.py)."""
    B, K = tokens.shape
    page_size = k_pages.shape[2]
    inv_freq = jnp.asarray(
        rope_frequencies(
            cfg.head_size, cfg.rope_theta, cfg.rope_scaling,
            cfg.max_position_embeddings,
        )
    )
    msc = rope_attention_scaling(cfg.rope_scaling)
    pos_k = positions[:, None] + jnp.arange(K)[None, :]  # [B, K]
    x = params["embed"][tokens]  # [B, K, E]
    page_ids, offsets = _verify_page_coords(
        block_tables, positions, K, page_size
    )

    def layer(carry, scanned):
        return _paged_verify_layer(
            carry, scanned, cfg, inv_freq, msc, pos_k, page_ids, offsets,
            block_tables, positions, lora_idx,
        )

    xs = _scan_xs(params, lora)
    xs["kp"] = k_pages
    xs["vp"] = v_pages
    x, (k_pages, v_pages) = jax.lax.scan(layer, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "bke,ve->bkv", x, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_pages, v_pages


def decode_verify_paged_pp(
    params: dict,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B, K] — last emitted token + K-1 proposals
    positions: jnp.ndarray,  # [B]
    k_pages: jnp.ndarray,  # [NL, P, page, KVH, D], layer axis sharded on pp
    v_pages: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, MP]
    lora: dict | None = None,
    lora_idx: jnp.ndarray | None = None,
    *,
    mesh,
    microbatches: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Speculative verify under pipeline parallelism: the same GPipe
    schedule as decode_step_paged_pp (stage-local layers + stage-local KV,
    [mb, K, E] activations hopping via ppermute), with the per-layer math
    shared through _paged_verify_layer — so a pp engine speculates with
    the identical accept/reject semantics the single-mesh engine has.
    Off-schedule ticks recompute clamped duplicate microbatches; their
    cache writes sink into reserved scratch page 0.

    Reference analog: none (the reference has neither PP nor speculation —
    vLLM flags ride Model.spec.args, api/k8s/v1/model_types.go:85-90);
    SURVEY §2's TPU-equivalents list makes both this repo's obligation.
    """
    from jax.sharding import PartitionSpec as P

    from kubeai_tpu.parallel.mesh import AXIS_PIPELINE

    B, K = tokens.shape
    M = microbatches
    if M < 1 or B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    n_stages = mesh.shape[AXIS_PIPELINE]
    NL = k_pages.shape[0]
    if NL % n_stages:
        raise ValueError(f"{NL} layers not divisible by {n_stages} pp stages")
    page_size = k_pages.shape[2]
    inv_freq = jnp.asarray(
        rope_frequencies(
            cfg.head_size, cfg.rope_theta, cfg.rope_scaling,
            cfg.max_position_embeddings,
        )
    )
    msc = rope_attention_scaling(cfg.rope_scaling)
    pos_k = positions[:, None] + jnp.arange(K)[None, :]  # [B, K]
    page_ids, offsets = _verify_page_coords(
        block_tables, positions, K, page_size
    )
    if lora_idx is None:
        lora_idx = jnp.zeros((B,), jnp.int32)

    mb = B // M

    def mbt(a):
        return a.reshape(M, mb, *a.shape[1:])

    x_mb = mbt(params["embed"][tokens])  # [M, mb, K, E]
    pos_mb, posk_mb = mbt(positions), mbt(pos_k)
    pid_mb, off_mb = mbt(page_ids), mbt(offsets)
    bt_mb, lidx_mb = mbt(block_tables), mbt(lora_idx)

    xs = _scan_xs(params, lora)
    xs_specs = jax.tree_util.tree_map(lambda _: P(AXIS_PIPELINE), xs)
    rep = P()

    # Same partial-manual vs fully-manual split as decode_step_paged_pp
    # (and the same XLA landmines documented there).
    tp_size = mesh.shape.get("tp", 1)
    manual_kw = (
        {"axis_names": {AXIS_PIPELINE}, "check_vma": True}
        if tp_size > 1 else {"check_vma": False}
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            xs_specs, P(AXIS_PIPELINE), P(AXIS_PIPELINE),
            rep, rep, rep, rep, rep, rep, rep,
        ),
        out_specs=(
            P(AXIS_PIPELINE), P(AXIS_PIPELINE), P(AXIS_PIPELINE),
        ),
        **manual_kw,
    )
    def run(xs, kp, vp, x_mb, pos_mb, posk_mb, pid_mb, off_mb, bt_mb, lidx_mb):
        stage = jax.lax.axis_index(AXIS_PIPELINE)
        last = n_stages - 1
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def local_layers(h, kp, vp, pos, posk, pid, off, bt, lidx):
            def layer(carry, scanned):
                return _paged_verify_layer(
                    carry, scanned, cfg, inv_freq, msc, posk, pid, off,
                    bt, pos, lidx,
                )

            xs_l = dict(xs)
            xs_l["kp"] = kp
            xs_l["vp"] = vp
            y, (kp, vp) = jax.lax.scan(layer, h, xs_l)
            return y, kp, vp

        ticks = M + n_stages - 1

        def tick(carry, t):
            buf, kp, vp, out = carry
            idx = jnp.clip(t - stage, 0, M - 1)
            active = (t - stage >= 0) & (t - stage < M)
            h = jnp.where(stage == 0, x_mb[jnp.clip(t, 0, M - 1)], buf)
            pid = jnp.where(active, pid_mb[idx], 0)
            off = jnp.where(active, off_mb[idx], 0)
            y, kp, vp = local_layers(
                h, kp, vp, pos_mb[idx], posk_mb[idx], pid, off,
                bt_mb[idx], lidx_mb[idx],
            )
            mb_out = t - last
            store = (stage == last) & (mb_out >= 0)
            out = jnp.where(
                store, out.at[jnp.clip(mb_out, 0, M - 1)].set(y), out
            )
            buf = jax.lax.ppermute(y, AXIS_PIPELINE, fwd)
            return (buf, kp, vp, out), None

        zero = jax.lax.pcast(
            jnp.zeros_like(x_mb[0]), AXIS_PIPELINE, to="varying"
        )
        out0 = jax.lax.pcast(
            jnp.zeros_like(x_mb), AXIS_PIPELINE, to="varying"
        )
        (_, kp, vp, out), _ = jax.lax.scan(
            tick, (zero, kp, vp, out0), jnp.arange(ticks)
        )
        return out[None], kp, vp  # [1, M, mb, K, E] per stage

    hidden, k_pages, v_pages = run(
        xs, k_pages, v_pages, x_mb, pos_mb, posk_mb, pid_mb, off_mb,
        bt_mb, lidx_mb,
    )
    # hidden is [n_stages, M, mb, K, E]; only the LAST stage stored real
    # outputs.
    x = hidden[-1].reshape(B, K, -1)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "bke,ve->bkv", x, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_pages, v_pages


def _trunk(params: dict, cfg: LlamaConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Transformer trunk: [B, S] tokens -> [B, S, E] final hidden states."""
    x = params["embed"][tokens]
    x, _ = jax.lax.scan(
        lambda h, lp: (trunk_layer(h, lp, cfg), None), x, params["layers"]
    )
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)


def hidden_states(
    params: dict,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B, S] right-padded
    lengths: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Mean-pooled, L2-normalized embeddings [B, E] — the TextEmbedding
    feature (the reference delegates embeddings to Infinity Pods,
    reference: internal/modelcontroller/engine_infinity.go; here any causal
    model doubles as an embedder)."""
    x = _trunk(params, cfg, tokens)  # [B, S, E]
    S = tokens.shape[1]
    mask = (jnp.arange(S)[None, :] < lengths[:, None]).astype(jnp.float32)
    summed = jnp.einsum("bse,bs->be", x.astype(jnp.float32), mask)
    pooled = summed / jnp.maximum(lengths[:, None].astype(jnp.float32), 1.0)
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
    )


def prefill_chunk(
    params: dict,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [1, C] one chunk (right-padded on the last chunk)
    start: jnp.ndarray,  # scalar int32: absolute position of tokens[:, 0]
    length: jnp.ndarray,  # scalar int32: true total prompt length
    k_slot: jnp.ndarray,  # [NL, L, KVH, D] this slot's cache
    v_slot: jnp.ndarray,
    want_logits: bool = False,
    lora: dict | None = None,
    lora_idx: jnp.ndarray | None = None,
):
    """One chunk of incremental prefill against the slot cache.

    The same compiled graph serves every chunk of every prompt length
    (static [1, C] shape) — unlike whole-prompt prefill, which compiles per
    power-of-two bucket — and activation memory stays O(C * L) instead of
    O(S^2). Stale cache contents beyond the causal frontier are masked by
    position. Returns (logits_or_None, k_slot, v_slot).
    """
    B, C = tokens.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(rope_frequencies(
            D, cfg.rope_theta, cfg.rope_scaling,
            cfg.max_position_embeddings,
        ))
    msc = rope_attention_scaling(cfg.rope_scaling)
    positions = start + jnp.arange(C)[None, :]
    x = params["embed"][tokens]

    def layer(x, scanned):
        lp = scanned["p"]
        lor = scanned.get("l")
        kc, vc = scanned["kc"], scanned["vc"]  # [L, KVH, D]

        def proj(h, w, target, bias=None):
            out = jnp.einsum("bse,eh->bsh", h, _w(w))
            if bias is not None:
                out = out + bias
            if lor is not None:
                out = out + _lora_delta(
                    h, lor[target]["A"], lor[target]["B"], lora_idx
                )
            return out

        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = proj(h, lp["wq"], "wq", lp.get("bq")).reshape(B, C, H, D)
        k = proj(h, lp["wk"], "wk", lp.get("bk")).reshape(B, C, KVH, D)
        v = proj(h, lp["wv"], "wv", lp.get("bv")).reshape(B, C, KVH, D)
        q = apply_rope(q, positions, inv_freq, msc)
        k = apply_rope(k, positions, inv_freq, msc)
        kc = jax.lax.dynamic_update_slice(
            kc, k[0].astype(kc.dtype), (start, 0, 0)
        )
        vc = jax.lax.dynamic_update_slice(
            vc, v[0].astype(vc.dtype), (start, 0, 0)
        )
        from kubeai_tpu.ops.attention import chunked_prefill_attention

        attn = chunked_prefill_attention(
            q, kc[None], vc[None], start[None]
        )
        x = x + proj(attn.reshape(B, C, H * D), lp["wo"], "wo")
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, {"kc": kc, "vc": vc}

    xs = _scan_xs(params, lora)
    xs["kc"] = k_slot
    xs["vc"] = v_slot
    x, caches = jax.lax.scan(layer, x, xs)
    k_slot, v_slot = caches["kc"], caches["vc"]
    if not want_logits:
        return None, k_slot, v_slot
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    idx = jnp.clip(length - 1 - start, 0, C - 1)
    last = jax.lax.dynamic_slice(x, (0, idx, 0), (1, 1, x.shape[-1]))[:, 0]
    logits = jnp.einsum(
        "be,ve->bv", last, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_slot, v_slot
