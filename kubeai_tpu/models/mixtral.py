"""Mixtral-family decoder: Llama attention + sparse-MoE FFN.

Expert parallelism, TPU-style: expert weights are stacked
[num_experts, ...] with the EXPERT axis sharded over the tp mesh axis
(see kubeai_tpu.parallel.sharding EXPERT rule — experts reuse the tensor
axis on one physical mesh). Routing is computed densely: every expert's
FFN runs as one batched einsum over the expert axis and the top-k router
weights zero out non-selected experts. This keeps shapes static and the
MXU busy — the standard serving trade (dense dispatch) until capacity-
based sorting is worth it; XLA shards the expert einsums so each device
computes only its local experts and psums the combine.

Parity: the reference serves Mixtral via vLLM catalog presets; here it is
the in-tree MoE path, and the `ep` axis promised in SURVEY.md §2 exists
as real sharded compute.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubeai_tpu.models.llama import _prefill_attention
from kubeai_tpu.models.registry import ModelFamily, register_model_family
from kubeai_tpu.ops.attention import decode_attention
from kubeai_tpu.ops.norms import rms_norm
from kubeai_tpu.ops.rope import apply_rope, rope_frequencies
from kubeai_tpu.parallel import sharding as sh


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    num_experts_per_tok: int = 2
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 32768
    dtype: Any = jnp.bfloat16

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def from_hf_dict(d: dict) -> "MixtralConfig":
        return MixtralConfig(
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_layers=d["num_hidden_layers"],
            num_heads=d["num_attention_heads"],
            num_kv_heads=d.get("num_key_value_heads", 8),
            num_experts=d.get("num_local_experts", 8),
            num_experts_per_tok=d.get("num_experts_per_tok", 2),
            rope_theta=d.get("rope_theta", 1e6),
            rms_norm_eps=d.get("rms_norm_eps", 1e-5),
            max_position_embeddings=d.get("max_position_embeddings", 32768),
        )

    @staticmethod
    def tiny(vocab_size: int = 512) -> "MixtralConfig":
        return MixtralConfig(
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=96,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            num_experts=4,
            num_experts_per_tok=2,
            rope_theta=10000.0,
        )


def param_specs(cfg: MixtralConfig) -> dict:
    L = None
    return {
        "embed": (sh.VOCAB, sh.EMBED),
        "layers": {
            "input_norm": (L, sh.EMBED),
            "wq": (L, sh.EMBED, sh.HEADS),
            "wk": (L, sh.EMBED, sh.KV_HEADS),
            "wv": (L, sh.EMBED, sh.KV_HEADS),
            "wo": (L, sh.HEADS, sh.EMBED),
            "post_attn_norm": (L, sh.EMBED),
            "router": (L, sh.EMBED, None),
            # Expert axis sharded over the mesh (EP = tp axis reuse).
            "w_gate": (L, sh.EXPERT, sh.EMBED, None),
            "w_up": (L, sh.EXPERT, sh.EMBED, None),
            "w_down": (L, sh.EXPERT, None, sh.EMBED),
        },
        "final_norm": (sh.EMBED,),
        "lm_head": (sh.VOCAB, sh.EMBED),
    }


def init_params(cfg: MixtralConfig, key: jax.Array | None = None) -> dict:
    if key is None:
        key = jax.random.PRNGKey(0)
    E, H, KVH, D, M, V, NL, X = (
        cfg.hidden_size,
        cfg.num_heads,
        cfg.num_kv_heads,
        cfg.head_size,
        cfg.intermediate_size,
        cfg.vocab_size,
        cfg.num_layers,
        cfg.num_experts,
    )
    ks = jax.random.split(key, 10)
    dt = cfg.dtype

    def rnd(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)

    return {
        "embed": rnd(ks[0], (V, E)),
        "layers": {
            "input_norm": jnp.ones((NL, E), dt),
            "wq": rnd(ks[1], (NL, E, H * D)),
            "wk": rnd(ks[2], (NL, E, KVH * D)),
            "wv": rnd(ks[3], (NL, E, KVH * D)),
            "wo": rnd(ks[4], (NL, H * D, E)),
            "post_attn_norm": jnp.ones((NL, E), dt),
            "router": rnd(ks[5], (NL, E, X)),
            "w_gate": rnd(ks[6], (NL, X, E, M)),
            "w_up": rnd(ks[7], (NL, X, E, M)),
            "w_down": rnd(ks[8], (NL, X, M, E)),
        },
        "final_norm": jnp.ones((E,), dt),
        "lm_head": rnd(ks[9], (V, E)),
    }


def _moe_ffn(x, lp, cfg):
    """x: [B, S, E] (or [B, E] for decode via S=1 squeeze by caller).

    Dense top-k MoE: softmax over the selected experts' router logits,
    all experts computed batched over the (sharded) expert axis, combine
    weighted by the routing probabilities.
    """
    router_logits = jnp.einsum(
        "bse,ex->bsx", x, lp["router"]
    ).astype(jnp.float32)  # [B, S, X]
    topv, topi = jax.lax.top_k(router_logits, cfg.num_experts_per_tok)
    probs = jax.nn.softmax(topv, axis=-1)  # normalize over selected only
    # Scatter the top-k probabilities back to a dense [B, S, X] weight map.
    weights = jnp.zeros_like(router_logits)
    b_idx = jnp.arange(router_logits.shape[0])[:, None, None]
    s_idx = jnp.arange(router_logits.shape[1])[None, :, None]
    weights = weights.at[b_idx, s_idx, topi].set(probs)

    # All experts, batched einsum over the expert axis (sharded -> each
    # device computes its local experts; XLA psums the combine).
    g = jax.nn.silu(jnp.einsum("bse,xem->bsxm", x, lp["w_gate"]))
    u = jnp.einsum("bse,xem->bsxm", x, lp["w_up"])
    y = jnp.einsum("bsxm,xme->bsxe", g * u, lp["w_down"])
    return jnp.einsum(
        "bsxe,bsx->bse", y, weights.astype(y.dtype)
    )


def prefill(params, cfg, tokens, lengths, lora=None, lora_idx=None):
    B, S = tokens.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(rope_frequencies(D, cfg.rope_theta))
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    x = params["embed"][tokens]

    def layer(x, lp):
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("bse,eh->bsh", h, lp["wq"]).reshape(B, S, H, D)
        k = jnp.einsum("bse,eh->bsh", h, lp["wk"]).reshape(B, S, KVH, D)
        v = jnp.einsum("bse,eh->bsh", h, lp["wv"]).reshape(B, S, KVH, D)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        attn = _prefill_attention(q, k, v)
        x = x + jnp.einsum("bsh,he->bse", attn.reshape(B, S, H * D), lp["wo"])
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _moe_ffn(h2, lp, cfg)
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(layer, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    idx = jnp.clip(lengths - 1, 0, S - 1)
    last = jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]
    logits = jnp.einsum(
        "be,ve->bv", last, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_all, v_all


def decode_step(params, cfg, tokens, positions, k_cache, v_cache,
                lora=None, lora_idx=None):
    B = tokens.shape[0]
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(rope_frequencies(D, cfg.rope_theta))
    x = params["embed"][tokens]
    pos1 = positions[:, None]
    lengths = positions + 1
    slot_idx = jnp.arange(B)

    def layer(carry, scanned):
        x = carry
        lp, kc, vc = scanned["p"], scanned["kc"], scanned["vc"]
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("be,eh->bh", h, lp["wq"]).reshape(B, 1, H, D)
        k = jnp.einsum("be,eh->bh", h, lp["wk"]).reshape(B, 1, KVH, D)
        v = jnp.einsum("be,eh->bh", h, lp["wv"]).reshape(B, 1, KVH, D)
        q = apply_rope(q, pos1, inv_freq)[:, 0]
        k = apply_rope(k, pos1, inv_freq)[:, 0]
        v = v[:, 0]
        kc = kc.at[slot_idx, positions].set(k.astype(kc.dtype))
        vc = vc.at[slot_idx, positions].set(v.astype(vc.dtype))
        attn = decode_attention(q, kc, vc, lengths)
        x = x + jnp.einsum("bh,he->be", attn.reshape(B, H * D), lp["wo"])
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _moe_ffn(h2[:, None], lp, cfg)[:, 0]
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        layer, x, {"p": params["layers"], "kc": k_cache, "vc": v_cache}
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "be,ve->bv", x, params["lm_head"], preferred_element_type=jnp.float32
    )
    return logits, k_cache, v_cache


def decode_step_paged(params, cfg, tokens, positions, k_pages, v_pages,
                      block_tables, lora=None, lora_idx=None, *,
                      attn_kernel=None):
    """Paged decode (block tables). Attention layout per `attn_kernel`
    (None = env default — see llama.decode_step_paged: "per_layer"
    scatter-then-attend with pools riding the scan, hardware-validated;
    "fused" pools outside the scan, new token as an extra attention
    column, one batched scatter after), MoE FFN unchanged."""
    from kubeai_tpu.ops.paged_attention import (
        batched_scatter_sequence,
        paged_decode_attention,
        paged_decode_attention_fused,
        resolve_decode_kernel,
        scatter_decode_token,
        token_page_coords,
    )

    from kubeai_tpu.ops.kv_quant import is_quantized_kv, kv_pages_shape

    attn_kernel = resolve_decode_kernel(attn_kernel)
    if is_quantized_kv(k_pages) and attn_kernel != "per_layer":
        raise ValueError(
            "quantized KV pools require attn_kernel='per_layer' (the "
            "fused kernel reads a raw bf16 pool)"
        )
    B = tokens.shape[0]
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    page_size = kv_pages_shape(k_pages)[2]
    inv_freq = jnp.asarray(rope_frequencies(D, cfg.rope_theta))
    x = params["embed"][tokens]
    pos1 = positions[:, None]
    page_ids, offsets = token_page_coords(block_tables, positions, page_size)
    lengths = positions + 1

    def layer_qkv(x, lp):
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("be,eh->bh", h, lp["wq"]).reshape(B, 1, H, D)
        k = jnp.einsum("be,eh->bh", h, lp["wk"]).reshape(B, 1, KVH, D)
        v = jnp.einsum("be,eh->bh", h, lp["wv"]).reshape(B, 1, KVH, D)
        q = apply_rope(q, pos1, inv_freq)[:, 0]
        k = apply_rope(k, pos1, inv_freq)[:, 0]
        return q, k, v[:, 0]

    def layer_finish(x, attn, lp):
        x = x + jnp.einsum("bh,he->be", attn.reshape(B, H * D), lp["wo"])
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        return x + _moe_ffn(h2[:, None], lp, cfg)[:, 0]

    if attn_kernel == "per_layer":

        def layer_pl(carry, scanned):
            x, lp = carry, scanned["p"]
            kp, vp = scanned["kp"], scanned["vp"]
            q, k, v = layer_qkv(x, lp)
            kp, vp = scatter_decode_token(kp, vp, k, v, page_ids, offsets)
            attn = paged_decode_attention(q, kp, vp, block_tables, lengths)
            return layer_finish(x, attn, lp), (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            layer_pl, x,
            {"p": params["layers"], "kp": k_pages, "vp": v_pages},
        )
    else:

        def layer(carry, scanned):
            x, lp = carry, scanned["p"]
            q, k, v = layer_qkv(x, lp)
            attn = paged_decode_attention_fused(
                q, k_pages, v_pages, k, v, block_tables, positions,
                scanned["li"],
            )
            return layer_finish(x, attn, lp), (k, v)

        x, (k_all, v_all) = jax.lax.scan(
            layer, x,
            {
                "p": params["layers"],
                "li": jnp.arange(cfg.num_layers, dtype=jnp.int32),
            },
        )
        k_pages, v_pages = batched_scatter_sequence(
            k_pages, v_pages, k_all[:, :, None], v_all[:, :, None],
            page_ids[:, None], offsets[:, None],
        )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "be,ve->bv", x, params["lm_head"], preferred_element_type=jnp.float32
    )
    return logits, k_pages, v_pages


def prefill_chunk(
    params,
    cfg: MixtralConfig,
    tokens: jnp.ndarray,  # [1, C] one chunk (right-padded on the last chunk)
    start: jnp.ndarray,  # scalar int32: absolute position of tokens[:, 0]
    length: jnp.ndarray,  # scalar int32: true total prompt length
    k_slot: jnp.ndarray,  # [NL, L, KVH, D] this slot's cache
    v_slot: jnp.ndarray,
    want_logits: bool = False,
    lora=None,  # accepted for signature parity; mixtral carries no LoRA
    lora_idx=None,
):
    """Chunked incremental prefill for Mixtral (llama-pattern attention
    chunk + the dense top-k MoE FFN, which is shape-generic over the
    chunk's [1, C, E]). Enables chunked admission and the prefix cache
    for the MoE family; equivalence vs whole-prompt prefill is
    test-enforced."""
    from kubeai_tpu.ops.attention import chunked_prefill_attention

    B, C = tokens.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(rope_frequencies(D, cfg.rope_theta))
    positions = start + jnp.arange(C)[None, :]
    x = params["embed"][tokens]

    def layer(x, scanned):
        lp = scanned["p"]
        kc, vc = scanned["kc"], scanned["vc"]  # [L, KVH, D]
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("bse,eh->bsh", h, lp["wq"]).reshape(B, C, H, D)
        k = jnp.einsum("bse,eh->bsh", h, lp["wk"]).reshape(B, C, KVH, D)
        v = jnp.einsum("bse,eh->bsh", h, lp["wv"]).reshape(B, C, KVH, D)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        kc = jax.lax.dynamic_update_slice(
            kc, k[0].astype(kc.dtype), (start, 0, 0)
        )
        vc = jax.lax.dynamic_update_slice(
            vc, v[0].astype(vc.dtype), (start, 0, 0)
        )
        attn = chunked_prefill_attention(q, kc[None], vc[None], start[None])
        x = x + jnp.einsum("bsh,he->bse", attn.reshape(B, C, H * D), lp["wo"])
        h2 = rms_norm(x, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + _moe_ffn(h2, lp, cfg)
        return x, {"kc": kc, "vc": vc}

    x, caches = jax.lax.scan(
        layer, x, {"p": params["layers"], "kc": k_slot, "vc": v_slot}
    )
    k_slot, v_slot = caches["kc"], caches["vc"]
    if not want_logits:
        return None, k_slot, v_slot
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    idx = jnp.clip(length - 1 - start, 0, C - 1)
    last = jax.lax.dynamic_slice(x, (0, idx, 0), (1, 1, x.shape[-1]))[:, 0]
    logits = jnp.einsum(
        "be,ve->bv", last, params["lm_head"],
        preferred_element_type=jnp.float32,
    )
    return logits, k_slot, v_slot


register_model_family(
    ModelFamily(
        "mixtral",
        config_from_hf=MixtralConfig.from_hf_dict,
        tiny_config=MixtralConfig.tiny,
        init_params=init_params,
        param_specs=param_specs,
        prefill=prefill,
        decode_step=decode_step,
        decode_step_paged=decode_step_paged,
        prefill_chunk=prefill_chunk,
        hf_architectures=("MixtralForCausalLM",),
    )
)
