"""Model families served by the TPU engine.

The reference's model catalog (reference: charts/models/values.yaml — ~60
presets across vLLM/Ollama/Infinity/FasterWhisper engines) maps here to
native JAX implementations grouped by CRD feature
(reference: api/k8s/v1/model_types.go:145-153):

  TextGeneration — llama (flagship), gemma, qwen, mixtral (MoE)
  TextEmbedding  — embeddings (mean-pooled encoder or CLM last-token)
  SpeechToText   — whisper

All models are pure-functional: params are pytrees of arrays with logical
sharding axes, forward passes are jittable with static shapes.
"""

from kubeai_tpu.models.registry import get_model_family, register_model_family
