"""Whisper encoder-decoder — the SpeechToText feature.

The reference serves speech via FasterWhisper Pods (reference:
internal/modelcontroller/engine_fasterwhisper.go); here transcription is
native: log-mel frontend (numpy), conv-downsampled transformer encoder,
causal decoder with cross-attention, greedy loop under jit.

Whisper's decoder is encoder-conditioned and transcription traffic is not
token-streamed at high QPS, so it uses its own compact generate loop
(jitted per step with static shapes) rather than the slot engine.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    vocab_size: int = 51865
    num_mel_bins: int = 80
    d_model: int = 384
    encoder_layers: int = 4
    encoder_heads: int = 6
    decoder_layers: int = 4
    decoder_heads: int = 6
    ffn_dim: int = 1536
    max_source_positions: int = 1500
    max_target_positions: int = 448
    decoder_start_token_id: int = 50258
    eos_token_id: int = 50257
    dtype: Any = jnp.float32

    @staticmethod
    def from_hf_dict(d: dict) -> "WhisperConfig":
        return WhisperConfig(
            vocab_size=d["vocab_size"],
            num_mel_bins=d.get("num_mel_bins", 80),
            d_model=d["d_model"],
            encoder_layers=d["encoder_layers"],
            encoder_heads=d["encoder_attention_heads"],
            decoder_layers=d["decoder_layers"],
            decoder_heads=d["decoder_attention_heads"],
            ffn_dim=d.get("encoder_ffn_dim", 4 * d["d_model"]),
            max_source_positions=d.get("max_source_positions", 1500),
            max_target_positions=d.get("max_target_positions", 448),
            decoder_start_token_id=d.get("decoder_start_token_id", 50258),
            eos_token_id=d.get("eos_token_id", 50257),
        )

    @staticmethod
    def tiny(vocab_size: int = 256) -> "WhisperConfig":
        return WhisperConfig(
            vocab_size=vocab_size,
            num_mel_bins=16,
            d_model=32,
            encoder_layers=2,
            encoder_heads=2,
            decoder_layers=2,
            decoder_heads=2,
            ffn_dim=64,
            max_source_positions=50,
            max_target_positions=32,
            decoder_start_token_id=1,
            eos_token_id=2,
        )


# ---- audio frontend ---------------------------------------------------------

SAMPLE_RATE = 16000
N_FFT = 400
HOP = 160


def _mel_filterbank(n_mels: int, n_fft: int = N_FFT, sr: int = SAMPLE_RATE):
    """Slaney-style mel filterbank (numpy, no deps)."""
    fmin, fmax = 0.0, sr / 2
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)
    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    mels = np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), n_mels + 2)
    freqs = mel_to_hz(mels)
    bins = np.floor((n_fft + 1) * freqs / sr).astype(int)
    fb = np.zeros((n_mels, n_fft // 2 + 1))
    for i in range(n_mels):
        lo, c, hi = bins[i], bins[i + 1], bins[i + 2]
        if c > lo:
            fb[i, lo:c] = (np.arange(lo, c) - lo) / (c - lo)
        if hi > c:
            fb[i, c:hi] = (hi - np.arange(c, hi)) / (hi - c)
    return fb


def log_mel_spectrogram(
    audio: np.ndarray, n_mels: int = 80, max_frames: int | None = None
) -> np.ndarray:
    """float32 PCM [-1, 1] @ 16 kHz -> [n_mels, T] log-mel features."""
    window = np.hanning(N_FFT + 1)[:-1]
    n = len(audio)
    frames = max(1, 1 + (n - N_FFT) // HOP) if n >= N_FFT else 1
    padded = np.pad(audio, (0, max(0, N_FFT + frames * HOP - n)))
    stft = np.stack(
        [
            np.fft.rfft(padded[i * HOP : i * HOP + N_FFT] * window)
            for i in range(frames)
        ],
        axis=1,
    )
    power = np.abs(stft) ** 2
    mel = _mel_filterbank(n_mels) @ power
    log_spec = np.log10(np.maximum(mel, 1e-10))
    log_spec = np.maximum(log_spec, log_spec.max() - 8.0)
    log_spec = (log_spec + 4.0) / 4.0
    if max_frames is not None:
        if log_spec.shape[1] < max_frames:
            log_spec = np.pad(
                log_spec, ((0, 0), (0, max_frames - log_spec.shape[1]))
            )
        else:
            log_spec = log_spec[:, :max_frames]
    return log_spec.astype(np.float32)


def decode_wav(data: bytes) -> np.ndarray:
    """WAV bytes -> mono float32 PCM (resampled to 16 kHz by decimation/
    linear interp — stdlib only)."""
    import io
    import wave

    with wave.open(io.BytesIO(data)) as w:
        sr = w.getframerate()
        n = w.getnframes()
        width = w.getsampwidth()
        ch = w.getnchannels()
        raw = w.readframes(n)
    dtype = {1: np.int8, 2: np.int16, 4: np.int32}.get(width)
    if dtype is None:
        raise ValueError(f"unsupported WAV sample width {width}")
    pcm = np.frombuffer(raw, dtype).astype(np.float32)
    pcm /= float(np.iinfo(dtype).max)
    if ch > 1:
        pcm = pcm.reshape(-1, ch).mean(axis=1)
    if sr != SAMPLE_RATE:
        t_new = np.linspace(0, len(pcm) - 1, int(len(pcm) * SAMPLE_RATE / sr))
        pcm = np.interp(t_new, np.arange(len(pcm)), pcm).astype(np.float32)
    return pcm


# ---- parameters -------------------------------------------------------------


def init_params(cfg: WhisperConfig, key=None) -> dict:
    if key is None:
        key = jax.random.PRNGKey(0)
    ks = iter(jax.random.split(key, 64))
    dt = cfg.dtype
    E, F = cfg.d_model, cfg.ffn_dim

    def rnd(shape, scale=0.05):
        return (jax.random.normal(next(ks), shape, jnp.float32) * scale).astype(dt)

    def attn_block(heads):
        return {
            "wq": rnd((E, E)), "bq": jnp.zeros((E,), dt),
            "wk": rnd((E, E)),
            "wv": rnd((E, E)), "bv": jnp.zeros((E,), dt),
            "wo": rnd((E, E)), "bo": jnp.zeros((E,), dt),
        }

    def ln():
        return {"w": jnp.ones((E,), dt), "b": jnp.zeros((E,), dt)}

    def ffn():
        return {
            "w1": rnd((E, F)), "b1": jnp.zeros((F,), dt),
            "w2": rnd((F, E)), "b2": jnp.zeros((E,), dt),
        }

    enc_layers = [
        {
            "ln1": ln(), "attn": attn_block(cfg.encoder_heads),
            "ln2": ln(), "ffn": ffn(),
        }
        for _ in range(cfg.encoder_layers)
    ]
    dec_layers = [
        {
            "ln1": ln(), "self_attn": attn_block(cfg.decoder_heads),
            "ln2": ln(), "cross_attn": attn_block(cfg.decoder_heads),
            "ln3": ln(), "ffn": ffn(),
        }
        for _ in range(cfg.decoder_layers)
    ]
    return {
        "conv1_w": rnd((3, cfg.num_mel_bins, E)),
        "conv1_b": jnp.zeros((E,), dt),
        "conv2_w": rnd((3, E, E)),
        "conv2_b": jnp.zeros((E,), dt),
        "enc_pos": rnd((cfg.max_source_positions, E), 0.02),
        "enc_layers": enc_layers,
        "enc_ln": ln(),
        "dec_embed": rnd((cfg.vocab_size, E), 0.02),
        "dec_pos": rnd((cfg.max_target_positions, E), 0.02),
        "dec_layers": dec_layers,
        "dec_ln": ln(),
    }


def _layer_norm(x, p):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) / jnp.sqrt(var + 1e-5) * p["w"] + p["b"]).astype(x.dtype)


def _mha(q_x, kv_x, p, heads, causal=False):
    E = q_x.shape[-1]
    D = E // heads
    q = (q_x @ p["wq"] + p["bq"]).reshape(*q_x.shape[:-1], heads, D)
    k = (kv_x @ p["wk"]).reshape(*kv_x.shape[:-1], heads, D)
    v = (kv_x @ p["wv"] + p["bv"]).reshape(*kv_x.shape[:-1], heads, D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
    if causal:
        Sq, Sk = q_x.shape[1], kv_x.shape[1]
        mask = jnp.arange(Sq)[:, None] + (Sk - Sq) >= jnp.arange(Sk)[None, :]
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q_x.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return out.reshape(*q_x.shape[:-1], E) @ p["wo"] + p["bo"]


def _ffn(x, p):
    return jax.nn.gelu(x @ p["w1"] + p["b1"], approximate=False) @ p["w2"] + p["b2"]


def encode(params: dict, cfg: WhisperConfig, mel: jnp.ndarray) -> jnp.ndarray:
    """mel: [B, n_mels, T] -> encoder states [B, T//2, E]."""
    x = jnp.moveaxis(mel, 1, 2)  # [B, T, mel]
    # conv1: kernel 3 stride 1 (same), gelu
    x = jax.lax.conv_general_dilated(
        x, params["conv1_w"], window_strides=(1,), padding="SAME",
        dimension_numbers=("NWC", "WIO", "NWC"),
    ) + params["conv1_b"]
    x = jax.nn.gelu(x, approximate=False)
    # conv2: kernel 3 stride 2, gelu
    x = jax.lax.conv_general_dilated(
        x, params["conv2_w"], window_strides=(2,), padding="SAME",
        dimension_numbers=("NWC", "WIO", "NWC"),
    ) + params["conv2_b"]
    x = jax.nn.gelu(x, approximate=False)
    x = x + params["enc_pos"][: x.shape[1]]
    for lp in params["enc_layers"]:
        h = _layer_norm(x, lp["ln1"])
        x = x + _mha(h, h, lp["attn"], cfg.encoder_heads)
        h = _layer_norm(x, lp["ln2"])
        x = x + _ffn(h, lp["ffn"])
    return _layer_norm(x, params["enc_ln"])


def decoder_logits(
    params: dict, cfg: WhisperConfig, tokens: jnp.ndarray, enc: jnp.ndarray
) -> jnp.ndarray:
    """tokens [B, S] + encoder states -> logits [B, S, V] (full forward;
    the greedy loop below re-runs with growing S under distinct jits per
    power-of-two bucket)."""
    x = params["dec_embed"][tokens] + params["dec_pos"][: tokens.shape[1]]
    for lp in params["dec_layers"]:
        h = _layer_norm(x, lp["ln1"])
        x = x + _mha(h, h, lp["self_attn"], cfg.decoder_heads, causal=True)
        h = _layer_norm(x, lp["ln2"])
        x = x + _mha(h, enc, lp["cross_attn"], cfg.decoder_heads)
        h = _layer_norm(x, lp["ln3"])
        x = x + _ffn(h, lp["ffn"])
    x = _layer_norm(x, params["dec_ln"])
    return jnp.einsum(
        "bse,ve->bsv", x, params["dec_embed"],
        preferred_element_type=jnp.float32,
    )


def transcribe_tokens(
    params: dict,
    cfg: WhisperConfig,
    mel: np.ndarray,  # [n_mels, T]
    max_tokens: int = 0,
    forced_tokens: tuple[int, ...] = (),
) -> list[int]:
    """Greedy decode; returns generated token ids (without the start token)."""
    max_tokens = max_tokens or (cfg.max_target_positions - 1)
    enc = jax.jit(lambda p, m: encode(p, cfg, m))(
        params, jnp.asarray(mel)[None]
    )
    tokens = [cfg.decoder_start_token_id, *forced_tokens]
    logits_fn = jax.jit(
        lambda p, t, e: decoder_logits(p, cfg, t, e)[:, -1]
    )
    out: list[int] = []
    for _ in range(max_tokens):
        if len(tokens) >= cfg.max_target_positions:
            break
        logits = logits_fn(params, jnp.asarray([tokens]), enc)
        tok = int(jnp.argmax(logits[0]))
        if tok == cfg.eos_token_id:
            break
        tokens.append(tok)
        out.append(tok)
    return out
