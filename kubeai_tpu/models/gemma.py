"""Gemma-family decoder (Gemma 1/2).

Covers the reference's Gemma catalog entries (e.g. `gemma-2b-it-tpu`,
reference: charts/models/values.yaml:80-87) natively. Architectural deltas
from Llama, all config-driven:

  - embeddings scaled by sqrt(hidden_size)
  - RMSNorm uses (1 + weight) (zero-centred weights)
  - GeGLU MLP (gelu(tanh) gate instead of silu)
  - separate head_dim (not hidden/heads)
  - Gemma-2: pre+post norms around attention AND MLP (sandwich), logit
    softcapping, optional query pre-scaling

Same engine contract as llama: param_specs/init_params/prefill/decode_step
with stacked layers + lax.scan, slot KV cache, LoRA-free for now (adapters
target the llama family first).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubeai_tpu.models.registry import ModelFamily, register_model_family
from kubeai_tpu.ops.attention import (
    causal_prefill_attention,
    chunked_prefill_attention,
    decode_attention,
)
from kubeai_tpu.models.llama import _prefill_attention
from kubeai_tpu.ops.norms import rms_norm
from kubeai_tpu.ops.rope import apply_rope, rope_frequencies
from kubeai_tpu.parallel import sharding as sh


@dataclasses.dataclass(frozen=True)
class GemmaConfig:
    vocab_size: int = 256000
    hidden_size: int = 2048
    intermediate_size: int = 16384
    num_layers: int = 18
    num_heads: int = 8
    num_kv_heads: int = 1
    head_dim: int = 256
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    max_position_embeddings: int = 8192
    # Gemma-2 extras
    sandwich_norms: bool = False  # pre+post norms (gemma2)
    final_logit_softcapping: float | None = None
    attn_logit_softcapping: float | None = None
    query_pre_attn_scalar: float | None = None
    # Gemma-2 alternates sliding-window (even layers) and global (odd
    # layers) attention; None = all-global (Gemma 1). `layer_types`
    # (serialized by HF as "sliding_attention"/"full_attention" per layer)
    # overrides the default alternating pattern when a checkpoint carries
    # a custom mapping.
    sliding_window: int | None = None
    layer_types: tuple[str, ...] | None = None
    dtype: Any = jnp.bfloat16

    def layer_windows(self) -> jnp.ndarray:
        """Per-layer effective window, 0 = global (scanned through the
        layer loop so one compiled graph serves both layer kinds)."""
        if self.sliding_window is None:
            return jnp.zeros((self.num_layers,), jnp.int32)
        if self.layer_types is not None:
            sliding = [t == "sliding_attention" for t in self.layer_types]
        else:
            sliding = [i % 2 == 0 for i in range(self.num_layers)]
        return jnp.asarray(
            [self.sliding_window if s else 0 for s in sliding], jnp.int32
        )

    @property
    def head_size(self) -> int:
        return self.head_dim

    @property
    def num_kv_heads_(self) -> int:
        return self.num_kv_heads

    @staticmethod
    def from_hf_dict(d: dict) -> "GemmaConfig":
        is_g2 = d.get("model_type") == "gemma2" or "Gemma2" in str(
            d.get("architectures")
        )
        return GemmaConfig(
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_layers=d["num_hidden_layers"],
            num_heads=d["num_attention_heads"],
            num_kv_heads=d.get("num_key_value_heads", 1),
            head_dim=d.get("head_dim", 256),
            rope_theta=d.get("rope_theta", 10000.0),
            rms_norm_eps=d.get("rms_norm_eps", 1e-6),
            max_position_embeddings=d.get("max_position_embeddings", 8192),
            sandwich_norms=is_g2,
            final_logit_softcapping=d.get("final_logit_softcapping"),
            attn_logit_softcapping=d.get("attn_logit_softcapping"),
            query_pre_attn_scalar=d.get("query_pre_attn_scalar"),
            sliding_window=d.get("sliding_window") if is_g2 else None,
            layer_types=tuple(d["layer_types"]) if d.get("layer_types") else None,
        )

    @staticmethod
    def tiny(vocab_size: int = 512) -> "GemmaConfig":
        return GemmaConfig(
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
        )


def param_specs(cfg: GemmaConfig) -> dict:
    L = None
    layers = {
        "input_norm": (L, sh.EMBED),
        "wq": (L, sh.EMBED, sh.HEADS),
        "wk": (L, sh.EMBED, sh.KV_HEADS),
        "wv": (L, sh.EMBED, sh.KV_HEADS),
        "wo": (L, sh.HEADS, sh.EMBED),
        "pre_mlp_norm": (L, sh.EMBED),
        "w_gate": (L, sh.EMBED, sh.MLP),
        "w_up": (L, sh.EMBED, sh.MLP),
        "w_down": (L, sh.MLP, sh.EMBED),
    }
    if cfg.sandwich_norms:
        layers["post_attn_norm"] = (L, sh.EMBED)
        layers["post_mlp_norm"] = (L, sh.EMBED)
    return {
        "embed": (sh.VOCAB, sh.EMBED),
        "layers": layers,
        "final_norm": (sh.EMBED,),
    }


def init_params(cfg: GemmaConfig, key: jax.Array | None = None) -> dict:
    if key is None:
        key = jax.random.PRNGKey(0)
    E, H, KVH, D, M, V, NL = (
        cfg.hidden_size,
        cfg.num_heads,
        cfg.num_kv_heads,
        cfg.head_size,
        cfg.intermediate_size,
        cfg.vocab_size,
        cfg.num_layers,
    )
    ks = jax.random.split(key, 9)
    dt = cfg.dtype

    def rnd(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)

    layers = {
        "input_norm": jnp.zeros((NL, E), dt),  # (1+w) convention
        "wq": rnd(ks[1], (NL, E, H * D)),
        "wk": rnd(ks[2], (NL, E, KVH * D)),
        "wv": rnd(ks[3], (NL, E, KVH * D)),
        "wo": rnd(ks[4], (NL, H * D, E)),
        "pre_mlp_norm": jnp.zeros((NL, E), dt),
        "w_gate": rnd(ks[5], (NL, E, M)),
        "w_up": rnd(ks[6], (NL, E, M)),
        "w_down": rnd(ks[7], (NL, M, E)),
    }
    if cfg.sandwich_norms:
        layers["post_attn_norm"] = jnp.zeros((NL, E), dt)
        layers["post_mlp_norm"] = jnp.zeros((NL, E), dt)
    return {
        "embed": rnd(ks[0], (V, E)),
        "layers": layers,
        "final_norm": jnp.zeros((E,), dt),
    }


def _norm(x, w, eps):
    # Gemma stores zero-centred norm weights: scale = 1 + w.
    return rms_norm(x, 1.0 + w.astype(jnp.float32), eps)


def _softcap(x, cap):
    if cap is None:
        return x
    return jnp.tanh(x / cap) * cap


def _mlp(x, gate, up, down):
    g = jax.nn.gelu(jnp.einsum("bse,em->bsm", x, gate), approximate=True)
    return jnp.einsum(
        "bsm,me->bse", g * jnp.einsum("bse,em->bsm", x, up), down
    )


def _q_scale(cfg: GemmaConfig) -> float:
    if cfg.query_pre_attn_scalar is not None:
        return cfg.query_pre_attn_scalar ** -0.5
    return cfg.head_size ** -0.5


def prefill(params, cfg, tokens, lengths, lora=None, lora_idx=None):
    B, S = tokens.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(rope_frequencies(D, cfg.rope_theta))
    positions = jnp.arange(S)[None, :].repeat(B, axis=0)
    x = params["embed"][tokens].astype(jnp.float32)
    x = (x * (cfg.hidden_size ** 0.5)).astype(params["embed"].dtype)

    def layer(x, scanned):
        lp, win = scanned["p"], scanned["win"]
        h = _norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("bse,eh->bsh", h, lp["wq"]).reshape(B, S, H, D)
        k = jnp.einsum("bse,eh->bsh", h, lp["wk"]).reshape(B, S, KVH, D)
        v = jnp.einsum("bse,eh->bsh", h, lp["wv"]).reshape(B, S, KVH, D)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        qs = q * (_q_scale(cfg) * D ** 0.5)
        if cfg.attn_logit_softcapping is not None or cfg.sliding_window:
            # Softcap / sliding window need the raw-logit path (the flash
            # kernel carries neither mask).
            attn = causal_prefill_attention(
                qs, k, v,
                logit_softcap=cfg.attn_logit_softcapping,
                window=win if cfg.sliding_window else None,
            )
        else:
            attn = _prefill_attention(qs, k, v)
        a_out = jnp.einsum(
            "bsh,he->bse", attn.reshape(B, S, H * D), lp["wo"]
        )
        if cfg.sandwich_norms:
            a_out = _norm(a_out, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + a_out
        h2 = _norm(x, lp["pre_mlp_norm"], cfg.rms_norm_eps)
        m_out = _mlp(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        if cfg.sandwich_norms:
            m_out = _norm(m_out, lp["post_mlp_norm"], cfg.rms_norm_eps)
        x = x + m_out
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(
        layer, x, {"p": params["layers"], "win": cfg.layer_windows()}
    )
    x = _norm(x, params["final_norm"], cfg.rms_norm_eps)
    idx = jnp.clip(lengths - 1, 0, S - 1)
    last = jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]
    logits = jnp.einsum(
        "be,ve->bv", last, params["embed"],
        preferred_element_type=jnp.float32,
    )
    logits = _softcap(logits, cfg.final_logit_softcapping)
    return logits, k_all, v_all


def decode_step(params, cfg, tokens, positions, k_cache, v_cache,
                lora=None, lora_idx=None):
    B = tokens.shape[0]
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(rope_frequencies(D, cfg.rope_theta))
    x = params["embed"][tokens].astype(jnp.float32)
    x = (x * (cfg.hidden_size ** 0.5)).astype(params["embed"].dtype)
    pos1 = positions[:, None]
    lengths = positions + 1
    slot_idx = jnp.arange(B)

    def layer(carry, scanned):
        x = carry
        lp, kc, vc = scanned["p"], scanned["kc"], scanned["vc"]
        h = _norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("be,eh->bh", h, lp["wq"]).reshape(B, 1, H, D)
        k = jnp.einsum("be,eh->bh", h, lp["wk"]).reshape(B, 1, KVH, D)
        v = jnp.einsum("be,eh->bh", h, lp["wv"]).reshape(B, 1, KVH, D)
        q = apply_rope(q, pos1, inv_freq)[:, 0]
        k = apply_rope(k, pos1, inv_freq)[:, 0]
        v = v[:, 0]
        kc = kc.at[slot_idx, positions].set(k.astype(kc.dtype))
        vc = vc.at[slot_idx, positions].set(v.astype(vc.dtype))
        attn = decode_attention(
            q * (_q_scale(cfg) * D ** 0.5), kc, vc, lengths,
            logit_softcap=cfg.attn_logit_softcapping,
            window=scanned["win"] if cfg.sliding_window else None,
        )
        a_out = jnp.einsum("bh,he->be", attn.reshape(B, H * D), lp["wo"])
        if cfg.sandwich_norms:
            a_out = _norm(a_out, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + a_out
        h2 = _norm(x, lp["pre_mlp_norm"], cfg.rms_norm_eps)
        m_out = _mlp(h2[:, None], lp["w_gate"], lp["w_up"], lp["w_down"])[:, 0]
        if cfg.sandwich_norms:
            m_out = _norm(m_out, lp["post_mlp_norm"], cfg.rms_norm_eps)
        x = x + m_out
        return x, (kc, vc)

    x, (k_cache, v_cache) = jax.lax.scan(
        layer, x,
        {
            "p": params["layers"], "kc": k_cache, "vc": v_cache,
            "win": cfg.layer_windows(),
        },
    )
    x = _norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "be,ve->bv", x, params["embed"], preferred_element_type=jnp.float32
    )
    logits = _softcap(logits, cfg.final_logit_softcapping)
    return logits, k_cache, v_cache


def decode_step_paged(params, cfg, tokens, positions, k_pages, v_pages,
                      block_tables, lora=None, lora_idx=None, *,
                      attn_kernel=None):
    """Paged decode (block tables). Attention layout per `attn_kernel`
    (None = env default — see llama.decode_step_paged for the layouts:
    "per_layer" scatter-then-attend with pools riding the scan is the
    hardware-validated path; "fused" keeps pools outside the scan, the
    new token rides as an extra attention column, and all layers' K/V
    write back in one batched scatter). The per-layer sliding window
    rides the scan, so Gemma-2's alternating local/global layers share
    one compiled graph."""
    from kubeai_tpu.ops.paged_attention import (
        batched_scatter_sequence,
        paged_decode_attention,
        paged_decode_attention_fused,
        resolve_decode_kernel,
        scatter_decode_token,
        token_page_coords,
    )

    from kubeai_tpu.ops.kv_quant import is_quantized_kv, kv_pages_shape

    attn_kernel = resolve_decode_kernel(attn_kernel)
    if is_quantized_kv(k_pages) and attn_kernel != "per_layer":
        raise ValueError(
            "quantized KV pools require attn_kernel='per_layer' (the "
            "fused kernel reads a raw bf16 pool)"
        )
    B = tokens.shape[0]
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    page_size = kv_pages_shape(k_pages)[2]
    inv_freq = jnp.asarray(rope_frequencies(D, cfg.rope_theta))
    x = params["embed"][tokens].astype(jnp.float32)
    x = (x * (cfg.hidden_size ** 0.5)).astype(params["embed"].dtype)
    pos1 = positions[:, None]
    page_ids, offsets = token_page_coords(block_tables, positions, page_size)
    lengths = positions + 1

    def layer_qkv(x, lp):
        h = _norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("be,eh->bh", h, lp["wq"]).reshape(B, 1, H, D)
        k = jnp.einsum("be,eh->bh", h, lp["wk"]).reshape(B, 1, KVH, D)
        v = jnp.einsum("be,eh->bh", h, lp["wv"]).reshape(B, 1, KVH, D)
        q = apply_rope(q, pos1, inv_freq)[:, 0]
        k = apply_rope(k, pos1, inv_freq)[:, 0]
        return q * (_q_scale(cfg) * D ** 0.5), k, v[:, 0]

    def layer_finish(x, attn, lp):
        a_out = jnp.einsum("bh,he->be", attn.reshape(B, H * D), lp["wo"])
        if cfg.sandwich_norms:
            a_out = _norm(a_out, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + a_out
        h2 = _norm(x, lp["pre_mlp_norm"], cfg.rms_norm_eps)
        m_out = _mlp(h2[:, None], lp["w_gate"], lp["w_up"], lp["w_down"])[:, 0]
        if cfg.sandwich_norms:
            m_out = _norm(m_out, lp["post_mlp_norm"], cfg.rms_norm_eps)
        return x + m_out

    if attn_kernel == "per_layer":

        def layer_pl(carry, scanned):
            x, lp = carry, scanned["p"]
            kp, vp = scanned["kp"], scanned["vp"]
            q, k, v = layer_qkv(x, lp)
            kp, vp = scatter_decode_token(kp, vp, k, v, page_ids, offsets)
            attn = paged_decode_attention(
                q, kp, vp, block_tables, lengths,
                logit_softcap=cfg.attn_logit_softcapping,
                window=scanned["win"] if cfg.sliding_window else None,
            )
            return layer_finish(x, attn, lp), (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            layer_pl, x,
            {
                "p": params["layers"], "win": cfg.layer_windows(),
                "kp": k_pages, "vp": v_pages,
            },
        )
    else:

        def layer(carry, scanned):
            x, lp = carry, scanned["p"]
            q, k, v = layer_qkv(x, lp)
            attn = paged_decode_attention_fused(
                q, k_pages, v_pages, k, v,
                block_tables, positions, scanned["li"],
                logit_softcap=cfg.attn_logit_softcapping,
                window=scanned["win"] if cfg.sliding_window else None,
            )
            return layer_finish(x, attn, lp), (k, v)

        x, (k_all, v_all) = jax.lax.scan(
            layer, x,
            {
                "p": params["layers"],
                "win": cfg.layer_windows(),
                "li": jnp.arange(cfg.num_layers, dtype=jnp.int32),
            },
        )
        k_pages, v_pages = batched_scatter_sequence(
            k_pages, v_pages, k_all[:, :, None], v_all[:, :, None],
            page_ids[:, None], offsets[:, None],
        )
    x = _norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = jnp.einsum(
        "be,ve->bv", x, params["embed"], preferred_element_type=jnp.float32
    )
    logits = _softcap(logits, cfg.final_logit_softcapping)
    return logits, k_pages, v_pages


def prefill_chunk(
    params,
    cfg: GemmaConfig,
    tokens: jnp.ndarray,  # [1, C] one chunk (right-padded on the last chunk)
    start: jnp.ndarray,  # scalar int32: absolute position of tokens[:, 0]
    length: jnp.ndarray,  # scalar int32: true total prompt length
    k_slot: jnp.ndarray,  # [NL, L, KVH, D] this slot's cache
    v_slot: jnp.ndarray,
    want_logits: bool = False,
    lora=None,  # accepted for signature parity; gemma carries no LoRA
    lora_idx=None,
):
    """Chunked incremental prefill for Gemma 1/2 (same contract as
    llama.prefill_chunk): one [1, C] graph per chunk against the slot
    cache, causal-frontier masking by absolute position — plus Gemma's
    specifics (embed normalizer, query scale, logit softcaps, sandwich
    norms, per-layer sliding-window alternation). Enables the engine's
    chunked admission and prefix cache for the gemma family; equivalence
    vs whole-prompt prefill is test-enforced."""
    B, C = tokens.shape
    H, KVH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inv_freq = jnp.asarray(rope_frequencies(D, cfg.rope_theta))
    positions = start + jnp.arange(C)[None, :]
    x = params["embed"][tokens].astype(jnp.float32)
    x = (x * (cfg.hidden_size ** 0.5)).astype(params["embed"].dtype)

    def layer(x, scanned):
        lp, win = scanned["p"], scanned["win"]
        kc, vc = scanned["kc"], scanned["vc"]  # [L, KVH, D]
        h = _norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("bse,eh->bsh", h, lp["wq"]).reshape(B, C, H, D)
        k = jnp.einsum("bse,eh->bsh", h, lp["wk"]).reshape(B, C, KVH, D)
        v = jnp.einsum("bse,eh->bsh", h, lp["wv"]).reshape(B, C, KVH, D)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        kc = jax.lax.dynamic_update_slice(
            kc, k[0].astype(kc.dtype), (start, 0, 0)
        )
        vc = jax.lax.dynamic_update_slice(
            vc, v[0].astype(vc.dtype), (start, 0, 0)
        )
        attn = chunked_prefill_attention(
            q * (_q_scale(cfg) * D ** 0.5), kc[None], vc[None], start[None],
            logit_softcap=cfg.attn_logit_softcapping,
            window=win if cfg.sliding_window else None,
        )
        a_out = jnp.einsum(
            "bsh,he->bse", attn.reshape(B, C, H * D), lp["wo"]
        )
        if cfg.sandwich_norms:
            a_out = _norm(a_out, lp["post_attn_norm"], cfg.rms_norm_eps)
        x = x + a_out
        h2 = _norm(x, lp["pre_mlp_norm"], cfg.rms_norm_eps)
        m_out = _mlp(h2, lp["w_gate"], lp["w_up"], lp["w_down"])
        if cfg.sandwich_norms:
            m_out = _norm(m_out, lp["post_mlp_norm"], cfg.rms_norm_eps)
        x = x + m_out
        return x, {"kc": kc, "vc": vc}

    x, caches = jax.lax.scan(
        layer, x,
        {
            "p": params["layers"], "win": cfg.layer_windows(),
            "kc": k_slot, "vc": v_slot,
        },
    )
    k_slot, v_slot = caches["kc"], caches["vc"]
    if not want_logits:
        return None, k_slot, v_slot
    x = _norm(x, params["final_norm"], cfg.rms_norm_eps)
    idx = jnp.clip(length - 1 - start, 0, C - 1)
    last = jax.lax.dynamic_slice(x, (0, idx, 0), (1, 1, x.shape[-1]))[:, 0]
    logits = jnp.einsum(
        "be,ve->bv", last, params["embed"],
        preferred_element_type=jnp.float32,
    )
    logits = _softcap(logits, cfg.final_logit_softcapping)
    return logits, k_slot, v_slot


register_model_family(
    ModelFamily(
        "gemma",
        config_from_hf=GemmaConfig.from_hf_dict,
        tiny_config=GemmaConfig.tiny,
        init_params=init_params,
        param_specs=param_specs,
        prefill=prefill,
        decode_step=decode_step,
        decode_step_paged=decode_step_paged,
        prefill_chunk=prefill_chunk,
        hf_architectures=("GemmaForCausalLM", "Gemma2ForCausalLM"),
    )
)
