"""Model-family registry: maps HF `architectures` / engine model ids to
native implementations.

Parity note: the reference selects an engine image by `(engine, imageName)`
from config (reference: internal/modelcontroller/model_controller.go:321-355);
here model *code* is selected by architecture, since the engine is in-tree.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

_FAMILIES: dict[str, "ModelFamily"] = {}


class ModelFamily:
    """A family bundle: config parser, param init, prefill/decode fns."""

    def __init__(
        self,
        name: str,
        *,
        config_from_hf: Callable,
        tiny_config: Callable,
        init_params: Callable,
        param_specs: Callable,
        prefill: Callable,
        decode_step: Callable,
        decode_step_paged: Callable | None = None,
        decode_step_paged_pp: Callable | None = None,
        decode_verify_paged: Callable | None = None,
        decode_verify_paged_pp: Callable | None = None,
        prefill_chunk: Callable | None = None,
        hf_architectures: tuple[str, ...] = (),
        feature: str = "TextGeneration",
        hidden_states=None,
    ):
        self.hidden_states = hidden_states
        self.name = name
        self.config_from_hf = config_from_hf
        self.tiny_config = tiny_config
        self.init_params = init_params
        self.param_specs = param_specs
        self.prefill = prefill
        self.decode_step = decode_step
        # Paged-KV decode (block tables + page pools). None = family only
        # supports the slot cache; the engine falls back automatically.
        self.decode_step_paged = decode_step_paged
        # Pipeline-parallel paged decode (stage-local KV over the pp mesh
        # axis). None = family cannot serve on a pp>1 mesh.
        self.decode_step_paged_pp = decode_step_paged_pp
        # Multi-position verify forward for speculative decoding (None =
        # speculation unsupported for this family).
        self.decode_verify_paged = decode_verify_paged
        # Pipeline-staged verify (None = no speculation on a pp>1 mesh).
        self.decode_verify_paged_pp = decode_verify_paged_pp
        # Incremental chunked prefill (None = whole-prompt prefill only;
        # chunked prefill is also the prefix cache's suffix path).
        self.prefill_chunk = prefill_chunk
        self.hf_architectures = hf_architectures
        self.feature = feature


def register_model_family(family: ModelFamily) -> ModelFamily:
    _FAMILIES[family.name] = family
    for arch in family.hf_architectures:
        _FAMILIES[arch] = family
    return family


def get_model_family(name: str) -> ModelFamily:
    _ensure_builtin()
    if name not in _FAMILIES:
        raise KeyError(
            f"unknown model family {name!r}; known: {sorted(set(f.name for f in _FAMILIES.values()))}"
        )
    return _FAMILIES[name]


_LOADED = False


def _ensure_builtin() -> None:
    global _LOADED
    if _LOADED:
        return
    from kubeai_tpu.models import llama

    register_model_family(
        ModelFamily(
            "llama",
            config_from_hf=llama.LlamaConfig.from_hf_dict,
            tiny_config=llama.LlamaConfig.tiny,
            init_params=llama.init_params,
            param_specs=llama.param_specs,
            prefill=llama.prefill,
            decode_step=llama.decode_step,
            decode_step_paged=llama.decode_step_paged,
            decode_step_paged_pp=llama.decode_step_paged_pp,
            decode_verify_paged=llama.decode_verify_paged,
            decode_verify_paged_pp=llama.decode_verify_paged_pp,
            prefill_chunk=llama.prefill_chunk,
            hf_architectures=("LlamaForCausalLM", "MistralForCausalLM"),
            hidden_states=llama.hidden_states,
        )
    )
    # Qwen2 is the Llama computation plus q/k/v biases — one implementation,
    # config-driven (attention_bias=True via from_hf_dict model_type).
    register_model_family(
        ModelFamily(
            "qwen",
            config_from_hf=llama.LlamaConfig.from_hf_dict,
            tiny_config=lambda: dataclasses.replace(
                llama.LlamaConfig.tiny(), attention_bias=True
            ),
            init_params=llama.init_params,
            param_specs=llama.param_specs,
            prefill=llama.prefill,
            decode_step=llama.decode_step,
            decode_step_paged=llama.decode_step_paged,
            decode_step_paged_pp=llama.decode_step_paged_pp,
            decode_verify_paged=llama.decode_verify_paged,
            decode_verify_paged_pp=llama.decode_verify_paged_pp,
            # Qwen2 is the llama computation with q/k/v biases, which
            # the chunk graph carries (lp.get("bq") projections) — so
            # chunked prefill and the prefix cache work unchanged.
            prefill_chunk=llama.prefill_chunk,
            hf_architectures=("Qwen2ForCausalLM",),
            hidden_states=llama.hidden_states,
        )
    )
    from kubeai_tpu.models import whisper

    register_model_family(
        ModelFamily(
            "whisper",
            config_from_hf=whisper.WhisperConfig.from_hf_dict,
            tiny_config=whisper.WhisperConfig.tiny,
            init_params=whisper.init_params,
            param_specs=lambda cfg: None,  # replicated (encoder-decoder)
            prefill=None,  # served via TranscriptionServer, not the slot engine
            decode_step=None,
            hf_architectures=("WhisperForConditionalGeneration",),
            feature="SpeechToText",
        )
    )
    # Further families (gemma, mixtral, …) self-register on import.
    for mod in ("gemma", "mixtral"):
        try:
            __import__(f"kubeai_tpu.models.{mod}")
        except ImportError:
            pass
    _LOADED = True
