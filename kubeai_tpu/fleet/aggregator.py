"""FleetStateAggregator: one place that can see the whole fleet.

Before this existed, fleet state was scattered and transient: the
autoscaler re-scraped every model's engines each tick and threw the
samples away, the LB knew endpoints but not their signals, and the
operator knew pods but not their load. The aggregator runs one
concurrent sweep over every serving endpoint's `/metrics` +
`/v1/state`, joins it with the operator's pod inventory (slice shape
from `google.com/tpu` requests, `model-role` labels, Ready/disruption
conditions), and publishes a timestamped `FleetSnapshot`:

  - per-model / per-role replica counts and aggregate signals (queue
    depth, oldest wait, TTFT/ITL quantiles, KV/slot utilization),
  - per-endpoint signal detail with explicit STALENESS: a failed scrape
    keeps the endpoint visible with its last-good data flagged stale —
    never silently merged into aggregates, never silently dropped,
  - cluster chip inventory by slice shape,
  - a ring buffer of recent snapshots (`/v1/fleet/history`) so the
    future capacity planner and prewarm forecaster have a time series
    to regress on.

The aggregates are computed by the SAME functions the autoscaler's
direct scrapers use (`aggregate_queue_pressure` / `aggregate_role_
signals` in kubeai_tpu/autoscaler/autoscaler.py), so an aggregator-fed
tick decides exactly what a direct-scrape tick would — asserted by
benchmarks/fleet_telemetry_sim.py in tier-1. Consumers read through a
freshness bound: a stale snapshot returns None and the caller falls
back to its direct scrape.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from kubeai_tpu.autoscaler.autoscaler import (
    KV_UTILIZATION_METRIC,
    QUEUE_DEPTH_METRIC,
    QUEUE_OLDEST_WAIT_METRIC,
    SLOT_CAPACITY_METRIC,
    SLOTS_ACTIVE_METRIC,
    aggregate_queue_pressure,
    aggregate_role_signals,
)
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.metrics.registry import (
    DEFAULT_METRICS,
    Metrics,
    _fmt_le as _registry_fmt_le,
    hist_buckets,
    parse_prometheus_text,
    quantiles_from_buckets,
)
from kubeai_tpu.operator import k8sutils, slicegroup

logger = logging.getLogger(__name__)

TTFT_HIST = "kubeai_engine_ttft_seconds"
ITL_HIST = "kubeai_engine_inter_token_latency_seconds"
ACTIVE_REQUESTS_METRIC = "kubeai_engine_active_requests"


def _default_fetch_metrics(addr: str, timeout: float) -> str:
    with urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=timeout
    ) as resp:
        return resp.read().decode()


def _default_fetch_state(addr: str, timeout: float) -> dict:
    with urllib.request.urlopen(
        f"http://{addr}/v1/state", timeout=timeout
    ) as resp:
        return json.loads(resp.read().decode())


def hist_quantiles(
    parsed: dict, name: str, qs: tuple[float, ...] = (0.5, 0.95, 0.99)
) -> dict:
    """Approximate quantiles from one endpoint's cumulative histogram
    buckets. The math lives in the shared estimator
    (`kubeai_tpu.metrics.registry.quantiles_from_buckets`) so the SLO
    evaluator's burn-rate reads and these per-endpoint views can never
    disagree about the same scrape. Returns {} when the histogram has no
    observations."""
    buckets, total, total_sum = hist_buckets(parsed, name)
    return quantiles_from_buckets(buckets, total, total_sum, qs)


def hist_detail(parsed: dict, name: str) -> dict:
    """JSON-safe raw histogram state for one scraped histogram: the
    cumulative buckets keyed by their canonical `le` STRING (a float
    +Inf would serialize as non-standard JSON `Infinity`), plus count
    and sum. This is what snapshots carry so the SLO evaluator can
    window observations across ticks; {} when never observed."""
    buckets, total, total_sum = hist_buckets(parsed, name)
    if total <= 0 or not buckets:
        return {}
    return {
        "buckets": [[_registry_fmt_le(b), c] for b, c in buckets],
        "count": total,
        "sum": total_sum,
    }


def endpoint_signals(parsed: dict) -> dict:
    """Per-endpoint scalar signals extracted from one `/metrics` parse —
    the snapshot's per-endpoint detail view."""
    depth = 0.0
    per_class: dict[str, float] = {}
    oldest = 0.0
    kv_util = 0.0
    slots_active = 0.0
    slot_capacity = 0.0
    active = 0.0
    for (name, labels), value in parsed.items():
        if name == QUEUE_DEPTH_METRIC:
            depth += value
            cls = dict(labels).get("class", "")
            if cls:
                per_class[cls] = per_class.get(cls, 0.0) + value
        elif name == QUEUE_OLDEST_WAIT_METRIC:
            oldest = max(oldest, value)
        elif name == KV_UTILIZATION_METRIC:
            kv_util = value
        elif name == SLOTS_ACTIVE_METRIC:
            slots_active = value
        elif name == SLOT_CAPACITY_METRIC:
            slot_capacity = value
        elif name == ACTIVE_REQUESTS_METRIC:
            active = value
    return {
        "queue_depth": depth,
        "queue_per_class": per_class,
        "queue_oldest_wait_s": oldest,
        "kv_utilization": kv_util,
        "slots_active": slots_active,
        "slot_capacity": slot_capacity,
        "active_requests": active,
        "ttft": hist_quantiles(parsed, TTFT_HIST),
        "itl": hist_quantiles(parsed, ITL_HIST),
        # Raw cumulative bucket state rides along so the SLO evaluator
        # can difference consecutive snapshots into per-window counts —
        # quantile summaries alone cannot be windowed.
        "ttft_hist": hist_detail(parsed, TTFT_HIST),
        "itl_hist": hist_detail(parsed, ITL_HIST),
    }


def merge_hist_details(details: list[dict]) -> dict:
    """Sum per-endpoint cumulative histogram details (`hist_detail`
    shape) into one: bucket counts add by `le`, as do count and sum.
    {} when nothing was observed. This is how per-version aggregates
    are built — the rollout judge compares versions, not endpoints."""
    by_le: dict[str, float] = {}
    total = 0.0
    total_sum = 0.0
    for d in details:
        if not d:
            continue
        for le, c in d.get("buckets", []):
            by_le[le] = by_le.get(le, 0.0) + c
        total += d.get("count", 0.0)
        total_sum += d.get("sum", 0.0)
    if total <= 0 or not by_le:
        return {}
    buckets = sorted(by_le.items(), key=lambda kv: float(kv[0]))
    return {
        "buckets": [[le, c] for le, c in buckets],
        "count": total,
        "sum": total_sum,
    }


def hist_detail_quantiles(detail: dict, qs=(0.5, 0.95, 0.99)) -> dict:
    """Quantile summary of a (possibly merged) `hist_detail` dict via
    the shared estimator; {} when empty."""
    if not detail:
        return {}
    return quantiles_from_buckets(
        [(float(le), c) for le, c in detail["buckets"]],
        detail["count"], detail["sum"], qs,
    )


class FleetStateAggregator:
    """Background fleet-state collector + snapshot ring.

    `fetch_metrics(addr, timeout) -> str` and
    `fetch_state(addr, timeout) -> dict` are injectable (tests and the
    deterministic sim drive the aggregator with no sockets); `clock` is
    the wall clock behind timestamps and staleness (FakeClock in the
    sim)."""

    def __init__(
        self,
        lb,
        model_client,
        store=None,
        namespace: str = "default",
        metrics: Metrics = DEFAULT_METRICS,
        usage=None,
        interval_s: float = 5.0,
        staleness_s: float | None = None,
        history: int = 120,
        scrape_timeout_s: float = 5.0,
        fetch_metrics=None,
        fetch_state=None,
        clock=time.time,
        cluster: str = "local",
    ):
        self.lb = lb
        # Which cluster's telemetry this is: stamped on every snapshot
        # so a federation join can flag (never merge) a peer's staleness
        # per cluster. "local" is the standalone default — consumers
        # that predate federation never see a different value.
        self.cluster = cluster
        self.model_client = model_client
        self.store = store
        self.namespace = namespace
        self.metrics = metrics
        self.usage = usage
        self.interval_s = interval_s
        # Endpoint data AND snapshots older than this are stale:
        # endpoints drop out of aggregates, consumer reads return None
        # (→ direct-scrape fallback).
        self.staleness_s = (
            staleness_s if staleness_s is not None else 3.0 * interval_s
        )
        self.scrape_timeout_s = scrape_timeout_s
        self._fetch_metrics = fetch_metrics or _default_fetch_metrics
        self._fetch_state = fetch_state or _default_fetch_state
        self._clock = clock
        self._lock = threading.Lock()
        # Serializes whole sweeps: the background loop and an on-demand
        # state_payload() refresh must not interleave gauge updates.
        self._collect_lock = threading.Lock()
        # addr -> {"parsed", "state", "ts" (last SUCCESS), "error"}
        self._endpoint_cache: dict[str, dict] = {}
        self._snapshots: deque[dict] = deque(maxlen=history)
        self._prev_series: dict[str, tuple] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.collect()
            except Exception as e:
                logger.warning("fleet collection failed: %s", e)

    # -- one sweep -------------------------------------------------------------

    def _scrape_endpoint(self, addr: str):
        """(parsed_metrics, state) or the exception that broke either
        fetch — /metrics is the signal source, /v1/state the admin
        detail; both must land for the endpoint to count as fresh."""
        text = self._fetch_metrics(addr, self.scrape_timeout_s)
        parsed = parse_prometheus_text(text)
        try:
            state = self._fetch_state(addr, self.scrape_timeout_s)
        except Exception:  # noqa: BLE001 — state detail is best-effort
            state = {}
        return parsed, state

    def collect(self) -> dict:
        """Run one synchronous fleet sweep and publish the snapshot."""
        with self._collect_lock:
            return self._collect_locked()

    def _collect_locked(self) -> dict:
        t0 = time.monotonic()
        now = self._clock()
        models = self.model_client.list_all_models()
        # Endpoint topology from the LB's live groups (role labels
        # included); pods the LB has ejected are already absent here.
        topology: dict[str, dict[str, dict]] = {}
        all_addrs: set[str] = set()
        for model in models:
            eps = self.lb.group(model.name).snapshot()["endpoints"]
            topology[model.name] = eps
            all_addrs.update(eps)

        results: dict[str, object] = {}
        if all_addrs:
            addrs = sorted(all_addrs)
            if len(addrs) == 1:
                try:
                    results[addrs[0]] = self._scrape_endpoint(addrs[0])
                except Exception as e:  # noqa: BLE001 — flagged stale
                    results[addrs[0]] = e
            else:
                with ThreadPoolExecutor(
                    max_workers=min(16, len(addrs))
                ) as pool:
                    futures = {
                        a: pool.submit(self._scrape_endpoint, a)
                        for a in addrs
                    }
                    for a, fut in futures.items():
                        try:
                            results[a] = fut.result()
                        except Exception as e:  # noqa: BLE001
                            results[a] = e

        with self._lock:
            for addr, res in results.items():
                if isinstance(res, Exception):
                    entry = self._endpoint_cache.setdefault(
                        addr, {"parsed": None, "state": {}, "ts": None}
                    )
                    entry["error"] = f"{type(res).__name__}: {res}"
                else:
                    parsed, state = res
                    self._endpoint_cache[addr] = {
                        "parsed": parsed,
                        "state": state,
                        "ts": now,
                        "error": None,
                    }
            # Endpoints no model routes to anymore leave the cache — the
            # per-endpoint staleness view must not accrete retirees.
            for addr in list(self._endpoint_cache):
                if addr not in all_addrs:
                    del self._endpoint_cache[addr]
            cache = {a: dict(e) for a, e in self._endpoint_cache.items()}

        per_model_pods, chips = self._pod_inventory()
        snap_models: dict[str, dict] = {}
        stale_total = 0
        endpoints_total = 0
        for model in models:
            eps = topology.get(model.name, {})
            endpoints_total += len(eps)
            ep_entries: dict[str, dict] = {}
            fresh_parsed: dict[str, dict] = {}
            roles_present: dict[str, dict[str, dict]] = {}
            replicas: dict[str, int] = {}
            stale_addrs: list[str] = []
            for addr, lb_view in eps.items():
                role = lb_view.get("role") or md.ROLE_UNIFIED
                replicas[role] = replicas.get(role, 0) + 1
                cached = cache.get(addr) or {
                    "parsed": None, "state": {}, "ts": None,
                    "error": "never scraped",
                }
                age = (
                    None if cached["ts"] is None
                    else max(0.0, now - cached["ts"])
                )
                stale = (
                    cached["parsed"] is None
                    or age is None
                    or age > self.staleness_s
                    or (cached.get("error") and age > 0)
                )
                # A scrape that failed THIS sweep but whose data is
                # within bound stays usable — flagged, not merged-fresh:
                # the entry carries the error and its age.
                usable = cached["parsed"] is not None and (
                    age is not None and age <= self.staleness_s
                )
                entry = {
                    "role": role,
                    # Serving version (pod-hash label of the backing
                    # pod), stamped by the LB sync — observable with or
                    # without a rollout in flight.
                    "version": lb_view.get("version") or "",
                    "stale": bool(stale),
                    "age_s": None if age is None else round(age, 3),
                    "error": cached.get("error"),
                    "in_flight": lb_view.get("in_flight", 0),
                    "breaker": lb_view.get("state") or "",
                }
                if cached["parsed"] is not None:
                    entry.update(endpoint_signals(cached["parsed"]))
                    state = cached.get("state") or {}
                    for k in (
                        "healthy", "draining", "pending_handoffs",
                        "kv_sharing", "kv_holdings", "cold_start",
                    ):
                        if k in state:
                            entry[k] = state[k]
                ep_entries[addr] = entry
                if stale:
                    stale_addrs.append(addr)
                if usable and not stale:
                    fresh_parsed[addr] = cached["parsed"]
                    roles_present.setdefault(role, {})[addr] = (
                        cached["parsed"]
                    )
            stale_total += len(stale_addrs)
            # Push the fresh who-holds-which-prefix map into the LB for
            # longest-held-prefix routing. Stale endpoints are simply
            # absent; an all-stale sweep pushes {} and the pick's own
            # freshness TTL handles the aggregator itself going dark.
            push = getattr(self.lb, "update_kv_holdings", None)
            if push is not None:
                holdings = {
                    addr: e["kv_holdings"]
                    for addr, e in ep_entries.items()
                    if not e["stale"]
                    and e.get("kv_sharing")
                    and e.get("kv_holdings")
                }
                if holdings or any(
                    e.get("kv_sharing") for e in ep_entries.values()
                ):
                    push(model.name, holdings)
            # Per-version rows: the fleet split on the pod-hash label.
            # The rollout judge reads these comparatively (new hash vs
            # old); `/v1/fleet/state` shows them unconditionally.
            version_rows: dict[str, dict] = {}
            for addr, e in ep_entries.items():
                row = version_rows.setdefault(
                    e.get("version") or "",
                    {
                        "endpoints": 0, "fresh": 0, "in_flight": 0,
                        "breakers_open": 0, "_ttft": [], "_itl": [],
                    },
                )
                row["endpoints"] += 1
                row["in_flight"] += e.get("in_flight", 0)
                if not e["stale"]:
                    row["fresh"] += 1
                    row["_ttft"].append(e.get("ttft_hist") or {})
                    row["_itl"].append(e.get("itl_hist") or {})
                if e.get("breaker") and e["breaker"] != "closed":
                    row["breakers_open"] += 1
            versions_out: dict[str, dict] = {}
            for v, row in sorted(version_rows.items()):
                ttft_hist = merge_hist_details(row.pop("_ttft"))
                itl_hist = merge_hist_details(row.pop("_itl"))
                row["ttft_hist"] = ttft_hist
                row["itl_hist"] = itl_hist
                row["ttft"] = hist_detail_quantiles(ttft_hist)
                row["itl"] = hist_detail_quantiles(itl_hist)
                versions_out[v] = row
            snap_models[model.name] = {
                "endpoints": ep_entries,
                "versions": versions_out,
                "replicas": replicas,
                "queue": aggregate_queue_pressure(fresh_parsed),
                "roles": {
                    role: aggregate_role_signals(parsed_by_addr)
                    for role, parsed_by_addr in roles_present.items()
                },
                "stale_endpoints": sorted(stale_addrs),
                "pods": per_model_pods.get(model.name, {}),
            }

        snapshot = {
            "ts": now,
            "cluster": self.cluster,
            "models": snap_models,
            "chips": chips,
            "endpoints_total": endpoints_total,
            "stale_total": stale_total,
            "collection_duration_s": round(time.monotonic() - t0, 6),
        }
        if self.usage is not None:
            snapshot["tenants"] = self.usage.summary()
        with self._lock:
            self._snapshots.append(snapshot)
        self._update_gauges(snapshot)
        self.metrics.fleet_collections.inc()
        self.metrics.fleet_collection_duration.observe(
            snapshot["collection_duration_s"]
        )
        return snapshot

    def _node_budget(self) -> dict:
        """Cluster chip BUDGET by slice shape, from Node allocatable
        capacity — what the scheduler could place, as opposed to the
        pod inventory below, which is what is currently requested. A
        cluster whose store carries no Node objects reports a zero
        budget; consumers (the capacity planner) treat that as
        'budget unknown — plan unconstrained'."""
        shapes: dict[str, dict] = {}
        total = 0
        try:
            nodes = self.store.list("Node")
        except Exception as e:  # noqa: BLE001 — budget stays unknown
            # A cluster where the operator cannot list Nodes (RBAC, or
            # an API server without the route) must not kill the whole
            # fleet sweep — the chip budget is simply unknown and the
            # planner plans unconstrained.
            logger.debug("node budget unavailable: %s", e)
            nodes = []
        for node in nodes:
            chips = k8sutils.node_chip_capacity(node)
            if chips <= 0:
                continue
            shape = k8sutils.node_slice_shape(node)
            slice_chips = k8sutils.node_slice_chip_count(node)
            entry = shapes.setdefault(
                shape, {"chips": 0, "nodes": 0, "slice_chips": slice_chips}
            )
            # Each node contributes ITS OWN allocatable chips to the
            # shape's budget — a multi-host slice's member nodes
            # together make up the slice, so summing whole-slice chips
            # per node would count the slice once per member.
            entry["chips"] += chips
            entry["nodes"] += 1
            # A replica cannot span slices, so the chips of one WHOLE
            # ICI slice (the topology product — not one member VM's
            # allocatable) bound the largest replica this shape hosts:
            # on a 4x4x4 slice of 4-chip VMs that is 64, and taking the
            # per-node max instead would tell the planner a multi-host
            # group can never place.
            entry["slice_chips"] = max(entry["slice_chips"], slice_chips)
            total += chips
        return {
            "total": total,
            "by_shape": {s: e["chips"] for s, e in shapes.items()},
            "nodes_by_shape": {s: e["nodes"] for s, e in shapes.items()},
            "slice_chips": {s: e["slice_chips"] for s, e in shapes.items()},
        }

    def _pod_inventory(self) -> tuple[dict, dict]:
        """Join the operator's pod view: per-model readiness/disruption
        counts and the cluster chip inventory by slice shape."""
        per_model: dict[str, dict] = {}
        by_shape: dict[str, int] = {}
        pods_by_shape: dict[str, int] = {}
        total_chips = 0
        if self.store is None:
            return per_model, {
                "total": 0, "by_shape": {}, "pods_by_shape": {},
                "budget": {
                    "total": 0, "by_shape": {}, "nodes_by_shape": {},
                    "slice_chips": {},
                },
            }
        group_members: dict[tuple[str, int], list[dict]] = {}
        for pod in self.store.list("Pod", self.namespace):
            model = k8sutils.get_label(pod, md.POD_MODEL_LABEL)
            if not model:
                continue
            g = slicegroup.group_index(pod)
            if g is not None:
                group_members.setdefault((model, g), []).append(pod)
            role = (
                k8sutils.get_label(pod, md.POD_ROLE_LABEL)
                or md.ROLE_UNIFIED
            )
            chips = k8sutils.pod_chip_count(pod)
            shape = k8sutils.pod_slice_shape(pod)
            entry = per_model.setdefault(
                model,
                {
                    "total": 0, "ready": 0, "disrupted": 0,
                    "chips": 0, "by_role": {}, "by_shape": {},
                    "by_disruption": {},
                },
            )
            entry["total"] += 1
            entry["chips"] += chips
            entry["by_role"][role] = entry["by_role"].get(role, 0) + 1
            entry["by_shape"][shape] = entry["by_shape"].get(shape, 0) + 1
            if k8sutils.pod_is_ready(pod):
                entry["ready"] += 1
            disruption = k8sutils.pod_disruption_reason(pod)
            if disruption is not None:
                entry["disrupted"] += 1
                # Per-reason counts: the demand forecaster reads the
                # SpotPreemption bucket as an early warm trigger.
                entry["by_disruption"][disruption] = (
                    entry["by_disruption"].get(disruption, 0) + 1
                )
            by_shape[shape] = by_shape.get(shape, 0) + chips
            pods_by_shape[shape] = pods_by_shape.get(shape, 0) + 1
            total_chips += chips
        # Join member pods into per-group health: a replica of a
        # multi-host model is a GROUP, and only complete all-ready
        # groups count as serving capacity. Models without group labels
        # carry no "groups" key — their entries are unchanged.
        for (model, g), members in sorted(group_members.items()):
            entry = per_model[model]
            groups = entry.setdefault(
                "groups",
                {"total": 0, "ready": 0, "partial": 0, "broken": 0},
            )
            groups["total"] += 1
            expected = slicegroup.expected_size(members)
            if slicegroup.group_ready(members, expected):
                groups["ready"] += 1
            elif not slicegroup.group_complete(members, expected):
                groups["partial"] += 1
            else:
                groups["broken"] += 1
        return per_model, {
            "total": total_chips,
            "by_shape": by_shape,
            "pods_by_shape": pods_by_shape,
            "budget": self._node_budget(),
        }

    # -- gauges (with label-churn hygiene) --------------------------------------

    def _update_gauges(self, snap: dict) -> None:
        m = self.metrics
        new_series: dict[str, tuple] = {}

        def set_(gauge, value, **labels):
            gauge.set(value, **labels)
            new_series.setdefault(gauge.name, (gauge, set()))[1].add(
                tuple(sorted(labels.items()))
            )

        for name, entry in snap["models"].items():
            for role, count in entry["replicas"].items():
                set_(m.fleet_endpoints, count, model=name, role=role)
            set_(
                m.fleet_stale_endpoints,
                len(entry["stale_endpoints"]), model=name,
            )
            set_(m.fleet_queue_depth, entry["queue"]["depth"], model=name)
            for addr, ep in entry["endpoints"].items():
                # Staleness visible per endpoint, not just as a count —
                # a flapping endpoint shows up as a sawtooth here while
                # kubeai_fleet_stale_endpoints only blinks. Never-scraped
                # endpoints export nothing: absence is not zero age.
                if ep.get("age_s") is not None:
                    set_(
                        m.fleet_endpoint_staleness,
                        ep["age_s"], model=name, endpoint=addr,
                    )
            for role, sig in entry["roles"].items():
                set_(
                    m.fleet_kv_utilization,
                    sig["kv_utilization"], model=name, role=role,
                )
            groups = (entry.get("pods") or {}).get("groups")
            if groups:
                for state in ("ready", "partial", "broken"):
                    set_(
                        m.slicegroup_groups,
                        groups[state], model=name, state=state,
                    )
        for shape, chips in snap["chips"]["by_shape"].items():
            set_(m.fleet_chips, chips, shape=shape)
        m.fleet_snapshot_ts.set(snap["ts"])
        # Retired label sets (model deleted, role gone, shape drained)
        # must not linger as frozen series.
        for name, (gauge, keys) in self._prev_series.items():
            current = (
                new_series.get(name, (gauge, set()))[1]
            )
            for k in keys - current:
                gauge.remove(**dict(k))
        self._prev_series = new_series

    # -- consumer API ----------------------------------------------------------

    def snapshot(self) -> dict | None:
        with self._lock:
            return self._snapshots[-1] if self._snapshots else None

    def history(self, n: int | None = None) -> list[dict]:
        with self._lock:
            snaps = list(self._snapshots)
        return snaps if n is None else snaps[-n:]

    def _fresh_model(self, model: str) -> dict | None:
        snap = self.snapshot()
        if snap is None:
            return None
        if self._clock() - snap["ts"] > self.staleness_s:
            return None
        return snap["models"].get(model)

    def model_entry(self, model: str) -> dict | None:
        """The model's row in the latest FRESH snapshot (None when the
        snapshot is stale or the model unknown) — the rollout judge's
        evidence source: `entry["versions"]` splits the fleet on the
        pod-hash label."""
        return self._fresh_model(model)

    def model_coverage(self, model: str) -> tuple[float | None, bool]:
        """The actuation governor's telemetry-coverage read:
        (fraction of the model's endpoints whose telemetry is fresh in
        the latest snapshot, snapshot_is_fresh). Coverage is None when
        there is no fresh snapshot or the model is unknown to it, and
        vacuously 1.0 for a model with zero endpoints (nothing to
        know)."""
        snap = self.snapshot()
        if snap is None or self._clock() - snap["ts"] > self.staleness_s:
            return None, False
        entry = snap["models"].get(model)
        if entry is None:
            return None, True
        eps = entry.get("endpoints") or {}
        if not eps:
            return 1.0, True
        fresh = sum(1 for e in eps.values() if not e.get("stale"))
        return fresh / len(eps), True

    def queue_pressure(self, model: str) -> dict | None:
        """The autoscaler's queue-pressure read: same shape as
        `scrape_queue_pressure`, or None when the snapshot is stale or
        the model unknown (→ caller falls back to direct scrape)."""
        entry = self._fresh_model(model)
        if entry is None:
            return None
        q = entry["queue"]
        return {
            "depth": q["depth"],
            "oldest_wait_s": q["oldest_wait_s"],
            "per_class": dict(q["per_class"]),
        }

    def role_signals(self, model: str, role: str) -> dict | None:
        """Per-role scaling signals: same shape as
        `scrape_role_signals`, or None when stale/unknown."""
        entry = self._fresh_model(model)
        if entry is None:
            return None
        sig = entry["roles"].get(role)
        if sig is None:
            # A fresh snapshot with no live endpoints of this role is an
            # answer, not a miss: the same empty aggregate a direct
            # scrape of zero addresses yields.
            if role in entry["replicas"]:
                return None
            return aggregate_role_signals({})
        return dict(sig)

    def state_payload(self) -> dict:
        """`GET /v1/fleet/state`: the latest snapshot, collected anew
        when none exists or the latest is past the staleness bound."""
        snap = self.snapshot()
        if snap is None or self._clock() - snap["ts"] > self.staleness_s:
            snap = self.collect()
        age = max(0.0, self._clock() - snap["ts"])
        payload = {"object": "fleet.state", "age_s": round(age, 3)}
        payload.update(snap)
        if self.usage is not None and "tenants" not in payload:
            payload["tenants"] = self.usage.summary()
        return payload
