"""Demand forecaster: the predictive half of serverless-grade cold start.

The aggregator's snapshot ring (`history()`) finally gets its promised
consumer: per model, the forecaster fits a least-squares trend over the
recent demand trajectory (scheduler queue depth + in-flight requests on
fresh endpoints) and projects it to a configurable horizon. Two signals
order a prewarm:

  * **trend** — the projected demand at the horizon exceeds current
    demand by the growth threshold: a spike is building, and a replica
    ordered NOW (restore-path boot) is Ready before it lands.
  * **spot** — the pod inventory's `by_disruption` bucket for
    SpotPreemption is rising: capacity is about to vanish and its
    replacement should be warming before the autoscaler notices the
    gap (the PR 5 classification, used as an early-warning trigger).

The forecaster also carries each model's MEASURED cold-start cost,
read from the replicas' `/v1/state` cold_start blocks — the capacity
planner prices this into preemption choices (preempting a model whose
replicas restore in seconds beats preempting one that recompiles for
minutes) and into how early a prewarm must be ordered.

Pure function of the snapshot ring: no clocks, no sockets — the
fake-clock cold-start sim drives it deterministically in tier-1.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from kubeai_tpu.operator import k8sutils

logger = logging.getLogger(__name__)

# Trigger vocabulary (metric label values; stable strings).
TRIGGER_TREND = "trend"
TRIGGER_SPOT = "spot"

# A model with no cold_start telemetry yet is assumed expensive: full
# HF conversion + XLA compile. Keeps preemption pricing conservative
# until a replica reports its measured boot.
DEFAULT_COLDSTART_S = 300.0


@dataclass
class Forecast:
    """One model's demand outlook at the forecast horizon."""

    model: str
    current: float = 0.0       # latest demand sample (queued + in flight)
    predicted: float = 0.0     # projected demand at t+horizon
    slope: float = 0.0         # demand units per second (fit)
    samples: int = 0           # ring samples behind the fit
    warm_trigger: bool = False
    trigger: str = ""          # "", "trend", or "spot"
    spot_disruptions: int = 0  # SpotPreemption pods in the latest snapshot
    coldstart_cost_s: float = DEFAULT_COLDSTART_S
    restore_available: bool = False  # any replica booted from a snapshot
    reasons: list = field(default_factory=list)

    def payload(self) -> dict:
        return {
            "model": self.model,
            "current": round(self.current, 3),
            "predicted": round(self.predicted, 3),
            "slope_per_s": round(self.slope, 6),
            "samples": self.samples,
            "warm_trigger": self.warm_trigger,
            "trigger": self.trigger,
            "spot_disruptions": self.spot_disruptions,
            "coldstart_cost_s": round(self.coldstart_cost_s, 3),
            "restore_available": self.restore_available,
            "reasons": list(self.reasons),
        }


class DemandForecaster:
    """See module docstring. `fleet` is a FleetStateAggregator (only
    `history()` is used, so anything with a compatible snapshot ring —
    the sim's fake aggregator included — plugs in)."""

    def __init__(
        self,
        fleet,
        *,
        horizon_s: float = 120.0,
        window: int = 12,
        min_samples: int = 3,
        growth_threshold: float = 1.5,
        min_demand: float = 1.0,
    ):
        self.fleet = fleet
        self.horizon_s = horizon_s
        self.window = window
        self.min_samples = min_samples
        self.growth_threshold = growth_threshold
        # Demand floor for the relative-growth test: a trajectory from
        # 0.01 to 0.04 triples but is noise, not a spike.
        self.min_demand = min_demand

    # -- snapshot readers ------------------------------------------------------

    @staticmethod
    def demand_of(entry: dict) -> float:
        """One snapshot entry's demand: queued + in flight on fresh
        endpoints (stale endpoints' numbers are fiction)."""
        depth = float(((entry.get("queue") or {}).get("depth")) or 0.0)
        active = sum(
            float(e.get("active_requests") or 0.0)
            for e in (entry.get("endpoints") or {}).values()
            if not e.get("stale")
        )
        return depth + active

    @staticmethod
    def _spot_disruptions(entry: dict) -> int:
        by = ((entry.get("pods") or {}).get("by_disruption")) or {}
        return int(by.get(k8sutils.REASON_SPOT_PREEMPTION, 0))

    @staticmethod
    def coldstart_of(entry: dict) -> tuple[float, bool]:
        """(measured cold-start cost, restore_available) from the
        replicas' cold_start blocks: the worst fresh replica's boot
        total prices the preemption (re-adding capacity costs at least
        that), restore_available when any replica restored a snapshot."""
        costs: list[float] = []
        restored = False
        for e in (entry.get("endpoints") or {}).values():
            if e.get("stale"):
                continue
            cs = e.get("cold_start") or {}
            total = float(cs.get("total_s") or 0.0)
            if total > 0:
                costs.append(total)
            restored = restored or bool(cs.get("restored"))
        return (max(costs) if costs else DEFAULT_COLDSTART_S), restored

    # -- forecasting -----------------------------------------------------------

    def forecast(self, model: str) -> Forecast:
        """Fit the model's demand trajectory over the ring and project
        it `horizon_s` ahead. Degrades gracefully: too few samples →
        no trend trigger (the spot trigger still fires)."""
        snaps = self.fleet.history(self.window)
        series: list[tuple[float, float]] = []
        spot_series: list[int] = []
        latest_entry: dict | None = None
        for snap in snaps:
            entry = (snap.get("models") or {}).get(model)
            if entry is None:
                continue
            series.append((float(snap["ts"]), self.demand_of(entry)))
            spot_series.append(self._spot_disruptions(entry))
            latest_entry = entry
        fc = Forecast(model=model, samples=len(series))
        if latest_entry is None:
            return fc
        fc.current = series[-1][1]
        fc.spot_disruptions = spot_series[-1]
        fc.coldstart_cost_s, fc.restore_available = self.coldstart_of(
            latest_entry
        )
        if len(series) >= self.min_samples:
            fc.slope = _slope(series)
            fc.predicted = max(0.0, fc.current + fc.slope * self.horizon_s)
        else:
            fc.predicted = fc.current
        # Spot early warning outranks the trend fit: capacity is
        # ALREADY being reclaimed, replacement warming can't wait for
        # a regression to notice.
        if fc.spot_disruptions > min(spot_series):
            fc.warm_trigger = True
            fc.trigger = TRIGGER_SPOT
            fc.reasons.append(
                f"spot preemptions rising ({min(spot_series)} -> "
                f"{fc.spot_disruptions})"
            )
        elif (
            fc.slope > 0
            and fc.predicted
            >= self.growth_threshold * max(fc.current, self.min_demand)
        ):
            fc.warm_trigger = True
            fc.trigger = TRIGGER_TREND
            fc.reasons.append(
                f"demand projected {fc.current:.1f} -> {fc.predicted:.1f} "
                f"in {self.horizon_s:.0f}s"
            )
        return fc

    def forecast_all(self) -> dict[str, Forecast]:
        snaps = self.fleet.history(self.window)
        models: set[str] = set()
        for snap in snaps:
            models.update((snap.get("models") or {}).keys())
        return {m: self.forecast(m) for m in sorted(models)}


def _slope(series: list[tuple[float, float]]) -> float:
    """Least-squares slope of (ts, demand) samples; 0 when degenerate
    (all samples at one timestamp)."""
    n = len(series)
    mean_t = sum(t for t, _ in series) / n
    mean_d = sum(d for _, d in series) / n
    var_t = sum((t - mean_t) ** 2 for t, _ in series)
    if var_t <= 0:
        return 0.0
    cov = sum((t - mean_t) * (d - mean_d) for t, d in series)
    return cov / var_t
