"""Engine step profiler: a per-phase monotonic timeline of Engine.step.

The engine's step loop is the hot path everyone blames when ITL climbs,
but until now it exported only one number per step (wall duration) — the
answer to "why is ITL high" required guesswork. The profiler breaks each
step into phases:

  schedule   — host-side bookkeeping before the decode dispatch (page
               allocation, speculation arm pick)
  prefill    — the admission pass (scheduler pops + prefill compute)
  decode     — the decode/speculation jit DISPATCH (async under JAX; the
               device wait surfaces in overlap_idle at reap time)
  dispatch   — host→device input staging for the chunk (the block-table
               upload before the decode jit)
  overlap_idle — time the host spends blocked on device compute at reap
               (`block_until_ready`). In the synchronous loop this is
               ~the whole device step; under the overlapped step
               pipeline it shrinks toward zero — the overlap win,
               made visible per step.
  readback   — jax.device_get of the (ready) decode chunk: the actual
               device→host token transfer.
  sample     — host-side token emission (stop checks, slot release)
  kv_transfer — paged-KV handoff export/import (disaggregated serving;
               recorded outside the step timeline)

(`host_sync` — the old single bucket covering device wait + transfer —
split into dispatch/readback/overlap_idle when the overlapped step
pipeline landed.)

The engine records plain floats under its own lock — it never touches a
metrics registry from the hot path (same discipline as `Engine._timing`).
The serve loop drains pending observations into the per-phase histogram
(`kubeai_engine_step_phase_seconds`), and a bounded ring of recent step
records backs `POST /v1/profile` on the engine server.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

# Canonical phase vocabulary (metric label values; docs list them).
PHASES = (
    "schedule", "prefill", "decode", "dispatch", "overlap_idle",
    "readback", "sample", "kv_transfer",
)


class StepProfiler:
    """Bounded ring of per-step phase timelines + a drainable list of
    (phase, seconds) observations for histogram export. Thread-safe; all
    methods are cheap enough for the engine lock's critical section."""

    def __init__(self, maxlen: int = 256, wall=time.time):
        self._cond = threading.Condition()
        self._ring: deque[dict] = deque(maxlen=maxlen)
        self._pending: list[tuple[str, float]] = []
        self._wall = wall
        self.steps_completed = 0

    def observe(self, phase: str, seconds: float) -> None:
        """One standalone phase observation (e.g. a KV handoff transfer
        that happens outside the step loop)."""
        with self._cond:
            self._pending.append((phase, float(seconds)))

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def observe_step(
        self,
        phases: dict[str, float],
        tokens: int = 0,
        batch: int = 0,
        duration_s: float = 0.0,
    ) -> None:
        """Close one step's record into the ring and queue its phases for
        histogram export. Wakes /v1/profile waiters."""
        with self._cond:
            self.steps_completed += 1
            self._ring.append(
                {
                    "step": self.steps_completed,
                    "ts": self._wall(),
                    "tokens": int(tokens),
                    "batch": int(batch),
                    "duration_s": round(float(duration_s), 9),
                    "phases_s": {
                        k: round(float(v), 9) for k, v in phases.items()
                    },
                }
            )
            self._pending.extend(
                (k, float(v)) for k, v in phases.items()
            )
            self._cond.notify_all()

    def drain(self) -> list[tuple[str, float]]:
        """Hand pending (phase, seconds) observations to the caller (the
        serve loop's histogram sync); clears the queue."""
        with self._cond:
            out, self._pending = self._pending, []
            return out

    def recent(self, n: int | None = None) -> list[dict]:
        with self._cond:
            records = list(self._ring)
        return records if n is None else records[-n:]

    def wait_for_steps(self, n: int, timeout_s: float) -> int:
        """Block until `n` NEW steps complete (or timeout); returns how
        many actually did. Backs /v1/profile's fresh-capture mode."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            start = self.steps_completed
            while self.steps_completed - start < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=min(remaining, 0.25))
            return self.steps_completed - start


def phase_totals(records: list[dict]) -> dict[str, float]:
    """Sum each phase across step records — the profile response's
    roll-up (which phase dominates the window)."""
    totals: dict[str, float] = {}
    for rec in records:
        for k, v in (rec.get("phases_s") or {}).items():
            totals[k] = totals.get(k, 0.0) + float(v)
    return {k: round(v, 9) for k, v in totals.items()}
